"""Join physical operators — shuffled-hash, broadcast-hash, nested-loop, cartesian.

Reference (SURVEY.md component #16): GpuHashJoin.scala:386 (`HashJoinIterator`:179
streams probe batches against a spillable built table), JoinGatherer.scala (bounded
gather-map iteration), GpuShuffledHashJoinBase.scala:97, shim GpuBroadcastHashJoinExec,
GpuBroadcastNestedLoopJoinExec.scala, GpuCartesianProductExec.scala.

The kernel side (ops/joining.py) replaces cudf's hash-table gather maps with a fused
rank-sort + searchsorted probe; this layer owns build-side materialization (single
spillable batch, like the reference's LazySpillableColumnarBatch build side), the
streamed probe loop, chunked output expansion, residual condition filtering, and
full-outer unmatched-build tracking across the whole stream.

Join type support matrix mirrors the reference (GpuHashJoin.tagJoin): equi-joins for
inner/left/right/full/semi/anti; residual conditions on inner only (the reference
falls conditional outer joins back to CPU / nested-loop); nested-loop handles cross
and conditional inner plus outer/semi/anti against a broadcast build side.
"""

from __future__ import annotations

import threading

import numpy as np
import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity
from spark_rapids_tpu.exec.base import TpuExec, TaskContext, acquire_semaphore
from spark_rapids_tpu.exec.coalesce import concat_all
from spark_rapids_tpu.expr.core import Col, EvalContext, Expression, bind_references
from spark_rapids_tpu.ops import joining as J
from spark_rapids_tpu.ops.filtering import (
    gather_cols, selection_mask, compact_cols, slice_to_capacity)
from spark_rapids_tpu.ops.strings import union_dictionaries
from spark_rapids_tpu.runtime import faults as F
from spark_rapids_tpu.runtime import memory as mem
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import retry as R
from spark_rapids_tpu.runtime.tracing import trace_range

# max pairs expanded per output chunk (the JoinGatherer row-target analog)
_MAX_CHUNK_ROWS = 1 << 20


def _align_string_keys(build_keys, stream_keys):
    out_b, out_s = [], []
    for b, s in zip(build_keys, stream_keys):
        if b.is_string:
            b, s = union_dictionaries(b, s)
        out_b.append(b)
        out_s.append(s)
    return out_b, out_s


def _null_extended(cols, idx, valid):
    """Gather `cols` rows by idx where valid, null otherwise (outer join side)."""
    return gather_cols(cols, idx, valid)


def _emit_pairs(join_type, stream_is_left, condition, preproject,
                stream_batch, build_batch, build_perm, lo, hi, counts, total,
                out_schema):
    """Pair-expansion emit shared by HashJoinExec and the join-chain fallback:
    expand in chunks (one fused program per chunk capacity), yield batches."""
    from spark_rapids_tpu.runtime import fuse
    total = int(total)
    semi_anti = join_type in (J.LEFT_SEMI, J.LEFT_ANTI)
    cond = condition
    cond_key = fuse.expr_key(cond) if cond is not None else None
    out_key = fuse.schema_key(out_schema)
    pos = 0
    while pos < total:
        out_cap = bucket_capacity(min(total - pos, _MAX_CHUNK_ROWS))

        def kernel(build_perm, lo, hi, counts, s_in, b_in, start, n_out,
                   _cap=out_cap):
            s_idx, b_idx, b_matched, live = J.expand_pairs(
                build_perm, lo, hi, counts, start, _cap)
            s_cols = gather_cols(s_in, s_idx, live)
            if preproject is not None:
                pctx = EvalContext(s_cols, n_out, _cap)
                s_cols = [e.eval(pctx) for e in preproject]
            if semi_anti:
                cols = s_cols
            else:
                b_cols = _null_extended(b_in, b_idx, b_matched)
                cols = (s_cols + b_cols) if stream_is_left else (b_cols + s_cols)
            if cond is not None:
                ctx = EvalContext(cols, n_out, _cap)
                pred = cond.eval(ctx)
                keep = pred.values & pred.validity & live
                return compact_cols(cols, keep)
            return cols, None

        key = ("join_emit", semi_anti, stream_is_left, out_cap,
               cond_key, out_key,
               tuple(fuse.expr_key(e) for e in preproject)
               if preproject is not None else None)
        s_in = [Col.from_vector(c) for c in stream_batch.columns]
        b_in = ([] if semi_anti else
                [Col.from_vector(c) for c in build_batch.columns])
        start = jnp.asarray(pos, jnp.int32)
        n_out_t = jnp.asarray(min(total - pos, out_cap), jnp.int32)
        args = (build_perm, lo, hi, counts, s_in, b_in, start, n_out_t)
        cols, count = fuse.call_fused(key, "HashJoin.emit",
                                      lambda: kernel, args,
                                      lambda: kernel(*args))
        n_out = min(total - pos, out_cap) if count is None else count
        yield ColumnarBatch([c.to_vector() for c in cols], n_out, out_schema)
        pos += out_cap


def _int_backed(dtype) -> bool:
    """Orderable fixed-point key: comparisons over raw device values ARE key
    comparisons (unlike string codes, which are only comparable under one
    shared dictionary, or floats, which need NaN totalization)."""
    return isinstance(dtype, (T.IntegralType, T.BooleanType, T.DateType,
                              T.TimestampType, T.DecimalType))


class _JoinCore:
    """Shared probe machinery over one materialized build batch.

    Single fixed-point-key joins take a FAST path: the build side is sorted
    ONCE (invalid/padding rows forced to the type max and clamped out via the
    valid count), and each stream batch probes with two searchsorted calls —
    no per-batch re-sort of build+stream (the rank path pays a multi-key sort
    over both sides per stream batch)."""

    def __init__(self, build_batch: ColumnarBatch, build_key_exprs,
                 stream_key_exprs, join_type: str, stream_prefilter=None):
        from spark_rapids_tpu.runtime import fuse
        self.build_batch = build_batch
        self.build_key_exprs = build_key_exprs
        self.stream_key_exprs = stream_key_exprs
        self.join_type = join_type
        # hoisted stream-side filter (inner single-int-key joins only — the
        # planner guarantees that): the predicate masks probe rows in-kernel,
        # so filtered rows emit zero pairs without a separate FilterExec
        # dispatch + compaction (whole-stage-codegen role)
        self.stream_prefilter = stream_prefilter
        from spark_rapids_tpu.expr.misc import CONTEXT_SENSITIVE
        bctx = EvalContext.from_batch(build_batch)
        self.build_keys_raw = [e.eval(bctx) for e in build_key_exprs]
        self.n_build = build_batch.num_rows
        self.build_cap = build_batch.capacity
        # stream keys reading per-batch context (input_file_name family etc.)
        # cannot be baked into a shared compiled program
        self.ctx_sensitive = any(
            e.collect(lambda x: isinstance(x, CONTEXT_SENSITIVE))
            for e in (*stream_key_exprs,
                      *([stream_prefilter] if stream_prefilter is not None
                        else [])))
        self._stream_key_key = (tuple(
            fuse.expr_key(e) for e in stream_key_exprs),
            fuse.expr_key(stream_prefilter)
            if stream_prefilter is not None else None)
        # matched-build tracking for full outer (host accumulation across stream)
        self.build_matched_acc = (np.zeros(self.build_cap, dtype=bool)
                                  if join_type == J.FULL_OUTER else None)
        self.fast = (len(self.build_keys_raw) == 1
                     and _int_backed(self.build_keys_raw[0].dtype))
        # the hoisting planner rule guarantees these; the eager and rank
        # probe paths do not evaluate the prefilter
        assert stream_prefilter is None or (self.fast
                                            and not self.ctx_sensitive)
        if self.fast:
            self._prep_fast_build()

    def _prep_fast_build(self):
        """Sort the single int build key once. Strategy picked from the key
        RANGE (one cheap reduction + host sync per build, like the
        reference's one-time build-table materialization):

        - range fits the packed budget → ONE-operand int64 sort of
          ((val - vmin) << idx_bits | row_idx); ~8x cheaper than the
          3-operand comparator sort (docs/perf_notes.md fix-3 measurement).
        - afterwards, uniqueness + compact domain decide the probe mode:
          dense direct-address rank table (O(1) gather per stream row),
          unique single-searchsorted, or the general two-searchsorted."""
        from spark_rapids_tpu.runtime import fuse
        import numpy as np
        k = self.build_keys_raw[0]
        cap = k.values.shape[0]
        idx_bits = max(int(cap - 1).bit_length(), 1)

        def stats(k, n_build):
            vals = k.values.astype(jnp.int8) if k.values.dtype == jnp.bool_ \
                else k.values
            eligible = k.validity & (jnp.arange(cap, dtype=jnp.int32) < n_build)
            big = jnp.asarray(jnp.iinfo(vals.dtype).max, vals.dtype)
            small = jnp.asarray(jnp.iinfo(vals.dtype).min, vals.dtype)
            vmin = jnp.min(jnp.where(eligible, vals, big))
            vmax = jnp.max(jnp.where(eligible, vals, small))
            return (vmin.astype(jnp.int64), vmax.astype(jnp.int64),
                    jnp.sum(eligible, dtype=jnp.int32))

        skey = ("join_build_stats", k.dtype, cap)
        n_build_t = jnp.asarray(self.n_build, jnp.int32)
        vmin_t, vmax_t, n_valid = fuse.call_fused(
            skey, "HashJoin.build_stats", lambda: stats, (k, n_build_t),
            lambda: stats(k, n_build_t))
        vmin, vmax = int(vmin_t), int(vmax_t)    # one host sync per build
        rng = max(vmax - vmin, 0)
        # vmax+1 (the ineligible-row sentinel) must stay representable in
        # int64 — the packed path keeps sorted keys as int64 precisely so a
        # dtype-max key can never collide with/overflow into the sentinel
        packable = (self.n_build > 0 and rng < (1 << (62 - idx_bits))
                    and vmax < (1 << 62))
        # one size/budget for BOTH dense-table builders (direct and
        # post-sort) so they make consistent engage/skip decisions
        dsize = rng + 2 if self.n_build > 0 else 1
        dense_budget = max(4 * cap, 1 << 22)
        from spark_rapids_tpu.runtime.hw import scatters_cheap
        direct_ok = (scatters_cheap() and self.n_build > 0
                     and self.build_matched_acc is None
                     and dsize <= dense_budget)
        if direct_ok:
            # CPU-only sort-free build: scatter row indices straight into the
            # direct-address table (XLA:CPU scatters are cheap; the sort they
            # replace was the dominant build cost — docs/perf_notes.md). A
            # duplicate-key build falls through to the sorted paths below;
            # on TPU large scatters serialize, so this path never engages.
            def rel_of(k, n_build, vmin):
                vals = k.values.astype(jnp.int8) \
                    if k.values.dtype == jnp.bool_ else k.values
                eligible = k.validity & (
                    jnp.arange(cap, dtype=jnp.int32) < n_build)
                return jnp.where(eligible, vals.astype(jnp.int64) - vmin,
                                 jnp.asarray(dsize, jnp.int64))

            # two kernels so a duplicate-key build discards only the cheap
            # uniqueness scatter, not a full table build
            def uniq_check(k, n_build, vmin):
                counts = jnp.zeros((dsize,), jnp.int32
                                   ).at[rel_of(k, n_build, vmin)].add(
                    1, mode="drop")
                return jnp.all(counts <= 1)

            def mktable_direct(k, n_build, vmin):
                return jnp.full((dsize,), -1, jnp.int32
                                ).at[rel_of(k, n_build, vmin)].set(
                    jnp.arange(cap, dtype=jnp.int32), mode="drop")

            dkey = ("join_build_direct_uniq", k.dtype, cap, dsize)
            dargs = (k, n_build_t, jnp.asarray(vmin, jnp.int64))
            uniq_t = fuse.call_fused(
                dkey, "HashJoin.build_prep", lambda: uniq_check, dargs,
                lambda: uniq_check(*dargs))
            if bool(uniq_t):
                tkey = ("join_build_direct_table", k.dtype, cap, dsize)
                table_t = fuse.call_fused(
                    tkey, "HashJoin.build_prep", lambda: mktable_direct,
                    dargs, lambda: mktable_direct(*dargs))
                self._probe_mode = "dense"
                self._dense_size = dsize
                self._dense_table = table_t
                # ranks ARE build-row indices for the direct table
                self._build_perm = jnp.arange(cap, dtype=jnp.int32)
                self._sorted_build = (k.values.astype(jnp.int8)
                                      if k.values.dtype == jnp.bool_
                                      else k.values)  # dtype carrier only
                self._n_valid = n_valid
                self._vmin = vmin
                return

        from spark_rapids_tpu.ops import pallas_kernels as PK
        # Pallas VMEM hash table (sparse domains the dense table can't
        # afford; the TPU path where large scatters rule `dense` out).
        # vmin > int64 min keeps the slot sentinel unambiguous; the build
        # itself refuses duplicate keys / overfull buckets via `ok`.
        nb = PK.hash_join_buckets(self.n_build)
        if (nb and self.n_build > 0 and self.build_matched_acc is None
                and vmin > jnp.iinfo(jnp.int64).min
                and PK.should_use("hashjoin")):
            def mktable_hash(k, n_build):
                vals = k.values.astype(jnp.int8) \
                    if k.values.dtype == jnp.bool_ else k.values
                eligible = k.validity & (
                    jnp.arange(cap, dtype=jnp.int32) < n_build)
                return PK.hash_join_build(vals.astype(jnp.int64),
                                          eligible, nb)
            hkey = ("join_build_hash", k.dtype, cap, nb)
            hargs = (k, n_build_t)
            tk_t, tr_t, ok_t = fuse.call_fused(
                hkey, "HashJoin.build_prep", lambda: mktable_hash, hargs,
                lambda: mktable_hash(*hargs))
            if bool(ok_t):    # one host sync per build, like vmin/vmax
                self._probe_mode = "pallas_hash"
                self._hash_buckets = nb
                self._hash_keys, self._hash_rows = tk_t, tr_t
                # probe positions ARE build-row indices
                self._build_perm = jnp.arange(cap, dtype=jnp.int32)
                self._sorted_build = (k.values.astype(jnp.int8)
                                      if k.values.dtype == jnp.bool_
                                      else k.values)  # dtype carrier only
                self._n_valid = n_valid
                self._vmin = vmin
                return

        if packable:
            def prep(k, n_build, vmin):
                vals = k.values.astype(jnp.int8) \
                    if k.values.dtype == jnp.bool_ else k.values
                eligible = k.validity & (
                    jnp.arange(cap, dtype=jnp.int32) < n_build)
                rel = (vals.astype(jnp.int64) - vmin)
                # ineligible rows above every real key (rng+1 relative)
                rel = jnp.where(eligible, rel, jnp.asarray(rng + 1, jnp.int64))
                packed = (rel << idx_bits) | jnp.arange(cap, dtype=jnp.int64)
                s = jax.lax.sort(packed)
                perm = (s & ((1 << idx_bits) - 1)).astype(jnp.int32)
                # int64 ON PURPOSE: casting back to the key dtype would wrap
                # the vmax+1 sentinel tail to INT_MIN when vmax == dtype max,
                # breaking the sortedness searchsorted depends on (probe
                # promotes both sides to a common type anyway)
                sorted_vals = (s >> idx_bits) + vmin
                nv = jnp.sum(eligible, dtype=jnp.int32)
                same = (s[1:] >> idx_bits) == (s[:-1] >> idx_bits)
                in_valid = (jnp.arange(cap - 1, dtype=jnp.int32) + 1) < nv
                unique = ~jnp.any(same & in_valid)
                return sorted_vals, perm, unique

            pkey = ("join_build_pack", k.dtype, cap, idx_bits, rng + 1)
            args = (k, n_build_t, jnp.asarray(vmin, jnp.int64))
            self._sorted_build, self._build_perm, uniq_t = fuse.call_fused(
                pkey, "HashJoin.build_prep", lambda: prep, args,
                lambda: prep(*args))
        else:
            def prep(k, n_build):
                vals = k.values.astype(jnp.int8) \
                    if k.values.dtype == jnp.bool_ else k.values
                eligible = k.validity & (
                    jnp.arange(cap, dtype=jnp.int32) < n_build)
                masked = jnp.where(
                    eligible, vals,
                    jnp.asarray(jnp.iinfo(vals.dtype).max, vals.dtype))
                # two sort keys: eligibility first so a LEGITIMATE max-valued
                # key still lands inside [0, n_valid) against the sentinel
                _, sorted_vals, perm = jax.lax.sort(
                    [(~eligible).astype(jnp.int8), masked,
                     jnp.arange(cap, dtype=jnp.int32)], num_keys=2)
                nv = jnp.sum(eligible, dtype=jnp.int32)
                same = sorted_vals[1:] == sorted_vals[:-1]
                in_valid = (jnp.arange(cap - 1, dtype=jnp.int32) + 1) < nv
                unique = ~jnp.any(same & in_valid)
                return sorted_vals, perm, unique

            key = ("join_build_prep", k.dtype, cap)
            args = (k, n_build_t)
            self._sorted_build, self._build_perm, uniq_t = fuse.call_fused(
                key, "HashJoin.build_prep", lambda: prep, args,
                lambda: prep(*args))
        self._n_valid = n_valid
        # probe-mode choice — static per compiled probe kernel
        self._vmin = vmin
        unique = bool(uniq_t) if self.n_build > 0 else True
        self._probe_mode = "two"
        if unique and self.build_matched_acc is None:
            self._probe_mode = "one"
            if dsize <= dense_budget and scatters_cheap():
                # direct-address rank table: scatter once per build, O(1)
                # gather per probe row (kept off-TPU: large 1:1 scatters
                # serialize there; searchsorted stays the TPU path)
                self._probe_mode = "dense"
                self._dense_size = dsize

                def mktable(sorted_vals, n_valid, vmin):
                    i = jnp.arange(cap, dtype=jnp.int32)
                    slot = jnp.where(
                        i < n_valid,
                        sorted_vals.astype(jnp.int64) - vmin,
                        jnp.asarray(dsize, jnp.int64))   # tail → dropped
                    table = jnp.full((dsize,), -1, jnp.int32)
                    return table.at[slot].set(i, mode="drop")

                tkey = ("join_dense_table", k.dtype, cap, dsize)
                targs = (self._sorted_build, n_valid,
                         jnp.asarray(vmin, jnp.int64))
                self._dense_table = fuse.call_fused(
                    tkey, "HashJoin.dense_table", lambda: mktable, targs,
                    lambda: mktable(*targs))

    def probe_batch(self, stream_batch: ColumnarBatch):
        from spark_rapids_tpu.runtime import fuse
        # from the stream (preserved) side's perspective, right/full outer are a
        # left outer over the swapped/streamed input
        jt = (J.LEFT_OUTER if self.join_type in (J.FULL_OUTER, J.RIGHT_OUTER)
              else self.join_type)
        track_matched = self.build_matched_acc is not None
        stream_key_exprs = self.stream_key_exprs
        if self.ctx_sensitive:
            return self._probe_batch_eager(stream_batch, jt, track_matched)
        if self.fast:
            return self._probe_batch_fast(stream_batch, jt, track_matched)

        def kernel(build_keys_raw, n_build, stream_cols, n_stream):
            scap = stream_cols[0].values.shape[0]
            sctx = EvalContext(stream_cols, n_stream, scap)
            stream_keys = [e.eval(sctx) for e in stream_key_exprs]
            build_keys, stream_keys = _align_string_keys(build_keys_raw,
                                                         stream_keys)
            b_ranks, s_ranks = J.join_ranks(
                build_keys, n_build, build_keys[0].values.shape[0],
                stream_keys, n_stream, scap)
            build_perm, lo, hi = J.probe(b_ranks, s_ranks)
            counts = J.pair_counts(lo, hi, n_stream, scap, jt)
            total = J.total_pairs(counts)
            if track_matched:
                # symmetric probe: which build rows matched this stream batch
                _, blo, bhi = J.probe(s_ranks, b_ranks)
                return build_perm, lo, hi, counts, total, (bhi - blo) > 0
            return build_perm, lo, hi, counts, total, None

        key = ("join_probe", jt, track_matched, self._stream_key_key,
               fuse.schema_key(stream_batch.schema)
               if stream_batch.schema else None)
        stream_cols = [Col.from_vector(c) for c in stream_batch.columns]
        n_build = jnp.asarray(self.n_build, jnp.int32)
        n_stream = jnp.asarray(stream_batch.lazy_num_rows, jnp.int32)
        build_perm, lo, hi, counts, total, matched = fuse.call_fused(
            key, "HashJoin.probe", lambda: kernel,
            (self.build_keys_raw, n_build, stream_cols, n_stream),
            lambda: kernel(self.build_keys_raw, n_build, stream_cols,
                           n_stream))
        if track_matched:
            self.build_matched_acc |= np.asarray(matched)
        return build_perm, lo, hi, counts, total

    def _probe_batch_eager(self, stream_batch, jt, track_matched):
        """Context-sensitive stream keys: evaluate with the batch's full
        context (scan provenance etc.) — never through a shared compiled
        program."""
        sctx = EvalContext.from_batch(stream_batch)
        stream_keys = [e.eval(sctx) for e in self.stream_key_exprs]
        build_keys, stream_keys = _align_string_keys(self.build_keys_raw,
                                                     stream_keys)
        b_ranks, s_ranks = J.join_ranks(
            build_keys, self.n_build, self.build_cap,
            stream_keys, stream_batch.lazy_num_rows, stream_batch.capacity)
        build_perm, lo, hi = J.probe(b_ranks, s_ranks)
        counts = J.pair_counts(lo, hi, stream_batch.lazy_num_rows,
                               stream_batch.capacity, jt)
        total = J.total_pairs(counts)
        if track_matched:
            _, blo, bhi = J.probe(s_ranks, b_ranks)
            self.build_matched_acc |= np.asarray((bhi - blo) > 0)
        return build_perm, lo, hi, counts, total

    def _probe_batch_fast(self, stream_batch, jt, track_matched):
        """Pre-sorted-build probe. Modes (chosen at build, static per compiled
        kernel): "pallas_hash" = VMEM hash-table probe kernel (unique keys;
        pallas_kernels.hash_join_probe, interpret-mode off-TPU); "dense" =
        O(1) direct-address rank-table gather (unique keys, compact domain);
        "one" = single searchsorted + equality (unique keys); "two" = general
        left+right searchsorted."""
        from spark_rapids_tpu.ops import pallas_kernels as PK
        from spark_rapids_tpu.runtime import fuse
        stream_key_exprs = self.stream_key_exprs
        mode = self._probe_mode
        vmin = self._vmin
        dsize = getattr(self, "_dense_size", 0)
        hash_buckets = getattr(self, "_hash_buckets", 0)

        stream_prefilter = self.stream_prefilter

        def kernel(sorted_build, n_valid, n_build, build_keys_raw, stream_cols,
                   n_stream, dense_table, hash_keys, hash_rows):
            scap = stream_cols[0].values.shape[0]
            sctx = EvalContext(stream_cols, n_stream, scap)
            k = stream_key_exprs[0].eval(sctx)
            svals = (k.values.astype(jnp.int8)
                     if k.values.dtype == jnp.bool_ else k.values)
            # mixed-width keys (e.g. int64 probe vs int32 build): promote BOTH
            # sides to the common dtype — casting the stream DOWN wraps values
            # and fabricates matches. Integer widening is monotone, so the
            # pre-sorted build array stays sorted and the n_valid clamp still
            # masks the sentinel tail.
            common = jnp.promote_types(svals.dtype, sorted_build.dtype)
            svals = svals.astype(common)
            sorted_common = sorted_build.astype(common)
            if stream_prefilter is not None:
                live = selection_mask(stream_prefilter.eval(sctx),
                                      n_stream, scap)
            else:
                live = jnp.arange(scap, dtype=jnp.int32) < n_stream
            if mode == "pallas_hash":
                # equality over int64 images is equality over any narrower
                # int key dtype, so no common-type promotion dance needed
                pos, found = PK.hash_join_probe(
                    hash_keys, hash_rows, svals.astype(jnp.int64),
                    hash_buckets)
                hit = found & k.validity & live
                lo = jnp.where(hit, pos, 0).astype(jnp.int32)
                hi = jnp.where(hit, pos + 1, lo).astype(jnp.int32)
            elif mode == "dense":
                slot = svals.astype(jnp.int64) - vmin
                in_dom = (slot >= 0) & (slot < dsize - 1)
                r = dense_table[jnp.clip(slot, 0, dsize - 1)]
                hit = in_dom & (r >= 0) & k.validity & live
                lo = jnp.where(hit, r, 0).astype(jnp.int32)
                hi = jnp.where(hit, r + 1, lo).astype(jnp.int32)
            elif mode == "one":
                bcap_ = sorted_common.shape[0]
                lo = jnp.minimum(
                    jnp.searchsorted(sorted_common, svals, side="left"),
                    n_valid).astype(jnp.int32)
                found = (sorted_common[jnp.clip(lo, 0, bcap_ - 1)] == svals) \
                    & (lo < n_valid) & k.validity & live
                hi = jnp.where(found, lo + 1, lo).astype(jnp.int32)
            else:
                lo = jnp.minimum(
                    jnp.searchsorted(sorted_common, svals, side="left"),
                    n_valid).astype(jnp.int32)
                hi = jnp.minimum(
                    jnp.searchsorted(sorted_common, svals, side="right"),
                    n_valid).astype(jnp.int32)
                hi = jnp.where(k.validity & live, hi, lo)
            counts = J.pair_counts(lo, hi, n_stream, scap, jt)
            total = J.total_pairs(counts)
            if track_matched:
                # which eligible build rows matched: probe the sorted stream
                bk = build_keys_raw[0]
                bvals = (bk.values.astype(jnp.int8)
                         if bk.values.dtype == jnp.bool_ else bk.values)
                bvals = bvals.astype(common)  # same promotion, build→stream probe
                s_eligible = k.validity & live
                s_masked = jnp.where(
                    s_eligible, svals,
                    jnp.asarray(jnp.iinfo(svals.dtype).max, svals.dtype))
                _, s_sorted = jax.lax.sort(
                    [(~s_eligible).astype(jnp.int8), s_masked], num_keys=2)
                ns = jnp.sum(s_eligible, dtype=jnp.int32)
                blo = jnp.minimum(
                    jnp.searchsorted(s_sorted, bvals, side="left"), ns)
                bhi = jnp.minimum(
                    jnp.searchsorted(s_sorted, bvals, side="right"), ns)
                bcap = bvals.shape[0]
                b_eligible = bk.validity & (
                    jnp.arange(bcap, dtype=jnp.int32) < n_build)
                return lo, hi, counts, total, (bhi > blo) & b_eligible
            return lo, hi, counts, total, None

        # vmin/dsize/bucket count are traced into the program only in their
        # own modes; keying them otherwise would recompile per distinct
        # build key range
        key = ("join_probe_fast", jt, track_matched, mode,
               vmin if mode == "dense" else None,
               dsize if mode == "dense" else None,
               hash_buckets if mode == "pallas_hash" else None,
               self._stream_key_key,
               fuse.schema_key(stream_batch.schema)
               if stream_batch.schema else None)
        stream_cols = [Col.from_vector(c) for c in stream_batch.columns]
        n_stream = jnp.asarray(stream_batch.lazy_num_rows, jnp.int32)
        dense = (self._dense_table if mode == "dense"
                 else jnp.zeros((1,), jnp.int32))
        hk = (self._hash_keys if mode == "pallas_hash"
              else jnp.zeros((1,), jnp.int64))
        hr = (self._hash_rows if mode == "pallas_hash"
              else jnp.zeros((1,), jnp.int32))
        args = (self._sorted_build, self._n_valid,
                jnp.asarray(self.n_build, jnp.int32), self.build_keys_raw,
                stream_cols, n_stream, dense, hk, hr)
        lo, hi, counts, total, matched = fuse.call_fused(
            key, "HashJoin.probe", lambda: kernel, args,
            lambda: kernel(*args))
        if track_matched:
            self.build_matched_acc |= np.asarray(matched)
        return self._build_perm, lo, hi, counts, total

    # -- whole-stage join-chain surface (BroadcastHashJoinChainExec) ---------

    def chain_capable(self) -> bool:
        """True when this core's probe matches AT MOST ONE build row per
        stream row through a shared compiled program — the property that lets
        a stack of joins fuse into one static-shape per-batch kernel (output
        rows <= stream rows, so stream capacity bounds every hop)."""
        return (self.fast and not self.ctx_sensitive
                and self.build_matched_acc is None
                and self._probe_mode in ("dense", "one", "pallas_hash"))

    def chain_static(self):
        """Kernel-key part: everything `chain_lookup` bakes into the trace."""
        mode = self._probe_mode
        return (mode,
                getattr(self, "_vmin", None) if mode == "dense" else None,
                getattr(self, "_dense_size", None) if mode == "dense" else None,
                getattr(self, "_hash_buckets", None)
                if mode == "pallas_hash" else None)

    def chain_args(self):
        """Traced operands for `chain_lookup` (unused modes ride dummies so
        the pytree shape stays uniform across modes)."""
        mode = self._probe_mode
        dense = (self._dense_table if mode == "dense"
                 else jnp.zeros((1,), jnp.int32))
        hk = (self._hash_keys if mode == "pallas_hash"
              else jnp.zeros((1,), jnp.int64))
        hr = (self._hash_rows if mode == "pallas_hash"
              else jnp.zeros((1,), jnp.int32))
        return (self._sorted_build, self._n_valid, self._build_perm,
                dense, hk, hr)

    def chain_lookup(self):
        """Traceable single-match probe `(chain_args, stream_key_col) ->
        (build_row, hit)`: the unique-match mode branches of
        `_probe_batch_fast`, with the position->row mapping through
        `_build_perm` folded in (expand_pairs does that mapping on the
        unfused path). Validity/liveness masking is the caller's job."""
        from spark_rapids_tpu.ops import pallas_kernels as PK
        mode = self._probe_mode
        vmin = getattr(self, "_vmin", 0)
        dsize = getattr(self, "_dense_size", 0)
        buckets = getattr(self, "_hash_buckets", 0)

        def lookup(cargs, k):
            sorted_build, n_valid, perm, dense, hk, hr = cargs
            pcap = perm.shape[0]
            svals = (k.values.astype(jnp.int8)
                     if k.values.dtype == jnp.bool_ else k.values)
            if mode == "pallas_hash":
                pos, found = PK.hash_join_probe(
                    hk, hr, svals.astype(jnp.int64), buckets)
                row = perm[jnp.clip(pos, 0, pcap - 1)]
                return jnp.where(found, row, 0).astype(jnp.int32), found
            if mode == "dense":
                slot = svals.astype(jnp.int64) - vmin
                in_dom = (slot >= 0) & (slot < dsize - 1)
                r = dense[jnp.clip(slot, 0, dsize - 1)]
                hit = in_dom & (r >= 0)
                row = perm[jnp.clip(r, 0, pcap - 1)]
                return jnp.where(hit, row, 0).astype(jnp.int32), hit
            # mode == "one": single searchsorted + equality (same common-type
            # promotion as the unfused fast probe — casting the stream DOWN
            # would wrap values and fabricate matches)
            common = jnp.promote_types(svals.dtype, sorted_build.dtype)
            sc = sorted_build.astype(common)
            sv = svals.astype(common)
            bcap = sc.shape[0]
            lo = jnp.minimum(jnp.searchsorted(sc, sv, side="left"),
                             n_valid).astype(jnp.int32)
            found = (sc[jnp.clip(lo, 0, bcap - 1)] == sv) & (lo < n_valid)
            row = perm[jnp.clip(lo, 0, pcap - 1)]
            return jnp.where(found, row, 0).astype(jnp.int32), found

        return lookup

    def unmatched_build_indices(self):
        assert self.build_matched_acc is not None
        live = np.arange(self.build_cap) < self.n_build
        return np.nonzero(live & ~self.build_matched_acc)[0]

    # Retryable (reference trait behind withRestoreOnRetry): the matched-row
    # accumulator is the core's only cross-batch mutable state — a probe
    # attempt that OOMs after updating it must roll back before the split
    # pieces re-probe
    def checkpoint(self):
        self._matched_ckpt = (None if self.build_matched_acc is None
                              else self.build_matched_acc.copy())

    def restore(self):
        if getattr(self, "_matched_ckpt", None) is not None:
            self.build_matched_acc = self._matched_ckpt.copy()


class HashJoinExec(TpuExec):
    """Equi-join with a materialized build side (reference GpuShuffledHashJoinBase:97;
    children are co-partitioned by upstream exchanges)."""

    def __init__(self, join_type: str, left_keys, right_keys,
                 left: TpuExec, right: TpuExec, condition: Expression | None = None,
                 build_side: str = "right", conf=None, stream_prefilter=None,
                 stream_preproject=None, stream_schema=None):
        super().__init__(left, right, conf=conf)
        # whole-stage hoists (planner-controlled, inner single-int-key joins
        # only): `stream_prefilter` masks probe rows against the RAW stream
        # child; `stream_preproject` re-derives the hoisted projection on
        # post-join gathered rows in the emit kernel; `stream_schema` is the
        # hoisted projection's output schema (the join's stream-side
        # contribution, since the raw child is now wider)
        self.stream_prefilter = stream_prefilter
        self.stream_preproject = (list(stream_preproject)
                                  if stream_preproject is not None else None)
        self._stream_schema = stream_schema
        jt = join_type.lower().replace("_", "")
        self.join_type = jt
        if jt not in (J.INNER, J.LEFT_OUTER, J.RIGHT_OUTER, J.FULL_OUTER,
                      J.LEFT_SEMI, J.LEFT_ANTI):
            # CROSS must go through NestedLoopJoinExec: the hash-probe kernel has
            # no all-pairs mode, so accepting it here would only fail at run time
            raise ValueError(f"unsupported join type {join_type}")
        if condition is not None and jt not in (J.INNER, J.CROSS):
            # reference: conditional outer joins are not supported by GpuHashJoin
            # (GpuHashJoin.tagJoin) — the planner must fall back / use nested loop
            raise ValueError("residual join conditions only supported for inner joins")
        self.left_keys = [bind_references(k, left.output) for k in left_keys]
        self.right_keys = [bind_references(k, right.output) for k in right_keys]
        # which side streams: the preserved side streams; the other side builds
        if jt == J.RIGHT_OUTER:
            self.stream_is_left = False
        elif jt == J.INNER and build_side == "left":
            self.stream_is_left = False
        else:
            self.stream_is_left = True
        self.condition = (bind_references(condition, self.output)
                          if condition is not None else None)
        self._build_time = self.metrics.metric(M.BUILD_TIME, M.MODERATE)
        self._join_time = self.metrics.metric(M.JOIN_TIME, M.MODERATE)

    @property
    def output(self) -> T.StructType:
        lf, rf = list(self.children[0].output), list(self.children[1].output)
        if self._stream_schema is not None:
            if self.stream_is_left:
                lf = list(self._stream_schema)
            else:
                rf = list(self._stream_schema)
        if self.join_type in (J.LEFT_SEMI, J.LEFT_ANTI):
            return T.StructType(lf)
        # outer joins make the non-preserved side nullable
        if self.join_type in (J.LEFT_OUTER, J.FULL_OUTER):
            rf = [T.StructField(f.name, f.data_type, True) for f in rf]
        if self.join_type in (J.RIGHT_OUTER, J.FULL_OUTER):
            lf = [T.StructField(f.name, f.data_type, True) for f in lf]
        return T.StructType(lf + rf)

    @property
    def num_partitions(self):
        return (self.children[0] if self.stream_is_left else self.children[1]).num_partitions

    def _emit(self, stream_batch, build_batch, core, build_perm, lo, hi, counts,
              total, out_schema):
        """Expand pairs in chunks (one fused program per chunk capacity) and
        yield output batches."""
        yield from _emit_pairs(
            self.join_type, self.stream_is_left, self.condition,
            self.stream_preproject, stream_batch, build_batch, build_perm,
            lo, hi, counts, total, out_schema)

    def _probe_stream(self, core, sb, stream_child, split, out_schema):
        """Probe+emit loop shared by the shuffled and broadcast variants,
        under the task-scoped OOM ladder: each stream batch probes inside
        with_retry (an OOM spills, splits the stream batch and re-probes the
        halves — the reference withRetry over the stream iterator) with the
        matched-row accumulator checkpointed per attempt."""
        def probe(b):
            with trace_range("HashJoin.probe", self._join_time), \
                    R.with_restore_on_retry(core):
                return b, core.probe_batch(b)

        # observed stream-side input cardinality (stats plane): out rows /
        # probe rows is the join's selectivity read-out
        in_rows = self.metrics.metric(M.NUM_INPUT_ROWS, M.ESSENTIAL)
        for stream_batch in stream_child.execute_partition(split):
            in_rows.add_lazy(stream_batch.lazy_num_rows)
            acquire_semaphore(self.metrics)
            for piece, (build_perm, lo, hi, counts, total) in R.with_retry(
                    [stream_batch], probe, conf=self.conf,
                    scope="joins.gather"):
                yield from self._emit(piece, sb.get_batch(), core,
                                      build_perm, lo, hi, counts, total,
                                      out_schema)

    def execute_partition(self, split):
        def it():
            build_child = self.children[1] if self.stream_is_left else self.children[0]
            stream_child = self.children[0] if self.stream_is_left else self.children[1]
            # nested attribution frame: the build's own work (concat +
            # spillable registration, minus child pulls) lands in
            # buildSelfTime and is subtracted from this join's selfTime, so
            # the profiler can render the build as a distinct line item
            # without double counting (buildTime stays the INCLUSIVE timer)
            with trace_range("HashJoin.build", self._build_time), \
                    M.node_frame(self._node_id,
                                 self.metrics.metric(M.BUILD_SELF_TIME,
                                                     M.MODERATE)), \
                    F.scope("joins.build"):
                from spark_rapids_tpu.runtime import pipeline as P
                build_it = build_child.execute_partition(split)
                if P.enabled(self.conf):
                    # build-segment boundary: the build subtree (scan +
                    # upstream operators) produces on the stage's worker
                    # thread while this thread registers/concats
                    build_it = P.stage_iterator(
                        build_it, edge="join.build", conf=self.conf,
                        registry=self.metrics,
                        node_id=getattr(build_child, "_node_id", None),
                        spillable=True)
                build_batch = concat_all(build_it, build_child.output,
                                         conf=self.conf)
                # hold the built table spillable while we stream (reference
                # LazySpillableColumnarBatch, GpuHashJoin.scala:200); the
                # single-batch registration cannot split — spill-only retry
                sb = R.call_with_retry(
                    lambda: mem.SpillableColumnarBatch(
                        build_batch, mem.ACTIVE_BATCHING_PRIORITY),
                    scope="joins.build")
            with sb:
                bk = self.left_keys if not self.stream_is_left else self.right_keys
                sk = self.right_keys if not self.stream_is_left else self.left_keys
                core = _JoinCore(sb.get_batch(), bk, sk, self.join_type,
                                 stream_prefilter=self.stream_prefilter)
                out_schema = self.output
                yield from self._probe_stream(core, sb, stream_child, split,
                                              out_schema)
                if self.join_type == J.FULL_OUTER:
                    yield from self._emit_unmatched_build(core, sb.get_batch(),
                                                          out_schema)
        return self.wrap_output(it())

    def _emit_unmatched_build(self, core, build_batch, out_schema):
        idxs = core.unmatched_build_indices()
        if len(idxs) == 0:
            return
        n = len(idxs)
        cap = bucket_capacity(n)
        idx_dev = jnp.zeros((cap,), jnp.int32).at[:n].set(jnp.asarray(idxs, jnp.int32))
        live = jnp.arange(cap) < n
        b_cols = gather_cols([Col.from_vector(c) for c in build_batch.columns],
                             idx_dev, live)
        stream_child = self.children[0] if self.stream_is_left else self.children[1]
        s_cols = [Col(jnp.full((cap,), f.data_type.default_value(),
                               dtype=f.data_type.jnp_dtype),
                      jnp.zeros((cap,), jnp.bool_), f.data_type)
                  for f in stream_child.output]
        cols = (s_cols + b_cols) if self.stream_is_left else (b_cols + s_cols)
        yield ColumnarBatch([c.to_vector() for c in cols], n, out_schema)

    def args_string(self):
        return (f"{self.join_type} lk={self.left_keys} rk={self.right_keys}"
                + (f" cond={self.condition}" if self.condition is not None else ""))


class _SharedBroadcast:
    """Per-join consumer state over a BroadcastExchangeExec relation: a
    reader countdown (the LAST stream partition releases the relation) and a
    globally-merged matched-row accumulator so full-outer unmatched-build
    rows are emitted exactly once (reference GpuBroadcastExchangeExec + the
    shared gatherer state in GpuBroadcastNestedLoopJoinExec)."""

    def __init__(self, exchange, n_readers: int):
        from spark_rapids_tpu.exec.broadcast import BroadcastExchangeExec
        assert isinstance(exchange, BroadcastExchangeExec), exchange
        self.exchange = exchange
        self._lock = threading.Lock()
        self._readers_left = n_readers
        self.matched_acc: np.ndarray | None = None

    def get(self) -> mem.SpillableColumnarBatch:
        return self.exchange.broadcast()

    def merge_matched(self, local: np.ndarray) -> None:
        with self._lock:
            if self.matched_acc is None:
                self.matched_acc = np.zeros_like(local)
            np.logical_or(self.matched_acc, local, out=self.matched_acc)

    def finish(self) -> bool:
        """Count down one reader; True for the last one (who must close())."""
        with self._lock:
            self._readers_left -= 1
            return self._readers_left == 0

    def close(self) -> None:
        self.exchange.release()

    def reader(self):
        """Per-reader idempotent countdown handle: `finish_once()` counts
        this reader down at most once, True for the last reader overall.
        Consumers call it on the NORMAL path (to emit full-outer unmatched
        rows before closing) AND from a finally (so a stream partition
        abandoned mid-iteration — downstream limit, error, cooperative
        cancellation draining the pipeline — still releases the broadcast
        relation instead of leaking it in HBM)."""
        shared = self

        class _Reader:
            __slots__ = ("_counted",)

            def __init__(self):
                self._counted = False

            def finish_once(self) -> bool:
                if self._counted:
                    return False
                self._counted = True
                return shared.finish()

        return _Reader()


class BroadcastHashJoinExec(HashJoinExec):
    """Build side is broadcast (materialized once, shared across stream partitions)
    — reference shim GpuBroadcastHashJoinExec + GpuBroadcastExchangeExec."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        from spark_rapids_tpu.exec.broadcast import BroadcastExchangeExec
        bi = 1 if self.stream_is_left else 0
        exchange = BroadcastExchangeExec(self.children[bi], conf=self.conf)
        self.children[bi] = exchange  # plan-visible broadcast exchange node
        self._shared = _SharedBroadcast(exchange, self.num_partitions)

    def execute_partition(self, split):
        def it():
            reader = self._shared.reader()
            try:
                stream_child = self.children[0] if self.stream_is_left else self.children[1]
                with trace_range("BroadcastHashJoin.build", self._build_time):
                    sb = self._shared.get()
                bk = self.left_keys if not self.stream_is_left else self.right_keys
                sk = self.right_keys if not self.stream_is_left else self.left_keys
                core = _JoinCore(sb.get_batch(), bk, sk, self.join_type,
                                 stream_prefilter=self.stream_prefilter)
                out_schema = self.output
                yield from self._probe_stream(core, sb, stream_child, split,
                                              out_schema)
                if core.build_matched_acc is not None:
                    self._shared.merge_matched(core.build_matched_acc)
                if reader.finish_once():
                    if self.join_type == J.FULL_OUTER:
                        core.build_matched_acc = self._shared.matched_acc
                        yield from self._emit_unmatched_build(
                            core, sb.get_batch(), out_schema)
                    self._shared.close()
            finally:
                # abandoned mid-stream (limit / error / cancellation): still
                # count this reader down so the LAST one out releases the
                # broadcast relation instead of leaking it in HBM
                if reader.finish_once():
                    self._shared.close()
        return self.wrap_output(it())


def _chainable(node) -> bool:
    """A broadcast hash join the chain fuser may absorb: inner, single
    int-backed equi key, no residual condition, every hoisted term
    context-free — the static half of the contract (`_JoinCore.chain_capable`
    checks the build-content half at run time)."""
    from spark_rapids_tpu.expr.misc import is_context_free
    return (type(node) is BroadcastHashJoinExec
            and node.join_type == J.INNER and node.condition is None
            and len(node.left_keys) == 1
            and _int_backed(node.left_keys[0].dtype)
            and _int_backed(node.right_keys[0].dtype)
            and is_context_free(*node.left_keys, *node.right_keys)
            and (node.stream_prefilter is None
                 or is_context_free(node.stream_prefilter))
            and (node.stream_preproject is None
                 or is_context_free(*node.stream_preproject)))


def maybe_chain(join, conf=None):
    """Collapse `BHJ(stream=BHJ(...))` stacks into one
    BroadcastHashJoinChainExec (planner hook, bottom-up: the stream child is
    already chained if it could be). Returns `join` unchanged when the stack
    doesn't qualify."""
    if not _chainable(join):
        return join
    si = 0 if join.stream_is_left else 1
    stream = join.children[si]
    if isinstance(stream, BroadcastHashJoinChainExec):
        return BroadcastHashJoinChainExec(stream.children[0],
                                          stream.hops + [join], conf=conf)
    if _chainable(stream):
        si2 = 0 if stream.stream_is_left else 1
        return BroadcastHashJoinChainExec(stream.children[si2],
                                          [stream, join], conf=conf)
    return join


class BroadcastHashJoinChainExec(TpuExec):
    """A stack of inner single-int-key broadcast hash joins probed by ONE
    fused per-batch kernel — the whole-stage-codegen analog for q18's shape
    (probe chains between exchanges collapse into a single XLA program).

    Each absorbed join ("hop") keeps its BroadcastExchangeExec child in the
    plan tree; this node takes over the probe side. When every hop's build
    turns out unique-keyed at run time (`_JoinCore.chain_capable`: dense /
    one / pallas_hash probe modes), a stream row matches at most one build
    row per hop, so stream capacity statically bounds every intermediate —
    probe -> gather -> probe -> gather -> compact runs as one dispatch per
    batch instead of (project + probe + emit) per hop. The output lands at a
    PREDICTED capacity bucket (last batch's survivor count): steady-state
    batches pay exactly one dispatch, a mispredicted batch pays one retry at
    full capacity. Non-unique / context-sensitive builds degrade per batch
    to the classic sequential probe+emit path — degraded, never wrong."""

    stream_child_index = 0   # the fused pipeline continues into children[0]

    def __init__(self, stream, hops, conf=None):
        super().__init__(
            stream,
            *[h.children[1 if h.stream_is_left else 0] for h in hops],
            conf=conf)
        self.hops = list(hops)
        self._build_time = self.metrics.metric(M.BUILD_TIME, M.MODERATE)
        self._join_time = self.metrics.metric(M.JOIN_TIME, M.MODERATE)

    @property
    def output(self) -> T.StructType:
        return self.hops[-1].output

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def execute_partition(self, split):
        def it():
            readers = [(h, h._shared.reader()) for h in self.hops]
            try:
                with trace_range("BroadcastHashJoin.build", self._build_time):
                    # outermost hop first: the nested (unfused) iterators
                    # materialize the outer join's build before pulling the
                    # stream triggers the inner one — keep that order so
                    # chaos schedules and memory watermarks line up
                    sbs = [None] * len(self.hops)
                    for i in reversed(range(len(self.hops))):
                        sbs[i] = self.hops[i]._shared.get()
                cores = []
                for h, sb in zip(self.hops, sbs):
                    bk = (h.left_keys if not h.stream_is_left
                          else h.right_keys)
                    sk = (h.right_keys if not h.stream_is_left
                          else h.left_keys)
                    cores.append(_JoinCore(
                        sb.get_batch(), bk, sk, h.join_type,
                        stream_prefilter=h.stream_prefilter))
                fused_ok = all(c.chain_capable() for c in cores)
                out_schema = self.output
                in_rows = self.metrics.metric(M.NUM_INPUT_ROWS, M.ESSENTIAL)
                pred_cap = [None]   # survivor-count capacity prediction

                def probe(b):
                    with trace_range("HashJoinChain.probe", self._join_time):
                        return self._fused_probe(b, cores, sbs, pred_cap,
                                                 out_schema)

                for stream_batch in self.children[0].execute_partition(split):
                    in_rows.add_lazy(stream_batch.lazy_num_rows)
                    acquire_semaphore(self.metrics)
                    if fused_ok:
                        for out in R.with_retry([stream_batch], probe,
                                                conf=self.conf,
                                                scope="joins.gather"):
                            if out is not None:
                                yield out
                    else:
                        yield from self._fallback(stream_batch, cores, sbs)
            finally:
                # abandoned mid-stream (limit / error / cancellation): still
                # count each reader down so the LAST one out releases its
                # broadcast relation instead of leaking it in HBM
                for h, r in readers:
                    if r.finish_once():
                        h._shared.close()
        return self.wrap_output(it())

    def _fused_probe(self, stream_batch, cores, sbs, pred_cap, out_schema):
        """One fused program per (stream shape, output bucket): every hop's
        key eval + prefilter + unique-match lookup + build gather + stream
        preproject, then a single front-compaction, sliced to the predicted
        output bucket. Returns the output batch or None (no survivors)."""
        from spark_rapids_tpu.runtime import fuse
        scap = stream_batch.capacity
        specs = [(c.stream_key_exprs[0], c.stream_prefilter,
                  h.stream_preproject, h.stream_is_left)
                 for h, c in zip(self.hops, cores)]
        spec_key = tuple(
            (fuse.expr_key(sk),
             fuse.expr_key(pf) if pf is not None else None,
             tuple(fuse.expr_key(e) for e in pp) if pp is not None else None,
             sil)
            for sk, pf, pp, sil in specs)
        statics = tuple(c.chain_static() for c in cores)
        stream_cols = [Col.from_vector(c) for c in stream_batch.columns]
        n_stream = jnp.asarray(stream_batch.lazy_num_rows, jnp.int32)
        hop_args = tuple(
            (c.chain_args(), [Col.from_vector(x)
                              for x in sb.get_batch().columns])
            for c, sb in zip(cores, sbs))

        def run(cap):
            key = ("join_chain", cap, statics, spec_key,
                   fuse.schema_key(stream_batch.schema)
                   if stream_batch.schema else None)

            def build():
                lookups = [c.chain_lookup() for c in cores]

                def kernel(stream_cols, n_stream, hop_args):
                    cap_in = stream_cols[0].values.shape[0]
                    live = jnp.arange(cap_in, dtype=jnp.int32) < n_stream
                    cur = stream_cols
                    for lk, (cargs, b_cols), spec in zip(lookups, hop_args,
                                                         specs):
                        sk_expr, prefilter, preproject, sil = spec
                        ctx = EvalContext(cur, n_stream, cap_in)
                        if prefilter is not None:
                            p = prefilter.eval(ctx)
                            live = live & p.values & p.validity
                        k = sk_expr.eval(ctx)
                        row, hit = lk(cargs, k)
                        hit = hit & k.validity & live
                        bg = gather_cols(b_cols, jnp.where(hit, row, 0), hit)
                        s_cols = ([e.eval(ctx) for e in preproject]
                                  if preproject is not None else cur)
                        cur = (s_cols + bg) if sil else (bg + s_cols)
                        live = hit
                    out, count = compact_cols(cur, live)
                    if cap != cap_in:
                        out = slice_to_capacity(out, None, cap)
                    return out, count

                return kernel

            args = (stream_cols, n_stream, hop_args)
            return fuse.call_fused(key, "HashJoinChain.probe", build, args,
                                   lambda: build()(*args))

        cap = min(pred_cap[0], scap) if pred_cap[0] is not None else scap
        cols, count = run(cap)
        count = int(count)   # one host sync per batch (the emit-total analog)
        if count == 0:
            pred_cap[0] = bucket_capacity(1)
            return None
        # output capacity must be bucket_capacity(count) EXACTLY — the
        # unfused emit's chunk capacity — or downstream float reductions see
        # a different XLA tree shape and bit-identity breaks. Steady state
        # predicts the right bucket (1 dispatch); a miss pays one rerun.
        tgt = bucket_capacity(count)
        pred_cap[0] = tgt
        if tgt != cap:
            cols, _ = run(tgt)
        return ColumnarBatch([c.to_vector() for c in cols], count, out_schema)

    def _fallback(self, stream_batch, cores, sbs):
        """Non-unique or context-sensitive build on some hop: probe + emit
        each hop sequentially (exactly the unfused two-node behavior)."""
        batches = [stream_batch]
        for h, core, sb in zip(self.hops, cores, sbs):
            schema = h.output

            def probe(b):
                with trace_range("HashJoin.probe", self._join_time), \
                        R.with_restore_on_retry(core):
                    return b, core.probe_batch(b)

            nxt = []
            for b in batches:
                for piece, (perm, lo, hi, counts, total) in R.with_retry(
                        [b], probe, conf=self.conf, scope="joins.gather"):
                    nxt.extend(_emit_pairs(
                        h.join_type, h.stream_is_left, None,
                        h.stream_preproject, piece, sb.get_batch(), perm,
                        lo, hi, counts, total, schema))
            batches = nxt
        return batches

    def args_string(self):
        return " -> ".join(
            f"{h.join_type} lk={h.left_keys} rk={h.right_keys}"
            for h in self.hops)


class NestedLoopJoinExec(TpuExec):
    """All-pairs join with optional condition (reference
    GpuBroadcastNestedLoopJoinExec.scala — build side broadcast, every pair
    evaluated; supports cross/inner plus outer/semi/anti)."""

    def __init__(self, join_type: str, left: TpuExec, right: TpuExec,
                 condition: Expression | None = None, conf=None):
        super().__init__(left, right, conf=conf)
        jt = join_type.lower().replace("_", "")
        self.join_type = J.INNER if jt == J.CROSS else jt
        if self.join_type == J.RIGHT_OUTER:
            raise ValueError("right outer nested-loop join: swap the inputs and "
                             "plan a left outer (the planner mirrors the reference's "
                             "build-side rules)")
        self.condition = (bind_references(condition, self._pair_schema())
                          if condition is not None else None)
        self._join_time = self.metrics.metric(M.JOIN_TIME, M.MODERATE)
        from spark_rapids_tpu.exec.broadcast import BroadcastExchangeExec
        exchange = BroadcastExchangeExec(self.children[1], conf=self.conf)
        self.children[1] = exchange  # plan-visible broadcast exchange node
        self._shared = _SharedBroadcast(exchange, self.num_partitions)

    def _pair_schema(self):
        return T.StructType(list(self.children[0].output) +
                            list(self.children[1].output))

    @property
    def output(self):
        lf, rf = list(self.children[0].output), list(self.children[1].output)
        if self.join_type in (J.LEFT_SEMI, J.LEFT_ANTI):
            return T.StructType(lf)
        if self.join_type in (J.LEFT_OUTER, J.FULL_OUTER):
            rf = [T.StructField(f.name, f.data_type, True) for f in rf]
        if self.join_type in (J.RIGHT_OUTER, J.FULL_OUTER):
            lf = [T.StructField(f.name, f.data_type, True) for f in lf]
        return T.StructType(lf + rf)

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def execute_partition(self, split):
        def it():
            reader = self._shared.reader()
            try:
                sb = self._shared.get()
                build = sb.get_batch()
                n_build = build.num_rows
                out_schema = self.output
                pair_schema = self._pair_schema()
                right_matched_acc = (np.zeros(build.capacity, dtype=bool)
                                     if self.join_type == J.FULL_OUTER else None)
                for lb in self.children[0].execute_partition(split):
                    acquire_semaphore(self.metrics)
                    with trace_range("NestedLoopJoin", self._join_time):
                        yield from self._join_batch(lb, build, n_build, out_schema,
                                                    pair_schema, right_matched_acc)
                if right_matched_acc is not None:
                    self._shared.merge_matched(right_matched_acc)
                if reader.finish_once():
                    if self.join_type == J.FULL_OUTER:
                        yield from self._unmatched_right(
                            build, n_build, self._shared.matched_acc, out_schema)
                    self._shared.close()
            finally:
                # same contract as BroadcastHashJoinExec: an abandoned
                # reader still counts down; the last one out releases
                if reader.finish_once():
                    self._shared.close()
        return self.wrap_output(it())

    def _join_batch(self, lb, build, n_build, out_schema, pair_schema, matched_acc):
        n_left = lb.num_rows
        lcols = [Col.from_vector(c) for c in lb.columns]
        rcols = [Col.from_vector(c) for c in build.columns]
        total = n_left * n_build
        left_match = np.zeros(lb.capacity, dtype=bool)
        jt = self.join_type
        # inner/outer pair chunks stream out as soon as each is produced so only
        # one expansion chunk is live at a time; semi/anti only need match flags
        emit_pairs = jt in (J.INNER, J.LEFT_OUTER, J.FULL_OUTER)
        pos = 0
        while pos < total:
            out_cap = bucket_capacity(min(total - pos, _MAX_CHUNK_ROWS))
            j = jnp.arange(out_cap, dtype=jnp.int32) + jnp.int32(pos)
            li = jnp.clip(j // max(n_build, 1), 0, lb.capacity - 1)
            ri = jnp.clip(j % max(n_build, 1), 0, build.capacity - 1)
            live = j < total
            lg = gather_cols(lcols, li, live)
            rg = gather_cols(rcols, ri, live)
            n_out = min(total - pos, out_cap)
            batch = ColumnarBatch([c.to_vector() for c in lg + rg], n_out, pair_schema)
            if self.condition is not None:
                ctx = EvalContext.from_batch(batch)
                pred = self.condition.eval(ctx)
                keep = selection_mask(pred, batch.lazy_num_rows, batch.capacity)
                # track which left/right rows matched (for outer/semi/anti)
                keep_h = np.asarray(keep)
                li_h, ri_h = np.asarray(li), np.asarray(ri)
                np.logical_or.at(left_match, li_h[keep_h], True)
                if matched_acc is not None:
                    np.logical_or.at(matched_acc, ri_h[keep_h], True)
                cols, count = compact_cols([Col.from_vector(c) for c in batch.columns],
                                           keep)
                batch = ColumnarBatch([c.to_vector() for c in cols], int(count),
                                      pair_schema)
            else:
                left_match[np.asarray(li[:n_out])] = True if n_build > 0 else False
                if matched_acc is not None and n_left > 0:
                    matched_acc[:n_build] = True
            pos += out_cap
            if emit_pairs and batch.num_rows:
                yield batch
        if jt in (J.LEFT_OUTER, J.FULL_OUTER):
            yield from self._unmatched_left(lb, lcols, left_match, out_schema)
        elif jt in (J.LEFT_SEMI, J.LEFT_ANTI):
            want = left_match if jt == J.LEFT_SEMI else ~left_match
            if self.condition is None and jt == J.LEFT_SEMI and n_build == 0:
                want = np.zeros_like(left_match)
            if self.condition is None and jt == J.LEFT_ANTI:
                want = (~left_match if n_build > 0 else
                        np.ones_like(left_match))
            keep = jnp.asarray(want) & (jnp.arange(lb.capacity) < n_left)
            cols, count = compact_cols(lcols, keep)
            if int(count):
                yield ColumnarBatch([c.to_vector() for c in cols], int(count),
                                    out_schema)

    def _unmatched_left(self, lb, lcols, left_match, out_schema):
        live = np.arange(lb.capacity) < lb.num_rows
        idxs = np.nonzero(live & ~left_match)[0]
        if len(idxs) == 0:
            return
        n = len(idxs)
        cap = bucket_capacity(n)
        idx_dev = jnp.zeros((cap,), jnp.int32).at[:n].set(jnp.asarray(idxs, jnp.int32))
        lg = gather_cols(lcols, idx_dev, jnp.arange(cap) < n)
        rnull = [Col(jnp.full((cap,), f.data_type.default_value(),
                              dtype=f.data_type.jnp_dtype),
                     jnp.zeros((cap,), jnp.bool_), f.data_type)
                 for f in self.children[1].output]
        yield ColumnarBatch([c.to_vector() for c in lg + rnull], n, out_schema)

    def _unmatched_right(self, build, n_build, matched_acc, out_schema):
        live = np.arange(build.capacity) < n_build
        idxs = np.nonzero(live & ~matched_acc)[0]
        if len(idxs) == 0:
            return
        n = len(idxs)
        cap = bucket_capacity(n)
        idx_dev = jnp.zeros((cap,), jnp.int32).at[:n].set(jnp.asarray(idxs, jnp.int32))
        rg = gather_cols([Col.from_vector(c) for c in build.columns], idx_dev,
                         jnp.arange(cap) < n)
        lnull = [Col(jnp.full((cap,), f.data_type.default_value(),
                              dtype=f.data_type.jnp_dtype),
                     jnp.zeros((cap,), jnp.bool_), f.data_type)
                 for f in self.children[0].output]
        yield ColumnarBatch([c.to_vector() for c in lnull + rg], n, out_schema)

    def args_string(self):
        return f"{self.join_type}" + (f" cond={self.condition}"
                                      if self.condition is not None else "")


class CartesianProductExec(NestedLoopJoinExec):
    """Reference GpuCartesianProductExec.scala — cross product of all partitions."""

    def __init__(self, left, right, condition=None, conf=None):
        super().__init__(J.CROSS, left, right, condition=condition, conf=conf)
