"""Join physical operators — shuffled-hash, broadcast-hash, nested-loop, cartesian.

Reference (SURVEY.md component #16): GpuHashJoin.scala:386 (`HashJoinIterator`:179
streams probe batches against a spillable built table), JoinGatherer.scala (bounded
gather-map iteration), GpuShuffledHashJoinBase.scala:97, shim GpuBroadcastHashJoinExec,
GpuBroadcastNestedLoopJoinExec.scala, GpuCartesianProductExec.scala.

The kernel side (ops/joining.py) replaces cudf's hash-table gather maps with a fused
rank-sort + searchsorted probe; this layer owns build-side materialization (single
spillable batch, like the reference's LazySpillableColumnarBatch build side), the
streamed probe loop, chunked output expansion, residual condition filtering, and
full-outer unmatched-build tracking across the whole stream.

Join type support matrix mirrors the reference (GpuHashJoin.tagJoin): equi-joins for
inner/left/right/full/semi/anti; residual conditions on inner only (the reference
falls conditional outer joins back to CPU / nested-loop); nested-loop handles cross
and conditional inner plus outer/semi/anti against a broadcast build side.
"""

from __future__ import annotations

import threading

import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity
from spark_rapids_tpu.exec.base import TpuExec, TaskContext, acquire_semaphore
from spark_rapids_tpu.exec.coalesce import concat_all
from spark_rapids_tpu.expr.core import Col, EvalContext, Expression, bind_references
from spark_rapids_tpu.ops import joining as J
from spark_rapids_tpu.ops.filtering import gather_cols, selection_mask, compact_cols
from spark_rapids_tpu.ops.strings import union_dictionaries
from spark_rapids_tpu.runtime import memory as mem
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.tracing import trace_range

# max pairs expanded per output chunk (the JoinGatherer row-target analog)
_MAX_CHUNK_ROWS = 1 << 20


def _align_string_keys(build_keys, stream_keys):
    out_b, out_s = [], []
    for b, s in zip(build_keys, stream_keys):
        if b.is_string:
            b, s = union_dictionaries(b, s)
        out_b.append(b)
        out_s.append(s)
    return out_b, out_s


def _null_extended(cols, idx, valid):
    """Gather `cols` rows by idx where valid, null otherwise (outer join side)."""
    return gather_cols(cols, idx, valid)


class _JoinCore:
    """Shared probe machinery over one materialized build batch."""

    def __init__(self, build_batch: ColumnarBatch, build_key_exprs,
                 stream_key_exprs, join_type: str):
        self.build_batch = build_batch
        self.build_key_exprs = build_key_exprs
        self.stream_key_exprs = stream_key_exprs
        self.join_type = join_type
        bctx = EvalContext.from_batch(build_batch)
        self.build_keys_raw = [e.eval(bctx) for e in build_key_exprs]
        self.n_build = build_batch.num_rows
        self.build_cap = build_batch.capacity
        # matched-build tracking for full outer (host accumulation across stream)
        self.build_matched_acc = (np.zeros(self.build_cap, dtype=bool)
                                  if join_type == J.FULL_OUTER else None)

    def probe_batch(self, stream_batch: ColumnarBatch):
        sctx = EvalContext.from_batch(stream_batch)
        stream_keys = [e.eval(sctx) for e in self.stream_key_exprs]
        build_keys, stream_keys = _align_string_keys(self.build_keys_raw, stream_keys)
        b_ranks, s_ranks = J.join_ranks(
            build_keys, self.n_build, self.build_cap,
            stream_keys, stream_batch.lazy_num_rows, stream_batch.capacity)
        build_perm, lo, hi = J.probe(b_ranks, s_ranks)
        # from the stream (preserved) side's perspective, right/full outer are a
        # left outer over the swapped/streamed input
        jt = (J.LEFT_OUTER if self.join_type in (J.FULL_OUTER, J.RIGHT_OUTER)
              else self.join_type)
        counts = J.pair_counts(lo, hi, stream_batch.lazy_num_rows,
                               stream_batch.capacity, jt)
        if self.build_matched_acc is not None:
            # symmetric probe: which build rows matched this stream batch
            s_perm, blo, bhi = J.probe(s_ranks, b_ranks)
            matched = np.asarray((bhi - blo) > 0)
            self.build_matched_acc |= matched
        return build_perm, lo, hi, counts

    def unmatched_build_indices(self):
        assert self.build_matched_acc is not None
        live = np.arange(self.build_cap) < self.n_build
        return np.nonzero(live & ~self.build_matched_acc)[0]


class HashJoinExec(TpuExec):
    """Equi-join with a materialized build side (reference GpuShuffledHashJoinBase:97;
    children are co-partitioned by upstream exchanges)."""

    def __init__(self, join_type: str, left_keys, right_keys,
                 left: TpuExec, right: TpuExec, condition: Expression | None = None,
                 build_side: str = "right", conf=None):
        super().__init__(left, right, conf=conf)
        jt = join_type.lower().replace("_", "")
        self.join_type = jt
        if jt not in (J.INNER, J.LEFT_OUTER, J.RIGHT_OUTER, J.FULL_OUTER,
                      J.LEFT_SEMI, J.LEFT_ANTI):
            # CROSS must go through NestedLoopJoinExec: the hash-probe kernel has
            # no all-pairs mode, so accepting it here would only fail at run time
            raise ValueError(f"unsupported join type {join_type}")
        if condition is not None and jt not in (J.INNER, J.CROSS):
            # reference: conditional outer joins are not supported by GpuHashJoin
            # (GpuHashJoin.tagJoin) — the planner must fall back / use nested loop
            raise ValueError("residual join conditions only supported for inner joins")
        self.left_keys = [bind_references(k, left.output) for k in left_keys]
        self.right_keys = [bind_references(k, right.output) for k in right_keys]
        # which side streams: the preserved side streams; the other side builds
        if jt == J.RIGHT_OUTER:
            self.stream_is_left = False
        elif jt == J.INNER and build_side == "left":
            self.stream_is_left = False
        else:
            self.stream_is_left = True
        self.condition = (bind_references(condition, self.output)
                          if condition is not None else None)
        self._build_time = self.metrics.metric(M.BUILD_TIME, M.MODERATE)
        self._join_time = self.metrics.metric(M.JOIN_TIME, M.MODERATE)

    @property
    def output(self) -> T.StructType:
        lf, rf = list(self.children[0].output), list(self.children[1].output)
        if self.join_type in (J.LEFT_SEMI, J.LEFT_ANTI):
            return T.StructType(lf)
        # outer joins make the non-preserved side nullable
        if self.join_type in (J.LEFT_OUTER, J.FULL_OUTER):
            rf = [T.StructField(f.name, f.data_type, True) for f in rf]
        if self.join_type in (J.RIGHT_OUTER, J.FULL_OUTER):
            lf = [T.StructField(f.name, f.data_type, True) for f in lf]
        return T.StructType(lf + rf)

    @property
    def num_partitions(self):
        return (self.children[0] if self.stream_is_left else self.children[1]).num_partitions

    def _emit(self, stream_batch, build_batch, core, build_perm, lo, hi, counts,
              out_schema):
        """Expand pairs in chunks and yield output batches."""
        total = int(J.total_pairs(counts))
        semi_anti = self.join_type in (J.LEFT_SEMI, J.LEFT_ANTI)
        pos = 0
        while pos < total:
            out_cap = bucket_capacity(min(total - pos, _MAX_CHUNK_ROWS))
            s_idx, b_idx, b_matched, live = J.expand_pairs(
                build_perm, lo, hi, counts, pos, out_cap)
            n_out = min(total - pos, out_cap)
            s_cols = gather_cols([Col.from_vector(c) for c in stream_batch.columns],
                                 s_idx, live)
            if semi_anti:
                cols = s_cols
            else:
                b_cols = _null_extended(
                    [Col.from_vector(c) for c in build_batch.columns], b_idx,
                    b_matched)
                cols = (s_cols + b_cols) if self.stream_is_left else (b_cols + s_cols)
            batch = ColumnarBatch([c.to_vector() for c in cols], n_out, out_schema)
            if self.condition is not None:
                batch = self._filter_condition(batch)
            yield batch
            pos += out_cap

    def _filter_condition(self, batch):
        ctx = EvalContext.from_batch(batch)
        pred = self.condition.eval(ctx)
        keep = selection_mask(pred, batch.lazy_num_rows, batch.capacity)
        cols, count = compact_cols([Col.from_vector(c) for c in batch.columns], keep)
        return ColumnarBatch([c.to_vector() for c in cols], count, batch.schema)

    def execute_partition(self, split):
        def it():
            build_child = self.children[1] if self.stream_is_left else self.children[0]
            stream_child = self.children[0] if self.stream_is_left else self.children[1]
            with trace_range("HashJoin.build", self._build_time):
                build_batch = concat_all(build_child.execute_partition(split),
                                         build_child.output)
            # hold the built table spillable while we stream (reference
            # LazySpillableColumnarBatch, GpuHashJoin.scala:200)
            with mem.SpillableColumnarBatch(build_batch,
                                            mem.ACTIVE_BATCHING_PRIORITY) as sb:
                bk = self.left_keys if not self.stream_is_left else self.right_keys
                sk = self.right_keys if not self.stream_is_left else self.left_keys
                core = _JoinCore(sb.get_batch(), bk, sk, self.join_type)
                out_schema = self.output
                for stream_batch in stream_child.execute_partition(split):
                    acquire_semaphore(self.metrics)
                    with trace_range("HashJoin.probe", self._join_time):
                        build_perm, lo, hi, counts = core.probe_batch(stream_batch)
                    yield from self._emit(stream_batch, sb.get_batch(), core,
                                          build_perm, lo, hi, counts, out_schema)
                if self.join_type == J.FULL_OUTER:
                    yield from self._emit_unmatched_build(core, sb.get_batch(),
                                                          out_schema)
        return self.wrap_output(it())

    def _emit_unmatched_build(self, core, build_batch, out_schema):
        idxs = core.unmatched_build_indices()
        if len(idxs) == 0:
            return
        n = len(idxs)
        cap = bucket_capacity(n)
        idx_dev = jnp.zeros((cap,), jnp.int32).at[:n].set(jnp.asarray(idxs, jnp.int32))
        live = jnp.arange(cap) < n
        b_cols = gather_cols([Col.from_vector(c) for c in build_batch.columns],
                             idx_dev, live)
        stream_child = self.children[0] if self.stream_is_left else self.children[1]
        s_cols = [Col(jnp.full((cap,), f.data_type.default_value(),
                               dtype=f.data_type.jnp_dtype),
                      jnp.zeros((cap,), jnp.bool_), f.data_type)
                  for f in stream_child.output]
        cols = (s_cols + b_cols) if self.stream_is_left else (b_cols + s_cols)
        yield ColumnarBatch([c.to_vector() for c in cols], n, out_schema)

    def args_string(self):
        return (f"{self.join_type} lk={self.left_keys} rk={self.right_keys}"
                + (f" cond={self.condition}" if self.condition is not None else ""))


class _SharedBroadcast:
    """Per-join consumer state over a BroadcastExchangeExec relation: a
    reader countdown (the LAST stream partition releases the relation) and a
    globally-merged matched-row accumulator so full-outer unmatched-build
    rows are emitted exactly once (reference GpuBroadcastExchangeExec + the
    shared gatherer state in GpuBroadcastNestedLoopJoinExec)."""

    def __init__(self, exchange, n_readers: int):
        from spark_rapids_tpu.exec.broadcast import BroadcastExchangeExec
        assert isinstance(exchange, BroadcastExchangeExec), exchange
        self.exchange = exchange
        self._lock = threading.Lock()
        self._readers_left = n_readers
        self.matched_acc: np.ndarray | None = None

    def get(self) -> mem.SpillableColumnarBatch:
        return self.exchange.broadcast()

    def merge_matched(self, local: np.ndarray) -> None:
        with self._lock:
            if self.matched_acc is None:
                self.matched_acc = np.zeros_like(local)
            np.logical_or(self.matched_acc, local, out=self.matched_acc)

    def finish(self) -> bool:
        """Count down one reader; True for the last one (who must close())."""
        with self._lock:
            self._readers_left -= 1
            return self._readers_left == 0

    def close(self) -> None:
        self.exchange.release()


class BroadcastHashJoinExec(HashJoinExec):
    """Build side is broadcast (materialized once, shared across stream partitions)
    — reference shim GpuBroadcastHashJoinExec + GpuBroadcastExchangeExec."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        from spark_rapids_tpu.exec.broadcast import BroadcastExchangeExec
        bi = 1 if self.stream_is_left else 0
        exchange = BroadcastExchangeExec(self.children[bi], conf=self.conf)
        self.children[bi] = exchange  # plan-visible broadcast exchange node
        self._shared = _SharedBroadcast(exchange, self.num_partitions)

    def execute_partition(self, split):
        def it():
            stream_child = self.children[0] if self.stream_is_left else self.children[1]
            with trace_range("BroadcastHashJoin.build", self._build_time):
                sb = self._shared.get()
            bk = self.left_keys if not self.stream_is_left else self.right_keys
            sk = self.right_keys if not self.stream_is_left else self.left_keys
            core = _JoinCore(sb.get_batch(), bk, sk, self.join_type)
            out_schema = self.output
            for stream_batch in stream_child.execute_partition(split):
                acquire_semaphore(self.metrics)
                with trace_range("BroadcastHashJoin.probe", self._join_time):
                    build_perm, lo, hi, counts = core.probe_batch(stream_batch)
                yield from self._emit(stream_batch, sb.get_batch(), core,
                                      build_perm, lo, hi, counts, out_schema)
            if core.build_matched_acc is not None:
                self._shared.merge_matched(core.build_matched_acc)
            if self._shared.finish():
                if self.join_type == J.FULL_OUTER:
                    core.build_matched_acc = self._shared.matched_acc
                    yield from self._emit_unmatched_build(core, sb.get_batch(),
                                                          out_schema)
                self._shared.close()
        return self.wrap_output(it())


class NestedLoopJoinExec(TpuExec):
    """All-pairs join with optional condition (reference
    GpuBroadcastNestedLoopJoinExec.scala — build side broadcast, every pair
    evaluated; supports cross/inner plus outer/semi/anti)."""

    def __init__(self, join_type: str, left: TpuExec, right: TpuExec,
                 condition: Expression | None = None, conf=None):
        super().__init__(left, right, conf=conf)
        jt = join_type.lower().replace("_", "")
        self.join_type = J.INNER if jt == J.CROSS else jt
        if self.join_type == J.RIGHT_OUTER:
            raise ValueError("right outer nested-loop join: swap the inputs and "
                             "plan a left outer (the planner mirrors the reference's "
                             "build-side rules)")
        self.condition = (bind_references(condition, self._pair_schema())
                          if condition is not None else None)
        self._join_time = self.metrics.metric(M.JOIN_TIME, M.MODERATE)
        from spark_rapids_tpu.exec.broadcast import BroadcastExchangeExec
        exchange = BroadcastExchangeExec(self.children[1], conf=self.conf)
        self.children[1] = exchange  # plan-visible broadcast exchange node
        self._shared = _SharedBroadcast(exchange, self.num_partitions)

    def _pair_schema(self):
        return T.StructType(list(self.children[0].output) +
                            list(self.children[1].output))

    @property
    def output(self):
        lf, rf = list(self.children[0].output), list(self.children[1].output)
        if self.join_type in (J.LEFT_SEMI, J.LEFT_ANTI):
            return T.StructType(lf)
        if self.join_type in (J.LEFT_OUTER, J.FULL_OUTER):
            rf = [T.StructField(f.name, f.data_type, True) for f in rf]
        if self.join_type in (J.RIGHT_OUTER, J.FULL_OUTER):
            lf = [T.StructField(f.name, f.data_type, True) for f in lf]
        return T.StructType(lf + rf)

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def execute_partition(self, split):
        def it():
            sb = self._shared.get()
            build = sb.get_batch()
            n_build = build.num_rows
            out_schema = self.output
            pair_schema = self._pair_schema()
            right_matched_acc = (np.zeros(build.capacity, dtype=bool)
                                 if self.join_type == J.FULL_OUTER else None)
            for lb in self.children[0].execute_partition(split):
                acquire_semaphore(self.metrics)
                with trace_range("NestedLoopJoin", self._join_time):
                    yield from self._join_batch(lb, build, n_build, out_schema,
                                                pair_schema, right_matched_acc)
            if right_matched_acc is not None:
                self._shared.merge_matched(right_matched_acc)
            if self._shared.finish():
                if self.join_type == J.FULL_OUTER:
                    yield from self._unmatched_right(
                        build, n_build, self._shared.matched_acc, out_schema)
                self._shared.close()
        return self.wrap_output(it())

    def _join_batch(self, lb, build, n_build, out_schema, pair_schema, matched_acc):
        n_left = lb.num_rows
        lcols = [Col.from_vector(c) for c in lb.columns]
        rcols = [Col.from_vector(c) for c in build.columns]
        total = n_left * n_build
        left_match = np.zeros(lb.capacity, dtype=bool)
        jt = self.join_type
        # inner/outer pair chunks stream out as soon as each is produced so only
        # one expansion chunk is live at a time; semi/anti only need match flags
        emit_pairs = jt in (J.INNER, J.LEFT_OUTER, J.FULL_OUTER)
        pos = 0
        while pos < total:
            out_cap = bucket_capacity(min(total - pos, _MAX_CHUNK_ROWS))
            j = jnp.arange(out_cap, dtype=jnp.int32) + jnp.int32(pos)
            li = jnp.clip(j // max(n_build, 1), 0, lb.capacity - 1)
            ri = jnp.clip(j % max(n_build, 1), 0, build.capacity - 1)
            live = j < total
            lg = gather_cols(lcols, li, live)
            rg = gather_cols(rcols, ri, live)
            n_out = min(total - pos, out_cap)
            batch = ColumnarBatch([c.to_vector() for c in lg + rg], n_out, pair_schema)
            if self.condition is not None:
                ctx = EvalContext.from_batch(batch)
                pred = self.condition.eval(ctx)
                keep = selection_mask(pred, batch.lazy_num_rows, batch.capacity)
                # track which left/right rows matched (for outer/semi/anti)
                keep_h = np.asarray(keep)
                li_h, ri_h = np.asarray(li), np.asarray(ri)
                np.logical_or.at(left_match, li_h[keep_h], True)
                if matched_acc is not None:
                    np.logical_or.at(matched_acc, ri_h[keep_h], True)
                cols, count = compact_cols([Col.from_vector(c) for c in batch.columns],
                                           keep)
                batch = ColumnarBatch([c.to_vector() for c in cols], int(count),
                                      pair_schema)
            else:
                left_match[np.asarray(li[:n_out])] = True if n_build > 0 else False
                if matched_acc is not None and n_left > 0:
                    matched_acc[:n_build] = True
            pos += out_cap
            if emit_pairs and batch.num_rows:
                yield batch
        if jt in (J.LEFT_OUTER, J.FULL_OUTER):
            yield from self._unmatched_left(lb, lcols, left_match, out_schema)
        elif jt in (J.LEFT_SEMI, J.LEFT_ANTI):
            want = left_match if jt == J.LEFT_SEMI else ~left_match
            if self.condition is None and jt == J.LEFT_SEMI and n_build == 0:
                want = np.zeros_like(left_match)
            if self.condition is None and jt == J.LEFT_ANTI:
                want = (~left_match if n_build > 0 else
                        np.ones_like(left_match))
            keep = jnp.asarray(want) & (jnp.arange(lb.capacity) < n_left)
            cols, count = compact_cols(lcols, keep)
            if int(count):
                yield ColumnarBatch([c.to_vector() for c in cols], int(count),
                                    out_schema)

    def _unmatched_left(self, lb, lcols, left_match, out_schema):
        live = np.arange(lb.capacity) < lb.num_rows
        idxs = np.nonzero(live & ~left_match)[0]
        if len(idxs) == 0:
            return
        n = len(idxs)
        cap = bucket_capacity(n)
        idx_dev = jnp.zeros((cap,), jnp.int32).at[:n].set(jnp.asarray(idxs, jnp.int32))
        lg = gather_cols(lcols, idx_dev, jnp.arange(cap) < n)
        rnull = [Col(jnp.full((cap,), f.data_type.default_value(),
                              dtype=f.data_type.jnp_dtype),
                     jnp.zeros((cap,), jnp.bool_), f.data_type)
                 for f in self.children[1].output]
        yield ColumnarBatch([c.to_vector() for c in lg + rnull], n, out_schema)

    def _unmatched_right(self, build, n_build, matched_acc, out_schema):
        live = np.arange(build.capacity) < n_build
        idxs = np.nonzero(live & ~matched_acc)[0]
        if len(idxs) == 0:
            return
        n = len(idxs)
        cap = bucket_capacity(n)
        idx_dev = jnp.zeros((cap,), jnp.int32).at[:n].set(jnp.asarray(idxs, jnp.int32))
        rg = gather_cols([Col.from_vector(c) for c in build.columns], idx_dev,
                         jnp.arange(cap) < n)
        lnull = [Col(jnp.full((cap,), f.data_type.default_value(),
                              dtype=f.data_type.jnp_dtype),
                     jnp.zeros((cap,), jnp.bool_), f.data_type)
                 for f in self.children[0].output]
        yield ColumnarBatch([c.to_vector() for c in lnull + rg], n, out_schema)

    def args_string(self):
        return f"{self.join_type}" + (f" cond={self.condition}"
                                      if self.condition is not None else "")


class CartesianProductExec(NestedLoopJoinExec):
    """Reference GpuCartesianProductExec.scala — cross product of all partitions."""

    def __init__(self, left, right, condition=None, conf=None):
        super().__init__(J.CROSS, left, right, condition=condition, conf=conf)
