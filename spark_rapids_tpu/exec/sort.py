"""Sort exec (reference GpuSortExec.scala:56). Batches within a partition are
concatenated then sorted in one fused XLA program; SortOrder carries Spark's
ASC/DESC + NULLS FIRST/LAST semantics (ops/sorting.py)."""

from __future__ import annotations

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore
from spark_rapids_tpu.expr.core import EvalContext, bind_references
from spark_rapids_tpu.ops.filtering import gather_cols
from spark_rapids_tpu.ops.sorting import SortOrder, sort_permutation
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.tracing import trace_range

import jax.numpy as jnp


class SortExec(TpuExec):
    def __init__(self, sort_exprs: list, orders: list, child: TpuExec,
                 global_sort: bool = False, conf=None):
        """sort_exprs: expressions producing sort keys; orders: list[SortOrder].
        global_sort gathers every partition first (a total order, as Spark gets
        from range-partition + per-partition sort; out-of-core merge is the
        RangePartitioner path in the exchange layer)."""
        if global_sort and child.num_partitions > 1:
            child = _GatherAllExec(child, conf=conf)
        super().__init__(child, conf=conf)
        self.sort_exprs = [bind_references(e, child.output) for e in sort_exprs]
        self.orders = list(orders)
        self.global_sort = global_sort
        self._sort_time = self.metrics.metric(M.SORT_TIME, M.MODERATE)

    @property
    def output(self):
        return self.child.output

    def execute_partition(self, split):
        def it():
            # single-batch goal via the coalesce layer (reference
            # GpuSortExec + RequireSingleBatch): inputs accumulate in the
            # SPILL STORE — under HBM pressure earlier batches move to
            # host/disk instead of OOMing — with leak-safe close on error
            from spark_rapids_tpu.exec.coalesce import concat_all
            from spark_rapids_tpu.runtime import pipeline as P
            from spark_rapids_tpu.runtime import retry as R
            src = self.child.execute_partition(split)
            if P.enabled(self.conf):
                # sort-segment boundary: the input subtree produces on the
                # stage's worker thread while this thread accumulates the
                # single-batch goal in the spill store
                src = P.stage_iterator(
                    src, edge="sort.input", conf=self.conf,
                    registry=self.metrics,
                    node_id=getattr(self.child, "_node_id", None),
                    spillable=True)
            batch = concat_all(src, self.child.output, conf=self.conf)
            if batch.num_rows == 0:
                return
            acquire_semaphore(self.metrics)

            def run_sort():
                with trace_range("SortExec", self._sort_time):
                    from spark_rapids_tpu.expr.core import Col
                    from spark_rapids_tpu.expr.misc import CONTEXT_SENSITIVE
                    from spark_rapids_tpu.runtime import fuse
                    exprs, orders = self.sort_exprs, self.orders
                    ctx_sensitive = any(
                        e.collect(lambda x: isinstance(x, CONTEXT_SENSITIVE))
                        for e in exprs)

                    def kernel(cols, num_rows):
                        cap = cols[0].values.shape[0]
                        ctx = EvalContext(cols, num_rows, cap)
                        key_cols = [e.eval(ctx) for e in exprs]
                        perm = sort_permutation(key_cols, orders, num_rows, cap)
                        live = jnp.arange(cap, dtype=jnp.int32) < num_rows
                        return gather_cols(ctx.cols, perm, live)

                    if ctx_sensitive or not batch.columns:
                        ctx = EvalContext.from_batch(batch, split)
                        key_cols = [e.eval(ctx) for e in exprs]
                        perm = sort_permutation(key_cols, orders, ctx.num_rows,
                                                ctx.capacity)
                        live = (jnp.arange(ctx.capacity, dtype=jnp.int32)
                                < ctx.num_rows)
                        return gather_cols(ctx.cols, perm, live)
                    key = ("sort", fuse.schema_key(self.child.output),
                           tuple(fuse.expr_key(e) for e in exprs),
                           tuple(repr(o) for o in orders))
                    in_cols = [Col.from_vector(c) for c in batch.columns]
                    nr = jnp.asarray(batch.lazy_num_rows, jnp.int32)
                    return fuse.call_fused(key, "SortExec", lambda: kernel,
                                           (in_cols, nr),
                                           lambda: kernel(in_cols, nr))

            # the total sort needs the whole batch (its inputs already sit
            # spill-protected in the catalog while accumulating) — an OOM
            # here gets spill-only retries (withRetryNoSplit)
            cols = R.call_with_retry(run_sort, scope="sort.sort")
            yield ColumnarBatch([c.to_vector() for c in cols],
                                batch.lazy_num_rows, self.output)
        return self.wrap_output(it())

    def args_string(self):
        return str(list(zip(self.sort_exprs, self.orders)))


class TakeOrderedAndProjectExec(TpuExec):
    """limit + sort + project (reference GpuTakeOrderedAndProjectExec, limit.scala).
    Sorts each partition, takes the first `limit` rows, then the driver merges."""

    def __init__(self, limit: int, sort_exprs, orders, project_list, child, conf=None):
        super().__init__(child, conf=conf)
        self.limit = limit
        self.sort_exprs = sort_exprs
        self.orders = orders
        self.project_list = project_list

    @property
    def output(self):
        from spark_rapids_tpu.exec.basic import ProjectExec
        if self.project_list:
            tmp = ProjectExec(self.project_list, self.child, conf=self.conf)
            return tmp.output
        return self.child.output

    @property
    def num_partitions(self):
        return 1

    def execute_partition(self, split):
        from spark_rapids_tpu.exec.basic import LocalLimitExec, ProjectExec
        inner = SortExec(self.sort_exprs, self.orders, _GatherAllExec(self.child),
                         conf=self.conf)
        plan: TpuExec = LocalLimitExec(self.limit, inner, conf=self.conf)
        if self.project_list:
            plan = ProjectExec(self.project_list, plan, conf=self.conf)
        return self.wrap_output(plan.execute_partition(0))


class _GatherAllExec(TpuExec):
    """Pulls every child partition into one (driver-side single partition)."""

    def __init__(self, child, conf=None):
        super().__init__(child, conf=conf)

    @property
    def output(self):
        return self.child.output

    @property
    def num_partitions(self):
        return 1

    def execute_partition(self, split):
        for p in range(self.child.num_partitions):
            yield from self.child.execute_partition(p)
