"""Batch coalescing — goal-driven re-batching of a columnar stream.

Reference (SURVEY.md component #21): GpuCoalesceBatches.scala — `CoalesceGoal`:92
(`TargetSize`, `RequireSingleBatch`), `AbstractGpuCoalesceIterator`:133 (collect
batches until the goal is hit, then concat on device), `GpuCoalesceBatches`:455.
Batches awaiting concat are held spillable (reference makes the on-deck batch
spillable) so a large coalesce cannot OOM the device.
"""

from __future__ import annotations

import dataclasses

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.runtime import memory as mem
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import retry as R


@dataclasses.dataclass(frozen=True)
class CoalesceGoal:
    """Base goal (reference CoalesceGoal:92)."""


@dataclasses.dataclass(frozen=True)
class TargetSize(CoalesceGoal):
    target_size_bytes: int


@dataclasses.dataclass(frozen=True)
class RequireSingleBatch(CoalesceGoal):
    """Operators like out-of-core sort and build-side join need ONE batch
    (reference RequireSingleBatch)."""


def coalesce_iterator(it, goal: CoalesceGoal, metrics=None, use_catalog: bool = True,
                      conf=None):
    """Re-batch `it` per `goal` (reference AbstractGpuCoalesceIterator:133)."""
    concat_time = metrics.metric(M.CONCAT_TIME, M.MODERATE) if metrics else None

    pending: list = []
    pending_bytes = 0

    def flush():
        nonlocal pending, pending_bytes
        if not pending:
            return None
        batches = [p.get_batch() if isinstance(p, mem.SpillableColumnarBatch) else p
                   for p in pending]
        if concat_time is not None:
            with concat_time.timed():
                out = concat_batches(batches)
        else:
            out = concat_batches(batches)
        for p in pending:
            if isinstance(p, mem.SpillableColumnarBatch):
                p.close()
        pending, pending_bytes = [], 0
        return out

    limit = (goal.target_size_bytes if isinstance(goal, TargetSize) else None)
    try:
        for batch in it:
            if batch.num_rows == 0:
                continue
            size = batch.device_memory_size()
            if limit is not None and pending and pending_bytes + size > limit:
                yield flush()
            if use_catalog:
                # strict-budget registration under the OOM retry ladder: an
                # over-budget batch spills others, then splits in half — the
                # halves concat back to the same rows at flush
                with mem.alloc_site("coalesce.batch"):
                    sbs = R.register_with_retry(
                        batch, mem.ACTIVE_BATCHING_PRIORITY, conf=conf)
                for sb in sbs:
                    pending.append(sb)
                    pending_bytes += sb.size
            else:
                pending.append(batch)
                pending_bytes += size
            if limit is not None and pending_bytes >= limit:
                yield flush()
        out = flush()
        if out is not None:
            yield out
    finally:
        # consumer may stop early (limit); release catalogued pending batches
        for p in pending:
            if isinstance(p, mem.SpillableColumnarBatch):
                p.close()
        pending = []


def concat_all(it, schema, conf=None) -> ColumnarBatch:
    """Drain to exactly one batch (reference ConcatAndConsumeAll)."""
    out = list(coalesce_iterator(it, RequireSingleBatch(), conf=conf))
    if not out:
        return ColumnarBatch.empty(schema)
    assert len(out) == 1
    return out[0]


class CoalesceBatchesExec(TpuExec):
    """Physical coalesce node (reference GpuCoalesceBatches:455)."""

    def __init__(self, goal: CoalesceGoal, child: TpuExec, conf=None):
        super().__init__(child, conf=conf)
        self.goal = goal

    @property
    def output(self):
        return self.child.output

    def execute_partition(self, split):
        return self.wrap_output(
            coalesce_iterator(self.child.execute_partition(split), self.goal,
                              self.metrics, conf=self.conf))

    def args_string(self):
        return repr(self.goal)
