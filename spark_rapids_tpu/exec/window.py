"""Window exec — sort once, segmented scans for every frame (one XLA program).

Reference: GpuWindowExec.scala:92 + GpuWindowExpression.scala (windowAggregation:
847). Each task concatenates its input, sorts by (partition keys, order keys),
derives partition/tie boundaries, then computes all window expressions with the
kernels in ops/windowing.py. The planner (conv_window) guarantees rows of one
window partition land in one task (hash exchange on partition_by)."""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore
from spark_rapids_tpu.expr.core import (Alias, Col, EvalContext, bind_references)
from spark_rapids_tpu.expr.aggregates import (AggregateFunction, Average, Count,
                                              Max, Min, Sum)
from spark_rapids_tpu.expr.windows import (DenseRank, Lag, Lead, Rank, RowNumber,
                                           WindowExpression)
from spark_rapids_tpu.ops import windowing as W
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.ops.filtering import gather_cols
from spark_rapids_tpu.ops.sorting import SortOrder, sort_permutation
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.tracing import trace_range


def _unalias(e):
    return e.child if isinstance(e, Alias) else e


def supported_window_expr(we: WindowExpression) -> str | None:
    """Reason string when unsupported (used by the planner tag), else None."""
    f = we.func
    frame = we.spec.frame
    if isinstance(f, (Lead, Lag)):
        try:
            is_string = isinstance(f.children[0].dtype, T.StringType)
        except Exception:
            is_string = False
        if is_string and f.default is not None:
            return ("lead/lag over strings with a non-null default not "
                    "supported on device (default is not a dictionary code)")
        return None
    if isinstance(f, (RowNumber, Rank, DenseRank)):
        return None
    if isinstance(f, (Sum, Count, Min, Max, Average)):
        if frame.is_unbounded_to_current or frame.is_unbounded_both:
            return None
        if frame.frame_type == "rows":
            return None
        # bounded RANGE frame: Spark requires exactly one order key, and the
        # device search needs it numeric (int/long/float/double/date/decimal)
        ob = we.spec.order_by
        if len(ob) != 1:
            return ("bounded range frame needs exactly one order key, "
                    f"got {len(ob)}")
        okey_dt = ob[0][0].dtype
        if not (okey_dt.is_numeric or isinstance(okey_dt, (T.DateType,
                                                           T.TimestampType))):
            return f"range frame over non-numeric order key {okey_dt}"
        return None
    return f"window function {type(f).__name__} not supported"


class WindowExec(TpuExec):
    def __init__(self, window_exprs: list, child: TpuExec, conf=None):
        """window_exprs: Alias(WindowExpression) list; all must share one spec's
        partition/order for this exec (the planner groups them; reference
        GpuWindowExec partitions its expressions the same way)."""
        super().__init__(child, conf=conf)
        self.window_exprs = [bind_references(e, child.output)
                             for e in window_exprs]
        specs = {repr((_unalias(e).spec.partition_by,
                       _unalias(e).spec.order_by))
                 for e in self.window_exprs}
        assert len(specs) == 1, "one WindowExec handles one partition/order spec"
        self._win_time = self.metrics.metric(M.OP_TIME, M.MODERATE)

    @property
    def output(self):
        fields = list(self.child.output.fields)
        for i, e in enumerate(self.window_exprs):
            name = e.name if isinstance(e, Alias) else f"win{i}"
            fields.append(T.StructField(name, e.dtype, e.nullable))
        return T.StructType(fields)

    def execute_partition(self, split):
        def it():
            batches = list(self.child.execute_partition(split))
            if not batches:
                return
            acquire_semaphore(self.metrics)
            with trace_range("WindowExec", self._win_time):
                batch = concat_batches(batches)
                yield self._compute(batch)
        return self.wrap_output(it())

    def _compute(self, batch: ColumnarBatch) -> ColumnarBatch:
        cap = batch.capacity
        ctx = EvalContext.from_batch(batch)
        spec0 = _unalias(self.window_exprs[0]).spec
        part_cols = [e.eval(ctx) for e in spec0.partition_by]
        order_cols = [e.eval(ctx) for (e, _, _) in spec0.order_by]
        orders = ([SortOrder() for _ in part_cols]
                  + [SortOrder(asc, nf) for (_, asc, nf) in spec0.order_by])
        num_rows = ctx.num_rows
        perm = sort_permutation(part_cols + order_cols, orders, num_rows, cap)
        live = jnp.arange(cap, dtype=jnp.int32) < num_rows
        sorted_in = gather_cols(ctx.cols, perm, live)
        sorted_part = gather_cols(part_cols, perm, live)
        sorted_order = gather_cols(order_cols, perm, live)

        part_boundary = self._boundaries(sorted_part, cap)
        order_boundary = part_boundary | self._boundaries(sorted_order, cap) \
            if sorted_order else part_boundary
        seg_ids = jnp.cumsum(part_boundary.astype(jnp.int32)) - 1

        sctx = EvalContext(sorted_in, batch.lazy_num_rows, cap)
        bounds_memo = {}  # per-batch: partitions run concurrently in threads
        out_cols = list(sorted_in)
        for e in self.window_exprs:
            we = _unalias(e)
            out_cols.append(self._eval_window(
                we, sctx, part_boundary, order_boundary, seg_ids, cap, live,
                sorted_order, bounds_memo))
        return ColumnarBatch([c.to_vector() for c in out_cols],
                             batch.lazy_num_rows, self.output)

    @staticmethod
    def _boundaries(cols, cap) -> jnp.ndarray:
        """True where any key differs from the previous row (first row = True)."""
        b = jnp.zeros((cap,), jnp.bool_).at[0].set(True)
        for c in cols:
            prev_vals = jnp.roll(c.values, 1)
            prev_valid = jnp.roll(c.validity, 1)
            if isinstance(c.dtype, T.FractionalType):
                both_nan = jnp.isnan(c.values) & jnp.isnan(prev_vals)
                differs = ~both_nan & ~(c.values == prev_vals)
            else:
                differs = c.values != prev_vals
            b = b | differs | (c.validity != prev_valid)
        return b.at[0].set(True)

    def _eval_window(self, we, sctx, part_b, order_b, seg_ids, cap, live,
                     sorted_order, bounds_memo):
        f = we.func
        frame = we.spec.frame
        if isinstance(f, RowNumber):
            return Col(W.row_number(part_b, cap), live, T.INT)
        if isinstance(f, DenseRank):
            return Col(W.dense_rank(order_b, part_b), live, T.INT)
        if isinstance(f, Rank):
            return Col(W.rank(order_b, part_b, cap), live, T.INT)
        if isinstance(f, (Lead, Lag)):
            c = f.children[0].eval(sctx)
            off = f.offset if isinstance(f, Lead) else -f.offset
            if f.default is None:
                fill, fill_valid = jnp.asarray(
                    c.dtype.default_value(), c.values.dtype), False
            else:
                fill = jnp.asarray(f.default, c.values.dtype)
                fill_valid = True
            vals, valid = W.shift_within_partition(
                c.values, c.validity, seg_ids, off, cap, fill, fill_valid)
            return Col(vals, valid & live, c.dtype, c.dictionary)
        assert isinstance(f, AggregateFunction), f
        return self._eval_agg_window(f, we, sctx, part_b, order_b, seg_ids,
                                     cap, live, sorted_order, bounds_memo)

    def _frame_lo_hi(self, we, part_b, order_b, seg_ids, cap, sorted_order,
                     bounds_memo):
        """Per-row inclusive [lo, hi] index bounds of the frame. Every frame
        shape reduces to this; aggregates then answer range queries
        (prefix-sum differences / sparse-table gathers, ops/windowing.py).
        Memoized per batch: all expressions share one partition/order spec and
        usually repeat frames, and the range search is the priciest step."""
        frame = we.spec.frame
        cached = bounds_memo.get(frame)
        if cached is not None:
            return cached
        idx = jnp.arange(cap, dtype=jnp.int32)
        pstart = W.seg_starts(part_b)
        pend = self._partition_ends(part_b, cap)
        if frame.is_unbounded_both:
            lo, hi = pstart, pend
        elif frame.frame_type == "rows":
            if frame.is_unbounded_to_current:
                lo, hi = pstart, idx
            else:
                lo = pstart if frame.preceding is None else \
                    jnp.maximum(idx - frame.preceding, pstart)
                hi = pend if frame.following is None else \
                    jnp.minimum(idx + frame.following, pend)
        elif frame.is_unbounded_to_current:
            lo, hi = pstart, W.tie_group_ends(order_b, part_b)
        else:
            (_okey, asc, _nf) = we.spec.order_by[0]
            oc = sorted_order[0]
            lo, hi = W.range_frame_bounds(
                oc.values, oc.validity, seg_ids, asc,
                frame.preceding, frame.following, pstart, pend)
        bounds_memo[frame] = (lo, hi)
        return lo, hi

    @staticmethod
    def _range_sum(values, lo, hi):
        """Sum over [lo, hi] via one global inclusive cumsum (lo/hi never cross
        a partition, so cross-partition prefix mass cancels in the diff)."""
        cs = jnp.cumsum(values, axis=0)
        return cs[hi] - jnp.where(lo > 0, cs[jnp.maximum(lo - 1, 0)],
                                  jnp.zeros_like(cs[0]))

    def _eval_agg_window(self, f, we, sctx, part_b, order_b, seg_ids, cap,
                         live, sorted_order, bounds_memo):
        dict_ = None
        if isinstance(f, Count) and not f.children:
            vals = jnp.ones((cap,), jnp.int64)
            valid = live
            dtype = T.LONG
        else:
            c = f.children[0].eval(sctx)
            vals, valid, dtype = c.values, c.validity & live, c.dtype
            dict_ = c.dictionary
        if isinstance(f, (Min, Max)) and vals.dtype == jnp.bool_:
            vals = vals.astype(jnp.int8)  # iinfo sentinels need an int carrier

        out_dtype = f.dtype
        lo, hi = self._frame_lo_hi(we, part_b, order_b, seg_ids, cap,
                                   sorted_order, bounds_memo)
        nonempty = hi >= lo
        lo_q = jnp.where(nonempty, lo, 0)
        hi_q = jnp.where(nonempty, hi, 0)

        cnt_w = jnp.where(
            nonempty, self._range_sum(valid.astype(jnp.int64), lo_q, hi_q), 0)
        if isinstance(f, (Sum, Average, Count)):
            acc_dt = (jnp.float64 if isinstance(dtype, T.FractionalType)
                      else jnp.int64)
            data = jnp.where(valid, vals, jnp.zeros_like(vals)).astype(acc_dt)
            sum_w = self._range_sum(data, lo_q, hi_q)
            return self._finish(f, sum_w, cnt_w, None, out_dtype, live, None)

        # min/max: sparse-table range queries; Spark orders NaN as the LARGEST
        # value — min ignores NaN unless the frame is all-NaN, max returns NaN
        # as soon as the frame contains one
        if isinstance(dtype, T.FractionalType):
            nan = jnp.isnan(vals)
            nan_w = self._range_sum((valid & nan).astype(jnp.int32), lo_q, hi_q)
            nonnan_w = self._range_sum((valid & ~nan).astype(jnp.int32),
                                       lo_q, hi_q)
            eff_valid = valid & ~nan
            sent = jnp.asarray(jnp.inf if isinstance(f, Min) else -jnp.inf,
                               vals.dtype)
        else:
            nan_w = None
            eff_valid = valid
            info = jnp.iinfo(vals.dtype)
            sent = jnp.asarray(info.max if isinstance(f, Min) else info.min,
                               vals.dtype)
        combine = jnp.minimum if isinstance(f, Min) else jnp.maximum
        table = W.sparse_table(jnp.where(eff_valid, vals, sent), combine, sent)
        mm_w = W.range_query(table, combine, lo_q, hi_q)
        if nan_w is not None:
            if isinstance(f, Min):  # all-NaN frame → NaN
                mm_w = jnp.where((nonnan_w == 0) & (nan_w > 0), jnp.nan, mm_w)
            else:                   # any NaN in frame → NaN
                mm_w = jnp.where(nan_w > 0, jnp.nan, mm_w)
        return self._finish(f, None, cnt_w, mm_w, out_dtype, live, dict_)

    @staticmethod
    def _partition_ends(part_b, cap):
        idx = jnp.arange(cap, dtype=jnp.int32)
        next_b = jnp.concatenate([part_b[1:], jnp.ones((1,), jnp.bool_)])
        rev = lambda x: jnp.flip(x, 0)
        return rev(W.seg_cummax(rev(jnp.where(next_b, idx, 0)), rev(next_b)))

    @staticmethod
    def _finish(f, sum_w, cnt_w, mm_w, out_dtype, live, dict_):
        if isinstance(f, Count):
            return Col(cnt_w.astype(jnp.int64), live, T.LONG)
        if isinstance(f, Average):
            vals = sum_w.astype(jnp.float64) / jnp.maximum(cnt_w, 1)
            return Col(vals, (cnt_w > 0) & live, T.DOUBLE)
        if isinstance(f, Sum):
            dt = out_dtype.jnp_dtype
            return Col(sum_w.astype(dt), (cnt_w > 0) & live, out_dtype)
        # min/max: restore the value dtype (bool scans ran on an int8 carrier;
        # string scans ran on dictionary codes — the sorted dictionary rides
        # along so codes stay decodable, like expr/aggregates.py Min/Max)
        if isinstance(out_dtype, T.BooleanType):
            mm_w = mm_w.astype(jnp.bool_)
        return Col(mm_w, (cnt_w > 0) & live, out_dtype, dict_)

    def args_string(self):
        return str(self.window_exprs)
