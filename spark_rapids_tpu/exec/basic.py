"""Basic physical operators: project / filter / range / union / limits.

Reference: basicPhysicalOperators.scala (GpuProjectExec:83, GpuFilterExec:181,
GpuRangeExec:239, GpuUnionExec:370) and limit.scala. The filter keeps the surviving
row count as a device scalar (no host sync between chained operators — see
ops/filtering.py), which is the TPU-first departure from cudf's eager compaction."""

from __future__ import annotations

import typing

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity
from spark_rapids_tpu.exec.base import TpuExec, acquire_semaphore
from spark_rapids_tpu.expr.core import EvalContext, Expression, bind_references
from spark_rapids_tpu.ops.filtering import selection_mask, compact_cols
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.tracing import trace_range


class ProjectExec(TpuExec):
    def __init__(self, project_list: list, child: TpuExec, conf=None):
        super().__init__(child, conf=conf)
        self.project_list = [bind_references(e, child.output) for e in project_list]

    @property
    def output(self):
        return T.StructType([
            T.StructField(e.name, e.dtype, e.nullable) for e in self.project_list])

    def execute_partition(self, split):
        from spark_rapids_tpu.expr.core import Col
        from spark_rapids_tpu.expr.misc import (CONTEXT_SENSITIVE,
                                                MonotonicallyIncreasingID,
                                                Rand)
        from spark_rapids_tpu.runtime import fuse
        positional = any(
            e.collect(lambda x: isinstance(
                x, (MonotonicallyIncreasingID, Rand)))
            for e in self.project_list)
        ctx_sensitive = any(
            e.collect(lambda x: isinstance(x, CONTEXT_SENSITIVE))
            for e in self.project_list)
        exprs = self.project_list
        key = ("project", fuse.schema_key(self.child.output),
               tuple(fuse.expr_key(e) for e in exprs))

        def build():
            def kernel(cols, num_rows):
                ctx = EvalContext(cols, num_rows, cols[0].values.shape[0])
                return [e.eval(ctx) for e in exprs]
            return kernel

        def it():
            offset = 0
            for batch in self.child.execute_partition(split):
                acquire_semaphore(self.metrics)
                with trace_range("ProjectExec", self._op_time):
                    if ctx_sensitive or not batch.columns:
                        ctx = EvalContext.from_batch(batch, split, offset)
                        out = [e.eval(ctx) for e in exprs]
                    else:
                        in_cols = [Col.from_vector(c) for c in batch.columns]
                        nr = jnp.asarray(batch.lazy_num_rows, jnp.int32)
                        ctx = EvalContext.from_batch(batch, split, offset)
                        out = fuse.call_fused(
                            key, "ProjectExec", build, (in_cols, nr),
                            lambda: [e.eval(ctx) for e in exprs])
                    cols = [c.to_vector() for c in out]
                    yield ColumnarBatch(cols, batch.lazy_num_rows, self.output,
                                        metadata=batch.metadata)
                if positional:  # host sync only when an expr needs positions
                    offset += int(batch.num_rows)
        return self.wrap_output(it())

    def args_string(self):
        return str(self.project_list)


class FilterExec(TpuExec):
    def __init__(self, condition: Expression, child: TpuExec, conf=None):
        super().__init__(child, conf=conf)
        self.condition = bind_references(condition, child.output)

    @property
    def output(self):
        return self.child.output

    def execute_partition(self, split):
        from spark_rapids_tpu.expr.core import Col
        from spark_rapids_tpu.expr.misc import CONTEXT_SENSITIVE
        from spark_rapids_tpu.runtime import fuse
        cond = self.condition
        ctx_sensitive = bool(
            cond.collect(lambda x: isinstance(x, CONTEXT_SENSITIVE)))
        key = ("filter", fuse.schema_key(self.child.output),
               fuse.expr_key(cond))

        def build():
            def kernel(cols, num_rows):
                cap = cols[0].values.shape[0]
                ctx = EvalContext(cols, num_rows, cap)
                pred = cond.eval(ctx)
                keep = selection_mask(pred, num_rows, cap)
                return compact_cols(ctx.cols, keep)
            return kernel

        def eager(batch):
            ctx = EvalContext.from_batch(batch, split)
            pred = cond.eval(ctx)
            keep = selection_mask(pred, ctx.num_rows, ctx.capacity)
            return compact_cols(ctx.cols, keep)

        fusion = self.conf.stage_fusion_enabled

        def it():
            for batch in self.child.execute_partition(split):
                acquire_semaphore(self.metrics)
                with trace_range("FilterExec", self._op_time):
                    if ctx_sensitive or not batch.columns:
                        new_cols, count = eager(batch)
                    else:
                        in_cols = [Col.from_vector(c) for c in batch.columns]
                        nr = jnp.asarray(batch.lazy_num_rows, jnp.int32)
                        new_cols, count = fuse.call_fused(
                            key, "FilterExec", build, (in_cols, nr),
                            lambda: eager(batch))
                        if fusion and new_cols:
                            # selective filters re-land at a right-sized
                            # capacity so downstream programs stop paying the
                            # stale one (ops/filtering.maybe_host_resize)
                            from spark_rapids_tpu.ops.filtering import \
                                maybe_host_resize
                            resized = maybe_host_resize(new_cols, count)
                            if resized is not None:
                                new_cols, count = resized
                    yield ColumnarBatch([c.to_vector() for c in new_cols], count,
                                        self.output, metadata=batch.metadata)
        return self.wrap_output(it())

    def args_string(self):
        return repr(self.condition)


class RangeExec(TpuExec):
    """range(start, end, step) — generates LongType rows on device
    (reference GpuRangeExec:239)."""

    def __init__(self, start: int, end: int, step: int = 1, num_slices: int = 1,
                 conf=None, max_rows_per_batch: int = 1 << 20):
        super().__init__(conf=conf)
        self.start, self.end, self.step = start, end, step
        self.num_slices = num_slices
        self.max_rows_per_batch = max_rows_per_batch

    @property
    def output(self):
        return T.StructType([T.StructField("id", T.LONG, False)])

    @property
    def num_partitions(self):
        return self.num_slices

    def execute_partition(self, split):
        total = max(0, -(-(self.end - self.start) // self.step))
        per = -(-total // self.num_slices)
        lo = split * per
        hi = min(total, (split + 1) * per)

        def it():
            i = lo
            while i < hi:
                n = min(self.max_rows_per_batch, hi - i)
                acquire_semaphore(self.metrics)
                cap = bucket_capacity(n)
                vals = (self.start
                        + (jnp.arange(cap, dtype=jnp.int64) + i) * self.step)
                col = TpuColumnVector(
                    T.LONG, vals, jnp.arange(cap) < n)
                yield ColumnarBatch([col], n, self.output)
                i += n
        return self.wrap_output(it())

    def args_string(self):
        return f"({self.start}, {self.end}, {self.step})"


class UnionExec(TpuExec):
    """Concatenation of children partitions (reference GpuUnionExec:370)."""

    def __init__(self, *children, conf=None):
        super().__init__(*children, conf=conf)

    @property
    def output(self):
        return self.children[0].output

    @property
    def num_partitions(self):
        return sum(c.num_partitions for c in self.children)

    def execute_partition(self, split):
        for c in self.children:
            if split < c.num_partitions:
                return self.wrap_output(c.execute_partition(split))
            split -= c.num_partitions
        raise IndexError(split)


class LocalLimitExec(TpuExec):
    """Per-partition limit (reference limit.scala GpuLocalLimitExec)."""

    def __init__(self, limit: int, child, conf=None):
        super().__init__(child, conf=conf)
        self.limit = limit

    @property
    def output(self):
        return self.child.output

    def execute_partition(self, split):
        def it():
            remaining = self.limit
            for batch in self.child.execute_partition(split):
                if remaining <= 0:
                    break
                n = batch.num_rows  # host sync at the limit boundary
                if n <= remaining:
                    remaining -= n
                    yield batch
                else:
                    live = jnp.arange(batch.capacity) < remaining
                    cols = [TpuColumnVector(c.dtype,
                                            jnp.where(live, c.data,
                                                      c.dtype.default_value()),
                                            c.validity & live, c.dictionary)
                            for c in batch.columns]
                    yield ColumnarBatch(cols, remaining, batch.schema,
                                        metadata=batch.metadata)
                    remaining = 0
        return self.wrap_output(it())

    def args_string(self):
        return str(self.limit)


class GlobalLimitExec(LocalLimitExec):
    """Whole-plan limit; requires a single partition upstream (Spark plans the same
    way: GlobalLimit over a single-partition exchange)."""

    def __init__(self, limit: int, child, conf=None):
        assert child.num_partitions == 1, \
            "GlobalLimitExec requires a single-partition child (insert a " \
            "SinglePartitioner exchange first, as Spark's planner does)"
        super().__init__(limit, child, conf=conf)

    @property
    def num_partitions(self):
        return 1


class ArrowScanExec(TpuExec):
    """Leaf: scan host Arrow tables (one per partition) onto the device — the test
    data source and the HostColumnarToGpu analog."""

    def __init__(self, tables: list, schema: T.StructType | None = None, conf=None,
                 batch_rows: int | None = None):
        super().__init__(conf=conf)
        self.tables = tables
        import pyarrow as pa
        self._schema = schema or T.StructType.from_arrow(tables[0].schema)
        self.batch_rows = batch_rows

    @property
    def output(self):
        return self._schema

    @property
    def num_partitions(self):
        return len(self.tables)

    def execute_partition(self, split):
        def it():
            t = self.tables[split]
            step = self.batch_rows or max(1, t.num_rows)
            for off in range(0, max(t.num_rows, 1), step):
                sl = t.slice(off, step)
                if t.num_rows == 0 and off > 0:
                    break
                acquire_semaphore(self.metrics)
                yield ColumnarBatch.from_arrow(sl, self._schema)
        return self.wrap_output(it())
