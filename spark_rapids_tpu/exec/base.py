"""TpuExec — base of the physical operator layer.

Reference: GpuExec.scala:40,281: base trait for all columnar operators, carrying the
metric registry, coalesce-goal declarations, and doExecuteColumnar. Here an exec is a
tree node with `execute_partition(split) -> Iterator[ColumnarBatch]`; a lightweight
local task scheduler (the stand-in for Spark's task execution — the reference
delegates scheduling to Spark itself, SURVEY.md §1) drives partitions through thread
pool tasks gated by the TpuSemaphore."""

from __future__ import annotations

import itertools
import threading
import typing

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
from spark_rapids_tpu.runtime.tracing import trace_range

_task_counter = itertools.count(1)
_task_local = threading.local()


def current_task_id() -> int:
    tid = getattr(_task_local, "task_id", None)
    if tid is None:
        tid = next(_task_counter)
        _task_local.task_id = tid
    return tid


class TaskContext:
    """Per-task scope: semaphore auto-release on completion (reference
    GpuSemaphore task-completion listener, GpuSemaphore.scala:58)."""

    def __init__(self):
        self.task_id = next(_task_counter)
        self._outer = None

    def __enter__(self):
        # save/restore the enclosing task id so inline nested tasks (e.g. a map
        # stage run on the calling thread) don't orphan the outer task's permit
        self._outer = getattr(_task_local, "task_id", None)
        _task_local.task_id = self.task_id
        return self

    def __exit__(self, *exc):
        TpuSemaphore.get().release_if_necessary(self.task_id)
        _task_local.task_id = self._outer
        return False


class TpuExec:
    """Base physical operator."""

    def __init__(self, *children: "TpuExec", conf: RapidsConf | None = None):
        self.children = list(children)
        self.conf = conf or (children[0].conf if children else RapidsConf())
        self.metrics = M.MetricsRegistry(self.conf.metrics_level)
        self._out_rows = self.metrics.metric(M.NUM_OUTPUT_ROWS, M.ESSENTIAL)
        self._out_batches = self.metrics.metric(M.NUM_OUTPUT_BATCHES, M.MODERATE)
        self._op_time = self.metrics.metric(M.OP_TIME, M.MODERATE)
        self._self_time = self.metrics.metric(M.SELF_TIME, M.ESSENTIAL)
        # query-scoped observability (SQL-UI analog): conversion runs inside
        # the action's QueryMetricsCollector scope, so every exec registers
        # its registry under a plan-node id at construction
        collector = M.current_collector()
        self._node_id = (collector.register(self)
                         if collector is not None else None)

    @property
    def child(self) -> "TpuExec":
        return self.children[0]

    @property
    def output(self) -> T.StructType:
        raise NotImplementedError

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions if self.children else 1

    def execute_partition(self, split: int) -> typing.Iterator[ColumnarBatch]:
        raise NotImplementedError

    # -- driver-side helpers -------------------------------------------------
    def execute_collect(self):
        """Run all partitions (threaded local scheduler) and collect to one arrow
        table — the test/driver path (Spark collect())."""
        import pyarrow as pa
        from concurrent.futures import ThreadPoolExecutor
        from spark_rapids_tpu.config import NUM_LOCAL_TASKS
        from spark_rapids_tpu.runtime import pipeline as P
        nthreads = max(1, min(self.conf.get(NUM_LOCAL_TASKS), self.num_partitions))
        collector = M.current_collector()
        pipe_on = P.enabled(self.conf)

        def run(split):
            # re-enter the driving action's query scope on the pool thread so
            # metrics/events fired by operators attribute to this query
            with M.collector_context(collector), TaskContext():
                it = self.execute_partition(split)
                if pipe_on:
                    # final-collect pipeline segment: upstream compute runs
                    # on the stage's worker thread while this thread does the
                    # D2H arrow conversion of the previous batch
                    it = P.stage_iterator(
                        it, edge="collect", conf=self.conf,
                        registry=self.metrics, node_id=self._node_id,
                        spillable=True)
                return [b.to_arrow() for b in it]

        if self.num_partitions == 1:
            parts = [run(0)]
        else:
            with ThreadPoolExecutor(max_workers=nthreads) as pool:
                parts = list(pool.map(run, range(self.num_partitions)))
        tables = [t for p in parts for t in p]
        if not tables:
            return self.output.to_arrow().empty_table()
        return pa.concat_tables(tables)

    def wrap_output(self, it):
        """Instrument an output iterator with row/batch metrics and one
        self-time attribution frame per batch pull: time spent producing a
        batch, minus time charged by nested operator frames on this thread,
        lands in this node's selfTime (the SQL-UI op-time analog). Row counts
        accumulate LAZILY (device scalars fold in at metric read time) — a
        per-batch host sync here would serialize every operator on the
        accelerator round-trip."""
        from spark_rapids_tpu.runtime import eventlog as EL
        from spark_rapids_tpu.runtime.scheduler import check_cancel
        it = iter(it)
        while True:
            # cooperative cancellation checkpoint on EVERY operator's batch
            # pull (runtime/scheduler.py): session.cancel()/deadline expiry
            # drains the whole operator chain one batch later, no matter
            # which segment a thread is computing in
            check_cancel()
            with M.node_frame(self._node_id, self._self_time):
                try:
                    b = next(it)
                except StopIteration:
                    return
            self._out_batches.add(1)
            self._out_rows.add_lazy(b.lazy_num_rows)
            # stats plane: observed output bytes per node (array metadata
            # only — device_memory_size never syncs the device)
            M.stats_add("outputBytes", b.device_memory_size(),
                        node=self._node_id)
            if EL.enabled():
                # batch lifecycle event; never force a device sync for the
                # row count — a still-lazy count is logged as null
                n = b.lazy_num_rows
                EL.emit("batch", node=self._node_id,
                        rows=n if isinstance(n, int) else None)
            yield b

    def tree_string(self, indent=0):
        s = "  " * indent + "*" + type(self).__name__ + " " + self.args_string() + "\n"
        for c in self.children:
            s += c.tree_string(indent + 1)
        return s

    def args_string(self):
        return ""

    def __repr__(self):
        return self.tree_string().rstrip()


def acquire_semaphore(metrics: M.MetricsRegistry):
    TpuSemaphore.get().acquire_if_necessary(
        current_task_id(), metrics.metric(M.SEMAPHORE_WAIT_TIME, M.MODERATE))
