"""BroadcastExchangeExec — standalone broadcast exchange operator.

Reference: GpuBroadcastExchangeExecBase (org/.../execution/
GpuBroadcastExchangeExec.scala:237) materializes the build side ONCE on its
own broadcast thread pool with a timeout, serializes the contiguous table,
and every consumer (broadcast hash join, nested-loop join, AQE reuse) reads
the same relation; GpuBroadcastToCpuExec bridges the relation back to the
host. Here the relation is a SpillableColumnarBatch (HBM-resident,
spillable under pressure) built by a daemon worker; `broadcast()` blocks
consumers on the shared future with `spark.sql.broadcastTimeout` semantics,
and `execute_partition` is the host-bridge path (one single-partition
stream of the relation).
"""

from __future__ import annotations

import concurrent.futures
import threading

from spark_rapids_tpu import config as CFG
from spark_rapids_tpu.exec.base import TaskContext, TpuExec
from spark_rapids_tpu.exec.coalesce import concat_all
from spark_rapids_tpu.runtime import faults as F
from spark_rapids_tpu.runtime import memory as mem
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import retry as R
from spark_rapids_tpu.runtime.tracing import trace_range

class BroadcastTimeout(RuntimeError):
    pass


def _spawn_build(fn) -> concurrent.futures.Future:
    """One dedicated daemon thread per broadcast build (like Spark's
    relation-future threads). A bounded shared pool would deadlock when a
    build side itself contains broadcast joins: outer builds could occupy
    every worker while blocking on inner builds stuck in the queue."""
    fut: concurrent.futures.Future = concurrent.futures.Future()

    def run():
        if not fut.set_running_or_notify_cancel():
            return
        try:
            fut.set_result(fn())
        except BaseException as e:  # noqa: BLE001 — future carries it
            fut.set_exception(e)

    threading.Thread(target=run, name="tpu-broadcast", daemon=True).start()
    return fut


class BroadcastExchangeExec(TpuExec):
    """Materialize the child once as a shared, spillable device relation."""

    def __init__(self, child: TpuExec, conf=None):
        super().__init__(child, conf=conf)
        self._build_time = self.metrics.metric(M.BUILD_TIME, M.ESSENTIAL)
        self._lock = threading.Lock()
        self._future: concurrent.futures.Future | None = None
        t = float(self.conf.get(CFG.BROADCAST_TIMEOUT))
        self._timeout = t if t > 0 else None  # <=0 waits forever
        self._max_bytes = self.conf.get(CFG.BROADCAST_MAX_TABLE_BYTES)

    @property
    def output(self):
        return self.child.output

    @property
    def num_partitions(self) -> int:
        return 1

    def _materialize(self) -> mem.SpillableColumnarBatch:
        # "joins.build" fault scope: in the default (non-mesh) plan every
        # equi-join builds through this exchange, so join-build OOM chaos
        # specs target the broadcast materialization; the coalesce layer's
        # registration retry splits over-budget input batches, and the final
        # single-batch registration gets a spill-only retry
        with trace_range("BroadcastExchange.build", self._build_time), \
                F.scope("joins.build"):
            batches = []
            for split in range(self.child.num_partitions):
                with TaskContext():
                    batches.extend(self.child.execute_partition(split))
            batch = concat_all(iter(batches), self.child.output,
                               conf=self.conf)
            size = batch.device_memory_size()
            if self._max_bytes and size > self._max_bytes:
                raise RuntimeError(
                    f"broadcast table {size} bytes exceeds "
                    f"{CFG.BROADCAST_MAX_TABLE_BYTES.key}={self._max_bytes} "
                    "(reference maxBroadcastTableSize guard)")
            return R.call_with_retry(
                lambda: mem.SpillableColumnarBatch(
                    batch, mem.ACTIVE_BATCHING_PRIORITY),
                scope="joins.build")

    def broadcast(self) -> mem.SpillableColumnarBatch:
        """The shared relation; first caller schedules the build, everyone
        blocks on the same future (reference executeBroadcast + relation
        future with broadcastTimeout)."""
        with self._lock:
            if self._future is None:
                # the build thread must re-enter the caller's query scope
                # (metrics/events attribution) and charge its wall time to
                # this node's selfTime — consumers only ever BLOCK on the
                # future, so the build is otherwise invisible to the
                # per-thread attribution frames
                collector = M.current_collector()

                def build():
                    with M.collector_context(collector), \
                            M.node_frame(self._node_id,
                                         self.metrics.metric(
                                             M.BUILD_SELF_TIME, M.ESSENTIAL)):
                        return self._materialize()

                self._future = _spawn_build(build)
            fut = self._future
        from spark_rapids_tpu.runtime.scheduler import check_cancel
        import time as _time
        deadline = (_time.monotonic() + self._timeout
                    if self._timeout is not None else None)
        # metric=None frame: the build thread charges itself; the
        # consumer's blocked wait must not double-count in its own frame.
        # The wait polls so a cancelled/deadlined query drains instead of
        # camping on a peer-started build for broadcastTimeout seconds
        with M.node_frame(self._node_id, None):
            while True:
                check_cancel()
                try:
                    return fut.result(timeout=0.05)
                except concurrent.futures.TimeoutError:
                    if (deadline is not None
                            and _time.monotonic() >= deadline):
                        raise BroadcastTimeout(
                            f"broadcast of {self.child.args_string()!s} did "
                            f"not finish within {self._timeout}s") from None

    def release(self) -> None:
        """Close the relation (called by the last consumer). If the build is
        still running (consumers timed out), a done-callback closes the
        relation when it lands instead of orphaning it in HBM."""
        with self._lock:
            fut, self._future = self._future, None
        if fut is None:
            return

        def close_result(f: concurrent.futures.Future):
            if f.exception() is None:
                f.result().close()

        fut.add_done_callback(close_result)

    def abort_query(self):
        """Query-death cleanup (session._run_action's exec sweep): the
        shared-broadcast reader countdown only counts readers whose
        generators STARTED — a cancelled query can abandon a stream
        partition's iterator unstarted, leaving the countdown short and the
        relation orphaned in HBM. release() is idempotent, so the sweep and
        a late last-reader countdown cannot double-close."""
        self.release()

    def execute_partition(self, split: int):
        # host-bridge / reuse path (GpuBroadcastToCpuExec analog): stream the
        # relation as a normal single-partition exec without taking ownership.
        # The batch is materialized BEFORE yielding: once device arrays are
        # referenced they outlive a concurrent release() by the last join
        # consumer; if that release closes the relation mid-acquire (spill
        # file unlinked / use-after-close), rebuild via a fresh broadcast().
        def it():
            batch = None
            for attempt in range(3):
                sb = self.broadcast()
                try:
                    batch = sb.get_batch()
                    break
                except mem.BufferClosedError:
                    if attempt == 2:
                        raise
            yield batch
        return self.wrap_output(it())

    def args_string(self):
        return f"timeout={self._timeout}s"
