"""Plugin bootstrap — driver/executor lifecycle (SURVEY.md component #1).

Reference: Plugin.scala —
  * RapidsDriverPlugin.init (:154): config fixup (:85-120, injects the SQL
    extension + enforces serializer confs), version check, and the shuffle
    heartbeat manager when the accelerated shuffle is on (:161).
  * RapidsExecutorPlugin.init (:175): cudf version check (:214), explicit
    device + memory initialization (GpuDeviceManager.initializeGpuAndMemory
    :125), heartbeat endpoint registration (:197), semaphore init (:203),
    and CRASH-FAST on failure (:210 System.exit(1)) so the cluster manager
    reschedules the executor rather than running degraded.

Standalone TPU translation: one process hosts both roles. TpuSession
bootstraps the plugin once per process (idempotent, conf from the first
session — matching the reference, where plugin config is process-wide);
`executor_init` performs EXPLICIT device acquisition (ordinal conf,
platform verification, HBM warmup touch that fails fast on a wedged or
absent backend) before any query runs, instead of the previous lazy
first-use initialization.
"""

from __future__ import annotations

import threading

from spark_rapids_tpu import config as CFG


class PluginInitError(RuntimeError):
    """Executor init failed — the reference exits the process (Plugin.scala
    :210) so Spark reschedules; standalone callers decide, so we raise."""


_lock = threading.Lock()
_initialized = False
_context: dict = {}


def context() -> dict:
    """The driver plugin context (Plugin.scala:165 plugin-context map):
    holds e.g. the shuffle heartbeat manager for endpoint registration."""
    return _context


def _fixup_and_check(conf) -> None:
    """Driver-side config fixup + environment check (Plugin.scala:85-120 +
    checkCudfVersion analog: the accelerator stack must be importable and
    version-compatible before anything executes)."""
    import jax
    major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    if (major, minor) < (0, 4):
        raise PluginInitError(f"jax {jax.__version__} too old; need >= 0.4")


def executor_init(conf) -> None:
    """Explicit device acquisition + runtime init (GpuDeviceManager
    .initializeGpuAndMemory analog). Raises PluginInitError on failure."""
    import jax

    from spark_rapids_tpu.runtime.memory import DeviceManager
    from spark_rapids_tpu.runtime.semaphore import TpuSemaphore

    try:
        devices = jax.devices()
    except Exception as e:  # backend init failure
        raise PluginInitError(f"no accelerator backend: {e}") from e
    ordinal = conf.get(CFG.DEVICE_ORDINAL)
    if not 0 <= ordinal < len(devices):
        raise PluginInitError(
            f"device ordinal {ordinal} out of range ({len(devices)} visible)")
    # warmup touch: allocate-and-compute a tiny buffer on the chosen device
    # so a wedged tunnel / dead backend fails HERE, not mid-query (the
    # reference's Cuda.setDevice + freeZero acquisition, GpuDeviceManager
    # .scala:93-101)
    import jax.numpy as jnp
    try:
        x = jax.device_put(jnp.ones((8,)), devices[ordinal]) + 1
        x.block_until_ready()
    except Exception as e:
        raise PluginInitError(
            f"device {ordinal} acquisition failed: {e}") from e
    DeviceManager.initialize(conf)
    TpuSemaphore.initialize(conf.get(CFG.CONCURRENT_TPU_TASKS))


def driver_init(conf) -> dict:
    """Driver-side init; returns the context the reference propagates to
    executors through the plugin-context map (Plugin.scala:165)."""
    _fixup_and_check(conf)
    ctx = {}
    if conf.get(CFG.SHUFFLE_MANAGER_ENABLED):
        from spark_rapids_tpu.shuffle.heartbeat import (
            RapidsShuffleHeartbeatManager)
        ctx["heartbeat_manager"] = RapidsShuffleHeartbeatManager()
    return ctx


def bootstrap(conf, eager_device: bool = False) -> None:
    """Idempotent process-wide bootstrap, called by TpuSession. The device
    warmup is opt-in (spark.rapids.tpu.device.eagerInit or `eager_device`)
    because CPU-platform tests construct many sessions."""
    global _initialized
    with _lock:
        if _initialized:
            return
        _context.update(driver_init(conf))
        if eager_device or conf.get(CFG.DEVICE_EAGER_INIT):
            executor_init(conf)
        _initialized = True


def reset_for_tests() -> None:
    global _initialized
    with _lock:
        _initialized = False
        _context.clear()
