"""Native (C++) runtime components, loaded via ctypes.

Reference: the reference's native layer is cuDF/RMM/nvcomp/UCX consumed through
JNI (SURVEY.md L0). The TPU build keeps compute in XLA but implements the
host-side native pieces in C++: the LZ4 block codec (nvcomp analog) here, built by
`make` on first import and cached next to the sources."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libtpulz4.so")
_lock = threading.Lock()
_lib = None


class NativeBuildError(RuntimeError):
    pass


def _build() -> None:
    res = subprocess.run(["make", "-C", _DIR, "-s"], capture_output=True,
                         text=True)
    if res.returncode != 0:
        raise NativeBuildError(
            f"native build failed:\n{res.stdout}\n{res.stderr}")


def lz4_lib():
    """Load (building if needed) the native LZ4 library."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        src = os.path.join(_DIR, "lz4.cpp")
        if (not os.path.exists(_LIB_PATH)
                or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)):
            _build()
        lib = ctypes.CDLL(_LIB_PATH)
        lib.tpu_lz4_compress_bound.restype = ctypes.c_size_t
        lib.tpu_lz4_compress_bound.argtypes = [ctypes.c_size_t]
        lib.tpu_lz4_compress.restype = ctypes.c_size_t
        lib.tpu_lz4_compress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t]
        lib.tpu_lz4_decompress.restype = ctypes.c_size_t
        lib.tpu_lz4_decompress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t]
        _lib = lib
        return _lib


def lz4_compress(data: bytes) -> bytes:
    if not data:
        return b""
    lib = lz4_lib()
    bound = lib.tpu_lz4_compress_bound(len(data))
    out = ctypes.create_string_buffer(bound)
    n = lib.tpu_lz4_compress(data, len(data), out, bound)
    if n == 0:
        raise ValueError("lz4 compression failed")
    return out.raw[:n]


def lz4_decompress(data: bytes, decompressed_len: int) -> bytes:
    if decompressed_len == 0:
        return b""
    lib = lz4_lib()
    out = ctypes.create_string_buffer(decompressed_len)
    n = lib.tpu_lz4_decompress(data, len(data), out, decompressed_len)
    if n != decompressed_len:
        raise ValueError("lz4 decompression failed (corrupt frame)")
    return out.raw[:n]


# ---------------------------------------------------------------------------
# native parquet chunk scanner (parquet_host.cpp)
# ---------------------------------------------------------------------------

_PQ_LIB_PATH = os.path.join(_DIR, "libtpuparquet.so")
_pq_lib = None

# error codes mirrored from parquet_host.cpp — each maps onto the scope the
# Python parser signals with NotImplementedError (caller falls back to arrow)
_SR_ERRORS = {-1: "malformed chunk", -2: "unsupported page type",
              -3: "unsupported page encoding", -4: "capacity exceeded",
              -5: "no dictionary page", -6: "def levels exceed num_values"}


def parquet_lib():
    """Load (building if needed) the native parquet scanner."""
    global _pq_lib
    with _lock:
        if _pq_lib is not None:
            return _pq_lib
        src = os.path.join(_DIR, "parquet_host.cpp")
        if (not os.path.exists(_PQ_LIB_PATH)
                or os.path.getmtime(_PQ_LIB_PATH) < os.path.getmtime(src)):
            _build()
        lib = ctypes.CDLL(_PQ_LIB_PATH)
        lib.sr_scan_chunk.restype = ctypes.c_int64
        lib.sr_scan_chunk.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,        # buf, buf_len
            ctypes.c_int64, ctypes.c_int32,         # num_values, max_def
            ctypes.c_void_p, ctypes.c_int64,        # pages, cap
            ctypes.c_void_p, ctypes.c_int64,        # segs, cap
            ctypes.c_void_p, ctypes.c_int64,        # def_levels, cap
            ctypes.c_void_p,                        # dict_out[3]
        ]
        _pq_lib = lib
        return _pq_lib


_PAGE_FIELDS = 9   # int64 per SrPage (see parquet_host.cpp)
_SEG_FIELDS = 5    # int64 per SrSeg


def scan_chunk_native(buf: bytes, num_values: int, max_def: int):
    """One native call over a column-chunk buffer → (pages, dict_info).

    pages: list of (num_values, def_levels[np.int32], bit_width, values_off,
                    body_off, body_len, n_present, segs) with segs
                    page-relative (kind, count, value, byte_off, byte_len);
    dict_info: (body_off, body_len, num_values).
    Raises NotImplementedError for out-of-stage-one chunks (same contract as
    the Python parser in io/parquet_native.py).
    """
    import numpy as np
    lib = parquet_lib()
    pages_cap, segs_cap = 1024, 8192
    for _attempt in range(6):  # -4 growth is bounded; then treat as corrupt
        pages_buf = np.zeros((pages_cap, _PAGE_FIELDS), np.int64)
        segs_buf = np.zeros((segs_cap, _SEG_FIELDS), np.int64)
        def_buf = np.zeros(max(num_values, 1), np.int32)
        dict_buf = np.zeros(3, np.int64)
        n = lib.sr_scan_chunk(
            buf, len(buf), num_values, max_def,
            pages_buf.ctypes.data, pages_cap,
            segs_buf.ctypes.data, segs_cap,
            def_buf.ctypes.data, len(def_buf),
            dict_buf.ctypes.data)
        if n == -4:  # capacity: grow and retry (pathological many-run pages)
            pages_cap *= 4
            segs_cap *= 16
            continue
        if n < 0:
            raise NotImplementedError(
                f"native parquet scan: {_SR_ERRORS.get(int(n), n)}")
        pages = []
        for i in range(int(n)):
            (nv, def_off, n_present, bw, body_off, body_len, values_off,
             seg_off, seg_count) = (int(v) for v in pages_buf[i])
            segs = [(int(k), int(c), int(v), int(bo), int(bl))
                    for k, c, v, bo, bl in segs_buf[seg_off:seg_off + seg_count]]
            def_levels = def_buf[def_off:def_off + nv].copy()
            pages.append((nv, def_levels, bw, values_off, body_off, body_len,
                          n_present, segs))
        return pages, (int(dict_buf[0]), int(dict_buf[1]), int(dict_buf[2]))
    raise NotImplementedError(
        "native parquet scan: segment/page capacity never converged "
        "(pathological or corrupt chunk)")
