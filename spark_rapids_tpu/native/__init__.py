"""Native (C++) runtime components, loaded via ctypes.

Reference: the reference's native layer is cuDF/RMM/nvcomp/UCX consumed through
JNI (SURVEY.md L0). The TPU build keeps compute in XLA but implements the
host-side native pieces in C++: the LZ4 block codec (nvcomp analog) here, built by
`make` on first import and cached next to the sources."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libtpulz4.so")
_lock = threading.Lock()
_lib = None


class NativeBuildError(RuntimeError):
    pass


def _build() -> None:
    res = subprocess.run(["make", "-C", _DIR, "-s"], capture_output=True,
                         text=True)
    if res.returncode != 0:
        raise NativeBuildError(
            f"native build failed:\n{res.stdout}\n{res.stderr}")


def lz4_lib():
    """Load (building if needed) the native LZ4 library."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        src = os.path.join(_DIR, "lz4.cpp")
        if (not os.path.exists(_LIB_PATH)
                or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)):
            _build()
        lib = ctypes.CDLL(_LIB_PATH)
        lib.tpu_lz4_compress_bound.restype = ctypes.c_size_t
        lib.tpu_lz4_compress_bound.argtypes = [ctypes.c_size_t]
        lib.tpu_lz4_compress.restype = ctypes.c_size_t
        lib.tpu_lz4_compress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t]
        lib.tpu_lz4_decompress.restype = ctypes.c_size_t
        lib.tpu_lz4_decompress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t]
        _lib = lib
        return _lib


def lz4_compress(data: bytes) -> bytes:
    if not data:
        return b""
    lib = lz4_lib()
    bound = lib.tpu_lz4_compress_bound(len(data))
    out = ctypes.create_string_buffer(bound)
    n = lib.tpu_lz4_compress(data, len(data), out, bound)
    if n == 0:
        raise ValueError("lz4 compression failed")
    return out.raw[:n]


def lz4_decompress(data: bytes, decompressed_len: int) -> bytes:
    if decompressed_len == 0:
        return b""
    lib = lz4_lib()
    out = ctypes.create_string_buffer(decompressed_len)
    n = lib.tpu_lz4_decompress(data, len(data), out, decompressed_len)
    if n != decompressed_len:
        raise ValueError("lz4 decompression failed (corrupt frame)")
    return out.raw[:n]
