// Native LZ4 block codec — the nvcomp analog for shuffle/spill compression.
//
// Reference (SURVEY.md component #34): NvcompLZ4CompressionCodec.scala:25 drives
// device-side batched LZ4 through nvcomp (C++/CUDA). On TPU the compression work
// belongs on the host CPU next to the NIC/disk (HBM-side compute is XLA's), so
// this is a from-scratch LZ4 *block format* implementation (compatible with the
// standard decoder spec) exposed through a C ABI and driven from Python via
// ctypes, batched by shuffle/compression.py.
//
// Build: `make -C spark_rapids_tpu/native` produces libtpulz4.so.

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

constexpr int MINMATCH = 4;
constexpr int HASH_LOG = 16;
constexpr int HASH_SIZE = 1 << HASH_LOG;
// last 5 bytes must be literals; matches must not start within 12 bytes of end
constexpr int LAST_LITERALS = 5;
constexpr int MFLIMIT = 12;

static inline uint32_t read32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
}

static inline uint16_t read16(const uint8_t* p) {
    uint16_t v;
    std::memcpy(&v, p, 2);
    return v;
}

static inline uint32_t hash4(uint32_t v) {
    return (v * 2654435761u) >> (32 - HASH_LOG);
}

}  // namespace

extern "C" {

// Worst-case compressed size for `n` input bytes (standard LZ4 bound).
size_t tpu_lz4_compress_bound(size_t n) {
    return n + n / 255 + 16;
}

// Compress src[0..n) into dst (capacity >= bound). Returns compressed size,
// or 0 on failure (dst too small).
size_t tpu_lz4_compress(const uint8_t* src, size_t n, uint8_t* dst,
                        size_t dst_cap) {
    if (n == 0) return 0;
    uint32_t table[HASH_SIZE];
    std::memset(table, 0, sizeof(table));

    const uint8_t* ip = src;
    const uint8_t* const iend = src + n;
    const uint8_t* const mflimit = (n >= MFLIMIT) ? iend - MFLIMIT : src;
    const uint8_t* anchor = src;
    uint8_t* op = dst;
    uint8_t* const oend = dst + dst_cap;

    if (n >= MFLIMIT) {
        table[hash4(read32(ip))] = 0;
        ip++;
        while (ip < mflimit) {
            // find a match
            const uint8_t* match = nullptr;
            uint32_t h = hash4(read32(ip));
            uint32_t cand = table[h];
            table[h] = (uint32_t)(ip - src);
            const uint8_t* cp = src + cand;
            if (cp < ip && (ip - cp) <= 0xFFFF && read32(cp) == read32(ip)) {
                match = cp;
            }
            if (!match) {
                ip++;
                continue;
            }
            // extend match forward
            const uint8_t* mip = ip + MINMATCH;
            const uint8_t* mmp = match + MINMATCH;
            const uint8_t* const matchlimit = iend - LAST_LITERALS;
            while (mip < matchlimit && *mip == *mmp) {
                mip++;
                mmp++;
            }
            size_t match_len = (size_t)(mip - ip) - MINMATCH;
            size_t lit_len = (size_t)(ip - anchor);

            // token + literal length + literals + offset + match length
            size_t need = 1 + lit_len / 255 + 1 + lit_len + 2 + match_len / 255 + 1;
            if (op + need > oend) return 0;
            uint8_t* token = op++;
            if (lit_len >= 15) {
                *token = (uint8_t)(15 << 4);
                size_t l = lit_len - 15;
                while (l >= 255) { *op++ = 255; l -= 255; }
                *op++ = (uint8_t)l;
            } else {
                *token = (uint8_t)(lit_len << 4);
            }
            std::memcpy(op, anchor, lit_len);
            op += lit_len;
            uint16_t offset = (uint16_t)(ip - match);
            *op++ = (uint8_t)(offset & 0xFF);
            *op++ = (uint8_t)(offset >> 8);
            if (match_len >= 15) {
                *token |= 15;
                size_t l = match_len - 15;
                while (l >= 255) { *op++ = 255; l -= 255; }
                *op++ = (uint8_t)l;
            } else {
                *token |= (uint8_t)match_len;
            }
            ip = mip;
            anchor = ip;
            if (ip < mflimit) table[hash4(read32(ip))] = (uint32_t)(ip - src);
        }
    }

    // trailing literals
    size_t lit_len = (size_t)(iend - anchor);
    size_t need = 1 + lit_len / 255 + 1 + lit_len;
    if (op + need > oend) return 0;
    uint8_t* token = op++;
    if (lit_len >= 15) {
        *token = (uint8_t)(15 << 4);
        size_t l = lit_len - 15;
        while (l >= 255) { *op++ = 255; l -= 255; }
        *op++ = (uint8_t)l;
    } else {
        *token = (uint8_t)(lit_len << 4);
    }
    std::memcpy(op, anchor, lit_len);
    op += lit_len;
    return (size_t)(op - dst);
}

// Decompress src[0..n) into dst of exactly dst_len bytes. Returns dst_len on
// success, 0 on malformed input.
size_t tpu_lz4_decompress(const uint8_t* src, size_t n, uint8_t* dst,
                          size_t dst_len) {
    const uint8_t* ip = src;
    const uint8_t* const iend = src + n;
    uint8_t* op = dst;
    uint8_t* const oend = dst + dst_len;

    while (ip < iend) {
        uint8_t token = *ip++;
        // literals
        size_t lit = token >> 4;
        if (lit == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return 0;
                b = *ip++;
                lit += b;
            } while (b == 255);
        }
        if (ip + lit > iend || op + lit > oend) return 0;
        std::memcpy(op, ip, lit);
        ip += lit;
        op += lit;
        if (ip >= iend) break;  // last sequence has no match
        // match
        if (ip + 2 > iend) return 0;
        uint16_t offset = read16(ip);
        ip += 2;
        if (offset == 0 || op - dst < offset) return 0;
        size_t mlen = (token & 15);
        if (mlen == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return 0;
                b = *ip++;
                mlen += b;
            } while (b == 255);
        }
        mlen += MINMATCH;
        if (op + mlen > oend) return 0;
        const uint8_t* mp = op - offset;
        // overlapping copy must be byte-wise
        for (size_t i = 0; i < mlen; i++) op[i] = mp[i];
        op += mlen;
    }
    return (op == oend) ? dst_len : 0;
}

}  // extern "C"
