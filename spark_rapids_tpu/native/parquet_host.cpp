// Native parquet column-chunk scanner — the host-side data-loader hot loop.
//
// Reference analog: the reference's parquet host path (GpuParquetScan.scala
// readPartFile:818) copies row-group bytes and hands them to libcudf (C++)
// for decode; its native layer owns all byte-level work. Here the device
// (XLA/Pallas) unpacks the bulk bit-packed indices, and THIS translation
// unit owns the byte-level host work that remained in Python: thrift
// compact-protocol page headers, definition-level RLE decode, and RLE/
// bit-packed hybrid run segmentation. One C call per column chunk replaces
// the per-page/per-varint Python loops (io/parquet_native.py keeps the same
// logic as documentation and fallback).
//
// Layout contract with spark_rapids_tpu/native/__init__.py (ctypes):
// every struct field is int64_t, arrays are caller-allocated.

#include <cstdint>
#include <cstring>

namespace {

struct Reader {
    const uint8_t* buf;
    int64_t len;
    int64_t pos;
    bool fail = false;

    uint8_t byte() {
        if (pos >= len) { fail = true; return 0; }
        return buf[pos++];
    }
    uint64_t varint() {
        uint64_t out = 0;
        int shift = 0;
        while (true) {
            uint8_t b = byte();
            if (fail || shift > 63) { fail = true; return 0; }
            out |= static_cast<uint64_t>(b & 0x7F) << shift;
            if (!(b & 0x80)) return out;
            shift += 7;
        }
    }
    int64_t zigzag() {
        uint64_t v = varint();
        return static_cast<int64_t>(v >> 1) ^ -static_cast<int64_t>(v & 1);
    }
    void skip(int64_t n) {
        if (n < 0 || pos + n > len) { fail = true; return; }
        pos += n;
    }
    void skip_binary() { skip(static_cast<int64_t>(varint())); }
};

// Minimal thrift compact struct walk keeping only the page-header fields we
// need (same field ids as io/parquet_native.py parse_page_header).
struct PageHeaderFields {
    int64_t page_type = -1;         // field 1
    int64_t uncompressed_size = 0;  // field 2
    int64_t compressed_size = 0;    // field 3
    int64_t num_values = 0;         // nested field 1
    int64_t encoding = 0;           // nested field 2 (v1/dict) or 4 (v2)
};

void walk_struct(Reader& r, int depth, int64_t parent_field,
                 PageHeaderFields& out) {
    int64_t fid = 0;
    while (!r.fail) {
        uint8_t head = r.byte();
        if (r.fail || head == 0) return;
        int64_t delta = head >> 4;
        int ftype = head & 0x0F;
        fid = delta ? fid + delta : r.zigzag();
        int64_t val = 0;
        switch (ftype) {
            case 1: val = 1; break;            // BOOLEAN_TRUE
            case 2: val = 0; break;            // BOOLEAN_FALSE
            case 3: val = r.byte(); break;     // byte
            case 4: case 5: case 6:            // i16/i32/i64
                val = r.zigzag(); break;
            case 7: r.skip(8); break;          // double
            case 8: r.skip_binary(); break;    // binary/string
            case 12:                            // struct
                walk_struct(r, depth + 1, fid, out);
                break;
            case 9: case 10: {                  // list/set
                uint8_t sz = r.byte();
                int64_t n = sz >> 4;
                int et = sz & 0x0F;
                if (n == 15) n = static_cast<int64_t>(r.varint());
                for (int64_t i = 0; i < n && !r.fail; i++) {
                    if (et == 4 || et == 5 || et == 6) r.zigzag();
                    else if (et == 8) r.skip_binary();
                    else if (et == 12) walk_struct(r, depth + 1, -1, out);
                    else if (et == 3) r.byte();
                    else if (et == 7) r.skip(8);
                    else { r.fail = true; }
                }
                break;
            }
            default:
                r.fail = true;
                return;
        }
        if (depth == 0) {
            if (fid == 1) out.page_type = val;
            else if (fid == 2) out.uncompressed_size = val;
            else if (fid == 3) out.compressed_size = val;
        } else if (depth == 1 &&
                   (parent_field == 5 || parent_field == 7 ||
                    parent_field == 8)) {
            // DataPageHeader(5) / DictionaryPageHeader(7) / DataPageHeaderV2(8)
            if (fid == 1) out.num_values = val;
            if ((parent_field == 8 && fid == 4) ||
                (parent_field != 8 && fid == 2))
                out.encoding = val;
        }
    }
}

}  // namespace

extern "C" {

struct SrSeg {
    int64_t kind;       // 0 = rle, 1 = packed
    int64_t count;
    int64_t value;
    int64_t byte_off;   // page-body-relative
    int64_t byte_len;
};

struct SrPage {
    int64_t num_values;
    int64_t def_off;     // start of this page's levels in def_levels out
    int64_t n_present;
    int64_t bit_width;
    int64_t body_off;    // page body offset in buf
    int64_t body_len;
    int64_t values_off;  // page-relative offset of the bit-width byte
    int64_t seg_off;
    int64_t seg_count;
};

// error codes (mirror the Python parser's NotImplementedError scope)
enum {
    SR_ERR_MALFORMED = -1,
    SR_ERR_PAGE_TYPE = -2,
    SR_ERR_ENCODING = -3,
    SR_ERR_CAPACITY = -4,      // pages/segs arrays too small: caller may grow
    SR_ERR_NO_DICT = -5,
    SR_ERR_DEF_CAPACITY = -6,  // def levels exceed footer num_values: corrupt
};

// Decode an RLE/bit-packed hybrid region. When `levels_out` is non-null the
// values are materialized (definition levels); otherwise only the run
// STRUCTURE is recorded into segs (bit-packed payload goes to the device).
static int64_t scan_hybrid(const uint8_t* page, int64_t page_len, int64_t pos,
                           int64_t end, int64_t bit_width, int64_t total,
                           SrSeg* segs, int64_t segs_cap, int64_t* n_segs,
                           int32_t* levels_out) {
    Reader r{page, end < page_len ? end : page_len, pos};
    int64_t got = 0;
    int64_t vbytes = (bit_width + 7) / 8;
    while (got < total && r.pos < r.len && !r.fail) {
        uint64_t h = r.varint();
        if (r.fail) return SR_ERR_MALFORMED;
        SrSeg s{};
        if (h & 1) {
            int64_t groups = static_cast<int64_t>(h >> 1);
            int64_t n = groups * 8;
            s.kind = 1;
            s.count = n < total - got ? n : total - got;
            s.byte_off = r.pos;
            s.byte_len = groups * bit_width;
            if (levels_out) {
                // unpack little-endian bit order
                for (int64_t i = 0; i < s.count; i++) {
                    int64_t bit0 = i * bit_width;
                    int64_t v = 0;
                    for (int64_t b = 0; b < bit_width; b++) {
                        int64_t bit = bit0 + b;
                        int64_t byi = r.pos + (bit >> 3);
                        if (byi >= r.len) return SR_ERR_MALFORMED;
                        v |= ((page[byi] >> (bit & 7)) & 1) << b;
                    }
                    levels_out[got + i] = static_cast<int32_t>(v);
                }
            }
            r.skip(s.byte_len);
            if (r.fail) return SR_ERR_MALFORMED;
        } else {
            int64_t run = static_cast<int64_t>(h >> 1);
            int64_t v = 0;
            for (int64_t i = 0; i < vbytes; i++)
                v |= static_cast<int64_t>(r.byte()) << (8 * i);
            if (r.fail) return SR_ERR_MALFORMED;
            s.kind = 0;
            s.count = run < total - got ? run : total - got;
            s.value = v;
            if (levels_out)
                for (int64_t i = 0; i < s.count; i++)
                    levels_out[got + i] = static_cast<int32_t>(v);
        }
        if (segs) {
            if (*n_segs >= segs_cap) return SR_ERR_CAPACITY;
            segs[(*n_segs)++] = s;
        }
        got += s.count;
    }
    return got;
}

// Scan one UNCOMPRESSED dictionary-encoded column chunk buffer.
// Returns the page count (>= 0) or a negative SR_ERR_* code.
// dict_out = {body_off, body_len, num_values}.
int64_t sr_scan_chunk(const uint8_t* buf, int64_t buf_len,
                      int64_t col_num_values, int32_t max_def,
                      SrPage* pages, int64_t pages_cap,
                      SrSeg* segs, int64_t segs_cap,
                      int32_t* def_levels, int64_t def_cap,
                      int64_t* dict_out) {
    int64_t pos = 0, n_pages = 0, n_segs = 0;
    int64_t values_seen = 0, def_used = 0;
    dict_out[0] = dict_out[1] = dict_out[2] = -1;
    while (pos < buf_len && values_seen < col_num_values) {
        Reader r{buf, buf_len, pos};
        PageHeaderFields ph;
        walk_struct(r, 0, -1, ph);
        if (r.fail) return SR_ERR_MALFORMED;
        int64_t header_len = r.pos - pos;
        int64_t body = pos + header_len;
        if (body + ph.compressed_size > buf_len) return SR_ERR_MALFORMED;
        if (ph.page_type == 2) {                      // dictionary page
            dict_out[0] = body;
            dict_out[1] = ph.compressed_size;
            dict_out[2] = ph.num_values;
        } else if (ph.page_type == 0) {               // data page v1
            if (ph.encoding != 8 && ph.encoding != 2)
                return SR_ERR_ENCODING;               // RLE_DICT / PLAIN_DICT
            if (n_pages >= pages_cap) return SR_ERR_CAPACITY;
            const uint8_t* page = buf + body;
            int64_t page_len = ph.compressed_size;
            int64_t p = 0;
            SrPage out{};
            out.num_values = ph.num_values;
            out.body_off = body;
            out.body_len = page_len;
            out.def_off = def_used;
            // def_cap is exactly the footer's num_values: overflow means a
            // corrupt chunk, not an undersized caller array — growing the
            // other buffers can never fix it
            if (def_used + ph.num_values > def_cap) return SR_ERR_DEF_CAPACITY;
            if (max_def) {
                if (p + 4 > page_len) return SR_ERR_MALFORMED;
                int64_t dl_len = 0;
                std::memcpy(&dl_len, page + p, 4);
                p += 4;
                int64_t got = scan_hybrid(page, page_len, p, p + dl_len, 1,
                                          ph.num_values, nullptr, 0, &n_segs,
                                          def_levels + def_used);
                if (got < 0) return got;
                for (int64_t i = got; i < ph.num_values; i++)
                    def_levels[def_used + i] = 0;
                p += dl_len;
            } else {
                for (int64_t i = 0; i < ph.num_values; i++)
                    def_levels[def_used + i] = 1;
            }
            int64_t n_present = 0;
            for (int64_t i = 0; i < ph.num_values; i++)
                n_present += def_levels[def_used + i];
            def_used += ph.num_values;
            if (p >= page_len) return SR_ERR_MALFORMED;
            out.bit_width = page[p];
            out.values_off = p;
            p += 1;
            out.n_present = n_present;
            out.seg_off = n_segs;
            int64_t got = scan_hybrid(page, page_len, p, page_len,
                                      out.bit_width, n_present, segs,
                                      segs_cap, &n_segs, nullptr);
            if (got < 0) return got;
            out.seg_count = n_segs - out.seg_off;
            pages[n_pages++] = out;
            values_seen += ph.num_values;
        } else {
            return SR_ERR_PAGE_TYPE;                  // v2 etc: fallback
        }
        pos = body + ph.compressed_size;
    }
    if (dict_out[0] < 0) return SR_ERR_NO_DICT;
    return n_pages;
}

}  // extern "C"
