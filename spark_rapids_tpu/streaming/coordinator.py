"""Micro-batch epoch coordinator: incremental aggregation, exactly once.

Each epoch is ONE normal admitted query — it enters the multi-tenant
scheduler, rides the pipelined executor, and (because the plan shape is
identical every epoch: a fixed-path delta scan unioned with a
marker-normalized state scan) replays from the persistent compiled-stage
cache, so a steady-state epoch retraces nothing::

    delta  = scan(epoch's batch files) |> [window bucket] |> partial agg
    state' = (state ∪ delta-partial) |> merge agg            # one query
    state' sorted canonically, watermark-retired, checksummed, snapshotted
    journal.commit(epoch, checksum)                          # the only truth

The update→merge split reuses exec/aggregate.py's own partial/merge
contract (AGG_MERGE_OPS): sums and counts merge by SUM, min/max by
MIN/MAX — so the incremental state is exactly a parked partial-aggregation
batch, and merging N epochs is associative no matter how batches were
grouped into epochs.

Crash consistency is the journal's (streaming/journal.py): work happens
between ``epoch.begin`` and ``epoch.commit``; the state snapshot is written
atomically BEFORE the commit and named by epoch, so the commit record's
checksum always has a matching durable artifact and a stale partial from a
killed attempt can never be adopted (the shuffle-epoch-bump fencing idiom).
Replays are bit-identical because the begin record pins the exact batch
ids, the delta scan is a single deterministic partition, and the state is
canonically sorted before checksum/snapshot.

Residency: the live state is a spillable, retained catalog buffer under
allocation site ``streaming.state`` — query-tagged and visible to the
memory plane's watermark timeline/heap snapshots, spillable under pressure,
exempt from the end-of-query leak detector (it outlives queries BY DESIGN;
per-epoch scratch is not exempt and stays leak-checked). Watermark
retirement (``streaming.watermark.delaySeconds``) runs host-side on the
collected state — never as a per-epoch literal in the engine plan, which
would bake a new constant into the kernel every epoch and retrace.

Mutual exclusion across processes: the whole begin→run→commit span holds
an advisory flock on ``<stream>/coordinator.lock``. flock dies with its
process (runtime/locks.py), so a SIGKILLed coordinator blocks nobody — a
fleet survivor adopting the stream proceeds straight into replay.
"""

from __future__ import annotations

import contextlib
import os
import threading

import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.runtime.checksum import block_checksum
from spark_rapids_tpu.runtime.locks import advisory_lock
from spark_rapids_tpu.streaming import source as SRC
from spark_rapids_tpu.streaming.journal import EpochJournal

_STATE_PREFIX = "state-"

# update-side builder and merge-side builder per supported aggregate op.
# The merge column operates on the update output's NAME — e.g. sum(v) lands
# as sum_v, and every later epoch merges sum(sum_v)
_UPDATE = {"sum": F.sum, "count": F.count, "min": F.min, "max": F.max}


class StreamStateCorruptError(RuntimeError):
    """A committed state snapshot failed its journal checksum — detected,
    never silently served; recovery rebuilds from the consumed batch log."""


class EpochCoordinator:
    """Drives one stream's windowed/keyed incremental aggregation.

    `aggs` is a list of (op, column) with op in sum/count/min/max; the
    state carries one column per agg named ``<op>_<column>``. With
    `time_column` + `window_seconds`, a ``window`` bucket column (floor of
    event time to the window width) joins the group keys and the watermark
    retires buckets entirely below max(event time) - delay."""

    def __init__(self, session, src: SRC.StreamingSource, *, keys: list,
                 aggs: list, time_column: str | None = None,
                 window_seconds: int = 0, state_dir: str | None = None):
        from spark_rapids_tpu import config as CFG
        from spark_rapids_tpu.exec.aggregate import AGG_MERGE_OPS
        self.session = session
        self.source = src
        self.keys = list(keys)
        self.aggs = [(op, c) for op, c in aggs]
        for op, _ in self.aggs:
            if op not in _UPDATE or op not in AGG_MERGE_OPS:
                raise ValueError(f"unsupported streaming aggregate {op!r}")
        self.time_column = time_column
        self.window_seconds = int(window_seconds)
        if bool(time_column) != bool(self.window_seconds):
            raise ValueError("time_column and window_seconds go together")
        self.state_dir = state_dir or os.path.join(src.directory, "_state")
        os.makedirs(self.state_dir, exist_ok=True)
        self.journal = EpochJournal(
            self.state_dir, source=src.name,
            max_commits=session.conf.get(CFG.STREAM_JOURNAL_HISTORY))
        self.watermark_delay = session.conf.get(CFG.STREAM_WATERMARK_DELAY)
        self.max_batches = session.conf.get(CFG.STREAM_MAX_BATCHES_PER_EPOCH)
        self._owner_lock = os.path.join(self.state_dir, "coordinator.lock")
        self._lock = threading.Lock()
        self._state_buf = None        # SpillableColumnarBatch (retained)
        self._state_schema = None     # pyarrow schema of the state table
        self._watermark = None
        self._loaded = False
        self._last_compiles = None

    # -- naming ---------------------------------------------------------------

    @property
    def group_cols(self) -> list:
        cols = list(self.keys)
        if self.window_seconds:
            cols.append("window")
        return cols

    @property
    def agg_cols(self) -> list:
        return [f"{op}_{c}" for op, c in self.aggs]

    def _snapshot_path(self, epoch: int) -> str:
        return os.path.join(self.state_dir, f"{_STATE_PREFIX}{epoch}.arrow")

    # -- state residency -------------------------------------------------------

    def _canonical(self, tbl: pa.Table) -> pa.Table:
        """Deterministic row order — the bit-identity anchor: group keys are
        unique after the merge agg, so sorting by them totally orders the
        table regardless of which attempt produced it."""
        if tbl.num_rows <= 1:
            return tbl
        return tbl.sort_by([(k, "ascending") for k in self.group_cols])

    def _set_state(self, tbl: pa.Table) -> None:
        """Swap the retained catalog buffer to `tbl` (the cache.device
        idiom, plan/cache.py): spillable, site-tagged streaming.state."""
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.runtime import memory as mem
        from spark_rapids_tpu.runtime import metrics as M
        old, self._state_buf = self._state_buf, None
        if old is not None:
            old.close()
        if tbl.num_rows:
            with mem.alloc_site("streaming.state", retained=True):
                self._state_buf = mem.SpillableColumnarBatch(
                    ColumnarBatch.from_arrow(
                        tbl, T.StructType.from_arrow(tbl.schema)))
        self._state_schema = tbl.schema
        self._loaded = True
        M.set_gauge("streaming.state.rows", tbl.num_rows)
        M.set_gauge("streaming.state.bytes", tbl.nbytes)

    def state_table(self) -> pa.Table:
        """The live state as a host table (unspills if demoted). Empty —
        with the state schema once known — before the first commit."""
        with self._lock:
            if not self._loaded:
                with advisory_lock(self._owner_lock):
                    self._recover_locked()
            if self._state_buf is None:
                schema = self._state_schema or pa.schema([])
                return schema.empty_table()
            return self._state_buf.get_batch().to_arrow()

    @property
    def watermark(self):
        return self._watermark

    @property
    def last_epoch_compiles(self):
        """XLA compiles of the most recent epoch query on this session —
        the steady-state ==0 gate's readout."""
        return self._last_compiles

    def close(self) -> None:
        """Release the retained state buffer (the catalog is leak-checked
        by tests even for exempt sites: retained means 'exempt while
        live', not 'abandonable')."""
        with self._lock:
            buf, self._state_buf = self._state_buf, None
            if buf is not None:
                buf.close()
            self._loaded = False

    # -- snapshot I/O ----------------------------------------------------------

    def _write_snapshot(self, epoch: int, tbl: pa.Table) -> int:
        """Atomic epoch-stamped state snapshot; returns its checksum. The
        ``streaming.state`` site arms both generic faults (exec_kill dies
        with the snapshot possibly written but the commit not — recovery
        fences the orphan by epoch) and payload corruption (the checksum
        verification on load must catch the flip)."""
        from spark_rapids_tpu.runtime import faults as FLT
        FLT.maybe_inject_any("streaming.state")
        body = SRC.table_to_ipc(tbl)
        crc = block_checksum(body)
        body = FLT.maybe_corrupt("streaming.state", body)
        path = self._snapshot_path(epoch)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(body)
        os.replace(tmp, path)
        return crc

    def _gc_snapshots(self, keep_epoch: int) -> None:
        for name in os.listdir(self.state_dir):
            if not name.startswith(_STATE_PREFIX):
                continue
            stem = name[len(_STATE_PREFIX):].split(".", 1)[0]
            with contextlib.suppress(ValueError, OSError):
                if int(stem) != keep_epoch:
                    os.unlink(os.path.join(self.state_dir, name))

    def _load_snapshot(self, epoch: int, want_checksum: int) -> pa.Table:
        try:
            with open(self._snapshot_path(epoch), "rb") as f:
                body = f.read()
        except OSError as e:
            raise StreamStateCorruptError(
                f"state snapshot for committed epoch {epoch} missing: "
                f"{e}") from e
        if block_checksum(body) != want_checksum:
            raise StreamStateCorruptError(
                f"state snapshot for committed epoch {epoch} fails its "
                f"journal checksum")
        return SRC.ipc_to_table(body)

    # -- the epoch query -------------------------------------------------------

    def _delta_frame(self, batch_ids: list):
        """This epoch's input as ONE deterministic scan partition, window
        bucket attached. The bucket is integer arithmetic on the event-time
        column with the CONSTANT window width — no per-epoch literals, so
        the traced kernel is identical every epoch."""
        from spark_rapids_tpu.io.filescan import FileScanNode
        from spark_rapids_tpu.session import DataFrame
        paths = [self.source.batch_path(b) for b in batch_ids]
        df = DataFrame(FileScanNode(paths, "parquet",
                                    files_per_partition=len(paths)),
                       self.session)
        if self.window_seconds:
            tc = F.col(self.time_column)
            df = df.with_column(
                "window", tc - (tc % F.lit(self.window_seconds)))
        return df

    def _epoch_result(self, batch_ids: list) -> pa.Table:
        """Run the epoch's admitted query: partial agg over the delta,
        merged with the parked state when one exists."""
        update = [_UPDATE[op](F.col(c)).alias(n)
                  for (op, c), n in zip(self.aggs, self.agg_cols)]
        partial = self._delta_frame(batch_ids) \
            .group_by(*self.group_cols).agg(*update)
        state = None
        if self._state_buf is not None:
            state = self.session.create_dataframe(
                self._state_buf.get_batch().to_arrow())
        if state is not None:
            merge = [self._merge_expr(op, n)
                     for (op, _), n in zip(self.aggs, self.agg_cols)]
            partial = state.union(partial) \
                .group_by(*self.group_cols).agg(*merge)
        out = partial.collect()
        qm = self.session.last_query_metrics()
        self._last_compiles = (qm.compile_metrics().get("compiles", 0)
                               if qm is not None else None)
        return out

    def _merge_expr(self, op: str, name: str):
        from spark_rapids_tpu.exec.aggregate import AGG_MERGE_OPS
        return _UPDATE[AGG_MERGE_OPS[op]](F.col(name)).alias(name)

    def _retire(self, tbl: pa.Table):
        """Host-side watermark retirement; returns (kept, retired_rows,
        watermark). The watermark only advances — late max(event time)
        regressions can't resurrect a retired bucket."""
        if (not self.window_seconds or self.watermark_delay < 0
                or not tbl.num_rows):
            return tbl, 0, self._watermark
        high = pc.max(tbl["window"]).as_py()
        wm = high - self.watermark_delay
        if pa.types.is_integer(tbl.schema.field("window").type):
            wm = int(wm // 1)
        if self._watermark is not None:
            wm = max(wm, self._watermark)
        keep = pc.greater_equal(tbl["window"], pa.scalar(
            wm, type=tbl.schema.field("window").type))
        kept = tbl.filter(keep)
        retired = tbl.num_rows - kept.num_rows
        return kept, retired, wm

    # -- protocol --------------------------------------------------------------

    def _recover_locked(self) -> dict | None:
        """Load committed state (rebuilding it from the consumed batch log
        when the snapshot is corrupt/missing) and replay a pending epoch if
        one exists. Returns the replayed commit record or None. Caller
        holds self._lock; the cross-process owner flock must already be
        held when this can write (run_epoch / recover)."""
        from spark_rapids_tpu.runtime import metrics as M
        doc = self.journal.snapshot()
        committed = int(doc["committed_epoch"])
        if not self._loaded:
            if committed == 0:
                self._loaded = True
            else:
                last = doc["commits"][-1] if doc["commits"] else None
                want = int(last["state_checksum"]) if (
                    last and int(last["epoch"]) == committed) else None
                try:
                    if want is None:
                        raise StreamStateCorruptError(
                            f"no commit record for epoch {committed} "
                            f"(journal history truncated)")
                    tbl = self._load_snapshot(committed, want)
                except StreamStateCorruptError:
                    M.resilience_add(M.STREAM_STATE_REBUILDS)
                    tbl = self._rebuild_state(doc["consumed"])
                self._set_state(tbl)
            if doc["commits"]:
                self._watermark = doc["commits"][-1].get("watermark")
        pending = doc["begin"]
        if not pending:
            return None
        # replay: the SAME batch ids against the committed state, under a
        # bumped attempt (the stale-partial fence); counted as resilience —
        # a no-faults stream never replays
        M.resilience_add(M.STREAM_EPOCH_REPLAYS)
        epoch = int(pending["epoch"])
        attempt = self.journal.begin(
            epoch, pending["batch_ids"],
            prev_state_checksum=pending.get("prev_state_checksum", 0))
        return self._run_epoch_locked(epoch, pending["batch_ids"], attempt)

    def _rebuild_state(self, consumed: list) -> pa.Table:
        """Deterministic full re-aggregation of every consumed batch — the
        recovery of last resort behind a corrupt snapshot. Correct because
        the batch log is append-only and commits are associative."""
        if not consumed:
            schema = self._state_schema
            return schema.empty_table() if schema else \
                pa.schema([]).empty_table()
        saved, self._state_buf = self._state_buf, None
        if saved is not None:
            saved.close()
        tbl = self._canonical(self._epoch_result(sorted(consumed)))
        # re-apply the journal's watermark so a rebuild can't resurrect
        # buckets the committed timeline already retired
        if self.window_seconds and self._watermark is not None:
            tbl = tbl.filter(pc.greater_equal(
                tbl["window"], pa.scalar(
                    self._watermark,
                    type=tbl.schema.field("window").type)))
        return tbl

    def _run_epoch_locked(self, epoch: int, batch_ids: list,
                          attempt: int) -> dict:
        rows_in = sum(pq.read_metadata(self.source.batch_path(b)).num_rows
                      for b in batch_ids)
        out = self._canonical(self._epoch_result(batch_ids))
        kept, retired, wm = self._retire(out)
        crc = self._write_snapshot(epoch, kept)
        rec = self.journal.commit(
            epoch, state_checksum=crc, state_rows=kept.num_rows,
            state_bytes=kept.nbytes, rows_in=rows_in,
            retired_rows=retired, watermark=wm,
            compiles=self._last_compiles)
        # only after the commit is durable: adopt the state + gc the old
        # snapshot (crash before this line replays epoch N+0 nothing — the
        # commit already names this snapshot)
        self._set_state(kept)
        self._watermark = wm
        self._gc_snapshots(epoch)
        return rec

    def recover(self) -> dict | None:
        """Explicit recovery entry (restart / fleet adoption): load state,
        replay any pending epoch. Returns the replayed commit or None."""
        with self._lock, advisory_lock(self._owner_lock):
            return self._recover_locked()

    def run_epoch(self) -> dict | None:
        """One micro-batch step: recover if needed, take the oldest
        unconsumed batches (bounded by streaming.maxBatchesPerEpoch), run
        the epoch, commit. Returns the commit record, or None when the
        source has nothing new."""
        with self._lock, advisory_lock(self._owner_lock):
            replayed = self._recover_locked()
            if replayed is not None:
                return replayed
            doc = self.journal.snapshot()
            consumed = set(doc["consumed"])
            pending = [b for b in self.source.list_batches()
                       if b not in consumed]
            if not pending:
                return None
            if self.max_batches > 0:
                pending = pending[:self.max_batches]
            epoch = int(doc["committed_epoch"]) + 1
            last = doc["commits"][-1] if doc["commits"] else None
            attempt = self.journal.begin(
                epoch, pending,
                prev_state_checksum=last["state_checksum"] if last else 0)
            return self._run_epoch_locked(epoch, pending, attempt)
