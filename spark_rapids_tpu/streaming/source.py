"""Micro-batch streaming source: a durable, append-only batch log.

Both ingestion paths converge on one on-disk layout — parquet batch files
named ``<batch_id>.parquet`` directly in the source directory:

  - **directory tail**: an external producer drops parquet files in; the
    source discovers them by fresh listing (never at plan construction —
    a registered view must see rows appended after registration).
  - **endpoint APPEND**: a client ships a CRC-stamped Arrow-IPC payload
    (runtime/endpoint.py MSG_APPEND); the frame is CRC-verified, decoded,
    and persisted HERE — durably, via a pid-unique intent file and
    ``os.replace`` — before the ACK is sent. Durability-before-ACK is what
    lets a fleet survivor adopting a dead replica's stream replay an
    acknowledged batch the dead replica never committed.

Idempotence by ``(source, batch_id)`` is structural: the batch id IS the
file name, a second APPEND of an existing id (or of an id the journal
already consumed) writes nothing and ACKs ``duplicate`` — which is what
makes APPEND safe to retry blindly across fleet replicas.

The atomic-replace discipline doubles as the partial-write fence: a client
that dies mid-APPEND (or a replica SIGKILLed mid-write) leaves at most an
orphaned ``*.tmp.<pid>`` intent the fleet sweeper reclaims — a fresh
listing can never observe a torn batch.
"""

from __future__ import annotations

import io
import os
import re
import threading

import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.runtime.checksum import block_checksum
from spark_rapids_tpu.shuffle.transport import TransportError

_BATCH_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.\-]{0,127}$")
_SUFFIX = ".parquet"


def table_to_ipc(tbl: pa.Table) -> bytes:
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, tbl.schema) as w:
        w.write_table(tbl)
    return sink.getvalue()


def ipc_to_table(body: bytes) -> pa.Table:
    return pa.ipc.open_stream(io.BytesIO(body)).read_all()


class StreamingSource:
    """One named stream over one batch-log directory.

    `schema` (a pyarrow schema) makes the empty source queryable and gates
    appends; when omitted it is adopted from the first batch seen."""

    def __init__(self, name: str, directory: str,
                 schema: pa.Schema | None = None):
        if not _BATCH_ID_RE.match(name or ""):
            raise ValueError(f"invalid stream source name {name!r}")
        self.name = name
        self.directory = directory
        self.schema = schema
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)

    # -- batch log ------------------------------------------------------------

    def list_batches(self) -> list:
        """Sorted batch ids from a FRESH directory listing; write intents
        and dotfiles never appear (atomic replace is the publish)."""
        out = []
        for name in os.listdir(self.directory):
            if not name.endswith(_SUFFIX) or name.startswith((".", "_")):
                continue
            out.append(name[:-len(_SUFFIX)])
        return sorted(out)

    def batch_path(self, batch_id: str) -> str:
        return os.path.join(self.directory, batch_id + _SUFFIX)

    def has_batch(self, batch_id: str) -> bool:
        return os.path.exists(self.batch_path(batch_id))

    def _adopt_schema(self) -> pa.Schema | None:
        if self.schema is None:
            ids = self.list_batches()
            if ids:
                self.schema = pq.read_schema(self.batch_path(ids[0]))
        return self.schema

    # -- ingest ---------------------------------------------------------------

    def append_table(self, batch_id: str, tbl: pa.Table) -> bool:
        """Persist one batch durably; False when (source, batch_id) already
        exists — the idempotent-duplicate path, which MUST stay cheap and
        side-effect-free (a retried APPEND lands here)."""
        from spark_rapids_tpu.runtime import eventlog as EL
        from spark_rapids_tpu.runtime import faults as F
        from spark_rapids_tpu.runtime import metrics as M
        if not _BATCH_ID_RE.match(batch_id or ""):
            raise ValueError(f"invalid batch id {batch_id!r}")
        path = self.batch_path(batch_id)
        with self._lock:
            if os.path.exists(path):
                return False
            schema = self._adopt_schema()
            if schema is not None and not tbl.schema.equals(
                    schema, check_metadata=False):
                raise ValueError(
                    f"append to {self.name!r} with schema "
                    f"{tbl.schema.names}/{[str(t) for t in tbl.schema.types]}"
                    f" != source schema {schema.names}/"
                    f"{[str(t) for t in schema.types]}")
            # chaos: an armed streaming.ingest fault fires before any byte
            # is durable — the client sees a typed error and retries; an
            # exec_kill here leaves at most a reclaimable intent file
            F.maybe_inject_any("streaming.ingest")
            tmp = f"{path}.tmp.{os.getpid()}"
            pq.write_table(tbl, tmp)
            os.replace(tmp, path)
            if self.schema is None:
                self.schema = tbl.schema
        EL.emit("stream.append", query=None, source=self.name,
                batch=batch_id, rows=tbl.num_rows)
        M.counter_add("streaming.appends")
        return True

    def append_ipc(self, batch_id: str, body: bytes, crc: int):
        """Verify the wire CRC, decode, persist; returns (table, fresh)
        where fresh=False is the idempotent-duplicate path. A CRC mismatch
        is a retryable TransportError — the payload was damaged in flight,
        the client's retry re-sends it intact — and is checked BEFORE the
        duplicate shortcut, so a torn re-send never ACKs as a duplicate."""
        got = block_checksum(body)
        if got != crc:
            raise TransportError(
                f"APPEND payload checksum mismatch (sent {crc:#x}, got "
                f"{got:#x}, {len(body)}B)")
        tbl = ipc_to_table(body)
        return tbl, self.append_table(batch_id, tbl)

    # -- query surface --------------------------------------------------------

    def dataframe(self, session):
        """A FRESH DataFrame over the batch log — re-listed per call, so a
        view resolved through it sees every batch durable at plan time
        (io/filescan.py freezes file lists at construction; the session
        re-resolves stream views on every sql())."""
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.io.filescan import FileScanNode
        from spark_rapids_tpu.plan import nodes as NN
        from spark_rapids_tpu.session import DataFrame
        ids = self.list_batches()
        if not ids:
            schema = self._adopt_schema()
            if schema is None:
                raise ValueError(
                    f"stream source {self.name!r} is empty and has no "
                    f"declared schema; pass schema= or append first")
            return DataFrame(NN.ScanNode([schema.empty_table()],
                                         T.StructType.from_arrow(schema)),
                             session)
        paths = [self.batch_path(b) for b in ids]
        return DataFrame(FileScanNode(paths, "parquet",
                                      files_per_partition=len(paths)),
                         session)
