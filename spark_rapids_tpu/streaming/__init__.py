"""Micro-batch streaming: exactly-once continuous ingestion.

Three pieces (docs/streaming.md has the full contract):

  - :class:`~spark_rapids_tpu.streaming.source.StreamingSource` — a durable
    append-only batch log (directory tail + endpoint APPEND frames),
    idempotent by (source, batch_id).
  - :class:`~spark_rapids_tpu.streaming.journal.EpochJournal` — the
    crash-consistent epoch.begin/epoch.commit journal exactly-once hangs
    off.
  - :class:`~spark_rapids_tpu.streaming.coordinator.EpochCoordinator` —
    runs each micro-batch as a normal admitted query against incremental
    aggregation state held as a spillable retained catalog buffer.
"""

from spark_rapids_tpu.streaming.coordinator import (EpochCoordinator,
                                                    StreamStateCorruptError)
from spark_rapids_tpu.streaming.journal import (EpochJournal,
                                                JournalCorruptError,
                                                validate_doc)
from spark_rapids_tpu.streaming.source import StreamingSource

__all__ = ["EpochCoordinator", "EpochJournal", "JournalCorruptError",
           "StreamStateCorruptError", "StreamingSource", "validate_doc"]
