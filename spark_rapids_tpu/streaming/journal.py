"""Crash-consistent epoch journal — the exactly-once spine of a stream.

One JSON document `epoch_journal.json` per stream directory records the
micro-batch epoch protocol::

    epoch.begin  {epoch, batch_ids, attempt, prev_state_checksum}
    epoch.commit {epoch, state_checksum, state_rows, watermark, ...}

The write discipline is the PlanHistoryStore idiom (runtime/history.py):
read-modify-replace under a cross-process advisory lock (runtime/locks.py)
with a pid-unique intent file landing via ``os.replace`` — a SIGKILL at any
byte leaves either the old document or the new one, never a torn file, and
a crashed writer's orphaned ``*.tmp.<pid>`` intent is recognizable to the
fleet sweeper (runtime/fleet.py).

Exactly-once falls out of three invariants the journal enforces:

  - ``begin`` is written BEFORE the epoch's query runs, naming the exact
    input batch ids; a crash between begin and commit leaves the begin
    record pending, and recovery replays those ids — not whatever the
    source directory lists now — against the last committed state, so the
    replay is bit-identical with the run that died.
  - Re-beginning a pending epoch bumps its ``attempt`` counter — the same
    fencing idiom as the shuffle epoch bump (cluster/minicluster.py
    MapOutputTracker): state snapshots are stamped with the epoch they
    belong to, so a stale partial from a dead attempt can never be adopted
    as committed state.
  - ``commit`` folds the epoch's batch ids into the ``consumed`` set in
    the SAME atomic replace that advances ``committed_epoch`` — a batch id
    is consumed if and only if its epoch committed, which is what makes
    APPEND idempotent by (source, batch_id) and committed epochs
    impossible to reapply.

The document is deliberately small: ``consumed``/``committed_epoch``/
``begin`` are the protocol state and never truncated; the ``commits``
history is an observability tail (profiler.py streaming) bounded by
``streaming.journal.maxCommits``.
"""

from __future__ import annotations

import json
import logging
import os
import threading

from spark_rapids_tpu.runtime.locks import advisory_lock

log = logging.getLogger("spark_rapids_tpu.streaming")

FILE = "epoch_journal.json"
_VERSION = 1


class JournalCorruptError(RuntimeError):
    """The journal exists but cannot carry the exactly-once contract.

    Unlike plan history, the journal is NOT an optimization: silently
    degrading a corrupt journal to empty would re-consume every committed
    batch. The stream refuses to run instead."""


class EpochJournal:
    """One stream's epoch journal. Thread-safe inside the process; the
    advisory lock orders writers across replica processes sharing the
    stream directory."""

    def __init__(self, directory: str, *, source: str = "",
                 max_commits: int = 256):
        self.directory = directory
        self.source = source
        self.max_commits = max(int(max_commits), 1)
        self.path = os.path.join(directory, FILE)
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)

    # -- file I/O -------------------------------------------------------------

    def _empty(self) -> dict:
        return {"version": _VERSION, "source": self.source,
                "committed_epoch": 0, "consumed": [], "begin": None,
                "commits": []}

    def _load(self) -> dict:
        """The document; a MISSING file is a fresh stream (empty doc), a
        corrupt one raises — exactly-once state must never silently
        degrade."""
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except FileNotFoundError:
            return self._empty()
        except (OSError, ValueError) as e:
            raise JournalCorruptError(
                f"epoch journal {self.path} unreadable: {e}") from e
        errs = validate_doc(doc)
        if errs:
            raise JournalCorruptError(
                f"epoch journal {self.path} violates its schema: {errs}")
        return doc

    def _store(self, doc: dict) -> None:
        doc["commits"] = doc["commits"][-self.max_commits:]
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, separators=(",", ":"))
        os.replace(tmp, self.path)

    # -- reads ----------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock, advisory_lock(self.path + ".lock"):
            return self._load()

    def committed_epoch(self) -> int:
        return int(self.snapshot()["committed_epoch"])

    def pending(self) -> dict | None:
        """The begin record of an epoch that began but never committed —
        what recovery must replay — or None."""
        begin = self.snapshot()["begin"]
        return dict(begin) if begin else None

    def is_consumed(self, batch_id: str) -> bool:
        return batch_id in self.snapshot()["consumed"]

    def last_commit(self) -> dict | None:
        commits = self.snapshot()["commits"]
        return dict(commits[-1]) if commits else None

    # -- protocol writes -------------------------------------------------------

    def begin(self, epoch: int, batch_ids: list, *,
              prev_state_checksum: int = 0) -> int:
        """Journal epoch.begin; returns the attempt number. Re-beginning the
        SAME pending epoch (recovery replay) bumps the attempt — the
        stale-partial fence; beginning any other epoch than committed+1, or
        while a different epoch is pending, is a protocol bug and raises."""
        with self._lock, advisory_lock(self.path + ".lock"):
            doc = self._load()
            committed = int(doc["committed_epoch"])
            pending = doc["begin"]
            if epoch != committed + 1:
                raise ValueError(
                    f"epoch.begin {epoch} out of order "
                    f"(committed {committed})")
            if pending and int(pending["epoch"]) != epoch:
                raise ValueError(
                    f"epoch.begin {epoch} while epoch "
                    f"{pending['epoch']} is pending")
            dup = set(batch_ids) & set(doc["consumed"])
            if dup:
                raise ValueError(
                    f"epoch.begin {epoch} names already-consumed "
                    f"batches {sorted(dup)}")
            attempt = int(pending["attempt"]) + 1 if pending else 1
            doc["begin"] = {"epoch": epoch,
                            "batch_ids": list(batch_ids),
                            "attempt": attempt,
                            "prev_state_checksum": int(prev_state_checksum)}
            self._store(doc)
        from spark_rapids_tpu.runtime import eventlog as EL
        EL.emit("stream.epoch.begin", query=None, source=self.source,
                epoch=epoch, attempt=attempt, batches=len(batch_ids))
        return attempt

    def commit(self, epoch: int, *, state_checksum: int, state_rows: int,
               state_bytes: int, rows_in: int = 0, retired_rows: int = 0,
               watermark=None, compiles: int | None = None) -> dict:
        """Journal epoch.commit: advance committed_epoch and fold the
        pending begin's batch ids into ``consumed`` in ONE atomic replace.
        The armed ``streaming.epoch.commit`` fault site fires BEFORE the
        write — an exec_kill there dies with the epoch's work done but
        unjournaled, the exact window recovery must close."""
        from spark_rapids_tpu.runtime import eventlog as EL
        from spark_rapids_tpu.runtime import faults as F
        F.maybe_inject_any("streaming.epoch.commit")
        with self._lock, advisory_lock(self.path + ".lock"):
            doc = self._load()
            pending = doc["begin"]
            if not pending or int(pending["epoch"]) != epoch:
                raise ValueError(
                    f"epoch.commit {epoch} without a matching begin "
                    f"(pending {pending and pending['epoch']})")
            rec = {"epoch": epoch, "batch_ids": list(pending["batch_ids"]),
                   "attempt": int(pending["attempt"]),
                   "state_checksum": int(state_checksum),
                   "state_rows": int(state_rows),
                   "state_bytes": int(state_bytes),
                   "rows_in": int(rows_in),
                   "retired_rows": int(retired_rows),
                   "watermark": watermark}
            if compiles is not None:
                rec["compiles"] = int(compiles)
            doc["committed_epoch"] = epoch
            doc["consumed"] = sorted(set(doc["consumed"]) |
                                     set(pending["batch_ids"]))
            doc["begin"] = None
            doc["commits"].append(rec)
            self._store(doc)
        EL.emit("stream.epoch.commit", query=None, source=self.source,
                epoch=epoch, attempt=rec["attempt"],
                batches=len(rec["batch_ids"]), rows_in=rec["rows_in"],
                state_rows=rec["state_rows"],
                state_bytes=rec["state_bytes"],
                retired_rows=rec["retired_rows"],
                watermark=watermark, state_checksum=rec["state_checksum"])
        return rec


def validate_doc(doc: dict) -> list:
    """Schema check of one journal document; returns violation strings
    (empty = valid). Shared by the journal's own loads, tools/profiler.py
    streaming and the tests, so the enforced schema cannot drift."""
    errs = []
    if not isinstance(doc, dict):
        return ["journal document is not an object"]
    if doc.get("version") != _VERSION:
        errs.append(f"version {doc.get('version')!r} != {_VERSION}")
    committed = doc.get("committed_epoch")
    if not isinstance(committed, int) or committed < 0:
        errs.append("committed_epoch missing or negative")
        committed = 0
    consumed = doc.get("consumed")
    if (not isinstance(consumed, list)
            or not all(isinstance(b, str) for b in consumed)):
        errs.append("consumed is not a list of batch ids")
        consumed = []
    begin = doc.get("begin")
    if begin is not None:
        if not isinstance(begin, dict):
            errs.append("begin is not an object")
        else:
            if begin.get("epoch") != committed + 1:
                errs.append(
                    f"pending begin epoch {begin.get('epoch')!r} is not "
                    f"committed_epoch+1 ({committed + 1})")
            if not isinstance(begin.get("attempt"), int) or \
                    begin["attempt"] < 1:
                errs.append("begin: missing positive integer 'attempt'")
            ids = begin.get("batch_ids")
            if not isinstance(ids, list) or not ids:
                errs.append("begin: missing non-empty batch_ids")
            elif set(ids) & set(consumed):
                errs.append("begin names already-consumed batch ids")
    commits = doc.get("commits")
    if not isinstance(commits, list):
        errs.append("commits is not a list")
        commits = []
    last = None
    for rec in commits:
        if not isinstance(rec, dict):
            errs.append("commit record is not an object")
            continue
        ep = rec.get("epoch")
        if not isinstance(ep, int) or ep < 1:
            errs.append(f"commit epoch {ep!r} invalid")
            continue
        if last is not None and ep != last + 1:
            errs.append(f"commit epochs not contiguous: {last} -> {ep}")
        last = ep
        for field in ("state_checksum", "state_rows", "state_bytes",
                      "rows_in", "retired_rows", "attempt"):
            if not isinstance(rec.get(field), int):
                errs.append(f"commit {ep}: missing integer {field!r}")
        ids = rec.get("batch_ids")
        if not isinstance(ids, list) or not ids:
            errs.append(f"commit {ep}: missing non-empty batch_ids")
        elif not set(ids) <= set(consumed):
            errs.append(f"commit {ep}: batch ids missing from consumed")
    if commits and last != committed:
        errs.append(
            f"last commit {last} != committed_epoch {committed}")
    return errs
