"""Typed configuration registry — the RapidsConf analog.

Reference: sql-plugin/src/main/scala/com/nvidia/spark/rapids/RapidsConf.scala:30,116,288
(110 typed `spark.rapids.*` entries with docs/defaults/internal flags, byte-unit parsing,
and markdown doc generation via `main`, RapidsConf.scala:1259). Same design here under the
`spark.rapids.tpu.*` namespace: a ConfBuilder DSL registers ConfEntry objects; RapidsConf
wraps a plain dict of overrides and resolves typed values; `python -m
spark_rapids_tpu.config` regenerates docs/configs.md.
"""

from __future__ import annotations

import dataclasses
import re
import typing

_REGISTERED: "dict[str, ConfEntry]" = {}

_BYTE_SUFFIXES = {
    "b": 1, "k": 1 << 10, "kb": 1 << 10, "m": 1 << 20, "mb": 1 << 20,
    "g": 1 << 30, "gb": 1 << 30, "t": 1 << 40, "tb": 1 << 40,
}


def parse_bytes(v) -> int:
    """Parse '512m', '4g', plain ints — Spark byte-unit strings
    (reference RapidsConf.scala byteConf entries)."""
    if isinstance(v, (int, float)):
        return int(v)
    m = re.fullmatch(r"\s*(\d+)\s*([a-zA-Z]*)\s*", str(v))
    if not m:
        raise ValueError(f"cannot parse byte value {v!r}")
    n, suf = int(m.group(1)), m.group(2).lower()
    if suf and suf not in _BYTE_SUFFIXES:
        raise ValueError(f"unknown byte suffix {suf!r} in {v!r}")
    return n * _BYTE_SUFFIXES.get(suf, 1)


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("true", "1", "yes")


@dataclasses.dataclass(frozen=True)
class ConfEntry:
    key: str
    doc: str
    default: typing.Any
    conv: typing.Callable
    internal: bool = False

    def get(self, settings: dict):
        if self.key in settings:
            return self.conv(settings[self.key])
        return self.default


class ConfBuilder:
    """`conf("spark.rapids.tpu.x").doc(...).boolean_conf(default)` DSL
    (reference RapidsConf.scala:288 ConfBuilder)."""

    def __init__(self, key: str):
        self._key = key
        self._doc = ""
        self._internal = False

    def doc(self, d: str) -> "ConfBuilder":
        self._doc = d
        return self

    def internal(self) -> "ConfBuilder":
        self._internal = True
        return self

    def _register(self, default, conv) -> ConfEntry:
        e = ConfEntry(self._key, self._doc, default, conv, self._internal)
        if e.key in _REGISTERED:
            raise ValueError(f"duplicate conf key {e.key}")
        _REGISTERED[e.key] = e
        return e

    def boolean_conf(self, default: bool) -> ConfEntry:
        return self._register(default, _parse_bool)

    def integer_conf(self, default: int) -> ConfEntry:
        return self._register(default, int)

    def double_conf(self, default: float) -> ConfEntry:
        return self._register(default, float)

    def string_conf(self, default) -> ConfEntry:
        return self._register(default, lambda v: v if v is None else str(v))

    def bytes_conf(self, default) -> ConfEntry:
        return self._register(parse_bytes(default), parse_bytes)


def conf(key: str) -> ConfBuilder:
    return ConfBuilder(key)


# ---------------------------------------------------------------------------
# Registry — mirrors the reference's main knobs (RapidsConf.scala:301-1139)
# ---------------------------------------------------------------------------

SQL_ENABLED = conf("spark.rapids.tpu.sql.enabled").doc(
    "Enable TPU acceleration of SQL operators; when false every plan stays on CPU "
    "(reference spark.rapids.sql.enabled)").boolean_conf(True)

EXPLAIN = conf("spark.rapids.tpu.sql.explain").doc(
    "NONE | ALL | NOT_ON_TPU — log why operators will / will not run on the TPU "
    "(reference spark.rapids.sql.explain)").string_conf("NONE")

BATCH_SIZE_BYTES = conf("spark.rapids.tpu.sql.batchSizeBytes").doc(
    "Target size of output batches from coalescing and readers "
    "(reference spark.rapids.sql.batchSizeBytes, RapidsConf.scala:411)"
).bytes_conf("512m")

MAX_READER_BATCH_SIZE_ROWS = conf("spark.rapids.tpu.sql.reader.batchSizeRows").doc(
    "Soft cap on rows per reader batch (reference reader.batchSizeRows)"
).integer_conf(2147483647)

MAX_READER_BATCH_SIZE_BYTES = conf("spark.rapids.tpu.sql.reader.batchSizeBytes").doc(
    "Soft cap on bytes per reader batch (reference reader.batchSizeBytes)"
).bytes_conf("512m")

CONCURRENT_TPU_TASKS = conf("spark.rapids.tpu.sql.concurrentTpuTasks").doc(
    "Tasks admitted to the TPU concurrently via the semaphore "
    "(reference spark.rapids.sql.concurrentGpuTasks, RapidsConf.scala:398)"
).integer_conf(2)

DEVICE_ORDINAL = conf("spark.rapids.tpu.device.ordinal").doc(
    "Which visible accelerator device this process acquires (reference: one "
    "GPU per executor, GpuDeviceManager.scala:103)").integer_conf(0)

DEVICE_EAGER_INIT = conf("spark.rapids.tpu.device.eagerInit").doc(
    "Acquire and warm up the device at session creation instead of first "
    "use — fails fast on a dead backend like the reference's executor "
    "plugin (Plugin.scala:210 crash-fast)").boolean_conf(False)

DEVICE_MEMORY_FRACTION = conf("spark.rapids.tpu.memory.hbm.allocFraction").doc(
    "Fraction of HBM the pool budget may use "
    "(reference spark.rapids.memory.gpu.allocFraction)").double_conf(0.9)

DEVICE_MEMORY_LIMIT = conf("spark.rapids.tpu.memory.hbm.limitBytes").doc(
    "Absolute HBM budget override; 0 = derive from allocFraction").bytes_conf(0)

HOST_SPILL_STORAGE_SIZE = conf("spark.rapids.tpu.memory.host.spillStorageSize").doc(
    "Bytes of host memory used for spilled device buffers before disk "
    "(reference spark.rapids.memory.host.spillStorageSize)").bytes_conf("1g")

SPILL_DIRS = conf("spark.rapids.tpu.memory.spill.dirs").doc(
    "Comma-separated local dirs for the disk spill tier "
    "(reference uses Spark local dirs, RapidsDiskStore.scala)").string_conf(None)

DIRECT_SPILL_ENABLED = conf(
    "spark.rapids.tpu.memory.direct.storage.spill.enabled").doc(
    "Spill the disk tier through the batched aligned direct-I/O store "
    "(O_DIRECT; the GDS analog — reference "
    "spark.rapids.memory.gpu.direct.storage.spill.enabled, RapidsGdsStore)"
).boolean_conf(False)

DIRECT_SPILL_BATCH_BYTES = conf(
    "spark.rapids.tpu.memory.direct.storage.spill.batchWriteBufferSize").doc(
    "Size at which a direct-spill batch file rotates (reference GDS "
    "batchWriteBufferSize)").bytes_conf("64m")

STRICT_DEVICE_BUDGET = conf("spark.rapids.tpu.memory.hbm.strictBudget").doc(
    "When a registration cannot spill the device tier back under the HBM "
    "budget, raise a retryable DeviceOomError (the DeviceMemoryEventHandler "
    "OOM analog) so the task-scoped retry framework (runtime/retry.py) can "
    "spill, split the input batch and re-run. false restores the legacy "
    "lenient accounting that silently left the device tier over budget"
).boolean_conf(True)

RETRY_MAX_SPLITS = conf("spark.rapids.tpu.memory.retry.maxSplits").doc(
    "Times one input batch may be split in half by OOM split-and-retry "
    "before the error is re-raised (reference RmmRapidsRetryIterator's "
    "splitSpillableInHalfByRows ladder)").integer_conf(8)

RETRY_SPLIT_FLOOR_BYTES = conf(
    "spark.rapids.tpu.memory.retry.splitFloorBytes").doc(
    "Split-and-retry never produces a batch smaller than this (nor below 2 "
    "rows); at the floor one spill-only retry runs and then the OOM "
    "propagates").bytes_conf("64k")

TEST_FAULTS = conf("spark.rapids.tpu.test.faults").doc(
    "Deterministic fault-injection spec 'kind:site:trigger,...' — kinds "
    "oom / splitoom / transport / error / exec_kill / hang / cancel / "
    "slow / corrupt / leak / disk_full; trigger COUNT, COUNT@SKIP or "
    "pPROB; e.g. 'oom:joins.build:2,transport:fetch:1,"
    "cancel:pipeline.put.scan.decode:1' (grammar + site list in "
    "runtime/faults.py; pipeline.put/get sites fire whatever kind is "
    "armed). Chaos testing only — never set in production; "
    "empty disables").string_conf(None)

TEST_FAULTS_SEED = conf("spark.rapids.tpu.test.faults.seed").doc(
    "Seed for probabilistic (pPROB) fault triggers; each (kind, site) "
    "entry draws from its own stream seeded by (seed, kind, site), so one "
    "seed yields one deterministic schedule per site even under the "
    "pipeline's worker-thread interleavings").integer_conf(0)

UNSPILL_ENABLED = conf("spark.rapids.tpu.memory.hbm.unspill.enabled").doc(
    "Re-promote spilled buffers back to HBM on access "
    "(reference spark.rapids.memory.gpu.unspill.enabled)").boolean_conf(False)

# NOTE: the reference's RMM pooling conf (spark.rapids.memory.gpu.pool,
# GpuDeviceManager.scala:204) has no TPU analog to toggle: XLA owns the HBM
# arena (BFC allocator) and the engine's power-of-two capacity bucketing
# (columnar/vector.py:bucket_capacity) is the pooling strategy — it is not
# optional, so no conf is registered for it.

STABLE_SORT = conf("spark.rapids.tpu.sql.stableSort.enabled").doc(
    "Force stable device sorts (reference spark.rapids.sql.stableSort.enabled)"
).boolean_conf(False)

HAS_NANS = conf("spark.rapids.tpu.sql.hasNans").doc(
    "Assume floating point columns may hold NaNs, enabling Spark-exact NaN ordering "
    "and equality (reference spark.rapids.sql.hasNans)").boolean_conf(True)

IMPROVED_FLOAT_OPS = conf("spark.rapids.tpu.sql.improvedFloatOps.enabled").doc(
    "Allow float aggregations whose ordering differs from CPU Spark "
    "(reference spark.rapids.sql.variableFloatAgg.enabled)").boolean_conf(True)

ENABLE_CAST_STRING_TO_FLOAT = conf("spark.rapids.tpu.sql.castStringToFloat.enabled").doc(
    "Enable string→float casts which can differ in rounding from CPU "
    "(reference spark.rapids.sql.castStringToFloat.enabled)").boolean_conf(False)

DECIMAL_ENABLED = conf("spark.rapids.tpu.sql.decimalType.enabled").doc(
    "Enable decimal(<=18) device execution (reference decimalType.enabled)"
).boolean_conf(True)

SHUFFLE_MANAGER_ENABLED = conf("spark.rapids.tpu.shuffle.enabled").doc(
    "Use the catalog-backed accelerated shuffle instead of the serializing fallback "
    "(reference RapidsShuffleManager wiring)").boolean_conf(True)

SHUFFLE_TRANSPORT_CLASS = conf("spark.rapids.tpu.shuffle.transport.class").doc(
    "Transport implementation classname for the P2P shuffle data plane "
    "(reference spark.rapids.shuffle.transport.class, RapidsConf.scala:925)"
).string_conf("spark_rapids_tpu.shuffle.transport.LocalTransport")

SHUFFLE_COMPRESSION_CODEC = conf("spark.rapids.tpu.shuffle.compression.codec").doc(
    "none | lz4 | copy — codec for shuffle buffers (reference "
    "spark.rapids.shuffle.compression.codec over nvcomp; here a native C++ LZ4)"
).string_conf("lz4")

SHUFFLE_COMPRESSION_TCP_ONLY = conf(
    "spark.rapids.tpu.shuffle.compression.tcpOnly").doc(
    "Compress shuffle frames only for peers whose link classifies as "
    "genuinely tcp (cross-host): loopback/local/ici stay uncompressed — "
    "spending CPU to shrink bytes that never cross a real wire loses. The "
    "movement ledger's wire-vs-payload dual units make the ratio visible "
    "per link class. false compresses every serialized transfer whenever "
    "the codec is active").boolean_conf(True)

SHUFFLE_MAX_INFLIGHT_BYTES = conf(
    "spark.rapids.tpu.shuffle.maxBytesInFlight").doc(
    "Throttle on concurrently fetched shuffle bytes "
    "(reference UCXShuffleTransport.scala:51-56)").bytes_conf("128m")

SHUFFLE_BOUNCE_BUFFER_SIZE = conf("spark.rapids.tpu.shuffle.bounceBuffers.size").doc(
    "Size of each staging (bounce) buffer used to window large transfers "
    "(reference spark.rapids.shuffle.bounceBuffers.size, 4 MB default)").bytes_conf("4m")

SHUFFLE_FETCH_MAX_RETRIES = conf("spark.rapids.tpu.shuffle.fetch.maxRetries").doc(
    "Fetch failures tolerated per reduce partition before the query fails; "
    "each failure invalidates the map outputs and recomputes them (reference "
    "TransferError -> FetchFailedException -> stage retry, "
    "RapidsShuffleIterator.scala:82)").integer_conf(2)

METRICS_LEVEL = conf("spark.rapids.tpu.sql.metrics.level").doc(
    "ESSENTIAL | MODERATE | DEBUG (reference spark.rapids.sql.metrics.level, "
    "RapidsConf.scala:465)").string_conf("MODERATE")

TRACE_ENABLED = conf("spark.rapids.tpu.sql.trace.enabled").doc(
    "Wrap hot regions in jax.profiler trace annotations (reference NVTX ranges, "
    "NvtxWithMetrics.scala)").boolean_conf(False)

CPU_FALLBACK_ENABLED = conf("spark.rapids.tpu.sql.cpuFallback.enabled").doc(
    "Allow untagged operators to run via the host (pyarrow) fallback engine rather "
    "than fail (the reference always retains Spark CPU execution)").boolean_conf(True)

TEST_ENABLED = conf("spark.rapids.tpu.sql.test.enabled").doc(
    "Fail if an operator unexpectedly falls back to CPU "
    "(reference spark.rapids.sql.test.enabled, RapidsConf.scala:854)").internal(
).boolean_conf(False)

TEST_ALLOWED_NON_TPU = conf("spark.rapids.tpu.sql.test.allowedNonTpu").doc(
    "Comma-separated operator class names allowed on CPU when test.enabled "
    "(reference test.allowedNonGpu)").internal().string_conf("")

ENABLE_WHOLE_STAGE_FUSION = conf("spark.rapids.tpu.sql.stageFusion.enabled").doc(
    "Trace adjacent project/filter/aggregate operators into a single XLA program. "
    "TPU-first optimization with no reference analog (cudf launches one kernel per op)"
).boolean_conf(True)

ENABLE_SCAN_FUSION = conf("spark.rapids.tpu.sql.stageFusion.scan.enabled").doc(
    "Fuse the parquet page-decode prologue (bit-unpack + dictionary gather + "
    "null spread) into the consuming aggregate's per-batch program, so a scan "
    "stage runs decode->project->filter->partial-agg as one XLA dispatch over "
    "ENCODED page bytes; batches no consumer can absorb decode standalone "
    "through the same fused kernel (degraded, never wrong). Requires "
    "stageFusion.enabled").boolean_conf(True)

ENABLE_GROUPBY_CHAIN = conf(
    "spark.rapids.tpu.sql.stageFusion.groupBy.chain.enabled").doc(
    "Chain the aggregation's per-batch update->concat->merge loop into one "
    "fused program per input batch with predictive output capacity (the "
    "broadcast-join probe-chain discipline): one host sync per batch instead "
    "of the per-batch key-stats / concat-count / right-sizing syncs. A "
    "mispredicted capacity discards the chained result and reruns the "
    "unchained path for that batch. Batches below a small capacity floor "
    "(1024) go unchained: the fused program's one-off compile cannot "
    "amortize over toy batches and would count against an armed cluster "
    "task deadline. Requires stageFusion.enabled"
).boolean_conf(True)

STAGE_CACHE_ENABLED = conf("spark.rapids.tpu.sql.stage.cache.enabled").doc(
    "Persist compiled stage executables (serialized XLA programs) to disk and "
    "reload them in later sessions, skipping tracing and compilation entirely "
    "on warm starts. Requires stage.cache.dir. Entries are keyed by backend "
    "platform + jax/package versions + kernel semantics + argument signature; "
    "corrupt or stale entries degrade to a retrace with a warning"
).boolean_conf(False)

STAGE_CACHE_DIR = conf("spark.rapids.tpu.sql.stage.cache.dir").doc(
    "Directory for the persistent compiled-stage cache (created on demand). "
    "Safe to share across sessions of the same build; entries from other "
    "backends/versions are ignored").string_conf("")

STAGE_CACHE_MAX_BYTES = conf("spark.rapids.tpu.sql.stage.cache.maxBytes").doc(
    "On-disk size budget for the compiled-stage cache; least-recently-used "
    "entries are pruned past it").bytes_conf("256m")

PARQUET_READER_TYPE = conf("spark.rapids.tpu.sql.format.parquet.reader.type").doc(
    "PERFILE | MULTITHREADED | COALESCING (reference GpuParquetScan.scala:317,426 "
    "reader strategies)").string_conf("MULTITHREADED")

MULTITHREADED_READ_NUM_THREADS = conf(
    "spark.rapids.tpu.sql.format.parquet.multiThreadedRead.numThreads").doc(
    "Thread pool size for the multithreaded reader (reference "
    "multiThreadedRead.numThreads)").integer_conf(20)

PARQUET_WRITER_TYPE = conf("spark.rapids.tpu.sql.format.parquet.writer.type").doc(
    "NATIVE encodes Parquet pages from device columns (stats + null "
    "compaction on device, thrift framing on host — reference "
    "ColumnarOutputWriter.scala device-buffer write); ARROW round-trips "
    "through host pyarrow. NATIVE falls back to ARROW for unsupported "
    "schemas (lists, decimal>18) and partitioned writes.").string_conf("NATIVE")

ORC_WRITER_TYPE = conf("spark.rapids.tpu.sql.format.orc.writer.type").doc(
    "NATIVE encodes ORC stripes from device columns (null compaction + "
    "stats on device, RLEv2/protobuf framing on host — reference "
    "GpuOrcFileFormat.scala device-buffer write); ARROW round-trips "
    "through host pyarrow. NATIVE falls back to ARROW for unsupported "
    "schemas (lists, decimal>18) and partitioned writes.").string_conf("NATIVE")

CSV_WRITER_TYPE = conf("spark.rapids.tpu.sql.format.csv.writer.type").doc(
    "NATIVE formats CSV from device buffers (one transfer per column, "
    "vectorized host text, no arrow round-trip); ARROW uses host pyarrow. "
    "NATIVE falls back to ARROW for unsupported schemas and partitioned "
    "writes; float/timestamp formatting differences are documented in "
    "io/csv_write_native.py.").string_conf("NATIVE")

CSV_ENABLED = conf("spark.rapids.tpu.sql.format.csv.enabled").doc(
    "Enable accelerated CSV reading (reference spark.rapids.sql.format.csv.enabled)"
).boolean_conf(True)

ORC_ENABLED = conf("spark.rapids.tpu.sql.format.orc.enabled").doc(
    "Enable accelerated ORC reading (reference spark.rapids.sql.format.orc.enabled)"
).boolean_conf(True)

NUM_LOCAL_TASKS = conf("spark.rapids.tpu.sql.localScheduler.numThreads").doc(
    "Partition-task threads in the local scheduler (stands in for Spark executor "
    "task slots; the reference delegates scheduling to Spark)").integer_conf(4)

MESH_ENABLED = conf("spark.rapids.tpu.mesh.enabled").doc(
    "Run shuffle exchanges as SPMD all_to_all collectives over a "
    "jax.sharding.Mesh (the ICI data plane; stands in for the reference's "
    "UCX RapidsShuffleManager, shuffle-plugin UCXShuffleTransport.scala). "
    "Joins, two-phase aggregates and global sorts then ride co-partitioned "
    "mesh exchanges").boolean_conf(False)

MESH_DEVICES = conf("spark.rapids.tpu.mesh.devices").doc(
    "Number of mesh devices for collective exchanges; 0 uses every visible "
    "device").integer_conf(0)

UDF_COMPILER_ENABLED = conf("spark.rapids.tpu.sql.udfCompiler.enabled").doc(
    "Compile Python UDF bytecode into device expressions "
    "(reference udf-compiler translates Scala bytecode → Catalyst)").boolean_conf(True)

CACHE_SERIALIZER = conf("spark.rapids.tpu.sql.cache.serializer").doc(
    "DataFrame cache tier: 'device' (spillable HBM batches) or 'parquet' "
    "(blob files; reference ParquetCachedBatchSerializer)").string_conf("device")

OPTIMIZER_ENABLED = conf("spark.rapids.tpu.sql.optimizer.enabled").doc(
    "Cost-based rejection of unprofitable device sections "
    "(reference spark.rapids.sql.optimizer.enabled, CostBasedOptimizer.scala:52)"
).boolean_conf(False)

OPTIMIZER_MIN_ROWS = conf("spark.rapids.tpu.sql.optimizer.minRows").doc(
    "Estimated row count below which a plan stays on the host when the "
    "optimizer is enabled (transfer+launch overhead dominates tiny inputs)"
).integer_conf(4096)

OPTIMIZER_HOST_ROW_COST = conf("spark.rapids.tpu.sql.optimizer.host.rowCost").doc(
    "Dual cost model: seconds per row·weight for host execution "
    "(reference spark.rapids.sql.optimizer.cpu.exec.*, CostBasedOptimizer.scala)"
).double_conf(60e-9)

OPTIMIZER_TPU_ROW_COST = conf("spark.rapids.tpu.sql.optimizer.tpu.rowCost").doc(
    "Dual cost model: seconds per row·weight for device execution "
    "(reference spark.rapids.sql.optimizer.gpu.exec.*)").double_conf(1.5e-9)

OPTIMIZER_TPU_DISPATCH_COST = conf(
    "spark.rapids.tpu.sql.optimizer.tpu.dispatchCost").doc(
    "Dual cost model: fixed seconds per device operator dispatch (jit call "
    "over the runtime tunnel)").double_conf(2e-3)

OPTIMIZER_TRANSFER_ROW_COST = conf(
    "spark.rapids.tpu.sql.optimizer.transferRowCost").doc(
    "Dual cost model: seconds per row crossing a host↔device boundary "
    "(the reference's transitionCost per-byte analog)").double_conf(8e-9)

ADAPTIVE_COALESCE_ENABLED = conf(
    "spark.rapids.tpu.sql.adaptive.coalescePartitions.enabled").doc(
    "After a shuffle map stage materializes, merge contiguous small reduce "
    "partitions into advisory-sized reader partitions (AQE; reference "
    "GpuCustomShuffleReaderExec + Spark CoalesceShufflePartitions)"
).boolean_conf(True)

ADVISORY_PARTITION_BYTES = conf(
    "spark.rapids.tpu.sql.adaptive.advisoryPartitionSizeInBytes").doc(
    "Target size of a coalesced post-shuffle partition "
    "(Spark spark.sql.adaptive.advisoryPartitionSizeInBytes)").bytes_conf("64m")

ORC_DEVICE_DECODE = conf("spark.rapids.tpu.sql.orc.deviceDecode.enabled").doc(
    "Decode in-scope ORC stripes on device (protobuf/RLEv2 run headers on "
    "host, packed bits unpacked on device — io/orc_native.py); out-of-scope "
    "files or columns fall back to the arrow host reader (reference "
    "GpuOrcScan hands stripes to libcudf)").boolean_conf(True)

CSV_DEVICE_DECODE = conf("spark.rapids.tpu.sql.csv.deviceDecode.enabled").doc(
    "Parse in-scope CSV files on device (host boundary scan + device digit "
    "kernels, io/csv_native.py); out-of-scope files use the arrow host "
    "reader (reference decodes CSV via cudf, GpuBatchScanExec)"
).boolean_conf(True)

CSV_READ_FLOATS = conf("spark.rapids.tpu.sql.csv.read.float.enabled").doc(
    "Allow float/double CSV columns on the device parse path; the final "
    "power-of-ten division can differ from Spark's strtod by 1 ulp "
    "(reference spark.rapids.sql.csv.read.float.enabled, same default)"
).boolean_conf(False)

SCAN_READAHEAD_DEPTH = conf("spark.rapids.tpu.sql.scan.readahead.depth").doc(
    "Decoded host batches a file scan prefetches ahead of device compute on "
    "a background thread (0 disables): host parquet/orc/csv decode of batch "
    "N+1 overlaps device compute of batch N for every reader strategy "
    "(reference MultiFileCloudParquetPartitionReader's prefetch role, "
    "GpuParquetScan.scala:1377, generalized past the MULTITHREADED reader)"
).integer_conf(2)

SCAN_READAHEAD_MAX_BUFFER = conf(
    "spark.rapids.tpu.sql.scan.readahead.maxBufferBytes").doc(
    "Byte cap on host tables buffered by the scan readahead queue; the "
    "effective budget also shrinks to the spill catalog's free host "
    "headroom (runtime/memory.scan_readahead_budget) so prefetch never "
    "competes with host spill storage").bytes_conf("256m")

PIPELINE_ENABLED = conf("spark.rapids.tpu.pipeline.enabled").doc(
    "Run each plan segment's batch loop on its own worker thread at the "
    "pipeline breakers (scan, exchange map/reduce, join build, sort, final "
    "collect), connected by bounded byte-budgeted queues, so host decode, "
    "device compute and exchange I/O overlap (runtime/pipeline.py; the "
    "reference gets this overlap from CUDA streams + UCX's async progress "
    "thread). Results are bit-identical either way").boolean_conf(True)

PIPELINE_QUEUE_DEPTH = conf("spark.rapids.tpu.pipeline.queueDepth").doc(
    "Batches one pipeline queue edge may hold ahead of its consumer; 2 is "
    "classic double buffering (batch N resident while N+1 decodes/uploads)"
).integer_conf(2)

PIPELINE_MAX_QUEUE_BYTES = conf("spark.rapids.tpu.pipeline.maxQueueBytes").doc(
    "Byte cap per pipeline queue edge; the effective budget also shrinks "
    "to the spill catalog's free host headroom "
    "(runtime/memory.host_prefetch_budget) and queued device batches are "
    "registered as spillable so the OOM-retry ladder can steal them"
).bytes_conf("256m")

PALLAS_ENABLED = conf("spark.rapids.tpu.sql.pallas.enabled").doc(
    "Route the string murmur3 hash, parquet bit-unpack, dense group-by "
    "one-hot matmul, exchange radix partition, and unique-key hash-join "
    "probe through the hand-written Pallas TPU kernels "
    "(ops/pallas_kernels.py); when false (or off-TPU) the fused-XLA jnp "
    "formulations run instead").boolean_conf(True)

BROADCAST_TIMEOUT = conf("spark.rapids.tpu.sql.broadcast.timeout").doc(
    "Seconds a consumer waits for the broadcast relation to materialize; "
    "<=0 waits forever (Spark spark.sql.broadcastTimeout; reference "
    "GpuBroadcastExchangeExec relation future)").double_conf(300.0)

BROADCAST_MAX_TABLE_BYTES = conf("spark.rapids.tpu.sql.broadcast.maxTableBytes"
                                 ).doc(
    "Fail a broadcast whose materialized relation exceeds this size "
    "(reference maxBroadcastTableSize guard); 0 disables").bytes_conf("8g")

CLUSTER_TASK_MAX_FAILURES = conf("spark.rapids.tpu.cluster.task.maxFailures").doc(
    "Attempts one MiniCluster task gets before the query fails with the "
    "task's error; each retry is placed on a different executor when one is "
    "available (Spark spark.task.maxFailures)").integer_conf(4)

CLUSTER_TASK_TIMEOUT = conf("spark.rapids.tpu.cluster.task.timeoutSeconds").doc(
    "Deadline for one MiniCluster task; a task running past it has its "
    "executor killed (the pipe protocol cannot cancel a wedged task) and is "
    "retried on another executor, counting as a task failure against the "
    "slow executor. <=0 disables the deadline").double_conf(0.0)

CLUSTER_BLACKLIST_MAX_TASK_FAILURES = conf(
    "spark.rapids.tpu.cluster.blacklist.maxTaskFailures").doc(
    "Task failures charged to one executor before the driver blacklists it "
    "from further task placement (Spark spark.blacklist.* / "
    "spark.excludeOnFailure.*); a respawned executor starts with a clean "
    "record").integer_conf(2)

CLUSTER_STAGE_MAX_RECOMPUTES = conf(
    "spark.rapids.tpu.cluster.stage.maxRecomputes").doc(
    "Partial (lineage-scoped) recomputes one shuffle's map outputs may go "
    "through after executor losses before the driver falls back to the "
    "whole-query heal ladder (Spark spark.stage.maxConsecutiveAttempts)"
).integer_conf(4)

CLUSTER_SPECULATION_ENABLED = conf(
    "spark.rapids.tpu.cluster.speculation.enabled").doc(
    "Speculatively duplicate a stage's straggler tasks on idle executors "
    "once they exceed speculation.multiplier x the median completed task "
    "time; the first finisher wins and the loser's map outputs are "
    "discarded so results stay bit-identical (Spark spark.speculation)"
).boolean_conf(False)

CLUSTER_SPECULATION_MULTIPLIER = conf(
    "spark.rapids.tpu.cluster.speculation.multiplier").doc(
    "How many times slower than the median completed task time a running "
    "task must be before it is speculated "
    "(Spark spark.speculation.multiplier)").double_conf(3.0)

CLUSTER_PLACEMENT_SEED = conf("spark.rapids.tpu.cluster.placement.seed").doc(
    "Seed for the MiniCluster's deterministic round-robin task placement "
    "(rotates which executor gets the first task); tests use it to pin "
    "which executor hosts which map split").integer_conf(0)

CLUSTER_HEARTBEAT_TIMEOUT = conf(
    "spark.rapids.tpu.cluster.heartbeat.timeoutSeconds").doc(
    "Seconds without a liveness beat before the driver's heartbeat manager "
    "expires a MiniCluster executor (expire_dead -> partial stage "
    "recompute); beats are recorded on every task reply and liveness scan"
).double_conf(60.0)

CLUSTER_MESH_ENABLED = conf("spark.rapids.tpu.cluster.mesh.enabled").doc(
    "Unified mesh-cluster plane: every MiniCluster executor brings up a "
    "LOCAL device mesh (distributed/mesh.LocalMesh) and the driver groups a "
    "hash-partitioned map stage's splits into mesh tasks of up to "
    "devicesPerExecutor lanes — partition ids for all lanes are computed in "
    "ONE jitted shard_map program over the executor's chips with the "
    "map-output statistics all-reduced over ICI, while shuffle blocks still "
    "cross executors over the TCP transport (N processes x M chips, the "
    "reference's production shape). A mesh failure degrades transparently "
    "to per-split TCP execution, bit-identical (docs/cluster.md)"
).boolean_conf(False)

CLUSTER_MESH_TWO_LEVEL = conf(
    "spark.rapids.tpu.cluster.mesh.exchange.twoLevel").doc(
    "Two-level shuffle exchange on the mesh-cluster plane: the driver "
    "assigns every reduce partition an OWNING executor; inside that "
    "executor's mesh tasks the owned partitions' content moves lane→lane "
    "as lax.all_to_all over ICI (LocalMesh.exchange_wave) and lands "
    "directly in the process-local block store, while only partitions "
    "owned by OTHER hosts are sliced out and parked for the TCP fetch. "
    "Consumers are placed at their partition's owner so the ICI-moved "
    "bytes are read via the local short-circuit. Waves with string keys "
    "or variable-width columns fall back to slice-and-park per batch "
    "without breaking the mesh group; any exchange failure degrades the "
    "task to per-split TCP under a bumped epoch, bit-identical "
    "(docs/cluster.md)").boolean_conf(True)

CLUSTER_MESH_DEVICES = conf(
    "spark.rapids.tpu.cluster.mesh.devicesPerExecutor").doc(
    "Devices in each executor's local mesh (also the lane width of one mesh "
    "map task); 0 uses every device visible to the executor process. "
    "Executors report their ACTUAL attached width on the spawn handshake "
    "(mesh.attach), and a mesh that comes up narrower than the group being "
    "dispatched degrades that task to the per-split TCP path"
).integer_conf(0)

CLUSTER_PLACEMENT_MOVEMENT_AWARE = conf(
    "spark.rapids.tpu.cluster.placement.movementAware").doc(
    "Schedule a reduce task on the executor already holding the most "
    "map-output bytes for its reduce partition (per-split sizes tracked by "
    "the MapOutputTracker from every map reply), so the biggest input is a "
    "local block-store read instead of a TCP fetch — Theseus's "
    "movement-optimized placement. Falls back to seeded round-robin when "
    "the preferred host is busy, blacklisted, dead, or over "
    "placement.maxLoadedBytes").boolean_conf(True)

CLUSTER_PLACEMENT_MAX_LOADED_BYTES = conf(
    "spark.rapids.tpu.cluster.placement.maxLoadedBytes").doc(
    "Spill-aware demotion threshold for movement-aware placement: when the "
    "byte-dominant executor already parks more than this many shuffle bytes "
    "(a proxy for its HBM+host spill budget), the preferred pick is DEMOTED "
    "back to round-robin so reduce work does not pile onto a host that "
    "would only spill it to disk (placement.demoted event)").bytes_conf("2g")

CLUSTER_SPAWN_MAX_RETRIES = conf(
    "spark.rapids.tpu.cluster.spawn.maxRetries").doc(
    "Extra bring-up attempts a MiniCluster executor slot gets when the "
    "spawn handshake fails on a transient socket/pipe error before the "
    "driver gives up on the slot (executor.spawn.retry event per retry)"
).integer_conf(1)

SCHEDULER_MAX_CONCURRENT = conf("spark.rapids.tpu.scheduler.maxConcurrent").doc(
    "Queries the driver-side scheduler admits concurrently "
    "(runtime/scheduler.py; the Spark fair-scheduler pool-size analog). "
    "Structural: process-global, applied only by a session that sets it "
    "explicitly").integer_conf(4)

SCHEDULER_QUEUE_MAX_DEPTH = conf("spark.rapids.tpu.scheduler.queue.maxDepth").doc(
    "Submissions allowed to wait for admission; one more is SHED immediately "
    "with a retryable QueryRejectedError carrying a backoff hint (load "
    "shedding at the front door instead of OOM cascades). 0 disables the "
    "depth bound").integer_conf(32)

SCHEDULER_QUEUE_TIMEOUT = conf("spark.rapids.tpu.scheduler.queue.timeoutSeconds").doc(
    "A submission still queued for admission after this long is shed with a "
    "retryable QueryRejectedError (backoff hint included); <=0 waits "
    "forever").double_conf(30.0)

SCHEDULER_PRIORITY = conf("spark.rapids.tpu.scheduler.priority").doc(
    "Admission priority of THIS session's queries (higher admits first; the "
    "Spark fair-scheduler pool-weight analog). Read per submission, so "
    "sessions with different priorities share one scheduler").integer_conf(0)

SCHEDULER_PRIORITY_AGING = conf(
    "spark.rapids.tpu.scheduler.priority.agingSeconds").doc(
    "Queue-wait seconds that add +1 effective priority to a waiting "
    "submission, so low-priority tenants cannot be starved by a stream of "
    "high-priority arrivals; <=0 disables aging").double_conf(10.0)

SCHEDULER_QUERY_DEADLINE = conf(
    "spark.rapids.tpu.scheduler.query.deadlineSeconds").doc(
    "Per-query wall-clock deadline measured from submission (queue wait "
    "included); past it the query's CancelToken flips and every cooperative "
    "checkpoint raises QueryDeadlineError, draining the pipeline without "
    "leaking threads, device buffers or semaphore permits. <=0 disables"
).double_conf(0.0)

SCHEDULER_FOOTPRINT_FLOOR = conf(
    "spark.rapids.tpu.scheduler.footprint.floorBytes").doc(
    "Lower bound on the admission footprint estimate "
    "(scheduler.estimate_footprint): no query books less HBM than this, so "
    "tiny plans cannot stampede admission. Applies to both the static "
    "heuristic and history-based estimates").bytes_conf("16m")

SCHEDULER_FOOTPRINT_DECODE_EXPANSION = conf(
    "spark.rapids.tpu.scheduler.footprint.decodeExpansion").doc(
    "Multiplier from on-disk scan bytes to estimated decoded device bytes "
    "in the static (cold-start) footprint heuristic; only used when the "
    "plan-shape history store has no observation for the plan's "
    "fingerprint").double_conf(3.0)

TRANSPORT_MAX_FRAME_BYTES = conf(
    "spark.rapids.tpu.shuffle.transport.maxFrameBytes").doc(
    "Upper bound on one length-prefixed wire frame (shuffle data plane AND "
    "the query endpoint); a longer length prefix raises TransportError "
    "BEFORE any allocation, so a corrupt/truncated header cannot trigger a "
    "multi-GB read. Applied process-wide by whichever server/endpoint is "
    "constructed with it").bytes_conf("1g")

ENDPOINT_HOST = conf("spark.rapids.tpu.endpoint.host").doc(
    "Bind address of the Arrow-over-TCP query endpoint "
    "(runtime/endpoint.py); loopback by default — bind wider only behind "
    "a trusted network boundary (the error channel carries pickled typed "
    "exceptions)").string_conf("127.0.0.1")

ENDPOINT_PORT = conf("spark.rapids.tpu.endpoint.port").doc(
    "TCP port of the query endpoint; 0 picks an ephemeral port (exposed as "
    "QueryEndpoint.port)").integer_conf(0)

ENDPOINT_IDLE_TIMEOUT = conf("spark.rapids.tpu.endpoint.idleTimeoutSeconds").doc(
    "Per-connection blocking-I/O timeout on the query endpoint: a client "
    "that neither submits nor drains its result stream for this long is "
    "treated as disconnected — its in-flight query is cancelled and its "
    "connection closed (the keepalive window of the serving contract). "
    "<=0 disables").double_conf(300.0)

ENDPOINT_REQUEST_TIMEOUT = conf(
    "spark.rapids.tpu.endpoint.requestTimeoutSeconds").doc(
    "Wall-clock bound on one endpoint submission (queue wait + execution + "
    "result streaming); past it the query's CancelToken flips with reason "
    "request_timeout and the client receives the typed cancellation error. "
    "<=0 disables (per-query scheduler deadlines still apply)"
).double_conf(0.0)

ENDPOINT_DRAIN_GRACE = conf("spark.rapids.tpu.endpoint.drain.graceSeconds").doc(
    "Graceful-drain budget of QueryEndpoint.shutdown() (the SIGTERM path): "
    "new submissions are shed immediately with a retryable "
    "QueryRejectedError while in-flight queries get this long to finish; "
    "past it their CancelTokens flip (reason drain) — the hard-kill "
    "escalation — before the endpoint closes").double_conf(30.0)

ENDPOINT_STREAM_BUFFER = conf(
    "spark.rapids.tpu.endpoint.maxStreamBufferBytes").doc(
    "Byte bound on result batches buffered between a query's executor and "
    "its client connection (Arrow-IPC payload bytes); a slow client "
    "backpressures the producer instead of growing the heap. The effective "
    "budget also shrinks to the spill catalog's free host headroom "
    "(runtime/memory.host_prefetch_budget), sharing the prefetch budget "
    "with the scan readahead and pipeline queues").bytes_conf("64m")

SHUFFLE_CHECKSUM = conf("spark.rapids.tpu.shuffle.checksum.enabled").doc(
    "Stamp every serialized shuffle block with a CRC32C checksum in the "
    "transport metadata and verify on fetch; a mismatch is a fetch failure "
    "routed through the existing retry/failover/recompute ladder (Spark "
    "shuffle checksums, SPARK-35275 analog)").boolean_conf(True)

SPILL_CHECKSUM = conf("spark.rapids.tpu.memory.spill.checksum.enabled").doc(
    "Stamp disk-tier spill payloads with a CRC32C checksum and verify on "
    "unspill; a mismatch raises SpillCorruptionError, which shuffle readers "
    "treat as a fetch failure (map-stage recompute) instead of decoding "
    "silently corrupt rows").boolean_conf(True)

EVENT_LOG_DIR = conf("spark.rapids.tpu.eventLog.dir").doc(
    "Directory for the structured JSONL event log (query/stage/batch "
    "lifecycle, spill, OOM-retry/split, fetch retry/failover/recompute, "
    "heartbeat loss, executor health gauges — runtime/eventlog.py; the Spark "
    "event-log analog consumed by tools/profiler.py). Empty disables with "
    "near-zero overhead").string_conf(None)

EVENT_LOG_HEALTH_INTERVAL = conf(
    "spark.rapids.tpu.eventLog.healthSample.intervalSeconds").doc(
    "Period of the executor-health gauge sampler (HBM used/free + "
    "spill-catalog tier occupancy) written to the event log by the "
    "heartbeat/sampler thread; <=0 disables sampling. Only meaningful when "
    "eventLog.dir is set").double_conf(5.0)

EVENT_LOG_MAX_BYTES = conf("spark.rapids.tpu.eventLog.maxBytes").doc(
    "Size at which the event-log JSONL file rotates (events-*.jsonl -> "
    ".1 -> .2 ... keepFiles retained), so a long-lived serving session "
    "cannot grow one file without bound; 0 disables rotation").bytes_conf(0)

EVENT_LOG_KEEP_FILES = conf("spark.rapids.tpu.eventLog.keepFiles").doc(
    "Rotated event-log files retained per active file (the keep-N of the "
    "size-based rotation; older rotations are deleted). Only meaningful "
    "when eventLog.maxBytes > 0").integer_conf(4)

STATS_HISTORY_DIR = conf("spark.rapids.tpu.stats.history.dir").doc(
    "Directory of the on-disk plan-shape history store "
    "(runtime/history.py): per-fingerprint observed peak device bytes, "
    "cardinalities and shuffle skew, written at query end and read at "
    "submit so scheduler.estimate_footprint books HBM from observation "
    "instead of the static decode heuristic. Structural: process-global, "
    "applied only by a session that sets it explicitly. Empty disables"
).string_conf(None)

STATS_HISTORY_MAX_SHAPES = conf("spark.rapids.tpu.stats.history.maxShapes").doc(
    "Plan-shape fingerprints retained in the history store; beyond it the "
    "least-recently-updated shapes are evicted on write, bounding the file "
    "for long-lived serving sessions").integer_conf(256)

STATS_HISTORY_ENABLED = conf("spark.rapids.tpu.stats.history.enabled").doc(
    "Consult and update the plan-shape history store (when history.dir is "
    "set). false keeps the static footprint heuristic while the stats "
    "plane still captures per-node observations").boolean_conf(True)

TRACE_DIR = conf("spark.rapids.tpu.trace.dir").doc(
    "Directory for per-process JSONL span files (runtime/tracing.py): every "
    "trace_range/span region and span_event instant is appended with its "
    "wall-clock start, duration, pid/thread and the ambient query's trace "
    "id, which propagates across MiniCluster tasks, shuffle fetches and "
    "endpoint submissions. tools/profiler.py trace merges the files into "
    "Chrome-trace JSON (Perfetto) with a critical-path table. Empty "
    "disables with near-zero overhead").string_conf(None)

TRACE_ID_OVERRIDE = conf("spark.rapids.tpu.trace.id").doc(
    "Explicit trace id for this session's next queries (normally derived "
    "from the query id); clients submitting over the endpoint can instead "
    "set 'trace' per request. Empty derives per query").string_conf(None)

FLEET_DIR = conf("spark.rapids.tpu.fleet.dir").doc(
    "Shared fleet directory (runtime/fleet.py): every QueryEndpoint replica "
    "registers a lease-stamped membership record here (heartbeat-renewed, "
    "mtime-expired), so replicas and clients discover live peers and a "
    "survivor's sweeper can adopt a dead replica's lease plus its "
    "shared-store write intents. Must be on a filesystem visible to every "
    "replica. Empty disables fleet membership").string_conf(None)

FLEET_LEASE_TIMEOUT = conf("spark.rapids.tpu.fleet.lease.timeoutSeconds").doc(
    "Age past which a replica's membership lease (its record file's mtime) "
    "is considered expired: the replica stops being returned as a live "
    "member and any surviving replica's sweeper may adopt the lease — "
    "unlinking the record and reclaiming orphaned shared-store write "
    "intents. Must comfortably exceed fleet.heartbeat.intervalSeconds"
).double_conf(10.0)

FLEET_HEARTBEAT_INTERVAL = conf(
    "spark.rapids.tpu.fleet.heartbeat.intervalSeconds").doc(
    "Period of a registered replica's lease-renewal heartbeat (an mtime "
    "touch on its membership record); each beat also sweeps expired peer "
    "leases, so fleet adoption needs no dedicated coordinator. <=0 "
    "disables the heartbeat thread (the lease then expires unless renewed "
    "manually)").double_conf(2.0)

STREAM_WATERMARK_DELAY = conf(
    "spark.rapids.tpu.streaming.watermark.delaySeconds").doc(
    "Event-time lateness bound of a windowed streaming aggregation "
    "(streaming/coordinator.py): after each committed epoch the watermark "
    "advances to max(event time) - delay, window groups entirely below it "
    "are retired out of the incremental state (emitted once as finalized "
    "rows), and later-arriving rows for a retired window are dropped — "
    "this is what keeps state bytes bounded on an unbounded stream. <0 "
    "(the default) disables retirement (state grows with the key space)"
).double_conf(-1.0)

STREAM_MAX_BATCHES_PER_EPOCH = conf(
    "spark.rapids.tpu.streaming.maxBatchesPerEpoch").doc(
    "Cap on the input batches one micro-batch epoch consumes "
    "(streaming/coordinator.py): a backlogged source is drained over "
    "several epochs of bounded footprint instead of one giant admitted "
    "query. <=0 means unbounded (drain everything pending)"
).integer_conf(32)

STREAM_JOURNAL_HISTORY = conf(
    "spark.rapids.tpu.streaming.journal.maxCommits").doc(
    "Commit records retained in a stream's epoch journal for "
    "observability (profiler.py streaming); the exactly-once state itself "
    "(committed epoch, consumed batch ids, pending begin) is never "
    "truncated").integer_conf(256)

ENDPOINT_RESULT_CACHE_ENABLED = conf(
    "spark.rapids.tpu.endpoint.resultCache.enabled").doc(
    "Serve identical hot queries from an in-memory result cache on the "
    "endpoint: hits are keyed by (catalog epoch, parameterized plan "
    "signature, SQL text digest), stream the recorded Arrow-IPC frames "
    "bit-identically, bypass scheduler admission entirely, and are "
    "invalidated when the session catalog changes (any view "
    "registration)").boolean_conf(False)

ENDPOINT_RESULT_CACHE_MAX_BYTES = conf(
    "spark.rapids.tpu.endpoint.resultCache.maxBytes").doc(
    "Byte budget of the endpoint result cache (sum of cached Arrow-IPC "
    "payload bytes); least-recently-hit entries are evicted beyond it, and "
    "a single result larger than the budget is never admitted"
).bytes_conf("64m")

ENDPOINT_RESULT_CACHE_MAX_ENTRIES = conf(
    "spark.rapids.tpu.endpoint.resultCache.maxEntries").doc(
    "Entry-count bound on the endpoint result cache (bounds key/metadata "
    "overhead independently of maxBytes)").integer_conf(64)

ENDPOINT_STATS_ENABLED = conf("spark.rapids.tpu.endpoint.stats.enabled").doc(
    "Serve STATS frames on the query endpoint: a Prometheus-style text "
    "snapshot of live serving metrics — admission/shed/cancel/deadline "
    "counters, the resilience registry, HBM/spill-tier/queue-depth gauges "
    "and latency histograms per priority class (tools/tpu_client.py "
    "--stats)").boolean_conf(True)

ENDPOINT_STATS_HISTOGRAMS = conf(
    "spark.rapids.tpu.endpoint.stats.histograms.enabled").doc(
    "Include histogram families (query latency per priority class, "
    "admission queue wait) in STATS snapshots; counters and gauges are "
    "always served").boolean_conf(True)

ENDPOINT_SLO_LATENCY_TARGET = conf(
    "spark.rapids.tpu.endpoint.slo.latencyTargetSeconds").doc(
    "Per-query serving-latency objective of the endpoint's SLO accounting "
    "(runtime/endpoint.py): every served/cached submission whose wall time "
    "exceeds the target counts an slo.breach event and an srt_slo_total "
    "breach, and failed submissions count against availability; the "
    "per-replica SLO snapshot rides the fleet heartbeat's lease-record "
    "health summary so profiler.py fleet / fleet-stats can render a "
    "fleet-merged breach table. <=0 disables SLO accounting"
).double_conf(0.0)

FLIGHT_RECORDER_MAX_EVENTS = conf(
    "spark.rapids.tpu.flightRecorder.maxEvents").doc(
    "Bound of the black-box flight recorder's in-memory ring "
    "(runtime/blackbox.py): the most recent event-log records and tracing "
    "instants are retained per process at near-zero cost (a deque append, "
    "no I/O) and flushed to blackbox-<pid>.json on an unhandled endpoint "
    "error, a deadline/drain hard-kill, or a stuck-query detection from the "
    "fleet heartbeat — so a SIGKILLed replica leaves a record of what it "
    "was doing for the survivor that adopts its lease. 0 disables the "
    "ring; dumps land in eventLog.dir").integer_conf(512)

PROFILE_DIR = conf("spark.rapids.tpu.profile.dir").doc(
    "Directory for a whole-session XProf/Perfetto capture "
    "(jax.profiler.start_trace; the reference's Nsight workflow, "
    "docs/dev/nvtx_profiling.md); empty disables").string_conf(None)

OOM_DUMP_DIR = conf("spark.rapids.tpu.memory.hbm.oomDumpDir").doc(
    "Directory to write allocator state on device OOM "
    "(reference spark.rapids.memory.gpu.oomDumpDir)").string_conf(None)

MEMORY_WATERMARK_INTERVAL = conf(
    "spark.rapids.tpu.memory.profile.watermarkIntervalBytes").doc(
    "Granularity of the HBM watermark timeline: a memory.watermark event "
    "(+ Chrome counter-track sample when trace.dir is set) is emitted when "
    "any spill tier's occupancy or the device high-water mark moves by this "
    "many bytes since the last sample, bounding sample volume to "
    "O(peak/interval) rather than one per allocation. The allocation-site "
    "accounting itself is always on (a few dict updates under the catalog "
    "lock)").bytes_conf("16m")

MOVEMENT_ENABLED = conf("spark.rapids.tpu.movement.enabled").doc(
    "Meter every byte crossing a process/device boundary in the unified "
    "movement ledger (runtime/movement.py): shuffle send/recv per link "
    "class, disk spill I/O, host-device transfers, ICI collective "
    "estimates and endpoint egress. Feeds the query.end movement section, "
    "movement.sample events, srt_movement_bytes STATS gauges and the "
    "profiler's movement read-out. Off leaves only the raw per-node "
    "h2d/d2h meters").boolean_conf(True)

MOVEMENT_SAMPLE_INTERVAL = conf(
    "spark.rapids.tpu.movement.sample.intervalBytes").doc(
    "Granularity of movement.sample ledger snapshots (+ Chrome "
    "counter-track samples when trace.dir is set): a cumulative snapshot "
    "is emitted when the process has moved this many more bytes since the "
    "last sample, bounding event volume to O(moved/interval) rather than "
    "one per transfer. Forced flushes at query end and executor task "
    "completion always happen regardless").bytes_conf("32m")

MEMORY_PROFILE_TOPK = conf("spark.rapids.tpu.memory.profile.topK").doc(
    "Allocation sites listed per watermark sample, per-query memory "
    "summary and STATS gauge family (sites beyond the top K by bytes are "
    "dropped from the EVENT payloads only — session.heap_snapshot() and "
    "the leak detector always see every site)").integer_conf(10)

MEMORY_LEAK_CHECK = conf("spark.rapids.tpu.memory.leak.check").doc(
    "End-of-query leak detection: after an action drains, any non-retained "
    "catalog buffer still tagged to the finished query raises a "
    "memory.leak event + memoryLeakedBuffers resilience counter with the "
    "per-site breakdown, and the buffers are reclaimed. false disables "
    "(the buffers then linger until process exit)").boolean_conf(True)

MEMORY_LEAK_STRICT = conf("spark.rapids.tpu.memory.leak.strict").doc(
    "Escalate a detected end-of-query leak into a MemoryLeakError after "
    "the event/counter/reclaim, so test suites fail loudly on any leak "
    "instead of logging it (chaos specs use the 'leak' fault kind to prove "
    "the detector end to end)").boolean_conf(False)

SPARK_VERSION = conf("spark.rapids.tpu.spark.version").doc(
    "Spark behavior generation to emulate; selects the semantic shim "
    "(reference ShimLoader picks a per-release shim jar the same way). "
    "A -<platform> suffix (3.0.1-databricks, 3.0.1-emr) selects that "
    "platform's shim variant (reference spark301db/spark301emr/spark310db)"
).string_conf("3.5.0")

PARQUET_DEVICE_DECODE = conf(
    "spark.rapids.tpu.sql.parquet.deviceDecode.enabled").doc(
    "Decode dictionary-encoded uncompressed parquet chunks on device "
    "(bit-unpack + gather in one jitted program, ops/parquet_decode.py); "
    "out-of-scope chunks fall back to arrow per column (reference "
    "GpuParquetScan device decode, stage one)").boolean_conf(True)

PARQUET_ENCODED_UPLOAD = conf(
    "spark.rapids.tpu.sql.parquet.encodedUpload.enabled").doc(
    "Upload in-scope parquet data pages ENCODED — bit-packed dictionary "
    "indices, definition levels and the dictionary itself — and expand to "
    "dense columns lazily on device inside the first consuming kernel, so "
    "H2D carries encoded bytes instead of dense columns (movement-ledger "
    "h2d site scan.encoded). Out-of-scope pages upload dense; requires "
    "parquet.deviceDecode.enabled").boolean_conf(True)

PARQUET_REBASE_MODE = conf(
    "spark.rapids.tpu.sql.parquet.datetimeRebaseModeInRead").doc(
    "EXCEPTION | CORRECTED | LEGACY for dates before 1582-10-15 in parquet "
    "files (Spark spark.sql.parquet.datetimeRebaseModeInRead; LEGACY applies "
    "the Julian->proleptic-Gregorian rebase, shims.rebase_julian_to_gregorian_days)"
).string_conf("EXCEPTION")

ALLUXIO_PATHS_REPLACE = conf(
    "spark.rapids.tpu.alluxio.pathsToReplace").doc(
    "List of 'scheme://from->scheme://to' path-prefix rewrites applied to "
    "every file scan, so cached-filesystem mounts transparently replace "
    "direct storage paths (reference spark.rapids.alluxio.pathsToReplace, "
    "RapidsConf.scala:1031); ';'-separated").string_conf(None)


class RapidsConf:
    """Resolved view over user settings (reference RapidsConf.scala:1162 class)."""

    def __init__(self, settings: dict | None = None):
        self.settings = dict(settings or {})
        unknown = [k for k in self.settings
                   if k.startswith("spark.rapids.tpu.") and k not in _REGISTERED]
        if unknown:
            raise ValueError(f"unknown spark.rapids.tpu confs: {unknown}")

    def get(self, entry: ConfEntry):
        return entry.get(self.settings)

    # convenience typed properties used throughout the engine
    @property
    def is_sql_enabled(self):
        return self.get(SQL_ENABLED)

    @property
    def explain(self):
        return self.get(EXPLAIN).upper()

    @property
    def batch_size_bytes(self):
        return self.get(BATCH_SIZE_BYTES)

    @property
    def concurrent_tpu_tasks(self):
        return self.get(CONCURRENT_TPU_TASKS)

    @property
    def metrics_level(self):
        return self.get(METRICS_LEVEL).upper()

    @property
    def is_test_enabled(self):
        return self.get(TEST_ENABLED)

    @property
    def allowed_non_tpu(self):
        v = self.get(TEST_ALLOWED_NON_TPU)
        return set(x.strip() for x in v.split(",") if x.strip())

    @property
    def is_cpu_fallback_enabled(self):
        return self.get(CPU_FALLBACK_ENABLED)

    @property
    def stage_fusion_enabled(self):
        return self.get(ENABLE_WHOLE_STAGE_FUSION)

    @property
    def scan_fusion_enabled(self):
        return (self.get(ENABLE_SCAN_FUSION)
                and self.get(ENABLE_WHOLE_STAGE_FUSION))

    @property
    def groupby_chain_enabled(self):
        return (self.get(ENABLE_GROUPBY_CHAIN)
                and self.get(ENABLE_WHOLE_STAGE_FUSION))

    @property
    def stage_cache_enabled(self):
        return self.get(STAGE_CACHE_ENABLED)

    @property
    def stage_cache_dir(self):
        return self.get(STAGE_CACHE_DIR)

    @property
    def stage_cache_max_bytes(self):
        return self.get(STAGE_CACHE_MAX_BYTES)

    def copy_with(self, **kv):
        s = dict(self.settings)
        s.update(kv)
        return RapidsConf(s)


def all_entries():
    return dict(_REGISTERED)


def generate_docs() -> str:
    """Markdown doc table (reference RapidsConf.scala:1259 main → docs/configs.md)."""
    lines = [
        "# spark_rapids_tpu configuration",
        "",
        "Generated by `python -m spark_rapids_tpu.config`. "
        "Mirrors the reference's docs/configs.md generator (RapidsConf.scala:1259).",
        "",
        "| Name | Default | Description |",
        "|---|---|---|",
    ]
    for key in sorted(_REGISTERED):
        e = _REGISTERED[key]
        if e.internal:
            continue
        lines.append(f"| {e.key} | {e.default} | {e.doc} |")
    return "\n".join(lines) + "\n"


if __name__ == "__main__":
    import pathlib
    out = pathlib.Path(__file__).resolve().parent.parent / "docs" / "configs.md"
    out.parent.mkdir(exist_ok=True)
    out.write_text(generate_docs())
    print(f"wrote {out}")
