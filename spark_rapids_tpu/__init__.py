"""spark_rapids_tpu — a TPU-native accelerator for Spark-style columnar SQL execution.

A brand-new framework with the capabilities of the RAPIDS Accelerator for Apache Spark
(reference: /root/reference, NVIDIA spark-rapids v0.6.0-SNAPSHOT), re-designed TPU-first:

- Columnar kernels are jax.jit'd XLA programs (+ Pallas for irregular ops) instead of
  libcudf CUDA kernels (reference L0, SURVEY.md §1).
- Device batches are padded JAX arrays with validity masks; row counts are device scalars
  so one compiled kernel serves a whole bucket of batch sizes (XLA static-shape regime).
- Memory runtime is an HBM budget + tiered spill (device→host→disk) in place of RMM
  (reference GpuDeviceManager.scala / RapidsBufferCatalog.scala).
- The shuffle data plane is ICI collectives (all_to_all under shard_map) intra-slice with
  a host/TCP transport fallback, in place of UCX RDMA (reference shuffle-plugin).
- Whole-stage fusion: pipelines of project/filter/aggregate are traced into ONE XLA
  program per stage, which beats the reference's per-op kernel-launch model on TPU.

Layout mirrors the reference's layer map (SURVEY.md §1):
  config.py            — RapidsConf analog (reference RapidsConf.scala)
  types.py             — Spark SQL type system
  columnar/            — L2 columnar batch representation (GpuColumnVector.java analog)
  ops/                 — L0 kernel library (libcudf analog, jax/XLA/Pallas)
  plan/                — L3 planner/override layer (GpuOverrides/RapidsMeta/TypeChecks)
  exec/                — L4 physical operators (GpuExec layer)
  io/                  — L5 Parquet/ORC/CSV readers+writers
  shuffle/             — L6 partitioning, shuffle manager, transports
  runtime/             — L1 device & memory runtime (semaphore, spill, metrics, tracing)
  udf/                 — L7 UDF compiler + pandas UDF runtime
  ml/                  — L7 zero-copy ML export (ColumnarRdd analog)
"""

import jax as _jax

# Spark semantics require LongType/DoubleType (64-bit). Verified supported on TPU v5e.
_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from spark_rapids_tpu.config import RapidsConf  # noqa: E402,F401
from spark_rapids_tpu.types import (  # noqa: E402,F401
    BooleanType, ByteType, ShortType, IntegerType, LongType, FloatType, DoubleType,
    StringType, DateType, TimestampType, DecimalType, NullType, DataType,
)
