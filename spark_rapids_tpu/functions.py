"""Column-function builders — the pyspark.sql.functions facade.

Reference analogy: users of the reference write pyspark `F.*` expressions and the
plugin maps them to Gpu* implementations (GpuOverrides expression rules). Here
the same surface builds this engine's expression tree directly."""

from __future__ import annotations

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import arithmetic as _A
from spark_rapids_tpu.expr import conditional as _C
from spark_rapids_tpu.expr import datetime as _DT
from spark_rapids_tpu.expr import mathexprs as _M
from spark_rapids_tpu.expr import nullexprs as _N
from spark_rapids_tpu.expr import predicates as _P
from spark_rapids_tpu.expr import strings as _S
from spark_rapids_tpu.expr import aggregates as _AG
from spark_rapids_tpu.expr import windows as _W
from spark_rapids_tpu.expr.cast import Cast
from spark_rapids_tpu.expr.core import Alias, Expression, col, lit  # noqa: F401


def _e(c):
    from spark_rapids_tpu.session import _to_expr
    return _to_expr(c)


# aggregates
def sum(c):  # noqa: A001
    return _AG.Sum(_e(c))


def count(c=None):
    return _AG.Count(None if c is None else _e(c))


def min(c):  # noqa: A001
    return _AG.Min(_e(c))


def max(c):  # noqa: A001
    return _AG.Max(_e(c))


def avg(c):
    return _AG.Average(_e(c))


mean = avg


def first(c, ignore_nulls: bool = False):
    return _AG.First(_e(c), ignore_nulls)


# null / conditional
def coalesce(*cs):
    return _N.Coalesce(*[_e(c) for c in cs])


def isnull(c):
    return _N.IsNull(_e(c))


def isnan(c):
    return _N.IsNaN(_e(c))


def _v(value):
    """Value position: non-expressions are literals (pyspark convention — only
    the first argument of col-flavored helpers treats strings as columns)."""
    from spark_rapids_tpu.expr.core import _auto_lit
    return value if isinstance(value, Expression) else _auto_lit(value)


def when(cond, value):
    return _C.CaseWhen([(_e(cond), _v(value))])


def if_(cond, a, b):
    return _C.If(_e(cond), _v(a), _v(b))


def cast(c, to: T.DataType):
    return Cast(_e(c), to)


# strings
def upper(c):
    return _S.Upper(_e(c))


def lower(c):
    return _S.Lower(_e(c))


def length(c):
    return _S.Length(_e(c))


def trim(c):
    return _S.Trim(_e(c))


def substring(c, pos, length_):
    return _S.Substring(_e(c), _e(pos), _e(length_))


def concat(*cs):
    return _S.Concat(*[_e(c) for c in cs])


def like(c, pattern: str):
    return _S.Like(_e(c), lit(pattern))


# math
def sqrt(c):
    return _M.Sqrt(_e(c))


def pow(a, b):  # noqa: A001
    return _M.Pow(_e(a), _e(b))


def round(c, scale: int = 0):  # noqa: A001
    return _M.Round(_e(c), scale)


def floor(c):
    return _M.Floor(_e(c))


def ceil(c):
    return _M.Ceil(_e(c))


def abs(c):  # noqa: A001
    return _A.Abs(_e(c))


def pmod(a, b):
    return _A.Pmod(_e(a), _e(b))


# datetime
def year(c):
    return _DT.Year(_e(c))


def month(c):
    return _DT.Month(_e(c))


def dayofmonth(c):
    return _DT.DayOfMonth(_e(c))


# windows
def row_number():
    return _W.RowNumber()


def rank():
    return _W.Rank()


def dense_rank():
    return _W.DenseRank()


def lead(c, offset: int = 1, default=None):
    return _W.Lead(_e(c), offset, default)


def lag(c, offset: int = 1, default=None):
    return _W.Lag(_e(c), offset, default)


def over(func, partition_by=(), order_by=(), frame=None):
    """Build func OVER (PARTITION BY ... ORDER BY ...). order_by items are
    expressions (asc, nulls-first) or (expr, ascending, nulls_first) tuples."""
    orders = []
    for o in order_by:
        if isinstance(o, tuple):
            e, asc, nf = o
            orders.append((_e(e), asc, nf))
        else:
            orders.append((_e(o), True, True))
    spec = _W.WindowSpec(tuple(_e(p) for p in partition_by), tuple(orders),
                         frame or _W.DEFAULT_FRAME)
    return _W.WindowExpression(func, spec)


def alias(e, name: str):
    return Alias(_e(e), name)


# -- round-2 surface ---------------------------------------------------------

def last(c, ignore_nulls: bool = False):
    return _AG.Last(_e(c), ignore_nulls)


def stddev(c):
    return _AG.StddevSamp(_e(c))


stddev_samp = stddev


def stddev_pop(c):
    return _AG.StddevPop(_e(c))


def variance(c):
    return _AG.VarianceSamp(_e(c))


var_samp = variance


def var_pop(c):
    return _AG.VariancePop(_e(c))


def bitwise_not(c):
    return _A.BitwiseNot(_e(c))


def shiftleft(c, n):
    return _A.ShiftLeft(_e(c), _v(n))


def shiftright(c, n):
    return _A.ShiftRight(_e(c), _v(n))


def shiftrightunsigned(c, n):
    return _A.ShiftRightUnsigned(_e(c), _v(n))


def least(*cs):
    return _C.Least(*[_e(c) for c in cs])


def greatest(*cs):
    return _C.Greatest(*[_e(c) for c in cs])


def concat_ws(sep: str, *cs):
    return _S.ConcatWs(_v(sep), *[_e(c) for c in cs])


def lpad(c, ln: int, pad: str = " "):
    return _S.StringLPad(_e(c), _v(ln), _v(pad))


def rpad(c, ln: int, pad: str = " "):
    return _S.StringRPad(_e(c), _v(ln), _v(pad))


def repeat(c, n: int):
    return _S.StringRepeat(_e(c), _v(n))


def locate(substr: str, c, pos: int = 1):
    return _S.StringLocate(_v(substr), _e(c), _v(pos))


def instr(c, substr: str):
    return _S.StringLocate(_v(substr), _e(c), _v(1))


def substring_index(c, delim: str, count: int):
    return _S.SubstringIndex(_e(c), _v(delim), _v(count))


def translate(c, frm: str, to: str):
    return _S.StringTranslate(_e(c), _v(frm), _v(to))


def find_in_set(c, str_list: str):
    return _S.FindInSet(_e(c), _v(str_list))


def regexp_replace(c, pattern: str, replacement: str):
    return _S.RegExpReplace(_e(c), _v(pattern), _v(replacement))


def regexp_extract(c, pattern: str, idx: int = 1):
    return _S.RegExpExtract(_e(c), _v(pattern), _v(idx))


def unix_timestamp(c, fmt: str | None = None):
    return _DT.UnixTimestamp(_e(c), _v(fmt) if fmt is not None else None)


def to_unix_timestamp(c, fmt: str | None = None):
    return _DT.ToUnixTimestamp(_e(c), _v(fmt) if fmt is not None else None)


def from_unixtime(c, fmt: str | None = None):
    return _DT.FromUnixTime(_e(c), _v(fmt) if fmt is not None else None)


def date_format(c, fmt: str):
    return _DT.DateFormatClass(_e(c), _v(fmt))


def date_sub(c, days: int):
    return _DT.DateSub(_e(c), _v(days))


def add_months(c, n):
    return _DT.AddMonths(_e(c), _v(n))


def months_between(end, start, round_off: bool = True):
    return _DT.MonthsBetween(_e(end), _e(start), round_off)


def trunc(c, fmt: str):
    return _DT.TruncDate(_e(c), _v(fmt))


def hash(*cs):  # noqa: A001
    from spark_rapids_tpu.expr.misc import Murmur3Hash
    return Murmur3Hash(*[_e(c) for c in cs])


def rand(seed: int = 0):
    from spark_rapids_tpu.expr.misc import Rand
    return Rand(seed)


def spark_partition_id():
    from spark_rapids_tpu.expr.misc import SparkPartitionID
    return SparkPartitionID()


def monotonically_increasing_id():
    from spark_rapids_tpu.expr.misc import MonotonicallyIncreasingID
    return MonotonicallyIncreasingID()


def struct(*name_value_pairs):
    """named_struct('a', col, 'b', col) — alternating names and values."""
    from spark_rapids_tpu.expr.complexexprs import CreateNamedStruct
    return CreateNamedStruct(*[
        _v(x) if i % 2 == 0 else _e(x)
        for i, x in enumerate(name_value_pairs)])


def get_field(struct_expr, name: str):
    from spark_rapids_tpu.expr.complexexprs import GetStructField
    return GetStructField(_e(struct_expr), name)


def array(*cs):
    from spark_rapids_tpu.expr.complexexprs import CreateArray
    return CreateArray(*[_e(c) for c in cs])


def element_at0(arr, idx):
    """0-based array element (Spark's GetArrayItem; element_at is 1-based)."""
    from spark_rapids_tpu.expr.complexexprs import GetArrayItem
    return GetArrayItem(_e(arr), _e(idx) if isinstance(idx, Expression) else _v(idx))


def size(c):
    from spark_rapids_tpu.expr.complexexprs import Size
    return Size(_e(c))


def sinh(c):
    return _M.Sinh(_e(c))


def cosh(c):
    return _M.Cosh(_e(c))


def tanh(c):
    return _M.Tanh(_e(c))


def expm1(c):
    return _M.Expm1(_e(c))


def rint(c):
    return _M.Rint(_e(c))


def jax_udf(fn, return_type: T.DataType, null_aware: bool = False):
    """Accelerated user UDF (reference RapidsUDF.evaluateColumnar analog):
    `F.jax_udf(lambda v: v * 2 + 1, T.DOUBLE)(F.col("x"))` runs fused inside
    the device program, and composes anywhere an expression can appear
    (projections, filters, aggregate inputs, join conditions)."""
    from spark_rapids_tpu.udf.device_udf import jax_udf as _ju
    return _ju(fn, return_type, null_aware)


def pandas_agg_udf(fn, return_type: T.DataType):
    """GROUPED_AGG pandas UDF (reference GpuAggregateInPandasExec):
    `F.pandas_agg_udf(lambda s: s.max() - s.min(), T.DOUBLE)("v")` inside
    `df.group_by(k).agg(...)`. Arguments are column NAMES; fn receives one
    pandas Series per column and returns a scalar per group."""
    from spark_rapids_tpu.udf.pandas_exec import PandasAggUDF

    def make(*cols):
        names = []
        for c in cols:
            if not isinstance(c, str):
                raise TypeError(
                    "pandas_agg_udf arguments must be column names")
            names.append(c)
        return PandasAggUDF(fn, return_type, names)
    return make


def md5(c):
    return _S.Md5(_e(c))


def cot(c):
    return _M.Cot(_e(c))


def log(base, c=None):
    """log(x) natural, or log(base, x) (pyspark convention)."""
    if c is None:
        return _M.Log(_e(base))
    return _M.Logarithm(_v(base), _e(c))


def element_at(arr, i):
    from spark_rapids_tpu.expr.complexexprs import ElementAt
    return ElementAt(_e(arr), _v(i))


def array_contains(arr, value):
    from spark_rapids_tpu.expr.complexexprs import ArrayContains
    return ArrayContains(_e(arr), _v(value))


def bround(c, scale: int = 0):
    from spark_rapids_tpu.expr.mathexprs import BRound
    return BRound(_e(c), scale)


def split(c, pattern: str, limit: int = -1):
    from spark_rapids_tpu.expr.core import Literal
    from spark_rapids_tpu.expr.strings import StringSplit
    return StringSplit(_e(c), Literal(pattern),
                       Literal(limit) if limit != -1 else None)


def isin(c, values):
    from spark_rapids_tpu.expr.predicates import InSet
    return InSet(_e(c), list(values))


def time_add(ts, interval_us):
    from spark_rapids_tpu.expr.datetime import TimeAdd
    return TimeAdd(_e(ts), _e(interval_us))


def date_add_interval(d, days):
    from spark_rapids_tpu.expr.datetime import DateAddInterval
    return DateAddInterval(_e(d), _e(days))


def collect_list(c):
    from spark_rapids_tpu.expr.aggregates import CollectList
    return CollectList(_e(c))


def collect_set(c):
    from spark_rapids_tpu.expr.aggregates import CollectSet
    return CollectSet(_e(c))


def get_json_object(c, path: str):
    from spark_rapids_tpu.expr.core import Literal
    from spark_rapids_tpu.expr.strings import GetJsonObject
    return GetJsonObject(_e(c), Literal(path))


def input_file_name():
    from spark_rapids_tpu.expr.misc import InputFileName
    return InputFileName()


def input_file_block_start():
    from spark_rapids_tpu.expr.misc import InputFileBlockStart
    return InputFileBlockStart()


def input_file_block_length():
    from spark_rapids_tpu.expr.misc import InputFileBlockLength
    return InputFileBlockLength()


def scalar_subquery(df):
    """Evaluate a 1-column DataFrame eagerly as a scalar expression (Spark
    executes subquery stages first; same contract)."""
    from spark_rapids_tpu.expr.misc import ScalarSubquery
    return ScalarSubquery.from_dataframe(df)


def create_map(*kvs):
    from spark_rapids_tpu.expr.complexexprs import CreateMap
    return CreateMap(*[_e(x) for x in kvs])


def map_value(m, key):
    from spark_rapids_tpu.expr.complexexprs import GetMapValue
    return GetMapValue(_e(m), _e(key))
