"""Column-function builders — the pyspark.sql.functions facade.

Reference analogy: users of the reference write pyspark `F.*` expressions and the
plugin maps them to Gpu* implementations (GpuOverrides expression rules). Here
the same surface builds this engine's expression tree directly."""

from __future__ import annotations

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import arithmetic as _A
from spark_rapids_tpu.expr import conditional as _C
from spark_rapids_tpu.expr import datetime as _DT
from spark_rapids_tpu.expr import mathexprs as _M
from spark_rapids_tpu.expr import nullexprs as _N
from spark_rapids_tpu.expr import predicates as _P
from spark_rapids_tpu.expr import strings as _S
from spark_rapids_tpu.expr import aggregates as _AG
from spark_rapids_tpu.expr import windows as _W
from spark_rapids_tpu.expr.cast import Cast
from spark_rapids_tpu.expr.core import Alias, Expression, col, lit  # noqa: F401


def _e(c):
    from spark_rapids_tpu.session import _to_expr
    return _to_expr(c)


# aggregates
def sum(c):  # noqa: A001
    return _AG.Sum(_e(c))


def count(c=None):
    return _AG.Count(None if c is None else _e(c))


def min(c):  # noqa: A001
    return _AG.Min(_e(c))


def max(c):  # noqa: A001
    return _AG.Max(_e(c))


def avg(c):
    return _AG.Average(_e(c))


mean = avg


def first(c, ignore_nulls: bool = False):
    return _AG.First(_e(c), ignore_nulls)


# null / conditional
def coalesce(*cs):
    return _N.Coalesce([_e(c) for c in cs])


def isnull(c):
    return _N.IsNull(_e(c))


def isnan(c):
    return _N.IsNaN(_e(c))


def _v(value):
    """Value position: non-expressions are literals (pyspark convention — only
    the first argument of col-flavored helpers treats strings as columns)."""
    from spark_rapids_tpu.expr.core import _auto_lit
    return value if isinstance(value, Expression) else _auto_lit(value)


def when(cond, value):
    return _C.CaseWhen([(_e(cond), _v(value))])


def if_(cond, a, b):
    return _C.If(_e(cond), _v(a), _v(b))


def cast(c, to: T.DataType):
    return Cast(_e(c), to)


# strings
def upper(c):
    return _S.Upper(_e(c))


def lower(c):
    return _S.Lower(_e(c))


def length(c):
    return _S.Length(_e(c))


def trim(c):
    return _S.Trim(_e(c))


def substring(c, pos, length_):
    return _S.Substring(_e(c), _e(pos), _e(length_))


def concat(*cs):
    return _S.Concat([_e(c) for c in cs])


def like(c, pattern: str):
    return _S.Like(_e(c), lit(pattern))


# math
def sqrt(c):
    return _M.Sqrt(_e(c))


def pow(a, b):  # noqa: A001
    return _M.Pow(_e(a), _e(b))


def round(c, scale: int = 0):  # noqa: A001
    return _M.Round(_e(c), scale)


def floor(c):
    return _M.Floor(_e(c))


def ceil(c):
    return _M.Ceil(_e(c))


def abs(c):  # noqa: A001
    return _A.Abs(_e(c))


def pmod(a, b):
    return _A.Pmod(_e(a), _e(b))


# datetime
def year(c):
    return _DT.Year(_e(c))


def month(c):
    return _DT.Month(_e(c))


def dayofmonth(c):
    return _DT.DayOfMonth(_e(c))


# windows
def row_number():
    return _W.RowNumber()


def rank():
    return _W.Rank()


def dense_rank():
    return _W.DenseRank()


def lead(c, offset: int = 1, default=None):
    return _W.Lead(_e(c), offset, default)


def lag(c, offset: int = 1, default=None):
    return _W.Lag(_e(c), offset, default)


def over(func, partition_by=(), order_by=(), frame=None):
    """Build func OVER (PARTITION BY ... ORDER BY ...). order_by items are
    expressions (asc, nulls-first) or (expr, ascending, nulls_first) tuples."""
    orders = []
    for o in order_by:
        if isinstance(o, tuple):
            e, asc, nf = o
            orders.append((_e(e), asc, nf))
        else:
            orders.append((_e(o), True, True))
    spec = _W.WindowSpec(tuple(_e(p) for p in partition_by), tuple(orders),
                         frame or _W.DEFAULT_FRAME)
    return _W.WindowExpression(func, spec)


def alias(e, name: str):
    return Alias(_e(e), name)
