"""String expressions (reference stringFunctions.scala: GpuUpper, GpuLower,
GpuStringLocate, GpuSubstring, GpuStartsWith, GpuEndsWith, GpuContains, GpuLike,
GpuConcat, GpuStringTrim…). All are dictionary transforms — see ops/strings.py."""

from __future__ import annotations

import re

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression, Literal
from spark_rapids_tpu.ops import strings as S


class _UnaryString(Expression):
    out_dtype = T.STRING

    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return self.out_dtype

    def with_children(self, children):
        return type(self)(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        if self.out_dtype == T.STRING:
            return S.dict_transform_to_string(c, self.fn)
        return S.dict_transform_to_values(c, self.fn, self.out_dtype)

    def fn(self, s):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r})"


class Upper(_UnaryString):
    def fn(self, s):
        return s.upper()


class Lower(_UnaryString):
    def fn(self, s):
        return s.lower()


class Length(_UnaryString):
    out_dtype = T.INT

    def fn(self, s):
        return S.java_length(s)


class Trim(_UnaryString):
    def fn(self, s):
        return s.strip(" ")


class LTrim(_UnaryString):
    def fn(self, s):
        return s.lstrip(" ")


class RTrim(_UnaryString):
    def fn(self, s):
        return s.rstrip(" ")


class Reverse(_UnaryString):
    def fn(self, s):
        return s[::-1]


class InitCap(_UnaryString):
    def fn(self, s):
        return " ".join(w[:1].upper() + w[1:].lower() if w else w
                        for w in s.split(" "))


class Substring(Expression):
    """substring(str, pos[, len]) — Spark 1-based indexing, negative pos from end."""

    def __init__(self, child, pos: Expression, length: Expression | None = None):
        self.children = [child, pos] + ([length] if length is not None else [])

    @property
    def dtype(self):
        return T.STRING

    def with_children(self, children):
        return Substring(children[0], children[1],
                         children[2] if len(children) > 2 else None)

    def eval(self, ctx):
        pos = self.children[1]
        length = self.children[2] if len(self.children) > 2 else None
        assert isinstance(pos, Literal) and (length is None or isinstance(length, Literal)), \
            "substring pos/len must be literals (reference has the same GPU limitation)"
        p = pos.value
        ln = length.value if length is not None else None
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_string(c, lambda s: S.java_substring(s, p, ln))

    def __repr__(self):
        return f"substring({self.children[0]!r})"


class _StringPredicate(Expression):
    def __init__(self, child, pattern: Expression):
        self.children = [child, pattern]

    @property
    def dtype(self):
        return T.BOOLEAN

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def eval(self, ctx):
        pat = self.children[1]
        assert isinstance(pat, Literal), \
            "pattern must be a literal (reference GpuStartsWith has the same limit)"
        p = pat.value
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_values(c, lambda s: self.test(s, p), T.BOOLEAN)

    def test(self, s, p):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r}, {self.children[1]!r})"


class StartsWith(_StringPredicate):
    def test(self, s, p):
        return s.startswith(p)


class EndsWith(_StringPredicate):
    def test(self, s, p):
        return s.endswith(p)


class Contains(_StringPredicate):
    def test(self, s, p):
        return p in s


class Like(_StringPredicate):
    def eval(self, ctx):
        pat = self.children[1]
        assert isinstance(pat, Literal)
        rx = re.compile(S.like_to_regex(pat.value))
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_values(
            c, lambda s: rx.match(s) is not None, T.BOOLEAN)


class RLike(_StringPredicate):
    def eval(self, ctx):
        pat = self.children[1]
        assert isinstance(pat, Literal)
        rx = re.compile(pat.value)
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_values(
            c, lambda s: rx.search(s) is not None, T.BOOLEAN)


class Concat(Expression):
    """concat of string columns/literals; null if any input null (Spark concat)."""

    def __init__(self, *children):
        self.children = list(children)

    @property
    def dtype(self):
        return T.STRING

    def with_children(self, children):
        return Concat(*children)

    def eval(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        out = cols[0]
        for c in cols[1:]:
            out = S.concat_cols(out, c)
        return out

    def __repr__(self):
        return f"concat({', '.join(map(repr, self.children))})"


class StringReplace(Expression):
    def __init__(self, child, search: Expression, replace: Expression):
        self.children = [child, search, replace]

    @property
    def dtype(self):
        return T.STRING

    def with_children(self, children):
        return StringReplace(children[0], children[1], children[2])

    def eval(self, ctx):
        se, re_ = self.children[1], self.children[2]
        assert isinstance(se, Literal) and isinstance(re_, Literal)
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_string(c, lambda s: s.replace(se.value, re_.value))


class ConcatWs(Expression):
    """concat_ws(sep, ...) — nulls are SKIPPED (unlike concat); never null when
    the separator is a non-null literal (Spark semantics; reference
    stringFunctions.scala GpuConcatWs)."""

    def __init__(self, sep: Expression, *children):
        self.children = [sep] + list(children)

    @property
    def dtype(self):
        return T.STRING

    def with_children(self, children):
        return ConcatWs(children[0], *children[1:])

    def eval(self, ctx):
        import jax.numpy as jnp
        sep = self.children[0]
        assert isinstance(sep, Literal) and sep.value is not None, \
            "concat_ws separator must be a non-null literal"
        sep_col = Literal(sep.value, T.STRING).eval(ctx)
        acc = Literal("", T.STRING).eval(ctx)
        started = Col(jnp.zeros((ctx.capacity,), jnp.bool_),
                      jnp.ones((ctx.capacity,), jnp.bool_), T.BOOLEAN)
        for ch in self.children[1:]:
            c = ch.eval(ctx)
            joined = S.concat_cols(S.concat_cols(acc, sep_col), c)
            valid_c = Col(c.validity, jnp.ones_like(c.validity), T.BOOLEAN)
            use_joined = Col(c.validity & started.values,
                             jnp.ones_like(c.validity), T.BOOLEAN)
            # null c -> keep acc; first non-null -> c; else acc+sep+c
            step = S.if_strings(use_joined, joined,
                                S.if_strings(valid_c, c, acc))
            # keep the accumulator non-null (skip-null semantics)
            acc = Col(step.values, jnp.ones_like(step.validity), T.STRING,
                      step.dictionary)
            started = Col(started.values | c.validity,
                          started.validity, T.BOOLEAN)
        return acc

    def __repr__(self):
        return f"concat_ws({', '.join(map(repr, self.children))})"


class _LiteralArgsStringFn(Expression):
    """str column + literal args → dictionary transform."""

    out_dtype = T.STRING

    def __init__(self, child, *lits):
        self.children = [child] + list(lits)

    @property
    def dtype(self):
        return self.out_dtype

    def with_children(self, children):
        return type(self)(*children)

    def _lit_args(self):
        vals = []
        for e in self.children[1:]:
            assert isinstance(e, Literal) and e.value is not None, \
                f"{type(self).__name__} arguments must be non-null literals"
            vals.append(e.value)
        return vals

    def eval(self, ctx):
        args = self._lit_args()
        c = self.children[0].eval(ctx)
        if isinstance(self.out_dtype, T.StringType):
            return S.dict_transform_to_string(c, lambda s: self.fn(s, *args))
        return S.dict_transform_to_values(c, lambda s: self.fn(s, *args),
                                          self.out_dtype)

    def fn(self, s, *args):
        raise NotImplementedError

    def __repr__(self):
        return (f"{type(self).__name__.lower()}"
                f"({', '.join(map(repr, self.children))})")


class StringLPad(_LiteralArgsStringFn):
    """lpad(str, len, pad): pad to len (truncate if longer), Spark semantics."""

    def fn(self, s, ln, pad):
        if len(s) >= ln:
            return s[:ln]
        if not pad:
            return s
        need = ln - len(s)
        return ((pad * need)[:need]) + s


class StringRPad(_LiteralArgsStringFn):
    def fn(self, s, ln, pad):
        if len(s) >= ln:
            return s[:ln]
        if not pad:
            return s
        need = ln - len(s)
        return s + (pad * need)[:need]


class StringRepeat(_LiteralArgsStringFn):
    def fn(self, s, n):
        return s * max(int(n), 0)


class StringLocate(Expression):
    """locate(substr, str[, start]) — 1-based, 0 when absent (GpuStringLocate)."""

    out_dtype = T.INT

    def __init__(self, substr, child, start=None):
        from spark_rapids_tpu.expr.core import Literal as L
        self.children = [substr, child, start if start is not None else L(1, T.INT)]

    @property
    def dtype(self):
        return T.INT

    def with_children(self, children):
        return StringLocate(children[0], children[1], children[2])

    def eval(self, ctx):
        sub, start = self.children[0], self.children[2]
        assert isinstance(sub, Literal) and isinstance(start, Literal), \
            "locate substr/start must be literals"
        p, st = sub.value, start.value
        c = self.children[1].eval(ctx)

        def locate(s):
            if st is None or p is None:
                return None
            if st <= 0:
                return 0
            return s.find(p, st - 1) + 1
        return S.dict_transform_to_values(c, locate, T.INT)

    def __repr__(self):
        return f"locate({self.children[0]!r}, {self.children[1]!r})"


class SubstringIndex(_LiteralArgsStringFn):
    """substring_index(str, delim, count) — Spark/Hive semantics."""

    def fn(self, s, delim, count):
        if not delim or count == 0:
            return ""
        parts = s.split(delim)
        if count > 0:
            return delim.join(parts[:count])
        return delim.join(parts[count:])


class StringTranslate(_LiteralArgsStringFn):
    """translate(str, from, to) — per-char mapping; chars beyond `to` delete."""

    def fn(self, s, frm, to):
        table = {}
        for i, ch in enumerate(frm):
            if ch not in table:
                table[ord(ch)] = to[i] if i < len(to) else None
        return s.translate(table)


class FindInSet(_LiteralArgsStringFn):
    """find_in_set(str, comma_list) over a literal list: 1-based index, 0 when
    absent or when str contains a comma."""

    out_dtype = T.INT

    def __init__(self, child, str_list):
        super().__init__(child, str_list)

    def fn(self, s, str_list):
        if "," in s:
            return 0
        items = str_list.split(",")
        return items.index(s) + 1 if s in items else 0


def _java_replacement_to_python(rep: str) -> str:
    """Spark/Java `$1` group references → python `\\1` (literal \\$ kept)."""
    out = []
    i = 0
    while i < len(rep):
        ch = rep[i]
        if ch == "\\" and i + 1 < len(rep):
            nxt = rep[i + 1]
            out.append(nxt if nxt == "$" else "\\" + nxt)
            i += 2
        elif ch == "$" and i + 1 < len(rep) and rep[i + 1].isdigit():
            out.append("\\" + rep[i + 1])
            i += 2
        else:
            out.append("\\\\" if ch == "\\" else ch)
            i += 1
    return "".join(out)


class RegExpReplace(_LiteralArgsStringFn):
    """regexp_replace(str, pattern, replacement) with literal pattern
    (reference GpuRegExpReplace; Java-regex → python-re for the common
    subset — the planner's tag fn rejects known-incompatible constructs)."""

    def __init__(self, child, pattern, replacement):
        super().__init__(child, pattern, replacement)

    def eval(self, ctx):
        pat, rep = self._lit_args()
        rx = re.compile(pat)
        py_rep = _java_replacement_to_python(rep)
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_string(c, lambda s: rx.sub(py_rep, s))


class RegExpExtract(_LiteralArgsStringFn):
    """regexp_extract(str, pattern, idx): group idx of the FIRST match, or ""
    when no match (Spark semantics; null only for null input)."""

    def __init__(self, child, pattern, idx):
        super().__init__(child, pattern, idx)

    def eval(self, ctx):
        pat, idx = self._lit_args()
        rx = re.compile(pat)

        def extract(s):
            m = rx.search(s)
            if m is None:
                return ""
            g = m.group(int(idx))
            return g if g is not None else ""
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_string(c, extract)


class Md5(_UnaryString):
    """md5(str) → 32-char hex digest (reference GpuOverrides expr[Md5] /
    HashFunctions). Like all string transforms, runs once per DICTIONARY
    entry (ops/strings.py design note), not per row."""

    def fn(self, s):
        import hashlib
        return hashlib.md5(s.encode("utf-8")).hexdigest()


def java_split(s: str, pattern: str, limit: int) -> list:
    """Java String.split / Spark split semantics: limit > 0 caps the part
    count (limit == 1 returns the input unsplit); limit == 0 drops trailing
    empty strings but an empty input still yields [""]; limit < 0 keeps
    all parts."""
    if s is None:
        return []
    if limit == 1:
        return [s]      # python maxsplit=0 means UNLIMITED, not zero splits
    maxsplit = limit - 1 if limit > 1 else 0
    parts = re.split(pattern, s, maxsplit=maxsplit)
    if limit == 0:
        while parts and parts[-1] == "":
            parts.pop()
        if not parts and s == "":
            return [""]  # Java: "".split(x) is [""], not []
    return parts


class StringSplit(Expression):
    """split(str, regex[, limit]) → array<string> (reference GpuStringSplit,
    stringFunctions.scala — literal pattern required). Like CreateArray,
    the split array has no flat device form: only the FUSED uses
    split(...)[i] and size(split(...)) run on device (dictionary
    transforms); a materialized split column pins its exec to the host."""

    def __init__(self, child, pattern, limit=None):
        self.children = ([child, pattern]
                         + ([limit] if limit is not None else []))

    @property
    def dtype(self):
        return T.ArrayType(T.STRING)

    def with_children(self, children):
        return StringSplit(children[0], children[1],
                           children[2] if len(children) > 2 else None)

    def pattern_limit(self):
        pat = self.children[1]
        lim = self.children[2] if len(self.children) > 2 else None
        assert isinstance(pat, Literal) and (lim is None
                                             or isinstance(lim, Literal)), \
            "split pattern/limit must be literals (reference limitation)"
        return pat.value, (-1 if lim is None else lim.value)

    def eval(self, ctx):
        raise NotImplementedError(
            "split arrays have no flat device form; only fused "
            "split(...)[i] / size(split(...)) run on device")

    def __repr__(self):
        return f"split({', '.join(map(repr, self.children))})"


class _RawInt(int):
    """int that remembers its raw JSON token (Spark's get_json_object
    returns the document's own text for scalar leaves: 1.00 stays "1.00",
    1e2 stays "1e2" — not Python's re-rendering)."""
    def __new__(cls, s):
        o = super().__new__(cls, s)
        o.raw = s
        return o


class _RawFloat(float):
    def __new__(cls, s):
        o = super().__new__(cls, s)
        o.raw = s
        return o


def json_path_get(doc: str, path: str):
    """Spark get_json_object semantics for the common path subset:
    $.field, $.a.b, $.a[0].b, $[1]. Returns the raw token text for JSON
    scalars, compact JSON text for objects/arrays, None for missing or
    invalid documents."""
    import json
    if doc is None or not path.startswith("$"):
        return None
    try:
        cur = json.loads(doc, parse_int=_RawInt, parse_float=_RawFloat)
    except (ValueError, TypeError):
        return None
    i = 1
    n = len(path)
    while i < n:
        if path[i] == ".":
            j = i + 1
            while j < n and path[j] not in ".[":
                j += 1
            key = path[i + 1:j]
            if not key or not isinstance(cur, dict) or key not in cur:
                return None
            cur = cur[key]
            i = j
        elif path[i] == "[":
            j = path.find("]", i)
            if j < 0:
                return None
            try:
                idx = int(path[i + 1:j])
            except ValueError:
                return None
            if not isinstance(cur, list) or not -len(cur) <= idx < len(cur):
                return None
            cur = cur[idx]
            i = j + 1
        else:
            return None
    if cur is None:
        return None
    if isinstance(cur, (dict, list)):
        return json.dumps(cur, separators=(",", ":"))
    if isinstance(cur, bool):
        return "true" if cur else "false"
    if isinstance(cur, (_RawInt, _RawFloat)):
        return cur.raw
    return str(cur)


class GetJsonObject(Expression):
    """get_json_object(json, path) with a literal path (reference
    GpuGetJsonObject; cudf's parser has the same literal-path limit).
    Dictionary transform: each distinct document parses once."""

    out_dtype = T.STRING

    def __init__(self, child, path):
        self.children = [child, path]

    @property
    def dtype(self):
        return T.STRING

    def with_children(self, children):
        return GetJsonObject(children[0], children[1])

    def eval(self, ctx):
        path = self.children[1]
        assert isinstance(path, Literal), "json path must be a literal"
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_string(
            c, lambda s: json_path_get(s, path.value))

    def __repr__(self):
        return f"get_json_object({self.children[0]!r}, {self.children[1]!r})"
