"""String expressions (reference stringFunctions.scala: GpuUpper, GpuLower,
GpuStringLocate, GpuSubstring, GpuStartsWith, GpuEndsWith, GpuContains, GpuLike,
GpuConcat, GpuStringTrim…). All are dictionary transforms — see ops/strings.py."""

from __future__ import annotations

import re

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression, Literal
from spark_rapids_tpu.ops import strings as S


class _UnaryString(Expression):
    out_dtype = T.STRING

    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return self.out_dtype

    def with_children(self, children):
        return type(self)(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        if self.out_dtype == T.STRING:
            return S.dict_transform_to_string(c, self.fn)
        return S.dict_transform_to_values(c, self.fn, self.out_dtype)

    def fn(self, s):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r})"


class Upper(_UnaryString):
    def fn(self, s):
        return s.upper()


class Lower(_UnaryString):
    def fn(self, s):
        return s.lower()


class Length(_UnaryString):
    out_dtype = T.INT

    def fn(self, s):
        return S.java_length(s)


class Trim(_UnaryString):
    def fn(self, s):
        return s.strip(" ")


class LTrim(_UnaryString):
    def fn(self, s):
        return s.lstrip(" ")


class RTrim(_UnaryString):
    def fn(self, s):
        return s.rstrip(" ")


class Reverse(_UnaryString):
    def fn(self, s):
        return s[::-1]


class InitCap(_UnaryString):
    def fn(self, s):
        return " ".join(w[:1].upper() + w[1:].lower() if w else w
                        for w in s.split(" "))


class Substring(Expression):
    """substring(str, pos[, len]) — Spark 1-based indexing, negative pos from end."""

    def __init__(self, child, pos: Expression, length: Expression | None = None):
        self.children = [child, pos] + ([length] if length is not None else [])

    @property
    def dtype(self):
        return T.STRING

    def with_children(self, children):
        return Substring(children[0], children[1],
                         children[2] if len(children) > 2 else None)

    def eval(self, ctx):
        pos = self.children[1]
        length = self.children[2] if len(self.children) > 2 else None
        assert isinstance(pos, Literal) and (length is None or isinstance(length, Literal)), \
            "substring pos/len must be literals (reference has the same GPU limitation)"
        p = pos.value
        ln = length.value if length is not None else None
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_string(c, lambda s: S.java_substring(s, p, ln))

    def __repr__(self):
        return f"substring({self.children[0]!r})"


class _StringPredicate(Expression):
    def __init__(self, child, pattern: Expression):
        self.children = [child, pattern]

    @property
    def dtype(self):
        return T.BOOLEAN

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def eval(self, ctx):
        pat = self.children[1]
        assert isinstance(pat, Literal), \
            "pattern must be a literal (reference GpuStartsWith has the same limit)"
        p = pat.value
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_values(c, lambda s: self.test(s, p), T.BOOLEAN)

    def test(self, s, p):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r}, {self.children[1]!r})"


class StartsWith(_StringPredicate):
    def test(self, s, p):
        return s.startswith(p)


class EndsWith(_StringPredicate):
    def test(self, s, p):
        return s.endswith(p)


class Contains(_StringPredicate):
    def test(self, s, p):
        return p in s


class Like(_StringPredicate):
    def eval(self, ctx):
        pat = self.children[1]
        assert isinstance(pat, Literal)
        rx = re.compile(S.like_to_regex(pat.value))
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_values(
            c, lambda s: rx.match(s) is not None, T.BOOLEAN)


class RLike(_StringPredicate):
    def eval(self, ctx):
        pat = self.children[1]
        assert isinstance(pat, Literal)
        rx = re.compile(pat.value)
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_values(
            c, lambda s: rx.search(s) is not None, T.BOOLEAN)


class Concat(Expression):
    """concat of string columns/literals; null if any input null (Spark concat)."""

    def __init__(self, *children):
        self.children = list(children)

    @property
    def dtype(self):
        return T.STRING

    def with_children(self, children):
        return Concat(*children)

    def eval(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        out = cols[0]
        for c in cols[1:]:
            out = S.concat_cols(out, c)
        return out

    def __repr__(self):
        return f"concat({', '.join(map(repr, self.children))})"


class StringReplace(Expression):
    def __init__(self, child, search: Expression, replace: Expression):
        self.children = [child, search, replace]

    @property
    def dtype(self):
        return T.STRING

    def with_children(self, children):
        return StringReplace(children[0], children[1], children[2])

    def eval(self, ctx):
        se, re_ = self.children[1], self.children[2]
        assert isinstance(se, Literal) and isinstance(re_, Literal)
        c = self.children[0].eval(ctx)
        return S.dict_transform_to_string(c, lambda s: s.replace(se.value, re_.value))
