"""Arithmetic expressions with Spark semantics (non-ANSI mode).

Reference: sql-plugin/.../org/apache/spark/sql/rapids/arithmetic.scala (676 LoC):
GpuAdd/GpuSubtract/GpuMultiply wrap like Java (two's complement, cudf does the same),
GpuDivide returns null on zero divisor ("Special case, in Spark divide by zero is
null"), GpuIntegralDivide → LongType, GpuRemainder/GpuPmod null on zero divisor,
GpuUnaryMinus, GpuAbs.

Type promotion follows Spark's numeric precedence byte<short<int<long<float<double;
Divide always yields double for non-decimal inputs (Spark implicit cast).
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression, valid_and

_NUMERIC_ORDER = [T.ByteType, T.ShortType, T.IntegerType, T.LongType, T.FloatType,
                  T.DoubleType]


def promote(a: T.DataType, b: T.DataType) -> T.DataType:
    if a == b:
        return a
    if isinstance(a, T.DecimalType) or isinstance(b, T.DecimalType):
        # simplified decimal promotion: widen to the max precision/scale pair
        da = a if isinstance(a, T.DecimalType) else None
        db = b if isinstance(b, T.DecimalType) else None
        if da and db:
            scale = max(da.scale, db.scale)
            prec = min(T.DecimalType.MAX_PRECISION,
                       max(da.precision - da.scale, db.precision - db.scale) + scale)
            return T.DecimalType(prec, scale)
        other = b if da else a
        if isinstance(other, IntegralTypeTuple):
            return da or db
        return T.DOUBLE
    ia = _NUMERIC_ORDER.index(type(a))
    ib = _NUMERIC_ORDER.index(type(b))
    return a if ia >= ib else b


IntegralTypeTuple = (T.ByteType, T.ShortType, T.IntegerType, T.LongType)

# -- decimal multiply/divide typing (Spark DecimalPrecision, capped to the
# -- engine's DECIMAL64 bound of 18; reference GpuMultiply/GpuDivide) --------

_INT_DIGITS = {T.ByteType: 3, T.ShortType: 5, T.IntegerType: 10,
               T.LongType: 18}


def _as_dec(t: T.DataType) -> T.DecimalType | None:
    if isinstance(t, T.DecimalType):
        return t
    d = _INT_DIGITS.get(type(t))
    return T.DecimalType(d, 0) if d is not None else None


def _dec_adjust(p: int, s: int) -> T.DecimalType:
    """Spark adjustPrecisionScale with MAX_PRECISION=18 (DECIMAL64): when
    the ideal precision overflows, keep the integral digits and at least
    min(scale, 6) fractional digits."""
    if p > 18:
        s = max(18 - (p - s), min(s, 6))
        p = 18
    return T.DecimalType(p, max(s, 0))


def decimal_mul_type(lt, rt):
    """Result type for decimal multiply, or None when not a decimal op."""
    if not (isinstance(lt, T.DecimalType) or isinstance(rt, T.DecimalType)):
        return None
    d1, d2 = _as_dec(lt), _as_dec(rt)
    if d1 is None or d2 is None:        # decimal × fractional → double
        return None
    return _dec_adjust(d1.precision + d2.precision + 1, d1.scale + d2.scale)


def decimal_div_type(lt, rt):
    """Result type for decimal divide, or None when not a decimal op."""
    if not (isinstance(lt, T.DecimalType) or isinstance(rt, T.DecimalType)):
        return None
    d1, d2 = _as_dec(lt), _as_dec(rt)
    if d1 is None or d2 is None:
        return None
    s = max(6, d1.scale + d2.precision + 1)
    p = d1.precision - d1.scale + d2.scale + s
    return _dec_adjust(p, s)


def _cast_col(c: Col, to: T.DataType) -> Col:
    if c.dtype == to:
        return c
    from spark_rapids_tpu.expr.cast import cast_col
    return cast_col(c, to)


class BinaryArithmetic(Expression):
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    @property
    def dtype(self):
        return promote(self.left.dtype, self.right.dtype)

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def eval(self, ctx):
        out_t = self.dtype
        l = _cast_col(self.left.eval(ctx), out_t)
        r = _cast_col(self.right.eval(ctx), out_t)
        validity = valid_and(l.validity, r.validity)
        vals = self.op(l.values, r.values)
        return Col(vals, validity, out_t).canonicalized()

    def op(self, lv, rv):
        raise NotImplementedError

    def __repr__(self):
        return f"({self.left!r} {self.symbol} {self.right!r})"


class Add(BinaryArithmetic):
    symbol = "+"

    def op(self, lv, rv):
        return lv + rv


class Subtract(BinaryArithmetic):
    symbol = "-"

    def op(self, lv, rv):
        return lv - rv


def _round_half_up_i64(q):
    """HALF_UP (away from zero) f64 → int64."""
    return jnp.where(q >= 0, jnp.floor(q + 0.5),
                     jnp.ceil(q - 0.5)).astype(jnp.int64)


class Multiply(BinaryArithmetic):
    symbol = "*"

    @property
    def dtype(self):
        dt = decimal_mul_type(self.left.dtype, self.right.dtype)
        return dt if dt is not None else promote(self.left.dtype,
                                                 self.right.dtype)

    def eval(self, ctx):
        out_t = self.dtype
        if not isinstance(out_t, T.DecimalType):
            return super().eval(ctx)
        # decimal multiply at Spark's result scale: unscaled product lives
        # at scale s1+s2, HALF_UP-rescaled to the adjusted result scale.
        # Exact int64 when the ideal precision fits DECIMAL64; float64
        # otherwise (~15 significant digits, docs/compatibility.md).
        l, r = self.left.eval(ctx), self.right.eval(ctx)
        d1, d2 = _as_dec(self.left.dtype), _as_dec(self.right.dtype)
        lv = l.values.astype(jnp.int64)
        rv = r.values.astype(jnp.int64)
        drop = d1.scale + d2.scale - out_t.scale
        if d1.precision + d2.precision + 1 <= 18:
            prod = lv * rv
            if drop:
                div = 10 ** drop
                a = jnp.abs(prod)
                q = (a + div // 2) // div
                prod = jnp.where(prod < 0, -q, q)
            vals = prod
            ok = jnp.abs(vals) < 10 ** out_t.precision   # overflow → null
        else:
            qf = (lv.astype(jnp.float64) * rv.astype(jnp.float64)
                  / (10.0 ** drop))
            # overflow check in the FLOAT domain: an out-of-int64-range
            # cast saturates to INT64_MIN whose abs is itself negative,
            # which would sail through an int-domain check
            ok = jnp.abs(qf) < float(10 ** out_t.precision)
            vals = _round_half_up_i64(jnp.where(ok, qf, 0.0))
        validity = valid_and(l.validity, r.validity) & ok
        return Col(vals, validity, out_t).canonicalized()

    def op(self, lv, rv):
        return lv * rv


class Divide(BinaryArithmetic):
    """Spark Divide: double result (non-decimal), NULL on zero divisor — even for
    doubles (reference GpuDivide, arithmetic.scala)."""
    symbol = "/"

    @property
    def dtype(self):
        dt = decimal_div_type(self.left.dtype, self.right.dtype)
        if dt is not None:
            return dt
        return T.DOUBLE

    def eval(self, ctx):
        out_t = self.dtype
        if isinstance(out_t, T.DecimalType):
            # decimal divide, HALF_UP at Spark's (DECIMAL64-adjusted)
            # result scale via float64 (~15 significant digits,
            # docs/compatibility.md); NULL on zero divisor and overflow
            l, r = self.left.eval(ctx), self.right.eval(ctx)
            d1, d2 = _as_dec(self.left.dtype), _as_dec(self.right.dtype)
            lv = l.values.astype(jnp.int64)
            rv = r.values.astype(jnp.int64)
            zero = rv == 0
            k = out_t.scale + d2.scale - d1.scale
            q = (lv.astype(jnp.float64)
                 / jnp.where(zero, 1, rv).astype(jnp.float64)
                 * (10.0 ** k))
            # overflow check in the FLOAT domain (see Multiply)
            ok = jnp.abs(q) < float(10 ** out_t.precision)
            vals = _round_half_up_i64(jnp.where(ok, q, 0.0))
            validity = valid_and(l.validity, r.validity) & ~zero & ok
            return Col(vals, validity, out_t).canonicalized()
        l = _cast_col(self.left.eval(ctx), out_t)
        r = _cast_col(self.right.eval(ctx), out_t)
        zero = r.values == 0
        validity = valid_and(l.validity, r.validity) & ~zero
        safe_r = jnp.where(zero, jnp.ones_like(r.values), r.values)
        vals = l.values / safe_r
        return Col(vals, validity, out_t).canonicalized()


class IntegralDivide(BinaryArithmetic):
    """Spark `div`: LongType result, null on zero divisor, truncation toward zero
    (Java semantics; jnp floor-divides, so adjust)."""
    symbol = "div"

    @property
    def dtype(self):
        return T.LONG

    def eval(self, ctx):
        l = _cast_col(self.left.eval(ctx), T.LONG)
        r = _cast_col(self.right.eval(ctx), T.LONG)
        zero = r.values == 0
        validity = valid_and(l.validity, r.validity) & ~zero
        safe_r = jnp.where(zero, jnp.ones_like(r.values), r.values)
        q = l.values // safe_r
        rem = l.values - q * safe_r
        # floor-div → trunc-div: bump quotient toward zero when signs differ & rem != 0
        q = jnp.where((rem != 0) & ((l.values < 0) != (safe_r < 0)), q + 1, q)
        return Col(q, validity, T.LONG).canonicalized()


class Remainder(BinaryArithmetic):
    """Spark %: Java remainder (sign follows dividend), null on zero divisor."""
    symbol = "%"

    def eval(self, ctx):
        out_t = self.dtype
        l = _cast_col(self.left.eval(ctx), out_t)
        r = _cast_col(self.right.eval(ctx), out_t)
        zero = r.values == 0
        validity = valid_and(l.validity, r.validity) & ~zero
        safe_r = jnp.where(zero, jnp.ones_like(r.values), r.values)
        if isinstance(out_t, T.FractionalType):
            vals = jnp.fmod(l.values, safe_r)  # C-style, sign of dividend (Java %)
        else:
            vals = _java_rem(l.values, safe_r)
        return Col(vals, validity, out_t).canonicalized()


class Pmod(BinaryArithmetic):
    """Spark pmod: r = a % n (Java remainder); if r < 0 then (r + n) % n else r.
    Null on zero divisor. Note the result keeps the divisor's sign for negative
    divisors (pmod(-7, -3) = -1), matching Spark exactly."""
    symbol = "pmod"

    def eval(self, ctx):
        out_t = self.dtype
        l = _cast_col(self.left.eval(ctx), out_t)
        r = _cast_col(self.right.eval(ctx), out_t)
        zero = r.values == 0
        validity = valid_and(l.validity, r.validity) & ~zero
        safe_r = jnp.where(zero, jnp.ones_like(r.values), r.values)
        if isinstance(out_t, T.FractionalType):
            m = jnp.fmod(l.values, safe_r)
            vals = jnp.where(m < 0, jnp.fmod(m + safe_r, safe_r), m)
        else:
            m = _java_rem(l.values, safe_r)
            vals = jnp.where(m < 0, _java_rem(m + safe_r, safe_r), m)
        return Col(vals, validity, out_t).canonicalized()


def _java_rem(a, n):
    """Java % (sign follows dividend) from python-style jnp.remainder."""
    m = jnp.remainder(a, n)
    return jnp.where((m != 0) & ((m < 0) != (a < 0)), m - n, m)


class UnaryMinus(Expression):
    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return self.children[0].dtype

    def with_children(self, children):
        return UnaryMinus(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return c.with_(values=-c.values).canonicalized()

    def __repr__(self):
        return f"(- {self.children[0]!r})"


class Abs(Expression):
    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return self.children[0].dtype

    def with_children(self, children):
        return Abs(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return c.with_(values=jnp.abs(c.values)).canonicalized()

    def __repr__(self):
        return f"abs({self.children[0]!r})"


# ---------------------------------------------------------------------------
# Bitwise (reference org/apache/spark/sql/rapids/bitwise.scala: GpuBitwiseAnd/
# Or/Xor/Not, GpuShiftLeft/Right/RightUnsigned — Java shift semantics)
# ---------------------------------------------------------------------------

class BitwiseAnd(BinaryArithmetic):
    symbol = "&"

    def op(self, lv, rv):
        return lv & rv


class BitwiseOr(BinaryArithmetic):
    symbol = "|"

    def op(self, lv, rv):
        return lv | rv


class BitwiseXor(BinaryArithmetic):
    symbol = "^"

    def op(self, lv, rv):
        return lv ^ rv


class BitwiseNot(Expression):
    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return self.children[0].dtype

    def with_children(self, children):
        return BitwiseNot(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return c.with_(values=~c.values).canonicalized()

    def __repr__(self):
        return f"(~ {self.children[0]!r})"


class _Shift(Expression):
    """base SHIFT amount: Java masks the shift count to the base width
    (x << 33 == x << 1 for ints); result type is the base's (int or long)."""
    symbol = "?"

    def __init__(self, base, amount):
        self.children = [base, amount]

    @property
    def dtype(self):
        base_t = self.children[0].dtype
        return base_t if isinstance(base_t, T.LongType) else T.INT

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def eval(self, ctx):
        out_t = self.dtype
        b = _cast_col(self.children[0].eval(ctx), out_t)
        a = _cast_col(self.children[1].eval(ctx), T.INT)
        width_mask = 63 if isinstance(out_t, T.LongType) else 31
        amt = (a.values & width_mask).astype(b.values.dtype)
        validity = valid_and(b.validity, a.validity)
        return Col(self.op(b.values, amt), validity, out_t).canonicalized()

    def __repr__(self):
        return f"({self.children[0]!r} {self.symbol} {self.children[1]!r})"


class ShiftLeft(_Shift):
    symbol = "<<"

    def op(self, bv, amt):
        return bv << amt


class ShiftRight(_Shift):
    symbol = ">>"

    def op(self, bv, amt):
        return bv >> amt  # arithmetic shift on signed ints


class ShiftRightUnsigned(_Shift):
    symbol = ">>>"

    def op(self, bv, amt):
        unsigned = jnp.uint64 if bv.dtype == jnp.int64 else jnp.uint32
        return (bv.astype(unsigned) >> amt.astype(unsigned)).astype(bv.dtype)


class UnaryPositive(Expression):
    """+x: identity (reference GpuOverrides expr[UnaryPositive])."""

    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return self.children[0].dtype

    def with_children(self, children):
        return UnaryPositive(children[0])

    def eval(self, ctx):
        return self.children[0].eval(ctx)

    def __repr__(self):
        return f"(+ {self.children[0]!r})"
