"""Aggregate functions with Spark two-phase (update/merge) semantics.

Reference: AggregateFunctions.scala:704 (GpuSum, GpuCount, GpuMin, GpuMax, GpuAverage,
GpuFirst, GpuLast) consumed by GpuHashAggregateExec's update→concat→merge loop
(aggregate.scala:282-420). Same decomposition here:

  inputs      — expressions evaluated on the raw batch (pre-aggregation projection)
  update      — segment-reduce raw values into per-group state columns
  merge       — segment-reduce state columns of partial batches (re-aggregation)
  evaluate    — final expression over state columns

Null semantics implemented: COUNT never null and counts non-nulls (COUNT(*) counts
rows); SUM/MIN/MAX/AVG ignore nulls and are null iff no non-null input; AVG of
integrals is double; SUM of integrals is long (wrapping), of floats is double.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression
from spark_rapids_tpu.ops import grouping as G


class AggregateFunction(Expression):
    """Declarative aggregate. `state_types` names the partial-state columns."""

    def __init__(self, child: Expression | None):
        self.children = [child] if child is not None else []

    @property
    def child(self):
        return self.children[0] if self.children else None

    def with_children(self, children):
        return type(self)(children[0] if children else None)

    @property
    def state_types(self) -> list:
        raise NotImplementedError

    def update(self, in_col: Col, segctx: 'G.SegCtx') -> list:
        """Raw column → list of state Cols (one per state_types entry)."""
        raise NotImplementedError

    def merge(self, state_cols: list, segctx: 'G.SegCtx') -> list:
        """Partial states → merged states."""
        raise NotImplementedError

    def evaluate(self, state_cols: list) -> Col:
        """Merged states → final value column."""
        raise NotImplementedError

    def eval(self, ctx):
        raise RuntimeError("aggregate functions are evaluated by the aggregate exec")

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.child!r})"


def _sum_result_type(t: T.DataType) -> T.DataType:
    if isinstance(t, T.DecimalType):
        return T.DecimalType(min(t.precision + 10, T.DecimalType.MAX_PRECISION), t.scale)
    if isinstance(t, T.IntegralType):
        return T.LONG
    return T.DOUBLE


class Sum(AggregateFunction):
    @property
    def dtype(self):
        return _sum_result_type(self.child.dtype)

    @property
    def state_types(self):
        return [self.dtype]

    def _acc_dtype(self):
        return self.dtype.jnp_dtype

    def update(self, in_col, segctx):
        vals = in_col.values.astype(self._acc_dtype())
        s, cnt = G.segment_sum(vals, in_col.validity, segctx)
        return [Col(s, cnt > 0, self.dtype)]

    def merge(self, state_cols, segctx):
        st = state_cols[0]
        s, cnt = G.segment_sum(st.values, st.validity, segctx)
        return [Col(s, cnt > 0, self.dtype)]

    def evaluate(self, state_cols):
        return state_cols[0].canonicalized()


class Count(AggregateFunction):
    """COUNT(expr) counts non-null; COUNT(*) (child None) counts rows."""

    @property
    def dtype(self):
        return T.LONG

    @property
    def nullable(self):
        return False

    @property
    def state_types(self):
        return [T.LONG]

    def update(self, in_col, segctx):
        # COUNT(*): the exec passes a live-masked placeholder column, so its
        # validity is exactly "row is live" — padding never counts (segments
        # can span padding rows in the per-row scan design)
        validity = in_col.validity
        ones = validity.astype(jnp.int64)
        s, _ = G.segment_sum(ones, jnp.ones_like(validity), segctx)
        return [Col(s, jnp.ones_like(s, dtype=jnp.bool_), T.LONG)]

    def merge(self, state_cols, segctx):
        st = state_cols[0]
        s, _ = G.segment_sum(st.values, st.validity, segctx)
        return [Col(s, jnp.ones_like(s, dtype=jnp.bool_), T.LONG)]

    def evaluate(self, state_cols):
        return state_cols[0]

    def __repr__(self):
        return f"count({self.child!r})" if self.child else "count(*)"


class Min(AggregateFunction):
    @property
    def dtype(self):
        return self.child.dtype

    @property
    def state_types(self):
        return [self.dtype]

    def update(self, in_col, segctx):
        m = G.segment_min(in_col.values, in_col.validity, segctx,
                          self.dtype)
        cnt = G.segment_count(in_col.validity, segctx)
        return [Col(m, cnt > 0, self.dtype, in_col.dictionary)]

    def merge(self, state_cols, segctx):
        return self.update(state_cols[0], segctx)

    def evaluate(self, state_cols):
        return state_cols[0].canonicalized()


class Max(AggregateFunction):
    @property
    def dtype(self):
        return self.child.dtype

    @property
    def state_types(self):
        return [self.dtype]

    def update(self, in_col, segctx):
        m = G.segment_max(in_col.values, in_col.validity, segctx,
                          self.dtype)
        cnt = G.segment_count(in_col.validity, segctx)
        return [Col(m, cnt > 0, self.dtype, in_col.dictionary)]

    def merge(self, state_cols, segctx):
        return self.update(state_cols[0], segctx)

    def evaluate(self, state_cols):
        return state_cols[0].canonicalized()


class Average(AggregateFunction):
    """AVG: (sum: double|decimal, count: long) state; double result for non-decimal
    (Spark). Decimal avg yields decimal with +4 scale (Spark rule), capped at 18."""

    @property
    def dtype(self):
        ct = self.child.dtype
        if isinstance(ct, T.DecimalType):
            scale = min(ct.scale + 4, T.DecimalType.MAX_PRECISION)
            return T.DecimalType(T.DecimalType.MAX_PRECISION, scale)
        return T.DOUBLE

    @property
    def state_types(self):
        ct = self.child.dtype
        sum_t = _sum_result_type(ct)
        return [sum_t, T.LONG]

    def update(self, in_col, segctx):
        sum_t = self.state_types[0]
        vals = in_col.values.astype(sum_t.jnp_dtype)
        s, cnt = G.segment_sum(vals, in_col.validity, segctx)
        return [Col(s, cnt > 0, sum_t),
                Col(cnt, jnp.ones_like(cnt, dtype=jnp.bool_), T.LONG)]

    def merge(self, state_cols, segctx):
        s_st, c_st = state_cols
        s, _ = G.segment_sum(s_st.values, s_st.validity, segctx)
        c, _ = G.segment_sum(c_st.values, c_st.validity, segctx)
        return [Col(s, c > 0, self.state_types[0]),
                Col(c, jnp.ones_like(c, dtype=jnp.bool_), T.LONG)]

    def evaluate(self, state_cols):
        s_st, c_st = state_cols
        cnt = c_st.values
        ok = cnt > 0
        safe = jnp.where(ok, cnt, 1)
        if isinstance(self.dtype, T.DecimalType):
            in_scale = self.state_types[0].scale
            up = self.dtype.scale - in_scale
            num = s_st.values * (10 ** up)
            mag = jnp.abs(num)
            qm = (mag + safe // 2) // safe
            vals = jnp.where(num < 0, -qm, qm)
        else:
            vals = s_st.values.astype(jnp.float64) / safe
        return Col(vals, ok, self.dtype).canonicalized()

    def __repr__(self):
        return f"avg({self.child!r})"


class First(AggregateFunction):
    def __init__(self, child, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def with_children(self, children):
        return First(children[0], self.ignore_nulls)

    @property
    def dtype(self):
        return self.child.dtype

    @property
    def state_types(self):
        return [self.dtype]

    def update(self, in_col, segctx):
        vals, valid = G.segment_first(in_col.values, in_col.validity, segctx, self.ignore_nulls)
        return [Col(vals, valid, self.dtype, in_col.dictionary)]

    def merge(self, state_cols, segctx):
        st = state_cols[0]
        vals, valid = G.segment_first(st.values, st.validity, segctx,
                                      self.ignore_nulls)
        return [Col(vals, valid, self.dtype, st.dictionary)]

    def evaluate(self, state_cols):
        return state_cols[0].canonicalized()


class Last(AggregateFunction):
    """Spark Last(ignoreNulls) (reference AggregateFunctions.scala GpuLast)."""

    def __init__(self, child, ignore_nulls: bool = False):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def with_children(self, children):
        return Last(children[0], self.ignore_nulls)

    @property
    def dtype(self):
        return self.child.dtype

    @property
    def state_types(self):
        return [self.dtype]

    def update(self, in_col, segctx):
        vals, valid = G.segment_last(in_col.values, in_col.validity, segctx, self.ignore_nulls)
        return [Col(vals, valid, self.dtype, in_col.dictionary)]

    def merge(self, state_cols, segctx):
        st = state_cols[0]
        vals, valid = G.segment_last(st.values, st.validity, segctx,
                                     self.ignore_nulls)
        return [Col(vals, valid, self.dtype, st.dictionary)]

    def evaluate(self, state_cols):
        return state_cols[0].canonicalized()


class _CentralMoment(AggregateFunction):
    """Variance/stddev family over (n, sum, sum-of-squares) states — the
    numerically simple merge form (reference aggregate functions use cudf's
    m2-based groupby; sums suffice at double precision for SQL parity tests)."""

    @property
    def dtype(self):
        return T.DOUBLE

    @property
    def state_types(self):
        return [T.LONG, T.DOUBLE, T.DOUBLE]

    def update(self, in_col, segctx):
        v = in_col.values.astype(jnp.float64)
        zero = jnp.zeros_like(v)
        vv = jnp.where(in_col.validity, v, zero)
        s, cnt = G.segment_sum(vv, in_col.validity, segctx)
        s2, _ = G.segment_sum(vv * vv, in_col.validity, segctx)
        ones = jnp.ones_like(cnt, dtype=jnp.bool_)
        return [Col(cnt, ones, T.LONG), Col(s, ones, T.DOUBLE),
                Col(s2, ones, T.DOUBLE)]

    def merge(self, state_cols, segctx):
        n_st, s_st, s2_st = state_cols
        n, _ = G.segment_sum(n_st.values, n_st.validity, segctx)
        s, _ = G.segment_sum(s_st.values, s_st.validity, segctx)
        s2, _ = G.segment_sum(s2_st.values, s2_st.validity, segctx)
        ones = jnp.ones_like(n, dtype=jnp.bool_)
        return [Col(n, ones, T.LONG), Col(s, ones, T.DOUBLE),
                Col(s2, ones, T.DOUBLE)]

    def _moments(self, state_cols):
        n = state_cols[0].values
        s = state_cols[1].values
        s2 = state_cols[2].values
        nf = n.astype(jnp.float64)
        safe = jnp.where(n > 0, nf, 1.0)
        mean = s / safe
        m2 = jnp.maximum(s2 - s * mean, 0.0)  # sum((x-mean)^2)
        return n, m2

    def evaluate(self, state_cols):
        n, m2 = self._moments(state_cols)
        denom = self.denominator(n)
        ok = denom > 0
        vals = self.finish(m2 / jnp.where(ok, denom, 1.0))
        return Col(vals, ok, T.DOUBLE)

    def finish(self, var):
        return var


class VariancePop(_CentralMoment):
    def denominator(self, n):
        return n.astype(jnp.float64)


class VarianceSamp(_CentralMoment):
    def denominator(self, n):
        return (n - 1).astype(jnp.float64)


class StddevPop(VariancePop):
    def finish(self, var):
        return jnp.sqrt(var)


class StddevSamp(VarianceSamp):
    def finish(self, var):
        return jnp.sqrt(var)


class CollectList(AggregateFunction):
    """collect_list(x) → array of non-null values per group (reference
    GpuCollectList via cudf collect). Array results have no fixed-width
    device form in this engine, so the planner pins the aggregate to the
    host path (plan/nodes.py AggregateNode._agg_one)."""

    @property
    def dtype(self):
        return T.ArrayType(self.child.dtype)

    @property
    def state_types(self):
        raise NotImplementedError("collect_list runs on host")


class CollectSet(CollectList):
    """collect_set(x) — distinct non-null values (order unspecified in
    Spark; first-seen order here)."""


class PivotFirst(AggregateFunction):
    """PivotFirst(value, pivotColumn, pivotValues) — the aggregate Spark
    plans under df.groupBy(..).pivot(..).agg(first(..)) (reference
    GpuPivotFirst): per group, an array with one slot per pivot value
    holding the first matching value. Array output → host path, like
    collect_list."""

    def __init__(self, value, pivot, pivot_values: list):
        self.children = [value, pivot]
        self.pivot_values = list(pivot_values)

    def with_children(self, children):
        return PivotFirst(children[0], children[1], self.pivot_values)

    @property
    def dtype(self):
        return T.ArrayType(self.children[0].dtype)

    @property
    def state_types(self):
        raise NotImplementedError("pivot_first runs on host")
