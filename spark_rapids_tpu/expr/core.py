"""Expression tree + columnar evaluation — the GpuExpression layer.

Reference: the ~160 GPU expressions under
sql-plugin/src/main/scala/org/apache/spark/sql/rapids/ (SURVEY.md component #20), each
a Catalyst Expression whose `columnarEval` issues cudf kernels. Here `Expression.eval`
builds jax ops over a `Col` (values + validity arrays). Because jax ops are traceable,
the SAME eval path serves two execution modes:

- eager: called with concrete device arrays, one XLA dispatch per op (cudf-style);
- fused: called inside a single jax.jit trace covering a whole project/filter/aggregate
  stage, letting XLA fuse everything into one TPU program — the TPU-first win the
  reference cannot express (one CUDA kernel per op).

Null semantics are Spark's three-valued logic: null in → null out for most ops, with
Kleene AND/OR, null-safe equality, and the divide-by-zero→null rule implemented
explicitly (reference arithmetic.scala GpuDivide "divide by zero is null").

String columns flow as dictionary codes; scalar string functions run on the (small,
host-side) dictionary and become device gathers — see strings.py.
"""

from __future__ import annotations

import dataclasses
import typing

import numpy as np
import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T


@jax.tree_util.register_pytree_node_class
class Col:
    """A column value during evaluation: padded values + validity, plus static dtype
    and (for strings) the host dictionary. Registered as a pytree so Cols can cross
    jit boundaries."""

    __slots__ = ("values", "validity", "dtype", "dictionary")

    def __init__(self, values, validity, dtype: T.DataType, dictionary=None):
        self.values = values
        self.validity = validity
        self.dtype = dtype
        self.dictionary = dictionary

    def tree_flatten(self):
        # the host dictionary is static aux data; wrap it so jit's cache can
        # hash it (pa.Array is unhashable) — content-equal dictionaries from
        # different batches then hit the same compiled program
        d = self.dictionary
        if d is not None:
            from spark_rapids_tpu.runtime.fuse import DictRef
            d = DictRef(d)
        return (self.values, self.validity), (self.dtype, d)

    @classmethod
    def tree_unflatten(cls, aux, children):
        d = aux[1]
        if d is not None and type(d).__name__ == "DictRef":
            d = d.arr
        return cls(children[0], children[1], aux[0], d)

    @staticmethod
    def from_vector(cv, capacity=None):
        return Col(cv.data, cv.validity, cv.dtype, cv.dictionary)

    def to_vector(self):
        from spark_rapids_tpu.columnar.vector import TpuColumnVector
        return TpuColumnVector(self.dtype, self.values, self.validity, self.dictionary)

    @property
    def is_string(self):
        return isinstance(self.dtype, T.StringType)

    def with_(self, values=None, validity=None, dtype=None, dictionary="__keep__"):
        return Col(self.values if values is None else values,
                   self.validity if validity is None else validity,
                   self.dtype if dtype is None else dtype,
                   self.dictionary if isinstance(dictionary, str) and dictionary == "__keep__"
                   else dictionary)

    def canonicalized(self):
        """Force invalid slots to the dtype default (keeps hashes/sorts deterministic
        after ops that may write garbage into null slots)."""
        default = jnp.asarray(self.dtype.default_value(), dtype=self.values.dtype)
        return Col(jnp.where(self.validity, self.values, default), self.validity,
                   self.dtype, self.dictionary)


def valid_and(*validities):
    out = validities[0]
    for v in validities[1:]:
        out = out & v
    return out


class Expression:
    """Base expression. Subclasses define `dtype`, `nullable`, `children`, `eval`."""

    children: typing.Sequence["Expression"] = ()

    @property
    def dtype(self) -> T.DataType:
        raise NotImplementedError

    @property
    def nullable(self) -> bool:
        return True

    def eval(self, ctx: "EvalContext") -> Col:
        raise NotImplementedError

    # -- tree utilities -----------------------------------------------------
    def transform(self, fn):
        """Bottom-up transform returning a new tree (Catalyst transformUp analog)."""
        new_children = [c.transform(fn) for c in self.children]
        node = self.with_children(new_children) if new_children else self
        return fn(node)

    def with_children(self, children):
        if not children:
            return self
        clone = dataclasses.replace(self) if dataclasses.is_dataclass(self) else self
        clone.children = list(children)
        return clone

    def collect(self, pred):
        out = [self] if pred(self) else []
        for c in self.children:
            out.extend(c.collect(pred))
        return out

    @property
    def name(self):
        return str(self)

    # -- pyspark-Column-style operator sugar (used by the session API) -------
    # NB: __eq__/__ne__ build expressions (like pyspark Column); identity
    # hashing keeps expressions usable in sets/dicts, but `x in list_of_exprs`
    # must not be used for structural equality anywhere in the engine.
    def _bin(self, other, cls, swap=False):
        o = other if isinstance(other, Expression) else _auto_lit(other)
        return cls(o, self) if swap else cls(self, o)

    def __add__(self, other):
        from spark_rapids_tpu.expr.arithmetic import Add
        return self._bin(other, Add)

    def __radd__(self, other):
        from spark_rapids_tpu.expr.arithmetic import Add
        return self._bin(other, Add, swap=True)

    def __sub__(self, other):
        from spark_rapids_tpu.expr.arithmetic import Subtract
        return self._bin(other, Subtract)

    def __rsub__(self, other):
        from spark_rapids_tpu.expr.arithmetic import Subtract
        return self._bin(other, Subtract, swap=True)

    def __mul__(self, other):
        from spark_rapids_tpu.expr.arithmetic import Multiply
        return self._bin(other, Multiply)

    def __rmul__(self, other):
        from spark_rapids_tpu.expr.arithmetic import Multiply
        return self._bin(other, Multiply, swap=True)

    def __truediv__(self, other):
        from spark_rapids_tpu.expr.arithmetic import Divide
        return self._bin(other, Divide)

    def __rtruediv__(self, other):
        from spark_rapids_tpu.expr.arithmetic import Divide
        return self._bin(other, Divide, swap=True)

    def __mod__(self, other):
        from spark_rapids_tpu.expr.arithmetic import Remainder
        return self._bin(other, Remainder)

    def __rmod__(self, other):
        from spark_rapids_tpu.expr.arithmetic import Remainder
        return self._bin(other, Remainder, swap=True)

    def __neg__(self):
        from spark_rapids_tpu.expr.arithmetic import UnaryMinus
        return UnaryMinus(self)

    def __gt__(self, other):
        from spark_rapids_tpu.expr.predicates import GreaterThan
        return self._bin(other, GreaterThan)

    def __ge__(self, other):
        from spark_rapids_tpu.expr.predicates import GreaterThanOrEqual
        return self._bin(other, GreaterThanOrEqual)

    def __lt__(self, other):
        from spark_rapids_tpu.expr.predicates import LessThan
        return self._bin(other, LessThan)

    def __le__(self, other):
        from spark_rapids_tpu.expr.predicates import LessThanOrEqual
        return self._bin(other, LessThanOrEqual)

    def __eq__(self, other):
        from spark_rapids_tpu.expr.predicates import EqualTo
        return self._bin(other, EqualTo)

    def __ne__(self, other):
        from spark_rapids_tpu.expr.predicates import NotEqual
        return self._bin(other, NotEqual)

    def __and__(self, other):
        from spark_rapids_tpu.expr.predicates import And
        return self._bin(other, And)

    def __or__(self, other):
        from spark_rapids_tpu.expr.predicates import Or
        return self._bin(other, Or)

    def __invert__(self):
        from spark_rapids_tpu.expr.predicates import Not
        return Not(self)

    __hash__ = object.__hash__

    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def isin(self, *values):
        """col.isin(a, b, ...) or col.isin([a, b]) (pyspark Column.isin)."""
        from spark_rapids_tpu.expr.predicates import InSet
        if len(values) == 1 and isinstance(values[0], (list, tuple, set)):
            values = tuple(values[0])
        return InSet(self, list(values))

    def cast(self, to: T.DataType):
        from spark_rapids_tpu.expr.cast import Cast
        return Cast(self, to)

    def is_null(self):
        from spark_rapids_tpu.expr.nullexprs import IsNull
        return IsNull(self)

    def is_not_null(self):
        from spark_rapids_tpu.expr.nullexprs import IsNotNull
        return IsNotNull(self)


def _auto_lit(v):
    return Literal(v, _infer_literal_type(v))


class EvalContext:
    """Holds the input columns (as Cols) for bound-reference lookup during eval, the
    number-of-rows scalar, and the batch capacity (static)."""

    def __init__(self, cols, num_rows, capacity: int, split: int = 0,
                 row_offset: int = 0, scan_meta: dict | None = None):
        self.cols = list(cols)
        self.num_rows = num_rows  # device or host scalar
        self.capacity = capacity
        self.split = split  # task partition index (rand / partition-id exprs)
        # rows already emitted by earlier batches of this partition; only
        # maintained (host-synced) when the projection contains a
        # row-position-dependent expression (monotonically_increasing_id, rand)
        self.row_offset = row_offset
        # scan provenance (input_file_name family); None when unavailable
        self.scan_meta = scan_meta

    @staticmethod
    def from_batch(batch, split: int = 0, row_offset: int = 0):
        return EvalContext([Col.from_vector(c) for c in batch.columns],
                           batch.lazy_num_rows, batch.capacity, split,
                           row_offset,
                           scan_meta=getattr(batch, "metadata", None))

    def row_mask(self):
        """Bool mask of live (non-padding) rows."""
        return jnp.arange(self.capacity) < self.num_rows


class AttributeReference(Expression):
    """Named column reference, resolved to a BoundReference before execution
    (Catalyst AttributeReference analog)."""

    def __init__(self, name: str, dtype: T.DataType, nullable: bool = True):
        self._name = name
        self._dtype = dtype
        self._nullable = nullable

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return self._nullable

    @property
    def name(self):
        return self._name

    def eval(self, ctx):
        raise RuntimeError(f"unresolved attribute {self._name}; bind_references first")

    def __repr__(self):
        return f"'{self._name}"


class BoundReference(Expression):
    def __init__(self, ordinal: int, dtype: T.DataType, nullable: bool = True,
                 name: str = None):
        self.ordinal = ordinal
        self._dtype = dtype
        self._nullable = nullable
        self._name = name or f"input[{ordinal}]"

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return self._nullable

    @property
    def name(self):
        return self._name

    def eval(self, ctx):
        return ctx.cols[self.ordinal]

    def __repr__(self):
        return f"input[{self.ordinal}:{self._dtype}]"


class Literal(Expression):
    def __init__(self, value, dtype: T.DataType | None = None):
        self.value = value
        if dtype is None:
            dtype = _infer_literal_type(value)
        self._dtype = dtype

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return self.value is None

    def eval(self, ctx):
        cap = ctx.capacity
        if self.value is None:
            vals = jnp.full((cap,), self._dtype.default_value(),
                            dtype=self._dtype.jnp_dtype)
            return Col(vals, jnp.zeros((cap,), jnp.bool_), self._dtype)
        if isinstance(self._dtype, T.StringType):
            import pyarrow as pa
            d = pa.array([self.value], type=pa.string())
            return Col(jnp.zeros((cap,), jnp.int32), jnp.ones((cap,), jnp.bool_),
                       self._dtype, dictionary=d)
        v = self.value
        if isinstance(self._dtype, T.DecimalType) and not isinstance(v, int):
            from decimal import Decimal
            v = int(Decimal(str(v)).scaleb(self._dtype.scale))
        vals = jnp.full((cap,), v, dtype=self._dtype.jnp_dtype)
        return Col(vals, jnp.ones((cap,), jnp.bool_), self._dtype)

    def __repr__(self):
        return f"lit({self.value!r})"


def _infer_literal_type(v):
    if v is None:
        return T.NULL
    if isinstance(v, bool):
        return T.BOOLEAN
    if isinstance(v, int):
        return T.INT if -(2**31) <= v < 2**31 else T.LONG
    if isinstance(v, float):
        return T.DOUBLE
    if isinstance(v, str):
        return T.STRING
    raise TypeError(f"cannot infer literal type for {v!r}")


class Alias(Expression):
    def __init__(self, child: Expression, alias: str):
        self.children = [child]
        self.alias = alias

    @property
    def child(self):
        return self.children[0]

    @property
    def dtype(self):
        return self.child.dtype

    @property
    def nullable(self):
        return self.child.nullable

    @property
    def name(self):
        return self.alias

    def eval(self, ctx):
        return self.child.eval(ctx)

    def with_children(self, children):
        return Alias(children[0], self.alias)

    def __repr__(self):
        return f"{self.child!r} AS {self.alias}"


def bind_references(expr: Expression, schema: T.StructType) -> Expression:
    """Replace AttributeReferences with BoundReferences against `schema`
    (Catalyst BindReferences.bindReference analog, used by every exec)."""
    def fn(node):
        if isinstance(node, AttributeReference):
            i = schema.index_of(node.name)
            f = schema[i]
            return BoundReference(i, f.data_type, f.nullable, node.name)
        return node
    return expr.transform(fn)


# convenience: column factory used by the DataFrame layer and tests
def col(name: str, dtype: T.DataType = None, nullable: bool = True):
    return AttributeReference(name, dtype, nullable)


def lit(value, dtype: T.DataType | None = None):
    return Literal(value, dtype)
