"""Cast — Spark (non-ANSI) cast semantics on device.

Reference: sql-plugin/.../com/nvidia/spark/rapids/GpuCast.scala (1254 LoC) +
TypeChecks CastChecks table (TypeChecks.scala:878). The reference spends most of its
lines on exactly the edge cases implemented here:

- int narrowing wraps like Java (long→int keeps low 32 bits);
- float→integral truncates toward zero, clamps to the target range, NaN→0
  (Java (long)/(int) conversion semantics);
- numeric→boolean is `!= 0`; boolean→numeric is 1/0;
- date↔timestamp via days*86_400_000_000 micros (floor for ts→date);
- decimal rescale with overflow→null (reference GpuCast decimal paths);
- string→numeric/date parses per *dictionary entry* on host with Spark's rules
  (trim, optional sign, fractional truncation toward zero, overflow→null) then
  gathers on device — exact and O(|dict|) host work;
- numeric→string formats per row value via a host-built dictionary (Java formatting).
"""

from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression

_INT_BOUNDS = {
    T.ByteType: (-(2**7), 2**7 - 1),
    T.ShortType: (-(2**15), 2**15 - 1),
    T.IntegerType: (-(2**31), 2**31 - 1),
    T.LongType: (-(2**63), 2**63 - 1),
}

_MICROS_PER_DAY = 86_400_000_000


def _float_to_integral(vals, to: T.DataType):
    lo, hi = _INT_BOUNDS[type(to)]
    t = jnp.trunc(vals)
    t = jnp.where(jnp.isnan(vals), 0.0, t)
    t = jnp.clip(t, float(lo), float(hi))
    # values beyond f64 exact range clamp correctly because lo/hi round outward
    out = t.astype(jnp.int64)
    out = jnp.clip(out, lo, hi)
    return out.astype(to.jnp_dtype)


def cast_col(c: Col, to: T.DataType) -> Col:
    frm = c.dtype
    if frm == to:
        return c
    if isinstance(frm, T.NullType):
        from spark_rapids_tpu.expr.core import Literal
        cap = int(c.values.shape[0])
        if isinstance(to, T.StringType):
            import pyarrow as pa
            return Col(jnp.zeros(cap, jnp.int32), jnp.zeros(cap, jnp.bool_), to,
                       pa.array([], type=pa.string()))
        return Col(jnp.full(cap, to.default_value(), dtype=to.jnp_dtype),
                   jnp.zeros(cap, jnp.bool_), to)

    if isinstance(frm, T.StringType):
        return _cast_from_string(c, to)
    if isinstance(to, T.StringType):
        return _cast_to_string(c)

    vals, validity = c.values, c.validity

    if isinstance(frm, T.BooleanType):
        out = vals.astype(to.jnp_dtype)
        return Col(out, validity, to).canonicalized()
    if isinstance(to, T.BooleanType):
        return Col(vals != 0, validity, to)

    if isinstance(frm, T.DateType) and isinstance(to, T.TimestampType):
        return Col(vals.astype(jnp.int64) * _MICROS_PER_DAY, validity, to).canonicalized()
    if isinstance(frm, T.TimestampType) and isinstance(to, T.DateType):
        return Col(jnp.floor_divide(vals, _MICROS_PER_DAY).astype(jnp.int32),
                   validity, to).canonicalized()
    if isinstance(frm, T.TimestampType) and isinstance(to, T.LongType):
        return Col(jnp.floor_divide(vals, 1_000_000), validity, to).canonicalized()
    if isinstance(frm, T.LongType) and isinstance(to, T.TimestampType):
        return Col(vals * 1_000_000, validity, to).canonicalized()

    if isinstance(frm, T.DecimalType) or isinstance(to, T.DecimalType):
        return _cast_decimal(c, to)

    if isinstance(frm, T.FractionalType) and isinstance(to, T.IntegralType):
        return Col(_float_to_integral(vals, to), validity, to).canonicalized()

    # integral→integral (wraps), integral→float, float↔double
    return Col(vals.astype(to.jnp_dtype), validity, to).canonicalized()


def _cast_decimal(c: Col, to: T.DataType) -> Col:
    frm = c.dtype
    vals, validity = c.values, c.validity
    if isinstance(frm, T.DecimalType) and isinstance(to, T.DecimalType):
        ds = to.scale - frm.scale
        if ds >= 0:
            out = vals * (10 ** ds)
        else:
            # Spark HALF_UP rounding on scale reduction: round magnitude, reapply sign
            div = 10 ** (-ds)
            mag = jnp.abs(vals)
            qm = mag // div
            rm = mag - qm * div
            qm = qm + (2 * rm >= div)
            out = jnp.where(vals < 0, -qm, qm)
        bound = 10 ** to.precision
        ok = (out < bound) & (out > -bound)
        return Col(out, validity & ok, to).canonicalized()
    if isinstance(frm, T.IntegralType) and isinstance(to, T.DecimalType):
        out = vals.astype(jnp.int64) * (10 ** to.scale)
        bound = 10 ** to.precision
        ok = (out < bound) & (out > -bound)
        return Col(out, validity & ok, to).canonicalized()
    if isinstance(frm, T.DecimalType) and isinstance(to, T.IntegralType):
        div = 10 ** frm.scale
        q = jnp.floor_divide(vals, div)
        rem = vals - q * div
        q = jnp.where((rem != 0) & (vals < 0), q + 1, q)  # truncate toward zero
        lo, hi = _INT_BOUNDS[type(to)]
        ok = (q >= lo) & (q <= hi)
        return Col(q.astype(to.jnp_dtype), validity & ok, to).canonicalized()
    if isinstance(frm, T.DecimalType) and isinstance(to, T.FractionalType):
        return Col((vals / (10 ** frm.scale)).astype(to.jnp_dtype), validity,
                   to).canonicalized()
    if isinstance(frm, T.FractionalType) and isinstance(to, T.DecimalType):
        scaled = vals.astype(jnp.float64) * (10 ** to.scale)
        nan = jnp.isnan(scaled)
        # HALF_UP on magnitude
        mag = jnp.abs(scaled)
        r = jnp.floor(mag + 0.5)
        out64 = jnp.where(scaled < 0, -r, r)
        bound = float(10 ** to.precision)
        ok = ~nan & (jnp.abs(out64) < bound)
        out = jnp.where(ok, out64, 0.0).astype(jnp.int64)
        return Col(out, validity & ok, to).canonicalized()
    raise TypeError(f"unsupported decimal cast {frm} -> {to}")


# ---------------------------------------------------------------------------
# string casts (host dictionary transforms — see ops/strings.py design note)
# ---------------------------------------------------------------------------

def _parse_integral(s: str, lo: int, hi: int):
    """Spark UTF8String.toLong-style: trim, optional sign, digits, allow fractional
    tail truncated toward zero; overflow/garbage → null."""
    s = s.strip()
    if not s:
        return None
    try:
        from decimal import Decimal, InvalidOperation
        v = Decimal(s)
        v = int(v.to_integral_value(rounding="ROUND_DOWN"))
    except (InvalidOperation, ValueError, ArithmeticError):
        return None
    if v < lo or v > hi:
        return None
    return v


def _parse_double(s: str):
    t = s.strip()
    if not t:
        return None
    low = t.lower()
    if low in ("nan",):
        return float("nan")
    if low in ("inf", "+inf", "infinity", "+infinity"):
        return float("inf")
    if low in ("-inf", "-infinity"):
        return float("-inf")
    try:
        if low.endswith(("d", "f")) and not low.endswith(("nd", "nf")):
            # Java Double.parseDouble accepts trailing D/F
            t = t[:-1]
        return float(t)
    except ValueError:
        return None


def _parse_date(s: str):
    """Spark DateTimeUtils.stringToDate subset: yyyy[-m[m][-d[d]]] with optional
    trailing time part after 'T' or ' '."""
    import datetime
    t = s.strip()
    for sep in ("T", " "):
        if sep in t:
            t = t.split(sep, 1)[0]
    parts = t.split("-")
    try:
        if len(parts) == 1:
            d = datetime.date(int(parts[0]), 1, 1)
        elif len(parts) == 2:
            d = datetime.date(int(parts[0]), int(parts[1]), 1)
        elif len(parts) == 3:
            d = datetime.date(int(parts[0]), int(parts[1]), int(parts[2]))
        else:
            return None
    except ValueError:
        return None
    return (d - datetime.date(1970, 1, 1)).days


_TS_RE = None


def _parse_timestamp(s: str):
    """Spark DateTimeUtils.stringToTimestamp ANSI subset (the 3.2+ shape):
    [+-]y+[-m[m][-d[d]]] with an optional [T or space][h]h:[m]m[:[s]s[.f+]]
    time part and an optional Z/UTC/±hh[:mm] zone. The engine is UTC-only;
    offsets shift into UTC. Returns epoch micros or None (Spark ANSI-off
    yields null for unparseable strings). Special datetime strings
    ('epoch', 'now', ...) are a 3.0/3.1-generation behavior handled at plan
    time (shims.special_datetime_strings); this parser never accepts
    them — the 3.2+ semantics (SPARK-35581)."""
    global _TS_RE
    import datetime
    import re
    if _TS_RE is None:
        _TS_RE = re.compile(
            r"^([+-]?\d{4,6})(?:-(\d{1,2})(?:-(\d{1,2})"
            r"(?:[ T](\d{1,2}):(\d{1,2})(?::(\d{1,2})(?:\.(\d{1,9}))?)?"
            r"\s*(Z|UTC|[+-]\d{1,2}(?::\d{1,2})?)?)?)?)?$")
    m = _TS_RE.match(s.strip())
    if not m:
        return None
    try:
        frac = (m[7] or "")[:6].ljust(6, "0")
        dt = datetime.datetime(int(m[1]), int(m[2] or 1), int(m[3] or 1),
                               int(m[4] or 0), int(m[5] or 0),
                               int(m[6] or 0), int(frac),
                               tzinfo=datetime.timezone.utc)
    except ValueError:
        return None
    off = 0
    if m[8] and m[8] not in ("Z", "UTC"):
        zm = re.match(r"([+-])(\d{1,2})(?::(\d{1,2}))?$", m[8])
        zh, zmin = int(zm[2]), int(zm[3] or 0)
        # Java ZoneOffset bounds: |offset| <= 18:00, minutes <= 59
        if zh > 18 or zmin > 59 or zh * 3600 + zmin * 60 > 18 * 3600:
            return None
        off = (zh * 3600 + zmin * 60) * (1 if zm[1] == "+" else -1)
    epoch = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
    return ((dt - epoch) // datetime.timedelta(microseconds=1)
            - off * 1_000_000)


def _parse_bool(s: str):
    t = s.strip().lower()
    if t in ("t", "true", "y", "yes", "1"):
        return True
    if t in ("f", "false", "n", "no", "0"):
        return False
    return None


def _cast_from_string(c: Col, to: T.DataType) -> Col:
    from spark_rapids_tpu.ops.strings import dict_transform_to_values
    if isinstance(to, T.IntegralType):
        lo, hi = _INT_BOUNDS[type(to)]
        return dict_transform_to_values(c, lambda s: _parse_integral(s, lo, hi), to)
    if isinstance(to, T.DoubleType) or isinstance(to, T.FloatType):
        def f(s):
            v = _parse_double(s)
            return v
        return dict_transform_to_values(c, f, to)
    if isinstance(to, T.BooleanType):
        return dict_transform_to_values(c, _parse_bool, to)
    if isinstance(to, T.DateType):
        return dict_transform_to_values(c, _parse_date, to)
    if isinstance(to, T.TimestampType):
        return dict_transform_to_values(c, _parse_timestamp, to)
    if isinstance(to, T.DecimalType):
        def fdec(s, sc=to.scale, p=to.precision):
            from decimal import Decimal, InvalidOperation, ROUND_HALF_UP
            try:
                v = Decimal(s.strip()).scaleb(sc).to_integral_value(ROUND_HALF_UP)
            except (InvalidOperation, ValueError, ArithmeticError):
                return None
            v = int(v)
            return v if -(10**p) < v < 10**p else None
        return dict_transform_to_values(c, fdec, to)
    raise TypeError(f"unsupported cast string -> {to}")


def _java_double_str(v: float) -> str:
    """Java Double.toString formatting (what Spark CAST(double AS STRING) emits)."""
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "Infinity" if v > 0 else "-Infinity"
    if v == 0:
        return "-0.0" if math.copysign(1, v) < 0 else "0.0"
    a = abs(v)
    if 1e-3 <= a < 1e7:
        s = repr(a)
        if "e" in s or "E" in s:
            s = f"{a:.17g}"
        if "." not in s:
            s += ".0"
    else:
        m, e = f"{a:.16E}".split("E")
        m = m.rstrip("0").rstrip(".")
        # recompute with python repr mantissa for shortest form
        sh = repr(a)
        if "e" in sh:
            m2, e2 = sh.split("e")
            m = m2.rstrip("0").rstrip(".") if "." in m2 else m2
            e = e2
        if "." not in m:
            m += ".0"
        s = f"{m}E{int(e)}"
    return "-" + s if v < 0 else s


def _java_float_str(v) -> str:
    """Java Float.toString: shortest decimal that round-trips the FLOAT value (the
    widened double would print spurious digits, e.g. 0.10000000149011612)."""
    f = np.float32(v)
    if np.isnan(f):
        return "NaN"
    if np.isinf(f):
        return "Infinity" if f > 0 else "-Infinity"
    if f == 0:
        return "-0.0" if np.signbit(f) else "0.0"
    # shortest decimal that round-trips the f32 value
    short = np.format_float_positional(abs(f), unique=True, trim="-")
    a = abs(f.item())
    if 1e-3 <= a < 1e7:
        s = short if "." in short else short + ".0"
    else:
        import math as _m
        e = _m.floor(_m.log10(a))
        digits = short.replace(".", "").lstrip("0").rstrip("0") or "0"
        s = digits[0] + ("." + digits[1:] if len(digits) > 1 else ".0") + f"E{e}"
    return "-" + s if f < 0 else s


def _cast_to_string(c: Col) -> Col:
    """Format via a host-built dictionary over the distinct values actually present."""
    frm = c.dtype
    n = int(c.values.shape[0])
    vals = np.asarray(c.values)
    valid = np.asarray(c.validity)

    if isinstance(frm, T.BooleanType):
        fmt = lambda v: "true" if v else "false"
    elif isinstance(frm, T.IntegralType):
        fmt = lambda v: str(int(v))
    elif isinstance(frm, T.DecimalType):
        def fmt(v, sc=frm.scale):
            from decimal import Decimal
            return str(Decimal(int(v)).scaleb(-sc).quantize(
                Decimal(1).scaleb(-sc)) if sc > 0 else Decimal(int(v)))
    elif isinstance(frm, T.DateType):
        import datetime
        fmt = lambda v: (datetime.date(1970, 1, 1)
                         + datetime.timedelta(days=int(v))).isoformat()
    elif isinstance(frm, T.TimestampType):
        import datetime
        def fmt(v):
            dt = (datetime.datetime(1970, 1, 1)
                  + datetime.timedelta(microseconds=int(v)))
            s = dt.strftime("%Y-%m-%d %H:%M:%S")
            if dt.microsecond:
                s += ("%.6f" % (dt.microsecond / 1e6))[1:].rstrip("0")
            return s
        fmt = fmt
    elif isinstance(frm, T.FloatType):
        fmt = _java_float_str
    elif isinstance(frm, T.DoubleType):
        fmt = lambda v: _java_double_str(float(v))
    else:
        raise TypeError(f"unsupported cast {frm} -> string")

    import pyarrow as pa
    uv, inv = np.unique(vals, return_inverse=True)
    strs = [fmt(v) for v in uv]
    uniq = sorted(set(strs))
    index = {s: i for i, s in enumerate(uniq)}
    code_of_uv = np.array([index[s] for s in strs], dtype=np.int32)
    codes = code_of_uv[inv.reshape(-1)]
    codes[~valid] = 0
    return Col(jnp.asarray(codes), c.validity, T.STRING, pa.array(uniq, type=pa.string()))


class Cast(Expression):
    def __init__(self, child: Expression, to: T.DataType):
        self.children = [child]
        self.to = to

    @property
    def dtype(self):
        return self.to

    def with_children(self, children):
        return Cast(children[0], self.to)

    def eval(self, ctx):
        return cast_col(self.children[0].eval(ctx), self.to)

    def __repr__(self):
        return f"cast({self.children[0]!r} AS {self.to})"
