"""Conditional expressions (reference conditionalExpressions.scala: GpuIf,
GpuCaseWhen)."""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression


def _common_type(types):
    from spark_rapids_tpu.expr.arithmetic import promote
    out = None
    for t in types:
        if isinstance(t, T.NullType):
            continue
        out = t if out is None else (promote(out, t) if out != t else out)
    return out or T.NULL


class If(Expression):
    def __init__(self, pred, then, other):
        self.children = [pred, then, other]

    @property
    def dtype(self):
        return _common_type([self.children[1].dtype, self.children[2].dtype])

    def with_children(self, children):
        return If(children[0], children[1], children[2])

    def eval(self, ctx):
        from spark_rapids_tpu.expr.arithmetic import _cast_col
        out_t = self.dtype
        if isinstance(out_t, T.StringType):
            from spark_rapids_tpu.ops.strings import if_strings
            p = self.children[0].eval(ctx)
            return if_strings(p, self.children[1].eval(ctx), self.children[2].eval(ctx))
        p = self.children[0].eval(ctx)
        a = _cast_col(self.children[1].eval(ctx), out_t)
        b = _cast_col(self.children[2].eval(ctx), out_t)
        take_a = p.values & p.validity  # null predicate → else branch (Spark)
        vals = jnp.where(take_a, a.values, b.values)
        validity = jnp.where(take_a, a.validity, b.validity)
        return Col(vals, validity, out_t).canonicalized()

    def __repr__(self):
        return f"if({self.children[0]!r}, {self.children[1]!r}, {self.children[2]!r})"


class CaseWhen(Expression):
    """CASE WHEN p1 THEN v1 ... ELSE e END. branches: [(pred, value), ...]."""

    def __init__(self, branches, else_value=None):
        self.branches = [(p, v) for p, v in branches]
        self.else_value = else_value
        self.children = [x for pv in self.branches for x in pv] + (
            [else_value] if else_value is not None else [])

    @property
    def dtype(self):
        ts = [v.dtype for _, v in self.branches]
        if self.else_value is not None:
            ts.append(self.else_value.dtype)
        return _common_type(ts)

    def with_children(self, children):
        n = len(self.branches)
        branches = [(children[2 * i], children[2 * i + 1]) for i in range(n)]
        ev = children[2 * n] if self.else_value is not None else None
        return CaseWhen(branches, ev)

    # pyspark Column chaining: F.when(p, v).when(p2, v2).otherwise(e)
    def when(self, cond, value) -> "CaseWhen":
        from spark_rapids_tpu.expr.core import _auto_lit, Expression
        c = cond if isinstance(cond, Expression) else _auto_lit(cond)
        v = value if isinstance(value, Expression) else _auto_lit(value)
        return CaseWhen(self.branches + [(c, v)], self.else_value)

    def otherwise(self, value) -> "CaseWhen":
        from spark_rapids_tpu.expr.core import _auto_lit, Expression
        v = value if isinstance(value, Expression) else _auto_lit(value)
        return CaseWhen(self.branches, v)

    def eval(self, ctx):
        # fold right-to-left into nested Ifs — identical semantics, shares code
        from spark_rapids_tpu.expr.core import Literal
        out = self.else_value if self.else_value is not None else Literal(None, self.dtype)
        for p, v in reversed(self.branches):
            out = If(p, v, out)
        return out.eval(ctx)

    def __repr__(self):
        bs = " ".join(f"WHEN {p!r} THEN {v!r}" for p, v in self.branches)
        return f"CASE {bs} ELSE {self.else_value!r} END"


class _LeastGreatest(Expression):
    """Spark least/greatest: skip nulls (null only when ALL inputs null);
    NaN orders greater than any number (reference conditionalExpressions.scala
    GpuLeast/GpuGreatest)."""

    def __init__(self, *children):
        self.children = list(children)

    @property
    def dtype(self):
        return _common_type([c.dtype for c in self.children])

    def with_children(self, children):
        return type(self)(*children)

    def eval(self, ctx):
        import jax.numpy as jnp
        from spark_rapids_tpu.expr.arithmetic import _cast_col
        out_t = self.dtype
        cols = [_cast_col(c.eval(ctx), out_t) for c in self.children]
        out = cols[0]
        for c in cols[1:]:
            better = self.prefer(c.values, out.values)
            take_c = c.validity & (~out.validity | better)
            vals = jnp.where(take_c, c.values, out.values)
            out = Col(vals, out.validity | c.validity, out_t)
        return out.canonicalized()

    @staticmethod
    def _lt(a, b):
        """a < b with Spark total order for floats: NaN greatest."""
        import jax.numpy as jnp
        if jnp.issubdtype(a.dtype, jnp.floating):
            return (a < b) | (jnp.isnan(b) & ~jnp.isnan(a))
        return a < b

    def __repr__(self):
        name = type(self).__name__.lower()
        return f"{name}({', '.join(map(repr, self.children))})"


class Least(_LeastGreatest):
    def prefer(self, cand, cur):
        return self._lt(cand, cur)


class Greatest(_LeastGreatest):
    def prefer(self, cand, cur):
        return self._lt(cur, cand)
