"""Null-handling expressions (reference nullExpressions.scala: GpuIsNull,
GpuIsNotNull, GpuCoalesce, GpuIsNan, GpuNaNvl, GpuNvl)."""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression


class IsNull(Expression):
    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return IsNull(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        live = ctx.row_mask()
        return Col(~c.validity & live, jnp.ones_like(c.validity), T.BOOLEAN)

    def __repr__(self):
        return f"isnull({self.children[0]!r})"


class IsNotNull(Expression):
    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return IsNotNull(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return Col(c.validity, jnp.ones_like(c.validity), T.BOOLEAN)

    def __repr__(self):
        return f"isnotnull({self.children[0]!r})"


class IsNaN(Expression):
    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return IsNaN(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return Col(jnp.isnan(c.values) & c.validity, jnp.ones_like(c.validity),
                   T.BOOLEAN)

    def __repr__(self):
        return f"isnan({self.children[0]!r})"


class Coalesce(Expression):
    """First non-null child value per row."""

    def __init__(self, *children):
        self.children = list(children)

    @property
    def dtype(self):
        from spark_rapids_tpu.expr.arithmetic import promote
        t = self.children[0].dtype
        for c in self.children[1:]:
            if not isinstance(c.dtype, T.NullType):
                t = c.dtype if isinstance(t, T.NullType) else promote(t, c.dtype)
        return t

    def with_children(self, children):
        return Coalesce(*children)

    def eval(self, ctx):
        from spark_rapids_tpu.expr.arithmetic import _cast_col
        out_t = self.dtype
        if isinstance(out_t, T.StringType):
            from spark_rapids_tpu.ops.strings import coalesce_strings
            return coalesce_strings([c.eval(ctx) for c in self.children])
        cols = [_cast_col(c.eval(ctx), out_t) for c in self.children]
        vals = cols[-1].values
        validity = cols[-1].validity
        for c in reversed(cols[:-1]):
            vals = jnp.where(c.validity, c.values, vals)
            validity = c.validity | validity
        return Col(vals, validity, out_t).canonicalized()

    def __repr__(self):
        return f"coalesce({', '.join(map(repr, self.children))})"


class NaNvl(Expression):
    """nanvl(a, b): a unless a is NaN, then b."""

    def __init__(self, left, right):
        self.children = [left, right]

    @property
    def dtype(self):
        from spark_rapids_tpu.expr.arithmetic import promote
        return promote(self.children[0].dtype, self.children[1].dtype)

    def with_children(self, children):
        return NaNvl(children[0], children[1])

    def eval(self, ctx):
        from spark_rapids_tpu.expr.arithmetic import _cast_col
        out_t = self.dtype
        l = _cast_col(self.children[0].eval(ctx), out_t)
        r = _cast_col(self.children[1].eval(ctx), out_t)
        use_r = jnp.isnan(l.values) & l.validity
        vals = jnp.where(use_r, r.values, l.values)
        validity = jnp.where(use_r, r.validity, l.validity)
        return Col(vals, validity, out_t).canonicalized()

    def __repr__(self):
        return f"nanvl({self.children[0]!r}, {self.children[1]!r})"


class AtLeastNNonNulls(Expression):
    """True when >= n children are non-null (and non-NaN for floats —
    Spark's DropNaN semantics; reference GpuOverrides expr[AtLeastNNonNulls],
    used by DataFrame.dropna)."""

    def __init__(self, n: int, *children):
        self.n = int(n)
        self.children = list(children)

    @property
    def dtype(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return AtLeastNNonNulls(self.n, *children)

    def eval(self, ctx):
        import jax.numpy as jnp
        count = jnp.zeros((ctx.capacity,), jnp.int32)
        for ch in self.children:
            c = ch.eval(ctx)
            ok = c.validity
            if isinstance(c.dtype, T.FractionalType):
                ok = ok & ~jnp.isnan(c.values)
            count = count + ok.astype(jnp.int32)
        from spark_rapids_tpu.expr.core import Col
        return Col(count >= self.n,
                   jnp.ones((ctx.capacity,), jnp.bool_), T.BOOLEAN)

    def __repr__(self):
        return f"atleastnnonnulls({self.n}, " + \
            ", ".join(map(repr, self.children)) + ")"
