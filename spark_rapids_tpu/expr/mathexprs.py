"""Math expressions (reference mathExpressions.scala, 447 LoC: GpuSqrt, GpuFloor,
GpuCeil, GpuRound, GpuExp, GpuLog, GpuPow, trig…). Spark specifics: floor/ceil of
double returns LONG; round is HALF_UP (Java BigDecimal), not banker's; log of
non-positive is null (Spark returns null, Java would return NaN)."""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression
from spark_rapids_tpu.expr.arithmetic import _cast_col


class _UnaryMath(Expression):
    """double → double elementwise."""

    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.DOUBLE

    def with_children(self, children):
        return type(self)(children[0])

    def eval(self, ctx):
        c = _cast_col(self.children[0].eval(ctx), T.DOUBLE)
        return Col(self.op(c.values), c.validity, T.DOUBLE).canonicalized()

    def op(self, v):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r})"


class Sqrt(_UnaryMath):
    def op(self, v):
        return jnp.sqrt(v)


class Exp(_UnaryMath):
    def op(self, v):
        return jnp.exp(v)


class Sin(_UnaryMath):
    def op(self, v):
        return jnp.sin(v)


class Cos(_UnaryMath):
    def op(self, v):
        return jnp.cos(v)


class Tan(_UnaryMath):
    def op(self, v):
        return jnp.tan(v)


class Asin(_UnaryMath):
    def op(self, v):
        return jnp.arcsin(v)


class Acos(_UnaryMath):
    def op(self, v):
        return jnp.arccos(v)


class Atan(_UnaryMath):
    def op(self, v):
        return jnp.arctan(v)


class Cbrt(_UnaryMath):
    def op(self, v):
        return jnp.cbrt(v)


class Signum(_UnaryMath):
    def op(self, v):
        return jnp.sign(v)


class ToDegrees(_UnaryMath):
    def op(self, v):
        return jnp.degrees(v)


class ToRadians(_UnaryMath):
    def op(self, v):
        return jnp.radians(v)


class Log(Expression):
    """ln(x); Spark returns null for x <= 0 (not NaN)."""

    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.DOUBLE

    def with_children(self, children):
        return type(self)(children[0])

    def eval(self, ctx):
        c = _cast_col(self.children[0].eval(ctx), T.DOUBLE)
        ok = c.values > 0
        vals = jnp.log(jnp.where(ok, c.values, 1.0))
        return Col(self.post(vals), c.validity & ok, T.DOUBLE).canonicalized()

    def post(self, v):
        return v

    def __repr__(self):
        return f"log({self.children[0]!r})"


class Log2(Log):
    def post(self, v):
        return v / jnp.log(2.0)


class Log10(Log):
    def post(self, v):
        return v / jnp.log(10.0)


class Log1p(Expression):
    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.DOUBLE

    def with_children(self, children):
        return Log1p(children[0])

    def eval(self, ctx):
        c = _cast_col(self.children[0].eval(ctx), T.DOUBLE)
        ok = c.values > -1
        vals = jnp.log1p(jnp.where(ok, c.values, 0.0))
        return Col(vals, c.validity & ok, T.DOUBLE).canonicalized()


class Pow(Expression):
    def __init__(self, left, right):
        self.children = [left, right]

    @property
    def dtype(self):
        return T.DOUBLE

    def with_children(self, children):
        return Pow(children[0], children[1])

    def eval(self, ctx):
        l = _cast_col(self.children[0].eval(ctx), T.DOUBLE)
        r = _cast_col(self.children[1].eval(ctx), T.DOUBLE)
        validity = l.validity & r.validity
        return Col(jnp.power(l.values, r.values), validity, T.DOUBLE).canonicalized()

    def __repr__(self):
        return f"pow({self.children[0]!r}, {self.children[1]!r})"


class Atan2(Expression):
    def __init__(self, left, right):
        self.children = [left, right]

    @property
    def dtype(self):
        return T.DOUBLE

    def with_children(self, children):
        return Atan2(children[0], children[1])

    def eval(self, ctx):
        l = _cast_col(self.children[0].eval(ctx), T.DOUBLE)
        r = _cast_col(self.children[1].eval(ctx), T.DOUBLE)
        return Col(jnp.arctan2(l.values, r.values), l.validity & r.validity,
                   T.DOUBLE).canonicalized()


class Floor(Expression):
    """floor(double) → LONG in Spark (decimal floor keeps decimal)."""

    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        ct = self.children[0].dtype
        if isinstance(ct, T.DecimalType):
            return T.DecimalType(ct.precision, 0)
        if isinstance(ct, T.IntegralType):
            return ct
        return T.LONG

    def with_children(self, children):
        return type(self)(children[0])

    def eval(self, ctx):
        ct = self.children[0].dtype
        c = self.children[0].eval(ctx)
        if isinstance(ct, T.IntegralType):
            return c
        if isinstance(ct, T.DecimalType):
            div = 10 ** ct.scale
            q = jnp.floor_divide(c.values, div)
            return Col(q, c.validity, self.dtype).canonicalized()
        from spark_rapids_tpu.expr.cast import _float_to_integral
        v = self.round_op(_cast_col(c, T.DOUBLE).values)
        return Col(_float_to_integral(v, T.LONG), c.validity, T.LONG).canonicalized()

    def round_op(self, v):
        return jnp.floor(v)

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r})"


class Ceil(Floor):
    def round_op(self, v):
        return jnp.ceil(v)

    def eval(self, ctx):
        ct = self.children[0].dtype
        if isinstance(ct, T.DecimalType):
            c = self.children[0].eval(ctx)
            div = 10 ** ct.scale
            q = -jnp.floor_divide(-c.values, div)
            return Col(q, c.validity, self.dtype).canonicalized()
        return super().eval(ctx)


class Round(Expression):
    """round(x, d) HALF_UP (Spark/Hive), unlike numpy's banker's rounding."""

    def __init__(self, child, digits: int = 0):
        self.children = [child]
        self.digits = digits

    @property
    def dtype(self):
        ct = self.children[0].dtype
        if isinstance(ct, (T.IntegralType, T.DecimalType)):
            return ct
        return ct  # float/double keep their type

    def with_children(self, children):
        return Round(children[0], self.digits)

    def eval(self, ctx):
        ct = self.children[0].dtype
        c = self.children[0].eval(ctx)
        d = self.digits
        if isinstance(ct, T.IntegralType):
            if d >= 0:
                return c
            div = 10 ** (-d)
            # widen to int64: the +div//2 step must not overflow the narrow
            # type mid-computation; the final astype wraps like Java intValue
            mag = jnp.abs(c.values.astype(jnp.int64))
            qm = (mag + div // 2) // div * div
            return Col(jnp.where(c.values < 0, -qm, qm).astype(c.values.dtype),
                       c.validity, ct).canonicalized()
        if isinstance(ct, T.DecimalType):
            ds = ct.scale - d
            if ds <= 0:
                return c
            div = 10 ** ds
            mag = jnp.abs(c.values)
            qm = (mag + div // 2) // div * div
            return Col(jnp.where(c.values < 0, -qm, qm), c.validity, ct).canonicalized()
        scale = 10.0 ** d
        v = c.values * scale
        mag = jnp.floor(jnp.abs(v) + 0.5)
        out = jnp.where(v < 0, -mag, mag) / scale
        return Col(out.astype(c.values.dtype), c.validity, ct).canonicalized()

    def __repr__(self):
        return f"round({self.children[0]!r}, {self.digits})"


class Sinh(_UnaryMath):
    def op(self, v):
        return jnp.sinh(v)


class Cosh(_UnaryMath):
    def op(self, v):
        return jnp.cosh(v)


class Tanh(_UnaryMath):
    def op(self, v):
        return jnp.tanh(v)


class Asinh(_UnaryMath):
    def op(self, v):
        return jnp.arcsinh(v)


class Acosh(_UnaryMath):
    def op(self, v):
        return jnp.arccosh(v)


class Atanh(_UnaryMath):
    def op(self, v):
        return jnp.arctanh(v)


class Expm1(_UnaryMath):
    def op(self, v):
        return jnp.expm1(v)


class Rint(_UnaryMath):
    """Java Math.rint: round-half-even to a double."""

    def op(self, v):
        return jnp.round(v)


class Cot(_UnaryMath):
    """cot(x) = cos/sin (reference GpuOverrides expr[Cot])."""

    def op(self, v):
        return jnp.cos(v) / jnp.sin(v)


class Logarithm(Expression):
    """log(base, x) — null for x <= 0 or base <= 0 (Spark)."""

    def __init__(self, base, child):
        self.children = [base, child]

    @property
    def dtype(self):
        return T.DOUBLE

    def with_children(self, children):
        return Logarithm(children[0], children[1])

    def eval(self, ctx):
        b = _cast_col(self.children[0].eval(ctx), T.DOUBLE)
        c = _cast_col(self.children[1].eval(ctx), T.DOUBLE)
        ok = (c.values > 0) & (b.values > 0)
        vals = jnp.log(jnp.where(c.values > 0, c.values, 1.0)) / \
            jnp.log(jnp.where(b.values > 0, b.values, 2.0))
        return Col(vals, b.validity & c.validity & ok,
                   T.DOUBLE).canonicalized()

    def __repr__(self):
        return f"log({self.children[0]!r}, {self.children[1]!r})"


class BRound(Expression):
    """bround(x, d) — HALF_EVEN (banker's) rounding (reference GpuBRound,
    mathExpressions.scala). Floats use jnp.round (IEEE half-even); integral
    and decimal inputs round the quotient to the nearest even multiple."""

    def __init__(self, child, digits: int = 0):
        self.children = [child]
        self.digits = digits

    @property
    def dtype(self):
        return self.children[0].dtype

    def with_children(self, children):
        return BRound(children[0], self.digits)

    def _half_even_div(self, v, div):
        """Round v/div half-even, returning the rounded MULTIPLE (int64)."""
        q = jnp.floor_divide(v, div)
        rem = v - q * div
        twice = rem * 2
        up = (twice > div) | ((twice == div) & (q % 2 != 0))
        return (q + up.astype(q.dtype)) * div

    def eval(self, ctx):
        ct = self.children[0].dtype
        c = self.children[0].eval(ctx)
        d = self.digits
        if isinstance(ct, T.IntegralType):
            if d >= 0:
                return c
            v = c.values.astype(jnp.int64)
            out = self._half_even_div(v, 10 ** (-d))
            # narrow types wrap like Java's intValue/byteValue (Spark
            # non-ANSI; the host oracle applies the same _wrap_int)
            return Col(out.astype(c.values.dtype), c.validity,
                       ct).canonicalized()
        if isinstance(ct, T.DecimalType):
            ds = ct.scale - d
            if ds <= 0:
                return c
            out = self._half_even_div(c.values, 10 ** ds)
            return Col(out, c.validity, ct).canonicalized()
        # float/double: device path is digits == 0 only (the planner tags
        # other digits to host) — at scale 1 jnp.round's binary half-even
        # equals Spark's decimal-string HALF_EVEN, because every exactly-
        # representable .5 tie is also a decimal-string tie; at other scales
        # the binary product turns decimal ties into non-ties and diverges
        out = jnp.round(c.values)
        return Col(out.astype(c.values.dtype), c.validity, ct).canonicalized()

    def __repr__(self):
        return f"bround({self.children[0]!r}, {self.digits})"
