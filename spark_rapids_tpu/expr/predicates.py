"""Comparison and boolean predicates with Spark three-valued logic.

Reference: sql-plugin/.../org/apache/spark/sql/rapids/predicates.scala (631 LoC):
GpuEqualTo/GpuLessThan/... map to cudf comparators; GpuAnd/GpuOr implement Kleene
logic (false AND null = false, true OR null = true); GpuEqualNullSafe (<=>).

Spark float comparison details honored here (reference GpuGreaterThan etc. rely on
cudf NaN handling + spark.rapids.sql.hasNans): NaN == NaN is TRUE in Spark, and NaN is
greater than every other value. -0.0 == 0.0.

String comparisons run over dictionary codes after aligning both sides onto one sorted
union dictionary (order-preserving), so <,= on codes equals the string comparison.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression, valid_and
from spark_rapids_tpu.expr.arithmetic import promote, _cast_col


def align_strings(l: Col, r: Col):
    """Remap two string Cols onto a shared sorted dictionary (host union + device
    gather). Order-preserving, so code comparisons == string comparisons."""
    from spark_rapids_tpu.ops.strings import union_dictionaries
    return union_dictionaries(l, r)


def _comparable(l: Col, r: Col, ldt: T.DataType, rdt: T.DataType):
    if isinstance(ldt, T.StringType) and isinstance(rdt, T.StringType):
        return align_strings(l, r)
    if ldt == rdt:
        return l, r
    ct = promote(ldt, rdt)
    return _cast_col(l, ct), _cast_col(r, ct)


def _float_total(lv, rv, op):
    """Comparison with Spark NaN semantics: NaN equals NaN and sorts above +inf."""
    l_nan = jnp.isnan(lv)
    r_nan = jnp.isnan(rv)
    if op == "eq":
        return jnp.where(l_nan & r_nan, True, lv == rv)
    if op == "lt":
        return jnp.where(l_nan, False, jnp.where(r_nan, True, lv < rv))
    if op == "le":
        return jnp.where(l_nan, r_nan, jnp.where(r_nan, True, lv <= rv))
    raise AssertionError(op)


class BinaryComparison(Expression):
    symbol = "?"

    def __init__(self, left, right):
        self.children = [left, right]

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    @property
    def dtype(self):
        return T.BOOLEAN

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def eval(self, ctx):
        l, r = self.left.eval(ctx), self.right.eval(ctx)
        l, r = _comparable(l, r, self.left.dtype, self.right.dtype)
        validity = valid_and(l.validity, r.validity)
        vals = self.compare(l.values, r.values, isinstance(l.dtype, T.FractionalType))
        return Col(vals & validity, validity, T.BOOLEAN)

    def compare(self, lv, rv, is_float):
        raise NotImplementedError

    def __repr__(self):
        return f"({self.left!r} {self.symbol} {self.right!r})"


class EqualTo(BinaryComparison):
    symbol = "="

    def compare(self, lv, rv, is_float):
        return _float_total(lv, rv, "eq") if is_float else lv == rv


class LessThan(BinaryComparison):
    symbol = "<"

    def compare(self, lv, rv, is_float):
        return _float_total(lv, rv, "lt") if is_float else lv < rv


class LessThanOrEqual(BinaryComparison):
    symbol = "<="

    def compare(self, lv, rv, is_float):
        return _float_total(lv, rv, "le") if is_float else lv <= rv


class GreaterThan(BinaryComparison):
    symbol = ">"

    def compare(self, lv, rv, is_float):
        return _float_total(rv, lv, "lt") if is_float else lv > rv


class GreaterThanOrEqual(BinaryComparison):
    symbol = ">="

    def compare(self, lv, rv, is_float):
        return _float_total(rv, lv, "le") if is_float else lv >= rv


class NotEqual(BinaryComparison):
    symbol = "!="

    def compare(self, lv, rv, is_float):
        eq = _float_total(lv, rv, "eq") if is_float else lv == rv
        return ~eq


class EqualNullSafe(BinaryComparison):
    """<=>: null <=> null is TRUE, never returns null."""
    symbol = "<=>"

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        l, r = self.left.eval(ctx), self.right.eval(ctx)
        l, r = _comparable(l, r, self.left.dtype, self.right.dtype)
        both_valid = valid_and(l.validity, r.validity)
        both_null = ~l.validity & ~r.validity
        if isinstance(l.dtype, T.FractionalType):
            eq = _float_total(l.values, r.values, "eq")
        else:
            eq = l.values == r.values
        vals = (both_valid & eq) | both_null
        return Col(vals, jnp.ones_like(vals), T.BOOLEAN)


class And(Expression):
    """Kleene AND: F & x = F; T & null = null."""

    def __init__(self, left, right):
        self.children = [left, right]

    @property
    def dtype(self):
        return T.BOOLEAN

    def with_children(self, children):
        return And(children[0], children[1])

    def eval(self, ctx):
        l = self.children[0].eval(ctx)
        r = self.children[1].eval(ctx)
        lv = l.values & l.validity
        rv = r.values & r.validity
        false_l = l.validity & ~l.values
        false_r = r.validity & ~r.values
        vals = lv & rv
        validity = (l.validity & r.validity) | false_l | false_r
        return Col(vals & validity, validity, T.BOOLEAN)

    def __repr__(self):
        return f"({self.children[0]!r} AND {self.children[1]!r})"


class Or(Expression):
    """Kleene OR: T | x = T; F | null = null."""

    def __init__(self, left, right):
        self.children = [left, right]

    @property
    def dtype(self):
        return T.BOOLEAN

    def with_children(self, children):
        return Or(children[0], children[1])

    def eval(self, ctx):
        l = self.children[0].eval(ctx)
        r = self.children[1].eval(ctx)
        true_l = l.validity & l.values
        true_r = r.validity & r.values
        vals = true_l | true_r
        validity = (l.validity & r.validity) | true_l | true_r
        return Col(vals & validity, validity, T.BOOLEAN)

    def __repr__(self):
        return f"({self.children[0]!r} OR {self.children[1]!r})"


class Not(Expression):
    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.BOOLEAN

    def with_children(self, children):
        return Not(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return Col(~c.values & c.validity, c.validity, T.BOOLEAN)

    def __repr__(self):
        return f"(NOT {self.children[0]!r})"


class In(Expression):
    """IN over a literal list (reference GpuInSet). Null semantics: x IN (...) is null
    if x is null, or if no match and the list contains null."""

    def __init__(self, child, values: list):
        self.children = [child]
        self.values = values

    @property
    def dtype(self):
        return T.BOOLEAN

    def with_children(self, children):
        return In(children[0], self.values)

    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import Literal
        c = self.children[0].eval(ctx)
        has_null = any(v is None for v in self.values)
        non_null = [v for v in self.values if v is not None]
        match = jnp.zeros_like(c.validity)
        for v in non_null:
            lc = Literal(v, self.children[0].dtype).eval(ctx)
            if c.is_string:
                l2, r2 = _comparable(c, lc, c.dtype, lc.dtype)
                match = match | (l2.values == r2.values)
            else:
                match = match | (c.values == lc.values)
        validity = c.validity & (match | (~jnp.full_like(match, has_null)))
        return Col(match & validity, validity, T.BOOLEAN)

    def __repr__(self):
        return f"({self.children[0]!r} IN {self.values!r})"


class InSet(In):
    """Optimized literal-set membership (reference GpuInSet) — same device
    evaluation as In; Spark plans InSet when the list exceeds the
    optimizer threshold."""

    def __init__(self, child, values):
        super().__init__(child, sorted(values, key=lambda v: (v is None,
                                                              repr(v))))
