"""Date/time expressions (reference datetimeExpressions.scala, 845 LoC: GpuYear,
GpuMonth, GpuDayOfMonth, GpuDateAdd/Sub, GpuDateDiff, GpuHour/Minute/Second…).

All pure integer arithmetic on Spark's internal representations (date = int32 days,
timestamp = int64 micros UTC), using Howard Hinnant's civil-from-days algorithm in
jax ops — exact over the full range, fully fused into stage programs."""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression
from spark_rapids_tpu.expr.arithmetic import _cast_col, valid_and

_MICROS_PER_DAY = 86_400_000_000


def civil_from_days(z):
    """days-since-epoch → (year, month, day), Hinnant's algorithm in int32/int64."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097                                   # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)          # [0, 365]
    mp = (5 * doy + 2) // 153                                # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                        # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)                   # [1, 12]
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def _date_col(expr_dtype, col):
    """Days value for either DateType or TimestampType input."""
    if isinstance(expr_dtype, T.TimestampType):
        return jnp.floor_divide(col.values, _MICROS_PER_DAY).astype(jnp.int32)
    return col.values


class _DatePart(Expression):
    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.INT

    def with_children(self, children):
        return type(self)(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        days = _date_col(self.children[0].dtype, c)
        y, m, d = civil_from_days(days)
        return Col(self.pick(y, m, d, days), c.validity, T.INT).canonicalized()

    def pick(self, y, m, d, days):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r})"


class Year(_DatePart):
    def pick(self, y, m, d, days):
        return y


class Month(_DatePart):
    def pick(self, y, m, d, days):
        return m


class DayOfMonth(_DatePart):
    def pick(self, y, m, d, days):
        return d


class DayOfWeek(_DatePart):
    """Spark dayofweek: 1 = Sunday … 7 = Saturday. 1970-01-01 was a Thursday."""

    def pick(self, y, m, d, days):
        return ((days + 4) % 7 + 7) % 7 + 1


class WeekDay(_DatePart):
    """Spark weekday: 0 = Monday … 6 = Sunday."""

    def pick(self, y, m, d, days):
        return ((days + 3) % 7 + 7) % 7


class DayOfYear(_DatePart):
    def pick(self, y, m, d, days):
        jan1 = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        return (days - jan1 + 1).astype(jnp.int32)


class Quarter(_DatePart):
    def pick(self, y, m, d, days):
        return (m - 1) // 3 + 1


class LastDay(Expression):
    """last_day(date): last day of that month."""

    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.DATE

    def with_children(self, children):
        return LastDay(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        days = _date_col(self.children[0].dtype, c)
        y, m, _ = civil_from_days(days)
        ny = jnp.where(m == 12, y + 1, y)
        nm = jnp.where(m == 12, 1, m + 1)
        first_next = days_from_civil(ny, nm, jnp.ones_like(nm))
        return Col((first_next - 1).astype(jnp.int32), c.validity, T.DATE).canonicalized()


def days_from_civil(y, m, d):
    """(year, month, day) → days-since-epoch (Hinnant)."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9).astype(jnp.int64)
    doy = (153 * mp + 2) // 5 + d.astype(jnp.int64) - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


class _TimePart(Expression):
    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.INT

    def with_children(self, children):
        return type(self)(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        micros_in_day = c.values - jnp.floor_divide(
            c.values, _MICROS_PER_DAY) * _MICROS_PER_DAY
        return Col(self.pick(micros_in_day).astype(jnp.int32), c.validity,
                   T.INT).canonicalized()

    def pick(self, mid):
        raise NotImplementedError


class Hour(_TimePart):
    def pick(self, mid):
        return mid // 3_600_000_000


class Minute(_TimePart):
    def pick(self, mid):
        return (mid // 60_000_000) % 60


class Second(_TimePart):
    def pick(self, mid):
        return (mid // 1_000_000) % 60


class DateAdd(Expression):
    def __init__(self, date, delta):
        self.children = [date, delta]

    @property
    def dtype(self):
        return T.DATE

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def eval(self, ctx):
        d = self.children[0].eval(ctx)
        n = _cast_col(self.children[1].eval(ctx), T.INT)
        days = _date_col(self.children[0].dtype, d)
        return Col(self.op(days, n.values), valid_and(d.validity, n.validity),
                   T.DATE).canonicalized()

    def op(self, days, n):
        return days + n

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r}, {self.children[1]!r})"


class DateSub(DateAdd):
    def op(self, days, n):
        return days - n


class DateDiff(Expression):
    def __init__(self, end, start):
        self.children = [end, start]

    @property
    def dtype(self):
        return T.INT

    def with_children(self, children):
        return DateDiff(children[0], children[1])

    def eval(self, ctx):
        e = self.children[0].eval(ctx)
        s = self.children[1].eval(ctx)
        ed = _date_col(self.children[0].dtype, e)
        sd = _date_col(self.children[1].dtype, s)
        return Col(ed - sd, valid_and(e.validity, s.validity), T.INT).canonicalized()


class UnixTimestampSeconds(Expression):
    """unix_timestamp(ts): seconds since epoch (floor)."""

    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.LONG

    def with_children(self, children):
        return UnixTimestampSeconds(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return Col(jnp.floor_divide(c.values, 1_000_000), c.validity,
                   T.LONG).canonicalized()


# ---------------------------------------------------------------------------
# Parse/format (reference datetimeExpressions.scala: GpuUnixTimestamp,
# GpuFromUnixTime, GpuDateFormatClass — cudf strftime/strptime; here the format
# runs through a host-built dictionary over distinct values, ops/strings.py)
# ---------------------------------------------------------------------------

_JAVA_FMT = [  # longest-match-first Java SimpleDateFormat → strftime tokens
    ("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"), ("HH", "%H"),
    ("mm", "%M"), ("ss", "%S"), ("EEEE", "%A"), ("EEE", "%a"), ("a", "%p"),
    ("DDD", "%j"), ("hh", "%I"),
]

DEFAULT_TS_FMT = "yyyy-MM-dd HH:mm:ss"


def java_fmt_to_strftime(fmt: str) -> str:
    """Common-subset SimpleDateFormat → strftime; raises ValueError on tokens
    outside the subset (the planner tags those to fall back to host)."""
    out = []
    i = 0
    while i < len(fmt):
        if fmt[i] == "'":  # java literal quoting
            j = fmt.index("'", i + 1) if "'" in fmt[i + 1:] else len(fmt)
            out.append(fmt[i + 1:j].replace("%", "%%"))
            i = j + 1
            continue
        for tok, rep in _JAVA_FMT:
            if fmt.startswith(tok, i):
                out.append(rep)
                i += len(tok)
                break
        else:
            ch = fmt[i]
            if ch.isalpha():
                raise ValueError(f"unsupported datetime format token {ch!r}")
            out.append("%%" if ch == "%" else ch)
            i += 1
    return "".join(out)


def _epoch_dt(micros: int):
    import datetime
    return (datetime.datetime(1970, 1, 1)
            + datetime.timedelta(microseconds=int(micros)))


class _ToUnixSeconds(Expression):
    """unix_timestamp / to_unix_timestamp over timestamp, date, or string
    input (string parses with the literal Java format; bad parses → null)."""

    def __init__(self, child, fmt=None):
        from spark_rapids_tpu.expr.core import Literal as L
        self.children = [child, fmt if fmt is not None
                         else L(DEFAULT_TS_FMT, T.STRING)]

    @property
    def dtype(self):
        return T.LONG

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import Literal
        from spark_rapids_tpu.ops.strings import dict_transform_to_values
        fe = self.children[1]
        assert isinstance(fe, Literal), "format must be a literal"
        c = self.children[0].eval(ctx)
        src = self.children[0].dtype
        if isinstance(src, T.TimestampType):
            return Col(jnp.floor_divide(c.values, 1_000_000), c.validity,
                       T.LONG).canonicalized()
        if isinstance(src, T.DateType):
            return Col(c.values.astype(jnp.int64) * 86_400, c.validity,
                       T.LONG).canonicalized()
        assert isinstance(src, T.StringType), src
        import datetime
        pyfmt = java_fmt_to_strftime(fe.value)

        def parse(s):
            try:
                dt = datetime.datetime.strptime(s, pyfmt)
            except (ValueError, TypeError):
                return None
            return int((dt - datetime.datetime(1970, 1, 1)).total_seconds())
        return dict_transform_to_values(c, parse, T.LONG)

    def __repr__(self):
        return (f"{type(self).__name__.lower()}({self.children[0]!r}, "
                f"{self.children[1]!r})")


class UnixTimestamp(_ToUnixSeconds):
    pass


class ToUnixTimestamp(_ToUnixSeconds):
    pass


class FromUnixTime(Expression):
    """from_unixtime(seconds, fmt) → formatted string (UTC session zone)."""

    def __init__(self, child, fmt=None):
        from spark_rapids_tpu.expr.core import Literal as L
        self.children = [child, fmt if fmt is not None
                         else L(DEFAULT_TS_FMT, T.STRING)]

    @property
    def dtype(self):
        return T.STRING

    def with_children(self, children):
        return FromUnixTime(children[0], children[1])

    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import Literal
        from spark_rapids_tpu.expr.arithmetic import _cast_col
        from spark_rapids_tpu.ops.strings import value_transform_to_string
        fe = self.children[1]
        assert isinstance(fe, Literal), "format must be a literal"
        pyfmt = java_fmt_to_strftime(fe.value)
        c = _cast_col(self.children[0].eval(ctx), T.LONG)
        return value_transform_to_string(
            c, lambda sec: _epoch_dt(int(sec) * 1_000_000).strftime(pyfmt))

    def __repr__(self):
        return f"from_unixtime({self.children[0]!r}, {self.children[1]!r})"


class DateFormatClass(Expression):
    """date_format(ts|date, fmt) → string."""

    def __init__(self, child, fmt):
        self.children = [child, fmt]

    @property
    def dtype(self):
        return T.STRING

    def with_children(self, children):
        return DateFormatClass(children[0], children[1])

    def eval(self, ctx):
        import datetime
        from spark_rapids_tpu.expr.core import Literal
        from spark_rapids_tpu.ops.strings import value_transform_to_string
        fe = self.children[1]
        assert isinstance(fe, Literal), "format must be a literal"
        pyfmt = java_fmt_to_strftime(fe.value)
        c = self.children[0].eval(ctx)
        if isinstance(self.children[0].dtype, T.DateType):
            fmt = lambda d: (datetime.date(1970, 1, 1)
                             + datetime.timedelta(days=int(d))).strftime(pyfmt)
        else:
            fmt = lambda us: _epoch_dt(us).strftime(pyfmt)
        return value_transform_to_string(c, fmt)

    def __repr__(self):
        return f"date_format({self.children[0]!r}, {self.children[1]!r})"


class AddMonths(Expression):
    """add_months(date, n): calendar month add, day clamped to month end."""

    def __init__(self, date, months):
        self.children = [date, months]

    @property
    def dtype(self):
        return T.DATE

    def with_children(self, children):
        return AddMonths(children[0], children[1])

    def eval(self, ctx):
        from spark_rapids_tpu.expr.arithmetic import _cast_col
        from spark_rapids_tpu.expr.core import valid_and
        d = self.children[0].eval(ctx)
        n = _cast_col(self.children[1].eval(ctx), T.INT)
        days = _date_col(self.children[0].dtype, d)
        y, m, dom = civil_from_days(days)
        total = (y * 12 + (m - 1) + n.values).astype(jnp.int64)
        ny = jnp.floor_divide(total, 12)
        nm = total - ny * 12 + 1
        # clamp day-of-month to the target month's length
        month_start = days_from_civil(ny, nm, jnp.ones_like(nm))
        ny2 = jnp.where(nm == 12, ny + 1, ny)
        nm2 = jnp.where(nm == 12, 1, nm + 1)
        month_len = days_from_civil(ny2, nm2, jnp.ones_like(nm)) - month_start
        nd = jnp.minimum(dom, month_len)
        out = days_from_civil(ny, nm, nd)
        return Col(out.astype(jnp.int32), valid_and(d.validity, n.validity),
                   T.DATE).canonicalized()

    def __repr__(self):
        return f"add_months({self.children[0]!r}, {self.children[1]!r})"


class MonthsBetween(Expression):
    """months_between(d1, d2[, roundOff]): whole months plus a /31-day
    fraction, zero fraction when both are month-ends or the same day-of-month
    (Spark semantics, date inputs)."""

    def __init__(self, end, start, round_off=True):
        self.children = [end, start]
        self.round_off = round_off

    @property
    def dtype(self):
        return T.DOUBLE

    def with_children(self, children):
        return MonthsBetween(children[0], children[1], self.round_off)

    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import valid_and
        e = self.children[0].eval(ctx)
        s = self.children[1].eval(ctx)
        ed = _date_col(self.children[0].dtype, e)
        sd = _date_col(self.children[1].dtype, s)
        ey, em, edom = civil_from_days(ed)
        sy, sm, sdom = civil_from_days(sd)

        def month_len(y, m):
            start = days_from_civil(y, m, jnp.ones_like(m))
            y2 = jnp.where(m == 12, y + 1, y)
            m2 = jnp.where(m == 12, 1, m + 1)
            return days_from_civil(y2, m2, jnp.ones_like(m)) - start

        both_last = (edom == month_len(ey, em)) & (sdom == month_len(sy, sm))
        months = ((ey - sy) * 12 + (em - sm)).astype(jnp.float64)
        frac = jnp.where(both_last | (edom == sdom), 0.0,
                         (edom - sdom).astype(jnp.float64) / 31.0)
        out = months + frac
        if self.round_off:
            out = jnp.round(out * 1e8) / 1e8
        return Col(out, valid_and(e.validity, s.validity),
                   T.DOUBLE).canonicalized()

    def __repr__(self):
        return f"months_between({self.children[0]!r}, {self.children[1]!r})"


class TruncDate(Expression):
    """trunc(date, 'year'|'month'|'quarter'|'week') → date (bad fmt → null)."""

    def __init__(self, date, fmt):
        self.children = [date, fmt]

    @property
    def dtype(self):
        return T.DATE

    def with_children(self, children):
        return TruncDate(children[0], children[1])

    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import Literal
        fe = self.children[1]
        assert isinstance(fe, Literal), "trunc format must be a literal"
        lvl = (fe.value or "").lower()
        d = self.children[0].eval(ctx)
        days = _date_col(self.children[0].dtype, d)
        y, m, _dom = civil_from_days(days)
        if lvl in ("year", "yyyy", "yy"):
            out = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(m))
        elif lvl in ("month", "mon", "mm"):
            out = days_from_civil(y, m, jnp.ones_like(m))
        elif lvl == "quarter":
            qm = ((m - 1) // 3) * 3 + 1
            out = days_from_civil(y, qm, jnp.ones_like(m))
        elif lvl == "week":  # Monday start; epoch day 0 = Thursday
            out = days - ((days + 3) % 7)
        else:
            return Col(jnp.zeros_like(days), jnp.zeros_like(d.validity),
                       T.DATE)
        return Col(out.astype(jnp.int32), d.validity, T.DATE).canonicalized()

    def __repr__(self):
        return f"trunc({self.children[0]!r}, {self.children[1]!r})"


class TimeAdd(Expression):
    """timestamp + literal interval (reference GpuTimeAdd,
    datetimeExpressions.scala): only microsecond-precision intervals
    without a months component run on device — the planner tags months
    intervals onto the host, same limit as the reference."""

    def __init__(self, ts, interval_us):
        self.children = [ts, interval_us]

    @property
    def dtype(self):
        return T.TIMESTAMP

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def eval(self, ctx):
        t = self.children[0].eval(ctx)
        us = _cast_col(self.children[1].eval(ctx), T.LONG)
        return Col(t.values + us.values,
                   valid_and(t.validity, us.validity),
                   T.TIMESTAMP).canonicalized()

    def __repr__(self):
        return f"timeadd({self.children[0]!r}, {self.children[1]!r})"


class DateAddInterval(Expression):
    """date + literal interval in whole days (reference GpuDateAddInterval:
    month components and sub-day remainders fall back, matching its
    tagging)."""

    def __init__(self, date, days):
        self.children = [date, days]

    @property
    def dtype(self):
        return T.DATE

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def eval(self, ctx):
        d = self.children[0].eval(ctx)
        n = _cast_col(self.children[1].eval(ctx), T.INT)
        days = _date_col(self.children[0].dtype, d)
        return Col(days + n.values, valid_and(d.validity, n.validity),
                   T.DATE).canonicalized()

    def __repr__(self):
        return (f"dateaddinterval({self.children[0]!r}, "
                f"{self.children[1]!r})")
