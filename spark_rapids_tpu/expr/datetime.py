"""Date/time expressions (reference datetimeExpressions.scala, 845 LoC: GpuYear,
GpuMonth, GpuDayOfMonth, GpuDateAdd/Sub, GpuDateDiff, GpuHour/Minute/Second…).

All pure integer arithmetic on Spark's internal representations (date = int32 days,
timestamp = int64 micros UTC), using Howard Hinnant's civil-from-days algorithm in
jax ops — exact over the full range, fully fused into stage programs."""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression
from spark_rapids_tpu.expr.arithmetic import _cast_col, valid_and

_MICROS_PER_DAY = 86_400_000_000


def civil_from_days(z):
    """days-since-epoch → (year, month, day), Hinnant's algorithm in int32/int64."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097                                   # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)          # [0, 365]
    mp = (5 * doy + 2) // 153                                # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                        # [1, 31]
    m = jnp.where(mp < 10, mp + 3, mp - 9)                   # [1, 12]
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def _date_col(expr_dtype, col):
    """Days value for either DateType or TimestampType input."""
    if isinstance(expr_dtype, T.TimestampType):
        return jnp.floor_divide(col.values, _MICROS_PER_DAY).astype(jnp.int32)
    return col.values


class _DatePart(Expression):
    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.INT

    def with_children(self, children):
        return type(self)(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        days = _date_col(self.children[0].dtype, c)
        y, m, d = civil_from_days(days)
        return Col(self.pick(y, m, d, days), c.validity, T.INT).canonicalized()

    def pick(self, y, m, d, days):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r})"


class Year(_DatePart):
    def pick(self, y, m, d, days):
        return y


class Month(_DatePart):
    def pick(self, y, m, d, days):
        return m


class DayOfMonth(_DatePart):
    def pick(self, y, m, d, days):
        return d


class DayOfWeek(_DatePart):
    """Spark dayofweek: 1 = Sunday … 7 = Saturday. 1970-01-01 was a Thursday."""

    def pick(self, y, m, d, days):
        return ((days + 4) % 7 + 7) % 7 + 1


class WeekDay(_DatePart):
    """Spark weekday: 0 = Monday … 6 = Sunday."""

    def pick(self, y, m, d, days):
        return ((days + 3) % 7 + 7) % 7


class DayOfYear(_DatePart):
    def pick(self, y, m, d, days):
        jan1 = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        return (days - jan1 + 1).astype(jnp.int32)


class Quarter(_DatePart):
    def pick(self, y, m, d, days):
        return (m - 1) // 3 + 1


class LastDay(Expression):
    """last_day(date): last day of that month."""

    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.DATE

    def with_children(self, children):
        return LastDay(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        days = _date_col(self.children[0].dtype, c)
        y, m, _ = civil_from_days(days)
        ny = jnp.where(m == 12, y + 1, y)
        nm = jnp.where(m == 12, 1, m + 1)
        first_next = days_from_civil(ny, nm, jnp.ones_like(nm))
        return Col((first_next - 1).astype(jnp.int32), c.validity, T.DATE).canonicalized()


def days_from_civil(y, m, d):
    """(year, month, day) → days-since-epoch (Hinnant)."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9).astype(jnp.int64)
    doy = (153 * mp + 2) // 5 + d.astype(jnp.int64) - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


class _TimePart(Expression):
    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.INT

    def with_children(self, children):
        return type(self)(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        micros_in_day = c.values - jnp.floor_divide(
            c.values, _MICROS_PER_DAY) * _MICROS_PER_DAY
        return Col(self.pick(micros_in_day).astype(jnp.int32), c.validity,
                   T.INT).canonicalized()

    def pick(self, mid):
        raise NotImplementedError


class Hour(_TimePart):
    def pick(self, mid):
        return mid // 3_600_000_000


class Minute(_TimePart):
    def pick(self, mid):
        return (mid // 60_000_000) % 60


class Second(_TimePart):
    def pick(self, mid):
        return (mid // 1_000_000) % 60


class DateAdd(Expression):
    def __init__(self, date, delta):
        self.children = [date, delta]

    @property
    def dtype(self):
        return T.DATE

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def eval(self, ctx):
        d = self.children[0].eval(ctx)
        n = _cast_col(self.children[1].eval(ctx), T.INT)
        days = _date_col(self.children[0].dtype, d)
        return Col(self.op(days, n.values), valid_and(d.validity, n.validity),
                   T.DATE).canonicalized()

    def op(self, days, n):
        return days + n

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r}, {self.children[1]!r})"


class DateSub(DateAdd):
    def op(self, days, n):
        return days - n


class DateDiff(Expression):
    def __init__(self, end, start):
        self.children = [end, start]

    @property
    def dtype(self):
        return T.INT

    def with_children(self, children):
        return DateDiff(children[0], children[1])

    def eval(self, ctx):
        e = self.children[0].eval(ctx)
        s = self.children[1].eval(ctx)
        ed = _date_col(self.children[0].dtype, e)
        sd = _date_col(self.children[1].dtype, s)
        return Col(ed - sd, valid_and(e.validity, s.validity), T.INT).canonicalized()


class UnixTimestampSeconds(Expression):
    """unix_timestamp(ts): seconds since epoch (floor)."""

    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.LONG

    def with_children(self, children):
        return UnixTimestampSeconds(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return Col(jnp.floor_divide(c.values, 1_000_000), c.validity,
                   T.LONG).canonicalized()
