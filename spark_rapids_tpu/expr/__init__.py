from spark_rapids_tpu.expr.core import (  # noqa: F401
    Col, Expression, BoundReference, AttributeReference, Literal, Alias, bind_references,
)
