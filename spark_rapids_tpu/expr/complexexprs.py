"""Complex-type create/extract expressions.

Reference: complexTypeCreator.scala (GpuCreateNamedStruct, GpuCreateArray) and
complexTypeExtractors.scala (GpuGetStructField, GpuGetArrayItem) plus
collectionOperations GpuSize. The reference materializes real nested cudf
columns; our columnar layer is flat, so the device path covers the FUSED
create+extract pairs (`struct(a, b).x`, `array(a, b)[i]`, `size(array(...))`)
by algebraic rewrite inside eval — no nested column is ever materialized.
Standalone nested outputs (a projection ENDING in struct/array) are pinned to
the host by the planner's tag functions, mirroring how the reference gates
nested types per-op through TypeSig (TypeChecks.scala:129).
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression, Literal


class CreateNamedStruct(Expression):
    """named_struct('a', x, 'b', y) — alternating name literals and values."""

    def __init__(self, *name_value_pairs):
        assert len(name_value_pairs) % 2 == 0, "name/value pairs required"
        self.children = list(name_value_pairs)

    @property
    def field_names(self):
        names = []
        for e in self.children[0::2]:
            assert isinstance(e, Literal), "struct field names must be literals"
            names.append(e.value)
        return names

    @property
    def field_values(self):
        return self.children[1::2]

    @property
    def dtype(self):
        return T.StructDataType(self.field_names,
                                [v.dtype for v in self.field_values])

    def with_children(self, children):
        return CreateNamedStruct(*children)

    def eval(self, ctx):
        raise NotImplementedError(
            "struct values have no flat device form; only fused "
            "struct(...).field extraction runs on device")

    def __repr__(self):
        return f"named_struct({', '.join(map(repr, self.children))})"


class GetStructField(Expression):
    """struct.field — device path requires the child to be CreateNamedStruct
    (fused extract); real struct columns stay on host."""

    def __init__(self, child, name: str):
        self.children = [child]
        self.field = name

    @property
    def dtype(self):
        ct = self.children[0].dtype
        if isinstance(ct, T.StructDataType):
            return ct.types[ct.names.index(self.field)]
        return T.NULL

    def with_children(self, children):
        return GetStructField(children[0], self.field)

    def eval(self, ctx):
        src = self.children[0]
        if not isinstance(src, CreateNamedStruct):
            raise NotImplementedError(
                "GetStructField on a real struct column runs on host")
        i = src.field_names.index(self.field)
        return src.field_values[i].eval(ctx)

    def __repr__(self):
        return f"{self.children[0]!r}.{self.field}"


class CreateArray(Expression):
    """array(a, b, c) — homogeneous element type (common promotion)."""

    def __init__(self, *children):
        self.children = list(children)

    @property
    def dtype(self):
        from spark_rapids_tpu.expr.conditional import _common_type
        elem = (_common_type([c.dtype for c in self.children])
                if self.children else T.NULL)
        return T.ArrayType(elem)

    def with_children(self, children):
        return CreateArray(*children)

    def eval(self, ctx):
        raise NotImplementedError(
            "array values have no flat device form; only fused array(...)[i] "
            "extraction runs on device")

    def __repr__(self):
        return f"array({', '.join(map(repr, self.children))})"


class GetArrayItem(Expression):
    """arr[i] — null when i is out of bounds (Spark non-ANSI). Device path
    requires CreateArray child; a literal index selects one element, a column
    index multiplexes across elements with jnp.where chains."""

    def __init__(self, child, index):
        self.children = [child, index]

    @property
    def dtype(self):
        ct = self.children[0].dtype
        return ct.element_type if isinstance(ct, T.ArrayType) else T.NULL

    def with_children(self, children):
        return GetArrayItem(children[0], children[1])

    def eval(self, ctx):
        from spark_rapids_tpu.expr.arithmetic import _cast_col
        from spark_rapids_tpu.expr.strings import StringSplit, java_split
        src, idx = self.children
        if isinstance(src, StringSplit):
            # fused split(s, re)[i]: one python split per DICTIONARY entry
            if not isinstance(idx, Literal):
                raise NotImplementedError(
                    "split(...)[col] runs on host (literal index only)")
            from spark_rapids_tpu.ops import strings as S
            pat, lim = src.pattern_limit()
            c = src.children[0].eval(ctx)
            i = idx.value

            def fn(s):
                parts = java_split(s, pat, lim)
                return (parts[int(i)] if i is not None
                        and 0 <= int(i) < len(parts) else None)
            return S.dict_transform_to_string(c, fn)
        if not isinstance(src, CreateArray):
            raise NotImplementedError(
                "GetArrayItem on a real array column runs on host")
        elem_t = self.dtype
        elems = [_cast_col(e.eval(ctx), elem_t) for e in src.children]
        n = len(elems)
        if isinstance(idx, Literal):
            i = idx.value
            if i is None or i < 0 or i >= n:
                return Col(jnp.full((ctx.capacity,), elem_t.default_value(),
                                    elem_t.jnp_dtype),
                           jnp.zeros((ctx.capacity,), jnp.bool_), elem_t)
            return elems[int(i)]
        ic = _cast_col(idx.eval(ctx), T.INT)
        out = Col(jnp.full((ctx.capacity,), elem_t.default_value(),
                           elem_t.jnp_dtype),
                  jnp.zeros((ctx.capacity,), jnp.bool_), elem_t,
                  elems[0].dictionary if elems and elems[0].is_string else None)
        for i, e in enumerate(elems):
            if e.is_string and e.dictionary is not out.dictionary:
                from spark_rapids_tpu.ops.strings import union_dictionaries
                e, out = union_dictionaries(e, out)
            hit = ic.validity & (ic.values == i)
            out = Col(jnp.where(hit, e.values, out.values),
                      jnp.where(hit, e.validity, out.validity),
                      elem_t, out.dictionary)
        return out

    def __repr__(self):
        return f"{self.children[0]!r}[{self.children[1]!r}]"


class Size(Expression):
    """size(array) — element count; -1 for null input (Spark legacy mode).
    Device path covers CreateArray (constant size, never null)."""

    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.INT

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return Size(children[0])

    def eval(self, ctx):
        src = self.children[0]
        from spark_rapids_tpu.expr.strings import StringSplit, java_split
        if isinstance(src, StringSplit):
            from spark_rapids_tpu.ops import strings as S
            pat, lim = src.pattern_limit()
            c = src.children[0].eval(ctx)
            out = S.dict_transform_to_values(
                c, lambda s: len(java_split(s, pat, lim)), T.INT)
            # legacy Spark: size(null) == -1, never null (matches host)
            return Col(jnp.where(out.validity, out.values, -1),
                       jnp.ones_like(out.validity), T.INT)
        if not isinstance(src, CreateArray):
            raise NotImplementedError(
                "size() on a real array column runs on host")
        return Col(jnp.full((ctx.capacity,), len(src.children), jnp.int32),
                   jnp.ones((ctx.capacity,), jnp.bool_), T.INT)

    def __repr__(self):
        return f"size({self.children[0]!r})"


class ElementAt(Expression):
    """element_at(array, i): ONE-based; negative indexes from the end; null
    index → null; out of range → null (Spark non-ANSI). Device path requires
    a fused CreateArray child like GetArrayItem (reference GpuOverrides
    expr[ElementAt])."""

    def __init__(self, child, index, strict_zero: bool = False):
        self.children = [child, index]
        # pre-3.4 shim semantics: index 0 raises instead of yielding null
        # (set by the planner from the active SparkShim)
        self.strict_zero = strict_zero

    @property
    def dtype(self):
        ct = self.children[0].dtype
        return ct.element_type if isinstance(ct, T.ArrayType) else T.NULL

    def with_children(self, children):
        return ElementAt(children[0], children[1], self.strict_zero)

    def eval(self, ctx):
        src, idx = self.children
        if not isinstance(src, CreateArray):
            raise NotImplementedError(
                "ElementAt on a real array column runs on host")
        n = len(src.children)

        # 1-based → 0-based (negatives wrap from the end), then reuse the
        # fused multiplex of GetArrayItem
        if isinstance(idx, Literal):
            i = idx.value
            if i == 0 and self.strict_zero:
                raise RuntimeError("SQL array indices start at 1")
            if i is None or i == 0:
                zero = Literal(None, T.INT)
                return GetArrayItem(src, zero).eval(ctx)
            return GetArrayItem(
                src, Literal(int(i) - 1 if i > 0 else n + int(i),
                             T.INT)).eval(ctx)
        from spark_rapids_tpu.expr.arithmetic import _cast_col
        ic = _cast_col(idx.eval(ctx), T.INT)
        shifted = jnp.where(ic.values > 0, ic.values - 1, n + ic.values)
        # i == 0 is invalid in Spark element_at: make it out-of-range
        shifted = jnp.where(ic.values == 0, jnp.int32(n), shifted)
        zero_based = Col(shifted, ic.validity, T.INT)

        class _Wrap(Expression):
            def __init__(self, col):
                self.children = []
                self._col = col

            @property
            def dtype(self):
                return T.INT

            def with_children(self, children):
                return self

            def eval(self, _ctx):
                return self._col

        return GetArrayItem(src, _Wrap(zero_based)).eval(ctx)

    def __repr__(self):
        return f"element_at({self.children[0]!r}, {self.children[1]!r})"


class ArrayContains(Expression):
    """array_contains(array, value): true if present; null when absent but
    the array holds a null; false otherwise (Spark). Device path over fused
    CreateArray (reference GpuOverrides expr[ArrayContains])."""

    def __init__(self, child, value):
        self.children = [child, value]

    @property
    def dtype(self):
        return T.BOOLEAN

    def with_children(self, children):
        return ArrayContains(children[0], children[1])

    def eval(self, ctx):
        from spark_rapids_tpu.expr.arithmetic import _cast_col
        src, needle = self.children
        if not isinstance(src, CreateArray):
            raise NotImplementedError(
                "ArrayContains on a real array column runs on host")
        elem_t = src.dtype.element_type
        nv = _cast_col(needle.eval(ctx), elem_t)
        found = jnp.zeros((ctx.capacity,), jnp.bool_)
        has_null = jnp.zeros((ctx.capacity,), jnp.bool_)
        for e in src.children:
            ec = _cast_col(e.eval(ctx), elem_t)
            if ec.is_string and nv.is_string and \
                    ec.dictionary is not nv.dictionary:
                from spark_rapids_tpu.ops.strings import union_dictionaries
                ec, nv = union_dictionaries(ec, nv)
            found = found | (ec.validity & (ec.values == nv.values))
            has_null = has_null | ~ec.validity
        valid = nv.validity & (found | ~has_null)
        return Col(found, valid, T.BOOLEAN)

    def __repr__(self):
        return f"array_contains({self.children[0]!r}, {self.children[1]!r})"


class CreateMap(Expression):
    """map(k1, v1, k2, v2, ...) — fused-only, like CreateArray/
    CreateNamedStruct: only map(...)[key] extraction runs on device."""

    def __init__(self, *children):
        assert len(children) % 2 == 0, "map() needs key/value pairs"
        self.children = list(children)

    @property
    def dtype(self):
        from spark_rapids_tpu.expr.conditional import _common_type
        ks = [c.dtype for c in self.children[0::2]]
        vs = [c.dtype for c in self.children[1::2]]
        return T.MapType(_common_type(ks) if ks else T.NULL,
                         _common_type(vs) if vs else T.NULL)

    def with_children(self, children):
        return CreateMap(*children)

    def eval(self, ctx):
        raise NotImplementedError(
            "map values have no flat device form; only fused map(...)[k] "
            "extraction runs on device")

    def __repr__(self):
        return f"map({', '.join(map(repr, self.children))})"


class GetMapValue(Expression):
    """map[key] — null when the key is absent (Spark non-ANSI). Device path
    requires a fused CreateMap child (reference GpuGetMapValue; same
    design as GetArrayItem over CreateArray): a chain of key-equality
    selects over the pair expressions."""

    def __init__(self, child, key):
        self.children = [child, key]

    @property
    def dtype(self):
        ct = self.children[0].dtype
        return ct.value_type if isinstance(ct, T.MapType) else T.NULL

    def with_children(self, children):
        return GetMapValue(children[0], children[1])

    def eval(self, ctx):
        from spark_rapids_tpu.expr.arithmetic import _cast_col
        from spark_rapids_tpu.expr.predicates import EqualTo
        src, key = self.children
        if not isinstance(src, CreateMap):
            raise NotImplementedError(
                "GetMapValue on a real map column runs on host")
        elem_t = self.dtype
        out = Col(jnp.full((ctx.capacity,), elem_t.default_value(),
                           elem_t.jnp_dtype),
                  jnp.zeros((ctx.capacity,), jnp.bool_), elem_t)
        # later pairs win on duplicate keys (Spark map semantics)
        for k_expr, v_expr in zip(src.children[0::2], src.children[1::2]):
            hit_col = EqualTo(key, k_expr).eval(ctx)
            hit = hit_col.validity & hit_col.values
            v = _cast_col(v_expr.eval(ctx), elem_t)
            if v.is_string and v.dictionary is not out.dictionary:
                from spark_rapids_tpu.ops.strings import union_dictionaries
                v, out = union_dictionaries(v, out)
            out = Col(jnp.where(hit, v.values, out.values),
                      jnp.where(hit, v.validity, out.validity),
                      elem_t, out.dictionary)
        return out

    def __repr__(self):
        return f"{self.children[0]!r}[{self.children[1]!r}]"
