"""Decimal plan expressions (reference decimalExpressions.scala:
GpuPromotePrecision — no-op marker around an already-cast child;
GpuCheckOverflow — null out results beyond the target precision;
GpuUnscaledValue / GpuMakeDecimal — long <-> unscaled-decimal reinterpret).

Decimals are carried as unscaled int64 values (DecimalType(precision, scale),
precision <= 18), matching the reference's DECIMAL64-only device support.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression


class PromotePrecision(Expression):
    """Marker around a child Catalyst already cast to the join/arith type —
    evaluates to the child's cast (reference GpuPromotePrecision)."""

    def __init__(self, child, to: T.DecimalType | None = None):
        self.children = [child]
        self._to = to

    @property
    def dtype(self):
        return self._to if self._to is not None else self.children[0].dtype

    def with_children(self, children):
        return PromotePrecision(children[0], self._to)

    def eval(self, ctx):
        from spark_rapids_tpu.expr.cast import cast_col
        c = self.children[0].eval(ctx)
        return cast_col(c, self.dtype) if c.dtype != self.dtype else c

    def __repr__(self):
        return f"promote_precision({self.children[0]!r})"


class CheckOverflow(Expression):
    """Null out (non-ANSI) values whose unscaled magnitude exceeds the target
    precision after rescale (reference GpuCheckOverflow)."""

    def __init__(self, child, to: T.DecimalType, null_on_overflow: bool = True):
        self.children = [child]
        self.to = to
        self.null_on_overflow = null_on_overflow

    @property
    def dtype(self):
        return self.to

    def with_children(self, children):
        return CheckOverflow(children[0], self.to, self.null_on_overflow)

    def eval(self, ctx):
        from spark_rapids_tpu.expr.cast import cast_col
        c = self.children[0].eval(ctx)
        if c.dtype != self.to:
            c = cast_col(c, self.to)
        limit = 10 ** self.to.precision
        ok = (c.values > -limit) & (c.values < limit)
        return Col(jnp.where(ok, c.values, 0), c.validity & ok, self.to)

    def __repr__(self):
        return f"check_overflow({self.children[0]!r}, {self.to})"


class UnscaledValue(Expression):
    """decimal → its unscaled long (reference GpuUnscaledValue)."""

    def __init__(self, child):
        self.children = [child]

    @property
    def dtype(self):
        return T.LONG

    def with_children(self, children):
        return UnscaledValue(children[0])

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return Col(c.values.astype(jnp.int64), c.validity, T.LONG)

    def __repr__(self):
        return f"unscaled_value({self.children[0]!r})"


class MakeDecimal(Expression):
    """long (unscaled) → decimal(precision, scale); null when the value does
    not fit the precision (reference GpuMakeDecimal)."""

    def __init__(self, child, precision: int, scale: int,
                 null_on_overflow: bool = True):
        self.children = [child]
        self.to = T.DecimalType(precision, scale)
        self.null_on_overflow = null_on_overflow

    @property
    def dtype(self):
        return self.to

    def with_children(self, children):
        return MakeDecimal(children[0], self.to.precision, self.to.scale,
                           self.null_on_overflow)

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        v = c.values.astype(jnp.int64)
        limit = 10 ** self.to.precision
        ok = (v > -limit) & (v < limit)
        return Col(jnp.where(ok, v, 0), c.validity & ok, self.to)

    def __repr__(self):
        return f"make_decimal({self.children[0]!r}, {self.to})"
