"""Window expressions: specs, frames, ranking and offset functions.

Reference: GpuWindowExpression.scala (frame types, `windowAggregation`:847),
GpuWindowExec.scala:92. A WindowExpression pairs a function (ranking / offset /
aggregate) with a WindowSpec (partition keys, order keys, frame). Frames follow
Spark: ROWS or RANGE, with UNBOUNDED/CURRENT/numeric offsets; Spark's default
frame with an ORDER BY is RANGE UNBOUNDED PRECEDING..CURRENT ROW."""

from __future__ import annotations

import dataclasses

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.expr.aggregates import AggregateFunction

UNBOUNDED = None  # sentinel for unbounded preceding/following
CURRENT = 0


@dataclasses.dataclass(frozen=True)
class WindowFrame:
    """row/range frame with offsets relative to the current row. `preceding` and
    `following` use UNBOUNDED (None) or non-negative ints (reference
    GpuSpecifiedWindowFrame)."""
    frame_type: str = "range"          # "rows" | "range"
    preceding: int | None = UNBOUNDED
    following: int | None = CURRENT

    @property
    def is_unbounded_to_current(self):
        return self.preceding is UNBOUNDED and self.following == CURRENT

    @property
    def is_unbounded_both(self):
        return self.preceding is UNBOUNDED and self.following is UNBOUNDED


DEFAULT_FRAME = WindowFrame("range", UNBOUNDED, CURRENT)
FULL_FRAME = WindowFrame("rows", UNBOUNDED, UNBOUNDED)


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    partition_by: tuple = ()
    order_by: tuple = ()               # ((expr, ascending, nulls_first), ...)
    frame: WindowFrame = DEFAULT_FRAME

    def with_frame(self, frame: WindowFrame) -> "WindowSpec":
        return WindowSpec(self.partition_by, self.order_by, frame)


class WindowFunction(Expression):
    """Base for ranking/offset functions that only exist over a window."""
    children: list = []

    @property
    def nullable(self):
        return False


class RowNumber(WindowFunction):
    def __init__(self):
        self.children = []

    @property
    def dtype(self):
        return T.INT

    def with_children(self, children):
        return self

    def __repr__(self):
        return "row_number()"


class Rank(WindowFunction):
    def __init__(self):
        self.children = []

    @property
    def dtype(self):
        return T.INT

    def with_children(self, children):
        return self

    def __repr__(self):
        return "rank()"


class DenseRank(WindowFunction):
    def __init__(self):
        self.children = []

    @property
    def dtype(self):
        return T.INT

    def with_children(self, children):
        return self

    def __repr__(self):
        return "dense_rank()"


class Lead(WindowFunction):
    """lead(col, n, default) — value n rows after the current row within the
    partition (reference GpuLead)."""

    def __init__(self, child, offset: int = 1, default=None):
        self.children = [child]
        self.offset = offset
        self.default = default

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def nullable(self):
        return True

    def with_children(self, children):
        return type(self)(children[0], self.offset, self.default)

    def __repr__(self):
        return f"{type(self).__name__.lower()}({self.children[0]!r}, {self.offset})"


class Lag(Lead):
    pass


class WindowExpression(Expression):
    """func OVER spec (reference GpuWindowExpression)."""

    def __init__(self, func: Expression, spec: WindowSpec):
        assert isinstance(func, (WindowFunction, AggregateFunction)), func
        self.func = func
        self.spec = spec
        # children cover the function inputs AND the spec's partition/order
        # expressions so bind_references rewrites all of them
        self._n_func = len(getattr(func, "children", []))
        self.children = (list(getattr(func, "children", []))
                         + [e for e in spec.partition_by]
                         + [e for (e, _, _) in spec.order_by])

    @property
    def dtype(self):
        return self.func.dtype

    @property
    def nullable(self):
        if isinstance(self.func, (RowNumber, Rank, DenseRank)):
            return False
        return True

    def with_children(self, children):
        nf = self._n_func
        f = self.func.with_children(children[:nf]) if nf else self.func
        np_ = len(self.spec.partition_by)
        parts = tuple(children[nf:nf + np_])
        orders = tuple(
            (c, asc, nfirst) for c, (_, asc, nfirst)
            in zip(children[nf + np_:], self.spec.order_by))
        return WindowExpression(f, WindowSpec(parts, orders, self.spec.frame))

    def __repr__(self):
        return f"{self.func!r} OVER {self.spec}"
