"""Non-deterministic and hash expressions.

Reference: GpuMurmur3Hash (HashFunctions.scala — Spark-exact murmur3 over
columns, the `hash()` SQL function), GpuRand (randomExpressions; the reference
marks rand as non-deterministic: per-partition seeded, NOT bit-identical with
CPU Spark), GpuMonotonicallyIncreasingID and GpuSparkPartitionID
(datetimeExpressions neighbors in namedExpressions/MiscExpressions).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Col, Expression


class Murmur3Hash(Expression):
    """hash(col, ...) — Spark Murmur3Hash with seed 42, bit-exact (same kernel
    as the hash partitioner, ops/hashing.py + shuffle/partitioning.py)."""

    def __init__(self, *children, seed: int = 42):
        self.children = list(children)
        self.seed = seed

    @property
    def dtype(self):
        return T.INT

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return Murmur3Hash(*children, seed=self.seed)

    def eval(self, ctx):
        from spark_rapids_tpu.shuffle.partitioning import murmur3_row_hash
        from spark_rapids_tpu.ops.hashing import pack_utf8_words
        import numpy as np
        cols = [c.eval(ctx) for c in self.children]
        dict_words = {}
        for i, c in enumerate(cols):
            if c.is_string:
                strs = (c.dictionary.to_pylist()
                        if c.dictionary is not None else [])
                words, lens = pack_utf8_words(strs)
                if words.shape[0] == 0:
                    words = np.zeros((1, 1), dtype=np.int32)
                    lens = np.zeros(1, dtype=np.int32)
                dict_words[i] = (jnp.asarray(words), jnp.asarray(lens))
        h = murmur3_row_hash(cols, ctx.capacity, seed=self.seed,
                             dict_words=dict_words)
        return Col(h, jnp.ones((ctx.capacity,), jnp.bool_), T.INT)

    def __repr__(self):
        return f"hash({', '.join(map(repr, self.children))})"


class Rand(Expression):
    """rand([seed]) — uniform [0,1) doubles from a counter-based PRNG keyed by
    (seed, partition). Like the reference's GpuRand this is a real RNG with the
    same distribution but NOT bit-identical to CPU Spark's XORShiftRandom
    stream (the reference carries the same caveat)."""

    def __init__(self, seed: int = 0):
        self.children = []
        self.seed = int(seed)

    @property
    def dtype(self):
        return T.DOUBLE

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return Rand(self.seed)

    def eval(self, ctx):
        key = jax.random.PRNGKey(self.seed ^ (ctx.split * 0x9E3779B9))
        key = jax.random.fold_in(key, ctx.row_offset)  # fresh draw per batch
        vals = jax.random.uniform(key, (ctx.capacity,), dtype=jnp.float64)
        return Col(vals, jnp.ones((ctx.capacity,), jnp.bool_), T.DOUBLE)

    def __repr__(self):
        return f"rand({self.seed})"


class SparkPartitionID(Expression):
    """spark_partition_id() — the task's partition index."""

    def __init__(self):
        self.children = []

    @property
    def dtype(self):
        return T.INT

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return SparkPartitionID()

    def eval(self, ctx):
        return Col(jnp.full((ctx.capacity,), ctx.split, jnp.int32),
                   jnp.ones((ctx.capacity,), jnp.bool_), T.INT)

    def __repr__(self):
        return "spark_partition_id()"


class MonotonicallyIncreasingID(Expression):
    """monotonically_increasing_id(): (partition_id << 33) + row_offset —
    Spark's exact layout (31-bit partition, 33-bit per-partition counter)."""

    def __init__(self):
        self.children = []

    @property
    def dtype(self):
        return T.LONG

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return MonotonicallyIncreasingID()

    def eval(self, ctx):
        base = (jnp.int64(ctx.split) << 33) + ctx.row_offset
        ids = base + jnp.arange(ctx.capacity, dtype=jnp.int64)
        return Col(ids, jnp.ones((ctx.capacity,), jnp.bool_), T.LONG)

    def __repr__(self):
        return "monotonically_increasing_id()"


class _ScanMetaExpr(Expression):
    """Base for the input_file_name family (reference GpuInputFileName /
    GpuInputFileBlockStart/Length, InputFileBlockRules): the value comes
    from the batch's scan provenance; away from a 1:1 file↔batch scan
    (coalescing readers, post-shuffle) Spark's own contract is the empty
    string / -1, which is what a batch without metadata yields."""

    meta_key = None

    def __init__(self):
        self.children = []

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return type(self)()

    def _meta_value(self, ctx):
        meta = getattr(ctx, "scan_meta", None) or {}
        return meta.get(self.meta_key)

    def __repr__(self):
        return f"{type(self).__name__.lower()}()"


class InputFileName(_ScanMetaExpr):
    meta_key = "input_file"

    @property
    def dtype(self):
        return T.STRING

    def eval(self, ctx):
        import pyarrow as pa
        name = self._meta_value(ctx) or ""
        d = pa.array([name], type=pa.string())
        return Col(jnp.zeros((ctx.capacity,), jnp.int32),
                   jnp.ones((ctx.capacity,), jnp.bool_), T.STRING,
                   dictionary=d)


class InputFileBlockStart(_ScanMetaExpr):
    meta_key = "block_start"

    @property
    def dtype(self):
        return T.LONG

    def eval(self, ctx):
        v = self._meta_value(ctx)
        return Col(jnp.full((ctx.capacity,), -1 if v is None else int(v),
                            jnp.int64),
                   jnp.ones((ctx.capacity,), jnp.bool_), T.LONG)


class InputFileBlockLength(InputFileBlockStart):
    meta_key = "block_length"


class ScalarSubquery(Expression):
    """Scalar subquery, evaluated EAGERLY at plan-build time (Spark runs
    subquery stages before the enclosing query; the reference's
    GpuScalarSubquery likewise only carries the already-computed value).
    After construction it behaves exactly like a literal."""

    def __init__(self, value, dtype):
        self.children = []
        self.value = value
        self._dtype = dtype

    @classmethod
    def from_dataframe(cls, df) -> "ScalarSubquery":
        tbl = df.collect()
        if tbl.num_columns != 1:
            raise ValueError("scalar subquery must return one column")
        if tbl.num_rows > 1:
            raise ValueError(
                "more than one row returned by a subquery used as an "
                "expression")  # Spark's exact error condition
        value = tbl.column(0)[0].as_py() if tbl.num_rows else None
        return cls(value, df.schema.fields[0].data_type)

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return self.value is None

    def with_children(self, children):
        return self

    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import Literal
        return Literal(self.value, self._dtype).eval(ctx)

    def __repr__(self):
        return f"scalar_subquery(={self.value!r})"


# Expressions whose eval reads per-partition / per-batch context (split,
# row_offset, scan provenance): the whole-stage fuser (runtime/fuse.py) keeps
# any projection containing one of these on the eager path rather than baking
# one partition's context into a shared compiled program.
CONTEXT_SENSITIVE = (Rand, SparkPartitionID, MonotonicallyIncreasingID,
                     _ScanMetaExpr)


def is_context_free(*exprs) -> bool:
    """True when no expression reads per-batch/per-partition context — the
    planner's fusibility predicate (hoisting into shared compiled kernels is
    only sound for context-free trees)."""
    return not any(
        e.collect(lambda x: isinstance(x, CONTEXT_SENSITIVE))
        for e in exprs if e is not None)
