"""Official TPC-DS query text through session.sql() vs the same NumPy
oracles as the hand-built DataFrame suite (VERDICT r3 item 4: the reference
is a Spark *SQL* plugin — qa_nightly_sql.py — so the SQL surface must run the
official text, not hand translations)."""

import pytest

from spark_rapids_tpu.benchmarks import tpcds
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.sql.tpcds_queries import SQL_QUERIES


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpcds_sql")
    paths = tpcds.generate(0.012, str(d))
    spark = TpuSession()
    dfs = tpcds.load(spark, paths)   # registers temp views for session.sql
    return spark, tpcds.load_np(paths)


def _rows(df):
    return [tuple(r.values()) for r in df.collect().to_pylist()]


# SQL-only queries (no DataFrame adaptation): oracle fn + float columns
_SQL_ONLY = {
    "q13": (tpcds.np_q13, {0, 1, 2, 3}),
    "q36": (tpcds.np_q36, {0}),
    # q27 runs the official rollup shape (the DataFrame adaptation omits
    # the rollup levels); g_state shifts the float slots right by one
    "q27": (tpcds.np_q27_rollup, {3, 4, 5, 6}),
    # q28: six-bucket cross join; avgs at 0,3,6,9,12,15 (DISTINCT rewrite)
    "q28": (tpcds.np_q28, {0, 3, 6, 9, 12, 15}),
    # round-5 set-operation queries (INTERSECT/EXCEPT lowering):
    # q8 nests an INTERSECT inside FROM (decimal profit sums are exact);
    # q38/q87 intersect/subtract the three sales channels
    "q8": (tpcds.np_q8, set()),
    "q38": (tpcds.np_q38, set()),
    "q87": (tpcds.np_q87, set()),
    # q14: cross-channel INTERSECT + IN-subquery + iceberg HAVING + 4-col
    # rollup; sum_sales is float
    "q14": (tpcds.np_q14, {4}),
    # round-5 breadth: catalog/web-channel queries
    "q15": (tpcds.np_q15, {1}),
    "q45": (tpcds.np_q45, {2}),
    # q61: two scalar-aggregate derived tables cross-joined; decimal ratio
    "q61": (tpcds.np_q61, {0, 1, 2}),
    # q97: full-outer overlap of per-channel distinct (customer, item)
    "q97": (tpcds.np_q97, set()),
    # q33/q56: three-channel UNION ALL sums by an item attribute, with an
    # uncorrelated IN-subquery item filter; total_sales is float
    "q33": (tpcds.np_q33, {1}),
    "q56": (tpcds.np_q56, {1}),
    # q12/q20: q98's class-partition revenue-ratio window over web/catalog
    "q12": (tpcds.np_q12, {4, 5, 6}),
    "q20": (tpcds.np_q20, {4, 5, 6}),
}


@pytest.mark.parametrize("name", sorted(SQL_QUERIES, key=lambda q: int(q[1:])))
def test_sql_query_matches_oracle(data, name):
    spark, tb = data
    got = _rows(spark.sql(SQL_QUERIES[name]))
    if name in _SQL_ONLY:
        oracle, float_cols = _SQL_ONLY[name]
        exp = [tuple(r) for r in oracle(tb)]
    else:
        exp = [tuple(r) for r in tpcds.NP_QUERIES[name](tb)]
        float_cols = tpcds.FLOAT_COLS[name]
    assert exp, "vacuous test: oracle returned no rows"
    tpcds.check_rows(got, exp, float_cols)


def test_sql_q3_matches_handbuilt(data):
    """VERDICT r3 item 4's explicit 'done' check: session.sql(official q3)
    returns the same oracle-checked rows as the hand-built q3."""
    spark, tb = data
    got_sql = _rows(spark.sql(SQL_QUERIES["q3"]))
    dfs = {name: spark._views[name] for name in spark._views}
    got_df = _rows(tpcds.QUERIES["q3"](dfs))
    assert got_sql == got_df
