"""Official TPC-DS query text through session.sql() vs the same NumPy
oracles as the hand-built DataFrame suite (VERDICT r3 item 4: the reference
is a Spark *SQL* plugin — qa_nightly_sql.py — so the SQL surface must run the
official text, not hand translations)."""

import pytest

from spark_rapids_tpu.benchmarks import tpcds
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.sql.tpcds_queries import SQL_QUERIES


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpcds_sql")
    paths = tpcds.generate(0.012, str(d))
    spark = TpuSession()
    dfs = tpcds.load(spark, paths)   # registers temp views for session.sql
    return spark, tpcds.load_np(paths)


def _rows(df):
    return [tuple(r.values()) for r in df.collect().to_pylist()]


# every official text maps to (oracle fn, float columns) — the SQL-only
# queries (set ops, cross-channel, rollup forms) carry their own oracles;
# the rest reuse the DataFrame suite's. Shared with bench.py's SQL sweep.
_ORACLES = tpcds.sql_suite_oracles()


@pytest.mark.parametrize("name", sorted(SQL_QUERIES, key=lambda q: int(q[1:])))
def test_sql_query_matches_oracle(data, name):
    spark, tb = data
    got = _rows(spark.sql(SQL_QUERIES[name]))
    oracle, float_cols = _ORACLES[name]
    exp = [tuple(r) for r in oracle(tb)]
    assert exp, "vacuous test: oracle returned no rows"
    tpcds.check_rows(got, exp, float_cols)


def test_sql_q3_matches_handbuilt(data):
    """VERDICT r3 item 4's explicit 'done' check: session.sql(official q3)
    returns the same oracle-checked rows as the hand-built q3."""
    spark, tb = data
    got_sql = _rows(spark.sql(SQL_QUERIES["q3"]))
    dfs = {name: spark._views[name] for name in spark._views}
    got_df = _rows(tpcds.QUERIES["q3"](dfs))
    assert got_sql == got_df
