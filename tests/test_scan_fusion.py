"""Scan-side chain and chained group-by: bit-identity of the encoded-upload
decode→filter→partial-agg path and the fused update→concat→merge loop vs
their unfused/arrow twins (the `scan.enabled` / `groupBy.chain.enabled` A/Bs),
engagement proof through the movement ledger, and the steady-state
dispatch-count bound the chain exists to win."""

import datetime

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.session import TpuSession

SF = 0.01
FUSION = "spark.rapids.tpu.sql.stageFusion.enabled"
SCAN_FUSION = "spark.rapids.tpu.sql.stageFusion.scan.enabled"
GB_CHAIN = "spark.rapids.tpu.sql.stageFusion.groupBy.chain.enabled"
ENCODED = "spark.rapids.tpu.sql.parquet.encodedUpload.enabled"
DEVICE_DECODE = "spark.rapids.tpu.sql.parquet.deviceDecode.enabled"

# explicit deviceDecode=True overrides the cpu-backend gate, so the encoded
# path runs (and is testable) on the CPU CI backend
FULL_ON = {FUSION: True, DEVICE_DECODE: True, ENCODED: True,
           SCAN_FUSION: True, GB_CHAIN: True}
ARROW = {FUSION: False, DEVICE_DECODE: False}


@pytest.fixture(scope="module")
def paths():
    # 8 files/table at 2 files/partition: every partition feeds the
    # aggregation multiple batches, so the group-by chain actually engages
    return tpch.generate(SF, f"/tmp/tpch_scan_sf{SF}_f8", files_per_table=8)


_memo: dict = {}


def _collect(paths, query, conf):
    key = (query, tuple(sorted(conf.items())))
    if key not in _memo:
        spark = TpuSession(dict(conf))
        dfs = tpch.load(spark, paths, files_per_partition=2)
        _memo[key] = tpch.QUERIES[query](dfs).collect().to_pylist()
    return _memo[key]


# -- bit-identity across the ladder ------------------------------------------

@pytest.mark.parametrize("query", ["q1", "q3", "q5", "q18"])
def test_ladder_bit_identical_scan_chain_vs_arrow(paths, query):
    # exact equality, floats included: the encoded page expands through the
    # SAME traced decode body the dense path runs, the chain concats through
    # the SAME traced concat body, and chained results are only accepted at
    # the capacity bucket the unchained loop would have used
    assert _collect(paths, query, FULL_ON) == _collect(paths, query, ARROW)


@pytest.mark.parametrize("knob", [ENCODED, GB_CHAIN, SCAN_FUSION])
def test_q1_bit_identical_each_knob_off(paths, knob):
    off = dict(FULL_ON)
    off[knob] = False
    assert _collect(paths, "q1", FULL_ON) == _collect(paths, "q1", off)


# -- adversarial page layouts -------------------------------------------------

def _edge_parquet(tmp_path):
    """Dictionary strings with nulls (RLE-hybrid def levels), a
    low-cardinality dict int, a null-heavy double, an ALL-NULL column (empty
    dictionary page), and a post-1582 date — the layouts the encoded-upload
    fast path special-cases or must cleanly degrade on. Row groups above the
    chain's capacity floor make many batches so the chain runs too."""
    n = 6000
    tbl = pa.table({
        "k": pa.array([f"grp{i % 5}" if i % 7 else None for i in range(n)]),
        "i": pa.array([i % 11 for i in range(n)], pa.int64()),
        "x": pa.array([float(i % 13) / 4 if i % 3 else None
                       for i in range(n)], pa.float64()),
        "z": pa.array([None] * n, pa.float64()),
        "d": pa.array([datetime.date(2020, 1, 1 + i % 27)
                       for i in range(n)]),
    })
    path = str(tmp_path / "edge.parquet")
    pq.write_table(tbl, path, use_dictionary=True, row_group_size=1024)
    return path


def test_edge_pages_bit_identical_encoded_vs_arrow(tmp_path):
    path = _edge_parquet(tmp_path)
    c = F.col
    got = {}
    for name, conf in (("on", FULL_ON), ("arrow", ARROW)):
        spark = TpuSession(dict(conf))
        df = (spark.read_parquet(path)
              .filter(c("i") > F.lit(2))
              .group_by(c("k"))
              .agg(F.sum(c("x")).alias("sx"), F.count(c("i")).alias("ci"),
                   F.sum(c("z")).alias("sz"), F.min(c("d")).alias("md"))
              .sort(c("k")))
        got[name] = df.collect().to_pylist()
    assert got["on"] == got["arrow"]
    assert len(got["on"]) > 0


# -- engagement: the ledger must see encoded bytes, and fewer of them ---------

def _h2d_sites():
    from spark_rapids_tpu.runtime import movement as MV
    out: dict = {}
    for (edge, link, site), rec in MV.snapshot().items():
        if edge == "h2d":
            out[site] = out.get(site, 0) + rec["bytes"]
    return out


def test_encoded_upload_cuts_h2d_bytes(paths):
    def run(conf):
        before = _h2d_sites()
        spark = TpuSession(dict(conf))
        dfs = tpch.load(spark, paths, files_per_partition=2)
        tpch.QUERIES["q1"](dfs).collect()
        after = _h2d_sites()
        return {k: after.get(k, 0) - before.get(k, 0)
                for k in set(after) | set(before)}

    enc = run(FULL_ON)
    dense = run({**FULL_ON, ENCODED: False})
    assert enc.get("scan.encoded", 0) > 0          # the path engaged
    assert dense.get("scan.encoded", 0) == 0
    # the acceptance bar: encoded upload moves >=1.3x fewer bytes over PCIe
    assert sum(dense.values()) >= 1.3 * sum(enc.values())


# -- steady-state dispatch bound ----------------------------------------------

def test_groupby_chain_cuts_steady_state_dispatches(paths):
    from spark_rapids_tpu.runtime import stats as STATS

    def agg_dispatches(chain):
        spark = TpuSession({**FULL_ON, GB_CHAIN: chain})
        dfs = tpch.load(spark, paths, files_per_partition=2)
        df = tpch.QUERIES["q1"](dfs)
        df.collect()          # warm: traces + capacity predictions settle
        df.collect()
        tbl = STATS.node_table(df._last_collector)
        return sum(e["dispatches"] or 0 for e in tbl
                   if e["name"] == "HashAggregateExec")

    chained, unchained = agg_dispatches(True), agg_dispatches(False)
    # the chain replaces key-stats + concat + merge + right-size dispatches
    # with ONE fused program per steady-state batch
    assert chained < unchained
