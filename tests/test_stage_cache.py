"""Persistent compiled-stage cache: cross-session round-trip (a FRESH
PROCESS replays stored XLA executables with zero traces), corruption
degrading to a warned retrace, LRU pruning, and the session conf wiring.

Both the populate and the replay sessions run as subprocesses: the
zero-traces contract is a statement about PROCESS boundaries, and a pytest
parent is a poor stand-in for a fresh session — its jax persistent compile
cache is already warm and memoized on, which is exactly the hazard
stage_cache.configure() defuses for real sessions."""

import glob
import json
import os
import subprocess
import sys

import pytest

import spark_rapids_tpu
from spark_rapids_tpu.runtime import stage_cache
from spark_rapids_tpu.session import TpuSession

SF = 0.01

_CHILD = r"""
import json, sys
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.runtime import fuse, stage_cache
paths = tpch.generate(%r, %r)
spark = TpuSession({
    "spark.rapids.tpu.sql.stage.cache.enabled": True,
    "spark.rapids.tpu.sql.stage.cache.dir": sys.argv[1]})
dfs = tpch.load(spark, paths)
rows = tpch.QUERIES["q18"](dfs).collect().to_pylist()
st = stage_cache.get()
print(json.dumps({"rows": rows, "traces": fuse.stage_metrics()["traces"],
                  "hits": st.hits, "misses": st.misses, "saves": st.saves,
                  "corrupt": st.corrupt}))
"""


def _run_session(tmp_path, cache_dir):
    script = tmp_path / "child.py"
    script.write_text(_CHILD % (SF, f"/tmp/tpch_sf{SF}"))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(spark_rapids_tpu.__file__))
        + os.pathsep + env.get("PYTHONPATH", ""))
    p = subprocess.run([sys.executable, str(script), cache_dir],
                       capture_output=True, text=True, env=env, timeout=300)
    assert p.returncode == 0, p.stderr
    return json.loads(p.stdout.splitlines()[-1]), p.stderr


def test_cross_session_roundtrip_and_corruption(tmp_path):
    cdir = str(tmp_path / "stagecache")

    # session 1: populate the store
    out1, _ = _run_session(tmp_path, cdir)
    assert out1["saves"] > 0
    assert out1["traces"] > 0
    n_entries = len(glob.glob(os.path.join(cdir, "*.xc")))
    assert n_entries > 0

    # session 2 (fresh process): every fused stage replays a stored
    # executable — no Python retraces, no XLA compiles
    out2, _ = _run_session(tmp_path, cdir)
    assert out2["rows"] == out1["rows"]
    assert out2["traces"] == 0
    assert out2["hits"] > 0
    assert out2["saves"] == 0

    # corrupt one entry; session 3 degrades to a warned retrace and
    # re-saves the entry — degraded, never wrong
    garbage = b"this is not a serialized executable"
    victim = sorted(glob.glob(os.path.join(cdir, "*.xc")))[0]
    with open(victim, "wb") as f:
        f.write(garbage)
    out3, stderr = _run_session(tmp_path, cdir)
    assert out3["rows"] == out1["rows"]
    assert out3["corrupt"] >= 1
    assert out3["traces"] >= 1
    assert "corrupt stage-cache entry" in stderr
    assert (not os.path.exists(victim)
            or os.path.getsize(victim) != len(garbage))


def test_prune_keeps_directory_under_budget(tmp_path):
    store = stage_cache.StageCacheStore(str(tmp_path), max_bytes=200)
    for i in range(10):
        store.save(f"entry{i}", b"x" * 64)
    assert store.total_bytes() <= 200
    assert 0 < len(store.entries()) < 10


def test_oversized_entry_is_not_stored(tmp_path):
    store = stage_cache.StageCacheStore(str(tmp_path), max_bytes=16)
    store.save("big", b"y" * 64)
    assert store.entries() == []


def test_session_conf_wiring(tmp_path):
    d = str(tmp_path / "sc")
    try:
        TpuSession({"spark.rapids.tpu.sql.stage.cache.enabled": True,
                    "spark.rapids.tpu.sql.stage.cache.dir": d})
        st = stage_cache.get()
        assert st is not None and st.directory == d
        assert os.path.isdir(d)
        # explicit disable closes the store
        TpuSession({"spark.rapids.tpu.sql.stage.cache.enabled": False})
        assert stage_cache.get() is None
        # no stage.cache settings at all: process-global state untouched
        stage_cache.configure(d, 1 << 20)
        TpuSession()
        assert stage_cache.get() is not None
    finally:
        stage_cache.shutdown()
