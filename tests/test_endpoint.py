"""Arrow-over-TCP query endpoint tests (runtime/endpoint.py): submission
round-trips, wire-level fuzz (CRC mismatch, typed error marshalling),
disconnect-driven cancellation (half-close AND RST), idle/request timeouts,
graceful drain with hard-kill escalation, backoff-honoring client retries,
and exception pickle round-trips — the serving contract of ROADMAP item 2's
network half."""

import json
import pickle
import socket
import threading
import time

import pyarrow as pa
import pytest

from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import scheduler as SCHED
from spark_rapids_tpu.runtime.endpoint import (MSG_SUBMIT, EndpointClient,
                                               QueryEndpoint, _ResultStream)
from spark_rapids_tpu.runtime.memory import SpillCorruptionError
from spark_rapids_tpu.runtime.retry import DeviceOomError, SplitAndRetryOom
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.shuffle.transport import (TransportError, send_frame)

SQL = "select k % 5 kk, sum(v) s, count(*) c from t group by kk order by kk"


def _session(extra=None):
    spark = TpuSession(dict(extra or {}))
    spark.create_or_replace_temp_view(
        "t", spark.create_dataframe(
            pa.table({"k": list(range(200)),
                      "v": [float(i) / 3 for i in range(200)],
                      "s": [f"s{i % 7}" for i in range(200)]}),
            num_partitions=4))
    return spark


@pytest.fixture
def served():
    spark = _session()
    ep = QueryEndpoint(spark)
    try:
        yield spark, ep, EndpointClient(("127.0.0.1", ep.port), timeout_s=30)
    finally:
        faults.reset()
        ep.shutdown(grace_s=5)


def _counter(name):
    return M.global_registry().metric(name).value


def _wait(pred, timeout_s=20.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def _no_endpoint_threads():
    return not any(t.name.startswith("srt-endpoint-w")
                   for t in threading.enumerate())


# -- round trips --------------------------------------------------------------

def test_submit_matches_direct_collect(served):
    spark, ep, cli = served
    direct = spark.sql(SQL).collect().to_pylist()
    out = cli.submit(SQL)
    assert out.to_pylist() == direct
    s = cli.last_summary
    assert s["rows"] == out.num_rows and s["batches"] >= 1
    assert s["query"].startswith("q") and s["resilience"] == {}


def test_ping_and_sequential_submissions_share_connection(served):
    spark, ep, cli = served
    assert cli.ping()
    direct = spark.sql(SQL).collect().to_pylist()
    # protocol supports multiple submissions per connection: drive two
    # SUBMITs down one socket by hand
    sock = cli.connect()
    try:
        for _ in range(2):
            send_frame(sock, MSG_SUBMIT, json.dumps({"sql": SQL}).encode())
            got = []
            from spark_rapids_tpu.runtime.endpoint import (MSG_RESULT_BATCH,
                                                           MSG_RESULT_END,
                                                           _CRC,
                                                           _ipc_to_table)
            from spark_rapids_tpu.shuffle.transport import recv_frame
            while True:
                msg, payload = recv_frame(sock)
                if msg == MSG_RESULT_END:
                    break
                assert msg == MSG_RESULT_BATCH
                got.append(_ipc_to_table(payload[_CRC.size:]))
            assert pa.concat_tables(got).to_pylist() == direct
    finally:
        sock.close()


def test_empty_result_keeps_schema(served):
    spark, ep, cli = served
    out = cli.submit("select k, v from t where k > 10000")
    assert out.num_rows == 0
    assert out.column_names == ["k", "v"]


def test_request_knobs_validated(served):
    spark, ep, cli = served
    sock = cli.connect()
    try:
        send_frame(sock, MSG_SUBMIT, json.dumps(
            {"sql": SQL, "evil_conf": "x"}).encode())
        from spark_rapids_tpu.runtime.endpoint import (MSG_QUERY_ERROR,
                                                       _unpickle_error)
        from spark_rapids_tpu.shuffle.transport import recv_frame
        msg, payload = recv_frame(sock)
        assert msg == MSG_QUERY_ERROR
        err = _unpickle_error(payload)
        assert isinstance(err, ValueError) and "evil_conf" in str(err)
    finally:
        sock.close()


def test_plan_error_marshalled_typed(served):
    spark, ep, cli = served
    with pytest.raises(Exception) as ei:
        cli.submit("select nope from missing_table")
    assert "missing_table" in str(ei.value)


def test_injected_error_marshalled(served):
    spark, ep, cli = served
    # a worker-thread execution fault (the pipeline queue sites fire any
    # armed kind) must arrive at the client as the marshalled RuntimeError
    faults.configure("error:pipeline.put:1", seed=1)
    # the exchange layer may rewrap the worker fault ("shuffle map stage
    # failed"); the contract is a typed RuntimeError arriving client-side
    with pytest.raises(RuntimeError,
                       match="fault-injection|shuffle map stage failed"):
        cli.submit(SQL)
    faults.reset()
    # the endpoint survives: next submission is clean
    assert cli.submit(SQL).num_rows > 0


# -- wire-level faults --------------------------------------------------------

def test_corrupt_result_batch_detected_by_crc(served):
    spark, ep, cli = served
    faults.configure("corrupt:endpoint.corrupt:1", seed=1)
    with pytest.raises(TransportError, match="checksum mismatch"):
        cli.submit(SQL)
    faults.reset()
    assert cli.submit(SQL).num_rows > 0


def test_accept_fault_drops_connection_then_recovers(served):
    spark, ep, cli = served
    faults.configure("transport:endpoint.accept:1", seed=1)
    with pytest.raises(TransportError):
        cli.submit(SQL)
    faults.reset()
    assert cli.submit(SQL).num_rows > 0


def test_send_fault_cancels_query_no_leak(served):
    spark, ep, cli = served
    base = _counter(M.CLIENT_DISCONNECTS)
    faults.configure("transport:endpoint.send:1", seed=1)
    with pytest.raises(TransportError):
        cli.submit(SQL)
    faults.reset()
    assert _wait(lambda: ep.active_queries() == 0)
    assert _counter(M.CLIENT_DISCONNECTS) == base + 1
    assert _wait(_no_endpoint_threads)


def test_recv_fault_closes_connection(served):
    spark, ep, cli = served
    faults.configure("transport:endpoint.recv:1", seed=1)
    with pytest.raises(TransportError):
        cli.submit(SQL)
    faults.reset()
    assert cli.submit(SQL).num_rows > 0


# -- disconnect-driven cancellation ------------------------------------------

@pytest.mark.parametrize("rst", [False, True])
def test_client_disconnect_cancels_query(served, rst):
    spark, ep, cli = served
    base_cancel = _counter(M.QUERIES_CANCELLED)
    base_disc = _counter(M.CLIENT_DISCONNECTS)
    # hold the query mid-aggregation so the kill deterministically lands
    # while it is in flight
    faults.configure("slow:agg.update:8", seed=1)
    sock = cli.connect()
    send_frame(sock, MSG_SUBMIT, json.dumps({"sql": SQL}).encode())
    time.sleep(0.3)
    if rst:
        # RST, not FIN: linger-0 close aborts the connection
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        __import__("struct").pack("ii", 1, 0))
    sock.close()
    assert _wait(lambda: ep.active_queries() == 0)
    faults.reset()
    assert _counter(M.QUERIES_CANCELLED) == base_cancel + 1
    assert _counter(M.CLIENT_DISCONNECTS) == base_disc + 1
    assert _wait(_no_endpoint_threads)
    # the engine is intact: a fresh submission is bit-identical to direct
    assert cli.submit(SQL).to_pylist() == spark.sql(SQL).collect().to_pylist()


def test_abandoned_stream_iterator_cancels(served):
    spark, ep, cli = served
    base_disc = _counter(M.CLIENT_DISCONNECTS)
    faults.configure("slow:agg.update:8", seed=1)
    it = cli.submit_iter(SQL)
    it.close()    # abandoning the generator closes the connection
    faults.reset()
    assert _wait(lambda: ep.active_queries() == 0)
    assert _counter(M.CLIENT_DISCONNECTS) >= base_disc  # may win the race
    assert _wait(_no_endpoint_threads)


# -- timeouts -----------------------------------------------------------------

def test_idle_connection_closed():
    spark = _session({"spark.rapids.tpu.endpoint.idleTimeoutSeconds": 0.2})
    ep = QueryEndpoint(spark)
    try:
        sock = socket.create_connection(("127.0.0.1", ep.port), timeout=5)
        sock.settimeout(5)
        # send nothing: the server's idle timeout must close the connection
        assert sock.recv(1) == b""
        sock.close()
    finally:
        ep.shutdown(grace_s=2)


def test_request_timeout_cancels(served):
    spark, ep, cli = served
    ep.request_timeout = 0.3
    try:
        faults.configure("slow:agg.update:12", seed=1)
        with pytest.raises(SCHED.QueryCancelledError) as ei:
            cli.submit(SQL)
        assert ei.value.reason == "request_timeout"
    finally:
        ep.request_timeout = 0.0
        faults.reset()
    assert _wait(lambda: ep.active_queries() == 0)


# -- scheduler integration ----------------------------------------------------

def test_shed_over_wire_and_retry_honors_backoff(served):
    spark, ep, cli = served
    sched = SCHED.QueryScheduler.get()
    occupant = f"ep-test-occ-{id(cli):x}"
    sched.submit(occupant, 1, description="test occupant")
    saved = sched.max_concurrent
    sched.max_concurrent = 1
    try:
        with pytest.raises(SCHED.QueryRejectedError) as ei:
            cli.submit(SQL, queue_timeout_s=0.05)
        assert ei.value.retryable and ei.value.backoff_hint_s > 0
        assert ei.value.reason in ("queue_timeout", "queue_full")

        # submit_with_retry: first attempt sheds, occupant releases during
        # the hinted backoff, the retry succeeds
        attempts = []

        def on_retry(attempt, delay):
            attempts.append((attempt, delay))
            sched.max_concurrent = saved
            sched.release(occupant)

        out = cli.submit_with_retry(SQL, max_attempts=4,
                                    queue_timeout_s=0.05, on_retry=on_retry)
        assert out.num_rows > 0 and len(attempts) == 1
    finally:
        sched.max_concurrent = saved
        sched.release(occupant)


def test_priority_and_deadline_forwarded(served):
    spark, ep, cli = served
    # a 1ms deadline must kill the query with the typed deadline error
    with pytest.raises(SCHED.QueryDeadlineError):
        cli.submit(SQL, deadline_s=0.001)
    assert _wait(lambda: ep.active_queries() == 0)


# -- graceful drain -----------------------------------------------------------

def test_drain_finishes_in_flight_and_sheds_new(served):
    spark, ep, cli = served
    direct = spark.sql(SQL).collect().to_pylist()
    faults.configure("slow:agg.update:6", seed=1)
    res = {}

    def bg():
        c2 = EndpointClient(("127.0.0.1", ep.port), timeout_s=30)
        res["rows"] = c2.submit(SQL).to_pylist()

    t = threading.Thread(target=bg, daemon=True)
    t.start()
    time.sleep(0.3)
    dr = {}
    dt = threading.Thread(target=lambda: dr.update(ep.shutdown(grace_s=30)),
                          daemon=True)
    dt.start()
    assert _wait(lambda: ep.draining, 5)
    with pytest.raises(SCHED.QueryRejectedError) as ei:
        cli.submit(SQL)
    assert ei.value.reason == "draining" and ei.value.backoff_hint_s > 0
    t.join(30)
    dt.join(30)
    faults.reset()
    assert res["rows"] == direct
    assert dr["leaked"] == 0
    assert _wait(_no_endpoint_threads)


def test_drain_hard_kills_past_grace(served):
    spark, ep, cli = served
    faults.configure("slow:agg.update:40", seed=1)   # ~10s of slow
    err = {}

    def bg():
        c2 = EndpointClient(("127.0.0.1", ep.port), timeout_s=30)
        try:
            c2.submit(SQL)
        except BaseException as e:  # noqa: BLE001
            err["e"] = e

    t = threading.Thread(target=bg, daemon=True)
    t.start()
    time.sleep(0.3)
    stats = ep.shutdown(grace_s=0.2)
    t.join(30)
    faults.reset()
    assert stats["cancelled"] >= 1 and stats["leaked"] == 0
    assert isinstance(err.get("e"), SCHED.QueryCancelledError)
    # the drain reason survives the wire (lossless cancel pickle)
    assert err["e"].reason == "drain"
    assert _wait(_no_endpoint_threads)


# -- backpressure -------------------------------------------------------------

def test_result_stream_bounds_bytes_and_unblocks_on_close():
    rs = _ResultStream(max_bytes=100)
    assert rs.put(b"x" * 80)
    state = {}

    def producer():
        state["second"] = rs.put(b"y" * 80)   # over budget: blocks

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.2)
    assert t.is_alive()                       # blocked on the byte budget
    kind, payload = rs.get(timeout=1)
    assert kind == "batch" and payload == b"x" * 80
    t.join(5)
    assert state["second"] is True            # freed capacity admitted it
    # close unblocks a blocked producer with False
    rs2 = _ResultStream(max_bytes=10)
    assert rs2.put(b"a" * 50)                 # oversized-but-empty admitted
    done = {}

    def p2():
        done["r"] = rs2.put(b"b" * 50)

    t2 = threading.Thread(target=p2, daemon=True)
    t2.start()
    time.sleep(0.1)
    rs2.close()
    t2.join(5)
    assert done["r"] is False


# -- exception pickle round-trips (the wire's error channel) ------------------

def test_device_oom_pickle_roundtrip():
    e = DeviceOomError("hbm exhausted", requested=1024, budget=512,
                       spillable_bytes=100, pinned_bytes=50, injected=True)
    rt = pickle.loads(pickle.dumps(e))
    assert type(rt) is DeviceOomError and rt.retryable
    assert (str(rt), rt.requested, rt.budget, rt.spillable_bytes,
            rt.pinned_bytes, rt.injected) == (
        "hbm exhausted", 1024, 512, 100, 50, True)
    # the subclass survives too (split demand is part of the contract)
    s = SplitAndRetryOom("must split", requested=7)
    rt2 = pickle.loads(pickle.dumps(s))
    assert type(rt2) is SplitAndRetryOom and rt2.requested == 7


def test_transport_and_spill_errors_pickle_roundtrip():
    e = TransportError("peer 1.2.3.4 fetch failed: reset")
    rt = pickle.loads(pickle.dumps(e))
    assert type(rt) is TransportError and rt.retryable
    assert str(rt) == str(e)
    c = SpillCorruptionError("spill crc mismatch tier=disk")
    rtc = pickle.loads(pickle.dumps(c))
    assert type(rtc) is SpillCorruptionError and rtc.retryable
    assert str(rtc) == str(c)


def test_cancelled_error_pickle_roundtrip():
    e = SCHED.QueryCancelledError("q died", query_id="q7",
                                  reason="client_disconnect")
    rt = pickle.loads(pickle.dumps(e))
    assert type(rt) is SCHED.QueryCancelledError
    assert rt.query_id == "q7" and rt.reason == "client_disconnect"
    d = SCHED.QueryDeadlineError("too slow", query_id="q8")
    rtd = pickle.loads(pickle.dumps(d))
    assert type(rtd) is SCHED.QueryDeadlineError and rtd.reason == "deadline"
