"""Mortgage ETL application benchmark (reference MortgageSpark.scala role):
pipe-delimited CSV scans -> delinquency aggregation -> join -> features ->
summary, checked against an independent single-pass oracle, plus the
parquet write/readback leg."""

import pytest

from spark_rapids_tpu.benchmarks import mortgage
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    d = tmp_path_factory.mktemp("mortgage")
    return mortgage.generate(0.01, str(d))


def test_mortgage_etl_matches_oracle(data):
    spark = TpuSession()
    got = [tuple(r.values()) for r in
           mortgage.etl(spark, data).collect().to_pylist()]
    exp = mortgage.np_oracle(data)
    assert len(got) == len(exp) == 3
    for g, e in zip(got, exp):
        assert g[:5] == e[:5], (g, e)
        assert g[5] == pytest.approx(e[5], rel=1e-9)
        assert g[6] == e[6]


def test_mortgage_etl_writes_features(data, tmp_path):
    spark = TpuSession()
    out = str(tmp_path / "features")
    mortgage.etl(spark, data, write_dir=out)
    back = spark.read_parquet(out).collect()
    exp = mortgage.np_oracle(data)
    assert back.num_rows == sum(e[1] for e in exp)
    cols = set(back.column_names)
    assert {"loan_id", "ever_30", "ever_90", "ever_180",
            "max_dq"} <= cols
