"""Column-pruning pass (plan/pruning.py) — the Catalyst ColumnPruning/
SchemaPruning analog feeding narrowed read schemas to the scans."""

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import spark_rapids_tpu.functions as F
import spark_rapids_tpu.io.readers as R
from spark_rapids_tpu.session import TpuSession


@pytest.fixture
def scan_spy(monkeypatch):
    """Record the column list every parquet read_file call receives."""
    seen = []
    orig = R.ParquetReader.read_file

    def spy(self, path, columns, filt, batch_rows):
        seen.append(tuple(columns or ()))
        return orig(self, path, columns, filt, batch_rows)
    monkeypatch.setattr(R.ParquetReader, "read_file", spy)
    return seen


@pytest.fixture
def wide_file(tmp_path):
    t = pa.table({
        "a": pa.array(range(100), pa.int64()),
        "b": pa.array([i * 2 for i in range(100)], pa.int64()),
        "c": pa.array([float(i) for i in range(100)]),
        "d": pa.array([str(i % 7) for i in range(100)]),
        "e": pa.array([i % 3 == 0 for i in range(100)]),
    })
    p = str(tmp_path / "wide.parquet")
    pq.write_table(t, p)
    return p, t


def test_scan_reads_only_selected_columns(wide_file, scan_spy):
    p, t = wide_file
    spark = TpuSession()
    out = spark.read_parquet(p).select("b", "d").collect()
    assert set(scan_spy) == {("b", "d")}
    assert out.column("b").to_pylist() == t.column("b").to_pylist()
    assert out.column("d").to_pylist() == t.column("d").to_pylist()


def test_filter_columns_survive_narrowing(wide_file, scan_spy):
    """A filter on a non-projected column must keep that column readable,
    and ordinals above the narrowed scan must rebind."""
    p, t = wide_file
    spark = TpuSession()
    out = (spark.read_parquet(p)
           .filter(F.col("a") > 90)
           .select(F.col("d"), (F.col("c") * 2).alias("c2"))).collect()
    assert set(scan_spy) == {("a", "c", "d")}
    assert out.column("d").to_pylist() == [str(i % 7) for i in range(91, 100)]
    assert out.column("c2").to_pylist() == [i * 2.0 for i in range(91, 100)]


def test_remap_across_join_and_sort(tmp_path, scan_spy):
    """Ordinal rebinding across a join (both sides narrowed by different
    amounts) and an ORDER BY on a non-projected-first column."""
    left = pa.table({
        "k": pa.array([1, 2, 3, 4], pa.int64()),
        "lv": pa.array([10.0, 20.0, 30.0, 40.0]),
        "junk1": pa.array(["x"] * 4),
    })
    right = pa.table({
        "k2": pa.array([2, 3, 4, 5], pa.int64()),
        "rv": pa.array([200, 300, 400, 500], pa.int64()),
        "junk2": pa.array([0.5] * 4),
        "junk3": pa.array([False] * 4),
    })
    lp, rp = str(tmp_path / "l.parquet"), str(tmp_path / "r.parquet")
    pq.write_table(left, lp)
    pq.write_table(right, rp)
    spark = TpuSession()
    df = (spark.read_parquet(lp)
          .join(spark.read_parquet(rp).select(
              F.col("k2").alias("k"), F.col("rv")), on="k")
          .select(F.col("lv"), F.col("rv"))
          .sort(F.col("rv"), ascending=False))
    rows = df.collect().to_pylist()
    assert rows == [{"lv": 40.0, "rv": 400},
                    {"lv": 30.0, "rv": 300},
                    {"lv": 20.0, "rv": 200}]
    assert ("k", "lv") in scan_spy and ("k2", "rv") in scan_spy
    assert all("junk1" not in c and "junk2" not in c for c in scan_spy)


def test_partition_columns_survive(tmp_path, scan_spy):
    base = tmp_path / "part"
    for part in ("p=1", "p=2"):
        d = base / part
        d.mkdir(parents=True)
        pq.write_table(pa.table({"x": pa.array([1, 2], pa.int64()),
                                 "y": pa.array([0.1, 0.2])}),
                       str(d / "f.parquet"))
    spark = TpuSession()
    out = spark.read_parquet(str(base)).select("x", "p").collect()
    assert sorted(out.column("p").to_pylist()) == [1, 1, 2, 2]
    assert set(scan_spy) == {("x",)}   # y pruned; p is a partition constant


def test_aggregate_narrow(wide_file, scan_spy):
    p, t = wide_file
    spark = TpuSession()
    out = (spark.read_parquet(p).group_by("d")
           .agg(F.sum(F.col("b")).alias("sb"))).collect()
    assert set(scan_spy) == {("b", "d")}
    exp = {}
    for i in range(100):
        exp[str(i % 7)] = exp.get(str(i % 7), 0) + i * 2
    got = {r["d"]: r["sb"] for r in out.to_pylist()}
    assert got == exp


def test_cache_is_a_pruning_barrier(wide_file):
    """CacheNode subtrees return untouched (a rebuilt copy would orphan the
    materialized cache — the exact regression test_cache_materializes_once
    guards; here we assert the pass-level contract directly)."""
    from spark_rapids_tpu.plan.pruning import prune_columns
    p, _ = wide_file
    spark = TpuSession()
    df = spark.read_parquet(p).cache().select("a")
    plan = df._plan
    pruned = prune_columns(plan)
    cache_nodes = []

    def walk(n):
        if type(n).__name__ == "CacheNode":
            cache_nodes.append(n)
        for c in n.children:
            walk(c)
    walk(plan)
    pruned_caches = []

    def walk2(n):
        if type(n).__name__ == "CacheNode":
            pruned_caches.append(n)
        for c in n.children:
            walk2(c)
    walk2(pruned)
    assert cache_nodes and pruned_caches
    assert cache_nodes[0] is pruned_caches[0]


def test_identity_preserving_when_nothing_narrows(wide_file):
    from spark_rapids_tpu.plan.pruning import prune_columns
    p, _ = wide_file
    spark = TpuSession()
    # every column used -> the ORIGINAL node objects come back
    df = spark.read_parquet(p).select("a", "b", "c", "d", "e")
    assert prune_columns(df._plan) is df._plan
