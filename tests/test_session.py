"""Session/DataFrame API tests — the end-user surface driving the full stack."""

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from conftest import make_table

import spark_rapids_tpu.functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Alias
from spark_rapids_tpu.session import TpuSession
from test_plan import norm


@pytest.fixture
def spark():
    return TpuSession()


def test_create_select_filter_collect(spark, mixed_table):
    df = spark.create_dataframe(mixed_table, num_partitions=3)
    out = (df.filter(F.col("i") > 0)
             .select("i", F.alias(F.col("i") + F.col("i"), "i2"), "s")
             .collect())
    host = (df.filter(F.col("i") > 0)
              .select("i", F.alias(F.col("i") + F.col("i"), "i2"), "s")
              .collect_host())
    assert norm(out) == norm(host)
    assert out.column("i2").to_pylist() == \
        [2 * v for v in out.column("i").to_pylist()]


def test_group_by_agg(spark, mixed_table):
    df = spark.create_dataframe(mixed_table, num_partitions=2)
    out = (df.group_by("b")
             .agg(F.alias(F.sum("l"), "s"), F.alias(F.count(), "n"),
                  F.alias(F.avg("i"), "a"))
             .collect())
    assert out.num_rows == 3  # True / False / null groups
    assert sum(out.column("n").to_pylist()) == mixed_table.num_rows


def test_join_and_sort(spark):
    left = spark.create_dataframe({"k": pa.array([1, 2, 3], pa.int64()),
                                   "v": pa.array([10, 20, 30], pa.int64())})
    right = spark.create_dataframe({"k": pa.array([2, 3, 4], pa.int64()),
                                    "w": pa.array(["b", "c", "d"])})
    out = (left.join(right.with_column("k2", F.col("k")).select("k2", "w"),
                     condition=F.col("k") == F.col("k2"), how="inner",
                     on=None)
           .collect())
    # keyless join with condition → nested loop
    assert sorted(out.column("v").to_pylist()) == [20, 30]

    out2 = left.sort("v", ascending=False).collect()
    assert out2.column("v").to_pylist() == [30, 20, 10]


def test_with_column_count_limit(spark):
    df = spark.range(100, num_slices=4)
    df2 = df.with_column("sq", F.col("id") * F.col("id"))
    assert df2.count() == 100
    out = df2.limit(5).collect()
    assert out.num_rows == 5
    assert df2.columns == ["id", "sq"]


def test_window_api(spark):
    df = spark.create_dataframe({
        "g": pa.array([1, 1, 2, 2], pa.int64()),
        "o": pa.array([2, 1, 2, 1], pa.int32()),
        "v": pa.array([1.0, 2.0, 3.0, 4.0])})
    out = df.window([
        F.alias(F.over(F.row_number(), partition_by=[F.col("g")],
                       order_by=[F.col("o")]), "rn"),
        F.alias(F.over(F.sum("v"), partition_by=[F.col("g")],
                       order_by=[F.col("o")]), "cs"),
    ]).collect()
    rows = sorted(zip(out["g"].to_pylist(), out["o"].to_pylist(),
                      out["rn"].to_pylist(), out["cs"].to_pylist()))
    assert rows == [(1, 1, 1, 2.0), (1, 2, 2, 3.0),
                    (2, 1, 1, 4.0), (2, 2, 2, 7.0)]


def test_read_write_roundtrip(spark, tmp_path, mixed_table):
    df = spark.create_dataframe(mixed_table, num_partitions=2)
    out_dir = str(tmp_path / "t")
    stats = df.write_parquet(out_dir)
    assert stats.num_rows == mixed_table.num_rows
    back = spark.read_parquet(out_dir).collect()
    assert norm(back) == norm(mixed_table)


def test_read_with_pushdown(spark, tmp_path):
    t = pa.table({"a": pa.array(range(1000), pa.int64())})
    pq.write_table(t, tmp_path / "x.parquet")
    df = spark.read_parquet(str(tmp_path / "x.parquet"),
                            pushed_filter=F.col("a") >= F.lit(990))
    assert df.count() == 10


def test_explain(spark, mixed_table):
    df = spark.create_dataframe(mixed_table).filter(F.col("i") > 0)
    txt = df.explain()
    assert "will run on TPU" in txt


def test_case_when_cast(spark):
    df = spark.create_dataframe({"x": pa.array([-5, 0, 7], pa.int64())})
    out = df.select(
        F.alias(F.if_(F.col("x") > 0, F.lit("pos"), F.lit("nonpos")), "sign"),
        F.alias(F.cast(F.col("x"), T.STRING), "s"),
    ).collect()
    assert out.column("sign").to_pylist() == ["nonpos", "nonpos", "pos"]
    assert out.column("s").to_pylist() == ["-5", "0", "7"]


def test_when_otherwise_like_rdiv(spark):
    df = spark.create_dataframe({"x": pa.array([-5, 0, 7], pa.int64()),
                                 "s": pa.array(["abc", "axx", "zzz"])})
    out = df.select(
        F.alias(F.when(F.col("x") > 0, "pos").when(F.col("x") == 0, "zero")
                .otherwise("neg"), "sign"),
        F.alias(F.like(F.col("s"), "a%"), "m"),
        F.alias(1.0 / F.cast(F.col("x"), T.DOUBLE), "inv"),
    ).collect()
    assert out.column("sign").to_pylist() == ["neg", "zero", "pos"]
    assert out.column("m").to_pylist() == [True, True, False]
    assert out.column("inv").to_pylist() == [-0.2, None, pytest.approx(1 / 7)]


def test_dataframe_reusable_across_actions(spark):
    """Planning one action must not mutate the logical plan: a second action on
    the same DataFrame (partially host, partially device) must be correct."""
    t = pa.table({"k": pa.array([1, 2, 1, 3, 2, 1]),
                  "v": pa.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])})
    df = (spark.create_dataframe(t, num_partitions=2)
          .filter(F.col("v") > 1.5)
          .group_by(F.col("k"))
          .agg(F.sum(F.col("v")).alias("s")))
    first = norm(df.collect())
    second = norm(df.collect())
    assert first == second


def test_dataframe_api_completeness():
    """distinct/drop/rename/sortWithinPartitions (pyspark-surface parity)."""
    import pyarrow as pa
    from spark_rapids_tpu.session import TpuSession
    spark = TpuSession()
    df = spark.create_dataframe({
        "a": pa.array([3, 1, 3, 2, 1], pa.int64()),
        "b": pa.array([1.0, 2.0, 1.0, 3.0, 2.0])}, num_partitions=2)
    d = df.distinct().collect()
    assert sorted(zip(d["a"].to_pylist(), d["b"].to_pylist())) == \
        [(1, 2.0), (2, 3.0), (3, 1.0)]
    assert df.drop("b").columns == ["a"]
    assert df.with_column_renamed("a", "x").columns == ["x", "b"]
    swp = df.sort_within_partitions("a").collect()
    # each partition independently ordered (partitions of sizes 3 and 2)
    vals = swp["a"].to_pylist()
    assert vals[:3] == sorted(vals[:3]) and vals[3:] == sorted(vals[3:])


def test_dataframe_rollup():
    """df.rollup(a, b).agg(...) produces base + subtotal + grand-total rows
    (Spark rollup; same Expand lowering as SQL's GROUP BY ROLLUP)."""
    import pyarrow as pa
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.session import TpuSession
    spark = TpuSession()
    t = pa.table({"a": pa.array(["x", "x", "y"]),
                  "b": pa.array(["p", "q", "p"]),
                  "v": pa.array([1.0, 2.0, 4.0])})
    out = (spark.create_dataframe(t).rollup("a", "b")
           .agg(F.sum(F.col("v")).alias("s"), F.count().alias("n"))
           .collect().to_pylist())
    rows = {(r["a"], r["b"]): (r["s"], r["n"]) for r in out}
    assert rows == {
        ("x", "p"): (1.0, 1), ("x", "q"): (2.0, 1), ("y", "p"): (4.0, 1),
        ("x", None): (3.0, 2), ("y", None): (4.0, 1),
        (None, None): (7.0, 3),
    }, rows


def test_dataframe_rollup_alias_collision_and_validation():
    import pyarrow as pa
    import pytest
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.session import TpuSession
    spark = TpuSession()
    t = pa.table({"a": pa.array(["x", "x", "y"]),
                  "v": pa.array([1.0, 2.0, 4.0])})
    df = spark.create_dataframe(t)
    # agg alias colliding with the key name stays a distinct column
    out = df.rollup("a").agg(F.max(F.col("v")).alias("a")).collect()
    assert out.num_columns == 2
    rows = {r[0]: r[1] for r in zip(out.column(0).to_pylist(),
                                    out.column(1).to_pylist())}
    assert rows == {"x": 2.0, "y": 4.0, None: 4.0}, rows
    # non-aggregate expressions are a plan-time error
    with pytest.raises(ValueError, match="aggregate expressions"):
        df.rollup("a").agg(F.col("v"))
