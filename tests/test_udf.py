"""UDF compiler + python runtime tests (reference udf-compiler suites +
cudf_udf pandas tests, SURVEY.md #38-40)."""

import math

import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Alias, col, lit
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.udf.compiler import compile_udf, udf
from spark_rapids_tpu.udf.python_runtime import PythonUDF


@pytest.fixture
def spark():
    return TpuSession()


def test_compile_arithmetic():
    e = compile_udf(lambda x, y: x * 2 + y - 1, [col("a"), col("b")])
    assert e is not None
    assert "2" in repr(e)


def test_compile_ternary_branches(spark):
    fn = lambda x: (x * 2) if x > 0 else -x  # noqa: E731
    df = spark.create_dataframe({"x": pa.array([-3, 0, 5], pa.int64())})
    e = compile_udf(fn, [col("x")])
    assert e is not None
    out = df.select(F.alias(e, "y")).collect()
    assert out.column("y").to_pylist() == [fn(-3), fn(0), fn(5)]


def test_compile_math_and_builtins(spark):
    fn = lambda x: math.sqrt(abs(x)) + 1.0  # noqa: E731
    e = compile_udf(fn, [col("x")])
    assert e is not None
    df = spark.create_dataframe({"x": pa.array([-4.0, 9.0], pa.float64())})
    out = df.select(F.alias(e, "y")).collect()
    assert out.column("y").to_pylist() == [3.0, 4.0]


def test_compile_string_methods(spark):
    fn = lambda s: s.upper()  # noqa: E731
    e = compile_udf(fn, [col("s")])
    assert e is not None
    df = spark.create_dataframe({"s": pa.array(["ab", "Cd"])})
    assert df.select(F.alias(e, "u")).collect()["u"].to_pylist() == \
        ["AB", "CD"]


def test_compile_closure_constant():
    k = 10
    e = compile_udf(lambda x: x + k, [col("a")])
    assert e is not None and "10" in repr(e)


def test_uncompilable_returns_none():
    import os
    assert compile_udf(lambda x: os.getpid() + x, [col("a")]) is None
    assert compile_udf(lambda x: [v for v in range(x)], [col("a")]) is None


def test_udf_factory_compiled_runs_on_device(spark):
    double = udf(lambda x: x * 2)
    df = spark.create_dataframe({"a": pa.array([1, 2, 3], pa.int64())})
    plan_df = df.select(F.alias(double(F.col("a")), "d"))
    assert "will run on TPU" in plan_df.explain()
    assert plan_df.collect()["d"].to_pylist() == [2, 4, 6]


def test_udf_fallback_python_worker(spark):
    """Uncompilable UDF runs through the arrow worker-process exchange."""
    def weird(x):
        return int(str(x)[::-1]) if x is not None else None

    rev = udf(weird, return_type=T.LONG)
    df = spark.create_dataframe({"a": pa.array([123, 450, None], pa.int64())},
                                num_partitions=2)
    e = rev(F.col("a"))
    assert isinstance(e, PythonUDF)
    out = df.select("a", F.alias(e, "r")).collect()
    rows = dict(zip(out["a"].to_pylist(), out["r"].to_pylist()))
    assert rows == {123: 321, 450: 54, None: None}


def test_udf_fallback_requires_return_type():
    with pytest.raises(ValueError, match="return_type"):
        udf(lambda x: complex(x))(F.col("a"))


def test_vectorized_pandas_udf(spark):
    """pandas (series→series) UDF — the reference's cudf_udf / pandas path."""
    def plus_mean(s):
        return s + s.mean()

    pudf = PythonUDF(plus_mean, [col("v")], T.DOUBLE, vectorized=True)
    df = spark.create_dataframe({"v": pa.array([1.0, 2.0, 3.0])})
    out = df.select(F.alias(pudf, "r")).collect()
    assert out["r"].to_pylist() == [3.0, 4.0, 5.0]


def test_compile_and_or_shortcircuit(spark):
    fn = lambda x: x > 0 and x < 10  # noqa: E731
    e = compile_udf(fn, [col("x")])
    assert e is not None
    df = spark.create_dataframe({"x": pa.array([-1, 5, 20], pa.int64())})
    assert df.select(F.alias(e, "m")).collect()["m"].to_pylist() == \
        [False, True, False]
    fn2 = lambda x: x < 0 or x > 10  # noqa: E731
    e2 = compile_udf(fn2, [col("x")])
    assert e2 is not None
    assert df.select(F.alias(e2, "m")).collect()["m"].to_pylist() == \
        [True, False, True]


def test_udf_in_filter_extracted_to_projection(spark):
    """ExtractPythonUDFs analog: a UDF inside a filter condition is pulled
    into an ArrowEvalPythonExec projection and the residual comparison stays
    a device filter (reference GpuArrowEvalPythonExec family, VERDICT r1
    weak #6)."""
    rev = udf(lambda x: int(str(abs(x))[::-1]) if x else 0, return_type=T.LONG)
    df = spark.create_dataframe({"a": pa.array([12, 340, 5], pa.int64())})
    e = rev(F.col("a"))
    assert isinstance(e, PythonUDF)
    fdf = df.filter(e > F.lit(20))
    plan = fdf.explain()
    assert "outside a projection" not in plan
    out = fdf.collect()  # udf via worker pool, comparison+filter on device
    assert sorted(out["a"].to_pylist()) == [12, 340]
    assert list(out.schema.names) == ["a"]  # temp __pyudf_ column dropped


def test_udf_filter_combined_with_device_predicate(spark):
    rev = udf(lambda x: int(str(abs(x))[::-1]) if x else 0, return_type=T.LONG)
    df = spark.create_dataframe(
        {"a": pa.array([12, 340, 5, None, 77], pa.int64())}, num_partitions=2)
    fdf = df.filter((rev(F.col("a")) > F.lit(20)) & (F.col("a") < F.lit(100)))
    out = fdf.collect()
    assert sorted(out["a"].to_pylist()) == [12, 77]


def test_udf_infinite_loop_falls_back():
    """`while True: pass` must return None (host fallback) quickly, not hang
    the symbolic executor (ADVICE r1)."""
    from spark_rapids_tpu.udf.compiler import compile_udf

    def bad(x):
        while True:
            pass

    assert compile_udf(bad, ["x"]) is None


def test_nested_udf_in_filter(spark):
    """Nested PythonUDFs extract only the OUTERMOST call; the inner one is
    evaluated inside it (no dead projected column)."""
    inner = udf(lambda x: x * 3 if x is not None else None, return_type=T.LONG)
    outer = udf(lambda x: x + 1 if x is not None else None, return_type=T.LONG)
    df = spark.create_dataframe({"a": pa.array([1, 5, None, 10], pa.int64())})
    fdf = df.filter(outer(inner(F.col("a"))) > F.lit(10))
    out = fdf.collect()
    # 3a+1 > 10 → a in {5, 10}
    assert sorted(out["a"].to_pylist()) == [5, 10]
    assert list(out.schema.names) == ["a"]


def test_udf_in_group_key_extracted(spark):
    """A UDF group key rides ArrowEvalPythonExec below a DEVICE aggregate
    (Spark ExtractPythonUDFs covers aggregates the same way)."""
    bucket = udf(lambda x: int(str(abs(x))[0]) if x else 0, return_type=T.LONG)
    df = spark.create_dataframe({
        "a": pa.array([11, 19, 25, 31, 22], pa.int64())}, num_partitions=2)
    q = df.group_by(F.alias(bucket(F.col("a")), "b")).agg(
        F.alias(F.count(F.col("a")), "c"))
    plan = q.explain()
    assert "outside a projection" not in plan
    rows = {r["b"]: r["c"] for r in q.collect().to_pylist()}
    assert rows == {1: 2, 2: 2, 3: 1}


def test_udf_in_agg_input_extracted(spark):
    rev = udf(lambda x: int(str(abs(x))[::-1]) if x else 0, return_type=T.LONG)
    df = spark.create_dataframe({
        "k": pa.array([1, 1, 2], pa.int64()),
        "a": pa.array([12, 34, 56], pa.int64())})
    q = df.group_by("k").agg(F.alias(F.sum(rev(F.col("a"))), "s"))
    assert "outside a projection" not in q.explain()
    rows = {r["k"]: r["s"] for r in q.collect().to_pylist()}
    assert rows == {1: 21 + 43, 2: 65}


def test_udf_in_sort_key_extracted(spark):
    rev = udf(lambda x: int(str(abs(x))[::-1]) if x else 0, return_type=T.LONG)
    df = spark.create_dataframe({
        "a": pa.array([12, 91, 40, 55], pa.int64())})
    q = df.sort(rev(F.col("a")))       # keys: 21, 19, 4, 55
    assert "outside a projection" not in q.explain()
    assert q.collect()["a"].to_pylist() == [40, 91, 12, 55]
    assert list(q.collect().schema.names) == ["a"]   # temp col dropped


def test_udf_reused_in_filter_projects_once(spark):
    """Structural dedupe: the same UDF call reused in one condition feeds
    every use site from ONE projected column (bind_references copies
    expression objects, so identity dedupe would miss this)."""
    rev = udf(lambda x: int(str(abs(x))[::-1]) if x else 0, return_type=T.LONG)
    df = spark.create_dataframe({"a": pa.array([12, 91, 40], pa.int64())})
    e = rev(F.col("a"))
    fdf = df.filter((e > F.lit(10)) & (e < F.lit(60)))   # 21, 19, 4
    plan = fdf.explain()
    assert plan.count("@PythonUDF") == 1   # one projected column, not two
    assert sorted(fdf.collect()["a"].to_pylist()) == [12, 91]
