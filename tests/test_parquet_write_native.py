"""Native (device-encode) Parquet writer tests — VERDICT r3 weak #7.

Round-trips files produced by io/parquet_write_native through BOTH pyarrow
(independent reader — framing/thrift must be spec-exact) and the engine's own
scan path. Reference suite analog: ParquetWriterSuite.scala."""

import datetime
import decimal
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.io import FileScanNode
from spark_rapids_tpu.io.parquet_write_native import (
    NativeParquetFile, supports_schema, write_batch_file)

UTC = datetime.timezone.utc


@pytest.fixture
def spark():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession()


@pytest.fixture
def spark_factory():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession


@pytest.fixture
def typed_table():
    return pa.table({
        "i64": pa.array([5, None, 3, -2**40, 0], pa.int64()),
        "i32": pa.array([1, 2, None, -4, 5], pa.int32()),
        "i16": pa.array([1, None, -3, 4, 5], pa.int16()),
        "i8": pa.array([7, None, 2, 1, -1], pa.int8()),
        "f32": pa.array([1.0, None, 2.5, -3.25, 0.5], pa.float32()),
        "f64": pa.array([1.5, float("nan"), None, -0.25, 2.0], pa.float64()),
        "b": pa.array([True, False, None, True, False], pa.bool_()),
        "s": pa.array(["b", "a", None, "cc", "a"], pa.string()),
        "dt": pa.array([datetime.date(2020, 1, 1), None,
                        datetime.date(1969, 12, 31),
                        datetime.date(2024, 2, 29),
                        datetime.date(1970, 1, 1)], pa.date32()),
        "ts": pa.array([datetime.datetime(2020, 1, 1, 12, 30, tzinfo=UTC),
                        None, None,
                        datetime.datetime(1960, 5, 5, tzinfo=UTC),
                        datetime.datetime(2038, 1, 19, 3, 14, tzinfo=UTC)],
                       pa.timestamp("us", tz="UTC")),
        "dec": pa.array([decimal.Decimal("12.34"), None,
                         decimal.Decimal("-0.01"),
                         decimal.Decimal("99999.99"),
                         decimal.Decimal("0.00")], pa.decimal128(10, 2)),
    })


def _pylist_eq(got: pa.Table, exp: pa.Table):
    assert got.num_rows == exp.num_rows
    for name in exp.column_names:
        g, e = got.column(name).to_pylist(), exp.column(name).to_pylist()
        for a, b in zip(g, e):
            if (isinstance(a, float) and isinstance(b, float)
                    and np.isnan(b)):
                assert np.isnan(a), (name, a, b)
            else:
                assert a == b, (name, a, b)


@pytest.mark.parametrize("codec", ["snappy", "gzip", "uncompressed"])
def test_roundtrip_pyarrow_all_types(tmp_path, typed_table, codec):
    batch = ColumnarBatch.from_arrow(typed_table)
    path = str(tmp_path / "t.parquet")
    write_batch_file(path, batch, batch.schema, codec)
    back = pq.read_table(path)
    # types survive exactly (logical/converted types in the thrift schema)
    for name in typed_table.column_names:
        assert back.column(name).type == typed_table.column(name).type, name
    _pylist_eq(back, typed_table)


def test_roundtrip_own_reader(tmp_path, typed_table):
    batch = ColumnarBatch.from_arrow(typed_table)
    path = str(tmp_path / "t.parquet")
    write_batch_file(path, batch, batch.schema, "snappy")
    got = FileScanNode(path, "parquet").collect_host()
    _pylist_eq(got, typed_table)


def test_statistics_written(tmp_path, typed_table):
    batch = ColumnarBatch.from_arrow(typed_table)
    path = str(tmp_path / "t.parquet")
    write_batch_file(path, batch, batch.schema, "snappy")
    md = pq.ParquetFile(path).metadata
    by_name = {md.row_group(0).column(i).path_in_schema:
               md.row_group(0).column(i).statistics
               for i in range(md.num_columns)}
    assert by_name["i64"].min == -2**40 and by_name["i64"].max == 5
    assert by_name["i64"].null_count == 1
    assert by_name["s"].min == "a" and by_name["s"].max == "cc"
    assert by_name["b"].min is False and by_name["b"].max is True
    # f64 contains NaN -> min/max suppressed, null_count still honest
    assert by_name["f64"].null_count == 1


def test_multiple_row_groups(tmp_path):
    tbl = pa.table({"x": pa.array(range(100), pa.int64())})
    b1 = ColumnarBatch.from_arrow(tbl.slice(0, 60))
    b2 = ColumnarBatch.from_arrow(tbl.slice(60, 40))
    path = str(tmp_path / "t.parquet")
    f = NativeParquetFile(path, b1.schema, "gzip")
    f.append_batch(b1)
    f.append_batch(b2)
    f.close()
    md = pq.ParquetFile(path).metadata
    assert md.num_row_groups == 2
    assert [md.row_group(i).num_rows for i in range(2)] == [60, 40]
    assert pq.read_table(path).column("x").to_pylist() == list(range(100))


def test_all_null_and_empty_strings(tmp_path):
    tbl = pa.table({
        "s": pa.array([None, None, None], pa.string()),
        "i": pa.array([None, None, None], pa.int64()),
        "e": pa.array(["", "x", ""], pa.string()),
    })
    batch = ColumnarBatch.from_arrow(tbl)
    path = str(tmp_path / "t.parquet")
    write_batch_file(path, batch, batch.schema, "snappy")
    _pylist_eq(pq.read_table(path), tbl)


def test_zero_rows(tmp_path):
    tbl = pa.table({"x": pa.array([], pa.int64()),
                    "s": pa.array([], pa.string())})
    batch = ColumnarBatch.from_arrow(tbl)
    path = str(tmp_path / "t.parquet")
    write_batch_file(path, batch, batch.schema, "snappy")
    back = pq.read_table(path)
    assert back.num_rows == 0
    assert back.column_names == ["x", "s"]


def test_unsupported_schema_probe():
    assert not supports_schema(T.StructType([
        T.StructField("a", T.ArrayType(T.INT), True)]))
    assert supports_schema(T.StructType([
        T.StructField("a", T.INT, True)]))


def test_session_write_uses_native(spark, tmp_path, typed_table):
    """End-to-end: DataFrame.write_parquet routes through the native encoder
    (created_by marker proves which writer produced the file)."""
    df = spark.create_dataframe(typed_table)
    out = str(tmp_path / "out")
    stats = df.write_parquet(out)
    assert stats.num_rows == typed_table.num_rows
    files = [f for f in os.listdir(out) if f.endswith(".parquet")]
    assert files
    md = pq.ParquetFile(os.path.join(out, files[0])).metadata
    assert b"spark-rapids-tpu native" in md.created_by.encode()
    back = spark.read_parquet(out).collect()
    got = pa.Table.from_arrays(
        [back.column(n) for n in typed_table.column_names],
        names=typed_table.column_names)
    _pylist_eq(got, typed_table)


def test_session_write_arrow_override(spark_factory, tmp_path):
    """writer.type=ARROW keeps the old pyarrow path."""
    spark = spark_factory({
        "spark.rapids.tpu.sql.format.parquet.writer.type": "ARROW"})
    t = pa.table({"x": pa.array([1, 2, 3], pa.int64())})
    out = str(tmp_path / "out")
    spark.create_dataframe(t).write_parquet(out)
    files = [f for f in os.listdir(out) if f.endswith(".parquet")]
    md = pq.ParquetFile(os.path.join(out, files[0])).metadata
    assert b"spark-rapids-tpu native" not in md.created_by.encode()


def test_roundtrip_device_decoder(tmp_path, monkeypatch):
    """Files from the native writer decode through the engine's own DEVICE
    parquet decode path (dictionary page + RLE indices + def levels) — the
    two halves of the native I/O stack agree on the wire format. The spy
    asserts the device path actually engaged (a scope-guard bounce would
    silently re-test the arrow host path)."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.io.filescan import FileSourceScanExec
    engaged = []
    orig = FileSourceScanExec._device_decode_batches

    def spy(self, *a, **kw):
        it = orig(self, *a, **kw)
        engaged.append(it is not None)
        return it
    monkeypatch.setattr(FileSourceScanExec, "_device_decode_batches", spy)
    tbl = pa.table({
        "i": pa.array([5, None, 3, -7, 9], pa.int64()),
        "s": pa.array(["b", "a", None, "cc", "a"], pa.string()),
        "d": pa.array([1.5, None, 2.5, -0.25, 0.0]),
    })
    batch = ColumnarBatch.from_arrow(tbl)
    path = str(tmp_path / "t.parquet")
    write_batch_file(path, batch, batch.schema, "uncompressed")
    on = TpuSession({"spark.rapids.tpu.sql.parquet.deviceDecode.enabled":
                     "true"})
    got = on.read_parquet(path).collect()
    assert engaged and all(engaged), engaged
    for n in tbl.column_names:
        assert got[n].to_pylist() == tbl[n].to_pylist(), n
