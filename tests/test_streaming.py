"""Continuous-ingestion tests (streaming/*): the crash-consistent epoch
journal protocol (begin/commit, attempt fencing, corrupt-refusal), the
durable idempotent batch log (directory tail + CRC-verified endpoint
APPEND), incremental windowed aggregation with watermark retirement and a
steady state that retraces nothing, exactly-once recovery — a crash
between begin and commit replays bit-identically, a corrupt state
snapshot rebuilds from the consumed batch log — and the staleness
contract: an APPEND through any replica invalidates every replica's
result cache via the shared fleet catalog epoch."""

import gc
import json
import os
import pathlib
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.runtime import eventlog, faults
from spark_rapids_tpu.runtime import fleet as FL
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.checksum import block_checksum
from spark_rapids_tpu.runtime.endpoint import (MSG_APPEND, EndpointClient,
                                               QueryEndpoint)
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.shuffle.transport import TransportError
from spark_rapids_tpu.streaming import (EpochCoordinator, EpochJournal,
                                        JournalCorruptError,
                                        StreamingSource, validate_doc)
from spark_rapids_tpu.streaming.journal import FILE as JOURNAL_FILE
from spark_rapids_tpu.streaming.source import ipc_to_table, table_to_ipc

REPO = pathlib.Path(__file__).resolve().parent.parent

SQL = "select k, sum(v) s, count(*) c from clicks group by k order by k"

# every coordinator in this module uses the same shape, so the compiled
# epoch kernels are shared across tests (and with the persistent cache)
KEYS, AGGS = ["k"], [("sum", "v"), ("count", "v"), ("max", "v")]


def _batch(i, rows=8):
    """Deterministic batch i: 2 keys, event time spans one 10s window."""
    base = i * 10
    return pa.table({
        "k": pa.array([j % 2 for j in range(rows)], type=pa.int64()),
        "v": pa.array([float(base + j) for j in range(rows)],
                      type=pa.float64()),
        "ts": pa.array([base + j for j in range(rows)], type=pa.int64())})


def _coord(spark, src, windowed=True, **kw):
    if windowed:
        kw.setdefault("time_column", "ts")
        kw.setdefault("window_seconds", 10)
    return EpochCoordinator(spark, src, keys=KEYS, aggs=AGGS, **kw)


def _oracle_state(tables, windowed=True):
    """Independent pyarrow recomputation of the expected state table."""
    tbl = pa.concat_tables(tables)
    group = list(KEYS)
    if windowed:
        tbl = tbl.append_column("window", pa.array(
            [t - (t % 10) for t in tbl["ts"].to_pylist()],
            type=pa.int64()))
        group.append("window")
    agg = tbl.group_by(group).aggregate(
        [("v", "sum"), ("v", "count"), ("v", "max")])
    agg = agg.rename_columns(group + ["sum_v", "count_v", "max_v"])
    return agg.sort_by([(c, "ascending") for c in group])


def _rows(tbl, group):
    """Order-and-type-insensitive row view for oracle comparison."""
    out = []
    for r in tbl.sort_by([(c, "ascending") for c in group]).to_pylist():
        out.append({k: (float(v) if isinstance(v, (int, float)) else v)
                    for k, v in r.items()})
    return out


def _wait(pred, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


@pytest.fixture(autouse=True)
def _clean_streaming_plane():
    yield
    faults.reset()
    eventlog.shutdown()


# -- journal protocol ----------------------------------------------------------

def test_journal_begin_commit_and_attempt_fencing(tmp_path):
    j = EpochJournal(str(tmp_path), source="s")
    assert j.committed_epoch() == 0 and j.pending() is None
    assert j.begin(1, ["b-0", "b-1"]) == 1
    assert j.pending()["batch_ids"] == ["b-0", "b-1"]
    # re-beginning the SAME pending epoch is the recovery replay: the
    # attempt bump is the stale-partial fence
    assert j.begin(1, ["b-0", "b-1"]) == 2
    rec = j.commit(1, state_checksum=7, state_rows=2, state_bytes=64,
                   rows_in=16)
    assert rec["attempt"] == 2 and j.committed_epoch() == 1
    assert j.pending() is None
    assert j.is_consumed("b-0") and j.is_consumed("b-1")
    assert not j.is_consumed("b-9")
    # protocol bugs raise instead of corrupting exactly-once state
    with pytest.raises(ValueError, match="out of order"):
        j.begin(3, ["b-2"])
    with pytest.raises(ValueError, match="already-consumed"):
        j.begin(2, ["b-1"])
    with pytest.raises(ValueError, match="without a matching begin"):
        j.commit(2, state_checksum=0, state_rows=0, state_bytes=0)
    j.begin(2, ["b-2"])
    with pytest.raises(ValueError, match="out of order"):
        j.begin(3, ["b-3"])     # can't skip past the pending epoch either
    # commit folds consumed + advances the epoch in ONE atomic replace
    j.commit(2, state_checksum=1, state_rows=1, state_bytes=8)
    doc = j.snapshot()
    assert doc["committed_epoch"] == 2
    assert doc["consumed"] == ["b-0", "b-1", "b-2"]
    assert validate_doc(doc) == []


def test_journal_refuses_corruption_and_validate_doc(tmp_path):
    j = EpochJournal(str(tmp_path), source="s")
    j.begin(1, ["b-0"])
    j.commit(1, state_checksum=1, state_rows=1, state_bytes=8)
    path = tmp_path / JOURNAL_FILE
    good = json.loads(path.read_text())
    # torn/garbage journal: the stream refuses to run — silently degrading
    # to empty would re-consume every committed batch
    path.write_text("{ not json")
    with pytest.raises(JournalCorruptError, match="unreadable"):
        j.snapshot()
    # schema violations are refused too, and validate_doc names them
    bad = dict(good, committed_epoch=5)
    path.write_text(json.dumps(bad))
    with pytest.raises(JournalCorruptError, match="violates its schema"):
        j.snapshot()
    assert any("last commit" in e for e in validate_doc(bad))
    assert any("not committed_epoch+1" in e for e in validate_doc(
        dict(good, begin={"epoch": 9, "attempt": 1, "batch_ids": ["x"]})))
    assert any("already-consumed" in e for e in validate_doc(
        dict(good, begin={"epoch": 2, "attempt": 1, "batch_ids": ["b-0"]})))
    assert any("not contiguous" in e for e in validate_doc(
        dict(good, commits=[dict(good["commits"][0]),
                            dict(good["commits"][0], epoch=3)])))
    assert validate_doc(good) == []
    path.write_text(json.dumps(good))
    assert j.committed_epoch() == 1


def test_journal_history_bounded_but_protocol_state_is_not(tmp_path):
    j = EpochJournal(str(tmp_path), source="s", max_commits=3)
    for e in range(1, 8):
        j.begin(e, [f"b-{e}"])
        j.commit(e, state_checksum=e, state_rows=1, state_bytes=8)
    doc = j.snapshot()
    assert len(doc["commits"]) == 3
    assert doc["committed_epoch"] == 7
    assert len(doc["consumed"]) == 7    # never truncated: the exactly-once set
    assert validate_doc(doc) == []


# -- batch log -----------------------------------------------------------------

def test_source_append_idempotent_and_crc_verified(tmp_path):
    src = StreamingSource("clicks", str(tmp_path))
    assert src.append_table("b-0000", _batch(0)) is True
    assert src.append_table("b-0000", _batch(0)) is False   # idempotent
    assert src.list_batches() == ["b-0000"]
    with pytest.raises(ValueError, match="invalid batch id"):
        src.append_table("../evil", _batch(0))
    with pytest.raises(ValueError, match="schema"):
        src.append_table("b-0001", pa.table({"z": [1]}))
    # the wire path: CRC verified BEFORE the duplicate shortcut, and a
    # mismatch is a retryable transport fault, not a duplicate ack
    body = table_to_ipc(_batch(1))
    with pytest.raises(TransportError, match="checksum mismatch"):
        src.append_ipc("b-0001", body, block_checksum(body) ^ 1)
    assert src.list_batches() == ["b-0000"]
    tbl, fresh = src.append_ipc("b-0001", body, block_checksum(body))
    assert fresh and tbl.equals(_batch(1))
    _, fresh = src.append_ipc("b-0001", body, block_checksum(body))
    assert not fresh
    assert ipc_to_table(body).equals(_batch(1))
    # write intents and dotfiles never surface as batches
    (tmp_path / "b-0009.parquet.tmp.123").write_bytes(b"torn")
    (tmp_path / ".hidden.parquet").write_bytes(b"x")
    assert src.list_batches() == ["b-0000", "b-0001"]


# -- epoch lifecycle -----------------------------------------------------------

def test_epoch_lifecycle_watermark_and_steady_state(tmp_path):
    """The tentpole happy path: five epochs of incremental windowed
    aggregation, state matching a full recomputation oracle every epoch,
    watermark retirement holding state flat, a steady state that compiles
    NOTHING, and zero resilience events / leaked buffers."""
    from spark_rapids_tpu.runtime.memory import DeviceManager
    res_before = M.resilience_snapshot()
    cat = DeviceManager.get().catalog
    buffers_base = cat.num_buffers
    spark = TpuSession({
        "spark.rapids.tpu.streaming.watermark.delaySeconds": 20,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path / "log")})
    src = spark.create_stream_source("clicks", str(tmp_path / "stream"))
    coord = _coord(spark, src)
    try:
        seen, state_rows = [], []
        for i in range(5):
            ack = spark.streaming_append("clicks", f"b-{i:04d}", _batch(i))
            assert not ack["duplicate"] and ack["rows"] == 8
            seen.append(_batch(i))
            rec = coord.run_epoch()
            assert rec["epoch"] == i + 1 and rec["attempt"] == 1
            assert rec["rows_in"] == 8
            state_rows.append(rec["state_rows"])
            # state == oracle over everything ingested, minus retirement
            oracle = _oracle_state(seen)
            wm = coord.watermark
            if wm is not None:
                oracle = oracle.filter(
                    pc.greater_equal(oracle["window"],
                                     pa.scalar(wm, type=pa.int64())))
            got = coord.state_table()
            assert _rows(got, KEYS + ["window"]) == \
                _rows(oracle, KEYS + ["window"])
        # watermark (delay 20s, 10s windows): exactly 3 live windows x 2
        # keys once retirement starts — state bytes stay flat forever
        assert state_rows[-2:] == [6, 6]
        assert coord.journal.last_commit()["retired_rows"] == 2
        assert coord.watermark == 20
        # steady state retraces nothing: the two plan shapes (first epoch,
        # union+merge) are compiled by epoch 3; 4 and 5 compile ZERO
        assert coord.last_epoch_compiles == 0
        assert coord.journal.last_commit()["compiles"] == 0
        # nothing new -> no epoch, no journal write
        assert coord.run_epoch() is None
        # a duplicate append is acked but consumed batches never re-ingest
        ack = spark.streaming_append("clicks", "b-0000", _batch(0))
        assert ack["duplicate"]
        assert coord.run_epoch() is None
        # the SQL surface sees every appended row (views re-resolve fresh)
        assert spark.sql("select count(*) c from clicks") \
            .collect().to_pylist() == [{"c": 40}]
        assert validate_doc(coord.journal.snapshot()) == []
    finally:
        coord.close()
    # a clean stream is resilience-silent: no replays, no rebuilds, and
    # every other counter untouched
    assert M.resilience_snapshot() == res_before
    eventlog.shutdown()
    recs = [json.loads(ln) for f in (tmp_path / "log").glob("*.jsonl")
            for ln in f.read_text().splitlines()]
    kinds = [r.get("event") for r in recs]
    assert kinds.count("stream.append") == 5       # duplicates emit nothing
    assert kinds.count("stream.epoch.begin") == 5
    assert kinds.count("stream.epoch.commit") == 5
    # the retained state buffer is released by close(): no leaks
    gc.collect()
    assert _wait(lambda: cat.num_buffers <= buffers_base)


def test_commit_crash_replays_pending_epoch_bit_identical(tmp_path):
    """A crash in the commit window (work done, journal not yet advanced)
    must replay the SAME batch ids on restart and land bit-identically
    with an unkilled run — the exactly-once headline, in-process."""
    res_before = M.resilience_snapshot()["streamEpochReplays"]
    spark = TpuSession({"spark.rapids.tpu.streaming.maxBatchesPerEpoch": 1})
    live_dir, oracle_dir = tmp_path / "live", tmp_path / "oracle"
    src = StreamingSource("clicks", str(live_dir))
    osrc = StreamingSource("clicks", str(oracle_dir))
    for i in range(3):
        src.append_table(f"b-{i:04d}", _batch(i))
        osrc.append_table(f"b-{i:04d}", _batch(i))
    coord = _coord(spark, src)
    oracle = _coord(spark, osrc)
    try:
        for _ in range(2):
            coord.run_epoch()
        # the armed commit fault fires AFTER the epoch's query and state
        # snapshot, BEFORE the journal write — the exact crash window
        faults.configure("error:streaming.epoch.commit:1", seed=1)
        with pytest.raises(RuntimeError, match="fault-injection"):
            coord.run_epoch()
        faults.reset()
        doc = coord.journal.snapshot()
        assert doc["committed_epoch"] == 2
        assert doc["begin"]["epoch"] == 3
        assert doc["begin"]["batch_ids"] == ["b-0002"]
        # a FRESH coordinator (the restarted process) recovers: the pending
        # epoch replays under a bumped attempt, counted as resilience
        recovered = _coord(spark, src)
        try:
            rec = recovered.recover()
            assert rec["epoch"] == 3 and rec["attempt"] == 2
            assert rec["batch_ids"] == ["b-0002"]
            assert recovered.journal.committed_epoch() == 3
            assert recovered.recover() is None      # nothing left pending
            for _ in range(3):
                oracle.run_epoch()
            assert recovered.state_table().equals(oracle.state_table())
            assert rec["state_checksum"] == \
                oracle.journal.last_commit()["state_checksum"]
            assert M.resilience_snapshot()["streamEpochReplays"] == \
                res_before + 1
        finally:
            recovered.close()
    finally:
        coord.close()
        oracle.close()


def test_corrupt_state_snapshot_rebuilds_from_batch_log(tmp_path):
    """A committed snapshot failing its journal checksum is detected (never
    silently served) and rebuilt by re-aggregating the consumed batch log —
    landing on the exact committed state."""
    res_before = M.resilience_snapshot()["streamStateRebuilds"]
    spark = TpuSession({})
    src = StreamingSource("clicks", str(tmp_path))
    for i in range(3):
        src.append_table(f"b-{i:04d}", _batch(i))
    coord = _coord(spark, src, windowed=False)
    try:
        rec = coord.run_epoch()
        assert rec["epoch"] == 1 and rec["state_rows"] == 2
        committed = coord.state_table()
    finally:
        coord.close()
    snap = tmp_path / "_state" / "state-1.arrow"
    snap.write_bytes(b"\x00" * 16 + snap.read_bytes()[16:])
    fresh = _coord(spark, src, windowed=False)
    try:
        got = fresh.state_table()     # recovery path: checksum fails -> rebuild
        assert got.equals(committed)
        assert M.resilience_snapshot()["streamStateRebuilds"] == \
            res_before + 1
        # the rebuilt state carries forward: the next epoch merges onto it
        src.append_table("b-0003", _batch(3))
        rec = fresh.run_epoch()
        assert rec["epoch"] == 2
        assert _rows(fresh.state_table(), KEYS) == _rows(
            _oracle_state([_batch(i) for i in range(4)], windowed=False),
            KEYS)
    finally:
        fresh.close()


# -- session + endpoint surfaces -----------------------------------------------

def test_endpoint_append_wire_result_cache_and_staleness(tmp_path):
    """The wire path end to end: APPEND through the endpoint is durable
    before its ack, idempotent on retry, and every APPEND bumps the
    catalog epoch so a cached result can never serve stale rows."""
    spark = TpuSession({
        "spark.rapids.tpu.endpoint.resultCache.enabled": True})
    src = spark.create_stream_source("clicks", str(tmp_path / "stream"))
    ep = QueryEndpoint(spark)
    cli = EndpointClient(("127.0.0.1", ep.port), timeout_s=30)
    try:
        ack = cli.append("clicks", "b-0000", _batch(0))
        assert not ack["duplicate"] and ack["rows"] == 8
        assert ack["replica"] == f"127.0.0.1:{ep.port}"
        assert src.has_batch("b-0000")          # the ack meant durable
        first = cli.submit(SQL).to_pylist()
        base = _oracle_state([_batch(0)], windowed=False)
        assert [(r["k"], r["s"], r["c"]) for r in first] == [
            (r["k"], r["sum_v"], int(r["count_v"]))
            for r in base.to_pylist()]
        assert cli.submit(SQL).to_pylist() == first
        assert cli.last_summary.get("cached") is True
        # a duplicate APPEND (the blind-retry path) acks but changes nothing
        epoch_before = spark.catalog_epoch
        ack = cli.append("clicks", "b-0000", _batch(0))
        assert ack["duplicate"] and spark.catalog_epoch == epoch_before
        assert cli.submit(SQL).to_pylist() == first
        assert cli.last_summary.get("cached") is True
        # a FRESH append invalidates: the very next submit reruns and sees
        # the new rows
        ack = cli.append("clicks", "b-0001", _batch(1))
        assert not ack["duplicate"]
        assert spark.catalog_epoch == epoch_before + 1
        rows = cli.submit(SQL).to_pylist()
        assert not (cli.last_summary or {}).get("cached")
        assert rows != first
        oracle = _oracle_state([_batch(0), _batch(1)], windowed=False)
        assert [(r["k"], r["s"], r["c"]) for r in rows] == [
            (r["k"], r["sum_v"], int(r["count_v"]))
            for r in oracle.to_pylist()]
    finally:
        ep.shutdown(grace_s=5)


def test_append_retry_rotates_to_live_replica(tmp_path):
    spark = TpuSession({})
    spark.create_stream_source("clicks", str(tmp_path / "stream"))
    ep = QueryEndpoint(spark)
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    try:
        cli = EndpointClient([("127.0.0.1", dead_port),
                              ("127.0.0.1", ep.port)], timeout_s=30)
        retries = []
        ack = cli.append_with_retry(
            "clicks", "b-0000", _batch(0),
            on_retry=lambda a, d: retries.append(a))
        assert not ack["duplicate"] and retries
        assert cli.address == ("127.0.0.1", ep.port)
        # the retried path is idempotent by construction
        assert cli.append_with_retry("clicks", "b-0000",
                                     _batch(0))["duplicate"]
    finally:
        ep.shutdown(grace_s=5)


def test_client_disconnect_mid_append_leaves_no_torn_batch(tmp_path):
    """A client dying mid-frame must leave NOTHING: no batch file, no
    half-parsed ingest — and the next real APPEND proceeds normally."""
    spark = TpuSession({})
    src = spark.create_stream_source("clicks", str(tmp_path / "stream"))
    ep = QueryEndpoint(spark)
    try:
        sock = socket.create_connection(("127.0.0.1", ep.port), timeout=10)
        # frame header promises 4096 payload bytes; send 16 and vanish
        sock.sendall(struct.pack("<BI", MSG_APPEND, 4096) + b"x" * 16)
        sock.close()
        time.sleep(0.2)
        assert src.list_batches() == []
        assert not any(".tmp." in n
                       for n in os.listdir(str(tmp_path / "stream")))
        cli = EndpointClient(("127.0.0.1", ep.port), timeout_s=30)
        assert not cli.append("clicks", "b-0000", _batch(0))["duplicate"]
        assert src.list_batches() == ["b-0000"]
    finally:
        ep.shutdown(grace_s=5)


def test_shared_catalog_epoch_invalidates_peer_replica_cache(tmp_path):
    """The cross-replica staleness regression: replica B's result cache
    holds a stream query; an APPEND lands through replica A. The shared
    fleet catalog epoch must invalidate B's entry — B re-runs and serves
    the fresh rows, never the cached stale ones."""
    fleet_dir = str(tmp_path / "fleet")
    # the shared-epoch primitive itself
    assert FL.shared_catalog_epoch(fleet_dir) == 0
    assert FL.bump_shared_catalog_epoch(fleet_dir) == 1
    assert FL.bump_shared_catalog_epoch(fleet_dir) == 2
    assert FL.shared_catalog_epoch(fleet_dir) == 2

    conf = {"spark.rapids.tpu.fleet.dir": fleet_dir,
            "spark.rapids.tpu.fleet.heartbeat.intervalSeconds": 0.2,
            "spark.rapids.tpu.endpoint.resultCache.enabled": True}
    sdir = str(tmp_path / "stream")
    sa, sb = TpuSession(dict(conf)), TpuSession(dict(conf))
    sa.create_stream_source("clicks", sdir)
    sb.create_stream_source("clicks", sdir)
    sa.streaming_append("clicks", "b-0000", _batch(0))
    ep_a, ep_b = QueryEndpoint(sa), QueryEndpoint(sb)
    try:
        cli_a = EndpointClient(("127.0.0.1", ep_a.port), timeout_s=30)
        cli_b = EndpointClient(("127.0.0.1", ep_b.port), timeout_s=30)
        first = cli_b.submit(SQL).to_pylist()
        assert cli_b.submit(SQL).to_pylist() == first
        assert cli_b.last_summary.get("cached") is True
        # append through A; B's next submit must NOT serve its cache
        ack = cli_a.append("clicks", "b-0001", _batch(1))
        assert not ack["duplicate"]
        rows = cli_b.submit(SQL).to_pylist()
        assert not (cli_b.last_summary or {}).get("cached")
        assert rows != first
        oracle = _oracle_state([_batch(0), _batch(1)], windowed=False)
        assert [(r["k"], r["s"], r["c"]) for r in rows] == [
            (r["k"], r["sum_v"], int(r["count_v"]))
            for r in oracle.to_pylist()]
    finally:
        ep_a.shutdown(grace_s=5)
        ep_b.shutdown(grace_s=5)


# -- crash recovery across real processes --------------------------------------

_CRASH_CHILD = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.streaming import EpochCoordinator, StreamingSource

src_dir, n_clean, spec = sys.argv[1], int(sys.argv[2]), sys.argv[3]
spark = TpuSession({"spark.rapids.tpu.streaming.maxBatchesPerEpoch": 1})
src = StreamingSource("clicks", src_dir)
coord = EpochCoordinator(spark, src, keys=["k"],
                         aggs=[("sum", "v"), ("count", "v"), ("max", "v")],
                         time_column="ts", window_seconds=10)
for _ in range(n_clean):
    coord.run_epoch()
print("COMMITTED", coord.journal.committed_epoch(), flush=True)
faults.configure(spec, seed=1)
coord.run_epoch()
print("SURVIVED", flush=True)     # must never be reached
"""


def _spawn_crash_child(src_dir, n_clean, spec):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-c", _CRASH_CHILD, str(src_dir), str(n_clean),
         spec],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)


def _run_oracle(spark, directory, batches, n_epochs):
    osrc = StreamingSource("clicks", str(directory))
    for b, t in batches:
        osrc.append_table(b, t)
    oracle = _coord(spark, osrc)
    try:
        for _ in range(n_epochs):
            oracle.run_epoch()
        return oracle.state_table(), oracle.journal.last_commit()
    finally:
        oracle.close()


@pytest.mark.slow
def test_exec_kill_mid_commit_replays_bit_identical(tmp_path):
    """A real coordinator PROCESS is SIGKILLed inside the commit window
    (state snapshot written, journal not advanced — exec_kill at the
    streaming.epoch.commit site). A fresh coordinator adopting the stream
    replays the pending epoch bit-identically with an unkilled oracle,
    and the dead attempt's orphan snapshot is never adopted."""
    res_before = M.resilience_snapshot()["streamEpochReplays"]
    src_dir = tmp_path / "stream"
    batches = [(f"b-{i:04d}", _batch(i)) for i in range(3)]
    src = StreamingSource("clicks", str(src_dir))
    for b, t in batches:
        src.append_table(b, t)
    child = _spawn_crash_child(src_dir, 2,
                               "exec_kill:streaming.epoch.commit:1")
    out, _ = child.communicate(timeout=300)
    assert "COMMITTED 2" in out and "SURVIVED" not in out, out
    assert child.returncode == -signal.SIGKILL
    journal = EpochJournal(str(src_dir / "_state"), source="clicks")
    pending = journal.pending()
    assert pending == {"epoch": 3, "batch_ids": ["b-0002"], "attempt": 1,
                       "prev_state_checksum": pending["prev_state_checksum"]}
    # the dead attempt got as far as its epoch-3 snapshot — the fence must
    # keep it un-adopted until the replayed commit names it
    assert (src_dir / "_state" / "state-3.arrow").exists()

    spark = TpuSession({"spark.rapids.tpu.streaming.maxBatchesPerEpoch": 1})
    recovered = _coord(spark, src)
    try:
        # the SIGKILLed child's flock died with it: recovery acquires the
        # owner lock immediately instead of deadlocking
        rec = recovered.recover()
        assert rec["epoch"] == 3 and rec["attempt"] == 2
        assert rec["batch_ids"] == ["b-0002"]
        state = recovered.state_table()
        oracle_state, oracle_commit = _run_oracle(
            spark, tmp_path / "oracle", batches, 3)
        assert state.equals(oracle_state)
        assert rec["state_checksum"] == oracle_commit["state_checksum"]
        assert M.resilience_snapshot()["streamEpochReplays"] == \
            res_before + 1
        assert validate_doc(recovered.journal.snapshot()) == []
    finally:
        recovered.close()


@pytest.mark.slow
def test_sigkill_between_begin_and_commit_replays(tmp_path):
    """The other crash point: the coordinator process dies AFTER journaling
    epoch.begin but BEFORE the state snapshot exists at all (wedged at the
    streaming.state site, then SIGKILLed). Recovery replays from the
    begin record's pinned batch ids, bit-identical with the oracle."""
    res_before = M.resilience_snapshot()["streamEpochReplays"]
    src_dir = tmp_path / "stream"
    batches = [(f"b-{i:04d}", _batch(i)) for i in range(2)]
    src = StreamingSource("clicks", str(src_dir))
    for b, t in batches:
        src.append_table(b, t)
    child = _spawn_crash_child(src_dir, 1, "hang:streaming.state:1")
    try:
        journal = EpochJournal(str(src_dir / "_state"), source="clicks")
        assert _wait(lambda: (child.poll() is None
                              and (p := journal.pending()) is not None
                              and p["epoch"] == 2), timeout_s=300)
        time.sleep(0.3)     # let the child reach the wedge point
        os.kill(child.pid, signal.SIGKILL)
        child.communicate(timeout=60)
        assert child.returncode == -signal.SIGKILL
    finally:
        if child.poll() is None:
            child.kill()
    assert not (src_dir / "_state" / "state-2.arrow").exists()

    spark = TpuSession({"spark.rapids.tpu.streaming.maxBatchesPerEpoch": 1})
    recovered = _coord(spark, src)
    try:
        rec = recovered.run_epoch()     # run_epoch recovers first
        assert rec["epoch"] == 2 and rec["attempt"] == 2
        oracle_state, oracle_commit = _run_oracle(
            spark, tmp_path / "oracle", batches, 2)
        assert recovered.state_table().equals(oracle_state)
        assert rec["state_checksum"] == oracle_commit["state_checksum"]
        assert M.resilience_snapshot()["streamEpochReplays"] == \
            res_before + 1
    finally:
        recovered.close()


# -- cross-replica fleet e2e ---------------------------------------------------

def _spawn_replica(fleet_dir, stream_spec):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "tools" / "fleet_replica.py"),
         "--fleet-dir", str(fleet_dir), "--synthetic", "20",
         "--lease-timeout", "3", "--heartbeat", "0.5", "--result-cache",
         "--stream-source", stream_spec],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
    deadline = time.monotonic() + 300
    port = None
    while time.monotonic() < deadline:
        ln = proc.stdout.readline()
        if ln.startswith("READY "):
            port = int(ln.split()[1])
            break
        if proc.poll() is not None:
            break
    if port is None:
        proc.kill()
        raise AssertionError("replica never became READY")
    threading.Thread(target=proc.stdout.read, daemon=True).start()
    return proc, port


@pytest.mark.slow
def test_two_process_fleet_append_staleness_and_cli(tmp_path):
    """Two real replica PROCESSES share one batch log and one fleet dir.
    An APPEND shipped through replica A (via the tpu_client CLI, riding
    the fleet rotation) must invalidate replica B's warmed result cache —
    and the duplicate re-send of the same batch id stays a no-op."""
    sdir = tmp_path / "stream"
    sdir.mkdir()
    # the directory-tail ingestion path: a producer drops a parquet file in
    pq.write_table(_batch(0), sdir / "b-0000.parquet")
    a = b = None
    try:
        a, aport = _spawn_replica(tmp_path / "fleet", f"clicks:{sdir}")
        b, bport = _spawn_replica(tmp_path / "fleet", f"clicks:{sdir}")
        cli_b = EndpointClient(("127.0.0.1", bport), timeout_s=120)
        first = cli_b.submit_with_retry(SQL).to_pylist()
        assert cli_b.submit(SQL).to_pylist() == first
        assert cli_b.last_summary.get("cached") is True

        batch_file = tmp_path / "b1.parquet"
        pq.write_table(_batch(1), batch_file)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        cmd = [sys.executable, str(REPO / "tools" / "tpu_client.py"),
               "--addresses", f"127.0.0.1:{aport},127.0.0.1:{bport}",
               "append", "--source", "clicks", "--batch", "b-0001",
               "--file", str(batch_file)]
        r = subprocess.run(cmd, capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode == 0, r.stderr
        assert "OK append source=clicks batch=b-0001 rows=8" in r.stderr
        r = subprocess.run(cmd, capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode == 0 and "duplicate" in r.stderr, r.stderr

        rows = cli_b.submit(SQL).to_pylist()
        assert not (cli_b.last_summary or {}).get("cached")
        assert rows != first
        oracle = _oracle_state([_batch(0), _batch(1)], windowed=False)
        assert [(r["k"], r["s"], r["c"]) for r in rows] == [
            (r["k"], r["sum_v"], int(r["count_v"]))
            for r in oracle.to_pylist()]
    finally:
        for proc in (a, b):
            if proc is not None:
                proc.kill()
                proc.wait(timeout=30)
