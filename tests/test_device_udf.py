"""Accelerated (jax) user UDFs — fused device evaluation everywhere an
expression composes (reference RapidsUDF / udf-examples suite role)."""

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest

from conftest import make_table

import spark_rapids_tpu.functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.session import TpuSession


def test_jax_udf_projection_and_nulls():
    spark = TpuSession()
    t = make_table(300, seed=2)
    udf = F.jax_udf(lambda a, b: a * 2.0 + jnp.abs(b), T.DOUBLE)
    df = spark.create_dataframe(t, num_partitions=2).select(
        F.col("d"), F.col("f"), udf(F.col("d"), F.col("f")).alias("u"))
    out = df.collect().to_pylist()
    for r in out:
        if r["d"] is None or r["f"] is None:
            assert r["u"] is None  # Spark UDF null contract
        else:
            assert r["u"] == pytest.approx(r["d"] * 2.0 + abs(r["f"]),
                                           rel=1e-6)


def test_jax_udf_runs_on_device():
    """The projection containing the UDF must be planner-approved, not a
    host fallback."""
    from spark_rapids_tpu.plan.overrides import explain_plan
    spark = TpuSession()
    t = make_table(50, seed=3)
    udf = F.jax_udf(lambda v: v * v, T.DOUBLE)
    df = spark.create_dataframe(t).select(udf(F.col("d")).alias("sq"))
    txt = explain_plan(df._plan, spark.conf)
    assert "will run on TPU" in txt.splitlines()[0], txt


def test_jax_udf_in_filter_and_agg():
    """Unlike python UDFs (projection-only), jax UDFs compose anywhere."""
    spark = TpuSession()
    t = make_table(400, seed=5)
    parity = F.jax_udf(lambda v: v % 2 == 0, T.BOOLEAN)
    df = (spark.create_dataframe(t, num_partitions=2)
          .filter(parity(F.col("i")))
          .group_by(F.col("b"))
          .agg(F.count(F.col("i")).alias("c")))
    got = {r["b"]: r["c"] for r in df.collect().to_pylist()}
    exp = {}
    for i, b in zip(t.column("i").to_pylist(), t.column("b").to_pylist()):
        if i is not None and i % 2 == 0:
            exp[b] = exp.get(b, 0) + 1
    assert got == exp


def test_jax_udf_null_aware():
    spark = TpuSession()
    t = pa.table({"x": pa.array([1.0, None, 3.0, None])})

    def fill_then_double(xv):
        vals, valid = xv
        return jnp.where(valid, vals, 99.0) * 2.0, jnp.ones_like(valid)

    udf = F.jax_udf(fill_then_double, T.DOUBLE, null_aware=True)
    out = spark.create_dataframe(t).select(
        udf(F.col("x")).alias("y")).collect()
    assert out.column("y").to_pylist() == [2.0, 198.0, 6.0, 198.0]


def test_jax_udf_string_pins_host():
    """String inputs would expose dictionary codes to the user fn — the
    planner must refuse the device path."""
    from spark_rapids_tpu.plan.overrides import explain_plan
    spark = TpuSession()
    t = make_table(30, seed=7)
    udf = F.jax_udf(lambda v: v, T.STRING)
    df = spark.create_dataframe(t).select(udf(F.col("s")).alias("u"))
    txt = explain_plan(df._plan, spark.conf)
    assert "cannot run" in txt or "unsupported" in txt


def test_jax_udf_host_oracle_agrees():
    from spark_rapids_tpu.plan.host_eval import eval_host
    from spark_rapids_tpu.expr.core import bind_references
    t = make_table(100, seed=11)
    udf = F.jax_udf(lambda a: jnp.sqrt(jnp.abs(a)) + 1.0, T.DOUBLE)
    e = udf(F.col("d"))
    schema = T.StructType.from_arrow(t.schema)
    host = eval_host(bind_references(e, schema), t).to_arrow().to_pylist()
    for v, d in zip(host, t.column("d").to_pylist()):
        if d is None:
            assert v is None
        else:
            assert v == pytest.approx(abs(d) ** 0.5 + 1.0, rel=1e-6)


def test_registered_udf_prefers_device_impl():
    """RapidsUDF analog (reference GpuUserDefinedFunction.scala:73): the
    registered device implementation is planned fused on TPU, not the row
    fallback; callable from both the DataFrame API and SQL."""
    from spark_rapids_tpu.plan.overrides import explain_plan
    spark = TpuSession()
    calls = {"row": 0}

    def slow_row_fn(v):
        calls["row"] += 1
        return v * 2.0

    my_fn = spark.udf.register("my_double", fn=slow_row_fn,
                               return_type=T.DOUBLE,
                               device_fn=lambda v: v * 2.0)
    t = pa.table({"x": pa.array([1.0, 2.0, None, 4.0])})
    spark.create_or_replace_temp_view("t", spark.create_dataframe(t))
    df = spark.create_dataframe(t).select(my_fn(F.col("x")).alias("y"))
    txt = explain_plan(df._plan, spark.conf)
    assert "will run on TPU" in txt.splitlines()[0], txt
    assert [r["y"] for r in df.collect().to_pylist()] == [2.0, 4.0, None, 8.0]
    got = spark.sql("select my_double(x) y from t").collect().to_pylist()
    assert [r["y"] for r in got] == [2.0, 4.0, None, 8.0]
    assert calls["row"] == 0, "device impl must be used, not the row fn"


def test_registered_udf_fallback_without_device_impl():
    """No device_fn: the registry compiles the bytecode to device exprs when
    it can, else routes to the python worker pool — never errors."""
    spark = TpuSession()
    spark.udf.register("plus_one", fn=lambda v: v + 1, return_type=T.LONG)
    # closure over opaque state defeats the bytecode compiler -> worker pool
    import math
    spark.udf.register("opaque", fn=lambda v: int(math.floor(v)) + 1,
                       return_type=T.LONG)
    t = pa.table({"x": pa.array([1, 2, 3], pa.int64()),
                  "d": pa.array([1.5, 2.5, 3.5])})
    spark.create_or_replace_temp_view("t", spark.create_dataframe(t))
    got = spark.sql("select plus_one(x) a from t order by a").collect()
    assert got.column("a").to_pylist() == [2, 3, 4]
    got = spark.sql("select opaque(d) b from t order by b").collect()
    assert got.column("b").to_pylist() == [2, 3, 4]
