"""Pallas kernel equivalence tests (interpret mode on the forced-CPU
platform; the same kernels compile with Mosaic on TPU — bench path).

Oracles: the jnp reference implementations in ops/hashing.py (itself pinned
to Spark golden vectors in test_columnar.py) and ops/parquet_decode.py.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from spark_rapids_tpu.ops import hashing as H
from spark_rapids_tpu.ops import parquet_decode as PD
from spark_rapids_tpu.ops import pallas_kernels as PK


def test_murmur3_words_matches_host_oracle():
    rng = np.random.default_rng(7)
    strs = ["", "a", "ab", "abc", "abcd", "hello world", "ünïcødé",
            "x" * 37, "tail3_", "padded to sixteen"]
    strs += ["".join(chr(rng.integers(32, 127)) for _ in range(rng.integers(0, 30)))
             for _ in range(50)]
    words, lens = H.pack_utf8_words(strs)
    out = np.asarray(PK.murmur3_words(jnp.asarray(words), jnp.asarray(lens), 42))
    host = [H.murmur3_bytes_host(s.encode("utf-8"), 42) for s in strs]
    assert list(out) == host


def test_murmur3_words_row_varying_seed():
    strs = ["alpha", "bravo", "charlie", "d", ""]
    words, lens = H.pack_utf8_words(strs)
    seeds = np.array([42, -7, 0, 123456, 99], dtype=np.int32)
    out = np.asarray(PK.murmur3_words(jnp.asarray(words), jnp.asarray(lens),
                                      jnp.asarray(seeds)))
    host = [H.murmur3_bytes_host(s.encode("utf-8"), int(sd))
            for s, sd in zip(strs, seeds)]
    assert list(out) == host


def test_murmur3_words_matches_jnp_kernel_large():
    rng = np.random.default_rng(11)
    strs = ["s%d_%s" % (i, "y" * int(rng.integers(0, 25))) for i in range(1000)]
    words, lens = H.pack_utf8_words(strs)
    w, l = jnp.asarray(words), jnp.asarray(lens)
    ref = np.asarray(H.hash_string_words(w, l, jnp.int32(42)))
    out = np.asarray(PK.murmur3_words(w, l, 42))
    assert (out == ref).all()


@pytest.mark.parametrize("bw", [1, 2, 3, 5, 7, 8, 11, 13, 16, 20, 24, 31, 32])
def test_bitunpack128_matches_reference(bw):
    rng = np.random.default_rng(bw)
    n = 300
    vals = rng.integers(0, 2 ** min(bw, 31), size=n, dtype=np.int64)
    # pack: value i at bits [i*bw, (i+1)*bw), little-endian bit order
    total_bits = n * bw
    buf = np.zeros((total_bits + 7) // 8, dtype=np.uint8)
    for i, v in enumerate(vals):
        for b in range(bw):
            bit = i * bw + b
            if (v >> b) & 1:
                buf[bit >> 3] |= 1 << (bit & 7)
    cap = 512
    words = PK.bytes_to_words_u32(buf)
    out = np.asarray(PK.bitunpack128(jnp.asarray(words), bw, n, cap))
    ref = np.asarray(PD.unpack_bits_device(
        jnp.asarray(buf), bw, n, cap)) if bw <= 25 else None
    expect = np.zeros(cap, dtype=np.int64)
    expect[:n] = vals
    assert (out.astype(np.uint32) == expect.astype(np.uint32)).all()
    if ref is not None:  # also agree with the stage-one jnp decoder
        assert (out[:n] == ref[:n]).all()


def test_bitunpack128_tiny_run():
    # fewer than 128 values, width 4
    vals = np.array([3, 9, 15, 0, 7, 1, 2, 4], dtype=np.int64)
    buf = np.zeros(4, dtype=np.uint8)
    for i, v in enumerate(vals):
        for b in range(4):
            bit = i * 4 + b
            if (v >> b) & 1:
                buf[bit >> 3] |= 1 << (bit & 7)
    words = PK.bytes_to_words_u32(buf)
    out = np.asarray(PK.bitunpack128(jnp.asarray(words), 4, len(vals), 16))
    assert list(out[:8]) == list(vals)
    assert (out[8:] == 0).all()


def test_pallas_dispatch_through_partitioning(monkeypatch):
    """Force the dispatch on (interpret mode off-TPU) and hash-partition a
    string column end-to-end — device results must match the forced-off jnp
    path bit for bit."""
    import pyarrow as pa
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioner
    from spark_rapids_tpu.expr.core import col

    t = pa.table({"s": pa.array(["a", "bb", "ccc", None, "dddd", "é"] * 10),
                  "v": pa.array(list(range(60)), pa.int64())})
    batch = ColumnarBatch.from_arrow(t)

    def run():
        p = HashPartitioner([col("s")], 4).bind(batch.schema)
        return {pid: part.to_arrow().to_pylist()
                for pid, part in p.partition(batch)}

    PK.set_mode(True)
    try:
        with_pallas = run()
    finally:
        PK.set_mode(False)
    without = run()
    PK.set_mode(None)
    assert with_pallas == without


def test_pallas_dispatch_through_parquet_decode(tmp_path):
    """Forced-on dispatch through decode_dictionary_page equals forced-off."""
    rng = np.random.default_rng(3)
    dict_vals = jnp.asarray(rng.integers(0, 1000, 32), dtype=jnp.int64)
    n = 100
    idx = rng.integers(0, 32, n)
    bw = 5
    buf = np.zeros((n * bw + 7) // 8, dtype=np.uint8)
    for i, v in enumerate(idx):
        for b in range(bw):
            bit = i * bw + b
            if (v >> b) & 1:
                buf[bit >> 3] |= 1 << (bit & 7)
    dl = np.ones(n, dtype=np.int32)

    PK.set_mode(True)
    try:
        v1, m1 = PD.decode_dictionary_page(buf, bw, n, dl, dict_vals, 128)
    finally:
        PK.set_mode(False)
    v2, m2 = PD.decode_dictionary_page(buf, bw, n, dl, dict_vals, 128)
    PK.set_mode(None)
    assert (np.asarray(v1) == np.asarray(v2)).all()
    assert (np.asarray(m1) == np.asarray(m2)).all()


def test_onehot_sum_matches_numpy():
    """Blocked one-hot matmul kernel (medium-domain dense group-by,
    VERDICT r4 next #7) vs a numpy bucket-add oracle; histograms of 0/1
    values are exact."""
    rng = np.random.default_rng(11)
    for cap, D in [(4096, 12), (2048, 1000), (1500, 300), (100, 5),
                   (8192, 1024)]:
        codes = rng.integers(-1, D, cap).astype(np.int32)
        vals = rng.normal(0, 10, cap).astype(np.float32)
        got = np.asarray(PK.onehot_sum_f32(jnp.asarray(vals),
                                           jnp.asarray(codes), D))
        exp = np.zeros(D, np.float64)
        np.add.at(exp, codes[codes >= 0], vals[codes >= 0].astype(np.float64))
        assert np.allclose(got, exp, rtol=1e-3, atol=1e-2), (cap, D)
    ones = np.ones(65536, np.float32)
    codes = rng.integers(0, 1024, 65536).astype(np.int32)
    got = np.asarray(PK.onehot_sum_f32(jnp.asarray(ones),
                                       jnp.asarray(codes), 1024))
    assert np.array_equal(got.astype(np.int64), np.bincount(codes,
                                                            minlength=1024))


def _np_stable_ranks(ids, lanes):
    ranks = np.zeros(len(ids), np.int32)
    seen = {}
    for i, v in enumerate(ids):
        if 0 <= v < lanes:
            ranks[i] = seen.get(v, 0)
            seen[v] = seen.get(v, 0) + 1
    return ranks, np.bincount(ids[(ids >= 0) & (ids < lanes)],
                              minlength=lanes)[:lanes]


@pytest.mark.parametrize("shape", ["uniform", "skewed", "single", "empty"])
def test_radix_ranks_matches_numpy(shape):
    rng = np.random.default_rng(3)
    lanes = 9
    if shape == "uniform":
        ids = rng.integers(0, lanes, 700).astype(np.int32)
    elif shape == "skewed":          # one partition takes almost everything
        ids = np.where(rng.random(700) < 0.95, 4,
                       rng.integers(0, lanes, 700)).astype(np.int32)
    elif shape == "single":
        ids = np.full(300, 7, np.int32)
    else:                            # every row out of range (all padding)
        ids = np.full(128, lanes, np.int32)
    ranks, counts = PK.radix_ranks(jnp.asarray(ids), lanes)
    exp_ranks, exp_counts = _np_stable_ranks(ids, lanes)
    assert (np.asarray(counts) == exp_counts).all()
    assert (np.asarray(ranks) == exp_ranks).all()


@pytest.mark.parametrize("nparts", [1, 2, 5, 64, 300])
def test_radix_partition_permutation_is_stable_argsort(nparts):
    rng = np.random.default_rng(nparts)
    ids = rng.integers(0, nparts, 1000).astype(np.int32)
    perm = np.asarray(PK.radix_partition_permutation(jnp.asarray(ids),
                                                     nparts))
    assert (perm == np.argsort(ids, kind="stable")).all()


def test_partition_permutation_routing_with_padding():
    """ops/sorting.partition_permutation forced through the radix kernel
    equals the stable-argsort path, padding sunk to the end."""
    from spark_rapids_tpu.ops.sorting import partition_permutation
    rng = np.random.default_rng(8)
    cap, n = 512, 389
    ids = jnp.asarray(rng.integers(0, 6, cap).astype(np.int32))
    PK.set_mode(True)
    try:
        with_pallas = np.asarray(partition_permutation(ids, 6, n, cap))
    finally:
        PK.set_mode(False)
    without = np.asarray(partition_permutation(ids, 6, n, cap))
    PK.set_mode(None)
    assert (with_pallas == without).all()


def _np_hash_oracle(bk, sk):
    lookup = {int(k): i for i, k in enumerate(bk)}
    pos = np.array([lookup.get(int(s), -1) for s in sk], np.int32)
    return pos, pos >= 0


@pytest.mark.parametrize("dtype", [np.int64, np.int32, np.int16])
def test_hash_join_build_probe_dtypes(dtype):
    rng = np.random.default_rng(hash(dtype.__name__) % 2**31)
    lo = int(np.iinfo(dtype).min) // 2
    hi = int(np.iinfo(dtype).max) // 2
    bk = rng.choice(np.arange(lo, hi, max((hi - lo) // 4000, 1),
                              dtype=np.int64), 1500, replace=False)
    sk = np.concatenate([rng.choice(bk, 800),
                         rng.integers(lo, hi, 700)]).astype(np.int64)
    H = PK.hash_join_buckets(len(bk))
    tk, tr, ok = PK.hash_join_build(jnp.asarray(bk),
                                    jnp.ones(len(bk), bool), H)
    assert bool(ok)
    pos, found = PK.hash_join_probe(tk, tr, jnp.asarray(sk), H)
    exp_pos, exp_found = _np_hash_oracle(bk, sk)
    assert (np.asarray(found) == exp_found).all()
    assert (np.asarray(pos)[exp_found] == exp_pos[exp_found]).all()


def test_hash_join_build_null_mask_and_empty():
    rng = np.random.default_rng(4)
    bk = rng.permutation(np.arange(0, 10**7, 2500)[:2000]).astype(np.int64)
    elig = rng.random(2000) < 0.7     # ineligible = null / beyond n_build
    H = PK.hash_join_buckets(2000)
    tk, tr, ok = PK.hash_join_build(jnp.asarray(bk), jnp.asarray(elig), H)
    assert bool(ok)
    pos, found = PK.hash_join_probe(tk, tr, jnp.asarray(bk), H)
    # eligible keys find themselves; ineligible keys were never inserted
    assert (np.asarray(found) == elig).all()
    assert (np.asarray(pos)[elig] == np.arange(2000)[elig]).all()
    # empty build: nothing matches
    tk0, tr0, ok0 = PK.hash_join_build(
        jnp.asarray(bk), jnp.zeros(2000, bool), H)
    assert bool(ok0)
    _, found0 = PK.hash_join_probe(tk0, tr0, jnp.asarray(bk), H)
    assert not np.asarray(found0).any()


def test_hash_join_build_refuses_duplicates():
    bk = np.array([5, 9, 5, 11] * 40, np.int64)    # duplicate keys
    H = PK.hash_join_buckets(len(bk))
    _, _, ok = PK.hash_join_build(jnp.asarray(bk),
                                  jnp.ones(len(bk), bool), H)
    assert not bool(ok)


def test_hash_join_build_refuses_bucket_overflow():
    # 128 buckets x 8 slots; hash all keys into few buckets by volume:
    # 2000 unique keys over 128 buckets averages >8 per bucket
    bk = np.arange(1, 2001, dtype=np.int64) * 977
    _, _, ok = PK.hash_join_build(jnp.asarray(bk),
                                  jnp.ones(len(bk), bool), 128)
    assert not bool(ok)


def test_probe_latch_smoke():
    """The per-kernel compile probes the next chip window will take: every
    kernel's tiny instance must run clean in interpret mode so a Mosaic
    failure (not a code bug) is the only thing that can latch it off."""
    import spark_rapids_tpu.ops.pallas_kernels as mod
    saved = mod._TPU_PROBE
    mod._TPU_PROBE = None
    try:
        for kernel in ("murmur3", "bitunpack", "onehot", "radix",
                       "hashjoin"):
            assert mod._probe_tpu(kernel) is True, kernel
    finally:
        mod._TPU_PROBE = saved


def test_join_core_pallas_hash_equivalence():
    """_JoinCore forced through the pallas_hash probe mode equals the
    forced-off jnp paths for every join type the mode serves, across
    sparse int64 keys with nulls."""
    import pyarrow as pa
    from spark_rapids_tpu.session import TpuSession
    rng = np.random.default_rng(9)
    bk = rng.permutation(np.arange(0, 2**44, 2**44 // 3000)[:3000])
    sk = np.concatenate([rng.choice(bk, 2000),
                         rng.integers(0, 2**44, 1000)]).astype(np.int64)
    bnull = rng.random(3000) < 0.05
    snull = rng.random(3000) < 0.05
    spark = TpuSession()
    build = spark.create_dataframe(pa.table({
        "k": pa.array([None if m else int(v) for v, m in zip(bk, bnull)],
                      pa.int64()),
        "b": pa.array(np.arange(3000, dtype=np.int64))}))
    stream = spark.create_dataframe(pa.table({
        "k": pa.array([None if m else int(v) for v, m in zip(sk, snull)],
                      pa.int64()),
        "s": pa.array(np.arange(3000, dtype=np.int64))}))

    def run(how):
        out = stream.join(build, on="k", how=how).collect().to_pylist()
        return sorted((tuple(r.values()) for r in out),
                      key=lambda t: tuple((v is None, v or 0) for v in t))

    for how in ("inner", "left", "left_semi", "left_anti"):
        PK.set_mode(True)
        try:
            a = run(how)
        finally:
            PK.set_mode(False)
        b = run(how)
        PK.set_mode(None)
        assert a == b, how


def test_dense_group_sum_pallas_dispatch_equivalence():
    """dense_group_sum(count_like) forced through the Pallas kernel equals
    the jnp one-hot path — the dense aggregation spine's TPU route."""
    from spark_rapids_tpu.ops import grouping as G
    rng = np.random.default_rng(12)
    cap, D = 4096, 700
    codes = jnp.asarray(rng.integers(0, D + 1, cap).astype(np.int32))
    ones = jnp.ones((cap,), jnp.int64)
    mask = jnp.asarray(rng.random(cap) < 0.9)
    PK.set_mode(True)
    try:
        a = np.asarray(G.dense_group_sum(ones, mask, codes, D, True,
                                         count_like=True))
    finally:
        PK.set_mode(False)
    b = np.asarray(G.dense_group_sum(ones, mask, codes, D, True,
                                     count_like=True))
    c = np.asarray(G.dense_group_sum(ones, mask, codes, D, False,
                                     count_like=True))
    PK.set_mode(None)
    assert np.array_equal(a, b) and np.array_equal(a, c)
