"""Pipelined executor (runtime/pipeline.py): bounded byte-budgeted stage
queues at the plan's pipeline breakers.

Proven here: bit-identical results with the pipeline on vs off (q18 and a
join+sort shape), the per-queue byte budget held under a tiny cap, OOM
split-and-retry recovering INSIDE a pipeline segment, and chaos — an
injected worker-thread fault (runtime/faults.py hooks on queue put/get)
cancels the whole pipeline, re-raises the original error at the consumer,
and leaks neither catalog registrations nor worker threads."""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F_
from spark_rapids_tpu import config as C
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.runtime import faults as F
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import pipeline as P
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.runtime.memory import DeviceManager
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(autouse=True)
def _clean_chaos_state():
    F.reset()
    M.reset_global_registry()
    tracing.clear_events()
    yield
    F.reset()
    M.reset_global_registry()
    tracing.clear_events()


@pytest.fixture(scope="module")
def tpch_paths(tmp_path_factory):
    return tpch.generate(0.005, str(tmp_path_factory.mktemp("tpch_pipe")))


def _pipe_threads():
    return [t for t in threading.enumerate()
            if t.is_alive() and t.name.startswith("srt-pipe-")]


def _await_no_pipe_threads(timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not _pipe_threads():
            return True
        time.sleep(0.05)
    return not _pipe_threads()


# -- BoundedBatchQueue unit behavior ------------------------------------------

def test_queue_byte_budget_respected():
    """With a slow consumer, buffered bytes never exceed the cap (one
    oversized item excepted — the progress guarantee)."""
    item_bytes = 1000
    budget = 2500

    def gen():
        for i in range(20):
            yield pa.table({"v": pa.array(np.full(125, i, np.int64))})

    qbox = []
    it = P.stage_iterator(gen(), edge="t.budget", depth=100,
                          max_bytes=budget, _queue_cb=qbox.append)
    got = []
    for t in it:
        time.sleep(0.01)            # slow consumer → producer hits the cap
        got.append(t)
    assert len(got) == 20
    (q,) = qbox
    assert q.peak_bytes <= max(budget, item_bytes), q.peak_bytes
    assert q.peak_depth <= budget // item_bytes + 1


def test_queue_depth_respected_and_oversized_progress():
    def gen():
        yield pa.table({"v": pa.array(np.zeros(1 << 16))})   # >> budget
        yield pa.table({"v": pa.array([1.0])})

    qbox = []
    got = list(P.stage_iterator(gen(), edge="t.oversized", depth=4,
                                max_bytes=16, _queue_cb=qbox.append))
    assert len(got) == 2            # oversized first item still flowed
    assert qbox[0].peak_depth <= 4


def test_stage_preserves_order_and_objects():
    tabs = [pa.table({"i": [k]}) for k in range(9)]
    got = list(P.stage_iterator(iter(tabs), edge="t.order", depth=3))
    assert [a is b for a, b in zip(got, tabs)] == [True] * 9


def test_stage_propagates_original_error_and_joins_thread():
    err = ValueError("decode exploded mid-stream")

    def gen():
        yield pa.table({"i": [1]})
        raise err

    it = P.stage_iterator(gen(), edge="t.err", depth=2)
    next(it)
    with pytest.raises(ValueError) as ei:
        next(it)
    assert ei.value is err          # the ORIGINAL exception object
    assert _await_no_pipe_threads()


def test_stage_early_close_releases_producer_and_spillables():
    """Abandoning the consumer mid-stream drains the queue, closes queued
    spillable registrations and stops the worker thread."""
    cat = DeviceManager.get().catalog
    base = cat.num_buffers

    def gen():
        for i in range(50):
            t = pa.table({"v": pa.array(np.arange(256, dtype=np.int64))})
            yield ColumnarBatch.from_arrow(t)

    it = P.stage_iterator(gen(), edge="t.close", depth=4, spillable=True)
    next(it)
    it.close()
    assert _await_no_pipe_threads()
    assert cat.num_buffers == base


# -- end-to-end equivalence ----------------------------------------------------

def _q18_rows(paths, extra_conf):
    conf = {"spark.rapids.tpu.sql.format.parquet.reader.type": "COALESCING"}
    conf.update(extra_conf)
    spark = TpuSession(conf)
    dfs = tpch.load(spark, paths, files_per_partition=2)
    return tpch.q18(dfs).collect().to_pylist()


def test_q18_q3_bit_identical_pipeline_on_off(tpch_paths):
    on = _q18_rows(tpch_paths, {"spark.rapids.tpu.pipeline.enabled": True})
    off = _q18_rows(tpch_paths, {"spark.rapids.tpu.pipeline.enabled": False})
    assert on == off

    def q3_rows(extra):
        conf = {"spark.rapids.tpu.pipeline.enabled": extra}
        spark = TpuSession(conf)
        dfs = tpch.load(spark, tpch_paths, files_per_partition=2)
        return tpch.q3(dfs).collect().to_pylist()

    q3_on, q3_off = q3_rows(True), q3_rows(False)
    assert q3_on and q3_on == q3_off    # non-vacuous: q3 returns rows


def _edges_of(spark):
    qm = spark.last_query_metrics()
    assert qm is not None
    edges = set()
    for summary in qm.node_summaries():
        for name in summary["metrics"]:
            if name.startswith((M.QUEUE_WAIT_TIME + ":",
                                M.QUEUE_FULL_TIME + ":")):
                edges.add(name.split(":", 1)[1])
    return edges


def test_q18_queue_metrics_populated(tpch_paths):
    spark = TpuSession({"spark.rapids.tpu.pipeline.enabled": True})
    dfs = tpch.load(spark, tpch_paths, files_per_partition=2)
    tpch.q18(dfs).collect()
    edges = _edges_of(spark)
    # at this scale q18 lowers to broadcast joins + a complete-mode
    # aggregate: the plan crosses scan, sort and collect breakers
    assert any(e.startswith("scan.") for e in edges), edges
    assert "sort.input" in edges, edges
    assert "collect" in edges, edges


def test_exchange_edges_and_metrics():
    rng = np.random.default_rng(5)
    t = pa.table({"k": pa.array(rng.integers(0, 16, 6000).astype(np.int64)),
                  "v": pa.array(rng.integers(0, 99, 6000).astype(np.int64))})
    spark = TpuSession({"spark.rapids.tpu.pipeline.enabled": True})
    df = (spark.create_dataframe(t, num_partitions=3)
          .repartition(4, "k")
          .group_by("k").agg(F_.alias(F_.sum(F_.col("v")), "sv")))
    rows = {r["k"]: r["sv"] for r in df.collect().to_pylist()}
    import collections
    exp = collections.defaultdict(int)
    for k, v in zip(t["k"].to_pylist(), t["v"].to_pylist()):
        exp[k] += v
    assert rows == dict(exp)
    edges = _edges_of(spark)
    assert any(e.startswith("exchange.") for e in edges), edges


def test_join_sort_bit_identical_tiny_queue_bytes():
    """A pathologically small pipeline.maxQueueBytes (forces constant
    producer blocking) still yields identical results."""
    rng = np.random.default_rng(7)
    # integer measures: sums are exact, so equality cannot flake on the
    # merge order of concurrently-arriving partial batches
    t1 = pa.table({"k": pa.array(rng.integers(0, 40, 4000).astype(np.int64)),
                   "v": pa.array(rng.integers(0, 1000, 4000).astype(np.int64))})
    t2 = pa.table({"k": pa.array(np.arange(40, dtype=np.int64)),
                   "w": pa.array(rng.normal(size=40))})

    def run(conf):
        spark = TpuSession(conf)
        a = spark.create_dataframe(t1, num_partitions=3)
        b = spark.create_dataframe(t2)
        q = (a.join(b, on="k")
             .group_by("k").agg(F_.alias(F_.sum(F_.col("v")), "sv"),
                                F_.alias(F_.max(F_.col("w")), "mw"))
             .sort("k"))
        return q.collect().to_pylist()

    on = run({"spark.rapids.tpu.pipeline.enabled": True,
              "spark.rapids.tpu.pipeline.maxQueueBytes": 64,
              "spark.rapids.tpu.pipeline.queueDepth": 1})
    off = run({"spark.rapids.tpu.pipeline.enabled": False})
    assert on == off


# -- OOM split-and-retry inside a pipeline segment -----------------------------

def test_oom_split_retry_inside_pipeline_segment():
    """An injected split-OOM on the exchange map writer recovers
    bit-identically while the map segment runs behind pipeline queues."""
    rng = np.random.default_rng(11)
    t = pa.table({"k": pa.array(rng.integers(0, 8, 5000).astype(np.int64)),
                  "v": pa.array(rng.integers(0, 500, 5000).astype(np.int64))})

    def run(extra):
        conf = {"spark.rapids.tpu.pipeline.enabled": True,
                # the toy batches are ~40KB; keep them splittable
                "spark.rapids.tpu.memory.retry.splitFloorBytes": "1k"}
        conf.update(extra)
        spark = TpuSession(conf)
        df = (spark.create_dataframe(t, num_partitions=2)
              .repartition(3, "k")
              .group_by("k").agg(F_.alias(F_.sum(F_.col("v")), "sv"))
              .sort("k"))
        return df.collect().to_pylist()

    clean = run({})
    M.reset_global_registry()
    chaotic = run({"spark.rapids.tpu.test.faults": "splitoom:exchange.map:1"})
    assert chaotic == clean
    g = M.global_registry()
    assert g.metric(M.NUM_OOM_SPLIT_RETRIES).value >= 1
    assert ("splitoom", "exchange.map") in F.injected_log()
    F.reset()


# -- chaos: worker-thread fault must fail the whole query CLEANLY --------------

def test_chaos_decode_fault_fails_clean(tmp_path):
    import pyarrow.parquet as pq
    rng = np.random.default_rng(3)
    t = pa.table({"k": pa.array(rng.integers(0, 9, 3000).astype(np.int64)),
                  "v": pa.array(rng.normal(size=3000))})
    for i in range(3):
        pq.write_table(t.slice(i * 1000, 1000), tmp_path / f"p{i}.parquet")

    cat = DeviceManager.get().catalog
    base = cat.num_buffers
    spark = TpuSession({
        "spark.rapids.tpu.pipeline.enabled": True,
        "spark.rapids.tpu.test.faults": "error:pipeline.put.scan.decode:1"})
    df = (spark.read_parquet(str(tmp_path))
          .group_by("k").agg(F_.alias(F_.sum(F_.col("v")), "sv")))
    with pytest.raises(RuntimeError, match="fault-injection"):
        df.collect()
    assert ("error", "pipeline.put.scan.decode") in F.injected_log()
    F.reset()
    # the failed pipeline left nothing behind: no catalog registrations, no
    # worker threads (give finalizers a moment)
    import gc
    gc.collect()
    assert _await_no_pipe_threads(), _pipe_threads()
    assert cat.num_buffers == base
    # and the engine still works afterwards
    out = spark.read_parquet(str(tmp_path)).collect()
    assert out.num_rows == 3000


def test_chaos_get_fault_at_consumer(tmp_path):
    """A fault armed on the queue GET side surfaces at the consumer too."""
    import pyarrow.parquet as pq
    t = pa.table({"v": pa.array(np.arange(2000, dtype=np.int64))})
    pq.write_table(t, tmp_path / "x.parquet")
    cat = DeviceManager.get().catalog
    base = cat.num_buffers
    spark = TpuSession({
        "spark.rapids.tpu.pipeline.enabled": True,
        "spark.rapids.tpu.test.faults": "error:pipeline.get.scan.upload:1"})
    with pytest.raises(RuntimeError, match="fault-injection"):
        spark.read_parquet(str(tmp_path)).collect()
    F.reset()
    import gc
    gc.collect()
    assert _await_no_pipe_threads(), _pipe_threads()
    assert cat.num_buffers == base
