"""TPC-H q1/q3/q5 end-to-end through the session API vs independent NumPy
oracles (BASELINE.md config-2; reference mortgage-app role)."""

import pytest

from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpch")
    paths = tpch.generate(0.005, str(d))
    spark = TpuSession()
    return tpch.load(spark, paths), tpch.load_np(paths)


def test_q1(data):
    dfs, tb = data
    got = tpch.q1(dfs).collect().to_pylist()
    exp = tpch.np_q1(tb)
    assert len(got) == len(exp) == 4
    for g_, e in zip(got, exp):
        g = list(g_.values())
        assert g[0] == e[0] and g[1] == e[1]
        for a, b in zip(g[2:], e[2:]):
            assert a == pytest.approx(b, rel=1e-9)


def test_q3(data):
    dfs, tb = data
    got = tpch.q3(dfs).collect().to_pylist()
    exp = tpch.np_q3(tb)
    assert len(got) == len(exp)
    for g, (k, d, p, rev) in zip(got, exp):
        assert g["l_orderkey"] == k
        assert g["o_shippriority"] == p
        assert g["revenue"] == pytest.approx(rev, rel=1e-9)


def test_q5(data):
    dfs, tb = data
    got = tpch.q5(dfs).collect().to_pylist()
    exp = tpch.np_q5(tb)
    assert len(got) == len(exp)
    for g, (n, v) in zip(got, exp):
        assert g["n_name"] == n
        assert g["revenue"] == pytest.approx(v, rel=1e-9)


def test_q18(data):
    import datetime
    dfs, tb = data
    got = tpch.q18(dfs).collect().to_pylist()
    exp = tpch.np_q18(tb)
    assert len(got) == len(exp)   # may be empty at tiny SF — both sides
    epoch = datetime.date(1970, 1, 1)
    for g, (c, o, d, t, s) in zip(got, exp):
        assert g["c_custkey"] == c and g["o_orderkey"] == o
        gd = g["o_orderdate"]
        if isinstance(gd, datetime.date):
            gd = (gd - epoch).days
        assert gd == d
        assert g["o_totalprice"] == pytest.approx(t, rel=1e-9)
        assert g["sum_qty"] == pytest.approx(s, rel=1e-9)
