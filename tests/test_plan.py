"""Planner/override layer tests (ring 2: host-oracle vs device equivalence).

Reference test strategy: SparkQueryCompareTestSuite.scala:183 runs each query under
withCpuSparkSession and withGpuSparkSession and diffs results; fallback assertions
via ExecutionPlanCaptureCallback (Plugin.scala:315). Here the host interpreter
(plan/nodes.py + plan/host_eval.py) is the CPU oracle."""

import math

import pyarrow as pa
import pytest

from conftest import make_table

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.expr.core import Alias, col, lit
from spark_rapids_tpu.expr import arithmetic as A
from spark_rapids_tpu.expr import predicates as P
from spark_rapids_tpu.expr.aggregates import Average, Count, Max, Min, Sum
from spark_rapids_tpu.expr.strings import Length, Upper
from spark_rapids_tpu.plan import (
    AggregateNode, ExchangeNode, FilterNode, JoinNode, LimitNode, ProjectNode,
    RangeNode, ScanNode, SortNode, TpuOverrides, UnionNode, explain_plan,
)
from spark_rapids_tpu.plan.transitions import (
    DeviceBridgeExec, HostBridgeNode, execute_hybrid,
)
from spark_rapids_tpu.exec.base import TpuExec


def split_table(tbl, n_parts):
    per = -(-tbl.num_rows // n_parts)
    return [tbl.slice(i * per, per) for i in range(n_parts)]


def norm(tbl: pa.Table, sort_cols=None):
    """Canonical row ordering for unordered compare (pytest ignore_order mark
    analog, integration_tests asserts.py)."""
    rows = list(zip(*[tbl.column(i).to_pylist() for i in range(tbl.num_columns)]))
    def key(r):
        out = []
        for v in r:
            if v is None:
                out.append((2, 0))
            elif isinstance(v, float) and math.isnan(v):
                out.append((1, 0))
            else:
                out.append((0, v))
        return out
    return sorted(rows, key=key)


def assert_tpu_and_host_equal(plan, conf=None, approx=False):
    host = plan.collect_host()
    hybrid = TpuOverrides(conf or RapidsConf()).apply(plan)
    dev = execute_hybrid(hybrid)
    assert host.num_rows == dev.num_rows, (host.num_rows, dev.num_rows)
    assert host.column_names == dev.column_names
    h, d = norm(host), norm(dev)
    for hr, dr in zip(h, d):
        for hv, dv in zip(hr, dr):
            if isinstance(hv, float) and isinstance(dv, float):
                if math.isnan(hv):
                    assert math.isnan(dv), (hr, dr)
                elif approx or abs(hv) > 1e13:
                    assert dv == pytest.approx(hv, rel=1e-9), (hr, dr)
                else:
                    assert hv == dv, (hr, dr)
            else:
                assert hv == dv, (hr, dr)
    return hybrid


def test_project_filter_equivalence(mixed_table):
    scan = ScanNode(split_table(mixed_table, 3))
    f = FilterNode(P.GreaterThan(col("i"), lit(0)), scan)
    p = ProjectNode([Alias(A.Add(col("i"), col("i")), "i2"),
                     Alias(A.Multiply(col("d"), lit(2.0)), "d2"),
                     col("s")], f)
    hybrid = assert_tpu_and_host_equal(p)
    assert isinstance(hybrid, TpuExec)  # fully on device


def test_aggregate_two_phase_equivalence(mixed_table):
    scan = ScanNode(split_table(mixed_table, 4))
    agg = AggregateNode(
        [col("b")],
        [Alias(Sum(col("l")), "sum_l"), Alias(Count(col("i")), "cnt"),
         Alias(Min(col("d")), "mn"), Alias(Max(col("d")), "mx"),
         Alias(Average(col("i")), "avg_i")],
        scan)
    assert_tpu_and_host_equal(agg, approx=True)


def test_global_aggregate_no_keys(mixed_table):
    scan = ScanNode(split_table(mixed_table, 3))
    agg = AggregateNode([], [Alias(Count(None), "n"),
                             Alias(Sum(col("i")), "s")], scan)
    assert_tpu_and_host_equal(agg)


def test_join_equivalence(mixed_table):
    left = ScanNode(split_table(mixed_table.select(["i", "l"]), 2))
    rt = pa.table({"i2": pa.array(list(range(-50, 50)), pa.int32()),
                   "tag": pa.array([f"t{v % 7}" for v in range(100)])})
    right = ScanNode([rt])
    for jt in ("inner", "left", "leftsemi", "leftanti"):
        j = JoinNode(left, right, [col("i")], [col("i2")], jt)
        assert_tpu_and_host_equal(j)


def test_sort_limit_equivalence(mixed_table):
    scan = ScanNode(split_table(mixed_table, 3))
    s = SortNode([(col("i"), True, True), (col("d"), False, False)], scan)
    out_host = s.collect_host()
    hybrid = TpuOverrides(RapidsConf()).apply(s)
    out_dev = execute_hybrid(hybrid)
    # sorted compare must preserve order
    for name in ("i", "d", "s"):
        assert out_host.column(name).to_pylist() == \
            out_dev.column(name).to_pylist(), name


def test_union_and_exchange(mixed_table):
    a = ScanNode(split_table(mixed_table, 2))
    b = ScanNode(split_table(mixed_table, 3))
    u = UnionNode(a, b)
    ex = ExchangeNode(u, "hash", 5, keys=[col("i")])
    assert_tpu_and_host_equal(ex)


def test_range_project(mixed_table):
    r = RangeNode(0, 1000, 3, num_slices=4)
    p = ProjectNode([col("id"), Alias(A.Remainder(col("id"), lit(7)), "m")], r)
    assert_tpu_and_host_equal(p)


def test_fallback_unsupported_expression(mixed_table):
    """An expression with no rule pins its exec to the host; the rest of the plan
    still runs on device, bridged (reference: willNotWorkOnGpu + transitions)."""
    class WeirdExpr(P.Not):  # subclass so binding works but no exact rule… Not has
        pass                 # a rule; use a genuinely unknown class instead

    from spark_rapids_tpu.expr.core import Expression

    class NoRuleExpr(Expression):
        def __init__(self, child):
            self.children = [child]

        @property
        def dtype(self):
            return T.BOOLEAN

        @property
        def nullable(self):
            return True

        def eval(self, ctx):
            raise RuntimeError("never on device")

    scan = ScanNode(split_table(mixed_table, 2))
    f = FilterNode(NoRuleExpr(col("b")), scan)
    txt = explain_plan(f)
    assert "cannot run on TPU" in txt and "NoRuleExpr" in txt

    hybrid = TpuOverrides(RapidsConf()).apply(f)
    # root (filter) stayed on host but its child scan is device-backed
    assert not isinstance(hybrid, TpuExec)
    assert isinstance(hybrid.children[0], HostBridgeNode)


def test_fallback_host_execution_end_to_end(mixed_table):
    """Host-pinned node actually executes through the interpreter with device
    children feeding it through the bridge."""
    from spark_rapids_tpu.plan import nodes as NN

    scan = ScanNode(split_table(mixed_table.select(["i", "l", "b"]), 2))
    proj = ProjectNode([col("i"), col("l"), col("b")], scan)
    # nested element type pins the generate to host (device rule rejects it)
    gen_tbl = pa.table({
        "k": pa.array([1, 2, 3], pa.int32()),
        "arr": pa.array([[[1], [2]], [], [[5]]],
                        pa.list_(pa.list_(pa.int64())))})
    g = NN.GenerateNode("arr", ScanNode([gen_tbl]), outer=False,
                        element_type=T.ArrayType(T.LONG))
    txt = explain_plan(g)
    assert "nested element type" in txt
    hybrid = TpuOverrides(RapidsConf()).apply(g)
    assert not isinstance(hybrid, TpuExec)
    out = execute_hybrid(hybrid)
    assert out.column("k").to_pylist() == [1, 1, 3]
    assert out.column("col").to_pylist() == [[1], [2], [5]]


def test_explain_output(mixed_table):
    scan = ScanNode(split_table(mixed_table, 2))
    p = ProjectNode([Alias(Upper(col("s")), "u"),
                     Alias(Length(col("s")), "n")], scan)
    txt = explain_plan(p)
    assert "*ProjectNode will run on TPU" in txt
    assert "@Upper will run on TPU" in txt


def test_supported_ops_doc():
    from spark_rapids_tpu.plan.overrides import REGISTRY
    from spark_rapids_tpu.plan.typesig import generate_supported_ops_doc
    doc = generate_supported_ops_doc(REGISTRY)
    assert "| ProjectNode |" in doc
    assert "| Cast |" in doc


def test_cast_string_to_float_conf_gate(mixed_table):
    from spark_rapids_tpu.expr.cast import Cast
    scan = ScanNode([mixed_table.select(["s"])])
    p = ProjectNode([Alias(Cast(col("s"), T.DOUBLE), "f")], scan)
    txt = explain_plan(p)
    assert "castStringToFloat" in txt


def test_host_eval_in_casewhen_nullsafe(mixed_table):
    """Host-oracle regressions: In reads expr.values; CaseWhen else_value;
    EqualNullSafe null<=>null is True."""
    from spark_rapids_tpu.expr.conditional import CaseWhen
    scan = ScanNode(split_table(mixed_table, 2))
    p = ProjectNode([
        Alias(P.In(col("i"), [1, 2, None]), "in_m"),
        Alias(CaseWhen([(P.GreaterThan(col("i"), lit(0)), lit(1))],
                       else_value=lit(-1)), "cw"),
        Alias(P.EqualNullSafe(col("i"), col("i")), "ns"),
    ], scan)
    assert_tpu_and_host_equal(p)
    host = p.collect_host()
    assert all(v is True for v in host["ns"].to_pylist())  # null<=>null == True


def test_keyless_right_join_falls_back(mixed_table):
    lt = pa.table({"a": pa.array([1, 5], pa.int64())})
    rt = pa.table({"b": pa.array([6, 7], pa.int64())})
    j = JoinNode(ScanNode([lt]), ScanNode([rt]), [], [], "right")
    txt = explain_plan(j)
    assert "keyless right outer" in txt
    hybrid = TpuOverrides(RapidsConf()).apply(j)
    out = execute_hybrid(hybrid)
    # keyless + no condition: every pair matches, no null-extended rows
    assert out.num_rows == 4


def test_host_join_duplicate_column_names():
    lt = pa.table({"k": pa.array([1, 2], pa.int64()),
                   "x": pa.array([10, 20], pa.int64())})
    rt = pa.table({"k": pa.array([2, 3], pa.int64()),
                   "x": pa.array([200, 300], pa.int64())})
    j = JoinNode(ScanNode([lt]), ScanNode([rt]), [col("k")], [col("k")], "inner")
    out = j.collect_host()
    assert out.num_columns == 4
    assert out.column(0).to_pylist() == [2]
    assert out.column(3).to_pylist() == [200]


def test_host_semi_join_with_condition():
    from spark_rapids_tpu.expr.predicates import GreaterThan
    lt = pa.table({"a": pa.array([1, 5, 7], pa.int64()),
                   "v": pa.array([0, 10, 10], pa.int64())})
    rt = pa.table({"b": pa.array([1, 5], pa.int64()),
                   "w": pa.array([5, 5], pa.int64())})
    j = JoinNode(ScanNode([lt]), ScanNode([rt]), [col("a")], [col("b")],
                 "leftsemi", condition=GreaterThan(col("v"), col("w")))
    out = j.collect_host()
    assert out["a"].to_pylist() == [5]


def test_expand_exec_equivalence(mixed_table):
    """Rollup-style expand: (i, b, grouping_id) projections interleave per row
    (reference GpuExpandExec)."""
    from spark_rapids_tpu.plan import ExpandNode
    scan = ScanNode(split_table(mixed_table.select(["i", "b", "l"]), 2))
    projections = [
        [col("i"), col("b"), lit(0)],
        [col("i"), lit(None, T.BOOLEAN), lit(1)],
        [lit(None, T.INT), lit(None, T.BOOLEAN), lit(3)],
    ]
    out_fields = [T.StructField("i", T.INT, True),
                  T.StructField("b", T.BOOLEAN, True),
                  T.StructField("gid", T.INT, False)]
    node = ExpandNode(projections, out_fields, scan)
    hybrid = assert_tpu_and_host_equal(node)
    assert isinstance(hybrid, TpuExec)
    agg = AggregateNode([col("gid")], [Alias(Count(None), "n")], node)
    assert_tpu_and_host_equal(agg)


def test_expand_with_strings(mixed_table):
    from spark_rapids_tpu.plan import ExpandNode
    scan = ScanNode([mixed_table.select(["s", "i"])])
    projections = [
        [col("s"), lit(0)],
        [lit("all", T.STRING), lit(1)],
    ]
    out_fields = [T.StructField("s", T.STRING, True),
                  T.StructField("gid", T.INT, False)]
    node = ExpandNode(projections, out_fields, scan)
    assert_tpu_and_host_equal(node)
