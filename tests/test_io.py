"""I/O layer tests: reader strategies, pushdown, partition discovery, writers.

Reference ring-2/3 coverage of GpuParquetScan/GpuOrcScan/CSV + writer suites
(ParquetWriterSuite, OrcScanSuite patterns; SURVEY.md §4)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from conftest import make_table

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.expr.core import Alias, col, lit
from spark_rapids_tpu.expr import predicates as P
from spark_rapids_tpu.expr.aggregates import Count, Sum
from spark_rapids_tpu.io import FileScanNode, FileSourceScanExec, write_columnar
from spark_rapids_tpu.plan import (AggregateNode, FilterNode, ProjectNode,
                                   TpuOverrides)
from spark_rapids_tpu.plan.transitions import execute_hybrid
from test_plan import norm


@pytest.fixture
def parquet_dir(tmp_path):
    root = tmp_path / "data"
    root.mkdir()
    for i in range(6):
        t = make_table(n=300, seed=i)
        pq.write_table(t, root / f"f{i}.parquet", row_group_size=100)
    return str(root)


def full_table(parquet_dir):
    files = sorted(os.path.join(parquet_dir, f) for f in os.listdir(parquet_dir)
                   if f.endswith(".parquet"))
    return pa.concat_tables([pq.read_table(f) for f in files])


@pytest.mark.parametrize("strategy", ["PERFILE", "MULTITHREADED", "COALESCING"])
def test_parquet_reader_strategies(parquet_dir, strategy):
    conf = RapidsConf({
        "spark.rapids.tpu.sql.format.parquet.reader.type": strategy})
    node = FileScanNode(parquet_dir, "parquet", files_per_partition=3)
    ex = FileSourceScanExec(node, conf=conf)
    got = ex.execute_collect()
    want = full_table(parquet_dir)
    assert norm(got) == norm(want)
    if strategy == "COALESCING":
        # 3 files/partition stitch into one batch per partition
        assert int(ex.metrics.snapshot()["numOutputBatches"]) <= \
            2 * node.num_partitions


def test_parquet_pushdown_prunes_and_filters(parquet_dir):
    node = FileScanNode(parquet_dir, "parquet",
                        pushed_filter=P.GreaterThan(col("i"), lit(500)))
    got = node.collect_host()
    want = full_table(parquet_dir)
    import pyarrow.compute as pc
    want = want.filter(pc.greater(want.column("i"), 500))
    assert norm(got) == norm(want)
    # device path agrees
    ex = FileSourceScanExec(node, conf=RapidsConf())
    assert norm(ex.execute_collect()) == norm(want)


def test_hive_partition_discovery(tmp_path):
    root = tmp_path / "hive"
    for year in (2020, 2021):
        for part in ("a", "b"):
            d = root / f"year={year}" / f"tag={part}"
            d.mkdir(parents=True)
            pq.write_table(pa.table({"v": pa.array([1, 2, 3], pa.int64())}),
                           d / "part-0.parquet")
    node = FileScanNode(str(root), "parquet")
    assert node.num_partitions == 4
    out = node.collect_host()
    assert set(out.column_names) == {"v", "year", "tag"}
    assert sorted(set(out.column("year").to_pylist())) == [2020, 2021]
    # partition column usable in a device plan
    agg = AggregateNode([col("year")], [Alias(Count(None), "n"),
                                        Alias(Sum(col("v")), "s")], node)
    hybrid = TpuOverrides(RapidsConf()).apply(agg)
    got = execute_hybrid(hybrid)
    rows = sorted(zip(got["year"].to_pylist(), got["n"].to_pylist(),
                      got["s"].to_pylist()))
    assert rows == [(2020, 6, 12), (2021, 6, 12)]


def test_scan_into_device_plan(parquet_dir):
    node = FileScanNode(parquet_dir, "parquet", files_per_partition=2)
    f = FilterNode(P.GreaterThan(col("i"), lit(0)), node)
    agg = AggregateNode([col("b")], [Alias(Count(None), "n")], f)
    host = agg.collect_host()
    dev = execute_hybrid(TpuOverrides(RapidsConf()).apply(agg))
    assert norm(host) == norm(dev)


def test_orc_roundtrip(tmp_path, mixed_table):
    import pyarrow.orc as orc
    path = tmp_path / "t.orc"
    # ORC writer rejects some null combos in old pyarrow; drop f
    tbl = mixed_table.drop_columns(["f"])
    orc.write_table(tbl, str(path))
    node = FileScanNode(str(path), "orc")
    got = FileSourceScanExec(node, conf=RapidsConf()).execute_collect()
    assert norm(got) == norm(tbl)


def test_csv_scan_with_schema(tmp_path):
    path = tmp_path / "t.csv"
    path.write_text("a,b,c\n1,x,2.5\n2,,\n,z,0.25\n")
    schema = T.StructType([T.StructField("a", T.INT, True),
                           T.StructField("b", T.STRING, True),
                           T.StructField("c", T.DOUBLE, True)])
    node = FileScanNode(str(path), "csv", schema=schema,
                        options={"header": True, "schema": schema})
    got = FileSourceScanExec(node, conf=RapidsConf()).execute_collect()
    assert got.column("a").to_pylist() == [1, 2, None]
    assert got.column("b").to_pylist() == ["x", None, "z"]
    assert got.column("c").to_pylist() == [2.5, None, 0.25]


def test_csv_disabled_falls_back(tmp_path):
    path = tmp_path / "t.csv"
    path.write_text("a\n1\n2\n")
    node = FileScanNode(str(path), "csv",
                        options={"header": True})
    conf = RapidsConf({"spark.rapids.tpu.sql.format.csv.enabled": "false"})
    from spark_rapids_tpu.plan import explain_plan
    txt = explain_plan(node, conf)
    assert "CSV scan disabled" in txt


def test_write_parquet_roundtrip(tmp_path, mixed_table):
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    conf = RapidsConf()
    src = ArrowScanExec([mixed_table.slice(0, 500), mixed_table.slice(500, 500)],
                        conf=conf)
    out = str(tmp_path / "out")
    stats = write_columnar(src, out, "parquet")
    assert stats.num_files == 2
    assert stats.num_rows == 1000
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    back = FileScanNode(out, "parquet").collect_host()
    assert norm(back) == norm(mixed_table)


def test_write_dynamic_partitioning(tmp_path):
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    t = pa.table({"k": pa.array([1, 2, 1, None, 2], pa.int64()),
                  "v": pa.array([10.0, 20.0, 30.0, 40.0, 50.0])})
    conf = RapidsConf()
    src = ArrowScanExec([t], conf=conf)
    out = str(tmp_path / "out")
    stats = write_columnar(src, out, "parquet", partition_by=["k"])
    assert sorted(stats.partitions) == [
        "k=1", "k=2", "k=__HIVE_DEFAULT_PARTITION__"]
    back = FileScanNode(os.path.join(out, "k=1"), "parquet").collect_host()
    assert sorted(back.column("v").to_pylist()) == [10.0, 30.0]


def test_write_mode_overwrite_and_error(tmp_path, mixed_table):
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    conf = RapidsConf()
    src = ArrowScanExec([mixed_table], conf=conf)
    out = str(tmp_path / "out")
    write_columnar(src, out, "parquet")
    with pytest.raises(FileExistsError):
        write_columnar(src, out, "parquet", mode="error")
    stats = write_columnar(src, out, "parquet", mode="overwrite")
    assert stats.num_rows == mixed_table.num_rows


def test_float_filter_not_pushed_nan_exact(tmp_path):
    """NaN semantics: Arrow IEEE ordering would drop NaN rows that Spark keeps,
    so float predicates go through the residual host filter instead."""
    t = pa.table({"f": pa.array([float("nan"), 1.0, -2.0, None], pa.float64()),
                  "i": pa.array([1, 2, 3, 4], pa.int64())})
    pq.write_table(t, tmp_path / "t.parquet")
    node = FileScanNode(str(tmp_path / "t.parquet"), "parquet",
                        pushed_filter=P.GreaterThan(col("f"), lit(0.0)))
    out = node.collect_host()
    # Spark: NaN > 0.0 is true (NaN is largest); null drops
    assert sorted(out.column("i").to_pylist()) == [1, 2]
    dev = FileSourceScanExec(node, conf=RapidsConf()).execute_collect()
    assert sorted(dev.column("i").to_pylist()) == [1, 2]


def test_scan_skips_temporary_dirs(tmp_path):
    out = tmp_path / "data"
    (out / "_temporary-xyz" / "task_0").mkdir(parents=True)
    pq.write_table(pa.table({"v": pa.array([1], pa.int64())}),
                   out / "good.parquet")
    pq.write_table(pa.table({"v": pa.array([99], pa.int64())}),
                   out / "_temporary-xyz" / "task_0" / "part.parquet")
    node = FileScanNode(str(out), "parquet")
    assert node.collect_host().column("v").to_pylist() == [1]


def test_inconsistent_partition_layout_rejected(tmp_path):
    root = tmp_path / "mixed"
    (root / "a=1").mkdir(parents=True)
    (root / "plain").mkdir(parents=True)
    pq.write_table(pa.table({"v": pa.array([1], pa.int64())}),
                   root / "a=1" / "f.parquet")
    pq.write_table(pa.table({"v": pa.array([2], pa.int64())}),
                   root / "plain" / "f.parquet")
    with pytest.raises(ValueError, match="inconsistent partition"):
        FileScanNode(str(root), "parquet")


def test_write_mode_ignore_and_bad_mode(tmp_path, mixed_table):
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    conf = RapidsConf()
    src = ArrowScanExec([mixed_table], conf=conf)
    out = str(tmp_path / "out")
    write_columnar(src, out, "parquet")
    n_files = len(os.listdir(out))
    stats = write_columnar(src, out, "parquet", mode="ignore")
    assert stats.num_files == 0 and len(os.listdir(out)) == n_files
    with pytest.raises(ValueError, match="save mode"):
        write_columnar(src, out, "parquet", mode="overwrit")


def test_write_mode_append_no_collision(tmp_path, mixed_table):
    """Append must never overwrite files from an earlier job that used the same
    task ids (part filenames carry a job-unique uuid)."""
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    conf = RapidsConf()
    src = ArrowScanExec([mixed_table], conf=conf)
    out = str(tmp_path / "out")
    write_columnar(src, out, "parquet")
    write_columnar(src, out, "parquet", mode="append")
    back = FileScanNode(out, "parquet").collect_host()
    assert back.num_rows == 2 * mixed_table.num_rows


# -- device CSV decode (stage one: io/csv_native.py + ops/csv_decode.py) ------

def _write_csv(tmp_path, text, name="t.csv"):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_csv_device_decode_ints_matches_host(tmp_path):
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.session import TpuSession
    text = "a,b\n1,10\n-5,9223372036854775807\n,42\n8,-9223372036854775808\n"
    path = _write_csv(tmp_path, text)
    schema = T.StructType([T.StructField("a", T.LONG), T.StructField("b", T.LONG)])

    on = TpuSession({"spark.rapids.tpu.sql.csv.deviceDecode.enabled": "true"}
                    ).read_csv(path, schema=schema).collect()
    off = TpuSession({"spark.rapids.tpu.sql.csv.deviceDecode.enabled": "false"}
                     ).read_csv(path, schema=schema).collect()
    assert on["a"].to_pylist() == off["a"].to_pylist() == [1, -5, None, 8]
    assert on["b"].to_pylist() == off["b"].to_pylist() == \
        [10, 9223372036854775807, 42, -9223372036854775808]

    # '+7' parses like Spark (Long.parseLong) on device; pyarrow's host
    # reader rejects it, so it is asserted on the device path only
    p2 = _write_csv(tmp_path, "a\n+7\n", name="plus.csv")
    on2 = TpuSession({"spark.rapids.tpu.sql.csv.deviceDecode.enabled": "true"}).read_csv(
        p2, schema=T.StructType([T.StructField("a", T.LONG)])).collect()
    assert on2["a"].to_pylist() == [7]


def test_csv_device_decode_malformed_is_null(tmp_path):
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.session import TpuSession
    text = "a\n12\nx9\n--3\n+\n8\n"
    path = _write_csv(tmp_path, text)
    schema = T.StructType([T.StructField("a", T.LONG)])
    out = TpuSession({"spark.rapids.tpu.sql.csv.deviceDecode.enabled": "true"}).read_csv(path, schema=schema).collect()
    assert out["a"].to_pylist() == [12, None, None, None, 8]


def test_csv_device_decode_doubles_gated(tmp_path):
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.session import TpuSession
    text = "x,y\n1.5,2\n-0.25,7\n,0\n3.,1\n"
    path = _write_csv(tmp_path, text)
    schema = T.StructType([T.StructField("x", T.DOUBLE), T.StructField("y", T.LONG)])
    # default: float columns keep the whole file on the host reader
    out = TpuSession().read_csv(path, schema=schema).collect()
    assert out["x"].to_pylist() == [1.5, -0.25, None, 3.0]
    # conf on: device parse, plain decimals are exact
    on = TpuSession({"spark.rapids.tpu.sql.csv.read.float.enabled": "true"}
                    ).read_csv(path, schema=schema).collect()
    assert on["x"].to_pylist() == [1.5, -0.25, None, 3.0]
    assert on["y"].to_pylist() == [2, 7, 0, 1]


def test_csv_device_decode_fallback_scope(tmp_path):
    """Quotes, exponents, ragged rows → host path, same results."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.session import TpuSession
    schema = T.StructType([T.StructField("x", T.DOUBLE)])
    path = _write_csv(tmp_path, "x\n1e3\n2.5\n", name="e.csv")
    out = TpuSession({"spark.rapids.tpu.sql.csv.read.float.enabled": "true"}
                     ).read_csv(path, schema=schema).collect()
    assert out["x"].to_pylist() == [1000.0, 2.5]

    schema2 = T.StructType([T.StructField("s", T.STRING)])
    path2 = _write_csv(tmp_path, 's\n"a,b"\nplain\n', name="q.csv")
    out2 = TpuSession().read_csv(path2, schema=schema2).collect()
    assert out2["s"].to_pylist() == ["a,b", "plain"]


def test_csv_device_decode_equivalence_fuzz(tmp_path):
    import numpy as np
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.session import TpuSession
    rng = np.random.default_rng(5)
    n = 500
    a = rng.integers(-10**12, 10**12, n)
    rows = ["a,b"]
    for i in range(n):
        av = "" if rng.random() < 0.1 else str(a[i])
        bv = str(rng.integers(-2**31, 2**31 - 1))
        rows.append(f"{av},{bv}")
    path = _write_csv(tmp_path, "\n".join(rows) + "\n", name="f.csv")
    schema = T.StructType([T.StructField("a", T.LONG), T.StructField("b", T.INT)])
    on = TpuSession({"spark.rapids.tpu.sql.csv.deviceDecode.enabled": "true"}
                    ).read_csv(path, schema=schema).collect()
    off = TpuSession({"spark.rapids.tpu.sql.csv.deviceDecode.enabled": "false"}
                     ).read_csv(path, schema=schema).collect()
    assert on["a"].to_pylist() == off["a"].to_pylist()
    assert on["b"].to_pylist() == off["b"].to_pylist()


def test_csv_device_decode_header_name_mapping(tmp_path):
    """Schema order != file header order: fields map BY NAME like the host
    reader, never by position."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.session import TpuSession
    path = _write_csv(tmp_path, "b,a\n1,2\n3,4\n", name="swap.csv")
    schema = T.StructType([T.StructField("a", T.LONG), T.StructField("b", T.LONG)])
    out = TpuSession().read_csv(path, schema=schema).collect()
    assert out["a"].to_pylist() == [2, 4]
    assert out["b"].to_pylist() == [1, 3]


def test_csv_device_decode_overflow_and_overlong(tmp_path):
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.session import TpuSession
    text = ("a\n9223372036854775807\n9223372036854775808\n"
            "-9223372036854775808\n-9223372036854775809\n"
            "123456789012345678901234567\n7\n")
    path = _write_csv(tmp_path, text, name="ovf.csv")
    schema = T.StructType([T.StructField("a", T.LONG)])
    out = TpuSession({"spark.rapids.tpu.sql.csv.deviceDecode.enabled": "true"}
                     ).read_csv(path, schema=schema).collect()
    assert out["a"].to_pylist() == [9223372036854775807, None,
                                    -9223372036854775808, None, None, 7]


def test_csv_quoted_fields_device_path(tmp_path):
    """RFC-4180 quoted fields stay on the DEVICE path: wrapping quotes strip,
    and delimiters inside quotes are content, not boundaries."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.io import csv_native as CN
    from spark_rapids_tpu.session import TpuSession
    path = _write_csv(tmp_path, 'a,b\n"5",10\n6,"20"\n"7","30"\n,40\n',
                      name="qint.csv")
    schema = T.StructType([T.StructField("a", T.LONG),
                           T.StructField("b", T.LONG)])
    shape = CN.try_scan_for_device(path, schema, ",", True, False)
    assert shape is not None          # quoted numerics are in scope now
    out = TpuSession().read_csv(path, schema=schema).collect()
    assert out["a"].to_pylist() == [5, 6, 7, None]
    assert out["b"].to_pylist() == [10, 20, 30, 40]


def test_csv_quotes_mask_embedded_delims_and_newlines(tmp_path):
    """Delimiters and newlines inside a quoted field must not split rows.
    The quoted field itself is non-numeric -> that CELL parses null, but row
    structure (and the sibling numeric column) survives on device."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.io import csv_native as CN
    path = _write_csv(tmp_path, 'a,b\n"1,5",10\n2,20\n', name="qdelim.csv")
    schema = T.StructType([T.StructField("a", T.LONG),
                           T.StructField("b", T.LONG)])
    shape = CN.try_scan_for_device(path, schema, ",", True, False)
    assert shape is not None and shape.n_rows == 2
    # stray/doubled quotes inside content -> host path
    p2 = _write_csv(tmp_path, 'a\n"5""6"\n', name="qq.csv")
    assert CN.try_scan_for_device(
        p2, T.StructType([T.StructField("a", T.LONG)]), ",", True,
        False) is None
    # unterminated quote -> host path
    p3 = _write_csv(tmp_path, 'a\n"5\n', name="unterm.csv")
    assert CN.try_scan_for_device(
        p3, T.StructType([T.StructField("a", T.LONG)]), ",", True,
        False) is None


def test_csv_float_gate_ignores_header_letters(tmp_path):
    """'e' in a header name must not disqualify the device float path."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.session import TpuSession
    path = _write_csv(tmp_path, "price,value\n1.5,2.25\n", name="hdr.csv")
    schema = T.StructType([T.StructField("price", T.DOUBLE),
                           T.StructField("value", T.DOUBLE)])
    from spark_rapids_tpu.io import csv_native as CN
    shape = CN.try_scan_for_device(path, schema, ",", True, True)
    assert shape is not None  # in scope despite 'e' in 'price'/'value'
    out = TpuSession({"spark.rapids.tpu.sql.csv.read.float.enabled": "true"}
                     ).read_csv(path, schema=schema).collect()
    assert out["price"].to_pylist() == [1.5]
    assert out["value"].to_pylist() == [2.25]


def test_input_file_name_metadata_exprs(tmp_path):
    """input_file_name()/block offsets from scan provenance on the device
    decode path (reference GpuInputFileName family)."""
    import os
    import numpy as np
    import pyarrow.parquet as pq
    from spark_rapids_tpu.session import TpuSession
    import spark_rapids_tpu.functions as F

    d = tmp_path / "t"
    d.mkdir()
    for i in range(2):
        pq.write_table(pa.table({"a": pa.array(np.arange(5) + i * 10)}),
                       str(d / f"part-{i}.parquet"), compression="NONE",
                       use_dictionary=True)
    spark = TpuSession({
        "spark.rapids.tpu.sql.parquet.deviceDecode.enabled": "true"})
    df = spark.read_parquet(str(d), files_per_partition=2).select(
        F.col("a"), F.alias(F.input_file_name(), "f"),
        F.alias(F.input_file_block_start(), "bs"),
        F.alias(F.input_file_block_length(), "bl"))
    out = df.collect()
    by_file = {}
    for r in out.to_pylist():
        by_file.setdefault(os.path.basename(r["f"]), []).append(r)
    assert set(by_file) == {"part-0.parquet", "part-1.parquet"}
    for rows in by_file.values():
        assert all(r["bs"] == 0 and r["bl"] > 0 for r in rows)


def test_input_file_name_survives_filter_and_host_path(tmp_path):
    import os
    import numpy as np
    import pyarrow.parquet as pq
    from spark_rapids_tpu.session import TpuSession
    import spark_rapids_tpu.functions as F

    p = str(tmp_path / "x.parquet")
    pq.write_table(pa.table({"a": pa.array(np.arange(6))}), p,
                   compression="NONE", use_dictionary=True)
    spark = TpuSession()
    df = (spark.read_parquet(p)
          .filter(F.col("a") > 1)
          .select(F.col("a"), F.alias(F.input_file_name(), "f")))
    out = df.collect()
    assert all(os.path.basename(v) == "x.parquet"
               for v in out["f"].to_pylist())

    # host reader path (device decode off) keeps single-file provenance too
    off = TpuSession({"spark.rapids.tpu.sql.parquet.deviceDecode.enabled":
                      "false"})
    out2 = (off.read_parquet(p).select(F.alias(F.input_file_name(), "f"))
            .collect())
    assert all(os.path.basename(v) == "x.parquet"
               for v in out2["f"].to_pylist())


def test_alluxio_path_rewrite(tmp_path):
    """Reference spark.rapids.alluxio.pathsToReplace (RapidsConf.scala:1031):
    scan paths rewrite by prefix before file resolution."""
    import pyarrow.parquet as pq
    from spark_rapids_tpu.session import TpuSession
    real = tmp_path / "mnt" / "alluxio" / "data"
    real.mkdir(parents=True)
    pq.write_table(pa.table({"x": pa.array([1, 2, 3])}),
                   str(real / "f.parquet"))
    spark = TpuSession({
        "spark.rapids.tpu.alluxio.pathsToReplace":
            f"s3://bucket->{tmp_path}/mnt/alluxio"})
    df = spark.read_parquet("s3://bucket/data")
    assert sorted(df.collect().column("x").to_pylist()) == [1, 2, 3]
