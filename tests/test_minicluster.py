"""MiniCluster: end-to-end queries across real OS processes.

Reference role: the reference executes on a Spark cluster — driver schedules,
executor JVMs exchange shuffle blocks over the transport
(RapidsShuffleInternalManagerBase.scala:200, Plugin.scala:137-211). These
tests stand up a driver + 2 executor processes and check oracle-correct
results for shuffle-requiring shapes (group-by, join, global sort) and
TPC-H q3 (VERDICT r2 'done' criterion)."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.cluster import MiniCluster
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_executors=2, platform="cpu") as c:
        yield c


@pytest.fixture(scope="module")
def spark():
    return TpuSession()


def _norm(rows):
    def n(x):
        if x is None or (isinstance(x, float) and x != x):
            return (1, 0.0)
        return (0, x)
    return sorted(tuple(n(v) for v in r) for r in rows)


def test_cluster_group_by(cluster, spark):
    rng = np.random.default_rng(3)
    n = 5000
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 97, n).astype(np.int64)),
        "v": pa.array(np.round(rng.uniform(-5, 5, n), 3)),
    })
    df = (spark.create_dataframe(tbl).repartition(4)
          .group_by(F.col("k"))
          .agg(F.sum(F.col("v")).alias("s"), F.count(F.col("v")).alias("c")))
    got = cluster.collect(df)
    exp = df.collect_host()
    assert got.num_rows == 97
    gm = {r["k"]: (r["s"], r["c"]) for r in got.to_pylist()}
    for r in exp.to_pylist():
        s, c = gm[r["k"]]
        assert c == r["c"]
        assert abs(s - r["s"]) < 1e-9 * max(1.0, abs(r["s"]))


def test_cluster_join(cluster, spark):
    rng = np.random.default_rng(4)
    left = pa.table({
        "k": pa.array(rng.integers(0, 50, 800).astype(np.int64)),
        "a": pa.array(rng.integers(0, 1000, 800).astype(np.int64)),
    })
    right = pa.table({
        "k": pa.array(rng.integers(0, 50, 300).astype(np.int64)),
        "b": pa.array(rng.integers(0, 1000, 300).astype(np.int64)),
    })
    dl = spark.create_dataframe(left).repartition(3)
    dr = spark.create_dataframe(right).repartition(2)
    df = dl.join(dr, on="k")
    got = cluster.collect(df)
    exp = df.collect_host()
    assert _norm(tuple(r.values()) for r in got.to_pylist()) == \
        _norm(tuple(r.values()) for r in exp.to_pylist())


def test_cluster_global_sort(cluster, spark):
    rng = np.random.default_rng(5)
    tbl = pa.table({"v": pa.array(rng.integers(-999, 999, 2000)
                                  .astype(np.int64))})
    df = spark.create_dataframe(tbl).repartition(4).sort(F.col("v"))
    got = cluster.collect(df)
    assert got.column("v").to_pylist() == sorted(tbl.column("v").to_pylist())


def test_cluster_tpch_q3(cluster, spark, tmp_path_factory):
    from spark_rapids_tpu.benchmarks import tpch
    import bench
    outdir = str(tmp_path_factory.mktemp("tpch_cluster"))
    paths = tpch.generate(0.01, outdir)
    dfs = tpch.load(spark, paths, files_per_partition=2)
    tb = tpch.load_np(paths)
    df = tpch.QUERIES["q3"](dfs)
    got = cluster.collect(df).to_pylist()
    exp = tpch.np_q3(tb)
    bench.CHECKS["q3"](got, exp)
