"""MiniCluster: end-to-end queries across real OS processes.

Reference role: the reference executes on a Spark cluster — driver schedules,
executor JVMs exchange shuffle blocks over the transport
(RapidsShuffleInternalManagerBase.scala:200, Plugin.scala:137-211). These
tests stand up a driver + 2 executor processes and check oracle-correct
results for shuffle-requiring shapes (group-by, join, global sort) and
TPC-H q3 (VERDICT r2 'done' criterion)."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.cluster import MiniCluster
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_executors=2, platform="cpu") as c:
        yield c


@pytest.fixture(scope="module")
def spark():
    return TpuSession()


def _norm(rows):
    def n(x):
        if x is None or (isinstance(x, float) and x != x):
            return (1, 0.0)
        return (0, x)
    return sorted(tuple(n(v) for v in r) for r in rows)


def test_cluster_group_by(cluster, spark):
    rng = np.random.default_rng(3)
    n = 5000
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 97, n).astype(np.int64)),
        "v": pa.array(np.round(rng.uniform(-5, 5, n), 3)),
    })
    df = (spark.create_dataframe(tbl).repartition(4)
          .group_by(F.col("k"))
          .agg(F.sum(F.col("v")).alias("s"), F.count(F.col("v")).alias("c")))
    got = cluster.collect(df)
    exp = df.collect_host()
    assert got.num_rows == 97
    gm = {r["k"]: (r["s"], r["c"]) for r in got.to_pylist()}
    for r in exp.to_pylist():
        s, c = gm[r["k"]]
        assert c == r["c"]
        assert abs(s - r["s"]) < 1e-9 * max(1.0, abs(r["s"]))


def test_cluster_join(cluster, spark):
    rng = np.random.default_rng(4)
    left = pa.table({
        "k": pa.array(rng.integers(0, 50, 800).astype(np.int64)),
        "a": pa.array(rng.integers(0, 1000, 800).astype(np.int64)),
    })
    right = pa.table({
        "k": pa.array(rng.integers(0, 50, 300).astype(np.int64)),
        "b": pa.array(rng.integers(0, 1000, 300).astype(np.int64)),
    })
    dl = spark.create_dataframe(left).repartition(3)
    dr = spark.create_dataframe(right).repartition(2)
    df = dl.join(dr, on="k")
    got = cluster.collect(df)
    exp = df.collect_host()
    assert _norm(tuple(r.values()) for r in got.to_pylist()) == \
        _norm(tuple(r.values()) for r in exp.to_pylist())


def test_cluster_global_sort(cluster, spark):
    rng = np.random.default_rng(5)
    tbl = pa.table({"v": pa.array(rng.integers(-999, 999, 2000)
                                  .astype(np.int64))})
    df = spark.create_dataframe(tbl).repartition(4).sort(F.col("v"))
    got = cluster.collect(df)
    assert got.column("v").to_pylist() == sorted(tbl.column("v").to_pylist())


def test_cluster_tpch_q3(cluster, spark, tmp_path_factory):
    from spark_rapids_tpu.benchmarks import tpch
    import bench
    outdir = str(tmp_path_factory.mktemp("tpch_cluster"))
    paths = tpch.generate(0.01, outdir)
    dfs = tpch.load(spark, paths, files_per_partition=2)
    tb = tpch.load_np(paths)
    df = tpch.QUERIES["q3"](dfs)
    got = cluster.collect(df).to_pylist()
    exp = tpch.np_q3(tb)
    bench.CHECKS["q3"](got, exp)


def test_cluster_union_scan_with_shuffle_parallelism(cluster, spark):
    """VERDICT r3 weak #5: a UNION mixing a scan leaf with a shuffle source
    must fan its splits across executors, not serialize as one task."""
    t = pa.table({"k": pa.array(np.arange(400) % 7, type=pa.int64()),
                  "v": pa.array(np.arange(400, dtype=np.float64))})
    scan_side = spark.create_dataframe(t, num_partitions=3)
    shuffled_side = spark.create_dataframe(t).repartition(2)
    df = scan_side.union(shuffled_side)
    cluster.task_log.clear()
    got = cluster.collect(df)
    assert got.num_rows == 800
    result_tasks = [(op, ei) for (op, ei) in cluster.task_log
                    if op == "result"]
    assert len(result_tasks) >= 5, result_tasks       # 3 leaf + 2 reduce
    assert len({ei for _, ei in result_tasks}) > 1, \
        f"result stage used one executor: {result_tasks}"


def test_cluster_executor_loss_recovers():
    """Kill one executor AFTER a map stage has parked its shuffle blocks:
    the result stage's fetch fails, the driver heals the pool and re-runs
    the lineage, and the query still returns oracle-correct rows
    (reference RapidsShuffleIterator.scala:82,153 FetchFailed → recompute)."""
    spark = TpuSession()
    rng = np.random.default_rng(11)
    t = pa.table({"k": pa.array(rng.integers(0, 9, 600), type=pa.int64()),
                  "v": pa.array(rng.random(600))})
    df = (spark.create_dataframe(t, num_partitions=4)
          .group_by(F.col("k")).agg(F.sum(F.col("v")).alias("s")))
    exp = {r["k"]: r["s"] for r in df.collect_host().to_pylist()}
    with MiniCluster(n_executors=2, platform="cpu") as cluster:
        state = {"killed": False}

        def kill_one(c):
            if not state["killed"]:
                state["killed"] = True
                c._procs[0].kill()       # dies with its shuffle blocks
                c._procs[0].join(timeout=5)

        cluster._after_stage_hook = kill_one
        got = {r["k"]: r["s"] for r in cluster.collect(df).to_pylist()}
        assert state["killed"]
        assert set(got) == set(exp)
        for k in exp:
            assert got[k] == pytest.approx(exp[k], rel=1e-9), k
        # pool healed: both executors alive again
        assert all(p.is_alive() for p in cluster._procs)
