"""Spill framework tests — mirrors the reference's RapidsBufferCatalogSuite /
RapidsDeviceMemoryStoreSuite / RapidsDiskStoreSuite (SURVEY.md §4 ring 2, runnable on
the CPU backend like ring 1)."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.runtime.memory as mem_mod

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.runtime.memory import (
    BufferCatalog, DeviceManager, SpillableColumnarBatch, TierEnum,
    ACTIVE_ON_DECK_PRIORITY, OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY,
)


def make_batch(n=100, seed=0):
    r = np.random.default_rng(seed)
    t = pa.table({
        "a": pa.array(r.integers(0, 1000, n), type=pa.int64()),
        "b": pa.array(r.normal(size=n)),
        "s": pa.array([["x", "yy", "zzz"][i % 3] for i in range(n)]),
    })
    return ColumnarBatch.from_arrow(t), t


def test_add_and_acquire_roundtrip(tmp_path):
    cat = BufferCatalog(device_budget=1 << 30, host_budget=1 << 30,
                        spill_dir=str(tmp_path))
    batch, t = make_batch()
    bid = cat.add_batch(batch)
    assert cat.get_tier(bid) == TierEnum.DEVICE
    out = cat.acquire_batch(bid)
    assert out.to_arrow().equals(t)
    cat.remove(bid)
    assert cat.num_buffers == 0
    assert cat.device_bytes == 0


def test_budget_spills_to_host_then_disk(tmp_path):
    batch, t = make_batch()
    one = batch.device_memory_size()
    # room for ~2 batches on device and ~1 on host → 3rd add pushes one to disk
    cat = BufferCatalog(device_budget=int(one * 2.5), host_budget=int(one * 1.2),
                        spill_dir=str(tmp_path))
    ids = [cat.add_batch(make_batch(seed=i)[0]) for i in range(4)]
    tiers = [cat.get_tier(i) for i in ids]
    assert tiers.count(TierEnum.DEVICE) <= 2
    assert TierEnum.HOST in tiers or TierEnum.DISK in tiers
    assert cat.device_bytes <= cat.device_budget
    assert cat.host_bytes <= cat.host_budget
    # every buffer still readable from any tier, bit-identical
    for i, bid in enumerate(ids):
        got = cat.acquire_batch(bid).to_arrow()
        assert got.equals(make_batch(seed=i)[1])
    assert cat.spilled_to_host_bytes > 0


def test_spill_priority_order(tmp_path):
    batch, _ = make_batch()
    one = batch.device_memory_size()
    cat = BufferCatalog(device_budget=one * 10, host_budget=one * 10,
                        spill_dir=str(tmp_path))
    shuffle_id = cat.add_batch(make_batch(seed=1)[0],
                               priority=OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY)
    active_id = cat.add_batch(make_batch(seed=2)[0], priority=ACTIVE_ON_DECK_PRIORITY)
    spilled = cat.synchronous_spill(int(one * 1.5))
    assert spilled > 0
    # the low-priority shuffle output spilled first; the active batch stayed
    assert cat.get_tier(shuffle_id) != TierEnum.DEVICE
    assert cat.get_tier(active_id) == TierEnum.DEVICE


def test_unspill_promotes_back(tmp_path):
    batch, t = make_batch()
    one = batch.device_memory_size()
    cat = BufferCatalog(device_budget=one * 10, host_budget=one * 10,
                        spill_dir=str(tmp_path), unspill=True)
    bid = cat.add_batch(batch)
    cat.synchronous_spill(0)
    assert cat.get_tier(bid) == TierEnum.HOST
    out = cat.acquire_batch(bid)
    assert cat.get_tier(bid) == TierEnum.DEVICE
    assert out.to_arrow().equals(t)


def test_spillable_columnar_batch_lifecycle(tmp_path):
    DeviceManager.reset()
    batch, t = make_batch()
    scb = SpillableColumnarBatch(batch)
    try:
        assert scb.num_rows == 100
        assert scb.get_batch().to_arrow().equals(t)
    finally:
        scb.close()
    with pytest.raises(mem_mod.BufferClosedError):
        scb.get_batch()


def test_spill_callback_feeds_metrics(tmp_path):
    batch, _ = make_batch()
    one = batch.device_memory_size()
    cat = BufferCatalog(device_budget=one * 10, host_budget=one * 10,
                        spill_dir=str(tmp_path))
    seen = []
    cat.add_batch(batch, spill_callback=seen.append)
    cat.synchronous_spill(0)
    assert seen and seen[0] == one


def test_oom_dump_dir_and_strict_raise(tmp_path):
    """When spill cannot reach the budget, allocator state is dumped
    (spark.rapids.tpu.memory.hbm.oomDumpDir, reference oomDumpDir) and
    strict mode (hbm.strictBudget, the default) raises a retryable
    DeviceOomError with the spillable/pinned breakdown, rolling the failed
    registration back out of the catalog."""
    from spark_rapids_tpu.runtime.memory import (ACTIVE_ON_DECK_PRIORITY,
                                                 BufferCatalog)
    from spark_rapids_tpu.runtime.retry import DeviceOomError
    cat = BufferCatalog(device_budget=1, host_budget=1 << 30,
                        oom_dump_dir=str(tmp_path))
    b, _ = make_batch(64)
    # a single unspillable-situation: add under a tiny budget; after spilling
    # everything else (nothing), the new buffer itself keeps us over budget
    with pytest.raises(DeviceOomError) as ei:
        cat.add_batch(b, ACTIVE_ON_DECK_PRIORITY)
    assert ei.value.retryable and ei.value.budget == 1
    assert "spillable" in str(ei.value)
    # rollback: the phantom registration must not stay charged
    assert cat.num_buffers == 0 and cat.device_bytes == 0
    dumps = list(tmp_path.glob("hbm-oom-*.txt"))
    assert dumps, "expected an OOM dump file"
    txt = dumps[0].read_text()
    assert "device_bytes=" in txt and "buffer_id" in txt
    # per-tier spillable-vs-pinned breakdown (postmortem satellite)
    assert "tier=DEVICE spillable_bytes=" in txt and "pinned_bytes=" in txt


def test_lenient_budget_keeps_legacy_over_budget(tmp_path):
    """strictBudget=false restores the pre-retry behavior: the catalog stays
    (knowingly) over budget instead of raising."""
    from spark_rapids_tpu.runtime.memory import BufferCatalog
    cat = BufferCatalog(device_budget=1, host_budget=1 << 30,
                        strict_budget=False, oom_dump_dir=str(tmp_path))
    b, t = make_batch(64)
    bid = cat.add_batch(b)
    assert cat.get_tier(bid) == TierEnum.DEVICE
    assert cat.device_bytes > cat.device_budget
    assert cat.acquire_batch(bid).to_arrow().equals(t)


def test_direct_spill_store_roundtrip(tmp_path):
    """GDS-analog batched aligned store (reference RapidsGdsStore +
    BatchSpiller): aligned offsets, batching into shared files, refcounted
    deletion."""
    from spark_rapids_tpu.runtime.direct_spill import ALIGN, DirectSpillStore
    st = DirectSpillStore(str(tmp_path / "d"), batch_bytes=1 << 14)
    payloads = [bytes([i]) * (100 + 1000 * i) for i in range(8)]
    handles = [st.write(p) for p in payloads]
    for h, p in zip(handles, payloads):
        assert h[1] % ALIGN == 0          # aligned offsets
        assert st.read(h) == p
    # several buffers share batch files (BatchSpiller coalescing)
    assert len({h[0] for h in handles}) < len(handles)
    for h in handles:
        st.delete(h)
    import os
    leftover = [f for f in os.listdir(tmp_path / "d")]
    assert len(leftover) <= 1             # only the open batch file may remain
    st.close()


def test_direct_spill_through_catalog(tmp_path):
    """Disk-tier spills ride the direct store when enabled; reads are
    bit-identical across tiers and removal cleans the blobs."""
    batch, t = make_batch()
    one = batch.device_memory_size()
    cat = BufferCatalog(device_budget=int(one * 1.2), host_budget=int(one * 0.5),
                        spill_dir=str(tmp_path), direct_spill=True,
                        direct_batch_bytes=1 << 16)
    ids = [cat.add_batch(make_batch(seed=i)[0]) for i in range(4)]
    tiers = [cat.get_tier(i) for i in ids]
    assert TierEnum.DISK in tiers
    for i, bid in enumerate(ids):
        assert cat.acquire_batch(bid).to_arrow().equals(make_batch(seed=i)[1])
    for bid in ids:
        cat.remove(bid)
    assert cat.num_buffers == 0


def test_direct_spill_with_unspill(tmp_path):
    """unspill + direct store: reading a direct-spilled buffer promotes it
    back to the device tier and releases the blob refcount."""
    batch, t = make_batch()
    one = batch.device_memory_size()
    cat = BufferCatalog(device_budget=int(one * 1.2), host_budget=int(one * 0.5),
                        spill_dir=str(tmp_path), direct_spill=True,
                        unspill=True, direct_batch_bytes=1 << 16)
    ids = [cat.add_batch(make_batch(seed=i)[0]) for i in range(4)]
    disk = [bid for bid in ids if cat.get_tier(bid) == TierEnum.DISK]
    assert disk
    bid = disk[0]
    got = cat.acquire_batch(bid)
    assert cat.get_tier(bid) == TierEnum.DEVICE
    assert got.to_arrow().equals(make_batch(seed=ids.index(bid))[1])
    for b in ids:
        cat.remove(b)


def test_sort_spills_accumulated_inputs(monkeypatch, tmp_path):
    """SortExec holds its input batches in the spill store while
    accumulating (reference GpuSortExec + RequireSingleBatch): a tiny HBM
    budget forces spills mid-sort and the order is still correct."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    from spark_rapids_tpu.exec.sort import SortExec
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.ops.sorting import SortOrder
    from spark_rapids_tpu.runtime.memory import BufferCatalog, DeviceManager

    rng = np.random.default_rng(2)
    vals = rng.integers(0, 10000, 4000)
    tables = [pa.table({"v": pa.array(vals[i::4])}) for i in range(4)]
    scan = ArrowScanExec(tables, batch_rows=250)  # many small batches
    # one batch ≈ 256-capacity int64 + validity ≈ 2.3KB; budget holds one
    small = BufferCatalog(device_budget=3000, host_budget=20000,
                          spill_dir=str(tmp_path))
    monkeypatch.setattr(DeviceManager.get(), "catalog", small)
    ex = SortExec([col("v")], [SortOrder()], scan)
    out = []
    for split in range(scan.num_partitions):
        for b in ex.execute_partition(split):
            out.extend(b.to_arrow()["v"].to_pylist())
    # per-partition sort: each partition independently ordered
    assert small.spilled_to_host_bytes > 0   # pressure actually spilled
    at = 0
    for t in tables:
        n = t.num_rows
        assert out[at:at + n] == sorted(t["v"].to_pylist())
        at += n
