"""Whole-stage fusion: bit-identity fused vs unfused (the
`spark.rapids.tpu.sql.stageFusion.enabled` A/B), the HAVING-fusion and
prestage-composition plan rewrites, the fused-stage explain() read-out, and
the executable-budget accounting for multi-shape stage kernels."""

import jax.numpy as jnp
import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.session import TpuSession

SF = 0.01
FUSION_KEY = "spark.rapids.tpu.sql.stageFusion.enabled"


@pytest.fixture(scope="module")
def paths():
    return tpch.generate(SF, f"/tmp/tpch_sf{SF}")


def _collect(paths, query, fusion: bool):
    spark = TpuSession({FUSION_KEY: fusion})
    dfs = tpch.load(spark, paths)
    return tpch.QUERIES[query](dfs).collect().to_pylist()


# -- bit-identity across the ladder ------------------------------------------

@pytest.mark.parametrize("query", ["q1", "q3", "q5", "q18"])
def test_ladder_bit_identical_fused_vs_unfused(paths, query):
    fused = _collect(paths, query, True)
    unfused = _collect(paths, query, False)
    # exact equality, floats included: fusion re-orders no arithmetic — the
    # fused program evaluates the same expression trees over the same rows
    assert fused == unfused


def _edge_table():
    # dictionary-encoded key column + null-heavy value column: the layouts
    # the fused paths special-case (dict digests in kernel signatures,
    # validity masking through compaction and the presorted group-by)
    n = 4000
    keys = pa.array([f"k{i % 7}" if i % 11 else None
                     for i in range(n)]).dictionary_encode()
    vals = pa.array([float(i % 13) if i % 3 else None for i in range(n)],
                    pa.float64())
    ones = pa.array([1.0] * n, pa.float64())
    return pa.table({"k": keys, "v": vals, "w": ones})


def _edge_query(spark):
    c = F.col
    df = spark.create_dataframe(_edge_table())
    return (df.filter(c("w") > F.lit(0.0))
            .select(c("k"), (c("v") + c("w")).alias("x"))
            .group_by(c("k"))
            .agg(F.sum(c("x")).alias("sx"), F.count(c("x")).alias("cx"))
            .filter(c("sx") > F.lit(100.0))
            .sort(c("k")))


def test_edge_batches_bit_identical_fused_vs_unfused():
    got = {}
    for fusion in (True, False):
        spark = TpuSession({FUSION_KEY: fusion})
        got[fusion] = _edge_query(spark).collect().to_pylist()
    assert got[True] == got[False]
    assert len(got[True]) > 0


# -- plan rewrites -----------------------------------------------------------

def _q18_agg_plan(paths, fusion: bool):
    from spark_rapids_tpu.plan.overrides import TpuOverrides
    c = F.col
    spark = TpuSession({FUSION_KEY: fusion})
    dfs = tpch.load(spark, paths)
    df = (dfs["lineitem"].group_by(c("l_orderkey"))
          .agg(F.sum(c("l_quantity")).alias("sum_qty"))
          .filter(c("sum_qty") > F.lit(300.0)))
    return TpuOverrides(spark.conf).apply(df._plan)


def test_having_fuses_into_aggregate(paths):
    from spark_rapids_tpu.exec.aggregate import HashAggregateExec
    from spark_rapids_tpu.exec.basic import FilterExec

    def find(node, cls):
        out = [node] if isinstance(node, cls) else []
        for ch in node.children:
            out += find(ch, cls)
        return out

    fused = _q18_agg_plan(paths, True)
    assert not find(fused, FilterExec)
    final = [a for a in find(fused, HashAggregateExec)
             if a.postfilter is not None]
    assert len(final) == 1

    unfused = _q18_agg_plan(paths, False)
    assert find(unfused, FilterExec)
    assert all(a.postfilter is None
               for a in find(unfused, HashAggregateExec))


def test_compose_prestage_folds_filter_project_stack():
    from spark_rapids_tpu.exec.basic import FilterExec, ProjectExec
    from spark_rapids_tpu.plan.stages import compose_prestage
    c = F.col
    spark = TpuSession()
    df = (spark.create_dataframe(_edge_table())
          .filter(c("w") > F.lit(0.0))
          .select(c("k"), (c("v") + c("w")).alias("x")))
    from spark_rapids_tpu.plan.overrides import TpuOverrides
    top = TpuOverrides(spark.conf).apply(df._plan)
    # walk down to the Project(Filter(scan)) stack the frame built
    while not isinstance(top, (ProjectExec, FilterExec)):
        top = top.children[0]
    cond, terms, base = compose_prestage(top)
    assert cond is not None and terms is not None
    assert not isinstance(base, (ProjectExec, FilterExec))


# -- broadcast-join probe chains ----------------------------------------------

def _find(node, name):
    out = [node] if type(node).__name__ == name else []
    for ch in node.children:
        out += _find(ch, name)
    return out


def test_probe_chain_forms_on_q18_and_q5(paths):
    from spark_rapids_tpu.plan.overrides import TpuOverrides
    for query in ("q18", "q5"):
        spark = TpuSession({FUSION_KEY: True})
        dfs = tpch.load(spark, paths)
        root = TpuOverrides(spark.conf).apply(tpch.QUERIES[query](dfs)._plan)
        chains = _find(root, "BroadcastHashJoinChainExec")
        assert len(chains) == 1, query
        assert len(chains[0].hops) == 2
        # the absorbed joins left the tree; their exchanges stayed
        assert not _find(root, "BroadcastHashJoinExec") or query == "q5"
        spark2 = TpuSession({FUSION_KEY: False})
        dfs2 = tpch.load(spark2, paths)
        root2 = TpuOverrides(spark2.conf).apply(tpch.QUERIES[query](dfs2)._plan)
        assert not _find(root2, "BroadcastHashJoinChainExec")


def _chain_pair_query(spark, dup_builds: bool):
    """Two stacked inner int-key broadcast joins; with `dup_builds` the
    middle build has duplicate keys, so the chain degrades to the
    sequential per-hop fallback at run time (probe mode 'two')."""
    c = F.col
    n = 5000
    stream = spark.create_dataframe(pa.table({
        "k": pa.array([i % 400 for i in range(n)], pa.int64()),
        "v": pa.array([float(i % 17) for i in range(n)], pa.float64())}))
    reps = 2 if dup_builds else 1
    b1 = spark.create_dataframe(pa.table({
        "k": pa.array([i for i in range(300) for _ in range(reps)],
                      pa.int64()),
        "j": pa.array([i * 2 for i in range(300) for _ in range(reps)],
                      pa.int64())}))
    b2 = spark.create_dataframe(pa.table({
        "j": pa.array(list(range(0, 600, 3)), pa.int64()),
        "w": pa.array([float(j) for j in range(0, 600, 3)], pa.float64())}))
    return (stream.join(b1, on="k").join(b2, on="j")
            .select(c("k"), c("v"), c("j"), c("w")))


@pytest.mark.parametrize("dup_builds", [False, True])
def test_chain_bit_identical_fused_vs_unfused(dup_builds):
    got = {}
    for fusion in (True, False):
        spark = TpuSession({FUSION_KEY: fusion})
        rows = _chain_pair_query(spark, dup_builds).collect().to_pylist()
        got[fusion] = sorted(map(tuple, (r.values() for r in rows)))
    assert got[True] == got[False]
    assert len(got[True]) > 0


def test_chain_single_dispatch_per_steady_state_batch(paths):
    from spark_rapids_tpu.runtime import stats as STATS
    spark = TpuSession({FUSION_KEY: True})
    dfs = tpch.load(spark, paths, files_per_partition=4)
    df = tpch.QUERIES["q5"](dfs)
    df.collect()          # warm: traces + capacity predictions settle
    df.collect()
    tbl = STATS.node_table(df._last_collector)
    chain = next(e for e in tbl if e["name"] == "BroadcastHashJoinChainExec")
    # per-hop one-off build preps aside, the whole 2-hop probe chain costs
    # ~1 dispatch per stream batch (vs probe+emit+project per hop unfused)
    assert chain["batches"] >= 4
    assert chain["dispatches"] <= 6 + 2 * chain["batches"]


# -- explain(fused=True) read-out --------------------------------------------

def test_explain_fused_names_stages_and_dispatches(paths):
    spark = TpuSession({FUSION_KEY: True})
    dfs = tpch.load(spark, paths)
    df = tpch.QUERIES["q18"](dfs)
    pre = df.explain(fused=True)          # before any action: tree only
    assert "*(" in pre and "== Fused stages ==" in pre
    df.collect()
    post = df.explain(fused=True)
    assert "*(" in post
    assert "HashAggregateExec" in post
    assert "Filter[HAVING]" in post       # the q18 HAVING hoist, named
    assert "dispatches=" in post          # per-member dispatch counts


# -- executable-budget accounting (multi-shape stage kernels) ----------------

def test_cache_size_counts_every_shape_signature():
    from spark_rapids_tpu.runtime import fuse
    k = fuse.get_kernel(("test-multi-shape-kernel",), "t",
                        lambda: (lambda c, n: c + n))
    for cap in (8, 16, 32):
        k(jnp.zeros(cap), jnp.asarray(1, jnp.int32))
    assert k.cache_size() >= 3


def test_sweep_budgets_executables_not_kernels(monkeypatch):
    from spark_rapids_tpu.runtime import fuse
    fuse.clear_kernels()
    # ONE kernel holding many shape signatures must count against the
    # executable budget as many, so the sweep evicts it
    k = fuse.get_kernel(("test-sweep-victim",), "t",
                        lambda: (lambda c, n: c * n))
    for cap in (8, 16, 32, 64, 128, 256):
        k(jnp.zeros(cap), jnp.asarray(2, jnp.int32))
    assert k.cache_size() >= 6
    monkeypatch.setattr(fuse, "_MAX_EXECUTABLES", 4)
    fuse._sweep_executables()
    with fuse._lock:
        assert ("test-sweep-victim",) not in fuse._kernels


def test_trace_driven_sweep_triggers_without_inserts(monkeypatch):
    from spark_rapids_tpu.runtime import fuse
    fuse.reset_metrics()
    monkeypatch.setattr(fuse, "_SWEEP_EVERY_TRACES", 1)
    k = fuse.get_kernel(("test-trace-sweep",), "t",
                        lambda: (lambda c, n: c - n))
    k(jnp.zeros(8), jnp.asarray(1, jnp.int32))
    k(jnp.zeros(16), jnp.asarray(1, jnp.int32))  # new shape -> trace -> sweep
    assert fuse._last_sweep_traces >= 1
    fuse.reset_metrics()
    assert fuse._last_sweep_traces == 0
