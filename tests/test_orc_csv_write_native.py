"""Native (device-encode) ORC and CSV writer tests — VERDICT r4 next #3.

ORC files round-trip through BOTH pyarrow.orc (independent reader — the
RLEv2/protobuf framing must be spec-exact) and the engine's own device scan
path (io/orc_native — the a1d7826-style cross-stack check). CSV round-trips
through the engine's reader and python's csv module. Reference suite analog:
OrcWriterSuite.scala / CsvScanSuite roles."""

import csv
import datetime
import decimal
import glob
import io
import os

import numpy as np
import pyarrow as pa
import pyarrow.orc as pa_orc
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.io import orc_native, orc_write_native, csv_write_native

UTC = datetime.timezone.utc


@pytest.fixture
def spark():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession()


@pytest.fixture
def typed_table():
    return pa.table({
        "i32": pa.array([1, None, 3, -4, 5], pa.int32()),
        "i64": pa.array([10**12, 2, None, -2**40, 5], pa.int64()),
        "f32": pa.array([1.0, 2.0, 3.0, None, 5.0], pa.float32()),
        "f64": pa.array([1.5, None, 3.25, -0.5, 2.0]),
        "s": pa.array(["apple", "banana", None, "apple", "cherry"]),
        "b": pa.array([True, False, None, True, False]),
        "dt": pa.array([datetime.date(2020, 1, 1),
                        datetime.date(1999, 12, 31), None,
                        datetime.date(2026, 7, 31),
                        datetime.date(1969, 7, 20)]),
        "ts": pa.array([datetime.datetime(2020, 1, 1, 12, 30, 15, 123456),
                        # pre-2015: negative seconds vs the ORC epoch
                        datetime.datetime(2014, 12, 31, 23, 59, 59, 999999),
                        None,
                        datetime.datetime(2015, 1, 1),
                        datetime.datetime(1969, 7, 20, 20, 17)],
                       pa.timestamp("us")),
        "dec": pa.array([decimal.Decimal("1.23"), decimal.Decimal("-45.60"),
                         None, decimal.Decimal("0.01"),
                         decimal.Decimal("99999.99")], pa.decimal128(7, 2)),
    })


def _naive(rows):
    return [v.replace(tzinfo=None) if isinstance(v, datetime.datetime)
            else v for v in rows]


def test_orc_all_types_pyarrow_roundtrip(tmp_path, typed_table):
    b = ColumnarBatch.from_arrow(typed_table)
    schema = T.StructType.from_arrow(typed_table.schema)
    p = str(tmp_path / "t.orc")
    orc_write_native.write_batch_file(p, b, schema)
    back = pa_orc.read_table(p)
    for name in typed_table.column_names:
        assert back.column(name).to_pylist() == \
            typed_table.column(name).to_pylist(), name


def test_orc_cross_stack_device_read(tmp_path, typed_table):
    """Native-writer stripes through the engine's device ORC decoder."""
    b = ColumnarBatch.from_arrow(typed_table)
    schema = T.StructType.from_arrow(typed_table.schema)
    p = str(tmp_path / "t.orc")
    orc_write_native.write_batch_file(p, b, schema)
    meta = orc_native.read_meta(p)
    got = orc_native.read_stripe_device(p, meta, 0, schema).to_arrow()
    for name in typed_table.column_names:
        # engine timestamps are UTC-aware (UTC-only engine)
        assert _naive(got.column(name).to_pylist()) == \
            typed_table.column(name).to_pylist(), name


def test_orc_multi_stripe(tmp_path):
    schema = T.StructType([T.StructField("x", T.LONG, True)])
    f = orc_write_native.NativeOrcFile(str(tmp_path / "m.orc"), schema)
    rng = np.random.default_rng(3)
    allv = []
    for _ in range(3):
        vals = rng.integers(-10**9, 10**9, 700)   # >512: several RLEv2 runs
        allv.extend(vals.tolist())
        f.append_batch(ColumnarBatch.from_arrow(
            pa.table({"x": pa.array(vals, pa.int64())})))
    f.close()
    back = pa_orc.read_table(str(tmp_path / "m.orc"))
    assert back.column("x").to_pylist() == allv
    meta = orc_native.read_meta(str(tmp_path / "m.orc"))
    assert len(meta.stripes) == 3


def test_orc_byte_rle_and_rlev2_edges():
    """Encoder outputs decode with the engine reader's own decoders."""
    # byte-RLE: long run + literals + short run
    data = bytes([7] * 200 + [1, 2, 3, 4] + [9] * 3)
    enc = orc_write_native.byte_rle(data)
    bits = np.frombuffer(data, np.uint8)
    dec = orc_native.decode_boolean_rle(enc, len(data) * 8)
    packed = np.packbits(dec.astype(np.uint8)).tobytes()
    assert packed == data
    # RLEv2 direct: width-64 values and a >512 chunk
    vals = np.array([0, 1, -1, 2**62, -2**62] * 200, np.int64)
    enc = orc_write_native.rlev2_direct(vals, signed=True)
    got = orc_native.rlev2_decode_host(enc, 0, len(enc), len(vals),
                                       signed=True)
    assert np.array_equal(np.asarray(got, np.int64), vals)


def test_session_write_orc_native_and_arrow_opt_out(spark, tmp_path):
    t = pa.table({"k": pa.array([2, 1, None], pa.int64()),
                  "s": ["b", "a", None]})
    df = spark.create_dataframe(t)
    p = str(tmp_path / "o")
    df.write_orc(p)
    files = glob.glob(p + "/*.orc")
    assert files and pa_orc.read_table(files[0]).num_rows == 3
    back = spark.read_orc(p).collect().sort_by([("k", "ascending")])
    assert back.column("s").to_pylist() == ["a", "b", None]
    # config opt-out routes through arrow
    from spark_rapids_tpu.session import TpuSession
    s2 = TpuSession({"spark.rapids.tpu.sql.format.orc.writer.type": "ARROW"})
    p2 = str(tmp_path / "o2")
    s2.create_dataframe(t).write_orc(p2)
    assert spark.read_orc(p2).collect().num_rows == 3


def test_orc_unsupported_schema_falls_back(spark, tmp_path):
    t = pa.table({"k": pa.array([1, 2], pa.int64()),
                  "a": pa.array([[1, 2], [3]], pa.list_(pa.int64()))})
    p = str(tmp_path / "lists")
    spark.create_dataframe(t).write_orc(p)       # arrow fallback, no error
    back = pa_orc.read_table(glob.glob(p + "/*.orc")[0])
    assert back.column("a").to_pylist() == [[1, 2], [3]]


def test_csv_native_quoting_and_nulls(spark, tmp_path):
    t = pa.table({
        "k": pa.array([1, 2, None, 4], pa.int64()),
        "s": pa.array(["plain", "with,comma", 'with"quote', "x\ny"]),
        "v": pa.array([1.5, None, 0.1, -2.25]),
        "b": pa.array([True, None, False, True]),
    })
    df = spark.create_dataframe(t)
    p = str(tmp_path / "c")
    df.write_csv(p)
    text = open(glob.glob(p + "/*.csv")[0]).read()
    rows = list(csv.reader(io.StringIO(text)))   # independent RFC-4180 parse
    assert rows[0] == ["k", "s", "v", "b"]
    body = {r[0]: r for r in rows[1:]}
    assert body["2"][1] == "with,comma"
    assert body[""][1] == 'with"quote'
    assert body["4"][1] == "x\ny"
    assert body["2"][2] == "" and body[""][3] == "false"
    back = spark.read_csv(p, schema=df.schema).collect().sort_by(
        [("v", "ascending")])
    assert back.column("s").to_pylist() == \
        t.sort_by([("v", "ascending")]).column("s").to_pylist()


def test_csv_native_typed_values(spark, tmp_path):
    t = pa.table({
        "dt": pa.array([datetime.date(2020, 1, 2), None]),
        "ts": pa.array([datetime.datetime(2020, 1, 2, 3, 4, 5, 600000),
                        None], pa.timestamp("us")),
        "dec": pa.array([decimal.Decimal("-4.05"), None],
                        pa.decimal128(7, 2)),
    })
    p = str(tmp_path / "cv")
    spark.create_dataframe(t).write_csv(p)
    text = open(glob.glob(p + "/*.csv")[0]).read().splitlines()
    assert text[1].startswith("2020-01-02,2020-01-02T03:04:05.600000,-4.05")
    assert text[2] == ",,"


def test_csv_stats_and_commit(spark, tmp_path):
    t = pa.table({"k": pa.array(range(100), pa.int64())})
    p = str(tmp_path / "cs")
    stats = spark.create_dataframe(t, num_partitions=3).write_csv(p)
    assert stats.num_rows == 100 and stats.num_files >= 3
    assert os.path.exists(os.path.join(p, "_SUCCESS"))
    back = spark.read_csv(p, schema=T.StructType(
        [T.StructField("k", T.LONG, True)])).collect()
    assert sorted(back.column("k").to_pylist()) == list(range(100))


@pytest.mark.parametrize("codec", ["zlib", "snappy", "none"])
def test_orc_compressed_roundtrip(tmp_path, typed_table, codec):
    """Chunked stream/footer compression readable by pyarrow AND the
    engine's device reader (review catch: native default silently dropped
    the arrow path's compression)."""
    b = ColumnarBatch.from_arrow(typed_table)
    schema = T.StructType.from_arrow(typed_table.schema)
    p = str(tmp_path / f"c_{codec}.orc")
    orc_write_native.write_batch_file(p, b, schema, compression=codec)
    back = pa_orc.read_table(p)
    for name in typed_table.column_names:
        assert back.column(name).to_pylist() == \
            typed_table.column(name).to_pylist(), name
    meta = orc_native.read_meta(p)
    got = orc_native.read_stripe_device(p, meta, 0, schema).to_arrow()
    assert _naive(got.column("ts").to_pylist()) == \
        typed_table.column("ts").to_pylist()


def test_orc_zlib_actually_compresses(tmp_path):
    t = pa.table({"s": pa.array(["constant string"] * 5000)})
    b = ColumnarBatch.from_arrow(t)
    schema = T.StructType.from_arrow(t.schema)
    pz = str(tmp_path / "z.orc")
    pn = str(tmp_path / "n.orc")
    orc_write_native.write_batch_file(pz, b, schema, compression="zlib")
    orc_write_native.write_batch_file(pn, b, schema, compression="none")
    assert os.path.getsize(pz) < os.path.getsize(pn)
