"""Fleet observability plane tests (PR 18): cross-replica query journeys
(one journey id spanning submit_with_retry's replica rotation, terminal
``query.journey`` records per attempt, profiler.py journey's merged
failover timeline), the fleet-wide stats rollup (aggregate == sum of
per-replica counters, dead replicas reported UNREACHABLE in place), the
black-box flight recorder (bounded ring fed by eventlog.emit, dump on
stuck-query detection, the dump path riding the victim's lease record
into the survivor's ``fleet.adopt``), SLO accounting, and the
trace-id-stable-across-failover regression."""

import json
import os
import pathlib
import signal
import subprocess
import sys
import threading
import time

import pyarrow as pa
import pytest

from spark_rapids_tpu.runtime import blackbox, eventlog, faults
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.endpoint import (EndpointClient, QueryEndpoint,
                                               merge_fleet_stats,
                                               parse_stats_text,
                                               render_fleet_stats)
from spark_rapids_tpu.runtime.fleet import FleetDirectory
from spark_rapids_tpu.session import TpuSession

SQL = "select k % 5 kk, sum(v) s, count(*) c from t group by kk order by kk"

REPO = pathlib.Path(__file__).resolve().parent.parent


def _session(extra=None):
    spark = TpuSession(dict(extra or {}))
    spark.create_or_replace_temp_view(
        "t", spark.create_dataframe(
            pa.table({"k": list(range(200)),
                      "v": [float(i) / 3 for i in range(200)]}),
            num_partitions=4))
    return spark


def _wait(pred, timeout_s=20.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def _read_events(log_dir):
    out = []
    for f in sorted(pathlib.Path(log_dir).glob("*.jsonl")):
        for ln in f.read_text().splitlines():
            try:
                out.append(json.loads(ln))
            except ValueError:
                pass
    return out


def _journeys(records, jid=None):
    return [r for r in records if r.get("event") == "query.journey"
            and (jid is None or r.get("journey") == jid)]


def _profiler(*args):
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "profiler.py"), *args],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    return r.returncode, r.stdout, r.stderr


@pytest.fixture(autouse=True)
def _clean_observability_plane():
    yield
    faults.reset()
    eventlog.shutdown()
    # the recorder is process-global: restore the default ring and drop the
    # dump directory so one test's config cannot leak into the next
    blackbox.reset()
    blackbox.configure(max_events=blackbox.DEFAULT_MAX_EVENTS)
    blackbox._dir = None


# -- query journeys ------------------------------------------------------------

def test_journey_served_then_cached_records(tmp_path):
    spark = _session({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.endpoint.resultCache.enabled": True})
    ep = QueryEndpoint(spark)
    cli = EndpointClient(("127.0.0.1", ep.port), timeout_s=30)
    try:
        first = cli.submit(SQL).to_pylist()
        j1 = cli.last_journey
        assert cli.submit(SQL).to_pylist() == first
        j2 = cli.last_journey
        assert j1 != j2 and j1.startswith("j-")
        # the summary frame echoes the journey plane
        s = cli.last_summary
        assert s["journey"] == j2 and s["attempt"] == 1
        assert s["replica"] == f"127.0.0.1:{ep.port}"
    finally:
        ep.shutdown(grace_s=5)
    eventlog.shutdown()

    recs = _read_events(tmp_path)
    (served,) = _journeys(recs, j1)
    assert served["outcome"] == "served" and served["attempt"] == 1
    assert served["replica"] == f"127.0.0.1:{ep.port}"
    assert served["wall_s"] >= 0 and isinstance(served["traces"], int)
    (cached,) = _journeys(recs, j2)
    assert cached["outcome"] == "cached" and cached["traces"] == 0
    assert cached["query"] == served["query"]   # replays the recorded run

    logs = sorted(str(f) for f in tmp_path.glob("*.jsonl"))
    rc, out, err = _profiler("journey", *logs)
    assert rc == 0, err
    assert "outcome served" in out and "outcome cached" in out


def test_journey_spans_failover_and_trace_rides_along(tmp_path):
    """The tentpole timeline: attempt 1 dies by replica timeout on a wedged
    replica, attempt 2 serves on the next one — ONE journey id, and (the
    retry-trace regression) ONE trace id equal to it across both attempts."""
    spark = _session({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path / "log"),
        "spark.rapids.tpu.fleet.dir": str(tmp_path / "fleet"),
        "spark.rapids.tpu.fleet.heartbeat.intervalSeconds": 0.2})
    ep_bad = QueryEndpoint(spark)
    ep_good = QueryEndpoint(spark)
    cli = EndpointClient([("127.0.0.1", ep_bad.port),
                          ("127.0.0.1", ep_good.port)], timeout_s=60)
    retries = []
    try:
        ep_bad.request_timeout = 0.3
        faults.configure("slow:agg.update:12", seed=1)
        rows = cli.submit_with_retry(
            SQL, on_retry=lambda a, d: (retries.append(a), faults.reset()),
        ).to_pylist()
        assert rows == spark.sql(SQL).collect().to_pylist()
        assert retries == [1]
        jid = cli.last_journey
        # the trace id defaults to the journey id and SURVIVES the retry:
        # the serving attempt's summary carries it, so both attempts' spans
        # share one distributed trace
        assert cli.last_summary["trace"] == jid
        assert cli.last_summary["attempt"] == 2
        bad_rid, good_rid = (ep_bad.fleet.replica_id,
                             ep_good.fleet.replica_id)
    finally:
        faults.reset()
        ep_bad.request_timeout = 0.0
        ep_bad.shutdown(grace_s=5)
        ep_good.shutdown(grace_s=5)
    eventlog.shutdown()

    recs = _read_events(tmp_path / "log")
    jrecs = sorted(_journeys(recs, jid), key=lambda r: r["attempt"])
    assert [r["attempt"] for r in jrecs] == [1, 2]
    assert jrecs[0]["outcome"] == "replica_timeout"
    assert jrecs[0]["replica"] == bad_rid
    assert jrecs[1]["outcome"] == "served"
    assert jrecs[1]["replica"] == good_rid

    logs = sorted(str(f) for f in (tmp_path / "log").glob("*.jsonl"))
    rc, out, err = _profiler("journey", *logs, "--journey", jid, "--json")
    assert rc == 0, err
    (jn,) = json.loads(out)["journeys"]
    assert jn["failovers"] == 1 and jn["outcome"] == "served"
    assert jn["attempts"][1]["failover_from"] == bad_rid
    assert len(jn["replicas"]) == 2


def test_explicit_trace_id_is_preserved_across_retry():
    spark = _session()
    ep = QueryEndpoint(spark)
    cli = EndpointClient(("127.0.0.1", ep.port), timeout_s=30)
    try:
        cli.submit_with_retry(SQL, trace="tr-explicit")
        assert cli.last_summary["trace"] == "tr-explicit"
        assert cli.last_summary["journey"] == cli.last_journey
    finally:
        ep.shutdown(grace_s=5)


# -- SLO layer -----------------------------------------------------------------

def test_slo_breach_accounting_and_stats(tmp_path):
    spark = _session({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.endpoint.slo.latencyTargetSeconds": 1e-4})
    ep = QueryEndpoint(spark)
    cli = EndpointClient(("127.0.0.1", ep.port), timeout_s=30)
    try:
        cli.submit(SQL)
        # the terminal journey record lands just after the summary frame
        assert _wait(lambda: ep.slo.snapshot()["served"] == 1)
        snap = ep.slo.snapshot()
        assert snap["breaches"] == 1
        assert snap["availability"] == 1.0   # slow, but it DID serve
        text = cli.stats()
        assert 'srt_slo_latency_target_seconds 0.0001' in text
        assert 'srt_slo_total{event="breaches"} 1' in text
        health = ep._fleet_health()
        assert health["slo"]["breaches"] == 1
    finally:
        ep.shutdown(grace_s=5)
    eventlog.shutdown()
    breaches = [r for r in _read_events(tmp_path)
                if r.get("event") == "slo.breach"]
    assert breaches and breaches[0]["journey"] == cli.last_journey
    assert breaches[0]["wall_s"] > breaches[0]["target_s"]


# -- fleet stats rollup --------------------------------------------------------

def test_fleet_stats_aggregate_equals_per_replica_sum():
    spark1, spark2 = _session(), _session()
    ep1, ep2 = QueryEndpoint(spark1), QueryEndpoint(spark2)
    try:
        EndpointClient(("127.0.0.1", ep1.port), timeout_s=30).submit(SQL)
        EndpointClient(("127.0.0.1", ep2.port), timeout_s=30).submit(SQL)
        # a dead address rides in the list: reported, never hides the rest
        cli = EndpointClient([("127.0.0.1", ep1.port),
                              ("127.0.0.1", ep2.port),
                              ("127.0.0.1", 1)], timeout_s=10)
        fs = cli.fleet_stats()
        assert fs["live"] == 2 and fs["total"] == 3
        live = [r for r in fs["replicas"].values() if r["ok"]]
        assert len(live) == 2
        dead = fs["replicas"]["127.0.0.1:1"]
        assert not dead["ok"] and dead["error"]
        for series, total in fs["aggregate"]["counters"].items():
            assert total == pytest.approx(
                sum(r["counters"].get(series, 0.0) for r in live)), series
        # a counter that definitely moved shows up in the aggregate (both
        # endpoints share this process's metrics registry, so assert the
        # sum invariant rather than an absolute count)
        admitted = "srt_queries_admitted_total"
        per_rep = [r["counters"][admitted] for r in live]
        assert fs["aggregate"]["counters"][admitted] == sum(per_rep) >= 2.0
        text = render_fleet_stats(fs)
        assert "UNREACHABLE" in text
        assert "fleet aggregate (2/3 replicas)" in text
        assert admitted in text
    finally:
        ep1.shutdown(grace_s=5)
        ep2.shutdown(grace_s=5)


def test_parse_stats_text_counters_and_gauges():
    text = ("# HELP srt_x things\n"
            "# TYPE srt_x counter\n"
            'srt_x{k="a"} 3\n'
            'srt_x{k="b"} 4.5\n'
            "# TYPE srt_g gauge\n"
            "srt_g 7\n"
            "# TYPE srt_h histogram\n"
            'srt_h_bucket{le="1"} 9\n')
    parsed = parse_stats_text(text)
    assert parsed["counters"] == {'srt_x{k="a"}': 3.0, 'srt_x{k="b"}': 4.5}
    assert parsed["gauges"] == {"srt_g": 7.0}
    merged = merge_fleet_stats({"a:1": text, "a:2": text,
                                "a:3": OSError("down")})
    assert merged["live"] == 2 and merged["total"] == 3
    assert merged["aggregate"]["counters"]['srt_x{k="a"}'] == 6.0


def test_tpu_client_stats_fans_out_and_fleet_stats_cli(tmp_path):
    spark = _session()
    ep = QueryEndpoint(spark)
    try:
        EndpointClient(("127.0.0.1", ep.port), timeout_s=30).submit(SQL)
        from tools import tpu_client
        addresses = f"127.0.0.1:{ep.port},127.0.0.1:1"
        # stats: one live + one dead replica -> rc 0, both sections printed
        assert tpu_client.main(["--addresses", addresses, "stats"]) == 0
        assert tpu_client.main(["--addresses", addresses,
                                "fleet-stats"]) == 0
        # no replica reachable -> rc 2 for both modes
        assert tpu_client.main(["--addresses", "127.0.0.1:1", "stats"]) == 2
        assert tpu_client.main(["--addresses", "127.0.0.1:1",
                                "fleet-stats"]) == 2
    finally:
        ep.shutdown(grace_s=5)


# -- black-box flight recorder -------------------------------------------------

def test_blackbox_ring_is_bounded_and_default_on(tmp_path):
    assert blackbox.enabled()   # default on, no configuration needed
    eventlog.configure(str(tmp_path))
    blackbox.configure(max_events=4, directory=str(tmp_path))
    blackbox.reset()
    for i in range(10):
        eventlog.emit("endpoint.start", query=None, seq=i)
    assert blackbox.ring_len() == 4   # bounded: only the most recent kept
    blackbox.set_inflight_provider(
        lambda: [{"query": "q-1", "journey": "j-t", "sql": SQL}])
    path = blackbox.dump("test_reason")
    assert path == str(tmp_path / f"blackbox-{os.getpid()}.json")
    bb = json.loads(pathlib.Path(path).read_text())
    assert bb["reason"] == "test_reason" and bb["pid"] == os.getpid()
    assert [e["seq"] for e in bb["events"]] == [6, 7, 8, 9]
    assert bb["inflight"][0]["journey"] == "j-t"
    # per-reason throttle: an immediate second dump is suppressed
    assert blackbox.dump("test_reason") is None
    assert blackbox.dump("other_reason") is not None
    # the dump announces itself in the event log
    eventlog.shutdown()
    dumps = [r for r in _read_events(tmp_path)
             if r.get("event") == "blackbox.dump"]
    assert dumps and dumps[0]["reason"] == "test_reason"
    assert dumps[0]["inflight"] == 1


def test_blackbox_disabled_and_unconfigured_are_noops(tmp_path):
    blackbox.configure(max_events=0)
    assert not blackbox.enabled() and blackbox.ring_len() == 0
    eventlog.configure(str(tmp_path))
    eventlog.emit("endpoint.start", query=None)
    assert blackbox.ring_len() == 0
    assert blackbox.dump("whatever") is None   # no ring -> no dump
    blackbox.configure(max_events=8)           # re-enable, but no directory
    blackbox._dir = None
    eventlog.emit("endpoint.start", query=None)
    assert blackbox.ring_len() == 1
    assert blackbox.dump_path() is None
    assert blackbox.dump("whatever") is None   # no directory -> no dump


def test_blackbox_overhead_contract_without_eventlog():
    """eventlog.emit is the ring's only feeder: with no event log configured
    emit() returns before building a record, so the recorder's steady-state
    cost in an untelemetered process is literally nothing."""
    eventlog.shutdown()
    blackbox.reset()
    eventlog.emit("endpoint.start", query=None)
    assert blackbox.ring_len() == 0


def test_session_knobs_configure_recorder(tmp_path):
    _session({"spark.rapids.tpu.eventLog.dir": str(tmp_path),
              "spark.rapids.tpu.flightRecorder.maxEvents": 7})
    assert blackbox.enabled()
    assert blackbox._ring.maxlen == 7
    assert blackbox.dump_path() == str(
        tmp_path / f"blackbox-{os.getpid()}.json")


def test_fleet_adopt_carries_blackbox_pointer(tmp_path):
    fleet_dir, log_dir = tmp_path / "fleet", tmp_path / "log"
    log_dir.mkdir()
    eventlog.configure(str(log_dir))
    dead = FleetDirectory(str(fleet_dir), lease_timeout_s=0.2,
                          heartbeat_interval_s=0)
    dead.register("127.0.0.1", 1111,
                  extra={"lease_timeout_s": 0.2,
                         "blackbox": "/scratch/blackbox-1111.json"})
    dead._hb_stop.set()   # simulate the SIGKILL: record left behind
    time.sleep(0.4)
    survivor = FleetDirectory(str(fleet_dir), lease_timeout_s=0.2,
                              heartbeat_interval_s=0)
    survivor.register("127.0.0.1", 2222)
    survivor.renew()
    assert survivor.sweep_expired() == [dead.replica_id]
    # the victim's final record became a departed- tombstone
    (tomb,) = survivor.departed()
    assert tomb["replica"] == dead.replica_id
    assert tomb["blackbox"] == "/scratch/blackbox-1111.json"
    assert tomb["adopted_by"] == survivor.replica_id
    assert tomb["departed"] > 0
    survivor.deregister()
    eventlog.shutdown()
    (adopt,) = [r for r in _read_events(log_dir)
                if r.get("event") == "fleet.adopt"]
    assert adopt["blackbox"] == "/scratch/blackbox-1111.json"
    assert adopt["replica"] == dead.replica_id
    # the roster still explains the dead replica
    rc, out, err = _profiler("fleet", str(fleet_dir), "--json")
    assert rc == 0, err
    roster = json.loads(out)
    assert roster["departed"] == 1
    (gone,) = [r for r in roster["replicas"] if r["status"] == "departed"]
    assert gone["blackbox"] == "/scratch/blackbox-1111.json"


def test_profiler_fleet_judges_liveness_from_embedded_timeout(tmp_path):
    fd = FleetDirectory(str(tmp_path), lease_timeout_s=0.2,
                        heartbeat_interval_s=0)
    fd.register("127.0.0.1", 1, extra={"lease_timeout_s": 0.2})
    rc, out, _ = _profiler("fleet", str(tmp_path), "--json")
    assert rc == 0
    assert json.loads(out)["replicas"][0]["status"] == "live"
    time.sleep(0.4)
    rc, out, _ = _profiler("fleet", str(tmp_path), "--json")
    assert json.loads(out)["replicas"][0]["status"] == "expired"
    fd.deregister()
    rc, _, err = _profiler("fleet", str(tmp_path))
    assert rc == 1 and "no membership records" in err


# -- heartbeat health roster ---------------------------------------------------

def test_lease_record_embeds_health_rollup(tmp_path):
    spark = _session({
        "spark.rapids.tpu.fleet.dir": str(tmp_path),
        "spark.rapids.tpu.fleet.heartbeat.intervalSeconds": 0.2,
        "spark.rapids.tpu.endpoint.resultCache.enabled": True})
    ep = QueryEndpoint(spark)
    cli = EndpointClient(("127.0.0.1", ep.port), timeout_s=30)
    try:
        cli.submit(SQL)
        cli.submit(SQL)   # a result-cache hit for the hit-rate gauge

        def _health():
            m = ep.fleet.members()
            return m[0].get("health") if m else None

        assert _wait(lambda: (_health() or {}).get("result_cache",
                                                   {}).get("hits") == 1)
        h = _health()
        assert h["active_queries"] == 0
        assert h["result_cache"] == {"hits": 1, "misses": 1}
        assert "hbm_watermark_bytes" in h and "fuse" in h
        assert h["resilience"] == {} or all(h["resilience"].values())
        m = ep.fleet.members()[0]
        assert m["lease_timeout_s"] == ep.fleet.lease_timeout_s
        rc, out, err = _profiler("fleet", str(tmp_path))
        assert rc == 0, err
        assert "[live]" in out and "result_cache 1h/1m" in out
    finally:
        ep.shutdown(grace_s=5)


# -- SIGKILL: the dump survives, the survivor explains it ----------------------

def _spawn_victim(fleet_dir, log_dir):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "tools" / "fleet_replica.py"),
         "--fleet-dir", str(fleet_dir), "--synthetic", "200",
         "--lease-timeout", "3", "--heartbeat", "0.5",
         "--request-timeout", "1.0",
         "--eventlog-dir", str(log_dir),
         "--faults", "hang:endpoint.send:1"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
    deadline = time.monotonic() + 240
    port = None
    while time.monotonic() < deadline:
        ln = proc.stdout.readline()
        if ln.startswith("READY "):
            port = int(ln.split()[1])
            break
        if proc.poll() is not None:
            break
    if port is None:
        proc.kill()
        raise AssertionError("victim replica never became READY")
    threading.Thread(target=proc.stdout.read, daemon=True).start()
    return proc, port


@pytest.mark.slow
def test_sigkill_blackbox_dump_and_merged_journey(tmp_path):
    """The post-mortem contract end to end with a real victim PROCESS: the
    wedged victim's heartbeat watchdog dumps the flight recorder (naming
    the in-flight journey) and closes the journey as replica_timeout
    BEFORE the SIGKILL; the in-process survivor serves attempt 2, adopts
    the lease with the blackbox path on fleet.adopt, and profiler.py
    journey renders the whole story from the merged logs."""
    fleet_dir, log_dir = tmp_path / "fleet", tmp_path / "log"
    log_dir.mkdir()
    spark = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(log_dir),
        "spark.rapids.tpu.fleet.dir": str(fleet_dir),
        "spark.rapids.tpu.fleet.lease.timeoutSeconds": 3,
        "spark.rapids.tpu.fleet.heartbeat.intervalSeconds": 0.5})
    spark.create_or_replace_temp_view(
        "t", spark.create_dataframe(
            pa.table({"k": pa.array([i % 50 for i in range(200)],
                                    type=pa.int64()),
                      "v": pa.array([float(i) for i in range(200)],
                                    type=pa.float64())}),
            num_partitions=2))
    oracle = spark.sql(SQL).collect().to_pylist()
    ep = QueryEndpoint(spark)
    victim, vport = _spawn_victim(fleet_dir, log_dir)
    bb_path = log_dir / f"blackbox-{victim.pid}.json"
    flight = {}
    try:
        cli = EndpointClient([("127.0.0.1", vport), ("127.0.0.1", ep.port)],
                             timeout_s=120)

        def run():
            try:
                flight["rows"] = cli.submit_with_retry(SQL).to_pylist()
                flight["journey"] = cli.last_journey
            except BaseException as e:  # noqa: BLE001 — asserted below
                flight["error"] = repr(e)[:200]

        t = threading.Thread(target=run, daemon=True)
        t.start()
        # let the query wedge at its first result frame, age past the 1s
        # request timeout, and a 0.5s heartbeat run the watchdog + dump
        assert _wait(bb_path.exists, timeout_s=30), \
            "victim never dumped its flight recorder"
        os.kill(victim.pid, signal.SIGKILL)
        t.join(timeout=240)
        assert flight.get("rows") == oracle, flight
        jid = flight["journey"]

        bb = json.loads(bb_path.read_text())
        assert bb["reason"] == "stuck_query" and bb["pid"] == victim.pid
        named = [i for i in bb["inflight"] if i["journey"] == jid]
        assert named and named[0]["sql"].startswith("select k % 5")
        assert named[0]["timed_out"] is True
        assert bb["events"]

        # the survivor adopts the victim's lease, blackbox pointer attached
        assert _wait(lambda: not (
            fleet_dir / f"replica-127.0.0.1-{vport}-{victim.pid}.json"
        ).exists(), timeout_s=30), "victim lease never adopted"
    finally:
        try:
            victim.kill()
        except OSError:
            pass
        victim.wait(timeout=30)
        ep.shutdown(grace_s=5)
    eventlog.shutdown()

    recs = _read_events(log_dir)
    (adopt,) = [r for r in recs if r.get("event") == "fleet.adopt"
                and r.get("dead_pid") == victim.pid]
    assert adopt["blackbox"] == str(bb_path)
    jrecs = sorted(_journeys(recs, jid), key=lambda r: r["attempt"])
    assert [r["outcome"] for r in jrecs] == ["replica_timeout", "served"]
    assert jrecs[0]["stuck"] is True and str(victim.pid) in jrecs[0]["replica"]
    assert jrecs[1]["traces"] == 0   # the survivor served from warm state

    logs = sorted(str(f) for f in log_dir.glob("*.jsonl"))
    rc, out, err = _profiler("journey", *logs, "--journey", jid, "--json")
    assert rc == 0, err
    (jn,) = json.loads(out)["journeys"]
    assert jn["failovers"] >= 1 and jn["outcome"] == "served"
    rc, out, err = _profiler("fleet", str(fleet_dir), "--json")
    assert rc == 0, err
    roster = json.loads(out)
    (gone,) = [r for r in roster["replicas"]
               if r["status"] == "departed" and r.get("pid") == victim.pid]
    assert gone["blackbox"] == str(bb_path)
    assert gone.get("health"), "tombstone lost the last-known health"
