"""Test harness setup.

Tests run on a virtual 8-device CPU platform (mirrors the reference's ring-1/ring-2
strategy, SURVEY.md §4: protocol/memory logic testable without real hardware; the
driver separately dry-runs the multi-chip path). Env must be set before jax imports.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force CPU even when a TPU is attached
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the axon site hook re-selects the TPU platform regardless of env; override it
jax.config.update("jax_platforms", "cpu")

# persistent machine-fingerprinted XLA compile cache (same helper bench.py
# and the driver entry points use): a cold full suite on a 1-core box is
# mostly LLVM compilation; repeated runs — including the driver's tier-1
# verify of THIS checkout — reload executables instead of re-compiling.
# Entries only ever load on the machine that built them (SIGILL guard,
# __graft_entry__._enable_compile_cache).
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from __graft_entry__ import _enable_compile_cache  # noqa: E402

_enable_compile_cache()

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _bound_xla_code_memory():
    """Free compiled executables between test modules. XLA:CPU's LLVM JIT
    code memory is bounded: ~3000 live executables in one process make later
    compiles abort/segfault (docs/perf_notes.md round-4 finding). The engine
    budgets its own fuse kernels; this drops everything else tests compile."""
    yield
    import gc
    from spark_rapids_tpu.runtime import fuse
    fuse.clear_kernels()
    jax.clear_caches()
    gc.collect()


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def make_table(n=1000, seed=0, with_nulls=True):
    """Random mixed-type pyarrow table, the data_gen.py analog
    (reference integration_tests/src/main/python/data_gen.py)."""
    r = np.random.default_rng(seed)
    null_mask = lambda: r.random(n) < 0.1 if with_nulls else np.zeros(n, bool)

    def witness(vals, mask):
        return pa.array([None if m else v for v, m in zip(vals.tolist(), mask)])

    ints = witness(r.integers(-1000, 1000, n, dtype=np.int32), null_mask())
    longs = witness(r.integers(-10**12, 10**12, n, dtype=np.int64), null_mask())
    doubles = witness(r.normal(0, 100, n), null_mask())
    floats = pa.array([None if m else float(np.float32(v)) for v, m in
                       zip(r.normal(0, 10, n), null_mask())], type=pa.float32())
    words = np.array(["apple", "banana", "cherry", "date", "elderberry", "fig",
                      "grape", "", "kiwi", "lemon"])
    strs = witness(words[r.integers(0, len(words), n)], null_mask())
    bools = witness(r.integers(0, 2, n).astype(bool), null_mask())
    # temporal + decimal columns (VERDICT r1 weak #4: the equivalence harness
    # cannot catch what it never generates) — dates span pre-epoch through
    # 2100, timestamps cover sub-second micros, decimal(12,2) covers signed
    # money-style values
    dates = pa.array([None if m else int(v) for v, m in
                      zip(r.integers(-10_000, 47_482, n), null_mask())],
                     type=pa.int32()).cast(pa.date32())
    ts = pa.array([None if m else int(v) for v, m in
                   zip(r.integers(-10**15, 4 * 10**15, n), null_mask())],
                  type=pa.int64()).cast(pa.timestamp("us", tz="UTC"))
    import decimal as _dec
    decs = pa.array([None if m else
                     _dec.Decimal(int(v)).scaleb(-2) for v, m in
                     zip(r.integers(-10**10, 10**10, n), null_mask())],
                    type=pa.decimal128(12, 2))
    return pa.table({
        "i": ints, "l": longs, "d": doubles, "f": floats, "s": strs, "b": bools,
        "dt": dates, "ts": ts, "dec": decs,
    })


@pytest.fixture
def mixed_table():
    return make_table()
