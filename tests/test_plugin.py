"""Plugin bootstrap tests (SURVEY.md #1; reference Plugin.scala lifecycle)."""

import pytest

from spark_rapids_tpu import config as CFG
from spark_rapids_tpu import plugin as PL
from spark_rapids_tpu.config import RapidsConf


@pytest.fixture(autouse=True)
def fresh():
    PL.reset_for_tests()
    yield
    PL.reset_for_tests()


def test_driver_init_builds_heartbeat_manager():
    ctx = PL.driver_init(RapidsConf(
        {"spark.rapids.tpu.shuffle.enabled": "true"}))
    from spark_rapids_tpu.shuffle.heartbeat import RapidsShuffleHeartbeatManager
    assert isinstance(ctx["heartbeat_manager"], RapidsShuffleHeartbeatManager)


def test_executor_init_bad_ordinal_crashes_fast():
    with pytest.raises(PL.PluginInitError, match="out of range"):
        PL.executor_init(RapidsConf({"spark.rapids.tpu.device.ordinal": "99"}))


def test_executor_init_acquires_device():
    from spark_rapids_tpu.runtime.memory import DeviceManager
    from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
    conf = RapidsConf({"spark.rapids.tpu.sql.concurrentTpuTasks": "3"})
    PL.executor_init(conf)
    assert DeviceManager.get() is not None
    assert TpuSemaphore.get().max_concurrent == 3


def test_bootstrap_idempotent_and_eager():
    conf = RapidsConf({"spark.rapids.tpu.device.eagerInit": "true"})
    PL.bootstrap(conf)
    PL.bootstrap(RapidsConf({"spark.rapids.tpu.device.ordinal": "99"}))
    # second call is a no-op: the bad ordinal never ran


def test_session_triggers_bootstrap():
    from spark_rapids_tpu.session import TpuSession
    TpuSession()
    assert PL._initialized


def test_bootstrap_retains_context():
    PL.bootstrap(RapidsConf({"spark.rapids.tpu.shuffle.enabled": "true"}))
    from spark_rapids_tpu.shuffle.heartbeat import RapidsShuffleHeartbeatManager
    assert isinstance(PL.context().get("heartbeat_manager"),
                      RapidsShuffleHeartbeatManager)
