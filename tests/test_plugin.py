"""Plugin bootstrap tests (SURVEY.md #1; reference Plugin.scala lifecycle)."""

import pytest

from spark_rapids_tpu import config as CFG
from spark_rapids_tpu import plugin as PL
from spark_rapids_tpu.config import RapidsConf


@pytest.fixture(autouse=True)
def fresh():
    PL.reset_for_tests()
    yield
    PL.reset_for_tests()


def test_driver_init_builds_heartbeat_manager():
    ctx = PL.driver_init(RapidsConf(
        {"spark.rapids.tpu.shuffle.enabled": "true"}))
    from spark_rapids_tpu.shuffle.heartbeat import RapidsShuffleHeartbeatManager
    assert isinstance(ctx["heartbeat_manager"], RapidsShuffleHeartbeatManager)


def test_executor_init_bad_ordinal_crashes_fast():
    with pytest.raises(PL.PluginInitError, match="out of range"):
        PL.executor_init(RapidsConf({"spark.rapids.tpu.device.ordinal": "99"}))


def test_executor_init_acquires_device():
    from spark_rapids_tpu.runtime.memory import DeviceManager
    from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
    conf = RapidsConf({"spark.rapids.tpu.sql.concurrentTpuTasks": "3"})
    PL.executor_init(conf)
    assert DeviceManager.get() is not None
    assert TpuSemaphore.get().max_concurrent == 3


def test_bootstrap_idempotent_and_eager():
    conf = RapidsConf({"spark.rapids.tpu.device.eagerInit": "true"})
    PL.bootstrap(conf)
    PL.bootstrap(RapidsConf({"spark.rapids.tpu.device.ordinal": "99"}))
    # second call is a no-op: the bad ordinal never ran


def test_session_triggers_bootstrap():
    from spark_rapids_tpu.session import TpuSession
    TpuSession()
    assert PL._initialized


def test_bootstrap_retains_context():
    PL.bootstrap(RapidsConf({"spark.rapids.tpu.shuffle.enabled": "true"}))
    from spark_rapids_tpu.shuffle.heartbeat import RapidsShuffleHeartbeatManager
    assert isinstance(PL.context().get("heartbeat_manager"),
                      RapidsShuffleHeartbeatManager)


def test_trace_conf_wires_annotations(tmp_path):
    """spark.rapids.tpu.sql.trace.enabled must actually flip the tracing
    module (it was a dead conf); a traced query still runs."""
    import pyarrow as pa
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.runtime import tracing
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({"spark.rapids.tpu.sql.trace.enabled": "true"})
    assert tracing._enabled
    df = s.create_dataframe({"a": pa.array([1, 2, 3], pa.int64())})
    assert df.filter(F.col("a") > 1).collect().num_rows == 2
    TpuSession()                     # default session must NOT clobber it
    assert tracing._enabled
    TpuSession({"spark.rapids.tpu.sql.trace.enabled": "false"})
    assert not tracing._enabled
