"""Partitioning tests — Spark-exact hash placement plus slicing invariants
(reference: HashPartitioningSuite / GpuPartitioningSuite patterns, SURVEY.md §4)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.ops.hashing import (murmur3_int_host, murmur3_long_host,
                                          murmur3_bytes_host, _to_signed)
from spark_rapids_tpu.ops.sorting import SortOrder
from spark_rapids_tpu.shuffle.partitioning import (
    HashPartitioner, RangePartitioner, RoundRobinPartitioner, SinglePartitioner,
    SPARK_HASH_SEED)

from conftest import make_table


def spark_hash_rows(table):
    """Host model of Spark Murmur3Hash(seed=42) over (int, long, string) rows."""
    out = []
    for i in range(table.num_rows):
        h = SPARK_HASH_SEED
        for name in table.column_names:
            v = table[name][i].as_py()
            if v is None:
                continue
            t = table.schema.field(name).type
            if t == pa.int32():
                h = murmur3_int_host(v, h)
            elif t == pa.int64():
                h = murmur3_long_host(v, h)
            elif t == pa.string():
                h = murmur3_bytes_host(v.encode(), h)
            else:
                raise NotImplementedError(str(t))
        out.append(_to_signed(h))
    return out


def collect_rows(parts):
    tables = [b.to_arrow() for _, b in parts]
    return pa.concat_tables(tables) if tables else None


def same_multiset(a: pa.Table, b: pa.Table) -> bool:
    def rows(t):
        cols = [t[name].to_pylist() for name in t.column_names]
        key = lambda v: (v is None, str(type(v)), v if v is not None else 0)
        return sorted(zip(*cols), key=lambda r: tuple(key(v) for v in r))
    return rows(a) == rows(b)


def test_hash_partition_matches_spark_placement():
    n = 500
    r = np.random.default_rng(1)
    t = pa.table({
        "i": pa.array([None if m else int(v) for v, m in
                       zip(r.integers(-10**6, 10**6, n), r.random(n) < 0.1)],
                      type=pa.int32()),
        "l": pa.array(r.integers(-10**12, 10**12, n), type=pa.int64()),
        "s": pa.array([["a", "bb", "ccc", "dddd", None][i % 5] for i in range(n)]),
    })
    batch = ColumnarBatch.from_arrow(t)
    nparts = 7
    p = HashPartitioner([col("i"), col("l"), col("s")], nparts).bind(batch.schema)
    parts = dict(p.partition(batch))
    expect_ids = [h % nparts + (nparts if h % nparts < 0 else 0)
                  for h in spark_hash_rows(t)]
    # group expected rows per partition and compare as multisets
    got_total = 0
    for pid, pb in parts.items():
        pt = pb.to_arrow()
        got_total += pt.num_rows
        want = t.filter(pa.array([e == pid for e in expect_ids]))
        assert same_multiset(pt, want), f"partition {pid}"
    assert got_total == n


def test_round_robin_balanced():
    t = make_table(n=1000)
    batch = ColumnarBatch.from_arrow(t)
    p = RoundRobinPartitioner(8)
    parts = p.partition(batch, split=3)
    sizes = [b.num_rows for _, b in parts]
    assert sum(sizes) == 1000
    assert max(sizes) - min(sizes) <= 1
    assert same_multiset(collect_rows(parts), t)


def test_single_partitioner():
    t = make_table(n=50)
    batch = ColumnarBatch.from_arrow(t)
    parts = SinglePartitioner().partition(batch)
    assert len(parts) == 1 and parts[0][0] == 0
    assert parts[0][1].to_arrow().equals(t)


@pytest.mark.parametrize("ascending", [True, False])
def test_range_partitioner_orders_partitions(ascending):
    n = 800
    r = np.random.default_rng(7)
    t = pa.table({"k": pa.array([None if m else int(v) for v, m in
                                 zip(r.integers(-1000, 1000, n), r.random(n) < 0.05)],
                                type=pa.int64()),
                  "v": pa.array(np.arange(n), type=pa.int32())})
    batch = ColumnarBatch.from_arrow(t)
    p = RangePartitioner([col("k")], [SortOrder(ascending=ascending)], 5).bind(batch.schema)
    p.set_bounds_from_sample([batch])
    parts = sorted(p.partition(batch), key=lambda x: x[0])
    assert sum(b.num_rows for _, b in parts) == n
    # every key in partition p must be <= (asc) every key in partition p+1, with
    # Spark null ordering (nulls first when ascending)
    def keyfn(x):
        return (x is None, x) if not ascending else (x is not None, x if x is not None else 0)
    seq = []
    for _, b in parts:
        ks = b.to_arrow()["k"].to_pylist()
        if ascending:
            seq.append((min((k for k in ks if k is not None), default=None),
                        max((k for k in ks if k is not None), default=None),
                        any(k is None for k in ks)))
    if ascending:
        # nulls (first) only in partition 0; min/max ranges non-overlapping
        for i in range(1, len(parts)):
            assert not seq[i][2] or i == 0
        prev_max = None
        for mn, mx, _ in seq:
            if mn is None:
                continue
            if prev_max is not None:
                assert mn >= prev_max
            prev_max = mx
    # full multiset preserved
    assert same_multiset(collect_rows(parts), t)


def test_string_hash_partition_roundtrip():
    t = pa.table({"s": pa.array(["apple", "banana", None, "", "चाय", "apple"] * 20)})
    batch = ColumnarBatch.from_arrow(t)
    p = HashPartitioner([col("s")], 4).bind(batch.schema)
    parts = p.partition(batch)
    assert sum(b.num_rows for _, b in parts) == t.num_rows
    # same value always lands in the same partition
    seen = {}
    for pid, b in parts:
        for v in b.to_arrow()["s"].to_pylist():
            assert seen.setdefault(v, pid) == pid
    expect = {h % 4 + (4 if h % 4 < 0 else 0)
              for h in spark_hash_rows(t.filter(pa.array([v is not None for v in
                                                          t["s"].to_pylist()])))}
    got_nonnull = {pid for pid, b in parts
                   for v in b.to_arrow()["s"].to_pylist() if v is not None}
    assert got_nonnull == expect
