"""Exec-layer equivalence tests — ring-2 analog of SparkQueryCompareTestSuite
(reference tests/.../SparkQueryCompareTestSuite.scala:183: run the same query on CPU
and device, diff results). Here the CPU oracle is pandas/pyarrow compute."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.basic import (ArrowScanExec, FilterExec, ProjectExec,
                                         RangeExec, UnionExec, LocalLimitExec)
from spark_rapids_tpu.exec.aggregate import HashAggregateExec, PARTIAL, FINAL
from spark_rapids_tpu.exec.sort import SortExec, _GatherAllExec
from spark_rapids_tpu.expr.core import col, lit, Alias
from spark_rapids_tpu.expr.arithmetic import Add, Multiply
from spark_rapids_tpu.expr.predicates import GreaterThan, LessThan, And, EqualTo
from spark_rapids_tpu.expr.aggregates import Sum, Count, Min, Max, Average
from spark_rapids_tpu.ops.sorting import SortOrder

from conftest import make_table


def sorted_frame(t: pa.Table):
    df = t.to_pandas()
    return df.sort_values(list(df.columns), na_position="first").reset_index(drop=True)


def assert_frames_equal(got: pa.Table, exp: pd.DataFrame, ignore_order=True):
    gdf = got.to_pandas()
    if ignore_order:
        gdf = gdf.sort_values(list(gdf.columns), na_position="first").reset_index(drop=True)
        exp = exp.sort_values(list(exp.columns), na_position="first").reset_index(drop=True)
    pd.testing.assert_frame_equal(gdf, exp, check_dtype=False)


def test_project_filter():
    t = make_table(500, seed=1)
    scan = ArrowScanExec([t])
    plan = FilterExec(And(GreaterThan(col("i"), lit(0)), LessThan(col("i"), lit(500))),
                      scan)
    plan = ProjectExec([Alias(Add(col("i"), col("l")), "x"), col("s")], plan)
    got = plan.execute_collect()
    df = t.to_pandas()
    exp = df[(df.i > 0) & (df.i < 500)]
    exp = pd.DataFrame({"x": exp.i + exp.l, "s": exp.s})
    assert_frames_equal(got, exp)


def test_multi_partition_scan():
    t1, t2 = make_table(100, seed=2), make_table(150, seed=3)
    scan = ArrowScanExec([t1, t2])
    plan = ProjectExec([col("i")], scan)
    got = plan.execute_collect()
    exp = pd.concat([t1.to_pandas()[["i"]], t2.to_pandas()[["i"]]])
    assert_frames_equal(got, exp)


def test_range_union_limit():
    r1 = RangeExec(0, 100)
    r2 = RangeExec(100, 200)
    u = UnionExec(r1, r2)
    got = LocalLimitExec(30, u).execute_collect()
    # local limit applies per partition: 30 from each of the 2 partitions
    assert got.num_rows == 60
    assert got.column("id").to_pylist()[:5] == [0, 1, 2, 3, 4]


def test_grouped_aggregate_complete():
    t = make_table(800, seed=4)
    scan = ArrowScanExec([t])
    plan = HashAggregateExec(
        [col("s")],
        [Alias(Sum(col("l")), "sum_l"), Alias(Count(col("i")), "cnt_i"),
         Alias(Min(col("d")), "min_d"), Alias(Max(col("i")), "max_i"),
         Alias(Average(col("d")), "avg_d"), Alias(Count(None), "cnt")],
        scan)
    got = plan.execute_collect()
    df = t.to_pandas()
    g = df.groupby("s", dropna=False)
    exp = pd.DataFrame({
        "s": [k for k, _ in g],
        "sum_l": [v.l.sum() if v.l.notna().any() else None for _, v in g],
        "cnt_i": [v.i.notna().sum() for _, v in g],
        "min_d": [v.d.min() if v.d.notna().any() else None for _, v in g],
        "max_i": [v.i.max() if v.i.notna().any() else None for _, v in g],
        "avg_d": [v.d.mean() if v.d.notna().any() else None for _, v in g],
        "cnt": [len(v) for _, v in g],
    })
    assert_frames_equal(got, exp)


def test_global_aggregate():
    t = make_table(300, seed=5)
    scan = ArrowScanExec([t])
    plan = HashAggregateExec(
        [], [Alias(Sum(col("i")), "s"), Alias(Count(None), "n"),
             Alias(Average(col("l")), "a")], scan)
    got = plan.execute_collect().to_pandas()
    df = t.to_pandas()
    assert got.shape == (1, 3)
    assert got.s[0] == df.i.sum()
    assert got.n[0] == len(df)
    assert abs(got.a[0] - df.l.mean()) < 1e-6


def test_global_aggregate_empty_input():
    t = make_table(0, seed=6)
    scan = ArrowScanExec([t])
    plan = HashAggregateExec([], [Alias(Count(None), "n"), Alias(Sum(col("i")), "s")],
                             scan)
    got = plan.execute_collect().to_pandas()
    assert got.n[0] == 0
    assert got.s.isna()[0]


def test_two_phase_aggregate():
    """partial on each partition → gather → final (pre-shuffle shape)."""
    t1, t2 = make_table(200, seed=7), make_table(300, seed=8)
    scan = ArrowScanExec([t1, t2])
    aggs = [Alias(Sum(col("l")), "sum_l"), Alias(Average(col("i")), "avg_i"),
            Alias(Count(None), "cnt")]
    partial = HashAggregateExec([col("s")], aggs, scan, mode=PARTIAL)
    final = HashAggregateExec([col("s", T.STRING)], aggs,
                              _GatherAllExec(partial), mode=FINAL)
    got = final.execute_collect()
    df = pd.concat([t1.to_pandas(), t2.to_pandas()])
    g = df.groupby("s", dropna=False)
    exp = pd.DataFrame({
        "s": [k for k, _ in g],
        "sum_l": [v.l.sum() if v.l.notna().any() else None for _, v in g],
        "avg_i": [v.i.mean() if v.i.notna().any() else None for _, v in g],
        "cnt": [len(v) for _, v in g],
    })
    assert_frames_equal(got, exp)


def test_sort():
    t = make_table(400, seed=9)
    scan = ArrowScanExec([t])
    plan = SortExec([col("i"), col("d")], [SortOrder(True), SortOrder(False)], scan)
    got = plan.execute_collect().to_pandas()
    exp = t.to_pandas().sort_values(
        ["i", "d"], ascending=[True, False],
        na_position="first", kind="stable").reset_index(drop=True)
    # pandas puts NaN (not null) interleaved differently for desc; compare key cols
    pd.testing.assert_series_equal(got.i, exp.i, check_dtype=False)


def test_sort_nulls_last_desc():
    t = pa.table({"x": pa.array([3, None, 1, 2, None, 5], type=pa.int32())})
    scan = ArrowScanExec([t])
    plan = SortExec([col("x")], [SortOrder(ascending=False)], scan)
    got = plan.execute_collect().column("x").to_pylist()
    assert got == [5, 3, 2, 1, None, None]  # desc → nulls last (Spark default)
    plan = SortExec([col("x")], [SortOrder(ascending=False, nulls_first=True)], scan)
    got = plan.execute_collect().column("x").to_pylist()
    assert got == [None, None, 5, 3, 2, 1]


def test_sort_float_nan_ordering():
    t = pa.table({"x": pa.array([1.0, float("nan"), None, float("inf"), -0.0, 0.0])})
    scan = ArrowScanExec([t])
    got = SortExec([col("x")], [SortOrder(True)], scan).execute_collect()
    vals = got.column("x").to_pylist()
    assert vals[0] is None          # nulls first
    assert vals[1] in (0.0, -0.0) and vals[2] in (0.0, -0.0)
    assert vals[3] == 1.0
    assert vals[4] == float("inf")
    assert np.isnan(vals[5])        # NaN greater than +inf (Spark)
