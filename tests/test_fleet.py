"""Serving-fleet tests (runtime/fleet.py + the fleet halves of
runtime/endpoint.py): on-disk membership with lease expiry, exactly-once
adoption with write-intent reclaim, client replica lists with failover
rotation, the fleet-only retryable request-timeout rejection, the
parameterized-plan result cache (hit / catalog-epoch invalidation), the
multi-process shared-store contracts (history merge under the advisory
lock, stage-cache racing-prune degradation), and the headline chaos
scenario — a replica SIGKILLed mid-stream with the client failing over to
a survivor bit-identically."""

import gc
import os
import pathlib
import signal
import socket
import subprocess
import sys
import threading
import time

import pyarrow as pa
import pytest

from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import scheduler as SCHED
from spark_rapids_tpu.runtime import stage_cache
from spark_rapids_tpu.runtime.endpoint import (EndpointClient, QueryEndpoint,
                                               _parse_addresses)
from spark_rapids_tpu.runtime.fleet import FleetDirectory, _is_write_intent
from spark_rapids_tpu.runtime.history import PlanHistoryStore
from spark_rapids_tpu.runtime.result_cache import ResultCache
from spark_rapids_tpu.session import TpuSession

SQL = "select k % 5 kk, sum(v) s, count(*) c from t group by kk order by kk"


def _session(extra=None):
    spark = TpuSession(dict(extra or {}))
    spark.create_or_replace_temp_view(
        "t", spark.create_dataframe(
            pa.table({"k": list(range(200)),
                      "v": [float(i) / 3 for i in range(200)]}),
            num_partitions=4))
    return spark


def _wait(pred, timeout_s=20.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def _failovers():
    return M.resilience_snapshot()["replicaFailovers"]


# -- membership + lease --------------------------------------------------------

def test_register_members_lease_expiry_and_renew(tmp_path):
    fd = FleetDirectory(str(tmp_path), lease_timeout_s=0.3,
                        heartbeat_interval_s=0)
    rid = fd.register("127.0.0.1", 1234)
    assert rid == f"127.0.0.1-1234-{os.getpid()}"
    assert [m["replica"] for m in fd.members()] == [rid]
    assert fd.addresses() == [("127.0.0.1", 1234)]
    time.sleep(0.5)
    # the lease (mtime) expired: dropped from the live view, still on disk
    assert fd.members() == []
    assert [m["replica"] for m in fd.members(live_only=False)] == [rid]
    fd.renew()
    assert [m["replica"] for m in fd.members()] == [rid]
    fd.deregister()
    assert fd.members(live_only=False) == []


def test_renew_rewrites_a_vanished_record(tmp_path):
    fd = FleetDirectory(str(tmp_path), lease_timeout_s=5,
                        heartbeat_interval_s=0)
    fd.register("127.0.0.1", 1, stores=["/tmp/x"])
    (rec,) = tmp_path.glob("replica-*.json")
    rec.unlink()    # the fleet dir was cleaned underneath the replica
    fd.renew()
    assert [m["replica"] for m in fd.members()] == [fd.replica_id]
    assert fd.members()[0]["stores"] == ["/tmp/x"]
    fd.deregister()


def test_write_intent_matching():
    pid = 123
    assert _is_write_intent("e.xc.tmp.123", pid)            # stage cache
    assert _is_write_intent("e.xc.tmp.123-7", pid)          # threaded seq
    assert _is_write_intent("plan_history.json.tmp.123", pid)
    assert not _is_write_intent("e.xc.tmp.1234", pid)       # other pid
    assert not _is_write_intent("e.xc.tmp.999-123", pid)    # seq != owner
    assert not _is_write_intent("e.xc", pid)                # durable entry
    assert not _is_write_intent("e.tmp", pid)               # no pid marker


def test_sweep_adopts_expired_lease_and_reclaims_intents(tmp_path):
    fleet, store = tmp_path / "fleet", tmp_path / "store"
    store.mkdir()
    dead = FleetDirectory(str(fleet), lease_timeout_s=0.3,
                          heartbeat_interval_s=0)
    dead.register("127.0.0.1", 1111, stores=[str(store)])
    pid = os.getpid()
    orphans = [store / f"aa.xc.tmp.{pid}", store / f"bb.xc.tmp.{pid}-3"]
    keep = [store / "cc.xc.tmp.999999999",   # another replica's intent
            store / "dd.xc"]                 # a durable entry
    for f in orphans + keep:
        f.write_bytes(b"x")

    survivor = FleetDirectory(str(fleet), lease_timeout_s=0.3,
                              heartbeat_interval_s=0)
    survivor.register("127.0.0.1", 2222)
    time.sleep(0.5)
    survivor.renew()     # own lease fresh; the dead replica's is expired
    adoptions_before = M.resilience_snapshot()["fleetAdoptions"]

    assert survivor.sweep_expired() == [dead.replica_id]
    assert not any(f.exists() for f in orphans)
    assert all(f.exists() for f in keep)
    s = survivor.stats()
    assert s["adoptions"] == 1 and s["reclaimed_intents"] == 2
    assert M.resilience_snapshot()["fleetAdoptions"] == adoptions_before + 1
    # the dead replica's record is gone; a second sweep adopts nothing
    assert survivor.sweep_expired() == []
    assert [m["replica"] for m in survivor.members()] == [survivor.replica_id]
    survivor.deregister()


def test_adoption_is_exactly_once_across_concurrent_sweepers(tmp_path):
    dead = FleetDirectory(str(tmp_path), lease_timeout_s=0.2,
                          heartbeat_interval_s=0)
    dead.register("127.0.0.1", 1111)
    time.sleep(0.4)
    # two unregistered observers (e.g. standbys) race to adopt: the fleet
    # advisory lock serializes them, so exactly one wins
    sweepers = [FleetDirectory(str(tmp_path), lease_timeout_s=0.2,
                               heartbeat_interval_s=0) for _ in range(2)]
    barrier = threading.Barrier(2)

    def sweep(fd):
        barrier.wait()
        fd.sweep_expired()

    threads = [threading.Thread(target=sweep, args=(fd,)) for fd in sweepers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert sum(fd.adoptions for fd in sweepers) == 1


def test_heartbeat_thread_renews_and_stops(tmp_path):
    fd = FleetDirectory(str(tmp_path), lease_timeout_s=5,
                        heartbeat_interval_s=0.1)
    fd.register("127.0.0.1", 1)
    assert _wait(lambda: fd.heartbeats >= 2)
    name = f"srt-fleet-hb-{1}"
    assert any(t.name == name for t in threading.enumerate())
    fd.deregister()
    assert _wait(lambda: not any(t.name == name
                                 for t in threading.enumerate()))


# -- client replica lists ------------------------------------------------------

def test_parse_addresses_forms():
    assert _parse_addresses(("h", 1)) == [("h", 1)]
    assert _parse_addresses("127.0.0.1:80") == [("127.0.0.1", 80)]
    assert _parse_addresses("h1:1, h2:2") == [("h1", 1), ("h2", 2)]
    assert _parse_addresses([("h1", 1), "h2:2"]) == [("h1", 1), ("h2", 2)]
    for bad in ("", ",", [], ":80"):
        with pytest.raises(ValueError):
            _parse_addresses(bad)


def test_rotate_single_address_is_a_noop():
    cli = EndpointClient(("h", 1))
    before = _failovers()
    assert cli.rotate() == ("h", 1)
    assert cli.address == ("h", 1) and _failovers() == before


def test_rotate_multi_address_counts_failovers():
    cli = EndpointClient("h1:1,h2:2,h3:3")
    before = _failovers()
    assert cli.address == ("h1", 1)
    assert cli.rotate() == ("h2", 2)
    assert cli.rotate() == ("h3", 3)
    assert cli.rotate() == ("h1", 1)     # wraps
    assert _failovers() == before + 3


def test_connection_refused_rotates_to_live_replica():
    spark = _session()
    ep = QueryEndpoint(spark)
    # a port that refuses: bound then released, nobody listening
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    try:
        direct = spark.sql(SQL).collect().to_pylist()
        cli = EndpointClient([("127.0.0.1", dead_port),
                              ("127.0.0.1", ep.port)], timeout_s=30)
        before = _failovers()
        retries = []
        rows = cli.submit_with_retry(
            SQL, on_retry=lambda a, d: retries.append(a)).to_pylist()
        assert rows == direct
        assert retries and _failovers() >= before + 1
        assert cli.address == ("127.0.0.1", ep.port)
    finally:
        ep.shutdown(grace_s=5)


def test_fleet_request_timeout_is_retryable_rejection(tmp_path):
    spark = _session({
        "spark.rapids.tpu.fleet.dir": str(tmp_path / "fleet"),
        "spark.rapids.tpu.fleet.heartbeat.intervalSeconds": 0.2})
    ep = QueryEndpoint(spark)
    cli = EndpointClient(("127.0.0.1", ep.port), timeout_s=30)
    assert ep.fleet is not None
    try:
        direct = spark.sql(SQL).collect().to_pylist()
        ep.request_timeout = 0.3
        faults.configure("slow:agg.update:12", seed=1)
        # on a fleet the request-timeout kill surfaces RETRYABLE (the query
        # belongs on a surviving peer), not as the non-retryable typed
        # cancellation a solo endpoint keeps
        with pytest.raises(SCHED.QueryRejectedError) as ei:
            cli.submit(SQL)
        assert ei.value.reason == "replica_timeout"
        assert ei.value.backoff_hint_s > 0
        assert ei.value.replica == ep.fleet.replica_id
        assert _wait(lambda: ep.active_queries() == 0)
        faults.reset()
        ep.request_timeout = 0.0
        assert cli.submit_with_retry(SQL).to_pylist() == direct
    finally:
        faults.reset()
        ep.request_timeout = 0.0
        ep.shutdown(grace_s=5)
    # the clean shutdown deregistered this replica's lease
    assert not list((tmp_path / "fleet").glob("replica-*.json"))


# -- result cache --------------------------------------------------------------

def test_result_cache_lru_bounds_and_epoch_drop():
    rc = ResultCache(max_bytes=100, max_entries=2)
    k1, k2, k3 = (ResultCache.key(0, f"sig{i}", f"q{i}") for i in range(3))
    assert rc.put(k1, [b"x" * 40], {"q": 1})
    assert rc.put(k2, [b"y" * 40], {"q": 2})
    assert rc.get(k1)["summary"] == {"q": 1}   # refreshes k1's recency
    assert rc.put(k3, [b"z" * 40], {"q": 3})   # over budget: evicts LRU k2
    assert rc.get(k2) is None
    assert rc.get(k1) and rc.get(k3)
    assert rc.evictions == 1
    # a result larger than the whole byte budget is simply not admitted
    assert not rc.put(ResultCache.key(0, "big", "qb"), [b"w" * 200], {})
    # a newer catalog epoch drops every stale entry
    assert rc.put(ResultCache.key(1, "sig", "q"), [b"a"], {})
    assert rc.stale_drops == 2 and rc.get(k1) is None


def test_endpoint_result_cache_hit_and_catalog_invalidation():
    spark = _session({"spark.rapids.tpu.endpoint.resultCache.enabled": True})
    ep = QueryEndpoint(spark)
    cli = EndpointClient(("127.0.0.1", ep.port), timeout_s=30)
    assert ep.result_cache is not None
    try:
        first = cli.submit(SQL).to_pylist()
        assert not (cli.last_summary or {}).get("cached")
        # identical SQL: served bit-identically from the recorded frames,
        # without touching the scheduler
        second = cli.submit(SQL).to_pylist()
        assert second == first
        assert cli.last_summary.get("cached") is True
        assert ep.result_cache.hits == 1
        # catalog change: replacing the view bumps the session epoch, so
        # the stale result can never serve again
        spark.create_or_replace_temp_view(
            "t", spark.create_dataframe(
                pa.table({"k": list(range(200)),
                          "v": [float(i) for i in range(200)]}),
                num_partitions=4))
        third = cli.submit(SQL).to_pylist()
        assert not (cli.last_summary or {}).get("cached")
        assert third != first
        assert third == spark.sql(SQL).collect().to_pylist()
    finally:
        ep.shutdown(grace_s=5)


# -- shared-store multi-process contracts --------------------------------------

def test_stage_cache_racing_prune_is_warned_retrace(tmp_path):
    store = stage_cache.StageCacheStore(str(tmp_path))
    store.save("e1", b"payload")
    assert store.load("e1") == b"payload"
    # a peer replica's LRU prune unlinks the entry behind this store's back
    os.unlink(tmp_path / "e1.xc")
    with pytest.warns(RuntimeWarning, match="pruned by a concurrent"):
        assert store.load("e1") is None
    assert store.pruned_misses == 1
    # an entry this process never saw is a plain miss, not a pruned race
    assert store.load("never-seen") is None
    assert store.pruned_misses == 1 and store.misses == 2


def test_stage_cache_prune_tolerates_vanishing_files(tmp_path):
    store = stage_cache.StageCacheStore(str(tmp_path), max_bytes=64)
    store.save("a", b"x" * 40)
    store.save("b", b"y" * 40)   # prunes the older entry down to max_bytes
    assert store.entries() == ["b"]
    assert store.total_bytes() == 40


_HISTORY_CHILD = r"""
import sys, time
from spark_rapids_tpu.runtime.history import PlanHistoryStore
st = PlanHistoryStore(sys.argv[1])
for i in range(25):
    st.record(sys.argv[2], {"out_rows": i, "peak_device_bytes": 100 + i})
    time.sleep(0.002)
print("DONE", st.shape_count())
"""


@pytest.mark.slow
def test_history_two_process_merge_under_advisory_lock(tmp_path):
    """Two real writer PROCESSES hammer one history directory: without the
    cross-process advisory lock their load->merge->replace windows overlap
    and the later replace silently drops the other replica's shapes."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = (str(pathlib.Path(__file__).resolve().parent.parent)
                         + os.pathsep + env.get("PYTHONPATH", ""))
    procs = [subprocess.Popen(
        [sys.executable, "-c", _HISTORY_CHILD, str(tmp_path), fp],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for fp in ("fp-a", "fp-b")]
    for p in procs:
        out, _ = p.communicate(timeout=240)
        assert p.returncode == 0 and "DONE" in out, out
    st = PlanHistoryStore(str(tmp_path))
    a, b = st.lookup("fp-a"), st.lookup("fp-b")
    assert a and b, "one writer's shapes were dropped by the other's replace"
    assert a["runs"] == 25 and b["runs"] == 25
    assert a["peak_device_bytes"] == 124 and b["peak_device_bytes"] == 124


# -- mid-stream SIGKILL failover ----------------------------------------------

def _spawn_victim(fleet_dir, faults_spec):
    repo = pathlib.Path(__file__).resolve().parent.parent
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, str(repo / "tools" / "fleet_replica.py"),
         "--fleet-dir", str(fleet_dir), "--synthetic", "200",
         "--lease-timeout", "3", "--heartbeat", "0.5",
         "--faults", faults_spec],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
    deadline = time.monotonic() + 240
    port = None
    while time.monotonic() < deadline:
        ln = proc.stdout.readline()
        if ln.startswith("READY "):
            port = int(ln.split()[1])
            break
        if proc.poll() is not None:
            break
    if port is None:
        proc.kill()
        raise AssertionError("victim replica never became READY")
    threading.Thread(target=proc.stdout.read, daemon=True).start()
    return proc, port


@pytest.mark.slow
def test_sigkill_midstream_failover_bit_identical(tmp_path):
    """The headline failover contract: a victim replica PROCESS (wedged by a
    hang fault at its first result frame, so the kill lands mid-stream) is
    SIGKILLed while serving; the client's submit_with_retry rotates to the
    in-process survivor and the result is bit-identical — with zero leaked
    buffers, permits, or threads on the survivor."""
    from spark_rapids_tpu.runtime.memory import DeviceManager
    from spark_rapids_tpu.runtime.semaphore import TpuSemaphore

    # the survivor serves the SAME deterministic synthetic table the victim
    # builds (tools/fleet_replica.py --synthetic), so results are
    # bit-comparable across the fleet
    spark = TpuSession({})
    spark.create_or_replace_temp_view(
        "t", spark.create_dataframe(
            pa.table({"k": pa.array([i % 50 for i in range(200)],
                                    type=pa.int64()),
                      "v": pa.array([float(i) for i in range(200)],
                                    type=pa.float64())}),
            num_partitions=2))
    oracle = spark.sql(SQL).collect().to_pylist()
    cat = DeviceManager.get().catalog
    buffers_base = cat.num_buffers

    ep = QueryEndpoint(spark)
    victim, vport = _spawn_victim(tmp_path / "fleet", "hang:endpoint.send:1")
    flight, retries = {}, []
    try:
        cli = EndpointClient([("127.0.0.1", vport), ("127.0.0.1", ep.port)],
                             timeout_s=120)
        failovers_before = _failovers()

        def run():
            try:
                flight["rows"] = cli.submit_with_retry(
                    SQL, on_retry=lambda a, d: retries.append(a)).to_pylist()
            except BaseException as e:  # noqa: BLE001 — asserted below
                flight["error"] = repr(e)[:200]

        t = threading.Thread(target=run, daemon=True)
        t.start()
        time.sleep(1.5)     # the victim is wedged at its first result frame
        os.kill(victim.pid, signal.SIGKILL)
        t.join(timeout=240)
        assert not t.is_alive(), "failover client never finished"
        assert flight.get("rows") == oracle, flight
        assert retries, "the kill missed the in-flight window"
        assert _failovers() >= failovers_before + 1
        assert cli.address == ("127.0.0.1", ep.port)
    finally:
        try:
            victim.kill()
        except OSError:
            pass
        victim.wait(timeout=30)
        ep.shutdown(grace_s=5)

    # nothing leaked on the survivor: buffers, permits, threads
    gc.collect()
    assert _wait(lambda: cat.num_buffers <= buffers_base)
    assert cat.num_buffers <= buffers_base
    assert not TpuSemaphore.get()._holders
    assert _wait(lambda: not any(
        th.name.startswith(("srt-pipe-", "srt-endpoint", "srt-fleet"))
        for th in threading.enumerate()))
