"""Expression semantics tests — the CastOpSuite / arithmetic / predicate suites
analog (reference tests/.../CastOpSuite.scala etc.), pinned to Spark behaviors:
Java remainder sign, divide-by-zero→null, HALF_UP rounding, Kleene logic, NaN
ordering/equality, date algorithms, string functions over dictionaries."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch
from spark_rapids_tpu.expr.core import EvalContext, col, lit, bind_references
from spark_rapids_tpu.expr.arithmetic import (Add, Divide, IntegralDivide, Multiply,
                                              Remainder, Pmod, UnaryMinus, Abs)
from spark_rapids_tpu.expr.predicates import (EqualTo, EqualNullSafe, LessThan,
                                              GreaterThan, And, Or, Not, In)
from spark_rapids_tpu.expr.nullexprs import IsNull, IsNotNull, IsNaN, Coalesce, NaNvl
from spark_rapids_tpu.expr.conditional import If, CaseWhen
from spark_rapids_tpu.expr.cast import Cast
from spark_rapids_tpu.expr.strings import (Upper, Lower, Length, Substring,
                                           StartsWith, EndsWith, Contains, Like,
                                           Concat, Trim, StringReplace, InitCap)
from spark_rapids_tpu.expr.mathexprs import Round, Floor, Ceil, Log, Sqrt, Pow
from spark_rapids_tpu.expr.datetime import (Year, Month, DayOfMonth, DayOfWeek,
                                            DateAdd, DateDiff, LastDay, Quarter,
                                            Hour, Minute, Second)


def run(expr, table):
    b = ColumnarBatch.from_arrow(table)
    e = bind_references(expr, b.schema)
    return e.eval(EvalContext.from_batch(b)).to_vector().to_arrow(b.num_rows).to_pylist()


@pytest.fixture
def t():
    return pa.table({
        "a": pa.array([1, 2, None, -7, 100], type=pa.int32()),
        "b": pa.array([10, 0, 3, None, -3], type=pa.int64()),
        "s": pa.array(["Hello", None, "world", "Hello", ""]),
        "d": pa.array([0, 18000, None, 19000, -1], type=pa.date32()),
        "x": pa.array([1.5, -2.5, None, 3.456, float("nan")]),
        "ts": pa.array([0, 3_600_000_001, None, 86_399_000_000, -1_000_000],
                       type=pa.timestamp("us", tz="UTC")),
    })


def test_arithmetic_nulls_and_overflow(t):
    assert run(Add(col("a"), col("b")), t) == [11, 2, None, None, 97]
    assert run(Multiply(col("a"), lit(2)), t) == [2, 4, None, -14, 200]
    # int32 overflow wraps like Java
    big = pa.table({"v": pa.array([2**31 - 1], type=pa.int32())})
    assert run(Add(col("v"), lit(1)), big) == [-(2**31)]


def test_division_semantics(t):
    assert run(Divide(col("a"), col("b")), t) == [0.1, None, None, None,
                                                 pytest.approx(-100 / 3)]
    assert run(IntegralDivide(col("b"), lit(-3)), t) == [-3, 0, -1, None, 1]
    assert run(Remainder(col("a"), lit(3)), t) == [1, 2, None, -1, 1]  # Java sign
    assert run(Pmod(col("a"), lit(3)), t) == [1, 2, None, 2, 1]
    assert run(Remainder(col("b"), lit(0)), t) == [None] * 5


def test_comparisons_and_kleene(t):
    assert run(EqualTo(col("s"), lit("Hello")), t) == [True, None, False, True, False]
    assert run(EqualNullSafe(col("s"), lit("Hello")), t) == [True, False, False, True,
                                                            False]
    # NaN == NaN is TRUE in Spark; NaN > everything
    nan_t = pa.table({"x": pa.array([float("nan"), 1.0, float("inf")])})
    assert run(EqualTo(col("x"), col("x")), nan_t) == [True, True, True]
    assert run(GreaterThan(col("x"), lit(float("inf"))), nan_t) == [True, False, False]
    # Kleene: false AND null = false; true OR null = true
    kt = pa.table({"p": pa.array([True, False, None]),
                   "q": pa.array([None, None, None], type=pa.bool_())})
    assert run(And(col("p"), col("q")), kt) == [None, False, None]
    assert run(Or(col("p"), col("q")), kt) == [True, None, None]
    assert run(Not(col("p")), kt) == [False, True, None]


def test_in_expression(t):
    assert run(In(col("a"), [1, 2]), t) == [True, True, None, False, False]
    # null in list: non-matching rows become null
    assert run(In(col("a"), [1, 2, None]), t) == [True, True, None, None, None]


def test_null_expressions(t):
    assert run(IsNull(col("a")), t) == [False, False, True, False, False]
    assert run(IsNotNull(col("a")), t) == [True, True, False, True, True]
    assert run(Coalesce(col("a"), lit(99)), t) == [1, 2, 99, -7, 100]
    assert run(IsNaN(col("x")), t) == [False, False, False, False, True]
    assert run(NaNvl(col("x"), lit(0.0)), t) == [1.5, -2.5, None, 3.456, 0.0]


def test_conditional(t):
    assert run(If(LessThan(col("a"), lit(0)), lit("neg"), lit("pos")),
               t) == ["pos", "pos", "pos", "neg", "pos"]
    e = CaseWhen([(LessThan(col("a"), lit(0)), lit(-1)),
                  (GreaterThan(col("a"), lit(50)), lit(2))], lit(0))
    assert run(e, t) == [0, 0, 0, -1, 2]
    # null predicate takes else branch
    e2 = If(LessThan(col("a"), col("b")), lit(1), lit(0))
    assert run(e2, t) == [1, 0, 0, 0, 0]


def test_casts(t):
    assert run(Cast(col("a"), T.LONG), t) == [1, 2, None, -7, 100]
    assert run(Cast(col("a"), T.STRING), t) == ["1", "2", None, "-7", "100"]
    assert run(Cast(col("x"), T.INT), t) == [1, -2, None, 3, 0]  # NaN→0, trunc
    assert run(Cast(lit("  42 "), T.INT), t)[0] == 42
    assert run(Cast(lit("1.99"), T.INT), t)[0] == 1   # fractional truncates
    assert run(Cast(lit("abc"), T.INT), t)[0] is None
    assert run(Cast(lit("2147483648"), T.INT), t)[0] is None  # overflow → null
    assert run(Cast(lit("true"), T.BOOLEAN), t)[0] is True
    assert run(Cast(lit("2021-03-05"), T.DATE), t)[0].isoformat() == "2021-03-05"
    # long → int wraps like Java
    big = pa.table({"v": pa.array([2**31], type=pa.int64())})
    assert run(Cast(col("v"), T.INT), big) == [-(2**31)]
    # double clamp to long range
    bigd = pa.table({"v": pa.array([1e300, -1e300, float("nan")])})
    assert run(Cast(col("v"), T.LONG), bigd) == [2**63 - 1, -(2**63), 0]
    # decimal casts
    dec = run(Cast(col("x"), T.DecimalType(10, 1)), t)
    assert [str(v) if v is not None else None for v in dec] == \
        ["1.5", "-2.5", None, "3.5", None]


def test_string_functions(t):
    assert run(Upper(col("s")), t) == ["HELLO", None, "WORLD", "HELLO", ""]
    assert run(Lower(col("s")), t) == ["hello", None, "world", "hello", ""]
    assert run(Length(col("s")), t) == [5, None, 5, 5, 0]
    assert run(Substring(col("s"), lit(2), lit(3)), t) == ["ell", None, "orl", "ell", ""]
    assert run(Substring(col("s"), lit(-3), lit(2)), t) == ["ll", None, "rl", "ll", ""]
    assert run(StartsWith(col("s"), lit("He")), t) == [True, None, False, True, False]
    assert run(EndsWith(col("s"), lit("o")), t) == [True, None, False, True, False]
    assert run(Contains(col("s"), lit("ell")), t) == [True, None, False, True, False]
    assert run(Like(col("s"), lit("H_llo")), t) == [True, None, False, True, False]
    assert run(Like(col("s"), lit("%o%")), t) == [True, None, True, True, False]
    assert run(Concat(col("s"), lit("!")), t) == ["Hello!", None, "world!", "Hello!", "!"]
    assert run(Trim(lit("  hi  ")), t)[0] == "hi"
    assert run(StringReplace(col("s"), lit("l"), lit("L")), t) == \
        ["HeLLo", None, "worLd", "HeLLo", ""]
    assert run(InitCap(lit("hello world")), t)[0] == "Hello World"


def test_math(t):
    assert run(Round(col("x"), 0), t) == [2.0, -3.0, None, 3.0, pytest.approx(np.nan, nan_ok=True)]
    assert run(Floor(col("x")), t) == [1, -3, None, 3, 0]  # NaN → 0 per Java cast
    assert run(Ceil(col("x")), t) == [2, -2, None, 4, 0]
    assert run(Log(lit(-1.0)), t)[0] is None  # Spark null, not NaN
    assert run(Sqrt(lit(4.0)), t)[0] == 2.0
    assert run(Pow(lit(2.0), lit(10)), t)[0] == 1024.0


def test_datetime(t):
    assert run(Year(col("d")), t) == [1970, 2019, None, 2022, 1969]
    assert run(Month(col("d")), t) == [1, 4, None, 1, 12]
    assert run(DayOfMonth(col("d")), t) == [1, 14, None, 8, 31]
    assert run(DayOfWeek(col("d")), t) == [5, 1, None, 7, 4]
    assert run(Quarter(col("d")), t) == [1, 2, None, 1, 4]
    assert run(DateAdd(col("d"), lit(1)), t)[0].isoformat() == "1970-01-02"
    assert run(DateDiff(col("d"), col("d")), t) == [0, 0, None, 0, 0]
    assert run(LastDay(col("d")), t)[0].isoformat() == "1970-01-31"
    assert run(Hour(col("ts")), t) == [0, 1, None, 23, 23]
    assert run(Minute(col("ts")), t) == [0, 0, None, 59, 59]
    assert run(Second(col("ts")), t) == [0, 0, None, 59, 59]


def test_unary_and_abs(t):
    assert run(UnaryMinus(col("a")), t) == [-1, -2, None, 7, -100]
    assert run(Abs(col("a")), t) == [1, 2, None, 7, 100]
