"""Device parquet decode (stage one): thrift page parsing, RLE/bit-packed
hybrid, device bit-unpack + dictionary gather, per-column arrow fallback
(reference GpuParquetScan.scala:1235 device decode role)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu import config as CFG
from spark_rapids_tpu.io import parquet_native as PN
from spark_rapids_tpu.session import TpuSession


def mixed_table(n=4000, seed=1):
    r = np.random.default_rng(seed)
    return pa.table({
        "i": pa.array([None if v % 13 == 0 else int(v)
                       for v in r.integers(0, 300, n)], pa.int32()),
        "l": pa.array([int(v) for v in r.integers(-10**9, 10**9, n)],
                      pa.int64()),
        "d": pa.array([None if v < 0.05 else float(round(v * 100, 4))
                       for v in r.random(n)]),
        "f": pa.array([float(np.float32(v)) for v in r.normal(0, 5, n)],
                      pa.float32()),
        "s": pa.array([None if v % 17 == 0 else f"cat{v % 43}"
                       for v in r.integers(0, 1000, n)]),
    })


@pytest.fixture
def unc_file(tmp_path):
    t = mixed_table()
    p = tmp_path / "unc"
    p.mkdir()
    pq.write_table(t, p / "part-0.parquet", compression="NONE",
                   use_dictionary=True, data_page_size=16 << 10)
    return str(p), t


def test_row_group_device_roundtrip(unc_file):
    path, t = unc_file
    import os
    f = os.path.join(path, "part-0.parquet")
    schema = T.StructType.from_arrow(t.schema)
    out = PN.read_row_group_device(f, 0, schema).to_arrow()
    for name in t.column_names:
        assert out.column(name).to_pylist() == t.column(name).to_pylist(), name


def test_multi_page_and_row_groups(tmp_path):
    t = mixed_table(3000, seed=7)
    f = str(tmp_path / "multi.parquet")
    pq.write_table(t, f, compression="NONE", use_dictionary=True,
                   data_page_size=2 << 10, row_group_size=700)
    schema = T.StructType.from_arrow(t.schema)
    md = pq.ParquetFile(f).metadata
    outs = [PN.read_row_group_device(f, rg, schema).to_arrow()
            for rg in range(md.num_row_groups)]
    got = pa.concat_tables(outs)
    for name in t.column_names:
        assert got.column(name).to_pylist() == t.column(name).to_pylist(), name


def test_snappy_chunks_decode_on_device(tmp_path):
    """Stage 1.5: snappy page bodies decompress on host (arrow C codec) and
    the decode still runs on device; results identical to the source."""
    t = mixed_table(1000, seed=3)
    f = str(tmp_path / "snappy.parquet")
    pq.write_table(t, f, compression="SNAPPY", use_dictionary=True)
    schema = T.StructType.from_arrow(t.schema)
    # the chunk parser itself accepts the compressed chunk (no fallback)
    pages = PN.read_chunk_pages(f, 0, 0)
    assert pages.num_values == 1000
    out = PN.read_row_group_device(f, 0, schema).to_arrow()
    for name in t.column_names:
        assert out.column(name).to_pylist() == t.column(name).to_pylist(), name


@pytest.mark.parametrize("codec", ["GZIP", "ZSTD"])
def test_gzip_zstd_chunks_decode_on_device(tmp_path, codec):
    t = mixed_table(800, seed=6)
    f = str(tmp_path / f"{codec.lower()}.parquet")
    pq.write_table(t, f, compression=codec, use_dictionary=True)
    assert PN.read_chunk_pages(f, 0, 0).num_values == 800
    schema = T.StructType.from_arrow(t.schema)
    out = PN.read_row_group_device(f, 0, schema).to_arrow()
    for name in t.column_names:
        assert out.column(name).to_pylist() == t.column(name).to_pylist(), name


def test_unsupported_codec_falls_back_per_column(tmp_path):
    t = mixed_table(500, seed=4)
    f = str(tmp_path / "brotli.parquet")
    pq.write_table(t, f, compression="BROTLI", use_dictionary=True)
    with pytest.raises(NotImplementedError):
        PN.read_chunk_pages(f, 0, 0)
    schema = T.StructType.from_arrow(t.schema)
    out = PN.read_row_group_device(f, 0, schema).to_arrow()  # arrow path
    for name in t.column_names:
        assert out.column(name).to_pylist() == t.column(name).to_pylist(), name


def test_session_scan_uses_device_decode(unc_file):
    path, t = unc_file
    import spark_rapids_tpu.functions as F
    spark = TpuSession()
    got = (spark.read_parquet(path)
           .group_by(F.col("s"))
           .agg(F.count(F.col("i")).alias("c"),
                F.sum(F.col("d")).alias("sd"))
           .collect().to_pylist())
    exp = {}
    for s, i, d in zip(t.column("s").to_pylist(), t.column("i").to_pylist(),
                       t.column("d").to_pylist()):
        c, sd = exp.get(s, (0, 0.0))
        exp[s] = (c + (i is not None), sd + (d or 0.0))
    assert len(got) == len(exp)
    for r in got:
        c, sd = exp[r["s"]]
        assert r["c"] == c
        assert (r["sd"] or 0.0) == pytest.approx(sd, rel=1e-9)


def test_device_decode_conf_off_matches(unc_file):
    path, t = unc_file
    on = TpuSession({"spark.rapids.tpu.sql.parquet.deviceDecode.enabled":
                      "true"}).read_parquet(path).collect()
    off = TpuSession({CFG.PARQUET_DEVICE_DECODE.key: "false"}) \
        .read_parquet(path).collect()
    for name in t.column_names:
        assert on.column(name).to_pylist() == off.column(name).to_pylist()


def test_unpack_bits_widths():
    """Device bit-unpack against a numpy reference for every width 1..32."""
    import jax.numpy as jnp
    from spark_rapids_tpu.ops.parquet_decode import unpack_bits_device
    r = np.random.default_rng(0)
    for bw in [1, 2, 3, 5, 7, 8, 12, 16, 20, 24, 31, 32]:
        n = 256
        vals = r.integers(0, 1 << min(bw, 31), n, dtype=np.int64)
        bits = np.zeros(n * bw, dtype=np.uint8)
        for i, v in enumerate(vals):
            for b in range(bw):
                bits[i * bw + b] = (int(v) >> b) & 1
        packed = np.packbits(bits, bitorder="little")
        got = np.asarray(unpack_bits_device(
            jnp.asarray(packed), bw, n, 256))[:n]
        assert (got == vals.astype(np.int32)).all(), bw


def test_native_scanner_matches_python_parser(tmp_path, monkeypatch):
    """The C scanner (native/parquet_host.cpp) and the Python parser must
    produce identical ChunkPages structures — same pages, def levels, run
    segmentation, and dictionary."""
    from spark_rapids_tpu import native as N
    try:
        N.parquet_lib()  # the comparison is vacuous without the C library
    except N.NativeBuildError:
        pytest.skip("no native toolchain")
    t = mixed_table(3000, seed=7)
    f = str(tmp_path / "m.parquet")
    pq.write_table(t, f, compression="NONE", use_dictionary=True,
                   data_page_size=4096, row_group_size=1500)
    md = pq.ParquetFile(f).metadata

    def parse_all():
        out = []
        for rg in range(md.num_row_groups):
            for c in range(md.num_columns):
                try:
                    out.append(PN.read_chunk_pages(f, rg, c, md=md))
                except NotImplementedError:
                    out.append(None)
        return out

    native = parse_all()

    def boom(*a, **k):
        raise N.NativeBuildError("forced python fallback")
    monkeypatch.setattr(N, "scan_chunk_native", boom)
    python = parse_all()

    assert len(native) == len(python)
    for cn, cp in zip(native, python):
        assert (cn is None) == (cp is None)
        if cn is None:
            continue
        assert cn.physical_type == cp.physical_type
        assert cn.num_values == cp.num_values
        if isinstance(cn.dict_values, list):
            assert cn.dict_values == cp.dict_values
        else:
            assert (cn.dict_values == cp.dict_values).all()
        assert len(cn.index_segments) == len(cp.index_segments)
        for pn_, pp in zip(cn.index_segments, cp.index_segments):
            nv_n, dl_n, bw_n, pb_n, vo_n, segs_n = pn_
            nv_p, dl_p, bw_p, pb_p, vo_p, segs_p = pp
            assert nv_n == nv_p and bw_n == bw_p and vo_n == vo_p
            assert pb_n == pb_p
            assert (dl_n == dl_p).all()
            assert segs_n == segs_p


@pytest.mark.parametrize("codec", ["NONE", "snappy"])
def test_v2_data_pages_device_path(tmp_path, codec):
    """DATA_PAGE_V2 (data_page_version='2.0'): uncompressed level prefix +
    optionally-compressed values section, def levels without the v1 length
    prefix — decodes on the device path, nulls included."""
    t = mixed_table(3000, seed=11)
    f = str(tmp_path / "v2.parquet")
    pq.write_table(t, f, compression=codec, use_dictionary=True,
                   data_page_version="2.0", data_page_size=4 << 10)
    schema = T.StructType.from_arrow(t.schema)
    md = pq.ParquetFile(f).metadata
    outs = [PN.read_row_group_device(f, rg, schema).to_arrow()
            for rg in range(md.num_row_groups)]
    got = pa.concat_tables(outs)
    for name in t.column_names:
        assert got.column(name).to_pylist() == t.column(name).to_pylist(), name
