"""Window exec tests — host-oracle equivalence across frames and functions
(reference WindowFunctionSuite / window_function_test.py patterns, SURVEY.md §4)."""

import numpy as np
import pyarrow as pa
import pytest

from conftest import make_table

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.expr.core import Alias, col, lit
from spark_rapids_tpu.expr.aggregates import Average, Count, Max, Min, Sum
from spark_rapids_tpu.expr.windows import (
    DEFAULT_FRAME, FULL_FRAME, DenseRank, Lag, Lead, Rank, RowNumber,
    WindowExpression, WindowFrame, WindowSpec,
)
from spark_rapids_tpu.plan import ScanNode, TpuOverrides, WindowNode, explain_plan
from spark_rapids_tpu.plan.transitions import execute_hybrid
from spark_rapids_tpu.exec.base import TpuExec
from test_plan import norm, split_table


def win_table(n=400, seed=11):
    """Order key is UNIQUE: ROWS-frame results over order-key ties depend on the
    physical tie order, which legitimately differs between the host path and the
    post-exchange device path (Spark is equally nondeterministic there). Tie
    semantics (RANGE frames, rank vs dense_rank) are covered by the deterministic
    single-partition tests below."""
    r = np.random.default_rng(seed)
    grp = r.integers(0, 8, n)
    ordv = r.permutation(n)
    vals = r.normal(0, 10, n)
    vmask = r.random(n) < 0.1
    return pa.table({
        "g": pa.array([int(v) for v in grp], pa.int64()),
        "o": pa.array([int(v) for v in ordv], pa.int32()),
        "v": pa.array([None if m else float(v) for v, m in zip(vals, vmask)],
                      pa.float64()),
    })


def spec(order=True, frame=DEFAULT_FRAME):
    return WindowSpec(
        (col("g"),),
        ((col("o"), True, True),) if order else (),
        frame)


def check(node, approx=True):
    host = node.collect_host()
    hybrid = TpuOverrides(RapidsConf()).apply(node)
    dev = execute_hybrid(hybrid)
    assert norm(host) == norm(dev) if not approx else True
    if approx:
        h, d = norm(host), norm(dev)
        assert len(h) == len(d)
        import math
        for hr, dr in zip(h, d):
            for hv, dv in zip(hr, dr):
                if isinstance(hv, float) and isinstance(dv, float):
                    if math.isnan(hv):
                        assert math.isnan(dv), (hr, dr)
                    else:
                        assert dv == pytest.approx(hv, rel=1e-9, abs=1e-9), (hr, dr)
                else:
                    assert hv == dv, (hr, dr)
    return hybrid


def test_ranking_functions():
    t = win_table()
    node = WindowNode([
        Alias(WindowExpression(RowNumber(), spec()), "rn"),
        Alias(WindowExpression(Rank(), spec()), "rk"),
        Alias(WindowExpression(DenseRank(), spec()), "dr"),
    ], ScanNode(split_table(t, 3)))
    hybrid = check(node)
    assert isinstance(hybrid, TpuExec)


def test_cumulative_and_range_aggregates():
    t = win_table()
    node = WindowNode([
        Alias(WindowExpression(Sum(col("v")), spec()), "cum_sum_range"),
        Alias(WindowExpression(Count(col("v")),
                               spec(frame=WindowFrame("rows", None, 0))),
              "cum_cnt_rows"),
        Alias(WindowExpression(Min(col("v")), spec()), "cum_min"),
        Alias(WindowExpression(Max(col("v")), spec()), "cum_max"),
    ], ScanNode(split_table(t, 2)))
    check(node)


def test_full_partition_frame():
    t = win_table()
    node = WindowNode([
        Alias(WindowExpression(Sum(col("v")), spec(frame=FULL_FRAME)), "tot"),
        Alias(WindowExpression(Average(col("v")), spec(frame=FULL_FRAME)), "avg"),
        Alias(WindowExpression(Count(None), spec(frame=FULL_FRAME)), "n"),
    ], ScanNode(split_table(t, 2)))
    check(node)


def test_sliding_rows_frame():
    t = win_table()
    node = WindowNode([
        Alias(WindowExpression(Sum(col("v")),
                               spec(frame=WindowFrame("rows", 2, 2))), "s5"),
        Alias(WindowExpression(Average(col("v")),
                               spec(frame=WindowFrame("rows", 3, 0))), "a4"),
        Alias(WindowExpression(Count(col("v")),
                               spec(frame=WindowFrame("rows", 0, 2))), "c3"),
    ], ScanNode(split_table(t, 2)))
    check(node)


def test_lead_lag():
    t = win_table()
    node = WindowNode([
        Alias(WindowExpression(Lead(col("v"), 2), spec()), "ld"),
        Alias(WindowExpression(Lag(col("v"), 1), spec()), "lg"),
        Alias(WindowExpression(Lag(col("o"), 3, default=-1), spec()), "lgd"),
    ], ScanNode(split_table(t, 2)))
    check(node)


def test_nan_min_max_window():
    t = pa.table({
        "g": pa.array([1, 1, 1, 2, 2], pa.int64()),
        "o": pa.array([1, 2, 3, 1, 2], pa.int32()),
        "v": pa.array([1.0, float("nan"), 2.0, float("nan"), float("nan")],
                      pa.float64()),
    })
    node = WindowNode([
        Alias(WindowExpression(Max(col("v")), spec(frame=FULL_FRAME)), "mx"),
        Alias(WindowExpression(Min(col("v")), spec(frame=FULL_FRAME)), "mn"),
    ], ScanNode([t]))
    host = node.collect_host()
    dev = execute_hybrid(TpuOverrides(RapidsConf()).apply(node))
    import math
    # group 1: max=NaN (NaN largest), min=1.0; group 2: all NaN → both NaN
    for out in (host, dev):
        rows = {g: (mx, mn) for g, mx, mn in zip(
            out["g"].to_pylist(), out["mx"].to_pylist(), out["mn"].to_pylist())}
        assert math.isnan(rows[1][0]) and rows[1][1] == 1.0
        assert math.isnan(rows[2][0]) and math.isnan(rows[2][1])


def test_sliding_min_max_on_device():
    """Sliding rows min/max runs on device (sparse-table range queries,
    ops/windowing.py — VERDICT r1 item #4)."""
    t = win_table(200)
    node = WindowNode([
        Alias(WindowExpression(Min(col("v")),
                               spec(frame=WindowFrame("rows", 2, 2))), "m"),
        Alias(WindowExpression(Max(col("v")),
                               spec(frame=WindowFrame("rows", 3, 1))), "x"),
        Alias(WindowExpression(Min(col("o")),
                               spec(frame=WindowFrame("rows", 0, 4))), "mi"),
        Alias(WindowExpression(Max(col("v")),
                               spec(frame=WindowFrame("rows", 2, None))), "xu"),
    ], ScanNode(split_table(t, 2)))
    hybrid = check(node)
    assert isinstance(hybrid, TpuExec), explain_plan(node)


def test_sliding_min_max_nan_and_empty_frames():
    t = pa.table({
        "g": pa.array([1, 1, 1, 1, 1], pa.int64()),
        "o": pa.array([1, 2, 3, 4, 5], pa.int32()),
        "v": pa.array([1.0, float("nan"), None, 4.0, 2.0], pa.float64()),
    })
    node = WindowNode([
        Alias(WindowExpression(Max(col("v")),
                               spec(frame=WindowFrame("rows", 1, 1))), "mx"),
        Alias(WindowExpression(Min(col("v")),
                               spec(frame=WindowFrame("rows", 1, 1))), "mn"),
    ], ScanNode([t]))
    hybrid = check(node)
    assert isinstance(hybrid, TpuExec), explain_plan(node)


def test_range_frame_bounded_int_key():
    """RANGE BETWEEN k PRECEDING AND k FOLLOWING over an int order key, asc and
    desc, with nulls in the VALUE column (VERDICT r1 item #4)."""
    r = np.random.default_rng(5)
    n = 300
    t = pa.table({
        "g": pa.array([int(v) for v in r.integers(0, 6, n)], pa.int64()),
        "o": pa.array([int(v) for v in r.integers(0, 40, n)], pa.int32()),
        "v": pa.array([None if m < 0.1 else float(x) for x, m in
                       zip(r.normal(0, 10, n), r.random(n))], pa.float64()),
    })
    for asc in (True, False):
        sp = WindowSpec((col("g"),), ((col("o"), asc, True),),
                        WindowFrame("range", 3, 5))
        node = WindowNode([
            Alias(WindowExpression(Sum(col("v")), sp), "s"),
            Alias(WindowExpression(Count(col("v")), sp), "c"),
            Alias(WindowExpression(Min(col("v")), sp), "mn"),
            Alias(WindowExpression(Max(col("v")), sp), "mx"),
            Alias(WindowExpression(Average(col("v")), sp), "av"),
        ], ScanNode(split_table(t, 2)))
        hybrid = check(node)
        assert isinstance(hybrid, TpuExec), explain_plan(node)


def test_range_frame_null_order_keys():
    """Null order values form their own peer group on bounded sides (Spark
    RangeBoundOrdering: null±offset compares equal only to nulls)."""
    t = pa.table({
        "g": pa.array([1, 1, 1, 1, 1, 2, 2], pa.int64()),
        "o": pa.array([None, None, 1, 3, 9, None, 5], pa.int32()),
        "v": pa.array([1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0], pa.float64()),
    })
    for nf in (True, False):
        sp = WindowSpec((col("g"),), ((col("o"), True, nf),),
                        WindowFrame("range", 2, 2))
        node = WindowNode([
            Alias(WindowExpression(Sum(col("v")), sp), "s"),
            Alias(WindowExpression(Count(col("v")), sp), "c"),
        ], ScanNode([t]))
        hybrid = check(node)
        assert isinstance(hybrid, TpuExec), explain_plan(node)


def test_range_frame_one_sided_and_unbounded():
    r = np.random.default_rng(9)
    n = 120
    t = pa.table({
        "g": pa.array([int(v) for v in r.integers(0, 4, n)], pa.int64()),
        "o": pa.array([int(v) for v in r.integers(0, 30, n)], pa.int32()),
        "v": pa.array([float(x) for x in r.normal(0, 3, n)], pa.float64()),
    })
    sp1 = WindowSpec((col("g"),), ((col("o"), True, True),),
                     WindowFrame("range", None, 4))   # unbounded → +4
    sp2 = WindowSpec((col("g"),), ((col("o"), True, True),),
                     WindowFrame("range", 2, None))   # -2 → unbounded
    sp3 = WindowSpec((col("g"),), ((col("o"), True, True),),
                     WindowFrame("range", 0, 0))      # peers only
    node = WindowNode([
        Alias(WindowExpression(Sum(col("v")), sp1), "s1"),
        Alias(WindowExpression(Sum(col("v")), sp2), "s2"),
        Alias(WindowExpression(Sum(col("v")), sp3), "s3"),
    ], ScanNode(split_table(t, 3)))
    hybrid = check(node)
    assert isinstance(hybrid, TpuExec), explain_plan(node)


def test_range_frame_float_key_with_nan():
    t = pa.table({
        "g": pa.array([1, 1, 1, 1, 1], pa.int64()),
        "o": pa.array([1.0, 2.5, float("nan"), float("nan"), 9.0],
                      pa.float64()),
        "v": pa.array([1.0, 2.0, 4.0, 8.0, 16.0], pa.float64()),
    })
    sp = WindowSpec((col("g"),), ((col("o"), True, True),),
                    WindowFrame("range", 2, 2))
    node = WindowNode([
        Alias(WindowExpression(Sum(col("v")), sp), "s"),
    ], ScanNode([t]))
    hybrid = check(node)
    assert isinstance(hybrid, TpuExec), explain_plan(node)


def test_range_frame_multi_order_key_falls_back():
    t = win_table(40)
    sp = WindowSpec((col("g"),),
                    ((col("o"), True, True), (col("v"), True, True)),
                    WindowFrame("range", 1, 1))
    node = WindowNode([
        Alias(WindowExpression(Sum(col("v")), sp), "s"),
    ], ScanNode([t]))
    txt = explain_plan(node)
    assert "one order key" in txt


def test_window_no_order_by_full_frame():
    t = win_table(100)
    node = WindowNode([
        Alias(WindowExpression(Sum(col("v")), spec(order=False,
                                                   frame=FULL_FRAME)), "s"),
    ], ScanNode(split_table(t, 2)))
    check(node)


def test_range_frame_ties_deterministic():
    """RANGE unbounded→current includes the whole tie group; single partition so
    tie order is deterministic for the rank functions too."""
    t = pa.table({
        "g": pa.array([1, 1, 1, 1, 2], pa.int64()),
        "o": pa.array([1, 1, 2, 2, 1], pa.int32()),
        "v": pa.array([10.0, 20.0, 30.0, 40.0, 5.0], pa.float64()),
    })
    node = WindowNode([
        Alias(WindowExpression(Sum(col("v")), spec()), "s"),
        Alias(WindowExpression(Rank(), spec()), "rk"),
        Alias(WindowExpression(DenseRank(), spec()), "dr"),
    ], ScanNode([t]))
    host = node.collect_host()
    dev = execute_hybrid(TpuOverrides(RapidsConf()).apply(node))
    for out in (host, dev):
        rows = sorted(zip(out["g"].to_pylist(), out["o"].to_pylist(),
                          out["v"].to_pylist(), out["s"].to_pylist(),
                          out["rk"].to_pylist(), out["dr"].to_pylist()))
        # RANGE sum includes ties: both o=1 rows see 30; both o=2 rows see 100
        assert rows == [
            (1, 1, 10.0, 30.0, 1, 1), (1, 1, 20.0, 30.0, 1, 1),
            (1, 2, 30.0, 100.0, 3, 2), (1, 2, 40.0, 100.0, 3, 2),
            (2, 1, 5.0, 5.0, 1, 1)]


def test_window_min_max_bool_and_string():
    t = pa.table({
        "g": pa.array([1, 1, 2, 2], pa.int64()),
        "o": pa.array([1, 2, 1, 2], pa.int32()),
        "b": pa.array([True, False, None, True]),
        "s": pa.array(["pear", "apple", "kiwi", None]),
    })
    node = WindowNode([
        Alias(WindowExpression(Min(col("b")), spec(frame=FULL_FRAME)), "bmin"),
        Alias(WindowExpression(Max(col("s")), spec(frame=FULL_FRAME)), "smax"),
        Alias(WindowExpression(Min(col("s")), spec(frame=FULL_FRAME)), "smin"),
    ], ScanNode([t]))
    host = node.collect_host()
    dev = execute_hybrid(TpuOverrides(RapidsConf()).apply(node))
    for out in (host, dev):
        rows = sorted(zip(out["g"].to_pylist(), out["bmin"].to_pylist(),
                          out["smax"].to_pylist(), out["smin"].to_pylist()))
        assert rows == [(1, False, "pear", "apple"), (1, False, "pear", "apple"),
                        (2, True, "kiwi", "kiwi"), (2, True, "kiwi", "kiwi")]


def test_lead_string_default_falls_back():
    t = win_table(30)
    st = pa.table({"g": t.column("g"), "o": t.column("o"),
                   "s": pa.array([f"v{i%5}" for i in range(30)])})
    node = WindowNode([
        Alias(WindowExpression(Lead(col("s"), 1, default="zzz"), spec()), "ld"),
    ], ScanNode([st]))
    txt = explain_plan(node)
    assert "non-null default" in txt
    out = execute_hybrid(TpuOverrides(RapidsConf()).apply(node))  # host path
    assert out.num_rows == 30
