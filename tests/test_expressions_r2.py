"""Round-2 expression surface: bitwise, least/greatest, string functions,
regexp, datetime parse/format, hash/ids, decimal plumbing, complex-type fusion,
variance aggregates — every device result checked against the host oracle
(reference integration_tests asserts.py assert_gpu_and_cpu_are_equal pattern)."""

import datetime
import math

import numpy as np
import pyarrow as pa
import pytest

from conftest import make_table

import spark_rapids_tpu.functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch
from spark_rapids_tpu.expr.core import EvalContext, bind_references, col, lit
from spark_rapids_tpu.plan.host_eval import eval_host
from spark_rapids_tpu.session import TpuSession


def run_device(expr, table):
    b = ColumnarBatch.from_arrow(table)
    e = bind_references(expr, b.schema)
    return (e.eval(EvalContext.from_batch(b)).to_vector()
            .to_arrow(b.num_rows).to_pylist())


def run_host(expr, table):
    schema = T.StructType.from_arrow(table.schema)
    return eval_host(bind_references(expr, schema), table).to_arrow().to_pylist()


def check(expr, table, approx=False):
    got = run_device(expr, table)
    exp = run_host(expr, table)
    if approx:
        for g, e in zip(got, exp):
            if g is None or e is None:
                assert g == e, (got, exp)
            elif isinstance(e, float) and math.isnan(e):
                assert math.isnan(g)
            else:
                assert g == pytest.approx(e, rel=1e-12), (got, exp)
    else:
        assert len(got) == len(exp), (got, exp)
        for g, e in zip(got, exp):
            if isinstance(e, float) and math.isnan(e):
                assert isinstance(g, float) and math.isnan(g), (got, exp)
            else:
                assert g == e, (got, exp)
    return got


@pytest.fixture
def t():
    return pa.table({
        "a": pa.array([1, -2, None, 7, 0], type=pa.int32()),
        "b": pa.array([3, 65, -1, None, 33], type=pa.int32()),
        "l": pa.array([2**40, -3, None, 1, -2**40], type=pa.int64()),
        "x": pa.array([1.5, -2.5, None, float("nan"), 0.5]),
        "y": pa.array([2.0, None, 3.0, 1.0, -0.5]),
        "s": pa.array(["hello world", "a,b,c", None, "ababab", ""]),
        "w": pa.array(["apple", "kiwi", "fig", None, "apple"]),
        "d": pa.array([0, 18262, 18291, None, 59], type=pa.date32()),
        "sec": pa.array([0, 86399, None, 1600000000, -1], type=pa.int64()),
        "ds": pa.array(["1970-01-01 00:00:00", "2020-01-02 03:04:05",
                        None, "not a date", "2001-12-31 23:59:59"]),
    })


# -- bitwise -----------------------------------------------------------------

def test_bitwise(t):
    check(F._A.BitwiseAnd(col("a"), col("b")), t)
    check(F._A.BitwiseOr(col("a"), col("b")), t)
    check(F._A.BitwiseXor(col("a"), col("b")), t)
    check(F.bitwise_not(col("a")), t)


def test_shifts(t):
    # shift of 65 on int32 masks to 1 (Java semantics)
    check(F.shiftleft(col("a"), 1), t)
    check(F._A.ShiftLeft(col("a"), col("b")), t)
    check(F.shiftright(col("l"), 3), t)
    check(F.shiftrightunsigned(col("a"), 2), t)
    check(F.shiftrightunsigned(col("l"), 7), t)


def test_least_greatest(t):
    # skip-null semantics + NaN greatest
    check(F.least(col("x"), col("y")), t)
    check(F.greatest(col("x"), col("y")), t)
    check(F.least(col("a"), col("b")), t)
    check(F.greatest(col("a"), col("b"), F.lit(5)), t)


def test_math_extras(t):
    y = pa.table({"y": pa.array([0.5, -0.25, None, 1.0, 2.5])})
    for fn in (F.sinh, F.cosh, F.tanh, F.expm1, F.rint):
        check(fn(col("y")), y, approx=True)


# -- strings -----------------------------------------------------------------

def test_concat_ws(t):
    check(F.concat_ws("-", col("s"), col("w")), t)
    check(F.concat_ws(",", col("w")), t)


def test_pad_repeat(t):
    check(F.lpad(col("w"), 8, "*"), t)
    check(F.rpad(col("w"), 3, "_"), t)
    check(F.repeat(col("w"), 2), t)


def test_locate_substring_index(t):
    check(F.locate("b", col("s")), t)
    check(F.locate("a", col("s"), 2), t)
    check(F.instr(col("s"), "world"), t)
    check(F.substring_index(col("s"), ",", 2), t)
    check(F.substring_index(col("s"), "b", -1), t)


def test_translate_find_in_set(t):
    check(F.translate(col("s"), "abc", "xy"), t)
    check(F.find_in_set(col("w"), "fig,apple,kiwi"), t)


def test_regexp(t):
    check(F.regexp_replace(col("s"), "[aeiou]", "#"), t)
    check(F.regexp_replace(col("s"), "(a)(b)", "$2$1"), t)
    check(F.regexp_extract(col("s"), r"(\w+) (\w+)", 2), t)
    check(F.regexp_extract(col("s"), r"(z)x?", 1), t)


# -- datetime ----------------------------------------------------------------

def test_unix_timestamp_roundtrip(t):
    check(F.unix_timestamp(col("ds")), t)
    check(F.unix_timestamp(col("d")), t)
    check(F.to_unix_timestamp(col("ds"), "yyyy-MM-dd HH:mm:ss"), t)
    check(F.from_unixtime(col("sec")), t)
    check(F.from_unixtime(col("sec"), "yyyy/MM/dd"), t)


def test_date_format_trunc(t):
    check(F.date_format(col("d"), "yyyy-MM-dd"), t)
    check(F.trunc(col("d"), "year"), t)
    check(F.trunc(col("d"), "month"), t)
    check(F.trunc(col("d"), "quarter"), t)
    check(F.trunc(col("d"), "week"), t)


def test_add_months_between(t):
    check(F.add_months(col("d"), 1), t)
    check(F.add_months(col("d"), -13), t)
    check(F.date_sub(col("d"), 40), t)
    check(F.months_between(col("d"), F.cast(F.lit(59), T.DATE)), t,
          approx=True)


# -- hash / ids --------------------------------------------------------------

def test_murmur3_hash_expression(t):
    check(F.hash(col("a")), t)
    check(F.hash(col("l")), t)
    check(F.hash(col("w")), t)
    check(F.hash(col("a"), col("w"), col("x")), t)


def _mm3_mixK1(k1):
    M = 0xFFFFFFFF
    k1 = (k1 * 0xCC9E2D51) & M
    k1 = ((k1 << 15) | (k1 >> 17)) & M
    return (k1 * 0x1B873593) & M


def _mm3_mixH1(h1, k1):
    M = 0xFFFFFFFF
    h1 ^= k1
    h1 = ((h1 << 13) | (h1 >> 19)) & M
    return (h1 * 5 + 0xE6546B64) & M


def _mm3_fmix(h1, length):
    M = 0xFFFFFFFF
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & M
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & M
    h1 ^= h1 >> 16
    return h1 - 2**32 if h1 >= 2**31 else h1


def _spark_hash_int(v, seed=42):
    return _mm3_fmix(_mm3_mixH1(seed, _mm3_mixK1(v & 0xFFFFFFFF)), 4)


def _spark_hash_long(v, seed=42):
    h1 = _mm3_mixH1(seed, _mm3_mixK1(v & 0xFFFFFFFF))
    h1 = _mm3_mixH1(h1, _mm3_mixK1((v >> 32) & 0xFFFFFFFF))
    return _mm3_fmix(h1, 8)


def _spark_hash_bytes(bs, seed=42):
    """Spark Murmur3_x86_32.hashUnsafeBytes: 4-byte LE blocks, then each tail
    byte SIGN-EXTENDED and mixed individually (Spark's documented divergence
    from standard murmur3's lumped tail)."""
    h1 = seed
    n = len(bs) // 4 * 4
    for i in range(0, n, 4):
        h1 = _mm3_mixH1(h1, _mm3_mixK1(int.from_bytes(bs[i:i + 4], "little")))
    for i in range(n, len(bs)):
        b = bs[i] - 256 if bs[i] >= 128 else bs[i]
        h1 = _mm3_mixH1(h1, _mm3_mixK1(b & 0xFFFFFFFF))
    return _mm3_fmix(h1, len(bs))


def test_murmur3_spec_oracle_self_check():
    """The oracle above is validated against PUBLIC murmur3_x86_32 vectors
    (standard lumped-tail variant shares the block/fmix core)."""
    def std(bs, seed=0):
        h1 = seed
        n = len(bs) // 4 * 4
        for i in range(0, n, 4):
            h1 = _mm3_mixH1(h1, _mm3_mixK1(int.from_bytes(bs[i:i+4], "little")))
        k1 = 0
        for i, b in enumerate(bs[n:]):
            k1 ^= b << (8 * i)
        if len(bs) > n:
            h1 ^= _mm3_mixK1(k1)
        return _mm3_fmix(h1, len(bs))
    assert std(b"foo") == -156908512
    assert std(b"hello") == 613153351
    assert std(b"") == 0


def test_murmur3_known_vectors(t):
    """Device hash() checked against an INDEPENDENT spec-derived Murmur3
    oracle (not the module's own host implementation — VERDICT r1 weak #2)."""
    tt = pa.table({"i": pa.array([42, -1, 0, 2**31 - 1], type=pa.int32()),
                   "l": pa.array([42, -1, 2**40, -2**40], type=pa.int64()),
                   "s": pa.array(["abc", "", "hello world", "ab"])})
    assert run_device(F.hash(col("i")), tt) == \
        [_spark_hash_int(v) for v in [42, -1, 0, 2**31 - 1]]
    assert run_device(F.hash(col("l")), tt) == \
        [_spark_hash_long(v) for v in [42, -1, 2**40, -2**40]]
    assert run_device(F.hash(col("s")), tt) == \
        [_spark_hash_bytes(s.encode()) for s in
         ["abc", "", "hello world", "ab"]]
    # chained multi-column: each column's hash seeds the next
    got = run_device(F.hash(col("i"), col("s")), tt)
    exp = [_spark_hash_bytes(s.encode(), seed=_spark_hash_int(v) & 0xFFFFFFFF)
           for v, s in zip([42, -1, 0, 2**31 - 1],
                           ["abc", "", "hello world", "ab"])]
    assert got == exp


def test_partition_ids_and_monotonic_id():
    spark = TpuSession()
    t_ = pa.table({"v": pa.array(range(100))})
    df = spark.create_dataframe(t_, num_partitions=4).select(
        F.col("v"), F.spark_partition_id().alias("p"),
        F.monotonically_increasing_id().alias("mid"))
    out = df.collect()
    pids = set(out.column("p").to_pylist())
    assert pids == {0, 1, 2, 3}
    mids = out.column("mid").to_pylist()
    assert len(set(mids)) == 100  # unique across partitions
    for p, m in zip(out.column("p").to_pylist(), mids):
        assert (m >> 33) == p


def test_rand_uniform():
    spark = TpuSession()
    t_ = pa.table({"v": pa.array(range(1000))})
    out = (spark.create_dataframe(t_, num_partitions=2)
           .select(F.rand(7).alias("r")).collect())
    vals = out.column("r").to_pylist()
    assert all(0.0 <= v < 1.0 for v in vals)
    assert 0.4 < float(np.mean(vals)) < 0.6


# -- decimal plumbing --------------------------------------------------------

def test_decimal_check_overflow():
    from decimal import Decimal
    from spark_rapids_tpu.expr.decimalexprs import CheckOverflow, UnscaledValue
    tt = pa.table({"dec": pa.array(
        [None, Decimal("12.34"), Decimal("-999.99"), Decimal("1000.00")],
        type=pa.decimal128(9, 2))})
    check(UnscaledValue(col("dec")), tt)
    # precision 4, scale 2 → |unscaled| must stay below 10^4: 1000.00 nulls out
    e2 = CheckOverflow(col("dec"), T.DecimalType(4, 2))
    assert run_device(e2, tt) == run_host(e2, tt)


def test_make_decimal_roundtrip():
    from spark_rapids_tpu.expr.decimalexprs import MakeDecimal
    tt = pa.table({"v": pa.array([123, None, -450, 10**10], type=pa.int64())})
    e = MakeDecimal(col("v"), 9, 2)
    assert run_device(e, tt) == run_host(e, tt)


# -- complex types (fused) ---------------------------------------------------

def test_struct_fusion(t):
    e = F.get_field(F.struct("u", col("a"), "v", col("w")), "v")
    check(e, t)
    e2 = F.get_field(F.struct("u", col("a"), "v", col("w")), "u")
    check(e2, t)


def test_array_fusion(t):
    check(F.element_at0(F.array(col("a"), col("b")), 1), t)
    check(F.element_at0(F.array(col("a"), col("b")), 5), t)  # out of bounds
    # column index multiplexes
    idx_t = pa.table({"a": pa.array([10, 20, 30], type=pa.int32()),
                      "b": pa.array([1, 2, None], type=pa.int32()),
                      "i": pa.array([0, 1, 0], type=pa.int32())})
    check(F.element_at0(F.array(col("a"), col("b")), col("i")), idx_t)
    check(F.size(F.array(col("a"), col("b"), F.lit(1))), t)


def test_complex_fallback_pins_host(t):
    """A projection ENDING in a struct has no device form: planner must pin it
    to host, and the session must still produce the right answer."""
    spark = TpuSession()
    df = spark.create_dataframe(t).select(
        F.struct("u", F.col("a"), "v", F.col("w")).alias("st"))
    from spark_rapids_tpu.plan.overrides import explain_plan
    txt = explain_plan(df._plan, spark.conf)
    assert "will run on TPU" not in txt.splitlines()[0] or "struct" in txt
    out = df.collect()
    assert out.column("st").to_pylist()[0] == {"u": 1, "v": "apple"}


# -- aggregates --------------------------------------------------------------

def test_variance_family_session():
    spark = TpuSession()
    r = np.random.default_rng(3)
    tt = pa.table({
        "k": pa.array([int(v) for v in r.integers(0, 5, 400)]),
        "v": pa.array([None if i % 11 == 0 else float(x)
                       for i, x in enumerate(r.normal(0, 3, 400))]),
    })
    df = (spark.create_dataframe(tt, num_partitions=3)
          .group_by(F.col("k"))
          .agg(F.var_pop(F.col("v")).alias("vp"),
               F.variance(F.col("v")).alias("vs"),
               F.stddev_pop(F.col("v")).alias("sp"),
               F.stddev(F.col("v")).alias("ss"),
               F.last(F.col("v"), ignore_nulls=True).alias("lst")))
    got = {r_["k"]: r_ for r_ in df.collect().to_pylist()}
    import statistics
    groups = {}
    for k, v in zip(tt.column("k").to_pylist(), tt.column("v").to_pylist()):
        groups.setdefault(k, []).append(v)
    for k, vs in groups.items():
        xs = [v for v in vs if v is not None]
        assert got[k]["vp"] == pytest.approx(statistics.pvariance(xs), rel=1e-9)
        assert got[k]["vs"] == pytest.approx(statistics.variance(xs), rel=1e-9)
        assert got[k]["sp"] == pytest.approx(statistics.pstdev(xs), rel=1e-9)
        assert got[k]["ss"] == pytest.approx(statistics.stdev(xs), rel=1e-9)
        last_nn = [v for v in vs if v is not None][-1]
        assert got[k]["lst"] == pytest.approx(last_nn)


# -- fallback tagging --------------------------------------------------------

def test_unsupported_format_falls_back():
    """A datetime format outside the device subset must tag will_not_work, not
    crash — the plan falls back to host and still answers."""
    spark = TpuSession()
    tt = pa.table({"d": pa.array([0, 18262], type=pa.date32())})
    df = spark.create_dataframe(tt).select(
        F.date_format(F.col("d"), "QQQ w").alias("q"))  # unsupported tokens
    from spark_rapids_tpu.plan.overrides import explain_plan
    txt = explain_plan(df._plan, spark.conf)
    assert "cannot run" in txt or "will run on host" in txt.lower() or \
        "not" in txt.lower()


# -- round-2b surface: Md5, Cot, Logarithm, ElementAt, ArrayContains, etc. --

def test_md5(t):
    import hashlib
    got = run_device(F.md5(col("w")), t)
    for g, s in zip(got, t.column("w").to_pylist()):
        if s is None:
            assert g is None
        else:
            assert g == hashlib.md5(s.encode()).hexdigest()
    check(F.md5(col("s")), t)


def test_cot_logarithm(t):
    y = pa.table({"y": pa.array([0.5, -0.25, None, 1.0, 2.5])})
    check(F.cot(col("y")), y, approx=True)
    check(F.log(2.0, col("y")), y, approx=True)   # neg → null
    check(F.log(col("y")), y, approx=True)


def test_unary_positive(t):
    from spark_rapids_tpu.expr.arithmetic import UnaryPositive
    check(UnaryPositive(col("a")), t)


def test_at_least_n_non_nulls(t):
    from spark_rapids_tpu.expr.nullexprs import AtLeastNNonNulls
    for n in (1, 2, 3):
        check(AtLeastNNonNulls(n, col("a"), col("x"), col("s")), t)


def test_element_at_fused(t):
    check(F.element_at(F.array(col("a"), col("b")), 1), t)
    check(F.element_at(F.array(col("a"), col("b")), 2), t)
    check(F.element_at(F.array(col("a"), col("b")), -1), t)   # from end
    check(F.element_at(F.array(col("a"), col("b")), 5), t)    # out of range
    idx_t = pa.table({"a": pa.array([10, 20, 30], type=pa.int32()),
                      "b": pa.array([1, 2, None], type=pa.int32()),
                      "i": pa.array([1, -1, 0], type=pa.int32())})
    check(F.element_at(F.array(col("a"), col("b")), col("i")), idx_t)


def test_array_contains_fused(t):
    check(F.array_contains(F.array(col("a"), col("b")), 7), t)
    check(F.array_contains(F.array(col("a"), col("b")), col("a")), t)
    # null-element semantics: absent + null in array → null
    nt = pa.table({"a": pa.array([1, None, 3], pa.int32()),
                   "b": pa.array([9, 9, 9], pa.int32())})
    check(F.array_contains(F.array(col("a"), col("b")), 1), nt)


def test_lag_registered_on_device():
    """WX.Lag was missing from the rule registry (api_validation caught it)."""
    from spark_rapids_tpu.plan.overrides import REGISTRY
    from spark_rapids_tpu.expr.windows import Lag
    assert REGISTRY.lookup_expr(Lag(col("a"), 1)) is not None


def test_fused_element_at_through_planner():
    """Code review r2: the fused paths must be reachable through the PLANNER
    (tag_create whitelist), not only via direct eval."""
    from spark_rapids_tpu.plan.overrides import explain_plan
    spark = TpuSession()
    tt = pa.table({"a": pa.array([1, 2, 3], pa.int32()),
                   "b": pa.array([9, None, 7], pa.int32())})
    df = spark.create_dataframe(tt).select(
        F.element_at(F.array(F.col("a"), F.col("b")), -1).alias("e"),
        F.array_contains(F.array(F.col("a"), F.col("b")), 2).alias("c"))
    txt = explain_plan(df._plan, spark.conf)
    assert "will run on TPU" in txt.splitlines()[0], txt
    out = df.collect().to_pylist()
    assert [r["e"] for r in out] == [9, None, 7]
    assert [r["c"] for r in out] == [False, True, False]


# -- round-2b additions: BRound, InSet, StringSplit, TimeAdd, DateAddInterval


@pytest.fixture
def spark():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession()


def test_bround_half_even(spark):
    df = spark.create_dataframe({"x": pa.array(
        [0.5, 1.5, 2.5, -0.5, -1.5, 2.675, 1.25])})
    out = df.select(F.alias(F.bround(F.col("x"), 0), "r"),
                    F.alias(F.bround(F.col("x"), 1), "r1")).collect()
    assert out["r"].to_pylist() == [0.0, 2.0, 2.0, -0.0, -2.0, 3.0, 1.0]
    assert out["r1"].to_pylist() == [0.5, 1.5, 2.5, -0.5, -1.5, 2.7, 1.2]


def test_bround_integral(spark):
    df = spark.create_dataframe({"x": pa.array([125, 135, -125, 7],
                                               pa.int64())})
    out = df.select(F.alias(F.bround(F.col("x"), -1), "r")).collect()
    assert out["r"].to_pylist() == [120, 140, -120, 10]


def test_inset(spark):
    df = spark.create_dataframe({"x": pa.array([1, 2, 3, None, 5],
                                               pa.int64())})
    fdf = df.filter(F.isin(F.col("x"), {1, 5, 9}))
    assert sorted(fdf.collect()["x"].to_pylist()) == [1, 5]
    plan = fdf.explain()
    assert "will run on TPU" in plan


def test_string_split_fused_extract(spark):
    df = spark.create_dataframe({"s": pa.array(
        ["a,b,c", "x", "", None, "p,q"])})
    out = df.select(
        F.alias(F.element_at0(F.split(F.col("s"), ","), 0), "p0"),
        F.alias(F.element_at0(F.split(F.col("s"), ","), 1), "p1"),
        F.alias(F.size(F.split(F.col("s"), ",")), "n")).collect()
    assert out["p0"].to_pylist() == ["a", "x", "", None, "p"]
    assert out["p1"].to_pylist() == ["b", None, None, None, "q"]
    assert out["n"].to_pylist() == [3, 1, 1, -1, 2]


def test_string_split_matches_host_oracle(spark):
    df = spark.create_dataframe({"s": pa.array(
        ["a-b-c-d", "--x--", "no delim", None] * 5)})
    q = df.select(F.alias(F.element_at0(F.split(F.col("s"), "-"), 2), "p"))
    assert q.collect()["p"].to_pylist() == \
        q.collect_host()["p"].to_pylist()


def test_time_add_and_date_add_interval(spark):
    import datetime
    ts = [datetime.datetime(2020, 1, 1, 12, 0, 0), None]
    df = spark.create_dataframe({
        "t": pa.array(ts, pa.timestamp("us")),
        "d": pa.array([datetime.date(2020, 1, 1), None], pa.date32())})
    hour_us = 3600 * 1000000
    out = df.select(
        F.alias(F.time_add(F.col("t"), F.lit(hour_us)), "t2"),
        F.alias(F.date_add_interval(F.col("d"), F.lit(10)), "d2")).collect()
    got = out["t2"].to_pylist()
    assert got[1] is None
    assert got[0].replace(tzinfo=None) == datetime.datetime(2020, 1, 1, 13)
    assert out["d2"].to_pylist() == [datetime.date(2020, 1, 11), None]


def test_java_split_limit_semantics():
    from spark_rapids_tpu.expr.strings import java_split
    assert java_split("a,b,c", ",", 1) == ["a,b,c"]
    assert java_split("a,b,c", ",", 2) == ["a", "b,c"]
    assert java_split("a,b,c", ",", -1) == ["a", "b", "c"]
    assert java_split("a,,", ",", 0) == ["a"]       # trailing empties drop
    assert java_split("a,,", ",", -1) == ["a", "", ""]
    assert java_split("", ",", 0) == [""]           # Java quirk
    assert java_split(",", ",", 0) == []


def test_bround_fractional_nonzero_digits_host_fallback(spark):
    df = spark.create_dataframe({"x": pa.array([25.0, 35.0, 2.675])})
    q = df.select(F.alias(F.bround(F.col("x"), -1), "r"))
    assert "runs on host" in q.explain()
    assert q.collect()["r"].to_pylist() == [20.0, 40.0, 0.0]


def test_collect_list_and_set(spark):
    df = spark.create_dataframe({
        "k": pa.array([1, 1, 2, 1, 2], pa.int64()),
        "v": pa.array([10, 20, 30, 20, None], pa.int64())})
    out = (df.group_by("k")
           .agg(F.alias(F.collect_list(F.col("v")), "l"),
                F.alias(F.collect_set(F.col("v")), "s"))
           .collect())
    rows = {r["k"]: r for r in out.to_pylist()}
    assert rows[1]["l"] == [10, 20, 20] and rows[1]["s"] == [10, 20]
    assert rows[2]["l"] == [30] and rows[2]["s"] == [30]


def test_stddev_host_fallback_matches_device(spark):
    import math
    df = spark.create_dataframe({
        "k": pa.array([1, 1, 1, 2, 2], pa.int64()),
        "v": pa.array([1.0, 2.0, 4.0, 3.0, 5.0])})
    q = df.group_by("k").agg(F.alias(F.stddev(F.col("v")), "s"))
    dev = {r["k"]: r["s"] for r in q.collect().to_pylist()}
    host = {r["k"]: r["s"] for r in q.collect_host().to_pylist()}
    for k in dev:
        assert math.isclose(dev[k], host[k], rel_tol=1e-9), k


def test_get_json_object(spark):
    docs = ['{"a": 1, "b": {"c": "x"}}', '{"a": [10, 20]}', "not json",
            None, '{"b": {"c": null}}', '{"arr": [{"k": 5}]}']
    df = spark.create_dataframe({"j": pa.array(docs)})
    out = df.select(
        F.alias(F.get_json_object(F.col("j"), "$.a"), "a"),
        F.alias(F.get_json_object(F.col("j"), "$.b.c"), "bc"),
        F.alias(F.get_json_object(F.col("j"), "$.a[1]"), "a1"),
        F.alias(F.get_json_object(F.col("j"), "$.arr[0].k"), "ak")).collect()
    assert out["a"].to_pylist() == ["1", "[10,20]", None, None, None, None]
    assert out["bc"].to_pylist() == ["x", None, None, None, None, None]
    assert out["a1"].to_pylist() == [None, "20", None, None, None, None]
    assert out["ak"].to_pylist() == [None, None, None, None, None, "5"]
    # device equals host oracle
    q = df.select(F.alias(F.get_json_object(F.col("j"), "$.b.c"), "r"))
    assert q.collect()["r"].to_pylist() == q.collect_host()["r"].to_pylist()


def test_scalar_subquery(spark):
    big = spark.create_dataframe({"v": pa.array([5, 9, 2], pa.int64())})
    mx = F.scalar_subquery(big.agg(F.alias(F.max(F.col("v")), "m")))
    df = spark.create_dataframe({"x": pa.array([1, 9, 4], pa.int64())})
    out = df.filter(F.col("x") == mx).collect()
    assert out["x"].to_pylist() == [9]
    with pytest.raises(ValueError, match="more than one row"):
        F.scalar_subquery(big.select(F.col("v")))


def test_fused_map_extraction(spark):
    df = spark.create_dataframe({
        "k": pa.array(["a", "b", "zz", None]),
        "x": pa.array([1, 2, 3, 4], pa.int64())})
    m = F.create_map(F.lit("a"), F.col("x"), F.lit("b"),
                     F.col("x") * F.lit(10))
    q = df.select(F.alias(F.map_value(m, F.col("k")), "v"))
    assert "cannot run on TPU" not in q.explain()   # fused path approved
    got = q.collect()["v"].to_pylist()
    assert got == [1, 20, None, None]
    assert got == q.collect_host()["v"].to_pylist()  # device == host oracle


def test_pivot_session_api(spark):
    df = spark.create_dataframe({
        "k": pa.array([1, 1, 2, 2, 1], pa.int64()),
        "cat": pa.array(["x", "y", "x", "x", "x"]),
        "v": pa.array([10, 20, 30, 40, 50], pa.int64())})
    out = (df.group_by("k").pivot("cat", ["x", "y"])
           .agg(F.alias(F.sum(F.col("v")), "s")).collect())
    rows = {r["k"]: r for r in out.to_pylist()}
    assert rows[1]["x_s"] == 60 and rows[1]["y_s"] == 20
    assert rows[2]["x_s"] == 70 and rows[2]["y_s"] is None

    # count(*) counts only matching rows; first() takes the first MATCH
    out2 = (df.group_by("k").pivot("cat", ["x", "y"])
            .agg(F.alias(F.count(F.col("v")), "c"),
                 F.alias(F.first(F.col("v")), "f")).collect())
    r2 = {r["k"]: r for r in out2.to_pylist()}
    assert r2[1]["x_c"] == 2 and r2[1]["y_c"] == 1
    assert r2[1]["x_f"] == 10 and r2[1]["y_f"] == 20
    assert r2[2]["y_c"] == 0 and r2[2]["y_f"] is None


def test_pivot_first_host_aggregate(spark):
    from spark_rapids_tpu.expr.aggregates import PivotFirst
    from spark_rapids_tpu.plan import nodes as NN
    from spark_rapids_tpu.expr import core as E
    df = spark.create_dataframe({
        "k": pa.array([1, 1, 2], pa.int64()),
        "cat": pa.array(["x", "y", "y"]),
        "v": pa.array([10, 20, 30], pa.int64())})
    pf = PivotFirst(E.col("v"), E.col("cat"), ["x", "y"])
    plan = NN.AggregateNode([E.col("k")], [E.Alias(pf, "p")], df._plan)
    from spark_rapids_tpu.session import DataFrame
    out = DataFrame(plan, spark).collect()
    rows = {r["k"]: r["p"] for r in out.to_pylist()}
    assert rows[1] == [10, 20] and rows[2] == [None, 30]
