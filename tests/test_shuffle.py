"""Shuffle layer tests: serializer round-trip, block store, exchange exec
(reference ring-1 mock-shuffle suites + GpuShuffleSuite patterns, SURVEY.md §4)."""

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec.basic import RangeExec
from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.ops.sorting import SortOrder
from spark_rapids_tpu.shuffle import serialization as ser
from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
from spark_rapids_tpu.shuffle.partitioning import (HashPartitioner, RangePartitioner,
                                                   RoundRobinPartitioner)

from conftest import make_table
from test_partitioning import same_multiset


def test_serializer_roundtrip_all_types():
    # make_table covers ints/longs/doubles/floats/strings/bools/date/
    # timestamp/decimal since the r2 generator widening
    t = make_table(n=333)
    batch = ColumnarBatch.from_arrow(t)
    blob = ser.serialize_batch(batch)
    assert isinstance(blob, bytes)
    out = ser.deserialize_batch(blob)
    assert out.to_arrow().equals(t)
    assert out.schema.names == batch.schema.names


def test_serializer_empty_batch():
    schema = T.StructType([T.StructField("a", T.LONG), T.StructField("s", T.STRING)])
    out = ser.deserialize_batch(ser.serialize_batch(ColumnarBatch.empty(schema)))
    assert out.num_rows == 0
    assert out.schema.names == ["a", "s"]


def test_block_store_write_read_unregister():
    store = ShuffleBlockStore.get()
    sid = store.register_shuffle()
    t = make_table(n=64)
    store.write_block(sid, 0, ColumnarBatch.from_arrow(t))
    store.write_block(sid, 2, ColumnarBatch.from_arrow(t))
    got = list(store.read_partition(sid, 0))
    assert len(got) == 1 and got[0].to_arrow().equals(t)
    assert list(store.read_partition(sid, 1)) == []
    store.unregister_shuffle(sid)


def _exchange_source(n=1000, parts=4):
    """RangeExec source: id column 0..n across `parts` partitions."""
    return RangeExec(0, n, 1, num_slices=parts, conf=RapidsConf())


def test_hash_exchange_end_to_end():
    src = _exchange_source(1000, 4)
    ex = ShuffleExchangeExec(HashPartitioner([col("id")], 8), src)
    out = ex.execute_collect()
    assert sorted(out["id"].to_pylist()) == list(range(1000))


def test_hash_exchange_serialized_fallback():
    src = RangeExec(0, 500, 1, num_slices=3,
                    conf=RapidsConf({"spark.rapids.tpu.shuffle.enabled": False}))
    ex = ShuffleExchangeExec(HashPartitioner([col("id")], 5), src)
    out = ex.execute_collect()
    assert sorted(out["id"].to_pylist()) == list(range(500))


def test_round_robin_exchange_balances():
    src = _exchange_source(999, 3)
    ex = ShuffleExchangeExec(RoundRobinPartitioner(7), src)
    sizes = []
    for p in range(7):
        rows = sum(b.num_rows for b in ex.execute_partition(p))
        sizes.append(rows)
    assert sum(sizes) == 999


def test_range_exchange_globally_sorted_partitions():
    src = _exchange_source(2000, 4)
    ex = ShuffleExchangeExec(
        RangePartitioner([col("id")], [SortOrder(ascending=True)], 6), src)
    maxes = []
    for p in range(6):
        vals = [v for b in ex.execute_partition(p) for v in b.to_arrow()["id"].to_pylist()]
        if vals:
            if maxes:
                assert min(vals) >= maxes[-1]
            maxes.append(max(vals))


def test_two_phase_aggregate_over_exchange_no_deadlock():
    """Regression: reduce tasks must not hold semaphore permits while blocked on the
    shuffle map stage (the reference releases the semaphore while awaiting fetches,
    RapidsShuffleIterator.scala:300)."""
    from spark_rapids_tpu.exec.aggregate import HashAggregateExec, PARTIAL, FINAL
    from spark_rapids_tpu.expr.aggregates import Sum, Count
    from spark_rapids_tpu.expr.core import Alias
    from spark_rapids_tpu.expr.arithmetic import Pmod
    from spark_rapids_tpu.runtime.semaphore import TpuSemaphore

    TpuSemaphore.initialize(2)  # tight permits + multi-partition exchange
    conf = RapidsConf({"spark.rapids.tpu.sql.localScheduler.numThreads": 4})
    src = RangeExec(0, 10000, 1, num_slices=4, conf=conf)
    key = Alias(Pmod(col("id"), lit_long(10)), "k")
    partial = HashAggregateExec([key], [Alias(Sum(col("id")), "s"),
                                        Alias(Count(col("id")), "c")], src,
                                mode=PARTIAL)
    ex = ShuffleExchangeExec(HashPartitioner([col("k")], 6), partial)
    final = HashAggregateExec([col("k")], [Alias(Sum(col("id")), "s"),
                                           Alias(Count(col("id")), "c")], ex,
                              mode=FINAL)
    out = final.execute_collect().sort_by("k")
    assert out["k"].to_pylist() == list(range(10))
    assert out["c"].to_pylist() == [1000] * 10
    expect = [sum(v for v in range(10000) if v % 10 == k) for k in range(10)]
    assert out["s"].to_pylist() == expect


def lit_long(v):
    from spark_rapids_tpu.expr.core import Literal
    from spark_rapids_tpu import types as TT
    return Literal(v, TT.LONG)


def test_adaptive_reader_coalesces_small_partitions():
    """AQE reader (GpuCustomShuffleReaderExec analog): many tiny reduce
    partitions merge into few advisory-sized reader partitions, results
    unchanged."""
    import pyarrow as pa
    import numpy as np
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    from spark_rapids_tpu.exec.exchange import (AdaptiveShuffleReaderExec,
                                                ShuffleExchangeExec)
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioner
    from spark_rapids_tpu.expr.core import col

    rng = np.random.default_rng(3)
    tables = [pa.table({"k": pa.array(rng.integers(0, 100, 200)),
                        "v": pa.array(np.arange(200) + i * 1000)})
              for i in range(3)]
    scan = ArrowScanExec(tables)
    conf = RapidsConf()
    ex = ShuffleExchangeExec(HashPartitioner([col("k")], 32), scan, conf=conf)
    reader = AdaptiveShuffleReaderExec(ex, conf=conf)
    # static count: asking must NOT run the map stage (the planner asks
    # during conversion; the AQE barrier is execution-time)
    assert reader.num_partitions == 32
    assert not ex._map_done.is_set()
    rows, nonempty = [], 0
    for split in range(reader.num_partitions):
        got = [b for b in reader.execute_partition(split)]
        nonempty += bool(sum(b.num_rows for b in got))
        for b in got:
            rows.extend(b.to_arrow().to_pylist())
    assert 1 <= len(reader._ensure_specs()) < 32   # tiny blocks merged
    assert nonempty == len(reader._ensure_specs())
    expect = [r for t in tables for r in t.to_pylist()]
    key = lambda r: (r["k"], r["v"])  # noqa: E731
    assert sorted(rows, key=key) == sorted(expect, key=key)


def test_adaptive_reader_respects_advisory_size():
    import pyarrow as pa
    import numpy as np
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    from spark_rapids_tpu.exec.exchange import (AdaptiveShuffleReaderExec,
                                                ShuffleExchangeExec)
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioner
    from spark_rapids_tpu.expr.core import col

    t = pa.table({"k": pa.array(np.arange(4000) % 16),
                  "v": pa.array(np.arange(4000, dtype=np.int64))})
    scan = ArrowScanExec([t])
    # tiny advisory target → little to no merging
    conf = RapidsConf({
        "spark.rapids.tpu.sql.adaptive.advisoryPartitionSizeInBytes": "1"})
    ex = ShuffleExchangeExec(HashPartitioner([col("k")], 8), scan, conf=conf)
    r1 = AdaptiveShuffleReaderExec(ex, conf=conf)
    list(r1.execute_partition(0))
    assert len(r1._ensure_specs()) == 8     # tiny target: no merging

    conf2 = RapidsConf({
        "spark.rapids.tpu.sql.adaptive.advisoryPartitionSizeInBytes": "1g"})
    ex2 = ShuffleExchangeExec(HashPartitioner([col("k")], 8), scan, conf=conf2)
    r2 = AdaptiveShuffleReaderExec(ex2, conf=conf2)
    list(r2.execute_partition(0))
    assert len(r2._ensure_specs()) == 1     # huge target: one reader spec


def test_group_by_with_adaptive_default_on():
    import pyarrow as pa
    from spark_rapids_tpu.session import TpuSession
    import spark_rapids_tpu.functions as F
    spark = TpuSession()
    df = spark.create_dataframe(
        {"k": pa.array([1, 2, 1, 3, 2, 1], pa.int64()),
         "v": pa.array([10, 20, 30, 40, 50, 60], pa.int64())},
        num_partitions=3)
    out = df.group_by("k").agg(F.alias(F.sum(F.col("v")), "s")).collect()
    got = dict(zip(out["k"].to_pylist(), out["s"].to_pylist()))
    assert got == {1: 100, 2: 70, 3: 40}


def test_adaptive_reader_early_close_frees_blocks():
    """Closing a coalesced reader mid-spec must still account for the
    never-opened pids so the shuffle blocks are freed (limit early-out)."""
    import pyarrow as pa
    import numpy as np
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    from spark_rapids_tpu.exec.exchange import (AdaptiveShuffleReaderExec,
                                                ShuffleExchangeExec)
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioner
    from spark_rapids_tpu.expr.core import col

    t = pa.table({"k": pa.array(np.arange(2000) % 16),
                  "v": pa.array(np.arange(2000, dtype=np.int64))})
    conf = RapidsConf({
        "spark.rapids.tpu.sql.adaptive.advisoryPartitionSizeInBytes": "1g"})
    ex = ShuffleExchangeExec(HashPartitioner([col("k")], 16),
                             ArrowScanExec([t]), conf=conf)
    reader = AdaptiveShuffleReaderExec(ex, conf=conf)
    it = reader.execute_partition(0)   # one spec holding all 16 pids
    next(it)                           # consume one batch then abandon
    it.close()
    sid = ex._shuffle_id
    assert ex._reads_left == 0
    assert sid not in ShuffleBlockStore.get()._blocks
    # the remaining (empty) splits still work
    for split in range(1, reader.num_partitions):
        assert list(reader.execute_partition(split)) == []
