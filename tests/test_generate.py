"""GenerateExec (explode/posexplode) — device gather-expansion vs host oracle
(reference GpuGenerateExec.scala / generate_expr_test.py patterns)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.plan import GenerateNode, ScanNode, TpuOverrides, \
    explain_plan
from spark_rapids_tpu.plan.transitions import execute_hybrid
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.session import TpuSession
from test_plan import split_table


def list_table(n=200, seed=3, elem=pa.int64()):
    r = np.random.default_rng(seed)
    arrs = []
    for i in range(n):
        u = r.random()
        if u < 0.1:
            arrs.append(None)
        elif u < 0.2:
            arrs.append([])
        else:
            arrs.append([None if r.random() < 0.1 else int(v)
                         for v in r.integers(-50, 50, int(r.integers(1, 6)))])
    return pa.table({
        "k": pa.array(list(range(n)), pa.int32()),
        "s": pa.array([f"r{i % 7}" for i in range(n)]),
        "arr": pa.array(arrs, pa.list_(elem)),
    })


def _key(row):
    return tuple((v is None, v) for v in row)


def run_both(node):
    host = node.collect_host()
    hybrid = TpuOverrides(RapidsConf()).apply(node)
    dev = execute_hybrid(hybrid)
    h = sorted((tuple(r.values()) for r in host.to_pylist()), key=_key)
    d = sorted((tuple(r.values()) for r in dev.to_pylist()), key=_key)
    assert h == d, (h[:5], d[:5])
    return hybrid


def test_explode_device():
    t = list_table()
    node = GenerateNode("arr", ScanNode(split_table(t, 3)),
                        element_type=T.LONG)
    hybrid = run_both(node)
    # the generate itself runs on device (child scan stays host: list output)
    from spark_rapids_tpu.exec.generate import GenerateExec
    assert isinstance(hybrid, GenerateExec), explain_plan(node)


def test_explode_outer_device():
    t = list_table(seed=7)
    node = GenerateNode("arr", ScanNode(split_table(t, 2)), outer=True,
                        element_type=T.LONG)
    run_both(node)


def test_posexplode_device():
    t = list_table(seed=11)
    for outer in (False, True):
        node = GenerateNode("arr", ScanNode([t]), outer=outer, pos=True,
                            element_type=T.LONG)
        run_both(node)


def test_explode_double_elements():
    r = np.random.default_rng(5)
    arrs = [[float(x) for x in r.normal(0, 3, int(r.integers(0, 4)))]
            for _ in range(80)]
    t = pa.table({"k": pa.array(list(range(80)), pa.int32()),
                  "arr": pa.array(arrs, pa.list_(pa.float64()))})
    node = GenerateNode("arr", ScanNode([t]), element_type=T.DOUBLE)
    run_both(node)


def test_explode_string_elements():
    arrs = [["a", "bb"], None, ["ccc", None, "a"], [], ["zz"]]
    t = pa.table({"k": pa.array([0, 1, 2, 3, 4], pa.int32()),
                  "arr": pa.array(arrs, pa.list_(pa.string()))})
    for outer in (False, True):
        node = GenerateNode("arr", ScanNode([t]), outer=outer,
                            element_type=T.STRING)
        run_both(node)


def test_explode_session_api():
    spark = TpuSession()
    t = list_table(60, seed=13)
    df = spark.create_dataframe(t, num_partitions=2).explode("arr")
    out = df.collect()
    exp = []
    for k, s, arr in zip(t.column("k").to_pylist(), t.column("s").to_pylist(),
                         t.column("arr").to_pylist()):
        for v in (arr or []):
            exp.append((k, s, v))
    got = sorted(zip(out.column("k").to_pylist(), out.column("s").to_pylist(),
                     out.column("col").to_pylist()), key=_key)
    assert got == sorted(exp, key=_key)


def test_explode_then_aggregate_session():
    """explode feeding a device group-by: the generate output is a normal
    device batch, so downstream execs stay on TPU."""
    import spark_rapids_tpu.functions as F
    spark = TpuSession()
    t = list_table(100, seed=17)
    df = (spark.create_dataframe(t, num_partitions=2)
          .explode("arr")
          .group_by(F.col("s"))
          .agg(F.count(F.col("col")).alias("c"),
               F.sum(F.col("col")).alias("sm")))
    got = {r["s"]: (r["c"], r["sm"]) for r in df.collect().to_pylist()}
    exp = {}
    for s, arr in zip(t.column("s").to_pylist(), t.column("arr").to_pylist()):
        for v in (arr or []):
            c, sm = exp.get(s, (0, 0))
            exp[s] = (c + (v is not None), sm + (v or 0))
    for s, (c, sm) in exp.items():
        assert got[s][0] == c
        assert got[s][1] == (sm if c else None)
