"""Join tests — CPU-reference equivalence over all join types with nulls, NaNs,
duplicate keys, and string keys (reference: JoinsSuite / HashJoinSuite patterns,
SURVEY.md §4 ring 2)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.exec.basic import ArrowScanExec
from spark_rapids_tpu.exec.joins import (BroadcastHashJoinExec, CartesianProductExec,
                                         HashJoinExec, NestedLoopJoinExec)
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.expr.predicates import GreaterThan

from test_partitioning import same_multiset


def left_table(n=200, seed=3):
    r = np.random.default_rng(seed)
    keys = [None if m else int(v) for v, m in
            zip(r.integers(0, 40, n), r.random(n) < 0.1)]
    return pa.table({"lk": pa.array(keys, type=pa.int64()),
                     "lv": pa.array(np.arange(n), type=pa.int32()),
                     "ls": pa.array([["x", "y", "z", None][i % 4] for i in range(n)])})


def right_table(n=120, seed=9):
    r = np.random.default_rng(seed)
    keys = [None if m else int(v) for v, m in
            zip(r.integers(0, 40, n), r.random(n) < 0.1)]
    return pa.table({"rk": pa.array(keys, type=pa.int64()),
                     "rv": pa.array(np.arange(n) * 10, type=pa.int32())})


def host_join(lt, rt, lkey, rkey, how):
    """Plain-python reference join with Spark semantics (null keys never match)."""
    lrows = lt.to_pylist()
    rrows = rt.to_pylist()
    out = []
    rmatched = [False] * len(rrows)
    for lr in lrows:
        k = lr[lkey]
        matches = [j for j, rr in enumerate(rrows)
                   if k is not None and rr[rkey] == k]
        for j in matches:
            rmatched[j] = True
        if how in ("inner",):
            out += [{**lr, **rrows[j]} for j in matches]
        elif how in ("leftouter", "fullouter"):
            if matches:
                out += [{**lr, **rrows[j]} for j in matches]
            else:
                out.append({**lr, **{c: None for c in rt.column_names}})
        elif how == "leftsemi":
            if matches:
                out.append(dict(lr))
        elif how == "leftanti":
            if not matches:
                out.append(dict(lr))
    if how == "fullouter":
        for j, rr in enumerate(rrows):
            if not rmatched[j]:
                out.append({**{c: None for c in lt.column_names}, **rr})
    if how == "rightouter":
        return host_join(rt, lt, rkey, lkey, "leftouter")
    cols = (lt.column_names + rt.column_names if how not in ("leftsemi", "leftanti")
            else lt.column_names)
    if how == "rightouter":
        cols = lt.column_names + rt.column_names
    return pa.table({c: pa.array([row.get(c) for row in out],
                                 type=(lt.schema.field(c).type if c in lt.column_names
                                       else rt.schema.field(c).type))
                     for c in cols})


def run_join(how, lt=None, rt=None, **kw):
    lt = left_table() if lt is None else lt
    rt = right_table() if rt is None else rt
    conf = RapidsConf()
    lscan = ArrowScanExec([lt], conf=conf)
    rscan = ArrowScanExec([rt], conf=conf)
    j = HashJoinExec(how, [col("lk")], [col("rk")], lscan, rscan, **kw)
    return j.execute_collect()


@pytest.mark.parametrize("how", ["inner", "leftouter", "rightouter", "fullouter",
                                 "leftsemi", "leftanti"])
def test_hash_join_types_match_host(how):
    lt, rt = left_table(), right_table()
    got = run_join(how)
    want = host_join(lt, rt, "lk", "rk", how)
    if how == "rightouter":
        # host reference emits columns right-first; reorder to left++right
        want = want.select(got.column_names)
    assert got.num_rows == want.num_rows, f"{how}: {got.num_rows} != {want.num_rows}"
    assert same_multiset(got, want), how


def test_inner_join_build_side_left():
    lt, rt = left_table(), right_table()
    got = run_join("inner", build_side="left")
    want = host_join(lt, rt, "lk", "rk", "inner")
    assert same_multiset(got, want)


def test_inner_join_with_condition():
    lt, rt = left_table(), right_table()
    got = run_join("inner", condition=GreaterThan(col("lv"), col("rv")))
    rows = host_join(lt, rt, "lk", "rk", "inner").to_pylist()
    want_rows = [r for r in rows if r["lv"] is not None and r["rv"] is not None
                 and r["lv"] > r["rv"]]
    assert got.num_rows == len(want_rows)


def test_string_key_join():
    lt = pa.table({"lk": pa.array(["a", "b", None, "c", "a", ""]),
                   "lv": pa.array(range(6), type=pa.int32())})
    rt = pa.table({"rk": pa.array(["a", None, "", "d"]),
                   "rv": pa.array(range(4), type=pa.int32())})
    conf = RapidsConf()
    j = HashJoinExec("inner", [col("lk")], [col("rk")],
                     ArrowScanExec([lt], conf=conf), ArrowScanExec([rt], conf=conf))
    got = j.execute_collect()
    want = pa.table({"lk": pa.array(["a", "a", ""]),
                     "lv": pa.array([0, 4, 5], type=pa.int32()),
                     "rk": pa.array(["a", "a", ""]),
                     "rv": pa.array([0, 0, 2], type=pa.int32())})
    assert same_multiset(got, want)


def test_multi_key_join_with_nan():
    lt = pa.table({"lk": pa.array([1.0, float("nan"), 2.0, None, -0.0]),
                   "lv": pa.array(range(5), type=pa.int32())})
    rt = pa.table({"rk": pa.array([float("nan"), 1.0, 0.0]),
                   "rv": pa.array(range(3), type=pa.int32())})
    conf = RapidsConf()
    j = HashJoinExec("inner", [col("lk")], [col("rk")],
                     ArrowScanExec([lt], conf=conf), ArrowScanExec([rt], conf=conf))
    got = j.execute_collect()
    # Spark: NaN == NaN in join keys; -0.0 == 0.0; null never matches
    lvs = sorted(got["lv"].to_pylist())
    assert lvs == [0, 1, 4]


def test_broadcast_hash_join_multi_partition_stream():
    lt = left_table(300)
    tables = [lt.slice(0, 100), lt.slice(100, 100), lt.slice(200, 100)]
    rt = right_table()
    conf = RapidsConf()
    j = BroadcastHashJoinExec("leftouter", [col("lk")], [col("rk")],
                              ArrowScanExec(tables, conf=conf),
                              ArrowScanExec([rt], conf=conf))
    got = j.execute_collect()
    want = host_join(lt, rt, "lk", "rk", "leftouter")
    assert same_multiset(got, want)


def test_nested_loop_cross_and_condition():
    lt = pa.table({"a": pa.array([1, 2, 3], type=pa.int64())})
    rt = pa.table({"b": pa.array([10, 2, 30, 1], type=pa.int64())})
    conf = RapidsConf()
    cross = CartesianProductExec(ArrowScanExec([lt], conf=conf),
                                 ArrowScanExec([rt], conf=conf))
    assert cross.execute_collect().num_rows == 12
    nl = NestedLoopJoinExec("inner", ArrowScanExec([lt], conf=conf),
                            ArrowScanExec([rt], conf=conf),
                            condition=GreaterThan(col("a"), col("b")))
    got = nl.execute_collect()
    pairs = sorted(zip(got["a"].to_pylist(), got["b"].to_pylist()))
    assert pairs == [(2, 1), (3, 1), (3, 2)]


def test_nested_loop_left_outer_with_condition():
    lt = pa.table({"a": pa.array([1, 5, 7], type=pa.int64())})
    rt = pa.table({"b": pa.array([6, 6], type=pa.int64())})
    conf = RapidsConf()
    nl = NestedLoopJoinExec("leftouter", ArrowScanExec([lt], conf=conf),
                            ArrowScanExec([rt], conf=conf),
                            condition=GreaterThan(col("a"), col("b")))
    got = nl.execute_collect()
    rows = sorted(zip(got["a"].to_pylist(), got["b"].to_pylist()))
    assert rows == [(1, None), (5, None), (7, 6), (7, 6)]


def test_nested_loop_semi_anti():
    lt = pa.table({"a": pa.array([1, 5, 7], type=pa.int64())})
    rt = pa.table({"b": pa.array([6, 6], type=pa.int64())})
    conf = RapidsConf()
    semi = NestedLoopJoinExec("leftsemi", ArrowScanExec([lt], conf=conf),
                              ArrowScanExec([rt], conf=conf),
                              condition=GreaterThan(col("a"), col("b")))
    assert semi.execute_collect()["a"].to_pylist() == [7]
    anti = NestedLoopJoinExec("leftanti", ArrowScanExec([lt], conf=conf),
                              ArrowScanExec([rt], conf=conf),
                              condition=GreaterThan(col("a"), col("b")))
    assert sorted(anti.execute_collect()["a"].to_pylist()) == [1, 5]


def test_join_empty_build_side():
    lt = left_table(50)
    rt = right_table(0)
    got = run_join("leftouter", lt=lt, rt=rt)
    assert got.num_rows == 50
    assert got["rv"].null_count == 50
    got_inner = run_join("inner", lt=lt, rt=rt)
    assert got_inner.num_rows == 0


def test_broadcast_full_outer_multi_partition_stream():
    """Regression: unmatched build rows must be emitted exactly once globally, not
    once per stream partition (matched flags merge across partitions)."""
    lt = left_table(300)
    tables = [lt.slice(0, 100), lt.slice(100, 100), lt.slice(200, 100)]
    rt = right_table()
    conf = RapidsConf()
    j = BroadcastHashJoinExec("fullouter", [col("lk")], [col("rk")],
                              ArrowScanExec(tables, conf=conf),
                              ArrowScanExec([rt], conf=conf))
    got = j.execute_collect()
    want = host_join(lt, rt, "lk", "rk", "fullouter")
    assert same_multiset(got, want)


def test_nested_loop_full_outer_multi_partition_left():
    lt = pa.table({"a": pa.array([1, 5, 7, 9], type=pa.int64())})
    tables = [lt.slice(0, 2), lt.slice(2, 2)]
    rt = pa.table({"b": pa.array([6, 6, 100], type=pa.int64())})
    conf = RapidsConf()
    nl = NestedLoopJoinExec("fullouter", ArrowScanExec(tables, conf=conf),
                            ArrowScanExec([rt], conf=conf),
                            condition=GreaterThan(col("a"), col("b")))
    got = nl.execute_collect()
    rows = sorted(zip(got["a"].to_pylist(), got["b"].to_pylist()),
                  key=lambda p: (p[0] is None, p[0] or 0, p[1] is None, p[1] or 0))
    # pairs where a > b: (7,6)x2, (9,6)x2; unmatched left: 1, 5; unmatched right:
    # 100 exactly once (6s both matched)
    assert rows == [(1, None), (5, None), (7, 6), (7, 6), (9, 6), (9, 6),
                    (None, 100)]


def test_hash_join_rejects_cross():
    lt = left_table(10)
    rt = right_table(10)
    conf = RapidsConf()
    with pytest.raises(ValueError):
        HashJoinExec("cross", [], [], ArrowScanExec([lt], conf=conf),
                     ArrowScanExec([rt], conf=conf))


def test_broadcast_exchange_exec_standalone():
    """Standalone BroadcastExchangeExec (reference GpuBroadcastExchangeExecBase):
    plan-visible node, one shared materialization, host-bridge stream path."""
    import pyarrow as pa
    from spark_rapids_tpu.exec.broadcast import BroadcastExchangeExec
    tbl = pa.table({"k": pa.array([1, 2, 3], pa.int64())})
    scan = ArrowScanExec([tbl])
    bx = BroadcastExchangeExec(scan)
    assert bx.num_partitions == 1
    sb1 = bx.broadcast()
    sb2 = bx.broadcast()
    assert sb1 is sb2  # single shared relation
    # host-bridge path streams the same relation
    out = list(bx.execute_partition(0))
    assert out[0].num_rows == 3
    assert "BroadcastExchangeExec" in bx.tree_string()
    bx.release()


def test_broadcast_join_rides_exchange():
    from spark_rapids_tpu.session import TpuSession
    spark = TpuSession()
    left = spark.create_dataframe({"k": pa.array([1, 2], pa.int64()),
                                   "a": pa.array([10, 20], pa.int64())})
    right = spark.create_dataframe({"k": pa.array([2, 3], pa.int64()),
                                    "b": pa.array([7, 8], pa.int64())})
    out = left.join(right, on="k").collect()
    assert out.num_rows == 1
    assert out["a"].to_pylist() == [20] and out["b"].to_pylist() == [7]


@pytest.mark.parametrize("how", ["inner", "leftouter", "fullouter", "leftsemi",
                                 "leftanti"])
def test_mixed_width_key_join(how):
    """int64 stream key vs int32 build key must NOT wrap on the fast path
    (advisor r3 high): 2**32+5 is not equal to 5."""
    lt = pa.table({"lk": pa.array([2**32 + 5, 5, -1, None, 2**31 + 7],
                                  type=pa.int64()),
                   "lv": pa.array(range(5), type=pa.int32())})
    rt = pa.table({"rk": pa.array([5, 7, -1], type=pa.int32()),
                   "rv": pa.array(range(3), type=pa.int32())})
    conf = RapidsConf()
    j = HashJoinExec(how, [col("lk")], [col("rk")],
                     ArrowScanExec([lt], conf=conf), ArrowScanExec([rt], conf=conf))
    got = j.execute_collect()
    rt64 = pa.table({"rk": rt["rk"].cast(pa.int64()), "rv": rt["rv"]})
    want = host_join(lt, rt64, "lk", "rk", how)
    assert got.num_rows == want.num_rows, (how, got.to_pylist(), want.to_pylist())
    if how in ("inner", "leftsemi", "leftanti"):
        assert sorted(got["lv"].to_pylist()) == sorted(want["lv"].to_pylist()), how


def test_mixed_width_key_join_wide_build():
    """int32 stream key vs int64 build key (widening direction) stays correct."""
    lt = pa.table({"lk": pa.array([5, -1, 3], type=pa.int32()),
                   "lv": pa.array(range(3), type=pa.int32())})
    rt = pa.table({"rk": pa.array([2**32 + 5, 5, -1], type=pa.int64()),
                   "rv": pa.array(range(3), type=pa.int32())})
    conf = RapidsConf()
    j = HashJoinExec("inner", [col("lk")], [col("rk")],
                     ArrowScanExec([lt], conf=conf), ArrowScanExec([rt], conf=conf))
    got = j.execute_collect()
    assert sorted(zip(got["lv"].to_pylist(), got["rv"].to_pylist())) == [(0, 1), (1, 2)]


@pytest.mark.parametrize("how", ["inner", "leftouter"])
def test_dtype_max_key_fast_path(how):
    """A legitimate dtype-max key must keep matching on the packed fast path
    (the ineligible-row sentinel is vmax+1 — kept in int64 so it can never
    wrap into/collide with a real key)."""
    import numpy as np
    m32 = np.iinfo(np.int32).max
    lt = pa.table({"lk": pa.array([m32, m32 - 1, 5, None], pa.int32()),
                   "lv": pa.array(range(4), type=pa.int32())})
    rt = pa.table({"rk": pa.array([m32, m32, 7], pa.int32()),
                   "rv": pa.array(range(3), type=pa.int32())})
    conf = RapidsConf()
    j = HashJoinExec(how, [col("lk")], [col("rk")],
                     ArrowScanExec([lt], conf=conf),
                     ArrowScanExec([rt], conf=conf))
    got = j.execute_collect()
    want = host_join(lt, rt, "lk", "rk", how)
    assert got.num_rows == want.num_rows, (got.to_pylist(), want.to_pylist())
    assert sorted(got["lv"].to_pylist()) == sorted(want["lv"].to_pylist())
    # and with int64 keys at the int64 max (packed path must refuse/stay safe)
    m64 = np.iinfo(np.int64).max
    lt64 = pa.table({"lk": pa.array([m64, 5], pa.int64()),
                     "lv": pa.array([0, 1], pa.int32())})
    rt64 = pa.table({"rk": pa.array([m64], pa.int64()),
                     "rv": pa.array([9], pa.int32())})
    j2 = HashJoinExec(how, [col("lk")], [col("rk")],
                      ArrowScanExec([lt64], conf=conf),
                      ArrowScanExec([rt64], conf=conf))
    got2 = j2.execute_collect()
    want2 = host_join(lt64, rt64, "lk", "rk", how)
    assert got2.num_rows == want2.num_rows
