"""Multi-tenant query lifecycle (runtime/scheduler.py): admission control,
deadlines, cooperative cancellation, overload shedding, and the checksum +
chaos satellites that ride with it.

The leak contract extends the PR-4 helpers: every cancellation test —
mid-scan, mid-join-build, mid-fetch, and while queued for admission —
asserts no leaked pipeline threads, no registered device buffers, and a
fully released semaphore."""

import gc
import pickle
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F_
from spark_rapids_tpu import config as C
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.runtime import eventlog
from spark_rapids_tpu.runtime import faults as F
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import scheduler as SCHED
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.runtime.memory import (BufferCatalog, DeviceManager,
                                             SpillCorruptionError)
from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.columnar.batch import ColumnarBatch


@pytest.fixture(autouse=True)
def _clean_state():
    F.reset()
    M.reset_global_registry()
    tracing.clear_events()
    yield
    F.reset()
    M.reset_global_registry()
    tracing.clear_events()
    eventlog.shutdown()


@pytest.fixture(scope="module")
def tpch_paths(tmp_path_factory):
    return tpch.generate(0.005, str(tmp_path_factory.mktemp("tpch_sched")))


def _pipe_threads():
    return [t for t in threading.enumerate()
            if t.is_alive() and t.name.startswith("srt-pipe-")]


def _assert_no_leaks(base_buffers, timeout=8.0):
    """The PR-4 leak-check helper, extended: pipeline threads joined,
    catalog registrations back to base, semaphore permits all home."""
    cat = DeviceManager.get().catalog
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        gc.collect()
        if (not _pipe_threads() and cat.num_buffers <= base_buffers
                and not TpuSemaphore.get()._holders):
            return
        time.sleep(0.1)
    assert not _pipe_threads(), _pipe_threads()
    assert cat.num_buffers <= base_buffers, [
        (b.buffer_id, b.tier, b.size, b.priority, b.query)
        for b in cat._buffers.values()]
    assert not TpuSemaphore.get()._holders, TpuSemaphore.get()._holders


# -- CancelToken / typed errors ------------------------------------------------

def test_cancel_token_cancel_and_check():
    tok = SCHED.CancelToken("qx")
    tok.check()                         # not cancelled: no raise
    assert not tok.cancelled
    tok.cancel("because")
    assert tok.cancelled and tok.reason == "because"
    with pytest.raises(SCHED.QueryCancelledError) as ei:
        tok.check()
    assert ei.value.query_id == "qx"


def test_cancel_token_deadline():
    tok = SCHED.CancelToken("qd", deadline_s=0.05)
    tok.check()
    assert tok.remaining_s() > 0
    time.sleep(0.07)
    assert tok.cancelled
    with pytest.raises(SCHED.QueryDeadlineError):
        tok.check()


def test_rejected_error_pickles_with_backoff_hint():
    e = SCHED.QueryRejectedError("shed", backoff_hint_s=3.25,
                                 query_id="q9", reason="queue_timeout")
    rt = pickle.loads(pickle.dumps(e))
    assert rt.retryable and rt.backoff_hint_s == 3.25
    assert rt.query_id == "q9" and rt.reason == "queue_timeout"
    assert str(rt) == "shed"


# -- admission control ---------------------------------------------------------

def test_admission_serializes_on_max_concurrent():
    sched = SCHED.QueryScheduler(max_concurrent=1)
    sched.submit("a", 100)
    order = []

    def second():
        sched.submit("b", 100)
        order.append("b-admitted")
        sched.release("b")

    t = threading.Thread(target=second, daemon=True)
    t.start()
    time.sleep(0.15)
    assert order == []                   # b waits while a runs
    states = {q["query"]: q["state"] for q in sched.active_queries()}
    assert states == {"a": "running", "b": "queued"}
    sched.release("a")
    t.join(timeout=5)
    assert order == ["b-admitted"]


def test_queue_full_sheds_immediately():
    sched = SCHED.QueryScheduler(max_concurrent=1, queue_max_depth=1)
    sched.submit("a", 1)
    tok_b = SCHED.CancelToken("b")
    t = threading.Thread(
        target=lambda: pytest.raises(
            SCHED.QueryCancelledError,
            lambda: sched.submit("b", 1, token=tok_b)),
        daemon=True)
    t.start()
    time.sleep(0.15)                     # b now occupies the 1-deep queue
    with pytest.raises(SCHED.QueryRejectedError) as ei:
        sched.submit("c", 1)
    assert ei.value.reason == "queue_full"
    assert ei.value.backoff_hint_s > 0
    tok_b.cancel()
    t.join(timeout=5)
    sched.release("a")


def test_queue_timeout_sheds_with_hint():
    sched = SCHED.QueryScheduler(max_concurrent=1)
    sched.submit("a", 1)
    t0 = time.monotonic()
    with pytest.raises(SCHED.QueryRejectedError) as ei:
        sched.submit("b", 1, timeout_s=0.1)
    assert 0.08 <= time.monotonic() - t0 < 5
    assert ei.value.reason == "queue_timeout"
    assert ei.value.backoff_hint_s > 0
    sched.release("a")
    assert M.global_registry().metric(M.QUERIES_SHED).value >= 1


def test_priority_aging_prevents_starvation():
    sched = SCHED.QueryScheduler(max_concurrent=1, aging_s=0.05)
    now = time.monotonic()
    lo = SCHED._Ticket("lo", 1, 0, None, "")
    hi = SCHED._Ticket("hi", 1, 2, None, "")
    assert sched._eff_priority(hi, now) > sched._eff_priority(lo, now)
    # after 4 aging periods the low-priority ticket out-ranks a fresh hi
    assert sched._eff_priority(lo, now + 0.2) > sched._eff_priority(hi, now)


def test_estimate_footprint_scales_with_scan_and_breakers(tpch_paths,
                                                          monkeypatch):
    spark = TpuSession()
    dfs = tpch.load(spark, tpch_paths)
    # at sf0.005 everything sits under the 16MB floor; drop it to see shape
    assert SCHED.estimate_footprint(dfs["lineitem"]._plan) == 16 << 20
    monkeypatch.setattr(SCHED, "_MIN_FOOTPRINT", 0)
    scan_only = SCHED.estimate_footprint(dfs["lineitem"]._plan)
    q18 = SCHED.estimate_footprint(tpch.q18(dfs)._plan)
    assert scan_only > 0                 # real scan bytes, decode-expanded
    assert q18 > scan_only               # joins/aggs add breaker working sets


# -- cooperative cancellation: the four canonical sites ------------------------

def _cancel_run(conf_extra, build_df):
    """Run build_df() under a cancel fault; returns (catalog base for the
    leak check, the injection log) after asserting the typed error
    surfaced."""
    cat = DeviceManager.get().catalog
    base = cat.num_buffers
    conf = {"spark.rapids.tpu.pipeline.enabled": True}
    conf.update(conf_extra)
    spark = TpuSession(conf)
    with pytest.raises(SCHED.QueryCancelledError):
        build_df(spark).collect()
    log = F.injected_log()
    F.reset()
    return base, log


def test_cancel_mid_scan(tmp_path):
    import pyarrow.parquet as pq
    rng = np.random.default_rng(3)
    t = pa.table({"k": pa.array(rng.integers(0, 9, 6000).astype(np.int64)),
                  "v": pa.array(rng.normal(size=6000))})
    for i in range(3):
        pq.write_table(t.slice(i * 2000, 2000), tmp_path / f"p{i}.parquet")
    base, log = _cancel_run(
        {"spark.rapids.tpu.test.faults": "cancel:pipeline.put.scan.decode:1"},
        lambda s: s.read_parquet(str(tmp_path)).group_by("k").agg(
            F_.alias(F_.sum(F_.col("v")), "sv")))
    _assert_no_leaks(base)
    assert ("cancel", "pipeline.put.scan.decode") in log


def test_cancel_mid_join_build(tpch_paths):
    base, log = _cancel_run(
        {"spark.rapids.tpu.test.faults": "cancel:joins.build:1"},
        lambda s: tpch.q18(tpch.load(s, tpch_paths,
                                     files_per_partition=2)))
    _assert_no_leaks(base)
    assert ("cancel", "joins.build") in log


@pytest.mark.parametrize("pipeline", [True, False])
def test_cancel_mid_fetch(pipeline):
    rng = np.random.default_rng(5)
    t = pa.table({"k": pa.array(rng.integers(0, 16, 8000).astype(np.int64)),
                  "v": pa.array(rng.integers(0, 99, 8000).astype(np.int64))})
    base, log = _cancel_run(
        {"spark.rapids.tpu.test.faults": "cancel:fetch:1",
         "spark.rapids.tpu.pipeline.enabled": pipeline},
        lambda s: s.create_dataframe(t, num_partitions=3)
                   .repartition(4, "k")
                   .group_by("k").agg(F_.alias(F_.sum(F_.col("v")), "sv")))
    _assert_no_leaks(base)
    assert ("cancel", "fetch") in log


def test_cancel_while_queued_for_admission():
    """session.cancel() reaches a query still WAITING for admission: it
    unblocks immediately with the typed error, never runs, leaks nothing."""
    sched = SCHED.QueryScheduler.get()
    occupant = "occupant-queued-test"
    sched.submit(occupant, 1)
    saved = sched.max_concurrent
    sched.max_concurrent = 1
    cat = DeviceManager.get().catalog
    base = cat.num_buffers
    spark = TpuSession()
    outcome = {}

    def submit_blocked():
        df = spark.create_dataframe(pa.table({"a": [1, 2, 3]}))
        try:
            df.agg(F_.alias(F_.sum(F_.col("a")), "s")).collect()
            outcome["r"] = "completed"
        except SCHED.QueryCancelledError as e:
            outcome["r"] = ("cancelled", e.query_id)

    t = threading.Thread(target=submit_blocked, daemon=True)
    try:
        t.start()
        deadline = time.monotonic() + 5
        queued = None
        while time.monotonic() < deadline and queued is None:
            queued = next((q for q in spark.active_queries()
                           if q["state"] == "queued"), None)
            time.sleep(0.02)
        assert queued is not None, spark.active_queries()
        assert spark.cancel(queued["query"]) is True
        t.join(timeout=5)
        assert outcome["r"] == ("cancelled", queued["query"])
        assert spark.cancel(queued["query"]) is False   # already gone
    finally:
        sched.max_concurrent = saved
        sched.release(occupant)
    _assert_no_leaks(base)


def test_deadline_kills_query(tpch_paths):
    cat = DeviceManager.get().catalog
    base = cat.num_buffers
    spark = TpuSession({
        "spark.rapids.tpu.pipeline.enabled": True,
        "spark.rapids.tpu.scheduler.query.deadlineSeconds": 0.02})
    dfs = tpch.load(spark, tpch_paths, files_per_partition=2)
    with pytest.raises(SCHED.QueryDeadlineError):
        tpch.q18(dfs).collect()
    _assert_no_leaks(base)
    assert M.global_registry().metric(M.QUERIES_CANCELLED).value >= 1


def test_cancelled_query_counters_do_not_leak_to_peer():
    """A cancelled query and a clean concurrent peer: the peer's scoped
    resilience stays all-zero and its rows are unaffected."""
    rng = np.random.default_rng(9)
    t = pa.table({"k": pa.array(rng.integers(0, 8, 4000).astype(np.int64)),
                  "v": pa.array(rng.integers(0, 50, 4000).astype(np.int64))})
    spark = TpuSession()
    q = (spark.create_dataframe(t, num_partitions=2)
         .group_by("k").agg(F_.alias(F_.sum(F_.col("v")), "sv")).sort("k"))
    clean = q.collect().to_pylist()

    outcome = {}

    def victim():
        s2 = TpuSession({
            "spark.rapids.tpu.scheduler.query.deadlineSeconds": 0.005})
        df = (s2.create_dataframe(t, num_partitions=2)
              .group_by("k").agg(F_.alias(F_.sum(F_.col("v")), "sv")))
        try:
            df.collect()
            outcome["v"] = "completed"
        except SCHED.QueryCancelledError:
            outcome["v"] = "cancelled"

    th = threading.Thread(target=victim, daemon=True)
    th.start()
    df2 = (spark.create_dataframe(t, num_partitions=2)
           .group_by("k").agg(F_.alias(F_.sum(F_.col("v")), "sv"))
           .sort("k"))
    rows = df2.collect().to_pylist()
    th.join(timeout=10)
    assert rows == clean
    peer = df2._last_collector.query_resilience()
    assert not any(peer.values()), peer


# -- fair-share demotion (isolation under a peer's OOM) ------------------------

def test_on_oom_retry_demotes_over_share_victim(tmp_path, monkeypatch):
    """With 2 queries sharing a 1MB budget (fair share 512KB), a faulting
    query at 0 bytes triggers demotion of the lower-priority peer holding
    768KB: the peer's spillable device buffers move off-device and the
    demotion lands in the FAULTING query's scope."""

    class _StubDM:
        catalog = BufferCatalog(device_budget=1 << 20, host_budget=8 << 20,
                                spill_dir=str(tmp_path),
                                strict_budget=False)

    monkeypatch.setattr(DeviceManager, "_instance", _StubDM())
    cat = DeviceManager._instance.catalog
    sched = SCHED.QueryScheduler(max_concurrent=4)
    monkeypatch.setattr(SCHED.QueryScheduler, "_instance", sched)
    cv = M.QueryMetricsCollector(description="victim")
    cf = M.QueryMetricsCollector(description="faulting")
    # victim holds 768KB of spillable device state, over its 512KB share
    t = pa.table({"v": pa.array(np.arange(96 << 10, dtype=np.int64))})
    with M.collector_context(cv):
        bid = cat.add_batch(ColumnarBatch.from_arrow(t))
    assert cat.get_tier(bid) == "DEVICE"
    sched.submit(cv.query_id, 1, priority=0, description="victim")
    sched.submit(cf.query_id, 1, priority=1, description="faulting")
    with M.collector_context(cf):
        demoted = sched.on_oom_retry(cf.query_id)
    assert demoted > 0
    assert cat.get_tier(bid) != "DEVICE"          # victim's buffer spilled
    assert cf.query_resilience()[M.QUERY_DEMOTIONS] == 1
    sched.release(cv.query_id)
    sched.release(cf.query_id)
    cat.remove(bid)


# -- checksum satellites -------------------------------------------------------

def _make_batch(n, seed):
    rng = np.random.default_rng(seed)
    t = pa.table({"k": pa.array(rng.integers(0, 50, n).astype(np.int64)),
                  "v": pa.array(rng.normal(size=n))})
    return ColumnarBatch.from_arrow(t), t


def test_transport_crc_catches_corruption_and_retries():
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.shuffle.fetch import ShuffleFetchIterator
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    from spark_rapids_tpu.shuffle.transport import TcpTransport
    ShuffleBlockStore.reset()
    store = ShuffleBlockStore.get()
    batch, t = _make_batch(200, seed=21)
    sid = store.register_shuffle()
    store.write_block(sid, 0, batch)
    transport = TcpTransport(RapidsConf())
    F.configure("corrupt:transport.corrupt:1")
    try:
        addr = ("127.0.0.1", transport.port)
        it = ShuffleFetchIterator(
            [lambda: transport.make_client(addr)], sid, 0,
            max_retries=1, retry_backoff_s=0.0)
        out = [b.to_arrow() for b in it]
        assert len(out) == 1 and out[0].to_pylist() == t.to_pylist()
        assert len(it.errors) == 1 and "checksum mismatch" in it.errors[0]
        assert M.resilience_snapshot()[M.FETCH_RETRIES] == 1
        assert ("corrupt", "transport.corrupt") in F.injected_log()
    finally:
        F.reset()
        transport.shutdown()
        ShuffleBlockStore.reset()


def test_spill_crc_catches_disk_corruption(tmp_path):
    cat = BufferCatalog(device_budget=1 << 30, host_budget=0,
                        spill_dir=str(tmp_path), strict_budget=False,
                        spill_checksum=True)
    batch, _ = _make_batch(500, seed=22)
    F.configure("corrupt:spill.write:1")
    try:
        bid = cat.add_batch(batch)
        cat.synchronous_spill(0)         # device→host→disk (host budget 0)
        assert cat.get_tier(bid) == "DISK"
        with pytest.raises(SpillCorruptionError, match="checksum mismatch"):
            cat.acquire_batch(bid)
    finally:
        F.reset()
        cat.remove(bid)


def test_spill_crc_clean_roundtrip(tmp_path):
    cat = BufferCatalog(device_budget=1 << 30, host_budget=0,
                        spill_dir=str(tmp_path), strict_budget=False,
                        spill_checksum=True)
    batch, t = _make_batch(500, seed=23)
    bid = cat.add_batch(batch)
    cat.synchronous_spill(0)             # device→host→disk (host budget 0)
    assert cat.get_tier(bid) == "DISK"
    got = cat.acquire_batch(bid).to_arrow()
    assert got.to_pylist() == t.to_pylist()
    cat.remove(bid)


def test_spill_corruption_routes_through_exchange_recompute(monkeypatch):
    """A SpillCorruptionError surfacing from a shuffle block read is a
    fetch failure: the exchange invalidates the map outputs, recomputes,
    and the query still returns correct rows."""
    from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
    rng = np.random.default_rng(31)
    t = pa.table({"k": pa.array(rng.integers(0, 8, 4000).astype(np.int64)),
                  "v": pa.array(rng.integers(0, 99, 4000).astype(np.int64))})
    real = ShuffleBlockStore.read_partition_with_keys
    state = {"fired": False}

    def flaky(self, shuffle_id, reduce_id):
        if not state["fired"]:
            state["fired"] = True
            raise SpillCorruptionError("injected unspill checksum mismatch")
        return real(self, shuffle_id, reduce_id)

    monkeypatch.setattr(ShuffleBlockStore, "read_partition_with_keys", flaky)
    spark = TpuSession({"spark.rapids.tpu.pipeline.enabled": False})
    df = (spark.create_dataframe(t, num_partitions=2).repartition(3, "k")
          .group_by("k").agg(F_.alias(F_.sum(F_.col("v")), "sv")))
    rows = {r["k"]: r["sv"] for r in df.collect().to_pylist()}
    import collections
    exp = collections.defaultdict(int)
    for k, v in zip(t["k"].to_pylist(), t["v"].to_pylist()):
        exp[k] += v
    assert rows == dict(exp)
    assert state["fired"]
    assert M.resilience_snapshot()[M.FETCH_RECOMPUTES] >= 1


# -- fault-injection satellites ------------------------------------------------

def test_prob_faults_per_site_reproducible():
    """pPROB draws come from a per-(kind, site) stream: the schedule each
    site sees is a function of (seed, kind, site) ALONE, not of how other
    sites' hits interleave — the worker-thread reproducibility fix."""
    def schedule(order):
        F.configure("oom:site.a:p0.5,oom:site.b:p0.5", seed=11)
        fired = {"site.a": [], "site.b": []}
        for site in order:
            try:
                F.maybe_inject("oom", site)
                fired[site].append(False)
            except Exception:
                fired[site].append(True)
        F.reset()
        return fired

    grouped = schedule(["site.a"] * 12 + ["site.b"] * 12)
    interleaved = schedule(["site.a", "site.b"] * 12)
    assert grouped == interleaved
    assert any(grouped["site.a"]) and not all(grouped["site.a"])


def test_slow_fault_delays_without_raising():
    F.configure("slow:slow.site:1")
    t0 = time.perf_counter()
    F.maybe_inject("oom", "slow.site")   # slow satisfies any checkpoint kind
    dt = time.perf_counter() - t0
    F.maybe_inject("oom", "slow.site")   # count exhausted: no delay
    assert dt >= 0.2
    assert ("slow", "slow.site") in F.injected_log()


def test_corrupt_fault_only_fires_at_payload_sites():
    F.configure("corrupt:x:5")
    F.maybe_inject_any("x")              # corrupt never raises here
    data = b"some payload bytes"
    out = F.maybe_corrupt("x", data)
    assert out != data and len(out) == len(data)
    assert F.maybe_corrupt("y", data) == data     # unarmed site: untouched


# -- event-log rotation satellite ----------------------------------------------

def test_eventlog_rotation_bounds_files(tmp_path):
    import glob
    import os
    path = eventlog.configure(str(tmp_path), max_bytes=600, keep=2)
    for i in range(100):
        eventlog.emit("query.start", query=f"q{i:03d}",
                      description="rotation-test")
    eventlog.shutdown()
    assert os.path.getsize(path) <= 1200          # active file stays bounded
    rotated = sorted(glob.glob(path + ".*"))
    assert rotated == [path + ".1", path + ".2"]  # keep-N enforced, no .3
    # every retained line is still valid JSONL with a known event
    import json
    for p in [path] + rotated:
        for line in open(p):
            rec = json.loads(line)
            assert eventlog.validate_record(rec) == [], rec


def test_eventlog_rotation_via_session_conf(tmp_path):
    spark = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.eventLog.maxBytes": "1k",
        "spark.rapids.tpu.eventLog.keepFiles": 3})
    t = pa.table({"a": list(range(100))})
    for _ in range(8):
        spark.create_dataframe(t).agg(
            F_.alias(F_.sum(F_.col("a")), "s")).collect()
    eventlog.shutdown()
    import glob
    files = glob.glob(str(tmp_path / "events-*.jsonl*"))
    assert any(f.endswith(".1") for f in files), files   # rotation happened
    assert not any(f.endswith(".4") for f in files), files


# -- lifecycle events end to end -----------------------------------------------

def test_lifecycle_events_in_eventlog(tmp_path):
    import json
    spark = TpuSession({"spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    t = pa.table({"a": [1, 2, 3]})
    spark.create_dataframe(t).agg(
        F_.alias(F_.sum(F_.col("a")), "s")).collect()
    s2 = TpuSession({
        "spark.rapids.tpu.scheduler.query.deadlineSeconds": 1e-9})
    with pytest.raises(SCHED.QueryDeadlineError):
        s2.create_dataframe(t).agg(
            F_.alias(F_.sum(F_.col("a")), "s")).collect()
    eventlog.shutdown()
    path = next(tmp_path.glob("events-*.jsonl"))
    events = [json.loads(ln) for ln in open(path) if ln.strip()]
    names = [e["event"] for e in events]
    assert "query.admitted" in names
    assert "query.end" in names
    assert "query.deadline" in names
    adm = next(e for e in events if e["event"] == "query.admitted")
    assert adm["estimate_bytes"] >= 16 << 20 and "waited_s" in adm
    for e in events:
        assert eventlog.validate_record(e) == [], e
