"""Shuffle transport + compression + heartbeat tests (ring 1: protocol logic
without real multi-host hardware — reference RapidsShuffleTestHelper-based suites
exercise the tag protocol the same way, SURVEY.md §4)."""

import os
import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.shuffle.compression import (
    BatchedTableCompressor, CopyCodec, Lz4Codec, TableCompressionCodec,
    get_codec,
)
from spark_rapids_tpu.shuffle.heartbeat import (
    RapidsShuffleHeartbeatEndpoint, RapidsShuffleHeartbeatManager,
)
from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
from spark_rapids_tpu.shuffle.transport import (
    DEFAULT_MAX_FRAME_BYTES, InflightThrottle, LocalTransport,
    RapidsShuffleTransport, TcpTransport, TransportError, configure_socket,
    max_frame_bytes, recv_frame, send_frame, set_max_frame_bytes,
)


def make_batch(n=100, seed=0):
    r = np.random.default_rng(seed)
    t = pa.table({
        "a": pa.array([None if x % 7 == 0 else int(x)
                       for x in r.integers(0, 1000, n)], pa.int64()),
        "s": pa.array([f"row{i % 13}" for i in range(n)]),
    })
    return ColumnarBatch.from_arrow(t), t


# -- native codec ------------------------------------------------------------

def test_lz4_roundtrip_various():
    from spark_rapids_tpu.native import lz4_compress, lz4_decompress
    for data in [b"", b"x", b"abc" * 10000, os.urandom(65536),
                 np.arange(50000, dtype=np.int64).tobytes()]:
        assert lz4_decompress(lz4_compress(data), len(data)) == data


def test_corrupt_frame_detected():
    """LZ4 block format has no checksum, so the codec framing carries a crc32
    that decode() verifies."""
    data = b"hello world " * 1000
    codec = get_codec("lz4")
    enc = bytearray(codec.encode(data))
    enc[-3] ^= 0xFF
    with pytest.raises(ValueError):
        TableCompressionCodec.decode(bytes(enc))
    # structural corruption is caught by the decompressor itself
    from spark_rapids_tpu.native import lz4_decompress
    with pytest.raises(ValueError):
        lz4_decompress(b"\xff\xff\xff\xff", 100)


def test_codec_registry_and_framing():
    payload = np.arange(10000, dtype=np.int32).tobytes()
    for name in ("none", "copy", "lz4"):
        codec = get_codec(name)
        enc = codec.encode(payload)
        assert TableCompressionCodec.decode(enc) == payload
    assert isinstance(get_codec("lz4"), Lz4Codec)
    with pytest.raises(ValueError):
        get_codec("zstd9000")
    comp = BatchedTableCompressor(get_codec("lz4"), num_threads=3)
    frames = [os.urandom(1000) for _ in range(8)]
    out = comp.decompress_all(comp.compress_all(frames))
    assert out == frames


# -- transports --------------------------------------------------------------

@pytest.fixture
def store():
    ShuffleBlockStore.reset()
    yield ShuffleBlockStore.get()
    ShuffleBlockStore.reset()


def fill_shuffle(store, n_blocks=3, reduce_ids=(0, 1)):
    sid = store.register_shuffle()
    expect = {}
    for rid in reduce_ids:
        tbls = []
        for b in range(n_blocks):
            batch, t = make_batch(50 + 10 * b, seed=rid * 10 + b)
            store.write_block(sid, rid, batch)
            tbls.append(t)
        expect[rid] = pa.concat_tables(tbls)
    return sid, expect


def collect(client, sid, rid):
    tables = [b.to_arrow() for b in client.fetch_blocks(sid, rid)]
    return pa.concat_tables(tables)


def test_local_transport(store):
    sid, expect = fill_shuffle(store)
    client = LocalTransport().make_client()
    for rid in expect:
        got = collect(client, sid, rid)
        assert got.to_pylist() == expect[rid].to_pylist()


@pytest.mark.parametrize("codec", ["none", "lz4"])
def test_tcp_transport_roundtrip(store, codec):
    sid, expect = fill_shuffle(store)
    conf = RapidsConf({
        "spark.rapids.tpu.shuffle.compression.codec": codec,
        "spark.rapids.tpu.shuffle.bounceBuffers.size": "1k",  # force windowing
    })
    transport = TcpTransport(conf)
    try:
        client = transport.make_client(("127.0.0.1", transport.port))
        for rid in expect:
            got = collect(client, sid, rid)
            assert got.to_pylist() == expect[rid].to_pylist()
    finally:
        transport.shutdown()


def test_compression_tcp_only_serves_per_link_variants(store):
    """With compression.tcpOnly (the default) the lz4 codec only applies
    to genuinely cross-host peers: loopback fetchers get raw serialization
    frames (TPUB magic), tcp peers get codec frames (TPUC magic) that
    decode to the same bytes, each cached as its own variant."""
    sid, expect = fill_shuffle(store, n_blocks=1, reduce_ids=(0,))
    transport = TcpTransport(RapidsConf(
        {"spark.rapids.tpu.shuffle.compression.codec": "lz4"}))
    try:
        server = transport.server
        server._serving_link.link = "loopback"
        raw = server.serialized_blocks(sid, 0)
        assert raw and all(f[:4] == b"TPUB" for f in raw), \
            "loopback frames must stay uncompressed"
        server._serving_link.link = "tcp"
        comp = server.serialized_blocks(sid, 0)
        assert comp and all(f[:4] == b"TPUC" for f in comp), \
            "cross-host frames must be codec-framed"
        assert [TableCompressionCodec.decode(f) for f in comp] == raw
        # both variants live side by side in the cache
        assert {(sid, 0, False), (sid, 0, True)} <= \
            set(server._frame_cache)
        # a real loopback fetch round-trips on the raw variant
        client = transport.make_client(("127.0.0.1", transport.port))
        got = collect(client, sid, 0)
        assert got.to_pylist() == expect[0].to_pylist()
    finally:
        transport.shutdown()


def test_compression_tcp_only_disabled_compresses_every_link(store):
    """tcpOnly=false restores the compress-everything behavior (and the
    none codec never compresses regardless of link)."""
    sid, _ = fill_shuffle(store, n_blocks=1, reduce_ids=(0,))
    transport = TcpTransport(RapidsConf({
        "spark.rapids.tpu.shuffle.compression.codec": "lz4",
        "spark.rapids.tpu.shuffle.compression.tcpOnly": "false"}))
    try:
        transport.server._serving_link.link = "loopback"
        frames = transport.server.serialized_blocks(sid, 0)
        assert frames and all(f[:4] == b"TPUC" for f in frames)
    finally:
        transport.shutdown()
    none = TcpTransport(RapidsConf(
        {"spark.rapids.tpu.shuffle.compression.codec": "none"}))
    try:
        none.server._serving_link.link = "tcp"
        frames = none.server.serialized_blocks(sid, 0)
        assert frames and all(f[:4] == b"TPUB" for f in frames)
    finally:
        none.shutdown()


def test_tcp_transport_concurrent_fetches(store):
    sid, expect = fill_shuffle(store, n_blocks=4, reduce_ids=tuple(range(6)))
    conf = RapidsConf({
        "spark.rapids.tpu.shuffle.maxBytesInFlight": "8k",
        "spark.rapids.tpu.shuffle.bounceBuffers.size": "2k",
    })
    transport = TcpTransport(conf)
    results = {}
    errors = []

    def fetch(rid):
        try:
            client = transport.make_client(("127.0.0.1", transport.port))
            results[rid] = collect(client, sid, rid)
        except Exception as e:  # pragma: no cover
            errors.append(e)
    try:
        threads = [threading.Thread(target=fetch, args=(rid,))
                   for rid in expect]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        for rid in expect:
            assert results[rid].to_pylist() == expect[rid].to_pylist()
    finally:
        transport.shutdown()


def test_tcp_transport_unknown_shuffle_error(store):
    transport = TcpTransport(RapidsConf())
    try:
        client = transport.make_client(("127.0.0.1", transport.port))
        with pytest.raises(TransportError):
            list(client.fetch_blocks(12345, 0))
    finally:
        transport.shutdown()


def test_transport_factory_by_classname(store):
    conf = RapidsConf({"spark.rapids.tpu.shuffle.transport.class":
                       "spark_rapids_tpu.shuffle.transport.TcpTransport"})
    t = RapidsShuffleTransport.make_transport(conf)
    assert isinstance(t, TcpTransport)
    t.shutdown()


def test_inflight_throttle_bounds():
    th = InflightThrottle(100)
    state = {"cur": 0, "peak": 0}
    lock = threading.Lock()

    def worker(n):
        for _ in range(20):
            with th.acquire(n):
                with lock:
                    state["cur"] += n
                    state["peak"] = max(state["peak"], state["cur"])
                with lock:
                    state["cur"] -= n
    threads = [threading.Thread(target=worker, args=(40,)) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert state["peak"] <= 120  # 100 limit + one oversubscribed acquire


def test_oversized_block_still_transfers():
    """A single block larger than the inflight limit must not deadlock
    (reference: throttle admits one request when idle)."""
    th = InflightThrottle(10)
    with th.acquire(1000):
        pass


# -- frame hardening (wire fuzz: corrupt/truncated prefixes) ------------------

def _socketpair():
    import socket
    return socket.socketpair()


def test_recv_frame_rejects_oversized_length_before_allocating():
    """A corrupt length prefix must raise TransportError instead of
    attempting a multi-GB read (transport.maxFrameBytes)."""
    import struct
    a, b = _socketpair()
    try:
        # a header claiming a 1 TB payload, then nothing
        a.sendall(struct.pack("<BI", 2, (1 << 32) - 1))
        set_max_frame_bytes(1 << 20)
        with pytest.raises(TransportError, match="maxFrameBytes"):
            recv_frame(b)
    finally:
        set_max_frame_bytes(DEFAULT_MAX_FRAME_BYTES)
        a.close()
        b.close()


def test_recv_frame_explicit_limit_and_exact_bound():
    a, b = _socketpair()
    try:
        send_frame(a, 7, b"x" * 64)
        msg, payload = recv_frame(b, max_bytes=64)   # exactly at the bound
        assert msg == 7 and payload == b"x" * 64
        send_frame(a, 7, b"y" * 65)
        with pytest.raises(TransportError, match="maxFrameBytes"):
            recv_frame(b, max_bytes=64)
    finally:
        a.close()
        b.close()


def test_recv_frame_truncated_header_and_payload():
    a, b = _socketpair()
    try:
        a.sendall(b"\x02\xff")   # 2 of 5 header bytes, then close
        a.close()
        with pytest.raises(TransportError, match="peer closed"):
            recv_frame(b)
    finally:
        b.close()
    import struct
    a, b = _socketpair()
    try:
        # full header promising 100 bytes, only 10 delivered, then close
        a.sendall(struct.pack("<BI", 2, 100) + b"z" * 10)
        a.close()
        with pytest.raises(TransportError, match="peer closed"):
            recv_frame(b)
    finally:
        b.close()


def test_tcp_transport_applies_max_frame_conf(store):
    transport = TcpTransport(RapidsConf({
        "spark.rapids.tpu.shuffle.transport.maxFrameBytes": "2m"}))
    try:
        assert max_frame_bytes() == 2 << 20
    finally:
        transport.shutdown()
        set_max_frame_bytes(DEFAULT_MAX_FRAME_BYTES)


def test_configure_socket_sets_keepalive_nodelay_timeout():
    import socket
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    a = socket.create_connection(srv.getsockname(), timeout=5)
    b, _ = srv.accept()
    try:
        configure_socket(a, timeout_s=12.5)
        assert a.getsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE) == 1
        assert a.getsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY) != 0
        assert a.gettimeout() == 12.5
        configure_socket(b)          # no timeout: blocking socket untouched
        assert b.gettimeout() is None
    finally:
        a.close()
        b.close()
        srv.close()


def test_transport_error_pickle_roundtrip_retryable():
    """TransportError crosses the serving wire typed: the pickle must keep
    the message, the class, and the retryable marker."""
    import pickle
    e = TransportError("peer ('1.2.3.4', 9) fetch failed: ECONNRESET")
    rt = pickle.loads(pickle.dumps(e))
    assert type(rt) is TransportError
    assert str(rt) == str(e)
    assert rt.retryable is True


# -- heartbeat ---------------------------------------------------------------

def test_heartbeat_registration_and_peers():
    mgr = RapidsShuffleHeartbeatManager(timeout_s=10)
    a = RapidsShuffleHeartbeatEndpoint(mgr, "exec-a", "h1", 1111,
                                       interval_s=600)
    b = RapidsShuffleHeartbeatEndpoint(mgr, "exec-b", "h2", 2222,
                                       interval_s=600)
    try:
        # late joiner saw the earlier peer at registration
        assert [p.executor_id for p in b.known_peers()] == ["exec-a"]
        # earlier peer learns the late joiner on its next beat
        a.beat_now()
        assert [p.executor_id for p in a.known_peers()] == ["exec-b"]
        assert {p.executor_id for p in mgr.live_peers()} == {"exec-a", "exec-b"}
    finally:
        a.close()
        b.close()


def test_heartbeat_expiry_failure_detection():
    mgr = RapidsShuffleHeartbeatManager(timeout_s=0.05)
    mgr.register("exec-x", "h", 1)
    import time
    time.sleep(0.1)
    dead = mgr.expire_dead()
    assert [p.executor_id for p in dead] == ["exec-x"]
    assert mgr.live_peers() == []
    with pytest.raises(KeyError):
        mgr.heartbeat("exec-x")


def test_unregister_invalidates_server_cache(store):
    sid, expect = fill_shuffle(store, n_blocks=1, reduce_ids=(0,))
    transport = TcpTransport(RapidsConf())
    try:
        client = transport.make_client(("127.0.0.1", transport.port))
        got = collect(client, sid, 0)
        assert got.num_rows == expect[0].num_rows
        assert any(k[:2] == (sid, 0)
                   for k in transport.server._frame_cache)
        store.unregister_shuffle(sid)
        assert not any(k[:2] == (sid, 0)
                       for k in transport.server._frame_cache)
    finally:
        transport.shutdown()


# -- fetch failure → retry → failover → recompute ----------------------------

def test_fetch_iterator_retries_then_succeeds(store):
    """A peer that fails twice then recovers: the iterator retries the SAME
    peer (fresh client each attempt) and yields the full partition once."""
    from spark_rapids_tpu.shuffle.fetch import ShuffleFetchIterator

    batch, t = make_batch(50, seed=3)
    sid = store.register_shuffle()
    store.write_block(sid, 0, batch)
    fails = {"n": 2}

    class FlakyClient:
        def fetch_blocks(self, shuffle_id, reduce_id):
            if fails["n"] > 0:
                fails["n"] -= 1
                yield from store.read_partition(shuffle_id, reduce_id)
                raise TransportError("connection reset mid-stream")
            yield from store.read_partition(shuffle_id, reduce_id)

    it = ShuffleFetchIterator([FlakyClient], sid, 0, max_retries=3,
                              retry_backoff_s=0.0)
    got = [b.to_arrow() for b in it]
    assert len(got) == 1 and got[0].num_rows == 50
    assert len(it.errors) == 2  # partial stream was never emitted twice


def test_fetch_iterator_fails_over_to_replica(store):
    from spark_rapids_tpu.shuffle.fetch import ShuffleFetchIterator

    batch, t = make_batch(30, seed=4)
    sid = store.register_shuffle()
    store.write_block(sid, 0, batch)

    class DeadClient:
        def fetch_blocks(self, shuffle_id, reduce_id):
            raise TransportError("peer unreachable")
            yield  # pragma: no cover

    class GoodClient:
        def fetch_blocks(self, shuffle_id, reduce_id):
            yield from store.read_partition(shuffle_id, reduce_id)

    it = ShuffleFetchIterator([DeadClient, GoodClient], sid, 0,
                              max_retries=1, retry_backoff_s=0.0)
    got = list(it)
    assert len(got) == 1
    assert len(it.errors) == 2  # both attempts against the dead peer logged


def test_fetch_iterator_recomputes_when_all_peers_dead(store):
    from spark_rapids_tpu.shuffle.fetch import ShuffleFetchIterator

    batch, t = make_batch(20, seed=5)

    class DeadClient:
        def fetch_blocks(self, shuffle_id, reduce_id):
            raise TransportError("peer unreachable")
            yield  # pragma: no cover

    recomputed = {"n": 0}

    def recompute():
        recomputed["n"] += 1
        yield batch

    it = ShuffleFetchIterator([DeadClient], 999, 0, recompute=recompute,
                              max_retries=2, retry_backoff_s=0.0)
    got = list(it)
    assert len(got) == 1 and recomputed["n"] == 1

    # without a recompute callback the error surfaces as TransportError
    it2 = ShuffleFetchIterator([DeadClient], 999, 0, max_retries=1,
                               retry_backoff_s=0.0)
    with pytest.raises(TransportError):
        list(it2)


def test_exchange_recomputes_map_stage_on_fetch_failure():
    """A TransportError surfaced from a reduce read invalidates the map
    outputs and recomputes them (TransferError→FetchFailed→stage retry,
    RapidsShuffleIterator.scala:82)."""
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioner
    from spark_rapids_tpu.expr.core import col

    _, t = make_batch(80, seed=6)
    ex = ShuffleExchangeExec(
        HashPartitioner([col("a")], 3), ArrowScanExec([t]),
        conf=RapidsConf())

    real_read = ShuffleBlockStore.read_partition
    state = {"fails": 1, "map_runs": 0}
    real_map = ShuffleExchangeExec._run_map_stage

    def flaky_read(self, shuffle_id, split):
        if state["fails"] > 0:
            state["fails"] -= 1
            raise TransportError("injected fetch failure")
        return real_read(self, shuffle_id, split)

    def counting_map(self):
        state["map_runs"] += 1
        return real_map(self)

    ShuffleBlockStore.read_partition = flaky_read
    ShuffleExchangeExec._run_map_stage = counting_map
    try:
        out = ex.execute_collect()
    finally:
        ShuffleBlockStore.read_partition = real_read
        ShuffleExchangeExec._run_map_stage = real_map
    assert out.num_rows == 80
    assert sorted(out.column("a").to_pylist(), key=lambda v: (v is None, v)) \
        == sorted(t.column("a").to_pylist(), key=lambda v: (v is None, v))
    assert state["map_runs"] == 2  # original + one recompute
