"""Memory observability plane: allocation-site heap profiler, watermark
timelines, end-of-query leak detection.

Covers: site/node tagging through the ambient alloc-site + fault-scope
ladder, per-site/per-query accounting across spill transitions, the
watermark timeline (event-log samples + Chrome counter-track records,
monotone under the OOM-split chaos path), the end-of-query leak detector
(proven by the `leak` fault kind: event + resilience counter + reclaim +
strict-mode escalation), the OOM-dump site breakdown, the profiler
`memory` subcommand incl. --diff math, and the STATS memory gauge
families.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu import config as CFG
from spark_rapids_tpu.runtime import eventlog as EL
from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.runtime import memory as mem
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.session import TpuSession

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _profiler():
    spec = importlib.util.spec_from_file_location(
        "srt_profiler", os.path.join(REPO, "tools", "profiler.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_state():
    EL.shutdown()
    faults.reset()
    M.reset_global_registry()
    tracing.clear_events()
    mem.set_profile_options(
        CFG.MEMORY_WATERMARK_INTERVAL.default, CFG.MEMORY_PROFILE_TOPK.default)
    yield
    EL.shutdown()
    tracing.shutdown_spans()
    faults.reset()
    M.reset_global_registry()
    tracing.clear_events()
    mem.set_profile_options(
        CFG.MEMORY_WATERMARK_INTERVAL.default, CFG.MEMORY_PROFILE_TOPK.default)


def make_batch(rows=256, seed=0):
    import numpy as np
    from spark_rapids_tpu.plan.nodes import ScanNode
    r = np.random.default_rng(seed)
    tbl = pa.table({"a": r.integers(0, 1000, rows),
                    "b": r.normal(0, 1, rows)})
    node = ScanNode([tbl])
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    return ColumnarBatch.from_arrow(tbl)


def _catalog(**kw):
    kw.setdefault("device_budget", 1 << 30)
    kw.setdefault("host_budget", 1 << 30)
    return mem.BufferCatalog(**kw)


# -- site tagging + accounting ------------------------------------------------

def test_alloc_site_tagging_and_snapshot():
    cat = _catalog()
    with mem.alloc_site("test.site"):
        bid = cat.add_batch(make_batch())
    snap = cat.heap_snapshot()
    sites = {s["site"]: s for s in snap["sites"]}
    assert "test.site" in sites
    e = sites["test.site"]
    assert e["live_bytes"] > 0 and e["device_bytes"] == e["live_bytes"]
    assert e["allocs"] == 1 and e["frees"] == 0
    assert e["tiers"] == {mem.TierEnum.DEVICE: e["live_bytes"]}
    assert snap["watermark_bytes"] >= e["live_bytes"]
    cat.remove(bid)
    snap2 = cat.heap_snapshot()
    e2 = {s["site"]: s for s in snap2["sites"]}["test.site"]
    assert e2["live_bytes"] == 0 and e2["frees"] == 1
    # process-lifetime peak/cumulative survive the free
    assert e2["peak_device_bytes"] == e["live_bytes"]
    assert e2["cumulative_bytes"] == e["live_bytes"]


def test_site_falls_back_to_fault_scope_then_unattributed():
    cat = _catalog()
    with faults.scope("joins.build"):
        b1 = cat.add_batch(make_batch())
    b2 = cat.add_batch(make_batch())
    sites = {s["site"] for s in cat.heap_snapshot()["sites"]}
    assert "joins.build" in sites
    assert mem.UNATTRIBUTED_SITE in sites
    assert cat.buffer_site(b1) == "joins.build"
    assert cat.buffer_site(b2) == mem.UNATTRIBUTED_SITE


def test_site_live_tracks_spill_transitions():
    # tiny device budget: the second registration spills the first to host
    b = make_batch()
    sz = b.device_memory_size()
    cat = _catalog(device_budget=int(sz * 1.5))
    with mem.alloc_site("spillee"):
        cat.add_batch(make_batch(seed=1), priority=-100.0)
    with mem.alloc_site("resident"):
        cat.add_batch(make_batch(seed=2))
    sites = {s["site"]: s for s in cat.heap_snapshot()["sites"]}
    assert sites["spillee"]["device_bytes"] == 0
    assert sites["spillee"]["tiers"].get(mem.TierEnum.HOST, 0) > 0
    assert sites["spillee"]["live_bytes"] > 0      # still live, other tier
    assert sites["resident"]["device_bytes"] > 0


def test_oom_dump_names_culprit_sites(tmp_path):
    b = make_batch()
    sz = b.device_memory_size()
    # one registration alone exceeds the lenient budget with nothing else
    # to spill: the catalog stays over budget and dumps allocator state
    cat = _catalog(device_budget=int(sz * 0.5), strict_budget=False,
                   oom_dump_dir=str(tmp_path))
    with mem.alloc_site("hog.subsystem"):
        cat.add_batch(make_batch(seed=1))
    dumps = list(tmp_path.glob("hbm-oom-*.txt"))
    assert dumps, "no OOM dump written"
    text = dumps[0].read_text()
    assert "top sites by live device bytes:" in text
    assert "site=hog.subsystem" in text
    # the per-buffer table names site/node/query columns
    assert "buffer_id\ttier\tsize\tpriority\tsite\tnode\tquery" in text


# -- watermark timeline -------------------------------------------------------

def test_watermark_events_and_counter_track(tmp_path):
    EL.configure(str(tmp_path))
    tracing.configure_spans(str(tmp_path), process="driver")
    cat = _catalog(watermark_interval_bytes=1)
    ids = [cat.add_batch(make_batch(seed=i)) for i in range(4)]
    for bid in ids:
        cat.remove(bid)
    EL.shutdown()
    tracing.shutdown_spans()
    events = [json.loads(ln) for ln in
              open(next(tmp_path.glob("events-*.jsonl")))]
    wms = [e for e in events if e["event"] == "memory.watermark"]
    assert len(wms) >= 4
    for e in wms:
        assert not EL.validate_record(e), EL.validate_record(e)
        assert e["device_bytes"] >= 0 and "sites" in e
    marks = [e["watermark_bytes"] for e in wms]
    assert marks == sorted(marks), "watermark ran backwards"
    spans = [json.loads(ln) for ln in
             open(next(tmp_path.glob("spans-*.jsonl")))]
    counters = [s for s in spans if s["ph"] == "C" and s["name"] == "memory"]
    assert counters, "no Chrome counter-track samples"
    for s in counters:
        assert not tracing.validate_span(s), tracing.validate_span(s)
        assert set(s["args"]) == {"device_bytes", "host_bytes", "disk_bytes"}


def test_counter_samples_render_as_chrome_counter_lane(tmp_path):
    tracing.configure_spans(str(tmp_path), process="driver")
    with tracing.trace_context("trace-x"):
        tracing.counter("memory", {"device_bytes": 123, "host_bytes": 0,
                                   "disk_bytes": 0})
        with tracing.span("query"):
            pass
    tracing.shutdown_spans()
    prof = _profiler()
    records, violations = prof.load_spans(str(tmp_path))
    assert violations == []
    _, spans = prof.pick_trace(records, "trace-x")
    trace = prof.chrome_trace(spans)
    cs = [e for e in trace["traceEvents"] if e["ph"] == "C"]
    assert len(cs) == 1
    # counter args are numeric series ONLY — a trace-id string would become
    # a bogus stacked series in Perfetto
    assert cs[0]["args"] == {"device_bytes": 123, "host_bytes": 0,
                             "disk_bytes": 0}


# -- end-of-query leak detection ---------------------------------------------

def _join_dfs(spark):
    df1 = spark.create_dataframe(pa.table(
        {"k": list(range(400)), "v": [float(i) for i in range(400)]}))
    df2 = spark.create_dataframe(pa.table(
        {"k": list(range(0, 800, 2)), "w": [float(i) for i in range(400)]}))
    return df1.join(df2, on="k").agg(F.sum("v").alias("s"))


def test_leak_fault_detected_counted_and_reclaimed(tmp_path):
    spark = TpuSession({
        "spark.rapids.tpu.test.faults": "leak:joins.build:1",
        "spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    out = _join_dfs(spark).collect()
    assert out.num_rows == 1
    assert ("leak", "joins.build") in faults.injected_log()
    # detector: resilience counter (process-wide AND query-scoped) + event
    assert M.resilience_snapshot()[M.MEMORY_LEAKS] == 1
    qm = spark.last_query_metrics()
    assert qm.query_resilience()[M.MEMORY_LEAKS] == 1
    evs = tracing.recent_events("memory.leak")
    assert len(evs) == 1
    assert evs[0][1]["sites"] == {"joins.build": evs[0][1]["bytes"]}
    assert evs[0][1]["query"] == qm.query_id
    # reclaimed: nothing is still tagged to the finished query
    from spark_rapids_tpu.runtime.memory import DeviceManager
    assert qm.query_id not in DeviceManager.get().catalog.query_device_bytes()
    EL.shutdown()
    log = [json.loads(ln) for ln in open(next(tmp_path.glob("*.jsonl")))]
    leaks = [e for e in log if e["event"] == "memory.leak"]
    assert len(leaks) == 1 and leaks[0]["query"] == qm.query_id


def test_clean_run_reports_zero_leaks(tmp_path):
    spark = TpuSession({"spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    _join_dfs(spark).collect()
    assert M.resilience_snapshot()[M.MEMORY_LEAKS] == 0
    assert tracing.recent_events("memory.leak") == []
    EL.shutdown()
    log = [json.loads(ln) for ln in open(next(tmp_path.glob("*.jsonl")))]
    assert not [e for e in log if e["event"] == "memory.leak"]
    # clean query: every alloc was freed (summary riding query.end)
    end = [e for e in log if e["event"] == "query.end"][-1]
    assert end["memory"]["peak_device_bytes"] > 0
    assert "joins.build" in end["memory"]["sites"]


def test_leak_strict_mode_raises():
    spark = TpuSession({
        "spark.rapids.tpu.test.faults": "leak:joins.build:1",
        "spark.rapids.tpu.memory.leak.strict": "true"})
    with pytest.raises(mem.MemoryLeakError, match="joins.build"):
        _join_dfs(spark).collect()
    # the strict escalation still reclaimed the buffers first
    from spark_rapids_tpu.runtime.memory import DeviceManager
    qid = spark.last_query_metrics().query_id
    assert qid not in DeviceManager.get().catalog.query_device_bytes()


def test_leak_check_disabled_leaves_buffers():
    spark = TpuSession({
        "spark.rapids.tpu.test.faults": "leak:joins.build:1",
        "spark.rapids.tpu.memory.leak.check": "false"})
    _join_dfs(spark).collect()
    assert M.resilience_snapshot()[M.MEMORY_LEAKS] == 0
    from spark_rapids_tpu.runtime.memory import DeviceManager
    cat = DeviceManager.get().catalog
    qid = spark.last_query_metrics().query_id
    leaked = cat.query_device_bytes().get(qid, 0)
    assert leaked > 0, "disabled detector should leave the leak in place"
    # manual cleanup so later tests see a clean catalog
    with cat._lock:
        stale = [b.buffer_id for b in cat._buffers.values()
                 if b.query == qid]
    for bid in stale:
        cat.remove(bid)


def test_cached_partitions_are_retained_not_leaks():
    spark = TpuSession()
    df = spark.create_dataframe(pa.table(
        {"k": [1, 2, 3] * 50, "v": [1.0] * 150})).cache()
    assert df.filter(F.col("k") > 1).count() == 100
    # the cache's device partitions outlive the query by design: no leak
    assert M.resilience_snapshot()[M.MEMORY_LEAKS] == 0
    assert tracing.recent_events("memory.leak") == []
    snap = spark.heap_snapshot()
    sites = {s["site"]: s for s in snap["sites"]}
    assert sites.get("cache.device", {}).get("retained_bytes", 0) > 0
    df.unpersist()


# -- q18 end to end -----------------------------------------------------------

def test_q18_join_build_bytes_land_on_join_node(tmp_path):
    from spark_rapids_tpu.benchmarks import tpch
    paths = tpch.generate(0.005, str(tmp_path / "tpch"))
    spark = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path / "log"),
        "spark.rapids.tpu.memory.profile.watermarkIntervalBytes": "1k"})
    dfs = tpch.load(spark, paths)
    tpch.q18(dfs).collect()
    qm = spark.last_query_metrics()
    msum = qm.memory
    assert msum is not None and msum["peak_device_bytes"] > 0
    build = msum["sites"].get("joins.build")
    assert build is not None and build["peak_bytes"] > 0
    # the build bytes are attributed to a JOIN plan node, by id
    names = {n["id"]: n["name"] for n in qm.node_summaries()
             if n["id"] is not None}
    assert build["nodes"], "join build carried no node attribution"
    assert any(("Join" in names.get(nid, "")
                or "Broadcast" in names.get(nid, ""))
               for nid in build["nodes"]), \
        {nid: names.get(nid) for nid in build["nodes"]}
    # clean run: zero leaks, and ≥90% of the recorded peak is attributed
    # to NAMED sites (the acceptance bar for the heap profiler)
    EL.shutdown()
    log_dir = tmp_path / "log"
    records = [json.loads(ln)
               for p in sorted(log_dir.glob("events-*.jsonl"))
               for ln in open(p) if ln.strip()]
    assert not [e for e in records if e["event"] == "memory.leak"]
    prof = _profiler()
    memo = prof.analyze_memory(records)
    assert memo["peak_attribution"] is not None
    assert memo["peak_attribution"] >= 0.9, memo["peak"]


def test_q18_watermark_monotone_under_oom_split_chaos(tmp_path):
    from spark_rapids_tpu.benchmarks import tpch
    paths = tpch.generate(0.005, str(tmp_path / "tpch"))
    spark = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path / "log"),
        "spark.rapids.tpu.memory.profile.watermarkIntervalBytes": "1k",
        "spark.rapids.tpu.test.faults": "oom:joins.build:2",
        # sf0.005 batches sit under the default 64k split floor; the chaos
        # ladder needs real splits to recover two back-to-back OOMs
        "spark.rapids.tpu.memory.retry.splitFloorBytes": "1b"})
    dfs = tpch.load(spark, paths)
    tpch.q18(dfs).collect()
    qm = spark.last_query_metrics()
    res = qm.query_resilience()
    assert res[M.NUM_OOM_RETRIES] >= 1, res
    assert res[M.MEMORY_LEAKS] == 0, res   # recovery must not leak
    EL.shutdown()
    records = [json.loads(ln)
               for p in sorted((tmp_path / "log").glob("events-*.jsonl"))
               for ln in open(p) if ln.strip()]
    wms = [e for e in records if e["event"] == "memory.watermark"]
    assert len(wms) >= 2, "chaos run produced too few watermark samples"
    marks = [e["watermark_bytes"] for e in wms]
    assert marks == sorted(marks), "watermark regressed under OOM chaos"
    assert not [e for e in records if e["event"] == "memory.leak"]


# -- profiler memory subcommand ----------------------------------------------

def _fake_log(path, sites_a):
    """Minimal event log with one watermark + one snapshot."""
    recs = [
        {"event": "memory.watermark", "ts": 1.0, "t": 1.0, "pid": 1,
         "query": "qx", "node": None, "device_bytes": 100, "host_bytes": 0,
         "disk_bytes": 0, "watermark_bytes": 100, "budget": 1000,
         "sites": {s: e["live_bytes"] for s, e in sites_a.items()}},
        {"event": "memory.snapshot", "ts": 2.0, "t": 2.0, "pid": 1,
         "query": "qx", "node": None, "device_bytes": 100, "host_bytes": 0,
         "disk_bytes": 0, "watermark_bytes": 100, "device_budget": 1000,
         "buffers": len(sites_a),
         "sites": [dict(site=s, **e) for s, e in sites_a.items()]},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_snapshot_diff_math(tmp_path):
    prof = _profiler()
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    _fake_log(a, {"joins.build": {"live_bytes": 60, "peak_device_bytes": 80,
                                  "cumulative_bytes": 100},
                  "gone.site": {"live_bytes": 40, "peak_device_bytes": 40,
                                "cumulative_bytes": 40}})
    _fake_log(b, {"joins.build": {"live_bytes": 90, "peak_device_bytes": 95,
                                  "cumulative_bytes": 200},
                  "new.site": {"live_bytes": 10, "peak_device_bytes": 10,
                               "cumulative_bytes": 10}})
    ra, _ = prof.load_log(str(a))
    rb, _ = prof.load_log(str(b))
    d = prof.diff_memory(prof.analyze_memory(ra), prof.analyze_memory(rb))
    rows = {r["site"]: r for r in d["sites"]}
    jb = rows["joins.build"]
    assert (jb["live_a"], jb["live_b"], jb["delta_live"]) == (60, 90, 30)
    assert jb["delta_peak"] == 15 and jb["delta_cumulative"] == 100
    assert rows["gone.site"]["delta_live"] == -40
    assert rows["new.site"]["delta_live"] == 10
    assert d["totals"]["device_bytes"] == 0   # both snapshots read 100


def test_profiler_memory_cli(tmp_path):
    spark = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.memory.profile.watermarkIntervalBytes": "1k"})
    _join_dfs(spark).collect()
    EL.shutdown()
    log = str(next(tmp_path.glob("events-*.jsonl")))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "profiler.py"),
         "memory", log], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "heap snapshot" in out.stdout
    assert "watermark timeline" in out.stdout
    assert "joins.build" in out.stdout
    assert "no leaks detected" in out.stdout
    # --diff against itself: all deltas zero, rc 0
    diff = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "profiler.py"),
         "memory", log, "--diff", log], capture_output=True, text=True)
    assert diff.returncode == 0, diff.stderr
    assert "memory diff" in diff.stdout
    # a log with no memory-plane events fails loudly (CI gate contract)
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    bad = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "profiler.py"),
         "memory", str(empty)], capture_output=True, text=True)
    assert bad.returncode == 1


# -- serving surface ----------------------------------------------------------

def test_stats_render_memory_gauges():
    spark = TpuSession()
    _join_dfs(spark).collect()
    from spark_rapids_tpu.runtime.endpoint import render_stats
    text = render_stats()
    assert "srt_hbm_watermark_bytes" in text
    # site gauges appear when something is live; the watermark gauge is
    # unconditional once the device is initialized
    from spark_rapids_tpu.runtime.memory import DeviceManager
    assert DeviceManager.get().catalog.watermark_bytes > 0


def test_session_heap_snapshot_shape():
    spark = TpuSession()
    _join_dfs(spark).collect()
    snap = spark.heap_snapshot()
    assert {"device_bytes", "host_bytes", "disk_bytes", "watermark_bytes",
            "device_budget", "buffers", "sites"} <= set(snap)
    for e in snap["sites"]:
        assert {"site", "tiers", "live_bytes", "peak_device_bytes",
                "cumulative_bytes", "allocs", "frees", "nodes"} <= set(e)
