"""Fine-grained fault recovery in the MiniCluster driver scheduler.

Mirrors Spark's task-level fault-tolerance contracts (task retry with
attempt limits, executor exclusion, FetchFailed → recompute only the lost
map outputs) against the driver scheduler in cluster/minicluster.py: an
injected executor SIGKILL (`exec_kill` fault kind) mid-stage must recover
through the lineage-scoped ladder — respawn the slot, re-run ONLY the dead
peer's map splits under a bumped shuffle epoch, re-fetch — to a result
bit-identical with a clean run, with recovery cost proportional to the
loss (proven by the resilience counters) and the whole-query `_heal()`
fallback never firing."""

import time

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu.cluster import MiniCluster
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.runtime import faults as FLT
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.session import TpuSession

N_EXEC = 3
N_SPLITS = 6


@pytest.fixture(autouse=True)
def _clean_chaos_state():
    FLT.reset()
    tracing.clear_events()
    yield
    FLT.reset()
    tracing.clear_events()


@pytest.fixture(scope="module")
def spark():
    return TpuSession()


@pytest.fixture(scope="module")
def df(spark):
    rng = np.random.default_rng(7)
    t = pa.table({"k": pa.array(rng.integers(0, 13, 3000), type=pa.int64()),
                  "v": pa.array(rng.random(3000))})
    return (spark.create_dataframe(t, num_partitions=N_SPLITS)
            .group_by(F.col("k")).agg(F.sum(F.col("v")).alias("s")))


@pytest.fixture(scope="module")
def clean_table(df):
    """The fault-free oracle: the SAME query on the SAME cluster shape with
    no chaos armed — every recovery test must reproduce these bytes."""
    with MiniCluster(n_executors=N_EXEC, platform="cpu") as c:
        return c.collect(df)


def _run_chaos(df, settings, no_heal=True, warm=False):
    """Collect `df` on a 3-executor cluster with `settings`; returns
    (table, resilience-counter deltas, cluster stats dict)."""
    base = M.resilience_snapshot()
    conf = RapidsConf(settings)
    with MiniCluster(n_executors=N_EXEC, conf=conf, platform="cpu") as c:
        heals = []
        orig = c._heal
        c._heal = lambda: (heals.append(1), orig())[-1]
        if warm:
            c.collect(df)       # absorb cold-compile latency (see @SKIP)
        got = c.collect(df)
        stats = {"heals": len(heals), "blacklist": set(c._blacklist),
                 "gen": list(c._gen),
                 "alive": [p.is_alive() for p in c._procs]}
    end = M.resilience_snapshot()
    delta = {k: end[k] - base[k] for k in end if end[k] - base[k]}
    if no_heal:
        assert stats["heals"] == 0, \
            f"whole-query heal fired; partial recovery expected ({delta})"
    return got, delta, stats


def test_exec_kill_mid_map_stage_bit_identical(df, clean_table):
    """SIGKILL one of 3 executors mid-map-stage (after its first map task
    parked blocks, via @SKIP): the driver must recompute ONLY the dead
    peer's splits and still produce the clean run's exact bytes."""
    got, delta, stats = _run_chaos(
        df, {"spark.rapids.tpu.test.faults": "exec_kill:cluster.map.0:1@1"})
    assert got.equals(clean_table), "recovered result is not bit-identical"
    assert delta.get("executorsLost", 0) >= 1
    assert delta.get("stagePartialRecomputes", 0) >= 1
    # proportionality: strictly fewer map tasks re-ran than a full stage
    assert 1 <= delta.get("mapTasksRecomputed", 0) < N_SPLITS, delta
    assert all(stats["alive"]), "pool not restored"


def test_exec_kill_mid_result_stage_bit_identical(df, clean_table):
    got, delta, stats = _run_chaos(
        df, {"spark.rapids.tpu.test.faults": "exec_kill:cluster.result.1:1"})
    assert got.equals(clean_table)
    assert delta.get("executorsLost", 0) >= 1
    # the dead peer hosted map splits reducers still need: partial recompute
    assert delta.get("stagePartialRecomputes", 0) >= 1
    assert all(stats["alive"])
    names = {n for n, _ in tracing.recent_events()}
    assert {"executor.lost", "stage.recompute.partial"} <= names, names


def test_partial_recompute_covers_exactly_the_lost_splits(df, clean_table):
    """Kill an executor AFTER the map stage completed, with the host map
    captured first: the recompute counter must equal the dead peer's split
    count, and only the dead slot's incarnation may bump (no pool heal)."""
    base = M.resilience_snapshot()
    with MiniCluster(n_executors=N_EXEC, platform="cpu") as c:
        state = {"lost": None}

        def kill_zero(cl):
            if state["lost"] is None:
                st = cl._tracker.state(cl._tracker.sids()[0])
                state["lost"] = sorted(
                    s for s, h in st.hosts.items() if h == 0)
                cl._procs[0].kill()
                cl._procs[0].join(timeout=5)

        c._after_stage_hook = kill_zero
        got = c.collect(df)
        gens = list(c._gen)
    delta = {k: v - base[k]
             for k, v in M.resilience_snapshot().items() if v - base[k]}
    assert got.equals(clean_table)
    assert 1 <= len(state["lost"]) < N_SPLITS
    assert delta.get("mapTasksRecomputed", 0) == len(state["lost"]), \
        (delta, state["lost"])
    assert gens[0] == 2 and gens[1:] == [1, 1], gens


def test_task_failure_retries_then_blacklists(df, clean_table):
    """Two injected task failures on the same executor: each retry lands
    elsewhere, the second strike blacklists the slot, the query succeeds."""
    got, delta, stats = _run_chaos(
        df, {"spark.rapids.tpu.test.faults": "error:cluster.map.1:2"})
    assert got.equals(clean_table)
    assert delta.get("taskAttempts", 0) >= 2
    assert delta.get("executorsBlacklisted", 0) == 1
    assert stats["blacklist"] == {1}
    ev = [a for n, a in tracing.recent_events("task.attempt")]
    assert any(a.get("reason") == "failure" for a in ev), ev


def test_task_attempts_exhaust_to_query_failure(df):
    """More consecutive failures than cluster.task.maxFailures: the query
    must surface the task's error, not loop forever."""
    conf = RapidsConf({
        "spark.rapids.tpu.test.faults": "error:cluster.map:99",
        "spark.rapids.tpu.cluster.task.maxFailures": 2,
        # keep every slot placeable so exhaustion (not ExecutorLostError →
        # heal-ladder) terminates the query
        "spark.rapids.tpu.cluster.blacklist.maxTaskFailures": 99})
    with MiniCluster(n_executors=N_EXEC, conf=conf, platform="cpu") as c:
        with pytest.raises(RuntimeError, match="failed 2 times"):
            c.collect(df)


@pytest.mark.slow
def test_task_timeout_kills_wedge_and_retries(df, clean_table):
    """A hung task past cluster.task.timeoutSeconds: the driver kills the
    wedged executor, charges a timeout attempt, and retries elsewhere.
    Warm-up query first — a COLD first task's XLA compile would trip any
    honest deadline (the @SKIP arms the hang for query 2)."""
    got, delta, stats = _run_chaos(
        df, {"spark.rapids.tpu.test.faults": "hang:cluster.map.2:1@2",
             "spark.rapids.tpu.cluster.task.timeoutSeconds": 12.0},
        warm=True)
    assert got.equals(clean_table)
    assert delta.get("executorsLost", 0) >= 1
    assert delta.get("taskAttempts", 0) >= 1
    ev = [a for n, a in tracing.recent_events("task.attempt")]
    assert any(a.get("reason") == "timeout" for a in ev), ev


@pytest.mark.slow
def test_speculation_dedup_bit_identical(df, clean_table):
    """A wedged straggler with speculation on: the duplicate wins the race,
    the loser's map output is discarded (dedup keyed by (shuffle, split)),
    and the result is still the clean run's exact bytes — no duplicated or
    lost blocks."""
    got, delta, stats = _run_chaos(
        df, {"spark.rapids.tpu.test.faults": "hang:cluster.map.0:1@2",
             "spark.rapids.tpu.cluster.speculation.enabled": True,
             "spark.rapids.tpu.cluster.speculation.multiplier": 1.5,
             "spark.rapids.tpu.cluster.task.timeoutSeconds": 12.0},
        warm=True)
    assert got.equals(clean_table)
    assert delta.get("speculationWon", 0) >= 1, delta


def test_heartbeat_expiry_recovers_between_queries(df, clean_table):
    """A silent death between queries is caught by the driver's poll of the
    heartbeat manager's expire_dead, and the slot is respawned through the
    same lineage-scoped path."""
    conf = RapidsConf(
        {"spark.rapids.tpu.cluster.heartbeat.timeoutSeconds": 0.4})
    with MiniCluster(n_executors=N_EXEC, conf=conf, platform="cpu") as c:
        assert c.collect(df).equals(clean_table)
        c._procs[1].kill()
        c._procs[1].join(timeout=5)
        time.sleep(0.6)
        assert c.check_liveness() == [1]
        assert all(p.is_alive() for p in c._procs)
        assert c.collect(df).equals(clean_table)
    ev = [a for n, a in tracing.recent_events("executor.lost")]
    assert any(a.get("reason") == "heartbeat.expired" for a in ev), ev


def test_all_empty_result_keeps_declared_schema(spark):
    """Satellite: an all-empty multi-executor result must derive its schema
    from the plan's declared output, not the first schema-less reply."""
    rng = np.random.default_rng(9)
    t = pa.table({"k": pa.array(rng.integers(0, 9, 400), type=pa.int64()),
                  "v": pa.array(rng.random(400))})
    df_empty = (spark.create_dataframe(t, num_partitions=4)
                .filter(F.col("k") < F.lit(-1))
                .group_by(F.col("k")).agg(F.sum(F.col("v")).alias("s")))
    with MiniCluster(n_executors=N_EXEC, platform="cpu") as c:
        out = c.collect(df_empty)
    assert out.num_rows == 0
    assert out.column_names == ["k", "s"]
    assert out.schema.field("k").type == pa.int64()
    assert out.schema.field("s").type == pa.float64()


def test_shutdown_reaps_all_executor_processes(df):
    """Satellite: shutdown() must escalate terminate → kill and join so no
    executor outlives the cluster, even one killed uncleanly mid-life."""
    c = MiniCluster(n_executors=N_EXEC, platform="cpu")
    try:
        c.collect(df)
        c._procs[2].kill()      # an already-dead slot must not wedge reaping
    finally:
        c.shutdown()
    assert all(p is not None and not p.is_alive() for p in c._procs)
    for conn in c._conns:
        assert conn.closed
