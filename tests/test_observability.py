"""Query-level observability: metric-annotated plans, the structured event
log and the profiling analyzer.

Covers: metrics-level gating (collection AND snapshot), the _NoopMetric
add_lazy leak fix, query-tagged span events, query-scoped resilience
isolation, event-log schema round-trip (every emitted event parses, carries
query attribution where required, and timestamps are monotonic), the
tools/profiler.py report path, and an end-to-end TPC-H q18 run whose
annotated explain's per-node row counts match the collected result."""

import json
import os
import subprocess
import sys

import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu.runtime import eventlog as EL
from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.session import TpuSession

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    EL.shutdown()
    faults.reset()
    M.reset_global_registry()
    tracing.clear_events()
    yield
    EL.shutdown()
    faults.reset()
    M.reset_global_registry()
    tracing.clear_events()


# -- metric levels ------------------------------------------------------------

def test_noop_metric_drops_add_lazy():
    reg = M.MetricsRegistry("ESSENTIAL")
    m = reg.metric("debugOnly", M.DEBUG)
    assert type(m) is M._NoopMetric
    # add_lazy on an above-level metric must DROP the value like add/set do:
    # appending device scalars to _pending on a metric whose value is never
    # read would pin them (and their device buffers) forever
    m.add_lazy(7)
    m.add_lazy(object())
    assert m._pending == []
    assert m.value == 0


def test_metrics_level_gates_collection_and_snapshot():
    for level, visible in (("ESSENTIAL", {"e"}),
                           ("MODERATE", {"e", "m"}),
                           ("DEBUG", {"e", "m", "d"})):
        reg = M.MetricsRegistry(level)
        reg.metric("e", M.ESSENTIAL).add(1)
        reg.metric("m", M.MODERATE).add(2)
        reg.metric("d", M.DEBUG).add(3)
        snap = reg.snapshot()
        assert set(snap) == visible, level
        # above-level metrics drop updates entirely (collection gating)
        for name in {"e", "m", "d"} - visible:
            assert reg.metric(name).value == 0


def test_gpu_metric_lazy_fold_and_timed():
    m = M.GpuMetric("x")
    m.add_lazy(5)          # int fast-path
    m.add_lazy(pa.scalar(7).as_py() + 0)   # still int
    assert m.value == 12
    with m.timed():
        pass
    assert m.value >= 12


# -- span-event query tagging -------------------------------------------------

def test_span_events_tagged_and_filterable_by_query():
    c1 = M.QueryMetricsCollector()
    c2 = M.QueryMetricsCollector()
    with M.collector_context(c1):
        tracing.span_event("oom.retry", site="t1")
    with M.collector_context(c2):
        tracing.span_event("oom.retry", site="t2")
    tracing.span_event("oom.retry", site="untagged")
    assert len(tracing.recent_events("oom.retry")) == 3
    own = tracing.recent_events("oom.retry", query=c1.query_id)
    assert [e[1]["site"] for e in own] == ["t1"]
    own2 = tracing.recent_events(query=c2.query_id)
    assert [e[1]["site"] for e in own2] == ["t2"]


def test_trace_range_metric_both_paths():
    m = M.GpuMetric("t")
    with tracing.trace_range("r", m):
        pass
    v1 = m.value
    assert v1 > 0
    tracing.set_enabled(True)
    try:
        with tracing.trace_range("r", m):
            pass
    finally:
        tracing.set_enabled(False)
    assert m.value > v1


def test_stop_profile_unregisters_atexit(monkeypatch):
    import atexit
    calls = []
    monkeypatch.setattr("jax.profiler.start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr("jax.profiler.stop_trace",
                        lambda: calls.append(("stop",)))
    registered = []
    monkeypatch.setattr(atexit, "register",
                        lambda fn: registered.append(fn) or fn)
    monkeypatch.setattr(atexit, "unregister",
                        lambda fn: registered.remove(fn))
    for _ in range(3):
        tracing.start_profile("/tmp/obs-prof-test")
        assert len(registered) == 1     # repeated cycles must not stack
        tracing.stop_profile()
        assert registered == []
    assert calls.count(("stop",)) == 3


# -- query-scoped collection --------------------------------------------------

def _session(**extra):
    return TpuSession(dict(extra))


def test_collector_registers_nodes_and_self_time():
    spark = _session()
    df = spark.create_dataframe(
        pa.table({"k": [1, 2, 2, 3] * 50, "v": [1.0, 2.0, 3.0, 4.0] * 50}))
    q = df.group_by("k").agg(F.sum("v").alias("s"))
    out = q.collect()
    qm = spark.last_query_metrics()
    assert qm is not None and qm.wall_s > 0
    nodes = [n for n in qm.node_summaries() if n["id"] is not None]
    assert nodes, "no exec registered with the collector"
    agg = [n for n in nodes if "Aggregate" in n["name"]]
    assert agg and agg[0]["metrics"]["numOutputRows"] == out.num_rows
    assert sum(n["metrics"].get("selfTime", 0) for n in nodes) > 0
    annotated = q.explain(metrics=True)
    assert qm.query_id in annotated
    assert "numOutputRows" in annotated and "selfTime" in annotated


def test_explain_metrics_before_action():
    spark = _session()
    df = spark.create_dataframe(pa.table({"a": [1, 2, 3]}))
    s = df.explain(metrics=True)
    assert "no completed action" in s


def test_query_resilience_isolated_across_queries():
    """resilience_add pins each increment to the AMBIENT query's scoped
    registry (not a start/finish delta of the process-wide one, which
    CONCURRENT queries mutate inside each other's windows — the
    multi-tenant scheduler's attribution contract)."""
    c1 = M.QueryMetricsCollector()
    c2 = M.QueryMetricsCollector()
    # interleaved increments, the shape a concurrent peer produces: the old
    # delta attribution would have charged c2's retries to c1 as well
    with M.collector_context(c1):
        M.resilience_add(M.NUM_OOM_RETRIES, 2)
    with M.collector_context(c2):
        M.resilience_add(M.NUM_OOM_RETRIES, 3)
        M.resilience_add(M.FETCH_RECOMPUTES)
    c1.finish()
    c2.finish()
    # the process-wide registry accumulates; the scoped registries isolate
    assert M.resilience_snapshot()[M.NUM_OOM_RETRIES] == 5
    assert c1.query_resilience()[M.NUM_OOM_RETRIES] == 2
    assert c1.query_resilience()[M.FETCH_RECOMPUTES] == 0
    assert c2.query_resilience()[M.NUM_OOM_RETRIES] == 3
    assert c2.query_resilience()[M.FETCH_RECOMPUTES] == 1


def test_node_frame_self_time_subtracts_children():
    import time
    parent = M.GpuMetric("p")
    child = M.GpuMetric("c")
    with M.node_frame(1, parent):
        assert M.current_node() == 1
        with M.node_frame(2, child):
            assert M.current_node() == 2
            time.sleep(0.02)
    assert M.current_node() is None
    assert child.value >= 15e6
    assert parent.value < child.value   # child time subtracted from parent


# -- event log ----------------------------------------------------------------

def test_eventlog_schema_roundtrip(tmp_path):
    spark = _session(**{
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.eventLog.healthSample.intervalSeconds": 0.05})
    df = spark.create_dataframe(
        pa.table({"k": [1, 2, 3] * 100, "v": [1.0, 2.0, 3.0] * 100}),
        num_partitions=2)
    res = df.group_by("k").agg(F.sum("v").alias("s")).sort("k").collect()
    assert res.num_rows == 3
    EL.emit_health()
    path = EL.current_path()
    EL.shutdown()
    recs = [json.loads(line) for line in open(path)]
    assert recs, "empty event log"
    # every emitted event parses and passes the shared schema validator
    for r in recs:
        assert EL.validate_record(r) == [], r
    # monotonic timestamps across the whole file
    ts = [r["t"] for r in recs]
    assert ts == sorted(ts)
    events = {r["event"] for r in recs}
    assert {"query.start", "query.end", "batch",
            "stage.map.start", "stage.map.end"} <= events
    qid = spark.last_query_metrics().query_id
    for r in recs:
        if r["event"] in EL.QUERY_SCOPED_EVENTS:
            assert r["query"] == qid
    end = [r for r in recs if r["event"] == "query.end"][0]
    assert end["wall_s"] > 0
    node_names = {n["name"] for n in end["nodes"] if n["id"] is not None}
    assert any("Aggregate" in n for n in node_names)
    health = [r for r in recs if r["event"] == "executor.health"]
    assert health and health[-1]["device_initialized"]
    assert "hbm_used_bytes" in health[-1]


def test_eventlog_disabled_is_noop(tmp_path):
    assert not EL.enabled()
    EL.emit("spill", bytes=1)        # must not throw, must not write
    spark = _session()
    df = spark.create_dataframe(pa.table({"a": [1, 2, 3]}))
    df.collect()
    assert EL.current_path() is None


def test_eventlog_spill_and_oom_attribution(tmp_path):
    """Injected join-build OOMs land in the event log attributed to the plan
    node that was executing (the acceptance-criteria chaos shape)."""
    spark = _session(**{
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.test.faults": "oom:joins.build:1"})
    left = spark.create_dataframe(
        pa.table({"k": list(range(200)), "v": [1.0] * 200}))
    right = spark.create_dataframe(
        pa.table({"k": list(range(0, 200, 2)), "w": [2.0] * 100}))
    out = left.join(right, on="k").agg(F.sum((F.col("v") + F.col("w")))
                                       .alias("t")).collect()
    assert out.num_rows == 1
    path = EL.current_path()
    EL.shutdown()
    recs = [json.loads(line) for line in open(path)]
    ooms = [r for r in recs if r["event"] == "oom.retry"]
    assert ooms, "injected OOM never reached the event log"
    qid = spark.last_query_metrics().query_id
    end = [r for r in recs if r["event"] == "query.end"
           and r["query"] == qid][0]
    nodes_by_id = {n["id"]: n for n in end["nodes"] if n["id"] is not None}
    hit = [nodes_by_id[r["node"]]["name"] for r in ooms
           if r.get("node") in nodes_by_id]
    assert hit and all(("Join" in n or "Broadcast" in n or "Coalesce" in n)
                       for n in hit), hit
    # the query-scoped resilience delta sees the recovery too
    assert end["resilience"]["numOomRetries"] >= 1


# -- profiler tool ------------------------------------------------------------

def _run_profiler(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "profiler.py"), *args],
        capture_output=True, text=True, cwd=REPO)


def test_profiler_report_and_compare(tmp_path):
    spark = _session(**{"spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    df = spark.create_dataframe(
        pa.table({"k": [1, 2, 3] * 200, "v": [1.0, 2.0, 3.0] * 200}),
        num_partitions=2)
    q = df.group_by("k").agg(F.sum("v").alias("s")).sort("k")
    assert q.collect().num_rows == 3
    path = EL.current_path()
    # second run in a fresh file for --compare
    spark2 = _session(**{"spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    assert q.collect().num_rows == 3
    path2 = EL.current_path()
    EL.shutdown()
    assert path != path2

    proc = _run_profiler("report", path, "--json")
    assert proc.returncode == 0, proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["violations"] == []
    assert len(rep["queries"]) == 1
    q0 = rep["queries"][0]
    assert q0["operators"] and q0["wall_s"] > 0
    assert q0["operators"][0]["self_s"] >= q0["operators"][-1]["self_s"]
    assert any("ShuffleExchangeExec" in s["node"] for s in q0["shuffles"])

    text = _run_profiler("report", path)
    assert text.returncode == 0 and "top operators by self time" in text.stdout

    cmp_proc = _run_profiler("report", path, "--compare", path2)
    assert cmp_proc.returncode == 0, cmp_proc.stderr
    assert "wall" in cmp_proc.stdout and "-> " in cmp_proc.stdout


def test_profiler_flags_schema_violations(tmp_path):
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"event":"nope","ts":1.0,"t":1.0}\n'
                   'not json at all\n')
    proc = _run_profiler("report", str(bad))
    assert proc.returncode == 1
    assert "SCHEMA VIOLATION" in proc.stderr


# -- end-to-end: TPC-H q18 ----------------------------------------------------

def test_q18_annotated_explain_row_counts(tmp_path):
    from spark_rapids_tpu.benchmarks import tpch
    paths = tpch.generate(0.005, str(tmp_path / "tpch"))
    spark = _session()
    dfs = tpch.load(spark, paths)
    tb = tpch.load_np(paths)
    df = tpch.q18(dfs)
    got = df.collect()
    qm = spark.last_query_metrics()
    assert qm is not None
    summaries = [n for n in qm.node_summaries() if n["id"] is not None]
    assert len(summaries) >= 5
    # the ROOT exec's output row count is the collected result's height
    root = summaries[0]
    assert root["depth"] == 0
    assert root["metrics"]["numOutputRows"] == got.num_rows
    # scan nodes account for every input row of the three scanned tables
    scan_rows = sum(n["metrics"]["numOutputRows"] for n in summaries
                    if "Scan" in n["name"])
    expected = sum(len(tb[t]["%s_orderkey" % p])
                   for t, p in (("lineitem", "l"), ("orders", "o")))
    expected += len(tb["customer"]["c_custkey"])
    assert scan_rows == expected
    # the join build is visible as a distinct metric on some plan node
    assert any(n["metrics"].get("buildSelfTime", 0) > 0 for n in summaries)
    # self-time attribution is populated
    total_self = sum(n["metrics"].get("selfTime", 0)
                     for n in summaries) / 1e9
    assert 0 < total_self
    annotated = df.explain(metrics=True)
    assert f"numOutputRows={got.num_rows}" in annotated.splitlines()[1]
