"""Shim layer + parquet datetime rebase (reference ShimLoader + the
per-version shim source sets; Spark datetimeRebaseModeInRead semantics)."""

import datetime

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu import config as CFG
from spark_rapids_tpu.shims import (
    GREGORIAN_SWITCH_DAY, Spark30Shim, Spark35Shim, load_shim,
    rebase_gregorian_to_julian_days, rebase_julian_to_gregorian_days,
)


def test_shim_selection():
    assert isinstance(load_shim("3.0.1"), Spark30Shim)
    assert load_shim("3.2.4").version_prefix == "3.2"
    assert load_shim("3.3.0").version_prefix == "3.3"  # newest <= requested
    assert isinstance(load_shim("3.5.0"), Spark35Shim)
    assert isinstance(load_shim("4.0.0"), Spark35Shim)


def test_rebase_known_values():
    """julian 1582-10-04 (hybrid day -141428) relabels as proleptic
    gregorian 1582-10-04 = day -141438 (the 10-day cutover shift); modern
    dates are untouched."""
    d = np.array([GREGORIAN_SWITCH_DAY, GREGORIAN_SWITCH_DAY - 1, 0, 18262])
    r = rebase_julian_to_gregorian_days(d)
    assert r[0] == GREGORIAN_SWITCH_DAY
    assert r[1] == GREGORIAN_SWITCH_DAY - 11  # -141428 -> -141438
    assert r[2] == 0 and r[3] == 18262
    # proleptic-gregorian label check via python datetime
    lab = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(r[1]))
    assert lab == datetime.date(1582, 10, 4)


def test_rebase_roundtrip_wide_range():
    """Bijective except julian-only leap days (Feb 29 of century years the
    Gregorian calendar skips) — Spark's RebaseDateTime rolls those to the
    next valid day the same way."""
    from spark_rapids_tpu.shims import _julian_jdn_to_ymd
    rng = np.random.default_rng(0)
    d = rng.integers(-700000, GREGORIAN_SWITCH_DAY, 5000)
    y, m, day = _julian_jdn_to_ymd(d + 2440588)
    julian_only_leap = (m == 2) & (day == 29) & (y % 100 == 0) & (y % 400 != 0)
    d = d[~julian_only_leap]
    rt = rebase_gregorian_to_julian_days(rebase_julian_to_gregorian_days(d))
    assert (rt == d).all()


@pytest.fixture
def legacy_parquet(tmp_path):
    """A parquet file holding pre-cutover dates (as a hybrid writer would)."""
    days = np.array([GREGORIAN_SWITCH_DAY - 1, 0, -200000], dtype=np.int32)
    t = pa.table({"d": pa.array(days, pa.int32()).cast(pa.date32()),
                  "v": pa.array([1, 2, 3], pa.int64())})
    p = tmp_path / "legacy"
    p.mkdir()
    pq.write_table(t, p / "part-0.parquet")
    return str(p)


def _read(path, mode):
    from spark_rapids_tpu.session import TpuSession
    spark = TpuSession({CFG.PARQUET_REBASE_MODE.key: mode})
    return spark.read_parquet(path).collect()


def test_rebase_exception_mode(legacy_parquet):
    with pytest.raises(Exception, match="1582-10-15"):
        _read(legacy_parquet, "EXCEPTION")


def test_rebase_corrected_mode(legacy_parquet):
    out = _read(legacy_parquet, "CORRECTED")
    days = [(v - datetime.date(1970, 1, 1)).days
            for v in out.column("d").to_pylist()]
    assert sorted(days) == sorted([GREGORIAN_SWITCH_DAY - 1, 0, -200000])


def test_rebase_legacy_mode(legacy_parquet):
    out = _read(legacy_parquet, "LEGACY")
    days = sorted((v - datetime.date(1970, 1, 1)).days
                  for v in out.column("d").to_pylist())
    exp = sorted(rebase_julian_to_gregorian_days(
        np.array([GREGORIAN_SWITCH_DAY - 1, 0, -200000])).tolist())
    assert days == exp


def test_shim_pins_lenient_date_cast_to_host():
    """The 3.0-generation shim pins string→date casts to host (the device
    parser implements only the 3.2+ subset) — the ShimLoader mechanism
    changing planner behavior."""
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.plan.overrides import explain_plan
    from spark_rapids_tpu.session import TpuSession
    t = pa.table({"s": pa.array(["2021-01-05", "2021-1-5"])})

    old = TpuSession({CFG.SPARK_VERSION.key: "3.0.1"})
    df_old = old.create_dataframe(t).select(
        F.cast(F.col("s"), T.DATE).alias("d"))
    assert "3.0-generation" in explain_plan(df_old._plan, old.conf)

    new = TpuSession({CFG.SPARK_VERSION.key: "3.5.0"})
    df_new = new.create_dataframe(t).select(
        F.cast(F.col("s"), T.DATE).alias("d"))
    assert "3.0-generation" not in explain_plan(df_new._plan, new.conf)
    # and both still answer
    assert df_old.collect().num_rows == 2
    assert df_new.collect().num_rows == 2


def test_adaptive_default_is_version_gated():
    """AQE coalescing defaults ON for 3.2+ and OFF for 3.0/3.1 (SPARK-33679),
    unless the conf is set explicitly."""
    import pyarrow as pa
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.plan.overrides import TpuOverrides
    from spark_rapids_tpu.exec.exchange import AdaptiveShuffleReaderExec

    def final_agg_child(conf):
        s = TpuSession(conf)
        df = (s.create_dataframe({"k": pa.array([1, 2, 1], pa.int64())},
                                 num_partitions=2)
              .group_by("k").agg(F.alias(F.count(F.col("k")), "c")))
        hybrid = TpuOverrides(s.conf).apply(df._plan)
        # FINAL HashAggregate sits at/near the root; find the reader below
        found = []

        def walk(n):
            if isinstance(n, AdaptiveShuffleReaderExec):
                found.append(n)
            for c in getattr(n, "children", []):
                walk(c)
        walk(hybrid)
        return found

    assert final_agg_child({})                                   # 3.5: on
    assert not final_agg_child({"spark.rapids.tpu.spark.version": "3.1.2"})
    assert final_agg_child({
        "spark.rapids.tpu.spark.version": "3.1.2",
        "spark.rapids.tpu.sql.adaptive.coalescePartitions.enabled": "true"})


def test_shim_generations_cover_reference_versions():
    """Six behavior generations, latest-not-exceeding selection across every
    reference shim version (reference shims/spark301..320 + ShimLoader)."""
    from spark_rapids_tpu.shims import load_shim
    picks = {v: load_shim(v).version_prefix for v in
             ("3.0.1", "3.0.2", "3.0.3", "3.1.1", "3.1.2", "3.2.0",
              "3.3.2", "3.4.1", "3.5.0")}
    assert picks == {"3.0.1": "3.0", "3.0.2": "3.0", "3.0.3": "3.0",
                     "3.1.1": "3.1", "3.1.2": "3.1", "3.2.0": "3.2",
                     "3.3.2": "3.3", "3.4.1": "3.4", "3.5.0": "3.5"}


def test_element_at_zero_shim_divergence():
    """element_at(arr, 0): pre-3.4 raises 'SQL array indices start at 1';
    3.4+ (ANSI off) yields null."""
    import pytest
    import pyarrow as pa
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.session import TpuSession

    t = pa.table({"a": pa.array([1, 2], pa.int64()),
                  "b": pa.array([3, 4], pa.int64())})
    def q(spark):
        df = spark.create_dataframe(t)
        return df.select(F.element_at(F.array(F.col("a"), F.col("b")),
                                      0).alias("x"))
    new = TpuSession({"spark.rapids.tpu.spark.version": "3.5.0"})
    assert q(new).collect().column("x").to_pylist() == [None, None]
    old = TpuSession({"spark.rapids.tpu.spark.version": "3.2.0"})
    with pytest.raises(RuntimeError, match="SQL array indices start at 1"):
        q(old).collect()


def test_platform_variant_shims():
    """Databricks/EMR shims (reference spark301db/spark301emr/spark310db):
    DBR 7.x enabled AQE by default two releases before OSS 3.2; EMR tracks
    OSS semantics under a distinct platform identity."""
    from spark_rapids_tpu.shims import (
        Spark30DatabricksShim, Spark30EmrShim, load_shim)
    db = load_shim("3.0.1-databricks")
    assert isinstance(db, Spark30DatabricksShim)
    assert db.adaptive_coalesce_default          # OSS 3.0 has False
    assert not load_shim("3.0.1").adaptive_coalesce_default
    assert db.lenient_string_to_date             # inherits 3.0 semantics
    emr = load_shim("3.0.1-emr")
    assert isinstance(emr, Spark30EmrShim)
    assert emr.platform == "emr"
    assert not emr.adaptive_coalesce_default     # EMR == OSS semantics
    assert load_shim("3.1.2-databricks").adaptive_coalesce_default
    # platforms fall back to OSS shims for generations they don't specialize
    assert load_shim("3.4.0-databricks").version_prefix == "3.4"
    assert load_shim("3.4.0-databricks").platform == ""
    with pytest.raises(ValueError):
        load_shim("3.0.1-mapr")


def test_register_shim_discovery():
    """ServiceLoader-analog: a registered third-party shim participates in
    selection and later registrations win ties (ShimLoader.scala:26-68)."""
    from spark_rapids_tpu import shims as S

    class CustomShim(S.Spark32Shim):
        platform = "custom"
    S.register_shim(CustomShim, "custom")
    try:
        assert isinstance(S.load_shim("3.2.0-custom"), CustomShim)
        # OSS fallback above the registered prefix still applies
        assert S.load_shim("3.5.0-custom").version_prefix == "3.5"
    finally:
        S._PLATFORM_SHIMS.pop("custom", None)


def test_string_to_timestamp_cast_ansi_subset():
    """Device string→timestamp cast implements the 3.2+ ANSI subset
    (device == host on every shape incl. zones and fractions)."""
    import pyarrow as pa
    from spark_rapids_tpu.session import TpuSession
    spark = TpuSession()
    t = pa.table({"s": ["2021-01-05 12:30:45.123456", "2021-1-5",
                        "2021-01-05T07:00:00+02:00", "2021-07",
                        "2021-01-05 23:59:59Z", "epoch", "junk", None]})
    spark.create_or_replace_temp_view("ts_t", spark.create_dataframe(t))
    df = spark.sql("select cast(s as timestamp) ts from ts_t")
    got = [r["ts"] for r in df.collect().to_pylist()]
    exp = [r["ts"] for r in df.collect_host().to_pylist()]
    assert got == exp
    assert got[0].microsecond == 123456
    assert got[2].hour == 5                  # +02:00 shifted into UTC
    assert got[5] is None and got[6] is None and got[7] is None


def test_special_datetime_strings_shim_divergence():
    """SPARK-35581: cast('epoch'... as date/timestamp) resolves on 3.0/3.1
    generations, yields null on 3.2+; DATE/TIMESTAMP typed literals keep
    the special strings on every generation."""
    import datetime
    from spark_rapids_tpu.session import TpuSession
    old = TpuSession({"spark.rapids.tpu.spark.version": "3.1.2"})
    new = TpuSession({"spark.rapids.tpu.spark.version": "3.5.0"})
    row = old.sql("select cast('epoch' as timestamp) e, "
                  "cast('Epoch' as date) d").collect().to_pylist()[0]
    assert row["e"] == datetime.datetime(1970, 1, 1,
                                         tzinfo=datetime.timezone.utc)
    assert row["d"] == datetime.date(1970, 1, 1)
    row = old.sql("select cast('today' as date) t, "
                  "cast('tomorrow' as date) tm").collect().to_pylist()[0]
    assert (row["tm"] - row["t"]).days == 1
    row = new.sql("select cast('epoch' as timestamp) e, "
                  "cast('today' as date) t").collect().to_pylist()[0]
    assert row["e"] is None and row["t"] is None
    # typed literals: every generation
    for s in (old, new):
        row = s.sql("select timestamp 'epoch' e").collect().to_pylist()[0]
        assert row["e"] == datetime.datetime(1970, 1, 1,
                                             tzinfo=datetime.timezone.utc)


def test_lenient_timestamp_cast_pins_to_host():
    """3.0/3.1 generations tag string→timestamp casts of column data off
    the device (the ANSI-subset device parser must not serve lenient
    semantics it does not implement)."""
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.plan.overrides import explain_plan
    from spark_rapids_tpu.session import TpuSession
    t = pa.table({"s": pa.array(["2021-01-05 10:00:00", "2021-1-5"])})
    old = TpuSession({CFG.SPARK_VERSION.key: "3.1.2"})
    df_old = old.create_dataframe(t).select(
        F.cast(F.col("s"), T.TIMESTAMP).alias("ts"))
    assert "3.0-generation" in explain_plan(df_old._plan, old.conf)
    new = TpuSession({CFG.SPARK_VERSION.key: "3.5.0"})
    df_new = new.create_dataframe(t).select(
        F.cast(F.col("s"), T.TIMESTAMP).alias("ts"))
    assert "3.0-generation" not in explain_plan(df_new._plan, new.conf)
    assert df_old.collect().num_rows == 2
    assert df_new.collect().num_rows == 2
