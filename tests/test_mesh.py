"""Mesh executor tests over the virtual 8-device CPU mesh (SURVEY.md §5 — the
ICI intra-slice exchange path the driver also dry-runs via __graft_entry__)."""

import numpy as np
import pyarrow as pa
import pytest

from conftest import make_table

import spark_rapids_tpu.functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.distributed import MeshExecutor
from spark_rapids_tpu.expr.core import Alias, col
from spark_rapids_tpu.expr.aggregates import Average, Count, Max, Min, Sum


def shards_of(tbl, n):
    per = -(-tbl.num_rows // n)
    return [tbl.slice(i * per, per) for i in range(n)]


def host_groupby(tbl, key, val_specs):
    import collections
    import math
    groups = collections.defaultdict(list)
    keys = tbl.column(key).to_pylist()
    for i, k in enumerate(keys):
        groups[k].append(i)
    out = {}
    for k, idxs in groups.items():
        out[k] = idxs
    return out


def test_mesh_aggregate_matches_host():
    r = np.random.default_rng(3)
    n = 4000
    t = pa.table({
        "k": pa.array([None if i % 31 == 0 else int(v) for i, v in
                       enumerate(r.integers(0, 25, n))], pa.int64()),
        "v": pa.array([None if i % 13 == 0 else float(v) for i, v in
                       enumerate(r.normal(0, 10, n))], pa.float64()),
    })
    ex = MeshExecutor(8)
    out = ex.aggregate(
        shards_of(t, 8), [col("k")],
        [Alias(Sum(col("v")), "s"), Alias(Count(col("v")), "c"),
         Alias(Min(col("v")), "mn"), Alias(Max(col("v")), "mx"),
         Alias(Average(col("v")), "avg")])
    # host oracle via the single-process plan layer
    from spark_rapids_tpu.plan import AggregateNode, ScanNode
    want = AggregateNode(
        [col("k")],
        [Alias(Sum(col("v")), "s"), Alias(Count(col("v")), "c"),
         Alias(Min(col("v")), "mn"), Alias(Max(col("v")), "mx"),
         Alias(Average(col("v")), "avg")],
        ScanNode([t])).collect_host()
    got = {r_["k"]: r_ for r_ in out.to_pylist()}
    exp = {r_["k"]: r_ for r_ in want.to_pylist()}
    assert set(got) == set(exp)
    for k in exp:
        for f in ("c", "mn", "mx"):
            assert got[k][f] == exp[k][f], (k, f, got[k], exp[k])
        for f in ("s", "avg"):
            a, b = got[k][f], exp[k][f]
            assert (a is None) == (b is None)
            if a is not None:
                assert a == pytest.approx(b, rel=1e-9)


def test_mesh_aggregate_with_filter_and_string_keys():
    r = np.random.default_rng(9)
    n = 2000
    words = ["alpha", "beta", "gamma", "delta", None]
    t = pa.table({
        "g": pa.array([words[int(v) % 5] for v in r.integers(0, 1000, n)]),
        "x": pa.array([int(v) for v in r.integers(-50, 50, n)], pa.int64()),
    })
    ex = MeshExecutor(8)
    out = ex.aggregate(
        shards_of(t, 5),  # fewer shards than chips: pads empties
        [col("g")],
        [Alias(Sum(col("x")), "s"), Alias(Count(None), "n")],
        filter_expr=col("x") > F.lit(0))
    from spark_rapids_tpu.plan import AggregateNode, FilterNode, ScanNode
    want = AggregateNode(
        [col("g")], [Alias(Sum(col("x")), "s"), Alias(Count(None), "n")],
        FilterNode(col("x") > F.lit(0), ScanNode([t]))).collect_host()
    got = sorted(out.to_pylist(), key=lambda d: (d["g"] is None, d["g"] or ""))
    exp = sorted(want.to_pylist(), key=lambda d: (d["g"] is None, d["g"] or ""))
    assert got == exp


def test_mesh_partials_actually_exchange():
    """Every key appears on every shard → without the all_to_all merge the
    result would have n_dev copies of each group."""
    t = pa.table({"k": pa.array([1, 2] * 64, pa.int64()),
                  "v": pa.array([1.0] * 128)})
    ex = MeshExecutor(8)
    out = ex.aggregate(shards_of(t, 8), [col("k")],
                       [Alias(Count(None), "n")])
    assert sorted(out.to_pylist(), key=lambda d: d["k"]) == [
        {"k": 1, "n": 64}, {"k": 2, "n": 64}]
