"""Mesh executor tests over the virtual 8-device CPU mesh (SURVEY.md §5 — the
ICI intra-slice exchange path the driver also dry-runs via __graft_entry__)."""

import numpy as np
import pyarrow as pa
import pytest

from conftest import make_table

import spark_rapids_tpu.functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.distributed import MeshExecutor
from spark_rapids_tpu.expr.core import Alias, col
from spark_rapids_tpu.expr.aggregates import Average, Count, Max, Min, Sum


def shards_of(tbl, n):
    per = -(-tbl.num_rows // n)
    return [tbl.slice(i * per, per) for i in range(n)]


def host_groupby(tbl, key, val_specs):
    import collections
    import math
    groups = collections.defaultdict(list)
    keys = tbl.column(key).to_pylist()
    for i, k in enumerate(keys):
        groups[k].append(i)
    out = {}
    for k, idxs in groups.items():
        out[k] = idxs
    return out


def test_mesh_aggregate_matches_host():
    r = np.random.default_rng(3)
    n = 4000
    t = pa.table({
        "k": pa.array([None if i % 31 == 0 else int(v) for i, v in
                       enumerate(r.integers(0, 25, n))], pa.int64()),
        "v": pa.array([None if i % 13 == 0 else float(v) for i, v in
                       enumerate(r.normal(0, 10, n))], pa.float64()),
    })
    ex = MeshExecutor(8)
    out = ex.aggregate(
        shards_of(t, 8), [col("k")],
        [Alias(Sum(col("v")), "s"), Alias(Count(col("v")), "c"),
         Alias(Min(col("v")), "mn"), Alias(Max(col("v")), "mx"),
         Alias(Average(col("v")), "avg")])
    # host oracle via the single-process plan layer
    from spark_rapids_tpu.plan import AggregateNode, ScanNode
    want = AggregateNode(
        [col("k")],
        [Alias(Sum(col("v")), "s"), Alias(Count(col("v")), "c"),
         Alias(Min(col("v")), "mn"), Alias(Max(col("v")), "mx"),
         Alias(Average(col("v")), "avg")],
        ScanNode([t])).collect_host()
    got = {r_["k"]: r_ for r_ in out.to_pylist()}
    exp = {r_["k"]: r_ for r_ in want.to_pylist()}
    assert set(got) == set(exp)
    for k in exp:
        for f in ("c", "mn", "mx"):
            assert got[k][f] == exp[k][f], (k, f, got[k], exp[k])
        for f in ("s", "avg"):
            a, b = got[k][f], exp[k][f]
            assert (a is None) == (b is None)
            if a is not None:
                assert a == pytest.approx(b, rel=1e-9)


def test_mesh_aggregate_with_filter_and_string_keys():
    r = np.random.default_rng(9)
    n = 2000
    words = ["alpha", "beta", "gamma", "delta", None]
    t = pa.table({
        "g": pa.array([words[int(v) % 5] for v in r.integers(0, 1000, n)]),
        "x": pa.array([int(v) for v in r.integers(-50, 50, n)], pa.int64()),
    })
    ex = MeshExecutor(8)
    out = ex.aggregate(
        shards_of(t, 5),  # fewer shards than chips: pads empties
        [col("g")],
        [Alias(Sum(col("x")), "s"), Alias(Count(None), "n")],
        filter_expr=col("x") > F.lit(0))
    from spark_rapids_tpu.plan import AggregateNode, FilterNode, ScanNode
    want = AggregateNode(
        [col("g")], [Alias(Sum(col("x")), "s"), Alias(Count(None), "n")],
        FilterNode(col("x") > F.lit(0), ScanNode([t]))).collect_host()
    got = sorted(out.to_pylist(), key=lambda d: (d["g"] is None, d["g"] or ""))
    exp = sorted(want.to_pylist(), key=lambda d: (d["g"] is None, d["g"] or ""))
    assert got == exp


def test_mesh_partials_actually_exchange():
    """Every key appears on every shard → without the all_to_all merge the
    result would have n_dev copies of each group."""
    t = pa.table({"k": pa.array([1, 2] * 64, pa.int64()),
                  "v": pa.array([1.0] * 128)})
    ex = MeshExecutor(8)
    out = ex.aggregate(shards_of(t, 8), [col("k")],
                       [Alias(Count(None), "n")])
    assert sorted(out.to_pylist(), key=lambda d: d["k"]) == [
        {"k": 1, "n": 64}, {"k": 2, "n": 64}]


# ---------------------------------------------------------------------------
# Session-level mesh execution: exchanges run as all_to_all collectives over
# the 8-device CPU mesh (spark.rapids.tpu.mesh.enabled); group-by, join and
# global sort ride the mesh exchange (VERDICT r1 item 2).
# ---------------------------------------------------------------------------

from spark_rapids_tpu.session import TpuSession


def mesh_session():
    return TpuSession({"spark.rapids.tpu.mesh.enabled": "true",
                       "spark.rapids.tpu.mesh.devices": "8"})


def physical_tree(df):
    from spark_rapids_tpu.plan.overrides import TpuOverrides
    return repr(TpuOverrides(df.session.conf).apply(df._plan))


def norm_rows(tbl):
    # floats: partial-aggregate accumulation order differs across partitionings
    # (same as Spark), so compare at 1e-9 relative precision
    def nv(v):
        if isinstance(v, float):
            return float(f"{v:.9e}")
        return v
    cols = tbl.column_names
    return sorted((tuple(nv(r[c]) for c in cols) for r in tbl.to_pylist()),
                  key=lambda t_: tuple((v is None, str(v)) for v in t_))


def test_mesh_session_group_by():
    spark = mesh_session()
    t = make_table(3000, seed=11)
    df = (spark.create_dataframe(t, num_partitions=5)
          .group_by(F.col("i"))
          .agg(F.sum(F.col("d")).alias("s"),
               F.count(F.col("l")).alias("c"),
               F.max(F.col("d")).alias("mx")))
    got = df.collect()
    exp = df.collect_host()
    # the plan must actually contain a mesh exchange
    assert "MeshExchangeExec" in physical_tree(df)
    assert norm_rows(got) == norm_rows(exp)


def project_like(exp, got):
    """Project the host-oracle table onto the device output's column set (the
    device path collapses the duplicated USING-join key like Spark; the host
    plan keeps both copies)."""
    idx = []
    seen = set()
    for i, n in enumerate(exp.column_names):
        if n not in seen:
            idx.append(i)
            seen.add(n)
    exp = exp.select(idx)
    assert exp.column_names == got.column_names, (exp.column_names,
                                                  got.column_names)
    return exp


def test_mesh_session_join():
    spark = mesh_session()
    r = np.random.default_rng(5)
    left = pa.table({
        "k": pa.array([None if i % 17 == 0 else int(v) for i, v in
                       enumerate(r.integers(0, 40, 1200))], pa.int64()),
        "lv": pa.array(r.normal(0, 5, 1200)),
    })
    right = pa.table({
        "k": pa.array([None if i % 23 == 0 else int(v) for i, v in
                       enumerate(r.integers(0, 40, 900))], pa.int64()),
        "rv": pa.array(r.normal(0, 5, 900)),
    })
    ldf = spark.create_dataframe(left, num_partitions=4)
    rdf = spark.create_dataframe(right, num_partitions=3)
    df = ldf.join(rdf, on="k", how="inner")
    got = df.collect()
    exp = project_like(df.collect_host(), got)
    assert "MeshExchangeExec" in physical_tree(df)
    assert norm_rows(got) == norm_rows(exp)


@pytest.mark.parametrize("how", ["left", "full"])
def test_mesh_session_outer_joins(how):
    spark = mesh_session()
    r = np.random.default_rng(9)
    left = pa.table({"k": pa.array([int(v) for v in r.integers(0, 12, 300)]),
                     "lv": pa.array(r.normal(0, 5, 300))})
    right = pa.table({"k": pa.array([int(v) for v in r.integers(6, 20, 250)]),
                      "rv": pa.array(r.normal(0, 5, 250))})
    ldf = spark.create_dataframe(left, num_partitions=3)
    rdf = spark.create_dataframe(right, num_partitions=2)
    df = ldf.join(rdf, on="k", how=how)
    got = df.collect()
    assert norm_rows(got) == norm_rows(project_like(df.collect_host(), got))


def test_mesh_session_global_sort():
    spark = mesh_session()
    t = make_table(2500, seed=21)
    df = (spark.create_dataframe(t, num_partitions=6)
          .select(F.col("i"), F.col("d"))
          .sort(F.col("i"), F.col("d")))
    got = df.collect()
    exp = df.collect_host()
    assert "MeshExchangeExec" in physical_tree(df)
    # global sort: exact row order must match the host oracle
    assert got.to_pylist() == exp.to_pylist()


def test_mesh_session_string_keys():
    """String group-by/join keys hash by UTF-8 bytes through the mesh-global
    dictionary, so both sides of the exchange agree on partition ids."""
    spark = mesh_session()
    r = np.random.default_rng(31)
    words = ["alpha", "bravo", "charlie", "delta", "echo", "", "Ω-unicode"]
    t = pa.table({
        "w": pa.array([None if i % 19 == 0 else words[v] for i, v in
                       enumerate(r.integers(0, len(words), 1000))]),
        "v": pa.array(r.normal(0, 3, 1000)),
    })
    df = (spark.create_dataframe(t, num_partitions=4)
          .group_by(F.col("w"))
          .agg(F.sum(F.col("v")).alias("s"), F.count(None).alias("c")))
    assert norm_rows(df.collect()) == norm_rows(df.collect_host())


def test_mesh_repartition_roundrobin():
    spark = mesh_session()
    t = make_table(800, seed=41)
    df = spark.create_dataframe(t, num_partitions=3).repartition(8)
    got = df.collect()
    assert "MeshExchangeExec" in physical_tree(df)
    assert norm_rows(got) == norm_rows(t)


def test_mesh_string_hash_spreads_devices():
    """String keys must hash their UTF-8 bytes through the mesh-global
    dictionary — distinct keys spread over devices, not funnel to one (the
    degenerate empty-dictionary hash is consistent, so result-equality tests
    alone cannot catch it)."""
    from spark_rapids_tpu.distributed.exchange import MeshExchangeExec
    from spark_rapids_tpu.exec.basic import ArrowScanExec
    from spark_rapids_tpu.shuffle.partitioning import HashPartitioner
    from spark_rapids_tpu.config import RapidsConf

    words = [f"word-{i}" for i in range(64)]
    t = pa.table({"w": pa.array(words * 4), "v": pa.array(range(256))})
    conf = RapidsConf({"spark.rapids.tpu.mesh.enabled": "true",
                       "spark.rapids.tpu.mesh.devices": "8"})
    ex = MeshExchangeExec(HashPartitioner([F.col("w")], 8),
                          ArrowScanExec([t], conf=conf), conf=conf)
    sizes = [sum(b.num_rows for b in ex.execute_partition(d))
             for d in range(8)]
    assert sum(sizes) == 256
    assert sum(1 for s_ in sizes if s_ > 0) >= 4, sizes
