"""SQL surface sweep — the qa_nightly_select_test / qa_nightly_sql.py role:
a broad battery of SELECT statements through session.sql(), each checked
device-vs-host (the reference's CPU/GPU equivalence contract) over a
mixed-type table with nulls."""

import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def spark():
    s = TpuSession()
    n = 500
    r = np.random.default_rng(7)
    mask = lambda p: r.random(n) < p

    def witness(vals, m):
        return pa.array([None if mm else v
                         for v, mm in zip(vals.tolist(), m)])
    t = pa.table({
        "i": witness(r.integers(-100, 100, n), mask(0.1)),
        "l": witness(r.integers(-10**12, 10**12, n), mask(0.1)),
        "d": witness(np.round(r.normal(0, 50, n), 3), mask(0.1)),
        "s": pa.array([None if m else ["alpha", "Beta", "gamma", "", "déjà vu",
                                       "x" * 20][v % 6]
                       for v, m in zip(r.integers(0, 6, n), mask(0.1))]),
        "b": witness(r.random(n) < 0.5, mask(0.15)),
        "g": pa.array([["u", "v", "w"][v % 3] for v in range(n)]),
    })
    s.create_or_replace_temp_view("t", s.create_dataframe(t, num_partitions=2))
    return s


QUERIES = [
    # projections / arithmetic / conditionals
    "select i + 1, l - i, d * 2.0, -i from t",
    "select i % 7, l / 3.0, abs(i), abs(d) from t",
    "select case when i > 0 then 'pos' when i < 0 then 'neg' else 'zero' end from t",
    "select case i % 3 when 0 then 'a' when 1 then 'b' else 'c' end from t",
    "select coalesce(i, 0), coalesce(s, 'missing'), nullif(g, 'u') from t",
    "select cast(i as bigint), cast(d as int), cast(i as double), cast(l as string) from t",
    "select i > 0, i >= l, d <> 0.0, s = 'alpha', b and (i > 0), not b from t",
    "select least(i, 0), greatest(i, 10) from t",
    # strings
    "select upper(s), lower(s), length(s), trim(s) from t",
    "select substr(s, 1, 3), substr(s, 2), s || '!' from t",
    "select concat(s, g), s like 'a%', s like '%a', s like '%ta%' from t",
    # predicates
    "select * from t where i between -10 and 10",
    "select * from t where s in ('alpha', 'gamma') and i is not null",
    "select * from t where (i > 50 or i < -50) and d is not null",
    "select * from t where s is null or b",
    "select * from t where not (i between 0 and 100)",
    # aggregation
    "select count(*), count(i), count(s) from t",
    "select sum(i), sum(l), sum(d), min(i), max(d), avg(d) from t",
    "select g, count(*), sum(i), avg(d), min(s), max(s) from t group by g order by g",
    "select g, b, count(*) from t group by g, b order by g, b",
    "select g, sum(d) sd from t group by g having sum(d) > 0 order by sd",
    "select g, stddev_samp(d), var_samp(d) from t group by g order by g",
    "select i % 5 k, count(*) c from t where i is not null group by i % 5 order by k",
    # distinct / order / limit
    "select distinct g from t order by g",
    "select distinct g, b from t order by g, b",
    "select i, d from t where i is not null order by i desc, d limit 17",
    "select s from t order by s nulls first limit 9",
    "select s from t order by s desc nulls last limit 9",
    "select i from t order by abs(i), i limit 11",
    # ordinals / aliases in order-by
    "select g, count(*) n from t group by g order by 2 desc, 1",
    "select g, sum(i) si from t group by g order by si, g",
    # joins (self-join via derived tables)
    "select a.g, b2.cnt from (select g, sum(i) si from t group by g) a, "
    "(select g, count(*) cnt from t group by g) b2 where a.g = b2.g order by a.g",
    "select x.g from (select distinct g from t) x "
    "left join (select g from t where i > 1000) y on x.g = y.g order by x.g",
    # windows
    "select g, i, row_number() over (partition by g order by i nulls last, l nulls last) rn "
    "from t order by g, rn limit 40",
    "select g, d, sum(d) over (partition by g) tot from t order by g, d nulls last limit 40",
    "select g, avg(d) over () global_avg from t limit 5",
    # union / subqueries
    "select i from t where i > 90 union all select i from t where i < -90 order by i",
    "select count(*) from t where d > (select avg(d) from t)",
    "select g, count(*) from t where i < (select max(i) from t) group by g order by g",
    # scalar exprs over aggregates
    "select sum(d) / count(d), max(i) - min(i) from t",
    "select g, sum(d) / count(*) from t group by g order by g",
]


def _norm(v):
    if isinstance(v, float):
        if math.isnan(v):
            return ("nan",)
        return float(f"{v:.10g}")   # relative rounding (sums of ~1e12 terms)
    return v


def _rows(tbl):
    # positional (duplicate auto-named columns must not collapse via dicts)
    cols = [c.to_pylist() for c in tbl.columns]
    return [tuple(_norm(v) for v in row) for row in zip(*cols)] if cols else []


@pytest.mark.parametrize("sql", QUERIES)
def test_sql_sweep_device_matches_host(spark, sql):
    df = spark.sql(sql)
    got = _rows(df.collect())
    exp = _rows(df.collect_host())
    has_order = "order by" in sql
    if not has_order:
        got, exp = sorted(got, key=repr), sorted(exp, key=repr)
    assert got == exp, f"{sql}\n{got[:5]} vs {exp[:5]}"


def test_distinct_aggregates_rewrite():
    """fn(DISTINCT x) lowers through the two-level rewrite (Spark
    RewriteDistinctAggregates role): inner GROUP BY (keys, x) dedupes,
    outer re-aggregates; min/max mix in (distinct-insensitive)."""
    import pyarrow as pa
    from spark_rapids_tpu.session import TpuSession
    spark = TpuSession()
    t = pa.table({"g": pa.array(["a", "a", "b", "b", "b", None]),
                  "x": pa.array([1, 1, 2, None, 3, 2], pa.int64()),
                  "y": pa.array([5.0, 6.0, 1.0, 2.0, 3.0, 9.0])})
    spark.create_or_replace_temp_view("dt", spark.create_dataframe(t))
    row = spark.sql("select count(distinct x) as c, sum(distinct x) as s, "
                    "avg(distinct x) as a from dt").collect().to_pylist()[0]
    assert row == {"c": 3, "s": 6, "a": 2.0}
    rows = sorted(spark.sql(
        "select g, count(distinct x) as c, min(y) as mn, max(y) as mx "
        "from dt group by g").collect().to_pylist(),
        key=lambda r: (r["g"] is None, r["g"]))
    assert rows == [
        {"g": "a", "c": 1, "mn": 5.0, "mx": 6.0},
        {"g": "b", "c": 2, "mn": 1.0, "mx": 3.0},
        {"g": None, "c": 1, "mn": 9.0, "mx": 9.0}]
    # general mixes route through the Expand rewrite (Spark
    # RewriteDistinctAggregates general form): several distinct arguments
    # and/or arbitrary regular aggregates alongside them
    row = spark.sql("select count(distinct x) c, sum(y) s from dt"
                    ).collect().to_pylist()[0]
    assert row == {"c": 3, "s": 26.0}
    row = spark.sql("select count(distinct x) cx, count(distinct g) cg, "
                    "avg(y) ay, count(*) n from dt").collect().to_pylist()[0]
    assert row == {"cx": 3, "cg": 2, "ay": 26.0 / 6, "n": 6}
    rows = sorted(spark.sql(
        "select g, count(distinct x) cx, sum(distinct x) sx, count(y) cy "
        "from dt group by g").collect().to_pylist(),
        key=lambda r: (r["g"] is None, r["g"]))
    assert rows == [
        {"g": "a", "cx": 1, "sx": 1, "cy": 2},
        {"g": "b", "cx": 2, "sx": 5, "cy": 3},
        {"g": None, "cx": 1, "sx": 2, "cy": 1}]


@pytest.fixture(scope="module")
def setop_views():
    spark = TpuSession()
    a = pa.table({"x": [1, 1, 2, 3, None, None],
                  "y": ["a", "a", "b", "c", "d", None]})
    b = pa.table({"x": [1, 2, 2, None, 5], "y": ["a", "b", "b", None, "e"]})
    spark.create_or_replace_temp_view("sa", spark.create_dataframe(a))
    spark.create_or_replace_temp_view("sb", spark.create_dataframe(b))
    return spark


@pytest.mark.parametrize("query", [
    "select x, y from sa union select x, y from sb",
    "select x, y from sa union all select x, y from sb",
    "select x, y from sa intersect select x, y from sb",
    "select x, y from sa except select x, y from sb",
    "select x, y from sa intersect all select x, y from sb",
    "select x, y from sa except all select x, y from sb",
    "select x, y from sa minus select x, y from sb",
    # INTERSECT binds tighter than UNION (standard precedence)
    "select x, y from sa union select x, y from sb "
    "intersect select x, y from sb",
    # arm widening: int vs double unify to double
    "select x from sa union select cast(x as double) from sb",
    # q38/q87 shape: aggregate over a set-op derived table
    "select count(*) n from (select x, y from sa "
    "intersect select x, y from sb) t",
    "select count(*) n from ((select x, y from sa) "
    "except (select x, y from sb)) t",
])
def test_set_operations_device_matches_host(setop_views, query):
    """UNION/INTERSECT/EXCEPT [ALL] with set-op NULL semantics (NULL==NULL,
    unlike join keys) — device rows match the host interpreter. Reference:
    Spark ResolveSetOperations feeding GpuUnionExec/GpuHashJoin."""
    df = setop_views.sql(query)
    got = sorted((tuple(r.values()) for r in df.collect().to_pylist()),
                 key=repr)
    exp = sorted((tuple(r.values()) for r in df.collect_host().to_pylist()),
                 key=repr)
    assert got == exp
    assert exp or "except" in query  # non-vacuous apart from empty EXCEPTs


@pytest.mark.parametrize("query", [
    "select g1, g2, sum(v) s from gs group by grouping sets "
    "((g1, g2), (g1), ()) order by g1, g2",
    "select g1, g2, sum(v) s from gs group by cube (g1, g2) "
    "order by g1, g2",
    "select g1, g2, grouping(g1) a, grouping(g2) b, sum(v) s from gs "
    "group by cube (g1, g2) order by g1, g2, a, b",
    "select g1, sum(v) s from gs group by grouping sets (g1, ()) "
    "order by g1",
    # distinct aggregates compose with grouping-set Expands
    "select g1, g2, count(distinct v) c from gs group by rollup (g1, g2) "
    "order by g1, g2",
])
def test_grouping_sets_device_matches_host(query):
    """CUBE / GROUPING SETS lower through the grouping-sets Expand with
    Spark's grouping-id bit convention (MSB = first key); grouping() reads
    the bits (reference GpuExpandExec role)."""
    spark = TpuSession()
    t = pa.table({"g1": ["a", "a", "b", "b"], "g2": [1, 2, 1, 2],
                  "v": [1.0, 2.0, 3.0, 4.0]})
    spark.create_or_replace_temp_view("gs", spark.create_dataframe(t))
    df = spark.sql(query)
    got = [tuple(r.values()) for r in df.collect().to_pylist()]
    exp = [tuple(r.values()) for r in df.collect_host().to_pylist()]
    assert got == exp and exp


def test_setop_parse_edge_cases():
    """Review-found regressions: mixed-nullability set-op arms keep the
    null-safe key lists aligned; a join tree starting with an aliased
    subquery still parses; outer ORDER BY/LIMIT over a parenthesized query
    with its own ORDER BY/LIMIT stack instead of merging."""
    spark = TpuSession()
    spark.create_or_replace_temp_view(
        "sa", spark.create_dataframe(pa.table({"x": [1, 1, 2, 3, None]})))
    spark.create_or_replace_temp_view("r", spark.range(1, 3))
    df = spark.sql("select x from sa intersect select id from r order by x")
    assert [r["x"] for r in df.collect().to_pylist()] == [1, 2]
    assert df.collect().to_pylist() == df.collect_host().to_pylist()

    rows = spark.sql("select * from ((select 1 x) a join (select 1 y) b "
                     "on a.x = b.y)").collect().to_pylist()
    assert rows == [{"x": 1, "y": 1}]

    spark.create_or_replace_temp_view(
        "t2", spark.create_dataframe(pa.table({"a": [1, 2], "b": [2, 1]})))
    got = spark.sql("(select a, b from t2 order by a) order by b"
                    ).collect().to_pylist()
    assert got == [{"a": 2, "b": 1}, {"a": 1, "b": 2}]
    got = spark.sql("(select a from t2 order by a limit 1) limit 3"
                    ).collect().to_pylist()
    assert got == [{"a": 1}]


def test_in_subquery():
    """Uncorrelated IN (subquery) folds to a literal-set membership at
    lowering (reference InSubqueryExec broadcast role); NOT IN keeps
    Spark's three-valued null semantics."""
    spark = TpuSession()
    spark.create_or_replace_temp_view(
        "ta", spark.create_dataframe(pa.table({"x": [1, 2, 3, 4, None]})))
    spark.create_or_replace_temp_view(
        "tb", spark.create_dataframe(pa.table({"y": [2, 4]})))
    df = spark.sql("select x from ta where x in (select y from tb) order by x")
    got = [r["x"] for r in df.collect().to_pylist()]
    assert got == [2, 4]
    assert df.collect().to_pylist() == df.collect_host().to_pylist()
    df = spark.sql(
        "select x from ta where x not in (select y from tb) order by x")
    got = [r["x"] for r in df.collect().to_pylist()]
    assert got == [1, 3]


def test_in_subquery_semi_join_and_widening():
    """Review catches: pushed-down `x IN (subquery)` lowers to a left-semi
    join (not an eagerly collected set); the eager fold (NOT IN / non-
    pushdown positions) widens both sides like Spark instead of truncating
    subquery values into the LHS dtype; a WITH clause inside a
    parenthesized set-op arm registers its CTEs."""
    spark = TpuSession()
    spark.create_or_replace_temp_view(
        "ia", spark.create_dataframe(pa.table({"x": [1, 2, 3, None]})))
    spark.create_or_replace_temp_view(
        "ib", spark.create_dataframe(pa.table({"y": [2.5, 2.0]})))
    # int LHS vs double subquery: 2 matches 2.0, nothing matches 2.5
    df = spark.sql("select x from ia where x in (select y from ib)")
    assert [r["x"] for r in df.collect().to_pylist()] == [2]
    assert df.collect().to_pylist() == df.collect_host().to_pylist()
    df = spark.sql(
        "select x from ia where x not in (select y from ib) order by x")
    assert [r["x"] for r in df.collect().to_pylist()] == [1, 3]
    # semi-join plan shape for the pushed-down form
    from spark_rapids_tpu.plan import nodes as NN

    def find(node, cls):
        hits = [node] if isinstance(node, cls) else []
        for c in node.children:
            hits += find(c, cls)
        return hits
    plan = spark.sql("select x from ia where x in (select y from ib)")._plan
    assert any(j.join_type == "leftsemi" for j in find(plan, NN.JoinNode))
    # CTE inside a parenthesized set-op arm
    got = spark.sql(
        "(with w as (select 1 x) select x from w) union all select 2 x "
        "order by x").collect().to_pylist()
    assert got == [{"x": 1}, {"x": 2}]


@pytest.mark.parametrize("query,want", [
    ("select cast(1.5 as decimal(5,2)) * cast(2.0 as decimal(5,2)) v",
     "3.0000"),
    ("select cast(7.5 as decimal(5,2)) * 3 v", "22.50"),
    ("select cast(1 as decimal(5,2)) / cast(3 as decimal(5,2)) v",
     "0.33333333"),
    ("select cast(-1 as decimal(5,2)) / cast(3 as decimal(5,2)) v",
     "-0.33333333"),
    ("select cast(1 as decimal(5,2)) / 0 v", "None"),
    # DECIMAL64-adjusted scale: (15,4)/(15,4) -> (18,6)
    ("select cast(84927.35 as decimal(15,4)) / "
     "cast(87665.52 as decimal(15,4)) v", "0.968766"),
    # mixed decimal/double rides double (host must read VALUES, not the
    # unscaled ints its decimal columns carry)
    ("select cast(1.50 as decimal(5,2)) + 0.25 v", "1.75"),
    ("select cast(7.50 as decimal(5,2)) / 2.0 v", "3.75"),
    # float64-path overflow -> null (not an INT64_MIN artifact)
    ("select cast(-999999999999999999 as decimal(18,0)) * "
     "cast(999999999999999999 as decimal(18,0)) v", "None"),
])
def test_decimal_multiply_divide(query, want):
    """Spark DecimalPrecision rules capped to DECIMAL64 (q61's shape;
    docs/compatibility.md) — device == host, HALF_UP at the result scale.
    Regression: multiply used the max-scale promote (1.5*2.0 gave 300.00)
    and divide floor-divided unscaled ints (anything/larger gave 0)."""
    spark = TpuSession()
    df = spark.sql(query)
    dev = df.collect().to_pylist()
    assert dev == df.collect_host().to_pylist()
    assert str(list(dev[0].values())[0]) == want


@pytest.mark.parametrize("query,want", [
    ("select k from eo where exists (select 1 from ei where ei.fk = eo.k) "
     "order by k", [2, 4]),
    ("select k from eo where not exists "
     "(select 1 from ei where ei.fk = eo.k) order by k", [None, 1, 3]),
    # inner-only predicates stay inside the subquery
    ("select k from eo where exists (select 1 from ei "
     "where ei.fk = eo.k and w > 2) order by k", [4]),
    # uncorrelated: plan-time fold (non-empty / empty)
    ("select k from eo where exists (select 1 from ei) order by k",
     [None, 1, 2, 3, 4]),
    ("select k from eo where exists (select 1 from ei where w > 100) "
     "order by k", []),
    ("select k from eo where not exists (select 1 from ei where w > 100) "
     "order by k", [None, 1, 2, 3, 4]),
])
def test_exists_subqueries(query, want):
    """[NOT] EXISTS lowers to a left-semi/anti join on the equality
    correlation (Spark RewritePredicateSubquery role); uncorrelated forms
    fold at plan time. NULL outer keys never match, so NOT EXISTS keeps
    them — Spark's anti-join semantics."""
    spark = TpuSession()
    spark.create_or_replace_temp_view("eo", spark.create_dataframe(
        pa.table({"k": [1, 2, 3, 4, None],
                  "v": [10.0, 20.0, 30.0, 40.0, 50.0]})))
    spark.create_or_replace_temp_view("ei", spark.create_dataframe(
        pa.table({"fk": [2, 2, 4, 7], "w": [1, 2, 3, 4]})))
    df = spark.sql(query)
    got = [r["k"] for r in df.collect().to_pylist()]
    assert got == [r["k"] for r in df.collect_host().to_pylist()] == want
