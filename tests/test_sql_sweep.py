"""SQL surface sweep — the qa_nightly_select_test / qa_nightly_sql.py role:
a broad battery of SELECT statements through session.sql(), each checked
device-vs-host (the reference's CPU/GPU equivalence contract) over a
mixed-type table with nulls."""

import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def spark():
    s = TpuSession()
    n = 500
    r = np.random.default_rng(7)
    mask = lambda p: r.random(n) < p

    def witness(vals, m):
        return pa.array([None if mm else v
                         for v, mm in zip(vals.tolist(), m)])
    t = pa.table({
        "i": witness(r.integers(-100, 100, n), mask(0.1)),
        "l": witness(r.integers(-10**12, 10**12, n), mask(0.1)),
        "d": witness(np.round(r.normal(0, 50, n), 3), mask(0.1)),
        "s": pa.array([None if m else ["alpha", "Beta", "gamma", "", "déjà vu",
                                       "x" * 20][v % 6]
                       for v, m in zip(r.integers(0, 6, n), mask(0.1))]),
        "b": witness(r.random(n) < 0.5, mask(0.15)),
        "g": pa.array([["u", "v", "w"][v % 3] for v in range(n)]),
    })
    s.create_or_replace_temp_view("t", s.create_dataframe(t, num_partitions=2))
    return s


QUERIES = [
    # projections / arithmetic / conditionals
    "select i + 1, l - i, d * 2.0, -i from t",
    "select i % 7, l / 3.0, abs(i), abs(d) from t",
    "select case when i > 0 then 'pos' when i < 0 then 'neg' else 'zero' end from t",
    "select case i % 3 when 0 then 'a' when 1 then 'b' else 'c' end from t",
    "select coalesce(i, 0), coalesce(s, 'missing'), nullif(g, 'u') from t",
    "select cast(i as bigint), cast(d as int), cast(i as double), cast(l as string) from t",
    "select i > 0, i >= l, d <> 0.0, s = 'alpha', b and (i > 0), not b from t",
    "select least(i, 0), greatest(i, 10) from t",
    # strings
    "select upper(s), lower(s), length(s), trim(s) from t",
    "select substr(s, 1, 3), substr(s, 2), s || '!' from t",
    "select concat(s, g), s like 'a%', s like '%a', s like '%ta%' from t",
    # predicates
    "select * from t where i between -10 and 10",
    "select * from t where s in ('alpha', 'gamma') and i is not null",
    "select * from t where (i > 50 or i < -50) and d is not null",
    "select * from t where s is null or b",
    "select * from t where not (i between 0 and 100)",
    # aggregation
    "select count(*), count(i), count(s) from t",
    "select sum(i), sum(l), sum(d), min(i), max(d), avg(d) from t",
    "select g, count(*), sum(i), avg(d), min(s), max(s) from t group by g order by g",
    "select g, b, count(*) from t group by g, b order by g, b",
    "select g, sum(d) sd from t group by g having sum(d) > 0 order by sd",
    "select g, stddev_samp(d), var_samp(d) from t group by g order by g",
    "select i % 5 k, count(*) c from t where i is not null group by i % 5 order by k",
    # distinct / order / limit
    "select distinct g from t order by g",
    "select distinct g, b from t order by g, b",
    "select i, d from t where i is not null order by i desc, d limit 17",
    "select s from t order by s nulls first limit 9",
    "select s from t order by s desc nulls last limit 9",
    "select i from t order by abs(i), i limit 11",
    # ordinals / aliases in order-by
    "select g, count(*) n from t group by g order by 2 desc, 1",
    "select g, sum(i) si from t group by g order by si, g",
    # joins (self-join via derived tables)
    "select a.g, b2.cnt from (select g, sum(i) si from t group by g) a, "
    "(select g, count(*) cnt from t group by g) b2 where a.g = b2.g order by a.g",
    "select x.g from (select distinct g from t) x "
    "left join (select g from t where i > 1000) y on x.g = y.g order by x.g",
    # windows
    "select g, i, row_number() over (partition by g order by i nulls last, l nulls last) rn "
    "from t order by g, rn limit 40",
    "select g, d, sum(d) over (partition by g) tot from t order by g, d nulls last limit 40",
    "select g, avg(d) over () global_avg from t limit 5",
    # union / subqueries
    "select i from t where i > 90 union all select i from t where i < -90 order by i",
    "select count(*) from t where d > (select avg(d) from t)",
    "select g, count(*) from t where i < (select max(i) from t) group by g order by g",
    # scalar exprs over aggregates
    "select sum(d) / count(d), max(i) - min(i) from t",
    "select g, sum(d) / count(*) from t group by g order by g",
]


def _norm(v):
    if isinstance(v, float):
        if math.isnan(v):
            return ("nan",)
        return float(f"{v:.10g}")   # relative rounding (sums of ~1e12 terms)
    return v


def _rows(tbl):
    # positional (duplicate auto-named columns must not collapse via dicts)
    cols = [c.to_pylist() for c in tbl.columns]
    return [tuple(_norm(v) for v in row) for row in zip(*cols)] if cols else []


@pytest.mark.parametrize("sql", QUERIES)
def test_sql_sweep_device_matches_host(spark, sql):
    df = spark.sql(sql)
    got = _rows(df.collect())
    exp = _rows(df.collect_host())
    has_order = "order by" in sql
    if not has_order:
        got, exp = sorted(got, key=repr), sorted(exp, key=repr)
    assert got == exp, f"{sql}\n{got[:5]} vs {exp[:5]}"


def test_distinct_aggregates_rewrite():
    """fn(DISTINCT x) lowers through the two-level rewrite (Spark
    RewriteDistinctAggregates role): inner GROUP BY (keys, x) dedupes,
    outer re-aggregates; min/max mix in (distinct-insensitive)."""
    import pyarrow as pa
    from spark_rapids_tpu.session import TpuSession
    spark = TpuSession()
    t = pa.table({"g": pa.array(["a", "a", "b", "b", "b", None]),
                  "x": pa.array([1, 1, 2, None, 3, 2], pa.int64()),
                  "y": pa.array([5.0, 6.0, 1.0, 2.0, 3.0, 9.0])})
    spark.create_or_replace_temp_view("dt", spark.create_dataframe(t))
    row = spark.sql("select count(distinct x) as c, sum(distinct x) as s, "
                    "avg(distinct x) as a from dt").collect().to_pylist()[0]
    assert row == {"c": 3, "s": 6, "a": 2.0}
    rows = sorted(spark.sql(
        "select g, count(distinct x) as c, min(y) as mn, max(y) as mx "
        "from dt group by g").collect().to_pylist(),
        key=lambda r: (r["g"] is None, r["g"]))
    assert rows == [
        {"g": "a", "c": 1, "mn": 5.0, "mx": 6.0},
        {"g": "b", "c": 2, "mn": 1.0, "mx": 3.0},
        {"g": None, "c": 1, "mn": 9.0, "mx": 9.0}]
    # unsupported mixes fail loudly, not silently wrong
    import pytest
    from spark_rapids_tpu.sql.lower import SqlAnalysisError
    with pytest.raises(SqlAnalysisError):
        spark.sql("select count(distinct x), sum(y) from dt").collect()
    with pytest.raises(SqlAnalysisError):
        spark.sql("select count(distinct x), count(distinct g) from dt"
                  ).collect()
