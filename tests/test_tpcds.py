"""TPC-DS subset end-to-end through the session API vs independent NumPy
oracles (BASELINE.md config-3; reference qa_nightly_select_test role)."""

import pytest

from spark_rapids_tpu.benchmarks import tpcds
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpcds")
    paths = tpcds.generate(0.012, str(d))
    spark = TpuSession()
    return tpcds.load(spark, paths), tpcds.load_np(paths)


def _rows(df):
    return [tuple(r.values()) for r in df.collect().to_pylist()]


def _check(got, exp, float_cols):
    assert len(got) == len(exp), (len(got), len(exp))
    for g, e in zip(got, exp):
        assert len(g) == len(e), (g, e)
        for i, (a, b) in enumerate(zip(g, e)):
            if i in float_cols:
                assert a == pytest.approx(b, rel=1e-9), (g, e)
            else:
                assert a == b, (g, e)


@pytest.mark.parametrize("name,float_cols", [
    ("q3", {3}), ("q42", {3}), ("q52", {3}), ("q55", {2}),
    ("q7", {1, 2, 3, 4}), ("q19", {3}),
    # round-3 breadth: window-heavy (q53/q63/q89/q98), decimal-heavy
    # (q48/q79 over decimal(7,2) ss_net_profit — exact, no float slot),
    # conditional aggregation (q43), multi-count cross join (q88/q96),
    # ticket/basket shapes (q34/q73/q46/q68/q79), avg-subquery joins
    # (q6/q65), state rollup base (q27)
    ("q6", set()), ("q27", {2, 3, 4, 5}), ("q34", set()),
    ("q43", {1, 2, 3, 4, 5, 6, 7}), ("q46", {5, 6}), ("q48", set()),
    ("q53", {1, 2}), ("q63", {1, 2}), ("q65", {2, 3}),
    ("q68", {5, 6, 7}), ("q73", set()), ("q79", {5}), ("q88", set()),
    ("q89", {5, 6}), ("q96", set()), ("q98", {4, 5, 6}),
])
def test_tpcds_query_matches_oracle(data, name, float_cols):
    dfs, tb = data
    got = _rows(tpcds.QUERIES[name](dfs))
    exp = [tuple(r) for r in tpcds.NP_QUERIES[name](tb)]
    assert exp, "vacuous test: oracle returned no rows"
    _check(got, exp, float_cols)


def test_tpcds_q3_over_mesh(tmp_path):
    """Config-3's defining property: the subset also runs with exchanges as
    all_to_all collectives over the virtual 8-device mesh."""
    paths = tpcds.generate(0.003, str(tmp_path))
    mesh = TpuSession({"spark.rapids.tpu.mesh.enabled": "true",
                       "spark.rapids.tpu.mesh.devices": "8"})
    dfs = tpcds.load(mesh, paths)
    got = _rows(tpcds.q3(dfs))
    exp = [tuple(r) for r in tpcds.np_q3(tpcds.load_np(paths))]
    assert exp, "vacuous test: oracle returned no rows"
    _check(got, exp, {3})
