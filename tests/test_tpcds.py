"""TPC-DS subset end-to-end through the session API vs independent NumPy
oracles (BASELINE.md config-3; reference qa_nightly_select_test role)."""

import pytest

from spark_rapids_tpu.benchmarks import tpcds
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpcds")
    paths = tpcds.generate(0.012, str(d))
    spark = TpuSession()
    return tpcds.load(spark, paths), tpcds.load_np(paths)


def _rows(df):
    return [tuple(r.values()) for r in df.collect().to_pylist()]


def _check(got, exp, float_cols):
    # single source of truth with bench.py's recorded sweep
    tpcds.check_rows(got, exp, float_cols)


# breadth: window-heavy (q53/q63/q89/q98), decimal-heavy (q48/q79 over
# decimal(7,2) ss_net_profit — exact, no float slot), conditional aggregation
# (q43), multi-count cross join (q88/q96), ticket/basket shapes
# (q34/q73/q46/q68/q79), avg-subquery joins (q6/q65), state rollup base (q27);
# float-tolerance columns come from the shared tpcds.FLOAT_COLS table
@pytest.mark.parametrize("name", sorted(tpcds.FLOAT_COLS))
def test_tpcds_query_matches_oracle(data, name):
    dfs, tb = data
    got = _rows(tpcds.QUERIES[name](dfs))
    exp = [tuple(r) for r in tpcds.NP_QUERIES[name](tb)]
    assert exp, "vacuous test: oracle returned no rows"
    _check(got, exp, tpcds.FLOAT_COLS[name])


def test_tpcds_q3_over_mesh(tmp_path):
    """Config-3's defining property: the subset also runs with exchanges as
    all_to_all collectives over the virtual 8-device mesh."""
    paths = tpcds.generate(0.003, str(tmp_path))
    mesh = TpuSession({"spark.rapids.tpu.mesh.enabled": "true",
                       "spark.rapids.tpu.mesh.devices": "8"})
    dfs = tpcds.load(mesh, paths)
    got = _rows(tpcds.q3(dfs))
    exp = [tuple(r) for r in tpcds.np_q3(tpcds.load_np(paths))]
    assert exp, "vacuous test: oracle returned no rows"
    _check(got, exp, {3})
