"""Unified mesh-cluster plane: N executor processes x M local mesh devices.

ROADMAP item 4 / the elastic mesh-cluster plane: a MiniCluster executor
drives a LOCAL device mesh (one jitted shard_map dispatch computes every
lane's Spark-exact partition ids per wave, with the wave's map-output
statistics psum-ed over ICI), while shuffle blocks still cross executors
over the TCP transport. Robustness is the contract under test:

- combined-plane results are BIT-IDENTICAL to the TCP-only plane (and to
  a single-process run for the q18 ladder query);
- a mesh participant killed or wedged inside the collective is surfaced
  by the PR-5 heartbeat/deadline machinery and the task transparently
  re-plans onto the per-split TCP path under a bumped epoch — degraded
  mode, counted in meshDegradedFallbacks, never a hang and never a
  whole-query heal;
- movement-aware placement schedules reduce tasks on the executor holding
  the most map-output bytes (with spill-aware demotion when that host is
  over its budget proxy);
- the disk-spill tier's ENOSPC is typed (SpillCapacityError) and rides
  the existing OOM recovery ladder.
"""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.functions as F
from spark_rapids_tpu.cluster import MiniCluster
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.runtime import faults as FLT
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.session import TpuSession

N_EXEC = 2
N_SPLITS = 6
MESH_CONF = {"spark.rapids.tpu.cluster.mesh.enabled": "true",
             "spark.rapids.tpu.cluster.mesh.devicesPerExecutor": 4}


@pytest.fixture(autouse=True)
def _clean_chaos_state():
    FLT.reset()
    tracing.clear_events()
    yield
    FLT.reset()
    tracing.clear_events()


@pytest.fixture(scope="module")
def spark():
    return TpuSession()


@pytest.fixture(scope="module")
def df(spark):
    rng = np.random.default_rng(7)
    t = pa.table({"k": pa.array(rng.integers(0, 13, 3000), type=pa.int64()),
                  "v": pa.array(rng.random(3000))})
    return (spark.create_dataframe(t, num_partitions=N_SPLITS)
            .group_by(F.col("k")).agg(F.sum(F.col("v")).alias("s")))


@pytest.fixture(scope="module")
def tcp_table(df):
    """The TCP-only-plane oracle: same query, same cluster shape, mesh
    off — every combined-plane run must reproduce these exact bytes."""
    with MiniCluster(n_executors=N_EXEC, platform="cpu") as c:
        return c.collect(df)


def _run_mesh(df, extra=None, no_heal=True):
    base = M.resilience_snapshot()
    conf = RapidsConf(dict(MESH_CONF, **(extra or {})))
    with MiniCluster(n_executors=N_EXEC, conf=conf, platform="cpu") as c:
        heals = []
        orig = c._heal
        c._heal = lambda: (heals.append(1), orig())[-1]
        got = c.collect(df)
        stats = {"mesh": dict(c.mesh_stats),
                 "placement": dict(c.placement_stats),
                 "widths": list(c._mesh), "mesh_ok": list(c._mesh_ok),
                 "heals": len(heals), "task_log": list(c.task_log),
                 "alive": [p.is_alive() for p in c._procs]}
    end = M.resilience_snapshot()
    delta = {k: end[k] - base[k] for k in end if end[k] - base[k]}
    if no_heal:
        assert stats["heals"] == 0, \
            f"whole-query heal fired; degraded fallback expected ({delta})"
    return got, delta, stats


# -- the combined plane, healthy ---------------------------------------------

def test_mesh_plane_bit_identical_and_grouped(df, tcp_table):
    """Mesh plane on: map splits run as mesh task groups (one task drives
    several lanes on one executor's local mesh) and the result is
    bit-identical to the TCP-only plane, with zero resilience noise."""
    got, delta, stats = _run_mesh(df)
    assert got.equals(tcp_table), "mesh plane result differs from TCP plane"
    assert stats["widths"] == [4] * N_EXEC, stats
    assert stats["mesh"]["mesh_tasks"] >= 1, stats
    assert stats["mesh"]["waves"] >= 1, stats
    assert stats["mesh"]["degraded"] == 0, stats
    assert any(op == "map.mesh" for op, _ in stats["task_log"]), stats
    assert not delta, f"healthy mesh run left resilience noise: {delta}"
    names = {n for n, _ in tracing.recent_events()}
    assert "mesh.attach" in names, names


def test_mesh_width_respects_conf_and_availability(df):
    """devicesPerExecutor narrower than the visible 8 devices: the
    handshake reports the conf'd width and groups are sized to it."""
    got, _, stats = _run_mesh(
        df, {"spark.rapids.tpu.cluster.mesh.devicesPerExecutor": 3})
    assert stats["widths"] == [3] * N_EXEC, stats
    mesh_tasks = [op for op, _ in stats["task_log"] if op == "map.mesh"]
    assert mesh_tasks, stats
    with MiniCluster(n_executors=N_EXEC, platform="cpu") as c:
        assert got.equals(c.collect(df))


# -- degraded-mode fallback ---------------------------------------------------

def test_mesh_participant_kill_degrades_to_tcp(df, tcp_table):
    """A mesh participant SIGKILLed inside the collective (mesh_kill
    site): the loss is detected, the group's lanes re-plan per-split onto
    the TCP path under a bumped epoch, and the result stays
    bit-identical — counter-checked, no whole-query heal."""
    got, delta, stats = _run_mesh(
        df, {"spark.rapids.tpu.test.faults": "exec_kill:cluster.mesh.1:1"})
    assert got.equals(tcp_table), "mesh-kill result is not bit-identical"
    assert delta.get("executorsLost", 0) >= 1, delta
    assert delta.get("meshDegradedFallbacks", 0) >= 1, delta
    assert all(stats["alive"]), "pool not restored"
    names = {n for n, _ in tracing.recent_events()}
    assert {"mesh.degraded", "mesh.detach", "mesh.attach",
            "executor.lost"} <= names, names


def test_mesh_failure_degrades_without_executor_loss(df, tcp_table):
    """The mesh itself failing (chips unavailable / collective error) with
    the executor alive: a TRANSPARENT re-plan — no executor lost, no
    task-attempt strike charged, the slot's mesh distrusted, result
    bit-identical."""
    got, delta, stats = _run_mesh(
        df,
        {"spark.rapids.tpu.test.faults": "error:cluster.mesh.begin.0:1"})
    assert got.equals(tcp_table)
    assert delta.get("meshDegradedFallbacks", 0) >= 1, delta
    assert delta.get("executorsLost", 0) == 0, delta
    assert delta.get("taskAttempts", 0) == 0, \
        f"degradation must not charge attempt strikes: {delta}"
    assert stats["mesh_ok"][0] is False, stats
    names = {n for n, _ in tracing.recent_events()}
    assert "mesh.degraded" in names, names


def test_mesh_hang_surfaced_by_deadline_not_a_hang(df, tcp_table):
    """A task wedged INSIDE the mesh collective (mesh_hang site) is
    detected by the PR-5 task-deadline machinery — the executor is killed
    and replaced, the lanes degrade to TCP, and the query completes
    bit-identically instead of hanging."""
    got, delta, stats = _run_mesh(
        df, {"spark.rapids.tpu.cluster.task.timeoutSeconds": 5.0,
             "spark.rapids.tpu.test.faults": "hang:cluster.mesh.0:1"})
    assert got.equals(tcp_table)
    assert delta.get("executorsLost", 0) >= 1, delta
    assert delta.get("meshDegradedFallbacks", 0) >= 1, delta
    assert all(stats["alive"]), stats


# -- movement-aware placement -------------------------------------------------

def test_tracker_movement_statistics():
    from spark_rapids_tpu.cluster.minicluster import MapOutputTracker
    tr = MapOutputTracker()
    tr.register_shuffle(1, None, None, "plain", [0, 1])
    tr.register_map_output(1, 0, 0, sizes=[100, 5])
    tr.register_map_output(1, 1, 1, sizes=[10, 50])
    assert tr.bytes_by_executor([1], 0) == {0: 100, 1: 10}
    assert tr.bytes_by_executor([1], 1) == {0: 5, 1: 50}
    assert tr.executor_load(0) == 105 and tr.executor_load(1) == 60
    # invalidation drops the bytes with the hosts and bumps the epoch
    tr.invalidate_splits(1, [0])
    assert tr.epoch(1) == 1
    assert tr.bytes_by_executor([1], 0) == {1: 10}
    assert tr.executor_load(0) == 0


def test_placement_policy_preferred_does_not_advance_rotation():
    from spark_rapids_tpu.cluster.minicluster import PlacementPolicy
    p = PlacementPolicy(3, seed=0)
    assert p.pick({0, 1, 2}, preferred=2) == 2
    # the round-robin cursor was not consumed by the preferred pick
    assert p.pick({0, 1, 2}) == 0
    assert p.pick({0, 1, 2}) == 1
    # a preferred executor the spec already failed on is ignored
    assert p.pick({0, 1}, prefer_not={0}, preferred=0) == 1


def test_movement_aware_placement_prefers_byte_dominant_host(spark):
    """One map split -> one executor holds ALL map-output bytes; the first
    reduce task must land exactly there (a local block-store read), with
    preferred hits counted."""
    rng = np.random.default_rng(3)
    t = pa.table({"k": pa.array(rng.integers(0, 7, 2000), type=pa.int64()),
                  "v": pa.array(rng.random(2000))})
    df1 = (spark.create_dataframe(t, num_partitions=1)
           .group_by(F.col("k")).agg(F.sum(F.col("v")).alias("s")))
    with MiniCluster(n_executors=N_EXEC, platform="cpu") as c:
        got = c.collect(df1)
        log = list(c.task_log)
        stats = dict(c.placement_stats)
    byte_host = next(ei for op, ei in log if op == "map")
    first_reduce = next(ei for op, ei in log if op == "result")
    assert first_reduce == byte_host, (log, stats)
    assert stats["preferred"] >= 1, stats
    exp = {r["k"]: r["s"] for r in df1.collect_host().to_pylist()}
    assert {r["k"]: r["s"] for r in got.to_pylist()} == pytest.approx(exp)


def test_spill_aware_demotion_when_host_over_budget(spark):
    """With placement.maxLoadedBytes shrunk below the parked bytes, the
    byte-dominant pick is DEMOTED back to round-robin (placement.demoted
    event + counter) instead of piling work on a spilling host."""
    rng = np.random.default_rng(3)
    t = pa.table({"k": pa.array(rng.integers(0, 7, 2000), type=pa.int64()),
                  "v": pa.array(rng.random(2000))})
    df1 = (spark.create_dataframe(t, num_partitions=1)
           .group_by(F.col("k")).agg(F.sum(F.col("v")).alias("s")))
    conf = RapidsConf(
        {"spark.rapids.tpu.cluster.placement.maxLoadedBytes": "1"})
    with MiniCluster(n_executors=N_EXEC, conf=conf, platform="cpu") as c:
        got = c.collect(df1)
        stats = dict(c.placement_stats)
    assert stats["demoted"] >= 1, stats
    assert stats["preferred"] == 0, stats
    names = {n for n, _ in tracing.recent_events()}
    assert "placement.demoted" in names, names
    exp = {r["k"]: r["s"] for r in df1.collect_host().to_pylist()}
    assert {r["k"]: r["s"] for r in got.to_pylist()} == pytest.approx(exp)


# -- typed ENOSPC on the disk-spill tier --------------------------------------

def test_spill_capacity_error_is_typed_and_retryable(tmp_path):
    """The disk-full fault at the spill writer surfaces as the typed,
    retryable SpillCapacityError (an OOM-class error), not a raw
    OSError."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.runtime.memory import BufferCatalog
    from spark_rapids_tpu.runtime.retry import (DeviceOomError,
                                                SpillCapacityError)

    def make(seed):
        r = np.random.default_rng(seed)
        return ColumnarBatch.from_arrow(pa.table(
            {"a": pa.array(r.integers(0, 1000, 4096), type=pa.int64())}))

    one = make(0).device_memory_size()
    cat = BufferCatalog(device_budget=int(one * 1.2),
                        host_budget=int(one * 0.5),
                        spill_dir=str(tmp_path))
    cat.add_batch(make(1))
    FLT.configure("disk_full:spill.write:1")
    with pytest.raises(SpillCapacityError) as ei:
        cat.add_batch(make(2))      # forces device->host->disk: ENOSPC
    assert isinstance(ei.value, DeviceOomError) and ei.value.retryable
    assert ("disk_full", "spill.write") in FLT.injected_log()
    # accounting stayed consistent: nothing half-moved to the disk tier
    assert cat.disk_bytes == 0 and cat.spilled_to_disk_bytes == 0


def test_spill_capacity_error_rides_oom_ladder(tmp_path):
    """SpillCapacityError routed through the EXISTING recovery ladder:
    call_with_retry absorbs the injected ENOSPC (spill-only retry) and the
    registration succeeds on the second attempt, with the recovery visible
    in the oom-retry counter."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.runtime import retry as R
    from spark_rapids_tpu.runtime.memory import BufferCatalog

    def make(seed):
        r = np.random.default_rng(seed)
        return ColumnarBatch.from_arrow(pa.table(
            {"a": pa.array(r.integers(0, 1000, 4096), type=pa.int64())}))

    one = make(0).device_memory_size()
    cat = BufferCatalog(device_budget=int(one * 1.2),
                        host_budget=int(one * 0.5),
                        spill_dir=str(tmp_path))
    cat.add_batch(make(1))
    base = M.resilience_snapshot()
    FLT.configure("disk_full:spill.write:1")
    bid = R.call_with_retry(lambda: cat.add_batch(make(2)),
                            scope="exchange.write", catalog=cat)
    got = cat.acquire_batch(bid).to_arrow()
    assert got.equals(make(2).to_arrow())
    delta = M.resilience_snapshot()
    assert delta[M.NUM_OOM_RETRIES] - base[M.NUM_OOM_RETRIES] >= 1
    assert ("disk_full", "spill.write") in FLT.injected_log()


# -- spawn-handshake hardening ------------------------------------------------

def test_spawn_handshake_retry_on_transient_failure(monkeypatch):
    """One transient bring-up failure must cost a retry (visible as an
    executor.spawn.retry event), not the slot."""
    calls = {"n": 0}
    orig = MiniCluster._spawn_executor_once

    def flaky(self, ei, arm_faults=True):
        calls["n"] += 1
        if calls["n"] == 2:     # first bring-up of slot 1 dies
            raise RuntimeError("executor 1 died during bring-up (injected)")
        return orig(self, ei, arm_faults)

    monkeypatch.setattr(MiniCluster, "_spawn_executor_once", flaky)
    with MiniCluster(n_executors=N_EXEC, platform="cpu") as c:
        assert all(p.is_alive() for p in c._procs)
    events = [(n, a) for n, a in tracing.recent_events()
              if n == "executor.spawn.retry"]
    assert events and events[0][1]["executor"] == 1, events


def test_spawn_retry_exhaustion_still_raises(monkeypatch):
    def dead(self, ei, arm_faults=True):
        raise RuntimeError(f"executor {ei} never came up (injected)")

    monkeypatch.setattr(MiniCluster, "_spawn_executor_once", dead)
    with pytest.raises(RuntimeError, match="never came up"):
        MiniCluster(n_executors=1, platform="cpu")
    events = [n for n, _ in tracing.recent_events()
              if n == "executor.spawn.retry"]
    assert events, "retry must be attempted (and logged) before giving up"


# -- LocalMesh unit coverage --------------------------------------------------

def test_local_mesh_wave_matches_per_batch_pids():
    """The stacked shard_map pid program is bit-exact with the per-batch
    partitioner (the property that makes mesh->TCP degradation sound), and
    the psum-ed wave statistics count every live row exactly once."""
    from spark_rapids_tpu.columnar.arrow import table_to_device
    from spark_rapids_tpu.distributed.mesh import LocalMesh
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.shuffle import partitioning as SP

    rng = np.random.default_rng(0)
    batches = [table_to_device(pa.table(
        {"k": pa.array(rng.integers(0, 99, n), type=pa.int64()),
         "v": pa.array(rng.random(n))})) for n in (700, 300, 1000)]
    part = SP.HashPartitioner([col("k")], 5).bind(batches[0].schema)
    lm = LocalMesh(4)
    pids_list, counts = lm.partition_wave(batches, part)
    for b, pids in zip(batches, pids_list):
        ref, mesh = part.partition(b), SP.slice_into_partitions(
            b, pids, part.num_partitions)
        assert len(ref) == len(mesh)
        for (p1, b1), (p2, b2) in zip(ref, mesh):
            assert p1 == p2 and b1.to_arrow().equals(b2.to_arrow())
    assert counts.sum() == sum(b.num_rows for b in batches)


def test_local_mesh_string_keys_fall_back_per_batch():
    """String keys: per-lane dictionaries cannot be trace-time constants
    of one stacked program, so the wave falls back to the per-batch pid
    path (counts None) — still bit-exact."""
    from spark_rapids_tpu.columnar.arrow import table_to_device
    from spark_rapids_tpu.distributed.mesh import LocalMesh
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.shuffle import partitioning as SP

    words = ["alpha", "beta", "gamma", "delta"]
    batches = [table_to_device(pa.table(
        {"s": pa.array([words[(i + off) % 4] for i in range(n)])}))
        for off, n in ((0, 64), (2, 32))]
    part = SP.HashPartitioner([col("s")], 3).bind(batches[0].schema)
    lm = LocalMesh(2)
    pids_list, counts = lm.partition_wave(batches, part)
    assert counts is None
    for b, pids in zip(batches, pids_list):
        assert np.array_equal(
            np.asarray(pids)[:b.num_rows],
            np.asarray(part.part_ids(b))[:b.num_rows])


def test_local_mesh_shrink_raises_degraded():
    from spark_rapids_tpu.distributed.mesh import (LocalMesh,
                                                   MeshDegradedError)
    from spark_rapids_tpu.columnar.arrow import table_to_device
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.shuffle import partitioning as SP
    lm = LocalMesh(2)
    batches = [table_to_device(pa.table(
        {"k": pa.array([1, 2, 3], type=pa.int64())})) for _ in range(3)]
    part = SP.HashPartitioner([col("k")], 2).bind(batches[0].schema)
    with pytest.raises(MeshDegradedError, match="shrank"):
        lm.partition_wave(batches, part)     # 3 lanes > 2 devices


# -- two-level exchange: partition content over ICI ---------------------------

def test_two_level_exchange_bit_identical_and_rides_ici(df, tcp_table):
    """Default-on two-level plane: reduce partitions owned by this
    executor move lane->lane as all_to_all over ICI (ici_rows counted,
    consumers placed at the owner), and the result stays bit-identical
    to the TCP-only plane with zero resilience noise."""
    got, delta, stats = _run_mesh(df)
    assert got.equals(tcp_table), "two-level result differs from TCP plane"
    assert stats["mesh"]["ici_rows"] > 0, stats
    assert stats["placement"].get("owner", 0) >= 1, stats
    assert stats["mesh"]["degraded"] == 0, stats
    assert not delta, f"two-level run left resilience noise: {delta}"


def test_two_level_off_keeps_content_off_ici(df, tcp_table):
    """The twoLevel knob off: same mesh grouping, same bytes, but no
    partition content rides ICI (the pid program's psum is all that
    touches the collective plane)."""
    got, _, stats = _run_mesh(
        df, {"spark.rapids.tpu.cluster.mesh.exchange.twoLevel": "false"})
    assert got.equals(tcp_table)
    assert stats["mesh"]["ici_rows"] == 0, stats
    assert stats["mesh"]["mesh_tasks"] >= 1, stats


def test_two_level_string_keys_fall_back_without_breaking_group(spark):
    """String keys cannot ride the stacked all_to_all program (per-batch
    dictionaries), so the wave falls back to per-batch slice-and-park —
    WITHOUT degrading the mesh group or charging a fallback."""
    words = ["alpha", "beta", "gamma", "delta", "epsilon"]
    rng = np.random.default_rng(11)
    t = pa.table({"s": pa.array([words[i % 5] for i in
                                 rng.integers(0, 5, 2000)]),
                  "v": pa.array(rng.random(2000))})
    sdf = (spark.create_dataframe(t, num_partitions=N_SPLITS)
           .group_by(F.col("s")).agg(F.sum(F.col("v")).alias("t")))
    with MiniCluster(n_executors=N_EXEC, platform="cpu") as c:
        tcp = c.collect(sdf)
    got, delta, stats = _run_mesh(sdf)
    assert got.equals(tcp), "string-key fallback is not bit-identical"
    assert stats["mesh"]["mesh_tasks"] >= 1, stats
    assert stats["mesh"]["ici_rows"] == 0, stats
    assert stats["mesh"]["degraded"] == 0, stats
    assert not delta, delta


def test_mesh_kill_mid_all_to_all_degrades_to_tcp(df, tcp_table):
    """An executor SIGKILLed INSIDE the content all_to_all: the loss is
    detected, the group re-plans per-split onto TCP under a bumped epoch
    (partial intra-mesh shards dropped with the dead store — bit-identity
    is the no-leak proof), counted in meshDegradedFallbacks."""
    got, delta, stats = _run_mesh(
        df, {"spark.rapids.tpu.test.faults":
             "exec_kill:cluster.mesh.exchange.1:1"})
    assert got.equals(tcp_table), "kill-mid-exchange is not bit-identical"
    assert delta.get("executorsLost", 0) >= 1, delta
    assert delta.get("meshDegradedFallbacks", 0) >= 1, delta
    assert all(stats["alive"]), "pool not restored"
    names = {n for n, _ in tracing.recent_events()}
    assert {"mesh.degraded", "executor.lost"} <= names, names


def test_mesh_exchange_error_degrades_transparently(df, tcp_table):
    """The all_to_all itself failing with the executor alive: transparent
    re-plan onto per-split TCP — surviving partial writes are dropped via
    drop_map_output under the bumped epoch, no executor lost, no attempt
    strike charged, result bit-identical."""
    got, delta, stats = _run_mesh(
        df, {"spark.rapids.tpu.test.faults":
             "error:cluster.mesh.exchange.0:1"})
    assert got.equals(tcp_table)
    assert delta.get("meshDegradedFallbacks", 0) >= 1, delta
    assert delta.get("executorsLost", 0) == 0, delta
    assert delta.get("taskAttempts", 0) == 0, \
        f"degradation must not charge attempt strikes: {delta}"
    names = {n for n, _ in tracing.recent_events()}
    assert "mesh.degraded" in names, names


# -- the q18 ladder query over the combined plane -----------------------------

def _load_multisplit(spark, paths):
    """Load each TPC-H table as an explicit sorted file LIST (one file per
    split) — directory loads collapse to a single FilePartition, which
    would leave nothing for a mesh group to exchange."""
    import os
    dfs = {}
    for name, p in paths.items():
        if os.path.isdir(p):
            fs = sorted(os.path.join(p, f) for f in os.listdir(p)
                        if f.endswith(".parquet"))
            dfs[name] = spark.read_parquet(fs, files_per_partition=1)
        else:
            dfs[name] = spark.read_parquet(p)
        spark.create_or_replace_temp_view(name, dfs[name])
    return dfs


@pytest.mark.slow
def test_mesh_cluster_q18_bit_identical_vs_single_process(tmp_path_factory):
    """TPC-H q18 on a 2-executor MiniCluster driving local meshes: the
    two-level plane (content over ICI) reproduces the TCP-only cluster
    bytes, the twoLevel-off mesh bytes, AND the single-process result."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.benchmarks import tpch
    data = str(tmp_path_factory.mktemp("tpch")) + "/sf001"
    paths = tpch.generate(0.01, data)
    spark = TpuSession()
    dfs = _load_multisplit(spark, paths)
    q18 = tpch.QUERIES["q18"](dfs)
    single = q18.collect()
    with MiniCluster(n_executors=N_EXEC, platform="cpu") as c:
        tcp = c.collect(q18)
    conf = RapidsConf(MESH_CONF)
    with MiniCluster(n_executors=N_EXEC, conf=conf, platform="cpu") as c:
        mesh = c.collect(q18)
        stats = dict(c.mesh_stats)
    off_conf = RapidsConf(dict(
        MESH_CONF,
        **{"spark.rapids.tpu.cluster.mesh.exchange.twoLevel": "false"}))
    with MiniCluster(n_executors=N_EXEC, conf=off_conf,
                     platform="cpu") as c:
        mesh_off = c.collect(q18)
        stats_off = dict(c.mesh_stats)
    assert mesh.equals(tcp), "two-level q18 differs from TCP plane"
    assert mesh.equals(single), "two-level q18 differs from 1-process"
    assert mesh.equals(mesh_off), "two-level q18 differs from twoLevel=off"
    assert stats["mesh_tasks"] >= 1 and stats["degraded"] == 0, stats
    assert stats["ici_rows"] > 0, stats
    assert stats_off["ici_rows"] == 0, stats_off
