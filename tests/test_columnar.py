"""Columnar batch representation tests (reference ring-2 analog: GpuColumnVector /
arrow import round-trips)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import ColumnarBatch, TpuColumnVector, bucket_capacity
from spark_rapids_tpu.columnar import arrow as ai


def test_bucket_capacity():
    assert bucket_capacity(0) == 8
    assert bucket_capacity(8) == 8
    assert bucket_capacity(9) == 16
    assert bucket_capacity(1000) == 1024


def test_fixed_width_roundtrip(mixed_table):
    batch = ColumnarBatch.from_arrow(mixed_table)
    assert batch.num_rows == mixed_table.num_rows
    assert batch.capacity == bucket_capacity(mixed_table.num_rows)
    out = batch.to_arrow()
    for name in ("i", "l", "d", "f", "b"):
        assert out.column(name).combine_chunks().equals(
            mixed_table.column(name).combine_chunks().cast(out.column(name).type)), name


def test_string_dictionary_roundtrip(mixed_table):
    batch = ColumnarBatch.from_arrow(mixed_table)
    scol = batch.column(batch.schema.index_of("s"))
    assert scol.is_string and scol.dictionary is not None
    # dictionary is sorted => code order == lexical order
    d = scol.dictionary.to_pylist()
    assert d == sorted(d)
    out = batch.to_arrow().column("s").combine_chunks()
    assert out.equals(mixed_table.column("s").combine_chunks())


def test_null_canonicalization():
    cv = TpuColumnVector.from_pylist(T.INT, [1, None, 3, None])
    vals, valid = cv.to_host(4)
    assert list(valid) == [True, False, True, False]
    assert vals[1] == 0 and vals[3] == 0  # canonical default in null slots
    assert not np.asarray(cv.validity)[4:].any()  # padded tail invalid


def test_decimal_roundtrip():
    arr = pa.array([None, "1.23", "-99999999.99", "0.01"]).cast(pa.decimal128(10, 2))
    t = pa.table({"dec": arr})
    batch = ColumnarBatch.from_arrow(t)
    col = batch.column(0)
    assert col.dtype == T.DecimalType(10, 2)
    vals, valid = col.to_host(4)
    assert vals[1] == 123 and vals[2] == -9999999999 and vals[3] == 1
    out = batch.to_arrow().column("dec").combine_chunks()
    assert out.equals(arr)


def test_timestamp_date_roundtrip():
    ts = pa.array([0, 1_600_000_000_000_000, None], type=pa.timestamp("us", tz="UTC"))
    dt = pa.array([0, 18000, None], type=pa.date32())
    t = pa.table({"ts": ts, "dt": dt})
    batch = ColumnarBatch.from_arrow(t)
    assert batch.column(0).dtype == T.TIMESTAMP
    assert batch.column(1).dtype == T.DATE
    out = batch.to_arrow()
    assert out.column("ts").combine_chunks().equals(ts)
    assert out.column("dt").combine_chunks().equals(dt)


def test_empty_batch():
    schema = T.StructType([T.StructField("a", T.INT), T.StructField("s", T.STRING)])
    b = ColumnarBatch.empty(schema)
    assert b.num_rows == 0


def test_conf_registry():
    from spark_rapids_tpu.config import (RapidsConf, BATCH_SIZE_BYTES, parse_bytes,
                                         generate_docs)
    c = RapidsConf({"spark.rapids.tpu.sql.batchSizeBytes": "64m"})
    assert c.get(BATCH_SIZE_BYTES) == 64 << 20
    assert RapidsConf().get(BATCH_SIZE_BYTES) == 512 << 20
    assert parse_bytes("4g") == 4 << 30
    with pytest.raises(ValueError):
        RapidsConf({"spark.rapids.tpu.sql.bogus": 1})
    docs = generate_docs()
    assert "spark.rapids.tpu.sql.enabled" in docs


def test_murmur3_matches_spark_vectors():
    """Golden vectors from Spark's Murmur3_x86_32 (seed 42), the contract the
    reference's GpuHashPartitioning depends on."""
    from spark_rapids_tpu.ops import hashing as H
    # spark.sql("select hash(0)") == 933211791 and hash(1) == -559580957 are
    # well-known Spark goldens; the rest are pinned regression values.
    assert H.murmur3_int_host(0, 42) == 933211791
    assert H.murmur3_int_host(1, 42) == -559580957
    assert H.murmur3_int_host(-1, 42) == -1604776387
    assert H.murmur3_long_host(0, 42) == -1670924195
    assert H.murmur3_long_host(1, 42) == -1712319331
    assert H.murmur3_bytes_host(b"", 42) == 142593372
    assert H.murmur3_bytes_host("abc".encode(), 42) == 1322437556


def test_murmur3_device_matches_host():
    import jax.numpy as jnp
    from spark_rapids_tpu.ops import hashing as H
    ints = np.array([0, 1, -1, 2**31 - 1, -2**31, 12345], dtype=np.int32)
    seed = jnp.int32(42)
    dev = np.asarray(H.hash_int(jnp.asarray(ints), seed))
    host = [H.murmur3_int_host(int(v), 42) for v in ints]
    assert list(dev) == host

    longs = np.array([0, 1, -1, 2**63 - 1, -2**63, 10**12], dtype=np.int64)
    dev = np.asarray(H.hash_long(jnp.asarray(longs), seed))
    host = [H.murmur3_long_host(int(v), 42) for v in longs]
    assert list(dev) == host

    strs = ["", "a", "ab", "abc", "abcd", "hello world", "ünïcødé", "x" * 37]
    words, lens = H.pack_utf8_words(strs)
    dev = np.asarray(H.hash_string_words(jnp.asarray(words), jnp.asarray(lens), seed))
    host = [H.murmur3_bytes_host(s.encode("utf-8"), 42) for s in strs]
    assert list(dev) == host


def test_hash_double_bits():
    """doubleToLongBits reconstructed without bitcast (TPU x64-rewrite can't bitcast
    f64<->i64); canonical NaN like Java; subnormals flush to zero (XLA FTZ)."""
    import jax.numpy as jnp
    from spark_rapids_tpu.ops import hashing as H
    vs = np.array([0.0, 1.0, -1.0, 0.1, np.inf, -np.inf, np.nan, 2.5e300, -0.0,
                   -123.456])
    got = np.asarray(H.double_to_long_bits(jnp.asarray(vs)))
    exp = [np.float64(v).view(np.int64) if not np.isnan(v)
           else np.int64(0x7FF8000000000000) for v in vs]
    assert [int(g) for g in got] == [int(e) for e in exp]


def test_murmur3_chained_seed_device():
    """Multi-column hash chains seeds: h2 = hash(col2, hash(col1, 42))."""
    import jax.numpy as jnp
    from spark_rapids_tpu.ops import hashing as H
    a = np.array([7, 8], dtype=np.int32)
    b = np.array([100, -100], dtype=np.int64)
    h1 = H.hash_int(jnp.asarray(a), jnp.int32(42))
    h2 = np.asarray(H.hash_long(jnp.asarray(b), h1))
    expect = [H.murmur3_long_host(int(bv), H.murmur3_int_host(int(av), 42))
              for av, bv in zip(a, b)]
    assert list(h2) == expect


# -- fixed-width row format (CudfUnsafeRow analog, SURVEY.md #9) --------------

def test_row_buffer_roundtrip():
    import jax
    import pyarrow as pa
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar import rows as R
    from spark_rapids_tpu import types as T

    t = pa.table({
        "i": pa.array([1, None, -3, 2**31 - 1], pa.int32()),
        "l": pa.array([10, 2**62, None, -5], pa.int64()),
        "f": pa.array([1.5, None, -0.25, 3.75], pa.float32()),
        "d": pa.array([2.5, -1e300, None, 0.0], pa.float64()),
        "b": pa.array([True, False, None, True], pa.bool_()),
    })
    batch = ColumnarBatch.from_arrow(t)
    buf = R.pack_rows(batch)
    nw, total = R.row_layout(batch.schema)
    assert buf.shape == (4, total) and nw == 1
    back = R.unpack_rows(buf, batch.schema)
    assert back.to_arrow().to_pylist() == t.to_pylist()


def test_row_buffer_many_fields_null_words():
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar import rows as R
    n_cols = 70    # spills into a second null bitset word
    data = {f"c{j}": pa.array([j, None, j * 2], pa.int64())
            for j in range(n_cols)}
    t = pa.table(data)
    batch = ColumnarBatch.from_arrow(t)
    buf = R.pack_rows(batch)
    nw, total = R.row_layout(batch.schema)
    assert nw == 2 and total == 2 + n_cols
    assert R.unpack_rows(buf, batch.schema).to_arrow().to_pylist() == \
        t.to_pylist()


def test_row_buffer_session_api():
    import pyarrow as pa
    from spark_rapids_tpu.session import TpuSession
    import spark_rapids_tpu.functions as F
    spark = TpuSession()
    df = spark.create_dataframe({
        "k": pa.array([1, 2, None, 4], pa.int64()),
        "v": pa.array([0.5, None, 2.5, 4.0], pa.float64())})
    buf, schema = df.collect_row_buffer()
    assert buf.shape[0] == 4
    df2 = spark.create_dataframe_from_rows(buf, schema, num_partitions=2)
    assert df2.collect().to_pylist() == df.collect().to_pylist()
    # and the re-imported frame computes on device
    out = df2.filter(F.col("k") > F.lit(1)).collect()
    assert sorted(x for x in out["k"].to_pylist()) == [2, 4]

    # string schemas take the variable-width layout (r4: no longer an error)
    sdf = spark.create_dataframe({"s": pa.array(["a", "b"])})
    (words, offsets), sschema = sdf.collect_row_buffer()
    assert len(offsets) == 3
    # nested types stay out of the row formats
    import pytest
    ldf = spark.create_dataframe(
        pa.table({"l": pa.array([[1, 2], [3]], pa.list_(pa.int64()))}))
    with pytest.raises(NotImplementedError):
        ldf.collect_row_buffer()


def test_row_buffer_arrow_pack_precision_and_nan():
    """Host arrow pack: nullable int64 keeps full 64-bit precision, valid
    NaN doubles survive, decimals keep their scale."""
    import math
    import decimal
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.columnar import rows as R
    from spark_rapids_tpu import types as T

    t = pa.table({
        "big": pa.array([2**63 - 1, None, -(2**63) + 1], pa.int64()),
        "d": pa.array([float("nan"), 1.5, None], pa.float64()),
        "dec": pa.array([decimal.Decimal("1.23"), None,
                         decimal.Decimal("-0.07")], pa.decimal128(5, 2)),
    })
    schema = T.StructType([
        T.StructField("big", T.LONG),
        T.StructField("d", T.DOUBLE),
        T.StructField("dec", T.DecimalType(5, 2)),
    ])
    buf = R.pack_arrow(t, schema)
    back = R.unpack_rows_arrow(buf, schema)
    assert back["big"].to_pylist() == [2**63 - 1, None, -(2**63) + 1]
    d = back["d"].to_pylist()
    assert math.isnan(d[0]) and d[1] == 1.5 and d[2] is None
    assert back["dec"].to_pylist() == [decimal.Decimal("1.23"), None,
                                       decimal.Decimal("-0.07")]


def test_variable_width_row_roundtrip():
    """UnsafeRow-style variable-width rows (VERDICT r3 missing #5): strings
    pack as (offset<<32)|len slots + a per-row byte region; round trip is
    exact, including nulls, empty strings, and multi-byte UTF-8."""
    from spark_rapids_tpu.columnar import rows as R
    from spark_rapids_tpu import types as T
    t = pa.table({
        "s": pa.array(["", "hello", None, "é中🙂", "x" * 300]),
        "i": pa.array([1, None, 3, 4, 5], pa.int64()),
        "t": pa.array([None, "b", "", None, "fin"]),
        "d": pa.array([1.5, 2.5, None, float("nan"), -0.0]),
    })
    schema = T.StructType([
        T.StructField("s", T.STRING, True),
        T.StructField("i", T.LONG, True),
        T.StructField("t", T.STRING, True),
        T.StructField("d", T.DOUBLE, True),
    ])
    assert not R.is_fixed_width(schema) and R.is_packable(schema)
    words, offsets = R.pack_arrow_var(t, schema)
    # rows are 8-byte aligned, var region packed after the fixed slots
    assert offsets[0] == 0 and offsets[-1] == len(words)
    back = R.unpack_rows_arrow_var(words, offsets, schema)
    for name in t.column_names:
        got = back.column(name).to_pylist()
        exp = t.column(name).to_pylist()
        if name == "d":
            assert got[:3] == exp[:3] and got[3] != got[3] and got[4] == 0.0
        else:
            assert got == exp, name


def test_variable_width_rows_through_session():
    from spark_rapids_tpu.session import TpuSession
    spark = TpuSession()
    t = pa.table({"s": pa.array(["a", None, "ccc"]),
                  "v": pa.array([1, 2, 3], pa.int32())})
    df = spark.create_dataframe(t)
    buf, schema = df.collect_row_buffer()
    df2 = spark.create_dataframe_from_rows(buf, schema)
    assert df2.collect().to_pylist() == t.to_pylist()


def test_concat_batches_edge_cases():
    """Ordered-dus concat (r4): later windows overwrite earlier padding;
    zero-row batches, mixed capacities, and cross-batch dictionaries."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.ops.concat import concat_batches

    def B(d):
        return ColumnarBatch.from_arrow(pa.table(d))

    b1 = B({"s": pa.array(["b", "a", None]),
            "v": pa.array([1, 2, None], pa.int64())})
    b2 = B({"s": pa.array(["z"] * 9), "v": pa.array(range(9), pa.int64())})
    b3 = B({"s": pa.array([None, "a"]), "v": pa.array([None, 100], pa.int64())})
    out = concat_batches([b1, b2, b3]).to_arrow()
    assert out.column("s").to_pylist() == ["b", "a", None] + ["z"] * 9 + [None, "a"]
    assert out.column("v").to_pylist() == [1, 2, None] + list(range(9)) + [None, 100]

    b0 = ColumnarBatch.from_arrow(pa.table({"s": pa.array([], pa.string()),
                                            "v": pa.array([], pa.int64())}))
    out = concat_batches([b1, b0, b3]).to_arrow()
    assert out.column("v").to_pylist() == [1, 2, None, None, 100]

    big = B({"s": pa.array([f"k{i % 5}" for i in range(500)]),
             "v": pa.array(range(500), pa.int64())})
    out = concat_batches([b1, big]).to_arrow()
    assert out.num_rows == 503
    assert out.column("v").to_pylist()[3:] == list(range(500))
